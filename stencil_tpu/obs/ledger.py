"""The performance ledger: append-only, cross-run measurement evidence.

ROADMAP item 1 calls the eventual hardware session "the TPU measurement
ledger" — this module is the ledger as software. Every recorded
measurement (a bench.py payload, a ``vs_baseline`` detail, a metrics-JSONL
gauge trimean) becomes one schema-validated JSON line in a ledger file,
keyed by::

    (metric, platform, config fingerprint, git rev, round/label)

so rounds stop being islands: ``apps/perf_tool.py`` renders trends across
labels, diffs two labels, and gates new measurements against trimean ±
MAD tolerance bands (the regression sentinel). The robust-stats core is
the reference's trimean discipline (bin/statistics.hpp:17), re-implemented
here in pure stdlib.

Entry schema (v1) — one JSON object per line::

    {"v": 1, "kind": "perf-ledger",
     "metric":   str,          # leg name, e.g. jacobi3d_512_mcells_per_s_per_chip
     "value":    finite float,
     "unit":     str | null,
     "platform": str,          # "tpu" | "cpu" | "unknown" | ...
     "config":   str,          # config fingerprint (config_fingerprint())
     "rev":      str | null,   # git revision of the measured tree
     "label":    str,          # round/run label, e.g. "r05"
     "source":   "bench" | "legacy-bench" | "legacy-multichip"
               | "metrics" | "manual" | "serve",
     "t":        unix seconds,
     "run":      str | null,   # telemetry run id where applicable
     "detail":   object?}      # free-form provenance (config detail, tags)

Write discipline mirrors plan/db.py and ckpt/snapshot.py: the whole file
is rewritten through tmp + fsync + atomic rename (a crash never leaves a
torn line), existing lines are preserved verbatim (append-only), corrupt
or future-versioned ledgers are REJECTED loudly (:class:`LedgerError`)
— never silently emptied or appended to — and ingest is idempotent
(an entry whose key already exists is skipped, so re-running
``perf_tool ingest`` over the same files is safe).

This module is PURE STDLIB by contract (the watchdog.py discipline):
``bench.py``'s parent process — which must never import jax — loads it by
file path to append the round payload when ``STENCIL_BENCH_LEDGER`` is
set (``STENCIL_BENCH_LABEL`` names the round).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import math
import os
import subprocess
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

try:
    import fcntl  # POSIX; absent on Windows — appends degrade to unlocked
except ImportError:  # pragma: no cover
    fcntl = None

SCHEMA_VERSION = 1
LEDGER_KIND = "perf-ledger"
SOURCES = ("bench", "legacy-bench", "legacy-multichip", "metrics", "manual",
           "serve")
_TMP_PREFIX = ".tmp-"

# bench.py contract: the parent appends its payload here after each round.
ENV_LEDGER = "STENCIL_BENCH_LEDGER"
ENV_LABEL = "STENCIL_BENCH_LABEL"


class LedgerError(ValueError):
    """Corrupt, unparseable, or future-versioned ledger."""


# -- robust stats (pure-stdlib mirror of utils/statistics.Statistics) ---------


def _quantile(sorted_v: Sequence[float], q: float) -> float:
    if len(sorted_v) == 1:
        return sorted_v[0]
    pos = q * (len(sorted_v) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_v) - 1)
    frac = pos - lo
    return sorted_v[lo] * (1 - frac) + sorted_v[hi] * frac


def trimean(values: Iterable[float]) -> float:
    """Tukey's trimean (Q1 + 2*Q2 + Q3) / 4 — numerically identical to
    ``utils/statistics.Statistics.trimean`` (same interpolated quantiles),
    duplicated here only to keep this module stdlib-importable."""
    v = sorted(float(x) for x in values)
    if not v:
        raise ValueError("trimean of an empty sample")
    return (_quantile(v, 0.25) + 2 * _quantile(v, 0.5) + _quantile(v, 0.75)) / 4


def mad(values: Iterable[float]) -> float:
    """Median absolute deviation — the tolerance-band width the
    regression sentinel pairs with the trimean center."""
    v = sorted(float(x) for x in values)
    if not v:
        raise ValueError("MAD of an empty sample")
    med = _quantile(v, 0.5)
    return _quantile(sorted(abs(x - med) for x in v), 0.5)


# -- entries ------------------------------------------------------------------


# Keys that do not change WHAT was measured, only how it was observed or
# perturbed: sinks, run ids, output prefixes, fault-injection specs. Two
# runs of the same program must land under ONE fingerprint even when
# their metrics files or injections differ — otherwise every run is its
# own config and no history ever accumulates under a key.
VOLATILE_CONFIG_KEYS = frozenset({
    "metrics_out", "metrics_dma", "run_id", "out", "prefix", "ckpt_dir",
    "campaign_dir", "plan_db", "inject", "resume", "paraview",
    "paraview_every", "checkpoint_period",
    # live-observability knobs (obs/live.py + obs/status.py): they change
    # how a run is WATCHED (sentinel bands, snapshot path, SLO records),
    # never what it computes — a sentinel-on rerun must land in the same
    # trend group as its sentinel-off history
    "status_file", "live_sentinel", "live_config", "deadline_ms",
})


def config_fingerprint(config: Optional[dict]) -> str:
    """12-hex fingerprint of a canonicalized config dict (sorted keys;
    None-valued and :data:`VOLATILE_CONFIG_KEYS` dropped) — the ledger's
    "same configuration" key."""
    clean = {k: v for k, v in sorted((config or {}).items())
             if v is not None and k not in VOLATILE_CONFIG_KEYS}
    blob = json.dumps(clean, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def make_entry(metric: str, value: float, *, label: str,
               unit: Optional[str] = None, platform: str = "unknown",
               config: Optional[dict] = None, rev: Optional[str] = None,
               source: str = "manual", run: Optional[str] = None,
               t: Optional[float] = None,
               detail: Optional[dict] = None) -> dict:
    """Build one v1 ledger entry; ``config`` is fingerprinted (and kept
    under ``detail.config`` only if the caller put it there)."""
    e = {
        "v": SCHEMA_VERSION,
        "kind": LEDGER_KIND,
        "metric": metric,
        "value": float(value),
        "unit": unit,
        "platform": platform,
        "config": config if isinstance(config, str) else config_fingerprint(config),
        "rev": rev,
        "label": label,
        "source": source,
        "t": time.time() if t is None else float(t),
        "run": run,
    }
    if detail:
        e["detail"] = detail
    return e


def entry_key(e: dict) -> Tuple[str, str, str, str, str]:
    """The identity under which entries dedup and trend-group."""
    return (e["metric"], e["platform"], e["config"], e.get("rev") or "",
            e["label"])


def validate_entry(e) -> List[str]:
    """Schema violations of one entry (empty = valid v1)."""
    if not isinstance(e, dict):
        return [f"not an object: {type(e).__name__}"]
    errs: List[str] = []
    v = e.get("v")
    if isinstance(v, int) and v > SCHEMA_VERSION:
        # refuse future schemas outright — a downgrade must not reinterpret
        return [f"ledger schema v{v} is newer than this build's "
                f"v{SCHEMA_VERSION}"]
    if v != SCHEMA_VERSION:
        errs.append(f"unknown schema version {v!r}")
    if e.get("kind") != LEDGER_KIND:
        errs.append(f"unknown kind {e.get('kind')!r}")
    for fld in ("metric", "platform", "config", "label"):
        if not isinstance(e.get(fld), str) or not e.get(fld):
            errs.append(f"{fld} must be a non-empty string")
    val = e.get("value")
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        errs.append("value must be a number")
    elif not math.isfinite(val):
        errs.append("value must be finite (strict-JSON ledger)")
    if not isinstance(e.get("t"), (int, float)):
        errs.append("t must be a number")
    for fld in ("unit", "rev", "run"):
        if e.get(fld) is not None and not isinstance(e[fld], str):
            errs.append(f"{fld} must be a string or null")
    if e.get("source") not in SOURCES:
        errs.append(f"unknown source {e.get('source')!r}")
    if "detail" in e and not isinstance(e["detail"], dict):
        errs.append("detail must be an object where present")
    return errs


# -- file I/O (tmp + fsync + rename; corruption rejected loudly) --------------


def _read_ledger(path: str) -> Tuple[List[dict], List[str]]:
    """One pass over the file: (validated entries, raw stripped lines).
    The raw lines let :func:`append_entries` preserve history verbatim
    without re-reading the file under its lock."""
    if not os.path.exists(path):
        return [], []
    entries: List[dict] = []
    raw: List[str] = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError as exc:
                raise LedgerError(f"{path}:{i}: unparseable JSON ({exc})")
            errs = validate_entry(e)
            if errs:
                raise LedgerError(f"{path}:{i}: {errs[0]}"
                                  + (f" (+{len(errs) - 1} more)"
                                     if len(errs) > 1 else ""))
            entries.append(e)
            raw.append(line)
    return entries, raw


def load_ledger(path: str) -> List[dict]:
    """Parse + validate every line; missing file -> []. Any unparseable
    or schema-invalid line raises :class:`LedgerError` — a corrupt ledger
    must never silently shrink into a shorter history (which would widen
    or recenter every tolerance band)."""
    return _read_ledger(path)[0]


@contextlib.contextmanager
def _ledger_lock(path: str):
    """Exclusive flock on ``<path>.lock`` for the append's
    read-modify-write: two concurrent appenders (a bench parent racing a
    perf_tool ingest in a campaign) would otherwise both read N lines and
    last-writer-wins away the other's entries — a silent rewrite of the
    'append-only' history. Best-effort where flock is unavailable."""
    if fcntl is None:
        yield
        return
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd = os.open(path + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        os.close(fd)  # releases the flock


def append_entries(path: str, entries: Sequence[dict],
                   dedup: bool = True) -> int:
    """Append validated entries atomically; returns the number written.

    Existing lines are preserved VERBATIM (append-only: history is
    evidence and never rewritten); the whole file goes through tmp +
    fsync + atomic rename under an exclusive ``<path>.lock`` flock so a
    crash never leaves a torn line and concurrent appenders serialize
    instead of losing each other's entries. With ``dedup`` (the default)
    entries whose :func:`entry_key` already exists are skipped — ingest
    is idempotent. Appending to a corrupt ledger raises instead of
    clobbering it."""
    for e in entries:
        errs = validate_entry(e)
        if errs:
            raise LedgerError(f"refusing to append invalid entry: {errs[0]} "
                              f"({e.get('metric')!r})")
    with _ledger_lock(path):
        return _append_locked(path, entries, dedup)


def _append_locked(path: str, entries: Sequence[dict], dedup: bool) -> int:
    existing, existing_raw = _read_ledger(path)  # raises on corruption
    seen = {entry_key(e) for e in existing}
    new_lines: List[str] = []
    for e in entries:
        k = entry_key(e)
        if dedup and k in seen:
            continue
        seen.add(k)
        new_lines.append(json.dumps(e, sort_keys=True))
    if not new_lines:
        return 0
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f"{_TMP_PREFIX}{os.path.basename(path)}-{os.getpid()}")
    with open(tmp, "w") as f:
        for ln in existing_raw + new_lines:
            f.write(ln + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(new_lines)


def git_rev(cwd: Optional[str] = None) -> Optional[str]:
    """Short git revision of ``cwd`` (best-effort; None outside a repo —
    a ledger append must never fail on a missing .git)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=cwd, capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


# -- ingest: the three payload shapes the repo already produces ---------------


def entries_from_bench_payload(payload: dict, *, label: str,
                               rev: Optional[str] = None,
                               source: str = "bench",
                               t: Optional[float] = None) -> List[dict]:
    """Map one bench.py payload (``{"metric", "value", "unit",
    "vs_baseline", "detail": {...}}``) into v1 entries: the headline
    metric, its ``vs_baseline`` ratio, and every numeric ``detail.*`` leg
    (nulls and strings skipped — a missing astaroth row is absence, not a
    zero)."""
    detail = payload.get("detail") or {}
    platform = str(detail.get("platform") or "unknown")
    config = {"platform": platform, "size": detail.get("size")}
    out: List[dict] = []

    def add(metric, value, unit=None):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        if not math.isfinite(float(value)):
            return
        out.append(make_entry(metric, value, label=label, unit=unit,
                              platform=platform, config=config, rev=rev,
                              source=source, t=t))

    add(payload.get("metric"), payload.get("value"), payload.get("unit"))
    if payload.get("metric"):
        add(f"{payload['metric']}.vs_baseline", payload.get("vs_baseline"),
            "ratio")
    for k, v in sorted(detail.items()):
        if k in ("platform", "size", "leg_errors"):
            continue  # config/diagnostics, not measurements
        add(k, v)
    # guard against a payload with no usable metric name at all
    return [e for e in out if isinstance(e["metric"], str) and e["metric"]]


def entries_from_legacy_bench(doc: dict, *, label: Optional[str] = None,
                              rev: Optional[str] = None,
                              t: Optional[float] = None) -> List[dict]:
    """Ingest one committed BENCH_r0N.json (the driver's wrapper:
    ``{"n", "cmd", "rc", "tail", "parsed": payload?}``). The round label
    comes from ``n`` (``r05``); a failed round (rc != 0 / no parsed
    payload, e.g. BENCH_r03) still lands a ``bench.rc`` entry so the
    trend shows the outage instead of skipping the round."""
    if label is None:
        n = doc.get("n")
        label = f"r{int(n):02d}" if isinstance(n, int) else "legacy"
    out: List[dict] = []
    parsed = doc.get("parsed")
    platform = "unknown"
    if isinstance(parsed, dict):
        out = entries_from_bench_payload(parsed, label=label, rev=rev,
                                         source="legacy-bench", t=t)
        platform = str((parsed.get("detail") or {}).get("platform")
                       or "unknown")
    rc = doc.get("rc")
    if isinstance(rc, int) and not isinstance(rc, bool):
        out.append(make_entry("bench.rc", rc, label=label, unit="rc",
                              platform=platform, config={"cmd": doc.get("cmd")},
                              rev=rev, source="legacy-bench", t=t))
    return out


def entries_from_legacy_multichip(doc: dict, *, label: str,
                                  rev: Optional[str] = None,
                                  t: Optional[float] = None) -> List[dict]:
    """Ingest one committed MULTICHIP_r0N.json (``{"n_devices", "rc",
    "ok", "skipped", "tail"}``). The label must come from the caller
    (the file carries no round number — perf_tool infers it from the
    filename)."""
    config = {"n_devices": doc.get("n_devices")}
    out = [make_entry("multichip_dryrun_ok",
                      1.0 if doc.get("ok") else 0.0, label=label,
                      unit="bool", platform="unknown", config=config,
                      rev=rev, source="legacy-multichip", t=t,
                      detail={"rc": doc.get("rc"),
                              "skipped": bool(doc.get("skipped"))})]
    return out


def entries_from_metrics_records(records: Sequence[dict], *,
                                 label: Optional[str] = None,
                                 platform: str = "unknown",
                                 rev: Optional[str] = None,
                                 spans: bool = False,
                                 t: Optional[float] = None) -> List[dict]:
    """Ingest telemetry metrics records (the ``--metrics-out`` JSONL,
    already schema-validated by the caller): one entry per gauge name —
    the TRIMEAN over that gauge's samples across the file (the
    reference's robust-stat discipline), split per method/batched tag
    exactly like ``apps/report.py`` aggregation so A/B legs never fold.
    ``spans=True`` also ingests per-span second trimeans as
    ``<name>.trimean_s``. The config fingerprint comes from the run's
    ``config`` meta record when present (a self-describing metrics file
    lands under its real configuration key)."""
    gauges: Dict[str, List[float]] = {}
    span_s: Dict[str, List[float]] = {}
    units: Dict[str, str] = {}
    attrib: Dict[Tuple[str, str], dict] = {}
    config: Optional[dict] = None
    run_id: Optional[str] = None
    newest_t = None
    for r in records:
        run_id = run_id or r.get("run")
        rt = r.get("t")
        if isinstance(rt, (int, float)):
            newest_t = rt if newest_t is None else max(newest_t, rt)
        if r.get("kind") == "meta" and r.get("name") == "config" and \
                isinstance(r.get("config"), dict) and config is None:
            config = r["config"]
        if r.get("kind") == "meta" and r.get("name") == "plan.attrib.phase":
            # the observatory's calibration evidence: fold a run's
            # samples to one trimean per (phase, method), carrying the
            # (collectives, wire_bytes) point plan/calibrate's
            # samples_from_ledger refits from
            g = attrib.setdefault((str(r["phase"]), str(r["method"])), {
                "samples": [], "collectives": int(r["collectives"]),
                "wire_bytes": int(r["wire_bytes"]),
                "predicted_s": float(r["predicted_s"]),
                "provenance": str(r.get("provenance", "")),
            })
            v = float(r["measured_s"])
            if math.isfinite(v):
                g["samples"].append(v)
        tags = [str(r[k]) for k in ("method", "batched") if k in r]
        key = r["name"] + (f"[{','.join(tags)}]" if tags else "")
        # a NaN sample from a degenerate run must be dropped HERE: NaN
        # poisons sorted() so the trimean of the remaining good samples
        # comes out silently wrong, not NaN (the bench-payload path's
        # add() applies the same finite filter)
        if r.get("kind") == "gauge":
            v = float(r["value"])
            if math.isfinite(v):
                gauges.setdefault(key, []).append(v)
                if isinstance(r.get("unit"), str):
                    units.setdefault(key, r["unit"])
        elif r.get("kind") == "span" and spans:
            v = float(r["seconds"])
            if math.isfinite(v):
                span_s.setdefault(key, []).append(v)
    label = label or run_id or "metrics"
    when = t if t is not None else newest_t
    out: List[dict] = []
    for name, vals in sorted(gauges.items()):
        tm = trimean(vals)
        if not math.isfinite(tm):
            continue
        out.append(make_entry(name, tm, label=label, unit=units.get(name),
                              platform=platform, config=config, rev=rev,
                              source="metrics", run=run_id, t=when,
                              detail={"samples": len(vals)}))
    for name, vals in sorted(span_s.items()):
        tm = trimean(vals)
        if not math.isfinite(tm):
            continue
        out.append(make_entry(f"{name}.trimean_s", tm, label=label, unit="s",
                              platform=platform, config=config, rev=rev,
                              source="metrics", run=run_id, t=when,
                              detail={"samples": len(vals)}))
    for (phase, method), g in sorted(attrib.items()):
        if not g["samples"]:
            continue
        tm = trimean(g["samples"])
        if not math.isfinite(tm):
            continue
        out.append(make_entry(
            f"plan.attrib.{phase}", tm, label=f"{label}[{method}]",
            unit="s", platform=platform, config=config, rev=rev,
            source="metrics", run=run_id, t=when,
            detail={"phase": phase, "method": method,
                    "collectives": g["collectives"],
                    "wire_bytes": g["wire_bytes"],
                    "predicted_s": g["predicted_s"],
                    "provenance": g["provenance"],
                    "samples": len(g["samples"])}))
    return out
