"""Map measured exchange-phase time back onto the plan IR's prediction.

The predict→measure→refit loop's MEASURE third. The autotuner ranks
plans with ``plan/cost.score`` — a prediction in seconds — and
``verify_plan`` audits the structural half of that prediction
(collectives, bytes, DMAs) against the realized IR; what nobody checks
is the seconds themselves. This module closes that gap per run: each
timed exchange phase (the ``trace_range`` names the host spans and any
xprof device capture both key on — "stencil.exchange_loop",
"exchange.hierarchical", …) becomes one ``plan.attrib.phase`` meta
record pairing the installed calibration's prediction with the measured
wall time for the SAME (method, collectives, wire_bytes) point:

    plan.attrib.phase  phase= method= kernel_variant=
                       predicted_s= measured_s= residual=
                       collectives= wire_bytes=

Those records are the raw material of ``plan/calibrate.fit`` (fitted
calibration rows) and the evidence ``perf_tool drift`` /
``verify_plan --time`` judge. ``judge_drift`` here is the single band
authority for both: the same trimean ± max(k·MAD, rtol·|center|, atol)
formula ``perf_tool.evaluate_gate`` applies to ledger history, applied
to a phase's measured samples with the prediction as the judged value —
a stale calibration is a prediction that fell out of the band of what
the fabric actually does.

For remote-dma plans the ``collectives`` field carries the DMA count:
cost.score prices per-copy overhead there, and the fit must see the
count that multiplies the constant it is recovering.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from ..plan import cost as plan_cost
from ..plan.ir import REMOTE_DMA, PlanChoice, PlanConfig
from .ledger import mad, trimean

ATTRIB_NAME = "plan.attrib.phase"
DRIFT_NAME = "calibration.drift"

# evaluate_gate's defaults (apps/perf_tool.py) — the shared band authority
DEFAULT_MAD_K = 3.0
DEFAULT_REL_TOL = 0.05
DEFAULT_ABS_TOL = 0.0


@dataclass(frozen=True)
class PhasePrediction:
    """The cost model's view of one exchange phase under a calibration."""

    method: str
    predicted_s: float
    collectives: int     # DMA count for remote-dma (per-copy pricing)
    wire_bytes: int
    provenance: str = "modeled(default)"


def predict_exchange(config: PlanConfig, choice: PlanChoice,
                     calibration: Optional[dict] = None,
                     ) -> Optional[PhasePrediction]:
    """Price one step's exchange for ``choice`` under ``calibration``
    (None = DEFAULT_CALIBRATION) — None when the choice is infeasible
    for the config."""
    c = plan_cost.score(config, choice, calibration)
    if c is None:
        return None
    prov = "modeled(default)"
    if calibration:
        prov = str(calibration.get("provenance", "override"))
    n = c.dmas if choice.method == REMOTE_DMA else c.collectives
    return PhasePrediction(method=choice.method,
                           predicted_s=float(c.exchange_s),
                           collectives=int(n),
                           wire_bytes=int(c.wire_bytes),
                           provenance=prov)


def emit_phase(rec, pred: PhasePrediction, measured_s: float, *,
               phase: str, kernel_variant: Optional[str] = None,
               fabric: Optional[Dict[str, object]] = None) -> Optional[dict]:
    """Emit one attribution record (one measured sample of one phase).

    ``fabric`` is machine_info's fabric fingerprint (procs/hosts/
    platform); its scalars ride along as extra fields so a fitted row
    can be traced to the fabric it was measured on. No-op (None) when
    the recorder is disabled — attribution must never tax an
    uninstrumented run.
    """
    if rec is None or not getattr(rec, "enabled", False):
        return None
    extra: Dict[str, object] = {}
    for k, v in (fabric or {}).items():
        if isinstance(v, (str, int, float, bool)):
            extra[f"fabric_{k}"] = v
    return rec.meta(
        ATTRIB_NAME,
        phase=phase,
        method=pred.method,
        kernel_variant=kernel_variant,
        predicted_s=float(pred.predicted_s),
        measured_s=float(measured_s),
        residual=float(measured_s - pred.predicted_s),
        collectives=int(pred.collectives),
        wire_bytes=int(pred.wire_bytes),
        provenance=pred.provenance,
        **extra)


@dataclass(frozen=True)
class DriftVerdict:
    """judge_drift's answer: did the prediction fall out of the band?"""

    ok: bool
    phase: str
    predicted_s: float
    center: float        # trimean of the measured samples
    lo: float
    hi: float
    n: int

    def describe(self) -> str:
        state = "within" if self.ok else "OUTSIDE"
        return (f"{self.phase}: predicted {self.predicted_s:.3e}s {state} "
                f"measured band [{self.lo:.3e}, {self.hi:.3e}] "
                f"(center {self.center:.3e}s, n={self.n})")


def judge_drift(phase: str, predicted_s: float,
                samples: Sequence[float], *,
                mad_k: float = DEFAULT_MAD_K,
                rel_tol: float = DEFAULT_REL_TOL,
                abs_tol: float = DEFAULT_ABS_TOL) -> DriftVerdict:
    """The drift band authority — shared by ``perf_tool drift``,
    ``verify_plan --time``, and the in-run sentinel.

    Same formula as ``perf_tool.evaluate_gate``: center = trimean of
    the measured samples, tolerance = max(mad_k·MAD, rel_tol·|center|,
    abs_tol), direction both. The judged value is the calibration's
    PREDICTION: drift means the installed constants no longer describe
    the fabric, whichever side they miss on. Keep rel_tol < 1 — at 1
    the low band edge hits zero and an under-prediction (the fabric
    slower than the model says) can never trip.
    """
    vals = [float(v) for v in samples]
    if not vals:
        raise ValueError(f"no measured samples for phase {phase!r}")
    center = trimean(vals)
    tol = max(mad_k * mad(vals), rel_tol * abs(center), abs_tol)
    lo, hi = center - tol, center + tol
    return DriftVerdict(ok=lo <= predicted_s <= hi, phase=phase,
                        predicted_s=float(predicted_s), center=center,
                        lo=lo, hi=hi, n=len(vals))


def emit_drift(rec, verdict: DriftVerdict) -> Optional[dict]:
    """Record a tripped in-run verdict (``calibration.drift`` meta —
    the Perfetto instant marker). Emits nothing for a healthy phase:
    the marker is an alarm, not a pulse."""
    if rec is None or not getattr(rec, "enabled", False) or verdict.ok:
        return None
    return rec.meta(DRIFT_NAME,
                    phase=verdict.phase,
                    predicted_s=float(verdict.predicted_s),
                    measured_s=float(verdict.center),
                    band_lo=float(verdict.lo),
                    band_hi=float(verdict.hi),
                    n=verdict.n)


def attribute_and_judge(rec, config: PlanConfig, choice: PlanChoice,
                        samples_s: Sequence[float], *, phase: str,
                        calibration: Optional[dict] = None,
                        kernel_variant: Optional[str] = None,
                        fabric: Optional[Dict[str, object]] = None,
                        rel_tol: float = 0.75) -> Optional[DriftVerdict]:
    """The one-call in-run path (jacobi epilogue, _bench_common): emit
    one attribution record per measured sample, then apply the drift
    band leniently (wide rel_tol — an in-run check on a handful of
    noisy samples flags multiple-x staleness, not 5% drift; the strict
    judgement belongs to ``perf_tool drift`` over a full metrics file).
    rel_tol must stay BELOW 1: at 1 the band's low edge reaches zero
    and a prediction far below the measured center — the canonical
    "fabric got slower than the model" staleness — can never trip.
    Returns the verdict, or None when the choice is infeasible /
    recorder disabled / no samples."""
    if rec is None or not getattr(rec, "enabled", False) or not samples_s:
        return None
    pred = predict_exchange(config, choice, calibration)
    if pred is None:
        return None
    for s in samples_s:
        emit_phase(rec, pred, s, phase=phase,
                   kernel_variant=kernel_variant, fabric=fabric)
    verdict = judge_drift(phase, pred.predicted_s, samples_s,
                          rel_tol=rel_tol)
    emit_drift(rec, verdict)
    return verdict


def phases_from_records(records: Sequence[dict]
                        ) -> Dict[str, Dict[str, object]]:
    """Group a metrics file's attribution records for the drift
    sentinel: key -> {"predicted_s": latest prediction, "samples":
    [measured...], "method": str, "provenance": str}. Grouping is by
    (phase, method) — an autotune run's probe records put several
    methods under one phase name, and their samples must never be
    judged against one prediction. The key is the plain phase name
    when a single method owns it, ``phase[method]`` otherwise. The
    prediction is taken from the LAST record of each group (all of one
    run's records for a group share it; across concatenated runs the
    newest calibration wins — that is the one being judged)."""
    groups: Dict[tuple, Dict[str, object]] = {}
    for r in records:
        if r.get("kind") != "meta" or r.get("name") != ATTRIB_NAME:
            continue
        g = groups.setdefault((str(r["phase"]), str(r["method"])),
                              {"samples": [], "predicted_s": 0.0,
                               "method": "", "provenance": ""})
        g["samples"].append(float(r["measured_s"]))
        g["predicted_s"] = float(r["predicted_s"])
        g["method"] = str(r["method"])
        g["provenance"] = str(r.get("provenance", ""))
    per_phase: Dict[str, int] = {}
    for phase, _ in groups:
        per_phase[phase] = per_phase.get(phase, 0) + 1
    return {
        (phase if per_phase[phase] == 1 else f"{phase}[{method}]"): g
        for (phase, method), g in groups.items()
    }


def ledger_detail(pred: PhasePrediction, *, phase: str,
                  samples: int) -> Dict[str, object]:
    """The ``detail`` dict a ledger entry derived from attribution
    carries — exactly the fields ``plan/calibrate.samples_from_ledger``
    needs to reconstruct a Sample."""
    return {"phase": phase, "method": pred.method,
            "collectives": int(pred.collectives),
            "wire_bytes": int(pred.wire_bytes),
            "predicted_s": float(pred.predicted_s),
            "provenance": pred.provenance, "samples": int(samples)}
