"""obs — the flight recorder: telemetry, watchdog, ledger, trace export.

Four parts, deliberately decoupled:

- :mod:`stencil_tpu.obs.telemetry` — a structured recorder of spans,
  counters, and gauges flushed as one-JSON-object-per-line to a metrics
  sink (the ``--metrics-out`` flag every bench app grows), riding the
  existing :mod:`stencil_tpu.utils.timer` buckets + profiler annotations.
- :mod:`stencil_tpu.obs.watchdog` — the revival watcher for stall-prone
  tunneled-TPU measurement runs: supervises a child process on heartbeat
  + total-budget deadlines, distinguishes stall from crash, retries with
  backoff, archives logs. Pure stdlib, importable WITHOUT importing jax
  (``bench.py``'s parent loads it by file path — the parent must never
  touch a JAX backend).
- :mod:`stencil_tpu.obs.ledger` — the cross-run performance ledger:
  append-only schema-validated entries keyed by (metric, platform,
  config fingerprint, git rev, label), ingested from bench payloads and
  metrics-JSONL gauge trimeans; ``apps/perf_tool.py`` renders trends and
  runs the trimean ± MAD regression sentinel over it. Pure stdlib by the
  same contract (``bench.py``'s parent appends the round payload when
  ``STENCIL_BENCH_LEDGER`` is set).
- :mod:`stencil_tpu.obs.trace_export` — metrics JSONL ->
  Chrome-trace/Perfetto timeline JSON (one lane per (run, proc),
  fault/checkpoint instant markers); ``apps/report.py --trace-out``.
- :mod:`stencil_tpu.obs.live` — the IN-run sentinel: streaming
  trimean ± MAD anomaly detection over bounded per-metric windows
  (the perf_tool band semantics applied online), emitting
  ``anomaly.detected`` / ``anomaly.cleared`` / ``replan.requested``
  mid-run; fed per-chunk by ``fault/recover.run_guarded`` and the
  campaign driver.
- :mod:`stencil_tpu.obs.status` — atomic run-status snapshots (one
  small JSON rewritten per chunk through tmp+fsync+rename): step,
  throughput, health counts, anomaly state, per-lane tenant SLO
  states; ``apps/report.py --status`` is the top-like reader. Pure
  stdlib by the watchdog contract.

This package intentionally imports nothing at package level so that the
stdlib-weight modules stay loadable directly.
"""

__all__ = ["telemetry", "watchdog", "ledger", "trace_export", "live",
           "status"]
