"""obs — the flight recorder: unified telemetry + the stall watchdog.

Two halves, deliberately decoupled:

- :mod:`stencil_tpu.obs.telemetry` — a structured recorder of spans,
  counters, and gauges flushed as one-JSON-object-per-line to a metrics
  sink (the ``--metrics-out`` flag every bench app grows), riding the
  existing :mod:`stencil_tpu.utils.timer` buckets + profiler annotations.
- :mod:`stencil_tpu.obs.watchdog` — the revival watcher for stall-prone
  tunneled-TPU measurement runs: supervises a child process on heartbeat
  + total-budget deadlines, distinguishes stall from crash, retries with
  backoff, archives logs. Pure stdlib, importable WITHOUT importing jax
  (``bench.py``'s parent loads it by file path — the parent must never
  touch a JAX backend).

This package intentionally imports nothing at package level so that
``stencil_tpu.obs.watchdog`` stays stdlib-weight when loaded directly.
"""

__all__ = ["telemetry", "watchdog"]
