"""Trace timeline export: telemetry JSONL -> Chrome-trace/Perfetto JSON.

``apps/report.py`` aggregates spans into trimean tables — good for
"how fast", useless for "what happened when". This module converts the
same metrics records into the Chrome trace-event format (loadable in
Perfetto / ``chrome://tracing``), so a self-healing run's story —
step chunks, health checks, an injected fault, the backoff, the
rollback, the checkpoint saves — reads as ONE timeline:

- one lane per ``(run, proc)``: each run becomes a trace "process"
  (pid) named after its run id + app, each JAX process index a thread
  (tid) within it;
- spans become complete (``ph: "X"``) duration events — emission time
  ``t`` is a span's END, so the event starts at ``t - seconds``;
- gauges, counters, and heartbeats become counter (``ph: "C"``) tracks
  (census/byte truths plot as flat lines; heartbeats as a rising seq);
- the fault/recovery/checkpoint vocabulary (``fault.injected``,
  ``health.fault``, ``recover.rollback``, ``ckpt.save``, ...) ALSO
  lands as instant events (``ph: "i"``, process-scoped) so the
  markers are visible at timeline zoom even where a span would be a
  sliver.

Timestamps are microseconds relative to the earliest event (Chrome
traces do not need absolute epochs; the original unix time survives in
each event's ``args.t``  via the run metadata). :func:`validate_trace`
is the schema authority the tests and `scripts/ci_perf_gate.py` use:
events sorted by ``ts``, ``X`` events with non-negative ``dur``, any
``B``/``E`` pairs balanced per lane.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

# Records whose occurrence matters at timeline zoom: each also becomes an
# instant marker (spans additionally keep their X duration event).
MARKER_NAMES = frozenset({
    "fault.injected",
    "health.fault",
    "recover.fault",
    "recover.rollback",
    "recover.aborted",
    "ckpt.save",
    "ckpt.save_skipped",
    "ckpt.restore",
    "ckpt.resumed_from_step",
    # the live-observability vocabulary (obs/live.py + campaign SLO):
    # in-run anomaly detect/clear, deadline violations, replan triggers
    "anomaly.detected",
    "anomaly.cleared",
    "slo.violation",
    "replan.requested",
    # the drift sentinel's alarm (obs/attribution.emit_drift): the
    # installed calibration's prediction fell out of the measured band
    "calibration.drift",
})

_LANE_TAGS = ("app", "phase", "method", "batched", "iters", "step",
              "fault_kind", "quantity", "from_step", "to_step", "reason",
              "seconds", "value", "bytes", "seq", "unit",
              "metric", "tenant", "deadline_ms", "p99_ms", "lane",
              # the attribution/drift vocabulary (obs/attribution.py):
              # the marker args must carry the evidence the alarm is about
              "predicted_s", "measured_s", "residual", "collectives",
              "wire_bytes", "provenance", "band_lo", "band_hi",
              "kernel_variant")


def _args(rec: dict) -> dict:
    out = {k: rec[k] for k in _LANE_TAGS if k in rec}
    out["t"] = rec["t"]
    return out


def to_trace(records: Sequence[dict]) -> dict:
    """Convert schema-valid telemetry records into a Chrome trace object
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``)."""
    # lane assignment: pid per run (ordered by first appearance), tid = proc
    pids: Dict[str, int] = {}
    run_app: Dict[str, str] = {}
    lanes: set = set()
    t0: Optional[float] = None
    for r in records:
        run = r["run"]
        if run not in pids:
            pids[run] = len(pids) + 1
        if r.get("app") and run not in run_app:
            run_app[run] = r["app"]
        lanes.add((run, r["proc"]))
        start = r["t"] - r["seconds"] if r["kind"] == "span" else r["t"]
        t0 = start if t0 is None else min(t0, start)
    t0 = t0 or 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    events: List[dict] = []
    for run, pid in pids.items():
        name = f"run {run}" + (f" ({run_app[run]})" if run in run_app else "")
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "ts": 0, "args": {"name": name}})
    for run, proc in sorted(lanes, key=lambda x: (pids[x[0]], x[1])):
        events.append({"ph": "M", "name": "thread_name", "pid": pids[run],
                       "tid": proc, "ts": 0,
                       "args": {"name": f"proc {proc}"}})

    for r in records:
        pid, tid = pids[r["run"]], r["proc"]
        kind, name = r["kind"], r["name"]
        if kind == "span":
            events.append({
                "ph": "X", "name": name, "cat": r.get("phase", "span"),
                "ts": us(r["t"] - r["seconds"]),
                "dur": round(r["seconds"] * 1e6, 3),
                "pid": pid, "tid": tid, "args": _args(r),
            })
        elif kind == "gauge":
            events.append({
                "ph": "C", "name": name, "cat": r.get("phase", "gauge"),
                "ts": us(r["t"]), "pid": pid, "tid": tid,
                "args": {"value": r["value"]},
            })
        elif kind == "counter":
            args = {}
            if "value" in r:
                args["value"] = r["value"]
            if "bytes" in r:
                args["bytes"] = r["bytes"]
            events.append({
                "ph": "C", "name": name, "cat": r.get("phase", "counter"),
                "ts": us(r["t"]), "pid": pid, "tid": tid, "args": args,
            })
        elif kind == "heartbeat":
            events.append({
                "ph": "C", "name": "heartbeat", "cat": "heartbeat",
                "ts": us(r["t"]), "pid": pid, "tid": tid,
                "args": {"value": r.get("seq", 0)},
            })
        elif kind == "meta" and name == "plan.attrib.phase":
            # predicted-vs-measured as PAIRED counter tracks per phase:
            # two flat-vs-jittering lines whose gap IS the calibration
            # residual, readable at a glance next to the span lanes
            for fld in ("predicted_s", "measured_s"):
                if isinstance(r.get(fld), (int, float)):
                    events.append({
                        "ph": "C", "name": f"plan.attrib.{r['phase']}.{fld}",
                        "cat": r.get("phase", "attrib"),
                        "ts": us(r["t"]), "pid": pid, "tid": tid,
                        "args": {"value": r[fld]},
                    })
        if name in MARKER_NAMES:
            # the marker lands at the record's emission time (a span's END
            # — for ckpt.save that is the moment the snapshot was durable)
            events.append({
                "ph": "i", "s": "p", "name": name,
                "cat": r.get("phase", "marker"), "ts": us(r["t"]),
                "pid": pid, "tid": tid, "args": _args(r),
            })

    meta = [e for e in events if e["ph"] == "M"]
    rest = sorted((e for e in events if e["ph"] != "M"),
                  key=lambda e: (e["ts"], e["pid"], e["tid"]))
    return {
        "traceEvents": meta + rest,
        "displayTimeUnit": "ms",
        "otherData": {"t0_unix_s": t0, "runs": {r: p for r, p in pids.items()}},
    }


def validate_trace(obj) -> List[str]:
    """Schema violations of a trace object (empty = valid): the checks
    the tests and CI gate rely on — parseable structure, monotonically
    sorted timestamps, complete ``X`` events with non-negative ``dur``,
    balanced ``B``/``E`` pairs per (pid, tid) lane."""
    errs: List[str] = []
    if not isinstance(obj, dict) or not isinstance(obj.get("traceEvents"), list):
        return ["trace must be an object with a traceEvents list"]
    last_ts = None
    open_stacks: Dict[Tuple, List[str]] = {}
    for i, e in enumerate(obj["traceEvents"]):
        if not isinstance(e, dict):
            errs.append(f"event {i}: not an object")
            continue
        ph = e.get("ph")
        if not isinstance(e.get("name"), str) or not e["name"]:
            errs.append(f"event {i}: missing name")
        if ph not in ("M", "X", "B", "E", "i", "I", "C"):
            errs.append(f"event {i}: unsupported ph {ph!r}")
            continue
        if ph == "M":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errs.append(f"event {i}: ts must be a non-negative number")
            continue
        if last_ts is not None and ts < last_ts:
            errs.append(f"event {i}: ts {ts} not sorted (prev {last_ts})")
        last_ts = ts
        if "pid" not in e or "tid" not in e:
            errs.append(f"event {i}: missing pid/tid lane")
            continue
        lane = (e["pid"], e["tid"])
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errs.append(f"event {i}: X event needs non-negative dur")
        elif ph == "B":
            open_stacks.setdefault(lane, []).append(e["name"])
        elif ph == "E":
            stack = open_stacks.get(lane) or []
            if not stack:
                errs.append(f"event {i}: E without matching B on lane {lane}")
            else:
                stack.pop()
    for lane, stack in open_stacks.items():
        if stack:
            errs.append(f"lane {lane}: unclosed B event(s) {stack}")
    return errs


def write_trace(path: str, records: Sequence[dict]) -> int:
    """Export ``records`` to ``path``; returns the event count. Refuses
    to write a trace that fails its own validator."""
    trace = to_trace(records)
    errs = validate_trace(trace)
    if errs:
        raise ValueError(f"refusing to write an invalid trace: {errs[0]}")
    # Perfetto/chrome://tracing parse STRICT JSON: a NaN gauge from a
    # degenerate run must fail here, not produce an unloadable file
    try:
        text = json.dumps(trace, allow_nan=False)
    except ValueError:
        raise ValueError("refusing to write a non-strict-JSON trace "
                         "(NaN/Infinity in some event's value or args)")
    with open(path, "w") as f:
        f.write(text + "\n")
    return len(trace["traceEvents"])
