"""The in-run sentinel: streaming trimean ± MAD anomaly detection.

``apps/perf_tool.py`` is the CROSS-run half of the regression story: it
judges a finished round against the ledger's history. This module is the
IN-run half — the signal ROADMAP #6 (mid-campaign replanning) and #4
(SLO-aware scheduling) presuppose: a run must be able to notice that it
got slow *while it is still running*, not in the post-mortem.

Same band semantics as the cross-run sentinel, applied online:

- per metric key, a bounded ring-buffer window of recent **healthy**
  samples (:class:`OnlineWindow`);
- the tolerance band is ``trimean(window) ± max(mad_k * MAD,
  rel_tol * |trimean|, abs_tol)`` — the exact ``perf_tool`` formula,
  computed over the window instead of the ledger history;
- direction-aware via the shared heuristic (:func:`default_direction`
  lives HERE and ``perf_tool`` imports it — one authority, two scopes):
  a seconds-suffixed key only trips HIGH, a throughput key only LOW;
- warmup discipline: nothing is judged until the window holds
  ``min_history`` samples (a cold window must never fire);
- non-finite samples are dropped at insertion (the metrics-ingest rule:
  a NaN must not poison the sorted quantiles);
- anomalous samples are **not** inserted — the band stays anchored on
  healthy history, so a sustained anomaly cannot normalize itself away;
- an active anomaly re-arms only after ``clear_after`` consecutive
  in-band samples (``anomaly.cleared``), after which a new excursion
  fires ``anomaly.detected`` again.

:class:`LiveSentinel` manages the windows and emits the schema-valid
telemetry vocabulary (``obs/telemetry.py NAME_FIELDS``):

- ``anomaly.detected`` — metric, step, value, band, direction;
- ``anomaly.cleared``  — metric, step (the window re-arms);
- ``replan.requested`` — fired on every detection. The ``on_replan``
  callback is where the mid-run plan hot-swap attaches
  (``plan/replan.ReplanController.request`` — the guarded loop finishes
  the current chunk, re-probes the autotuner, and installs the winning
  compiled plan, emitting ``replan.applied``/``replan.rejected``);
  without a hook the default stays record + log.

Fed by ``fault/recover.run_guarded`` (per-chunk step latencies) and the
campaign driver; surfaced by ``obs/status.py`` snapshots and as
Perfetto instant markers (``obs/trace_export.py``).
"""

from __future__ import annotations

import math
from collections import deque
from typing import Callable, Dict, List, Optional

from ..utils import logging as log
from .ledger import mad, trimean

ANOMALY_DETECTED = "anomaly.detected"
ANOMALY_CLEARED = "anomaly.cleared"
REPLAN_REQUESTED = "replan.requested"

# Units/suffixes where smaller is better (times, rc codes); everything
# else (throughputs, ratios, ok flags) defaults to higher-is-better.
# The ONE direction authority — apps/perf_tool.py imports these.
_LOWER_UNITS = ("s", "ms", "us", "rc")
_LOWER_SUFFIXES = ("_s", "_ms", "_seconds", "_iter_ms", ".rc")


def base_metric(name: str) -> str:
    """Strip the report-style ``[method,batched]`` tag suffix so per-leg
    threshold config matches the logical leg name."""
    return name.split("[", 1)[0]


def default_direction(metric: str, unit: Optional[str]) -> str:
    m = base_metric(metric)
    # throughput names ("..._gb_per_s", "mcells_per_s") end in "_s" too —
    # the rate test must run before the seconds-suffix test
    if m.endswith("_per_s") or m.endswith("_per_dev"):
        return "higher"
    if (unit or "") in _LOWER_UNITS or m.endswith(_LOWER_SUFFIXES):
        return "lower"
    return "higher"


class OnlineWindow:
    """One metric key's bounded recent-history window + anomaly state.

    ``observe(value, step)`` returns an event dict when the sample
    transitions the anomaly state (``"detected"`` / ``"cleared"``), else
    None. The window holds only finite, in-band samples, so eviction
    keeps the band anchored on recent *healthy* history.
    """

    def __init__(self, key: str, *, window: int = 64, min_history: int = 4,
                 mad_k: float = 4.0, rel_tol: float = 3.0,
                 abs_tol: float = 0.0, direction: str = "",
                 clear_after: int = 2, unit: Optional[str] = None):
        if window < max(1, int(min_history)):
            # a ValueError, not an assert: under -O an assert vanishes
            # and the window could never reach min_history — a sentinel
            # that silently cannot fire
            raise ValueError(f"{key}: window {window} cannot hold "
                             f"min_history {min_history}")
        self.key = key
        self.unit = unit
        self.samples: deque = deque(maxlen=int(window))
        self.min_history = int(min_history)
        self.mad_k = float(mad_k)
        self.rel_tol = float(rel_tol)
        self.abs_tol = float(abs_tol)
        self.direction = direction or default_direction(key, unit)
        self.clear_after = max(1, int(clear_after))
        self.active: Optional[dict] = None  # the open anomaly, if any
        self.detected = 0
        self.cleared = 0
        self._streak = 0  # consecutive in-band samples while active

    def band(self):
        """(center, lo, hi) of the current window, or None in warmup.

        The high edge uses the perf_tool formula verbatim. The LOW
        edge's relative component is ratio-symmetric —
        ``center·rel_tol/(1+rel_tol)``, i.e. ``lo >= center/(1+rel_tol)``
        — because with the wide default band (rel_tol 3) the additive
        form would put ``lo`` below zero for every positive-valued
        metric, and a "higher"-direction key (a throughput collapse)
        could then never trip. At perf_tool-scale tolerances
        (rel_tol ~0.05) the two forms agree to within 0.3%."""
        if len(self.samples) < self.min_history:
            return None
        center = trimean(self.samples)
        spread = self.mad_k * mad(self.samples)
        tol_hi = max(spread, self.rel_tol * abs(center), self.abs_tol)
        rel_lo = abs(center) * self.rel_tol / (1.0 + self.rel_tol)
        tol_lo = max(spread, rel_lo, self.abs_tol)
        return center, center - tol_lo, center + tol_hi

    def observe(self, value: float, step: int) -> Optional[dict]:
        v = float(value)
        if not math.isfinite(v):
            return None  # dropped at insertion — the metrics-ingest rule
        b = self.band()
        if b is None:
            # warmup: below min_history nothing is judged, ever
            self.samples.append(v)
            return None
        center, lo, hi = b
        bad = ((v < lo and self.direction in ("higher", "both"))
               or (v > hi and self.direction in ("lower", "both")))
        if bad:
            self._streak = 0
            if self.active is None:
                self.active = {
                    "metric": self.key, "step": int(step), "value": v,
                    "center": center, "lo": lo, "hi": hi,
                    "direction": self.direction,
                }
                self.detected += 1
                return dict(self.active, event="detected")
            # still anomalous: extend the open anomaly, do not re-emit
            self.active["last_step"] = int(step)
            self.active["last_value"] = v
            return None
        self.samples.append(v)
        if self.active is not None:
            self._streak += 1
            if self._streak >= self.clear_after:
                ev = {"event": "cleared", "metric": self.key,
                      "step": int(step), "value": v,
                      "since_step": self.active["step"]}
                self.active = None
                self._streak = 0
                self.cleared += 1
                return ev
        return None


def validate_config(config: dict) -> List[str]:
    """Violations of a LiveSentinel config (empty = valid) — checked at
    CLI parse time so a bad knob is an argparse error, not a traceback
    after backend init (or a window that silently can never fire)."""
    errs: List[str] = []
    if not isinstance(config, dict):
        return [f"config must be an object, not {type(config).__name__}"]
    for key, over in config.items():
        if not isinstance(over, dict):
            errs.append(f"{key!r}: overrides must be an object")
            continue
        unknown = sorted(set(over) - set(LiveSentinel._KNOBS))
        if unknown:
            errs.append(f"{key!r}: unknown knob(s) {unknown}")
        for k in ("window", "min_history", "clear_after"):
            v = over.get(k)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, int) or v < 1):
                errs.append(f"{key!r}: {k} must be a positive integer")
        for k in ("mad_k", "rel_tol", "abs_tol"):
            v = over.get(k)
            if v is not None and (isinstance(v, bool)
                                  or not isinstance(v, (int, float))
                                  or not math.isfinite(v) or v < 0):
                errs.append(f"{key!r}: {k} must be a finite number >= 0")
        d = over.get("direction")
        if d is not None and d not in ("", "higher", "lower", "both"):
            errs.append(f"{key!r}: direction must be higher/lower/both")
        # the relation check runs over the MERGED knobs ("*" defaults
        # cascade under per-key overrides, exactly as _window applies
        # them) so a split like {"*": {min_history: 8}, k: {window: 2}}
        # is caught here, not at the first mid-run observe()
        star = config.get("*") if isinstance(config.get("*"), dict) else {}
        merged = {"window": 64, "min_history": 4}
        merged.update({k: v for k, v in star.items() if k in merged})
        merged.update({k: v for k, v in over.items() if k in merged})
        if (isinstance(merged["window"], int)
                and isinstance(merged["min_history"], int)
                and merged["window"] < max(1, merged["min_history"])):
            errs.append(f"{key!r}: window {merged['window']} cannot hold "
                        f"min_history {merged['min_history']}")
    return errs


class LiveSentinel:
    """Per-key online windows + the telemetry/replan emission policy.

    ``config`` follows the ``perf_tool --leg-config`` shape:
    ``{"*": {...defaults...}, "<key>": {...overrides...}}`` with the
    knobs window/min_history/mad_k/rel_tol/abs_tol/direction/clear_after;
    a tagged key (``step.latency_s[16x16x16]``) inherits its
    :func:`base_metric` overrides like the cross-run gate does.

    Every detection also emits ``replan.requested`` (unless
    ``replan=False``) and invokes ``on_replan(event)`` when given — the
    mid-run plan hot-swap's trigger (``plan/replan.ReplanController``
    latches the request here and performs the swap between guarded-loop
    chunks); the default is record + log, never an exception (a broken
    replan hook must not kill the measurement).
    """

    _KNOBS = ("window", "min_history", "mad_k", "rel_tol", "abs_tol",
              "direction", "clear_after")

    def __init__(self, config: Optional[dict] = None, *, rec=None,
                 replan: bool = True,
                 on_replan: Optional[Callable[[dict], None]] = None):
        self.config = dict(config or {})
        self._rec = rec
        self.replan = bool(replan)
        self.on_replan = on_replan
        self.windows: Dict[str, OnlineWindow] = {}
        # detect/clear history of windows dropped by reset() — run
        # totals must survive a plan hot-swap's window reset
        self._retired_detected = 0
        self._retired_cleared = 0

    def _recorder(self):
        if self._rec is not None:
            return self._rec
        from . import telemetry

        return telemetry.get()

    def _window(self, key: str, unit: Optional[str]) -> OnlineWindow:
        w = self.windows.get(key)
        if w is None:
            over = dict(self.config.get("*", {}))
            over.update(self.config.get(base_metric(key), {}))
            over.update(self.config.get(key, {}))
            kw = {k: over[k] for k in self._KNOBS if k in over}
            w = self.windows[key] = OnlineWindow(key, unit=unit, **kw)
        return w

    def observe(self, key: str, value: float, *, step: int,
                unit: Optional[str] = None, **tags) -> Optional[dict]:
        """Feed one sample; emit the vocabulary on a state transition."""
        ev = self._window(key, unit).observe(value, step)
        if ev is None:
            return None
        rec = self._recorder()
        if ev["event"] == "detected":
            rec.meta(ANOMALY_DETECTED, metric=key, step=ev["step"],
                     value=ev["value"], center=ev["center"], lo=ev["lo"],
                     hi=ev["hi"], direction=ev["direction"], phase="live",
                     **tags)
            log.warn(
                f"live: ANOMALY {key} at step {ev['step']}: "
                f"{ev['value']:.6g} outside [{ev['lo']:.6g}, "
                f"{ev['hi']:.6g}] ({ev['direction']})")
            if self.replan:
                rec.meta(REPLAN_REQUESTED, reason=f"anomaly:{key}",
                         step=ev["step"], metric=key, phase="live")
                log.warn(f"live: replan requested (anomaly in {key}"
                         + ("; hot-swap hook attached)"
                            if self.on_replan is not None
                            else "; no hot-swap hook — recorded only)"))
                if self.on_replan is not None:
                    try:
                        self.on_replan(dict(ev))
                    except Exception as e:  # the hook must never kill a run
                        log.warn(f"live: replan hook failed: {e}")
        else:
            rec.meta(ANOMALY_CLEARED, metric=key, step=ev["step"],
                     value=ev["value"], since_step=ev["since_step"],
                     phase="live", **tags)
            log.warn(f"live: anomaly in {key} cleared at step {ev['step']} "
                     f"(open since step {ev['since_step']})")
        return ev

    def reset(self, key: Optional[str] = None) -> None:
        """Drop the window(s) — ALL of them, or one key's — so judgment
        restarts from warmup. The plan hot-swap calls this after
        ``replan.applied``: the old window's band describes the OLD
        compiled plan's latencies, and judging the new plan (plus its
        one-time swap-compile spike) against it would re-trip the
        sentinel on the first post-swap chunk. Detected/cleared totals
        are preserved — they are run history, not window state."""
        doomed = (list(self.windows.values()) if key is None
                  else [w for k, w in self.windows.items() if k == key])
        for w in doomed:
            self._retired_detected += w.detected
            self._retired_cleared += w.cleared
        if key is None:
            self.windows.clear()
        else:
            self.windows.pop(key, None)

    # -- state for status snapshots -------------------------------------------
    @property
    def detected_total(self) -> int:
        return (self._retired_detected
                + sum(w.detected for w in self.windows.values()))

    @property
    def cleared_total(self) -> int:
        return (self._retired_cleared
                + sum(w.cleared for w in self.windows.values()))

    def active(self) -> List[dict]:
        return [dict(w.active) for w in self.windows.values()
                if w.active is not None]

    def summary(self) -> dict:
        """The ``anomalies`` section of a status snapshot."""
        return {
            "active": self.active(),
            "detected": self.detected_total,
            "cleared": self.cleared_total,
        }
