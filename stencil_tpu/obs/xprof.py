"""Attribute DEVICE time to the plan's named ranges from an xprof dump.

Host-side spans (``obs/attribution.py``) time the dispatch side of an
exchange; on a real TPU the interesting seconds are on the device, and
``jax.profiler``'s programmatic capture already records them — tagged
with the very ``trace_range`` names the host spans use, because
``utils/timer.trace_range`` wraps ``jax.profiler.TraceAnnotation``.
This module turns one capture directory into per-range device seconds
keyed by those names, so a TPU session's attribution records carry
measured DEVICE time through the same ``plan.attrib.phase`` vocabulary
(ROADMAP #1: the scarce hardware session auto-refits its calibration).

Parsing is pure stdlib (gzip + json) over the Chrome-trace JSON the
profiler writes under ``<logdir>/plugins/profile/<run>/``
(``*.trace.json`` / ``*.trace.json.gz``): sum complete-event ("X")
durations per event name, with the ``#…#`` argument suffix XLA appends
stripped so "stencil.exchange#fused=…#" folds into "stencil.exchange".
The TensorFlow-side protobuf tooling is deliberately NOT a dependency —
a capture must be readable on the backend-less analysis box that runs
``plan_tool calibrate``.

``capture()`` is the collection side: a contextmanager around
``jax.profiler.start_trace/stop_trace`` that degrades to a no-op when
the profiler is unavailable or the platform is not TPU (CPU captures
cost seconds and attribute nothing the host spans don't already have).
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
from typing import Dict, Iterator, Optional, Sequence

TRACE_GLOBS = ("*.trace.json.gz", "*.trace.json")


def _iter_trace_files(logdir: str) -> Iterator[str]:
    # the profiler nests runs under plugins/profile/<timestamp>/; accept
    # a bare directory of dumps too so tests can synthesize one
    roots = [logdir, os.path.join(logdir, "plugins", "profile")]
    seen = set()
    for root in roots:
        for pat in TRACE_GLOBS:
            for path in sorted(glob.glob(os.path.join(root, pat)) +
                               glob.glob(os.path.join(root, "*", pat))):
                if path not in seen:
                    seen.add(path)
                    yield path


def _load_trace(path: str) -> dict:
    if path.endswith(".gz"):
        with gzip.open(path, "rt", encoding="utf-8") as f:
            return json.load(f)
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _base_name(name: str) -> str:
    # XLA suffixes annotations with #key=value# arg blocks; fold them
    i = name.find("#")
    return name[:i] if i > 0 else name


def range_seconds(logdir: str,
                  names: Optional[Sequence[str]] = None
                  ) -> Dict[str, float]:
    """Total device seconds per named range across every trace dump
    under ``logdir``. ``names`` filters to the ranges of interest
    (None = all). Durations are Chrome-trace microseconds."""
    want = set(names) if names is not None else None
    totals: Dict[str, float] = {}
    for path in _iter_trace_files(logdir):
        try:
            doc = _load_trace(path)
        except (OSError, ValueError):
            continue  # a truncated dump attributes nothing
        events = doc.get("traceEvents") or []
        for ev in events:
            if not isinstance(ev, dict) or ev.get("ph") != "X":
                continue
            name = _base_name(str(ev.get("name", "")))
            if not name or (want is not None and name not in want):
                continue
            dur = ev.get("dur")
            if isinstance(dur, (int, float)) and dur > 0:
                totals[name] = totals.get(name, 0.0) + dur / 1e6
    return totals


@contextlib.contextmanager
def capture(logdir: Optional[str]):
    """Programmatic profiler capture, gated: yields True when a trace
    is actually being recorded (TPU with a working profiler), False
    otherwise — callers decide whether to parse ``logdir`` after.

    Never raises out of the gate: a broken profiler must not take the
    run it was meant to observe down with it."""
    if not logdir:
        yield False
        return
    try:
        import jax
        if jax.default_backend() != "tpu":
            yield False
            return
        jax.profiler.start_trace(logdir)
    except Exception:
        yield False
        return
    try:
        yield True
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
