"""Structured telemetry: spans, counters, gauges → one-JSON-per-line sink.

The reference keeps its performance story honest with global timer buckets
(timer.hpp:44-47), NVTX ranges throughout src/stencil.cu, and Allreduced
per-method byte counters (src/stencil.cu:139-161,620-627). This module
unifies the TPU port's analogues of all three — ``utils/timer.py`` buckets
+ ``jax.profiler`` annotations, ``utils/hlo_check.collective_census``, and
``utils/mosaic_traffic`` — behind one recorder whose records land as one
JSON object per line in a metrics sink (``--metrics-out`` on every bench
app), machine-readable by ``apps/report.py`` and CI.

Record schema (v1) — every line carries:

- ``v``:     schema version (1)
- ``run``:   run id (shared by every record of one measurement run)
- ``proc``:  JAX process index (0 when no backend is up — resolved lazily,
             same discipline as utils/logging: recording a line must never
             initialize a backend)
- ``kind``:  ``span`` | ``counter`` | ``gauge`` | ``meta`` | ``heartbeat``
- ``name``:  record name (e.g. ``jacobi.iter``, ``census.collective-permute``)
- ``t``:     unix wall time of emission

plus per kind: spans carry ``seconds`` (and usually ``phase``); counters
carry ``value`` (a count) and/or ``bytes`` (a byte total — "bytes where
applicable"); gauges carry ``value``; heartbeats carry ``seq``; metas are
free-form. Anything else (``app``, ``phase``, ``method``, ``iters``, ...)
is an optional tag. :func:`validate_record` is the one schema authority —
CI validates every emitted line through it (``apps/report.py --validate``).

Spans ride :func:`stencil_tpu.utils.timer.timed` (global buckets keep
accumulating exactly as before) and ``timer.trace_range`` (so
``jax.profiler`` gets the same named range for free).

Heartbeats close the loop with :mod:`stencil_tpu.obs.watchdog`: when the
supervisor set ``STENCIL_HEARTBEAT_FILE``, every emitted record (and a
background thread, for long silent stretches like a 3-minute kernel
compile) touches that file; the watchdog reads only its mtime.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
import uuid
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils import timer
from .watchdog import HEARTBEAT_FILE_ENV, HEARTBEAT_INTERVAL_ENV

SCHEMA_VERSION = 1
KINDS = ("span", "counter", "gauge", "meta", "heartbeat")
REQUIRED_KEYS = ("v", "run", "proc", "kind", "name", "t")

# Name-specific vocabulary (still schema v1): the fault/health/recover
# records the self-healing layer (stencil_tpu/fault/) emits carry typed
# payload fields the CI fault gate greps for — validate them here so a
# renamed or untyped field fails the schema gate, not a post-mortem.
# The campaign.* and compile.* names are the multi-tenant layer's
# vocabulary (stencil_tpu/campaign/): eviction/backfill provenance and
# the compile-cache economics the campaign CI gate pins.
NAME_FIELDS = {
    "fault.injected": (("fault_kind", str), ("step", int)),
    "health.fault": (("fault_kind", str), ("quantity", str), ("step", int)),
    "health.check": (("step", int),),
    "recover.fault": (("fault_kind", str), ("step", int)),
    "recover.rollback": (("from_step", int), ("to_step", int),
                         ("fault_step", int)),
    "recover.aborted": (("reason", str), ("step", int)),
    "ckpt.save_skipped": (("reason", str),),
    "campaign.slot": (("slot", int),),
    "campaign.retire": (("tenant", str), ("step", int), ("lane", int)),
    "campaign.backfill": (("tenant", str), ("lane", int)),
    "campaign.evict": (("tenant", str), ("step", int), ("rc", int)),
    "campaign.step_latency_s": (("mode", str),),
    "campaign.summary": (("slots", int), ("tenants", int)),
    "compile.cache_hit": (("key", str),),
    "compile.build": (("key", str),),
    "compile.build_s": (("key", str),),
    # the live-observability vocabulary (obs/live.py + campaign SLO
    # tracking): in-run anomaly detect/clear, deadline violations, and
    # the replan trigger ROADMAP #6's hot-swap will consume
    "anomaly.detected": (("metric", str), ("step", int)),
    "anomaly.cleared": (("metric", str), ("step", int)),
    "slo.violation": (("tenant", str), ("step", int)),
    "replan.requested": (("reason", str), ("step", int)),
    # the always-on serving vocabulary (stencil_tpu/serve/): intake
    # admission verdicts (admit / quota-defer / priced rejection),
    # per-tenant result streaming, and the drain/park/revival
    # provenance the serve CI gate greps for
    "serve.admitted": (("job", str),),
    "serve.rejected": (("job", str), ("reason", str)),
    "serve.deferred": (("job", str), ("reason", str)),
    "serve.retired": (("job", str), ("outcome", str)),
    "serve.parked": (("job", str), ("step", int)),
    "serve.drain": (("reason", str),),
    "serve.revived": (("jobs", int),),
    # the capacity engine's decision records: every packed slot names
    # its bucket/width/winner, every preemption (and every veto) names
    # its priced gain against the victims' resume cost, every resize
    # names both widths — "what was chosen and why" is a record, not a
    # log line
    "serve.packed": (("bucket", str), ("width", int)),
    "serve.preempted": (("job", str), ("gain_ms", float),
                        ("resume_cost_ms", float)),
    "serve.preempt_veto": (("job", str), ("gain_ms", float),
                           ("resume_cost_ms", float)),
    "serve.resized": (("from_width", int), ("to_width", int),
                      ("reason", str)),
    # the hot-swap half of ROADMAP #6 (plan/replan.ReplanController):
    # a mid-run replan either installs a new compiled plan (applied —
    # old/new choice labels + the static model's predicted gain rides
    # as an optional modeled_gain tag) or degrades loudly onto the old
    # one (rejected — a throwing autotuner/apply must never kill a run)
    "replan.applied": (("old", str), ("new", str), ("step", int)),
    "replan.rejected": (("reason", str), ("step", int)),
    # the fused compute+exchange vocabulary (ops/fused_stencil +
    # the host-orchestrated fused loops in ops/jacobi /
    # astaroth/integrate): the overlap split of one fused substep —
    # pack+start, interior compute (the hiding window), the recv-
    # semaphore wait, boundary compute — variant-tagged spans so the
    # PR-12 live sentinel and the trace export see where wire time
    # goes; no extra required fields beyond the span schema
    "fused.pack": (),
    "fused.interior": (),
    "fused.dma_wait": (),
    "fused.boundary": (),
    # the hierarchical ICI+DCN level (parallel/hierarchy.py + the fused
    # host loop): the window where cross-host DCN slabs are in flight
    # behind the inner per-host programs — the outer-level analogue of
    # fused.dma_wait
    "fused.dcn": (),
    # the static-analysis vocabulary (stencil_tpu/analysis/): per-config
    # plan-auditor verdicts, the audit summaries the CI static gate
    # archives, and the lint summary — schema-gated like every other
    # subsystem's records
    "analysis.plan_verdict": (("method", str), ("ok", int)),
    "analysis.plan_mismatch": (("method", str),),
    "analysis.plan_sweep": (("checked", int), ("failed", int),
                            ("skipped", int)),
    "analysis.jit_audit": (("ok", int), ("recompiles", int),
                           ("transfers", int)),
    "analysis.lint": (("findings", int), ("new", int)),
    # the plan-observatory vocabulary (obs/attribution.py +
    # plan/calibrate.py): per-exchange-phase measured seconds mapped
    # back onto the ExchangePlan IR's prediction under the installed
    # calibration — the samples plan_tool calibrate fits and perf_tool
    # drift judges. `phase` is the trace_range name of the measured
    # region (so xprof device attribution keys the same way);
    # `collectives` carries the plan's collective count for the permute
    # methods and its DMA count for remote-dma (the per-copy overhead
    # is what the fit recovers there).
    "plan.attrib.phase": (("phase", str), ("method", str),
                          ("predicted_s", float), ("measured_s", float),
                          ("residual", float), ("collectives", int),
                          ("wire_bytes", int)),
    # the active plan + calibration stamp every instrumented run carries
    # (jacobi3d/bench/_bench_common): LEDGER entries become attributable
    # to the plan and calibration provenance that produced them
    "plan.fingerprint": (("fingerprint", str), ("choice", str),
                         ("calibration", str)),
    # a calibrate run's fitted-row summary (plan_tool calibrate)
    "calibration.fitted": (("platform", str), ("n", int),
                           ("provenance", str)),
    # the drift sentinel's in-run verdict: the installed calibration's
    # prediction fell outside the measured phase's trimean±MAD band
    "calibration.drift": (("phase", str), ("predicted_s", float),
                          ("measured_s", float)),
}

# The sanctioned metric-name vocabulary: every LITERAL name the library
# passes to a Recorder record site (span/counter/gauge/meta/emit). The
# repo lint's `telemetry-vocab` rule (analysis/astlint.py) checks record
# sites against this set, so a typo'd metric name fails the static gate
# instead of silently validating (schema v1 constrains record SHAPE, not
# names — a `recover.rollbck` counter is a perfectly valid record that no
# dashboard will ever aggregate). Dynamically-built names (f-strings like
# ``census.{kind}``/``timer.{k}``/``dma.{kernel}.*``) are explicitly
# generic and exempt from the check. Grow this list alongside new
# subsystems — the lint names the site that needs the entry.
KNOWN_NAMES = frozenset(NAME_FIELDS) | frozenset({
    "ablate.bit_for_bit_agreement",
    "analysis.verify_plan", "analysis.jit_warmup", "analysis.jit_audit_loop",
    "astaroth.exch_trimean_s", "astaroth.exchange", "astaroth.init",
    "astaroth.iter", "astaroth.iter_trimean_s", "astaroth.warmup",
    "batched_ab.bit_for_bit_agreement", "batched_ab.q_independent",
    "bench_alltoall.gb_per_s", "bench_link.gb_per_s", "bench_pack.gb_per_s",
    "ckpt.bytes_read", "ckpt.bytes_written", "ckpt.files_written",
    "ckpt.quarantined", "ckpt.restore", "ckpt.restore_skipped",
    "ckpt.resumed", "ckpt.resumed_from_step", "ckpt.save", "ckpt.write",
    "config",
    "dma.capture_error", "dma.skipped",
    "exchange.bytes_logical", "exchange.bytes_moved",
    "exchange.bytes_on_wire", "exchange.bytes_on_wire_per_quantity",
    "exchange.gb_per_s", "exchange.iter", "exchange.launches_per_chunk",
    "exchange.permutes_per_quantity",
    "exchange.trimean_s", "exchange.warmup",
    # interior-compute time over total fused-substep time: how much of
    # the wire the fused schedule actually hid (gauge, variant-tagged)
    "fused.overlap_fraction",
    "hb",
    "jacobi.exchange", "jacobi.exchange_bytes", "jacobi.exchange_warmup",
    "jacobi.init", "jacobi.iter", "jacobi.iter_trimean_s",
    "jacobi.loop_wall_s", "jacobi.mcells_per_s", "jacobi.mcells_per_s_per_dev",
    "jacobi.warmup",
    "live.anomaly_count",
    "machine", "machine.bandwidth_matrix", "machine.device",
    "machine.distance_matrix", "machine.fabric", "machine.partition",
    "overlap.hidden_frac",
    "pingpong.gb_per_s", "pingpong.latency_us",
    "plan.autotune", "plan.cache_hit", "plan.candidates", "plan.chosen",
    "plan.probe", "plan.probe_trimean_s", "plan.probes_run",
    # the placement leg (bench_qap --derived + the plan hot-swap): QAP
    # solver wall/cost rows, the derived-matrix placement cost, and the
    # modeled identity-over-placed improvement ratio
    "qap.cost", "qap.improvement", "qap.placement_cost", "qap.solve_s",
    "recover.backoff_s",
    # the serving daemon's exit gauges: sustained completion rate and
    # per-step tail latency under open-loop arrivals (the ROADMAP #4
    # bench leg), plus the queue-depth gauge the dashboard trends
    "serve.p99_ms", "serve.queue_depth", "serve.slot_width",
    "serve.tenants_per_hour",
    "wire_ab.bytes_ratio", "wire_ab.max_abs_err", "wire_ab.max_rel_err",
    "wire_ab.max_ulp_err",
})


def new_run_id() -> str:
    return time.strftime("%Y%m%dT%H%M%S") + "-" + uuid.uuid4().hex[:8]


class Recorder:
    """One measurement run's telemetry channel.

    ``sink`` is a path (opened append) or a file-like object, or None — a
    disabled recorder still accumulates timer buckets in spans and still
    beats the watchdog heartbeat file, so supervision works even when no
    metrics file was requested.
    """

    def __init__(
        self,
        sink=None,
        run_id: Optional[str] = None,
        app: Optional[str] = None,
        clock=time.time,
    ):
        self.run_id = run_id or new_run_id()
        self.app = app
        self._clock = clock
        self._owns_sink = isinstance(sink, (str, os.PathLike))
        self._sink = open(sink, "a", buffering=1) if self._owns_sink else sink
        self._lock = threading.Lock()
        self._proc: Optional[int] = None
        self._hb_path = os.environ.get(HEARTBEAT_FILE_ENV) or None
        self._hb_interval = float(
            os.environ.get(HEARTBEAT_INTERVAL_ENV, "5") or 5
        )
        self._hb_last = 0.0
        self._hb_seq = 0
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        # progress the heartbeat payload quotes (obs/watchdog contract:
        # readers that only stat the mtime keep working; JSON-aware ones
        # can say WHERE the run stalled). Shared with the beat thread —
        # plain dict reads/writes, races are benign (a beat quotes either
        # the old or the new step, both true recently).
        self._progress: Dict[str, object] = {}

    @property
    def enabled(self) -> bool:
        """True when records are actually written somewhere."""
        return self._sink is not None

    # -- emission ------------------------------------------------------------
    def emit(self, kind: str, name: str, *, phase: Optional[str] = None,
             **fields) -> dict:
        """Build one record, write it to the sink, touch the heartbeat.

        Returns the record dict either way, so callers (machine_info
        ``--json``) can route records themselves.
        """
        if self._proc is None:
            # cache only once a backend answered; 0 from a backend-less
            # process stays re-resolvable (utils/logging._prefix discipline)
            proc = 0
            jax = sys.modules.get("jax")
            if jax is not None:
                try:
                    proc = jax.process_index()
                    self._proc = proc
                except Exception:
                    pass
        else:
            proc = self._proc
        rec = {
            "v": SCHEMA_VERSION,
            "run": self.run_id,
            "proc": proc,
            "kind": kind,
            "name": name,
            "t": self._clock(),
        }
        if self.app:
            rec["app"] = self.app
        if phase is not None:
            rec["phase"] = phase
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        if self._sink is not None:
            line = json.dumps(rec, default=str)
            with self._lock:
                self._sink.write(line + "\n")
                try:
                    self._sink.flush()
                except (OSError, ValueError):
                    pass
        self._maybe_beat()
        return rec

    @contextlib.contextmanager
    def span(self, name: str, phase: Optional[str] = None,
             bucket: Optional[str] = None, **tags):
        """Timed region: timer bucket + profiler range + one span record.

        The record is emitted even when the body raises (the failed span
        is evidence), and the exception propagates — same discipline as
        ``timer.trace_range``.
        """
        t0 = time.perf_counter()
        prev_span = self._progress.get("span")
        self._progress["span"] = name  # the heartbeat payload quotes this
        try:
            with timer.timed(bucket or name), timer.trace_range(name):
                yield
        finally:
            self._progress["span"] = prev_span
            self.emit("span", name, phase=phase,
                      seconds=time.perf_counter() - t0, **tags)

    def counter(self, name: str, value: Optional[int] = None,
                bytes: Optional[int] = None, phase: Optional[str] = None,
                **tags) -> dict:
        return self.emit("counter", name, phase=phase, value=value,
                         bytes=bytes, **tags)

    def gauge(self, name: str, value: float, phase: Optional[str] = None,
              unit: Optional[str] = None, **tags) -> dict:
        return self.emit("gauge", name, phase=phase, value=value, unit=unit,
                         **tags)

    def meta(self, name: str, **fields) -> dict:
        return self.emit("meta", name, **fields)

    # -- heartbeat (watchdog contract) ---------------------------------------
    def heartbeat(self) -> None:
        """Touch the watchdog heartbeat file + emit a heartbeat record."""
        self._hb_seq += 1
        self._touch_hb()
        if self._sink is not None:
            self.emit("heartbeat", "hb", seq=self._hb_seq)
        else:
            self._hb_last = time.monotonic()

    def note_step(self, step: int) -> None:
        """Record the last completed step for the heartbeat payload
        (the guarded loop calls this per chunk): a stall report can then
        say "stalled at step 412 in exchange" instead of a bare age."""
        self._progress["step"] = int(step)

    def _touch_hb(self) -> None:
        if not self._hb_path:
            return
        # the body is a tiny JSON note (last step, current span) the
        # watchdog's stall report quotes; the LIVENESS contract is still
        # mtime-only, so pure-stdlib readers that just stat() keep
        # working and a hand-touched beat file stays a valid beat
        note = {"t": time.time()}
        note.update({k: v for k, v in self._progress.items()
                     if v is not None})
        try:
            with open(self._hb_path, "w") as f:
                f.write(json.dumps(note) + "\n")
        except (OSError, TypeError, ValueError):
            pass  # a torn-down supervisor must not crash the measurement

    def _maybe_beat(self) -> None:
        """Rate-limited beat on every emission: a chatty child never needs
        an explicit heartbeat call."""
        if not self._hb_path:
            return
        now = time.monotonic()
        if now - self._hb_last >= self._hb_interval:
            self._hb_last = now
            self._touch_hb()

    def start_heartbeat_thread(self, interval_s: Optional[float] = None) -> bool:
        """Beat from a daemon thread so long silent stretches (multi-minute
        XLA compiles) do not read as stalls. A hard wedge that freezes the
        interpreter freezes this thread too — which is exactly when the
        watchdog SHOULD fire. No-op (returns False) without a supervisor.
        """
        if not self._hb_path or self._hb_thread is not None:
            return False
        interval = interval_s or self._hb_interval

        def beat():
            while not self._hb_stop.wait(interval):
                self._hb_seq += 1
                self._touch_hb()

        self._touch_hb()  # first beat immediately: starts the stall clock
        self._hb_thread = threading.Thread(
            target=beat, name="stencil-heartbeat", daemon=True
        )
        self._hb_thread.start()
        return True

    # -- convenience ---------------------------------------------------------
    def record_timer_buckets(self, phase: Optional[str] = None) -> None:
        """Snapshot utils/timer's global buckets as gauges (the machine
        analogue of the apps' exit-time ``timers:`` line)."""
        for k, v in sorted(timer.buckets.items()):
            self.gauge(f"timer.{k}", v, phase=phase, unit="s")

    def close(self) -> None:
        self._hb_stop.set()
        if self._owns_sink and self._sink is not None:
            try:
                self._sink.close()
            finally:
                self._sink = None


# -- module-level default recorder -------------------------------------------

_recorder: Optional[Recorder] = None


def configure(metrics_out: Optional[str] = None, app: Optional[str] = None,
              run_id: Optional[str] = None, config: Optional[dict] = None,
              heartbeat_thread: bool = True) -> Recorder:
    """Install the process-default recorder (what ``--metrics-out`` wires).

    Emits the run's identity/config meta record first so every metrics
    file is self-describing, and starts the watchdog heartbeat thread when
    a supervisor is attached.
    """
    global _recorder
    if _recorder is not None:
        _recorder.close()
    _recorder = Recorder(sink=metrics_out or None, app=app, run_id=run_id)
    if config:
        clean = {k: v for k, v in config.items()
                 if isinstance(v, (str, int, float, bool, type(None)))}
        _recorder.meta("config", config=clean)
    if heartbeat_thread:
        _recorder.start_heartbeat_thread()
    return _recorder


def get() -> Recorder:
    """The process-default recorder (a disabled one before configure())."""
    global _recorder
    if _recorder is None:
        _recorder = Recorder(sink=None)
    return _recorder


def enabled() -> bool:
    return _recorder is not None and _recorder.enabled


# -- static truth: what the compiled artifacts say moves ---------------------


def record_census(census: Dict[str, Tuple[int, int]],
                  rec: Optional[Recorder] = None, **tags) -> None:
    """Record a ``collective_census`` result ({kind: (count, bytes)}) —
    one counter line per collective kind."""
    rec = rec or get()
    for kind, (count, nbytes) in sorted(census.items()):
        rec.counter(f"census.{kind}", value=count, bytes=nbytes,
                    phase="exchange", **tags)


def record_exchange_truth(ex, state, itemsizes: Sequence[int],
                          rec: Optional[Recorder] = None, **tags) -> dict:
    """Attach one exchange method's compile-time truth to the run: the
    collective census of the compiled program (exact on-wire volume — the
    analogue of the reference's Allreduced per-method byte counters,
    src/stencil.cu:139-161) plus the logical/moved byte accounting.

    Compiles one single-exchange program; callers gate on
    :func:`enabled` so metric-less runs pay nothing.

    Besides the raw census, records the packed on-wire totals and the
    ``exchange.permutes_per_quantity`` gauge — permute ops divided by the
    quantity count. With quantity batching this reads ~6/Q for the
    composed plan (one packed carrier pair per axis phase, Q-independent
    count); a reading that scales back up toward 6 (or 26) per quantity
    at Q > 1 flags a regression to per-quantity collectives
    (apps/report.py surfaces the gauge).
    """
    rec = rec or get()
    census = ex.collective_census(state)
    method = getattr(ex.method, "value", str(ex.method))
    nq = max(1, len(itemsizes))
    record_census(census, rec, method=method, **tags)
    from ..utils.hlo_check import census_per_quantity

    on_wire = sum(b for _c, b in census.values())
    rec.counter("exchange.bytes_on_wire", bytes=on_wire, phase="exchange",
                method=method, quantities=nq, **tags)
    per_q = census_per_quantity(census, nq)
    rec.counter(
        "exchange.bytes_on_wire_per_quantity",
        bytes=sum(b for _c, b in per_q.values()),
        phase="exchange", method=method, quantities=nq, **tags,
    )
    cp_count = census.get("collective-permute", (0, 0))[0]
    rec.gauge("exchange.permutes_per_quantity", cp_count / nq,
              phase="exchange", method=method, quantities=nq, **tags)
    # launch-count census (ROADMAP #7): the step driver's measured host
    # dispatches per chunk when a persistent/multistep loop ran
    # (ops/jacobi sets last_launches_per_chunk), else the plan's static
    # prediction — tagged so the auditor and the CI pin can tell a
    # measurement from a model (utils/hlo_check.kernel_launch_census is
    # the compiled-module side of the same evidence)
    lpc = getattr(ex, "last_launches_per_chunk", 0)
    src = "measured"
    if not lpc:
        plan = getattr(ex, "plan", None)
        lpc = plan.launches_per_chunk() if plan is not None else 0
        src = "modeled"
    if lpc:
        rec.gauge("exchange.launches_per_chunk", lpc, phase="exchange",
                  method=method, source=src, **tags)
    rec.counter("exchange.bytes_logical", bytes=ex.bytes_logical(itemsizes),
                phase="exchange", method=method, **tags)
    rec.counter("exchange.bytes_moved", bytes=ex.bytes_moved(itemsizes),
                phase="exchange", method=method, **tags)
    return census


def record_dma_traffic(build, rec: Optional[Recorder] = None,
                       **tags) -> list:
    """Attach the Mosaic kernels' static DMA truth: lower ``build()``'s
    Pallas kernels for the TPU platform (utils/mosaic_traffic) and record
    per-kernel HBM input/output bytes per grid pass.

    Expensive (a full TPU lowering) and not reentrant — callers gate it
    behind an explicit flag. A capture failure records a meta line instead
    of raising: the DMA truth is evidence, never the measurement.
    """
    rec = rec or get()
    from ..utils.mosaic_traffic import capture_traffic

    try:
        kernels = capture_traffic(build)
    except Exception as e:
        rec.meta("dma.capture_error", error=f"{type(e).__name__}: {e}"[:400],
                 **tags)
        return []
    for kt in kernels:
        rec.counter(f"dma.{kt.name}.in", bytes=kt.input_bytes(),
                    value=kt.steps, phase="compute", grid=list(kt.grid),
                    **tags)
        rec.counter(f"dma.{kt.name}.out", bytes=kt.output_bytes(),
                    value=kt.steps, phase="compute", grid=list(kt.grid),
                    **tags)
    return kernels


# -- schema validation (the authority apps/report.py + CI use) ---------------


def validate_record(rec) -> List[str]:
    """Return the list of schema violations (empty = valid v1 record)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"not an object: {type(rec).__name__}"]
    for k in REQUIRED_KEYS:
        if k not in rec:
            errs.append(f"missing required key {k!r}")
    if errs:
        return errs
    if rec["v"] != SCHEMA_VERSION:
        errs.append(f"unknown schema version {rec['v']!r}")
    if not isinstance(rec["run"], str) or not rec["run"]:
        errs.append("run must be a non-empty string")
    if not isinstance(rec["proc"], int):
        errs.append("proc must be an int")
    if not isinstance(rec["name"], str) or not rec["name"]:
        errs.append("name must be a non-empty string")
    if not isinstance(rec["t"], (int, float)):
        errs.append("t must be a number")
    kind = rec["kind"]
    if kind not in KINDS:
        errs.append(f"unknown kind {kind!r}")
    elif kind == "span":
        if not isinstance(rec.get("seconds"), (int, float)):
            errs.append("span requires numeric 'seconds'")
    elif kind == "counter":
        if not isinstance(rec.get("value"), int) and not isinstance(
                rec.get("bytes"), int):
            errs.append("counter requires integer 'value' and/or 'bytes'")
    elif kind == "gauge":
        if not isinstance(rec.get("value"), (int, float)):
            errs.append("gauge requires numeric 'value'")
    elif kind == "heartbeat":
        if not isinstance(rec.get("seq"), int):
            errs.append("heartbeat requires integer 'seq'")
    if "bytes" in rec and not isinstance(rec["bytes"], int):
        errs.append("'bytes' must be an integer where present")
    for fld, typ in NAME_FIELDS.get(rec["name"], ()):
        v = rec.get(fld)
        if not isinstance(v, typ) or (typ is int and isinstance(v, bool)):
            errs.append(
                f"{rec['name']} requires {typ.__name__} {fld!r}")
    return errs


def validate_jsonl(lines: Iterable[str]) -> Tuple[int, List[str]]:
    """Validate an iterable of JSONL lines; returns (n_valid, errors)."""
    n_ok = 0
    errors: List[str] = []
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            errors.append(f"line {i}: unparseable JSON ({e})")
            continue
        errs = validate_record(rec)
        if errs:
            errors.extend(f"line {i}: {e}" for e in errs)
        else:
            n_ok += 1
    return n_ok, errors
