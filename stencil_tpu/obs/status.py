"""Run-status snapshots: one atomic JSON file that always says "now".

The metrics JSONL is an append-only event log — great evidence, slow
"where is my run" reading (``report --follow`` re-aggregates the whole
file every redraw). This module is the O(1) complement: a single small
JSON document rewritten once per chunk through the ledger/ckpt write
discipline (tmp + fsync + atomic rename, so a reader NEVER sees a torn
snapshot), holding exactly what an operator polls for:

- current step / target iters, rolling per-step latency + throughput;
- health counts (checks, faults, rollbacks) from the guarded loop;
- the live sentinel's anomaly state (active excursions + totals);
- per-lane tenant states in a campaign (tenant, step, online p50/p99,
  deadline, SLO verdict).

``apps/report.py --status`` is the matching top-like reader (one-shot,
or re-rendered in place with ``--follow``); CI's live gate polls the
file mid-run to prove detection happens *during* the run.

Status document (schema v1)::

    {"v": 1, "kind": "run-status", "run": str|null, "app": str|null,
     "t": unix seconds of the last update,
     "step": int?, "iters": int?, "outcome": str?,
     "per_step_s": float?, "steps_per_s": float?,
     "health": {"checks": int, "faults": int, "rollbacks": int}?,
     "anomalies": {"active": [...], "detected": int, "cleared": int}?,
     "lanes": [{"lane": int, "tenant": str|null, "step": int?,
                "steps": int?, "p50_ms": float?, "p99_ms": float?,
                "deadline_ms": float?, "slo": "ok"|"violated"|null}]?,
     "slo": {"violations": [tid, ...]}?,
     "queue": {"depth": int, "admitted": int, "rejected": int,
               "backfills": int, ...}?}

The ``queue`` section is the serving daemon's (stencil_tpu/serve/):
waiting depth plus cumulative admission counters, so ``report --status
--follow`` reads as a serving dashboard. Additive — this function stays
the single schema authority.

PURE STDLIB by the watchdog/ledger contract: a supervisor (or a human's
``watch``) must be able to read the file without the package.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import List, Optional

STATUS_VERSION = 1
STATUS_KIND = "run-status"


def write_status(path: str, doc: dict) -> None:
    """Atomically replace ``path`` with ``doc`` (tmp + fsync + rename —
    the ledger discipline: a poll never reads a torn snapshot)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".tmp-{os.path.basename(path)}-{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_status(path: str) -> Optional[dict]:
    """The snapshot, or None when missing/unparseable (a reader polls —
    absence means the run has not started or the file moved)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


def validate_status(doc) -> List[str]:
    """Schema violations of one status document (empty = valid v1)."""
    if not isinstance(doc, dict):
        return [f"not an object: {type(doc).__name__}"]
    errs: List[str] = []
    if doc.get("v") != STATUS_VERSION:
        errs.append(f"unknown status version {doc.get('v')!r}")
    if doc.get("kind") != STATUS_KIND:
        errs.append(f"unknown kind {doc.get('kind')!r}")
    if not isinstance(doc.get("t"), (int, float)):
        errs.append("t must be a number")
    for fld in ("run", "app", "outcome"):
        if doc.get(fld) is not None and not isinstance(doc[fld], str):
            errs.append(f"{fld} must be a string or null")
    for fld in ("step", "iters"):
        v = doc.get(fld)
        if v is not None and (isinstance(v, bool) or not isinstance(v, int)):
            errs.append(f"{fld} must be an integer where present")
    for fld in ("per_step_s", "steps_per_s"):
        v = doc.get(fld)
        if v is not None and not isinstance(v, (int, float)):
            errs.append(f"{fld} must be a number where present")
    h = doc.get("health")
    if h is not None:
        if not isinstance(h, dict):
            errs.append("health must be an object")
        else:
            for fld in ("checks", "faults", "rollbacks"):
                if not isinstance(h.get(fld), int):
                    errs.append(f"health.{fld} must be an integer")
    a = doc.get("anomalies")
    if a is not None:
        if not isinstance(a, dict) or not isinstance(a.get("active"), list):
            errs.append("anomalies must be an object with an 'active' list")
        else:
            for fld in ("detected", "cleared"):
                if not isinstance(a.get(fld), int):
                    errs.append(f"anomalies.{fld} must be an integer")
            for i, ev in enumerate(a["active"]):
                if not isinstance(ev, dict) or not ev.get("metric"):
                    errs.append(f"anomalies.active[{i}] must name a metric")
    lanes = doc.get("lanes")
    if lanes is not None:
        if not isinstance(lanes, list):
            errs.append("lanes must be a list")
        else:
            for i, ln in enumerate(lanes):
                if not isinstance(ln, dict) or not isinstance(
                        ln.get("lane"), int):
                    errs.append(f"lanes[{i}] must carry an integer 'lane'")
                elif ln.get("slo") not in (None, "ok", "violated"):
                    errs.append(f"lanes[{i}].slo must be ok/violated/null")
    s = doc.get("slo")
    if s is not None and (not isinstance(s, dict)
                          or not isinstance(s.get("violations"), list)):
        errs.append("slo must be an object with a 'violations' list")
    q = doc.get("queue")
    if q is not None:
        if not isinstance(q, dict):
            errs.append("queue must be an object")
        else:
            for fld in ("depth", "admitted", "rejected", "backfills"):
                v = q.get(fld)
                if isinstance(v, bool) or not isinstance(v, int):
                    errs.append(f"queue.{fld} must be an integer")
            # capacity-engine counters (additive, optional: older
            # daemons never wrote them)
            for fld in ("preempted", "resized", "width"):
                v = q.get(fld)
                if v is not None and (isinstance(v, bool)
                                      or not isinstance(v, int)):
                    errs.append(f"queue.{fld} must be an integer")
    return errs


class StatusWriter:
    """The writer side: a persistent document merged per update and
    atomically flushed — the guarded loop updates step/health/anomalies,
    the campaign driver updates lanes/slo, and every update rewrites the
    ONE file (last-writer-wins per section is exactly right: each
    section has one owner)."""

    def __init__(self, path: str, *, app: Optional[str] = None,
                 run: Optional[str] = None, clock=time.time):
        self.path = path
        self._clock = clock
        self.doc: dict = {
            "v": STATUS_VERSION,
            "kind": STATUS_KIND,
            "run": run,
            "app": app,
            "t": clock(),
        }

    def set(self, **fields) -> dict:
        """Merge the given (non-None) fields WITHOUT flushing — for a
        section owner that runs inside someone else's update cycle (the
        campaign driver stages lanes/slo in ``on_chunk``; the guarded
        loop's per-chunk :meth:`update` flushes everything in ONE
        atomic write instead of two fsync+rename cycles per chunk)."""
        for k, v in fields.items():
            if v is not None:
                self.doc[k] = v
        return self.doc

    def update(self, **fields) -> dict:
        """Merge the given (non-None) fields, stamp ``t``, flush. A
        write failure is logged to the doc, never raised — status is
        evidence, not the measurement."""
        for k, v in fields.items():
            if v is not None:
                self.doc[k] = v
        self.doc["t"] = self._clock()
        try:
            write_status(self.path, self.doc)
        except OSError:
            pass  # a torn-down status dir must not crash the run
        return self.doc


def _age(t: float) -> str:
    age = time.time() - t
    return f"{age:.1f}s ago" if age >= 0 else "in the future?"


def render_status(doc: dict, now: Optional[float] = None) -> str:
    """The top-like rendering ``report --status`` shows."""
    lines: List[str] = []
    head = f"run {doc.get('run') or '-'}"
    if doc.get("app"):
        head += f" ({doc['app']})"
    step, iters = doc.get("step"), doc.get("iters")
    if step is not None:
        head += f" · step {step}"
        if iters:
            head += f"/{iters} ({100.0 * step / iters:.0f}%)"
    per = doc.get("per_step_s")
    if isinstance(per, (int, float)) and math.isfinite(per):
        head += f" · {per:.6g} s/step"
        if per > 0:
            head += f" · {1.0 / per:.4g} steps/s"
    if doc.get("outcome"):
        head += f" · outcome={doc['outcome']}"
    if isinstance(doc.get("t"), (int, float)):
        head += f" · updated {_age(doc['t'])}"
    lines.append(head)
    h = doc.get("health")
    a = doc.get("anomalies")
    parts = []
    if isinstance(h, dict):
        parts.append(f"health: checks={h.get('checks', 0)} "
                     f"faults={h.get('faults', 0)} "
                     f"rollbacks={h.get('rollbacks', 0)}")
    if isinstance(a, dict):
        parts.append(f"anomalies: {len(a.get('active') or [])} active, "
                     f"{a.get('detected', 0)} detected, "
                     f"{a.get('cleared', 0)} cleared")
    if parts:
        lines.append(" · ".join(parts))
    q = doc.get("queue")
    if isinstance(q, dict):
        qline = (f"queue: depth={q.get('depth', 0)} "
                 f"admitted={q.get('admitted', 0)} "
                 f"rejected={q.get('rejected', 0)} "
                 f"backfills={q.get('backfills', 0)}")
        if isinstance(q.get("deferred"), int):
            qline += f" deferred={q['deferred']}"
        if isinstance(q.get("retired"), int):
            qline += f" retired={q['retired']}"
        if isinstance(q.get("width"), int):
            qline += f" width={q['width']}"
        if isinstance(q.get("preempted"), int) and q["preempted"]:
            qline += f" preempted={q['preempted']}"
        if isinstance(q.get("resized"), int) and q["resized"]:
            qline += f" resized={q['resized']}"
        lines.append(qline)
    for ev in (a or {}).get("active") or []:
        lines.append(
            f"  ANOMALY {ev.get('metric')} since step {ev.get('step')}: "
            f"value {ev.get('value')} outside "
            f"[{ev.get('lo')}, {ev.get('hi')}] ({ev.get('direction')})")
    slo = doc.get("slo")
    if isinstance(slo, dict) and slo.get("violations"):
        lines.append(f"SLO violations: {', '.join(slo['violations'])}")
    lanes = doc.get("lanes")
    if lanes:
        lines.append("lanes:")
        lines.append("  lane  tenant        step/steps  p50_ms    p99_ms"
                     "    deadline_ms  slo")
        for ln in lanes:
            def fnum(v):
                return f"{v:.4g}" if isinstance(v, (int, float)) else "-"

            steps = (f"{ln.get('step', '-')}/{ln.get('steps', '-')}"
                     if ln.get("tenant") else "-")
            lines.append(
                f"  {ln.get('lane', '-'):<5} "
                f"{(ln.get('tenant') or '(dead)'):<13} "
                f"{steps:<11} "
                f"{fnum(ln.get('p50_ms')):<9} "
                f"{fnum(ln.get('p99_ms')):<9} "
                f"{fnum(ln.get('deadline_ms')):<12} "
                f"{ln.get('slo') or '-'}")
    return "\n".join(lines)
