"""Weighted fairness and elastic slot width: the capacity policies.

Two small, pure policy objects the scheduler consults at chunk and slot
boundaries — no devices, no state files, fully simulable in tests:

- :class:`FairnessPolicy` replaces the strict priority sort with
  STRIDE-style weighted shares plus deadline-aware aging. Every class
  carries a virtual "pass" that advances by ``1/weight`` per job served;
  the class with the lowest pass leads the next slot, so over a
  sustained backlog each class's served share converges to its weight
  fraction — doubling a weight can only raise that share (the monotone
  property tests/test_serve_capacity.py pins). Aging handles urgency the
  shares cannot: a job's EFFECTIVE rank decays from its class rank
  toward 0 at ``1/aging_s`` per second (the queue's sort key), and a job
  that has waited longer than ``aging_s * (rank + 1)`` becomes URGENT —
  it overrides the stride choice outright, which is the hard bound on
  ``low`` wait under sustained ``high`` load. Sustained pressure thus
  degrades ``low`` p99 smoothly (shares), never to infinity (aging).
- :class:`WidthPolicy` owns the elastic slot width: a power-of-two
  ladder from ``slot_min`` to ``slot_max``. Quantized widths keep the
  CompileCache hot — every depth maps to one of O(log) ladder rungs, so
  a surge compiles each (bucket, width) program once and reuses it for
  every later slot at that rung. ``slot_min == slot_max`` is the PR 19
  fixed-width daemon, bit for bit.

A running lane is still never reordered — both policies only ever judge
QUEUED jobs; preemption (scheduler.py) is a separate, priced decision.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional, Sequence

from .intake import PRIORITIES, ServeJob

# served-share weights: high jobs earn 8x low's share under backlog
DEFAULT_WEIGHTS = {"high": 8.0, "normal": 4.0, "low": 1.0}

# class names in urgency order (index == priority rank)
CLASS_ORDER = tuple(sorted(PRIORITIES, key=PRIORITIES.__getitem__))


class FairnessPolicy:
    """Stride-scheduled weighted shares with deadline-aware aging.

    ``weights`` maps class name -> positive share weight (missing
    classes default to :data:`DEFAULT_WEIGHTS`); ``aging_s`` is the
    seconds of waiting that promote a job by one full priority class
    (0 disables aging); ``clock`` is injectable for deterministic
    tests."""

    def __init__(self, weights: Optional[Dict[str, float]] = None, *,
                 aging_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        w = dict(DEFAULT_WEIGHTS)
        for k, v in (weights or {}).items():
            if k not in PRIORITIES:
                raise ValueError(f"unknown priority class {k!r} "
                                 f"(known: {sorted(PRIORITIES)})")
            v = float(v)
            if not math.isfinite(v) or v <= 0:
                raise ValueError(f"weight for {k!r} must be positive "
                                 f"and finite, got {v!r}")
            w[k] = v
        self.weights = {c: float(w[c]) for c in CLASS_ORDER}
        self.aging_s = float(aging_s)
        self.clock = clock
        self._pass: Dict[str, float] = {c: 0.0 for c in CLASS_ORDER}
        self._backlogged: set = set()
        self.served: Dict[str, int] = {c: 0 for c in CLASS_ORDER}

    # -- per-job urgency -------------------------------------------------------
    @staticmethod
    def base_rank(job: ServeJob) -> int:
        return PRIORITIES.get(job.priority, PRIORITIES["normal"])

    def wait_s(self, job: ServeJob, now: Optional[float] = None) -> float:
        t = getattr(job, "admit_t", None)
        if t is None:
            return 0.0
        return max(0.0, (self.clock() if now is None else now) - t)

    def effective_rank(self, job: ServeJob,
                       now: Optional[float] = None) -> float:
        """Aged urgency: the class rank decayed toward 0 by waiting —
        one full class per ``aging_s`` seconds queued."""
        r = float(self.base_rank(job))
        if self.aging_s > 0:
            r = max(0.0, r - self.wait_s(job, now) / self.aging_s)
        return r

    def queue_key(self, job: ServeJob, now: Optional[float] = None):
        """The live queue's sort key under this policy: aged rank, then
        deadline (tightest first), then admission order."""
        d = (float(job.deadline_ms) if job.deadline_ms is not None
             else math.inf)
        return (self.effective_rank(job, now), d, job.seq)

    def urgent(self, job: ServeJob, now: Optional[float] = None) -> bool:
        """The hard starvation bound: true once the job has waited past
        ``aging_s * (rank + 1)`` — it then overrides the stride shares
        and leads the next slot unconditionally."""
        return (self.aging_s > 0
                and self.wait_s(job, now)
                > self.aging_s * (self.base_rank(job) + 1))

    # -- stride shares ---------------------------------------------------------
    def note_backlog(self, classes_present: Sequence[str]) -> None:
        """Classic stride re-entry: a class entering backlog advances to
        the minimum pass among the classes already backlogged, so an
        absent class cannot bank credit and then monopolize."""
        present = {c for c in classes_present if c in self._pass}
        newly = present - self._backlogged
        if newly:
            floor = min((self._pass[c] for c in present - newly),
                        default=0.0)
            for c in newly:
                self._pass[c] = max(self._pass[c], floor)
        self._backlogged = present

    def lead_class(self,
                   classes_present: Sequence[str]) -> Optional[str]:
        """The class entitled to the next slot: lowest pass wins, ties
        broken by urgency rank (high first)."""
        present = [c for c in CLASS_ORDER if c in classes_present]
        if not present:
            return None
        return min(present,
                   key=lambda c: (self._pass[c], PRIORITIES[c]))

    def charge(self, priority: str, n: int = 1) -> None:
        """Account ``n`` served jobs to a class: its pass advances by
        ``n/weight``. Negative ``n`` refunds (a parked job was charged
        at pack time but not actually served to completion)."""
        c = priority if priority in self._pass else "normal"
        self._pass[c] += n / self.weights[c]
        self.served[c] = self.served.get(c, 0) + n

    def snapshot(self) -> dict:
        """The policy's state for telemetry records and summaries."""
        return {"pass": {c: round(v, 6) for c, v in self._pass.items()},
                "served": dict(self.served),
                "weights": dict(self.weights),
                "aging_s": self.aging_s}


class WidthPolicy:
    """Elastic slot width over a power-of-two ladder.

    ``choose(depth)`` returns the smallest ladder width that covers the
    queue depth, clamped to ``slot_max`` — a deterministic, quantized
    map from demand to batch size, so the CompileCache holds one program
    per (bucket, rung) and a surge never compiles per-depth."""

    def __init__(self, slot_min: int, slot_max: int):
        slot_min, slot_max = int(slot_min), int(slot_max)
        if slot_min < 1 or slot_max < slot_min:
            raise ValueError(
                f"need 1 <= slot_min <= slot_max, got "
                f"[{slot_min}, {slot_max}]")
        self.slot_min = slot_min
        self.slot_max = slot_max
        widths: List[int] = []
        w = slot_min
        while w < slot_max:
            widths.append(w)
            w *= 2
        widths.append(slot_max)
        self.widths = tuple(widths)

    @property
    def fixed(self) -> bool:
        return self.slot_min == self.slot_max

    def choose(self, depth: int) -> int:
        for w in self.widths:
            if w >= depth:
                return w
        return self.slot_max
