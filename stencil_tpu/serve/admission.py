"""Admission control: quotas, priority classes, priced deadline rejection.

Three verdicts, in judgment order:

- **reject** — the job's per-step ``deadline_ms`` is infeasible against
  the bucket's known p99 step latency. The pricing comes from the
  :class:`BucketPricer`: ONLINE samples once the daemon has stepped the
  bucket (the driver's per-chunk wall times), seeded from the
  performance LEDGER's per-bucket entries before that (metric
  ``serve.step_p99_ms``, ``detail.bucket`` keyed — the daemon writes
  them back at drain, so pricing survives restarts). A rejection always
  NAMES its price and source. No price -> no rejection: admission never
  guesses.
- **defer** — the owning tenant is at its quota of live (queued +
  running) jobs. Quota exhaustion QUEUES, it never rejects: the job
  waits in a holding pen and is promoted the moment one of the
  tenant's jobs retires.
- **admit** — into the LIVE priority queue.

Priority classes reorder only QUEUED jobs (the queue's order key); a
running lane is never preempted — structurally, because admission and
the queue only ever see unscheduled jobs.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

from ..obs import ledger as ledger_mod
from ..utils.statistics import percentile
from .intake import ServeJob

# the ledger metric carrying a bucket's p99 per-step latency prior
# (milliseconds); detail.bucket holds the bucket label
LEDGER_METRIC = "serve.step_p99_ms"


def bucket_label(bucket) -> str:
    """``(size, dtype, workload) -> "16x16x16/float32/jacobi"`` — the
    human- and ledger-facing bucket key."""
    (size, dtype, workload) = bucket
    x, y, z = size
    return f"{x}x{y}x{z}/{dtype}/{workload}"


class BucketPricer:
    """Per-bucket p99 step latency: online samples first, ledger priors
    until the daemon has its own evidence.

    Rows are keyed ``(bucket_label, width | None)``: every observation
    lands in the bucket AGGREGATE (width None) and, when the slot width
    is known, in a per-width row — elastic slots honestly cost more per
    step at larger B, and the pricer must stop pricing a B=64 slot with
    B=8 p99s. ``price(bucket, width=W)`` answers from the most specific
    row it has (online W, online aggregate, ledger W, ledger
    aggregate); both granularities write back to the ledger at drain
    (``detail.width`` marks the per-width rows)."""

    def __init__(self, ledger_path: Optional[str] = None, *,
                 window: int = 256, min_samples: int = 3):
        self.ledger_path = ledger_path or None
        self.window = int(window)
        self.min_samples = max(1, int(min_samples))
        self._online: Dict[Tuple[str, Optional[int]], deque] = {}
        self._prior: Dict[Tuple[str, Optional[int]],
                          Tuple[float, str, float]] = {}
        if self.ledger_path:
            # a corrupt ledger raises (LedgerError is a ValueError):
            # silently pricing from nothing would admit infeasible work
            for e in ledger_mod.load_ledger(self.ledger_path):
                if e.get("metric") != LEDGER_METRIC:
                    continue
                det = e.get("detail") or {}
                b = det.get("bucket")
                if not isinstance(b, str):
                    continue
                w = det.get("width")
                k = (b, int(w) if isinstance(w, int) else None)
                prev = self._prior.get(k)
                if prev is None or e.get("t", 0) >= prev[2]:
                    self._prior[k] = (
                        float(e["value"]),
                        f"ledger {self.ledger_path} [{e.get('label')}]",
                        e.get("t", 0))

    def observe(self, bucket, per_step_s: float, *,
                width: Optional[int] = None) -> None:
        """One chunk's per-step wall time for ``bucket`` (seconds),
        optionally attributed to the slot width that produced it."""
        label = bucket_label(bucket)
        keys = [(label, None)]
        if width:
            keys.append((label, int(width)))
        for k in keys:
            self._online.setdefault(
                k, deque(maxlen=self.window)).append(float(per_step_s))

    def price(self, bucket, *,
              width: Optional[int] = None) -> Optional[Tuple[float, str]]:
        """``(p99_ms, source)`` for the bucket (most width-specific row
        first), or None (unknown — the daemon has never stepped the
        shape and the ledger is silent)."""
        label = bucket_label(bucket)
        keys = ([(label, int(width)), (label, None)] if width
                else [(label, None)])
        for k in keys:
            samples = self._online.get(k)
            if samples and len(samples) >= self.min_samples:
                at = f" at B={k[1]}" if k[1] else ""
                return (percentile(samples, 99) * 1e3,
                        f"online p99 over {len(samples)} chunks{at}")
        for k in keys:
            prior = self._prior.get(k)
            if prior is not None:
                return (prior[0], prior[1])
        return None

    def ledger_entries(self, *, platform: str, label: str) -> List[dict]:
        """One ledger entry per online-priced (bucket, width) row —
        appended at drain so the NEXT daemon prices admission (and
        widths) before its first step."""
        out = []
        for (b, w), samples in sorted(
                self._online.items(),
                key=lambda kv: (kv[0][0], kv[0][1] or 0)):
            if len(samples) < self.min_samples:
                continue
            det = {"bucket": b, "samples": len(samples)}
            cfg = {"bucket": b}
            if w is not None:
                det["width"] = w
                cfg["width"] = w
            out.append(ledger_mod.make_entry(
                LEDGER_METRIC, percentile(samples, 99) * 1e3,
                label=label, unit="ms", platform=platform, source="serve",
                config=cfg, detail=det))
        return out


class AdmissionController:
    """The verdict function. ``quota`` is the per-tenant cap on LIVE
    (queued + running) jobs; 0 = unlimited."""

    def __init__(self, *, quota: int = 0,
                 pricer: Optional[BucketPricer] = None):
        if quota < 0:
            raise ValueError(f"quota must be >= 0, got {quota}")
        self.quota = int(quota)
        self.pricer = pricer

    def decide(self, job: ServeJob, live_by_owner: Dict[str, int], *,
               width_hint: Optional[int] = None) -> Tuple[str, str]:
        """``("admit" | "defer" | "reject", reason)``. Infeasibility is
        judged before quota — a doomed job must not occupy a quota
        slot waiting to be doomed. ``width_hint`` is the slot width the
        scheduler would run the job at (elastic daemons price the B the
        job will actually see, not the aggregate)."""
        if job.deadline_ms is not None and self.pricer is not None:
            priced = self.pricer.price(job.bucket(), width=width_hint)
            if priced is not None:
                p99_ms, source = priced
                if float(job.deadline_ms) < p99_ms:
                    return ("reject",
                            f"deadline {job.deadline_ms:g} ms infeasible: "
                            f"bucket {bucket_label(job.bucket())} p99 is "
                            f"{p99_ms:.4g} ms ({source})")
        if self.quota and live_by_owner.get(job.owner, 0) >= self.quota:
            return ("defer",
                    f"tenant {job.owner} at quota "
                    f"({live_by_owner.get(job.owner, 0)}/{self.quota} "
                    "live jobs); queued for promotion")
        return ("admit", "")
