"""Admission control: quotas, priority classes, priced deadline rejection.

Three verdicts, in judgment order:

- **reject** — the job's per-step ``deadline_ms`` is infeasible against
  the bucket's known p99 step latency. The pricing comes from the
  :class:`BucketPricer`: ONLINE samples once the daemon has stepped the
  bucket (the driver's per-chunk wall times), seeded from the
  performance LEDGER's per-bucket entries before that (metric
  ``serve.step_p99_ms``, ``detail.bucket`` keyed — the daemon writes
  them back at drain, so pricing survives restarts). A rejection always
  NAMES its price and source. No price -> no rejection: admission never
  guesses.
- **defer** — the owning tenant is at its quota of live (queued +
  running) jobs. Quota exhaustion QUEUES, it never rejects: the job
  waits in a holding pen and is promoted the moment one of the
  tenant's jobs retires.
- **admit** — into the LIVE priority queue.

Priority classes reorder only QUEUED jobs (the queue's order key); a
running lane is never preempted — structurally, because admission and
the queue only ever see unscheduled jobs.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

from ..obs import ledger as ledger_mod
from ..utils.statistics import percentile
from .intake import ServeJob

# the ledger metric carrying a bucket's p99 per-step latency prior
# (milliseconds); detail.bucket holds the bucket label
LEDGER_METRIC = "serve.step_p99_ms"


def bucket_label(bucket) -> str:
    """``(size, dtype, workload) -> "16x16x16/float32/jacobi"`` — the
    human- and ledger-facing bucket key."""
    (size, dtype, workload) = bucket
    x, y, z = size
    return f"{x}x{y}x{z}/{dtype}/{workload}"


class BucketPricer:
    """Per-bucket p99 step latency: online samples first, ledger priors
    until the daemon has its own evidence."""

    def __init__(self, ledger_path: Optional[str] = None, *,
                 window: int = 256, min_samples: int = 3):
        self.ledger_path = ledger_path or None
        self.window = int(window)
        self.min_samples = max(1, int(min_samples))
        self._online: Dict[str, deque] = {}
        self._prior: Dict[str, Tuple[float, str, float]] = {}
        if self.ledger_path:
            # a corrupt ledger raises (LedgerError is a ValueError):
            # silently pricing from nothing would admit infeasible work
            for e in ledger_mod.load_ledger(self.ledger_path):
                if e.get("metric") != LEDGER_METRIC:
                    continue
                b = (e.get("detail") or {}).get("bucket")
                if not isinstance(b, str):
                    continue
                prev = self._prior.get(b)
                if prev is None or e.get("t", 0) >= prev[2]:
                    self._prior[b] = (
                        float(e["value"]),
                        f"ledger {self.ledger_path} [{e.get('label')}]",
                        e.get("t", 0))

    def observe(self, bucket, per_step_s: float) -> None:
        """One chunk's per-step wall time for ``bucket`` (seconds)."""
        self._online.setdefault(
            bucket_label(bucket), deque(maxlen=self.window)).append(
            float(per_step_s))

    def price(self, bucket) -> Optional[Tuple[float, str]]:
        """``(p99_ms, source)`` for the bucket, or None (unknown — the
        daemon has never stepped the shape and the ledger is silent)."""
        label = bucket_label(bucket)
        samples = self._online.get(label)
        if samples and len(samples) >= self.min_samples:
            return (percentile(samples, 99) * 1e3,
                    f"online p99 over {len(samples)} chunks")
        prior = self._prior.get(label)
        if prior is not None:
            return (prior[0], prior[1])
        return None

    def ledger_entries(self, *, platform: str, label: str) -> List[dict]:
        """One ledger entry per online-priced bucket — appended at drain
        so the NEXT daemon prices admission before its first step."""
        out = []
        for b, samples in sorted(self._online.items()):
            if len(samples) < self.min_samples:
                continue
            out.append(ledger_mod.make_entry(
                LEDGER_METRIC, percentile(samples, 99) * 1e3,
                label=label, unit="ms", platform=platform, source="serve",
                config={"bucket": b}, detail={"bucket": b,
                                              "samples": len(samples)}))
        return out


class AdmissionController:
    """The verdict function. ``quota`` is the per-tenant cap on LIVE
    (queued + running) jobs; 0 = unlimited."""

    def __init__(self, *, quota: int = 0,
                 pricer: Optional[BucketPricer] = None):
        if quota < 0:
            raise ValueError(f"quota must be >= 0, got {quota}")
        self.quota = int(quota)
        self.pricer = pricer

    def decide(self, job: ServeJob,
               live_by_owner: Dict[str, int]) -> Tuple[str, str]:
        """``("admit" | "defer" | "reject", reason)``. Infeasibility is
        judged before quota — a doomed job must not occupy a quota
        slot waiting to be doomed."""
        if job.deadline_ms is not None and self.pricer is not None:
            priced = self.pricer.price(job.bucket())
            if priced is not None:
                p99_ms, source = priced
                if float(job.deadline_ms) < p99_ms:
                    return ("reject",
                            f"deadline {job.deadline_ms:g} ms infeasible: "
                            f"bucket {bucket_label(job.bucket())} p99 is "
                            f"{p99_ms:.4g} ms ({source})")
        if self.quota and live_by_owner.get(job.owner, 0) >= self.quota:
            return ("defer",
                    f"tenant {job.owner} at quota "
                    f"({live_by_owner.get(job.owner, 0)}/{self.quota} "
                    "live jobs); queued for promotion")
        return ("admit", "")
