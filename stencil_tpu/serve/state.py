"""The serving daemon's durable queue+lane state: ``serve-state.json``.

One small atomic JSON document (the ledger/status write discipline:
tmp + fsync + rename — a reader or a reviving daemon NEVER sees a torn
file) recording every job the daemon has ever accepted and where it
stands. The revival contract rides on it: a daemon killed mid-serve is
restarted (the PR 3 watchdog ladder), reads this file, re-queues every
``queued``/``deferred``/``running`` job (running ones resume from their
newest tenant snapshot — bit-identical by the ckpt contract) and NEVER
re-runs a ``done``/``fault``/``rejected`` one.

State document (schema v1)::

    {"v": 1, "kind": "serve-state",
     "t": unix seconds of the last write,
     "draining": bool,
     "counters": {"admitted": int, "rejected": int, "deferred": int,
                  "backfills": int, "retired": int},
     "jobs": {jid: {"state": "queued"|"deferred"|"running"|"done"|
                             "fault"|"rejected",
                    "steps_done": int, "owner": str, "priority": str,
                    "seq": int, "spec": {...the normalized job doc...},
                    "reason": str?}}}

PURE STDLIB by the watchdog/ledger/status contract: a supervisor (or a
human's ``jq``) must be able to read the file without the package.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

STATE_VERSION = 1
STATE_KIND = "serve-state"

# the full job lifecycle; the first three are "live" (a revived daemon
# owes them work), the last three are terminal (never re-run)
JOB_STATES = ("queued", "deferred", "running", "done", "fault", "rejected")
LIVE_STATES = ("queued", "deferred", "running")
COUNTERS = ("admitted", "rejected", "deferred", "backfills", "retired")


def make_state() -> dict:
    """A fresh v1 state document."""
    return {
        "v": STATE_VERSION,
        "kind": STATE_KIND,
        "t": 0.0,
        "draining": False,
        "counters": {k: 0 for k in COUNTERS},
        "jobs": {},
    }


def write_state(path: str, doc: dict) -> None:
    """Atomically replace ``path`` with ``doc``, stamping ``t`` (tmp +
    fsync + rename: a crash between admissions never tears the queue)."""
    doc["t"] = time.time()
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".tmp-{os.path.basename(path)}-{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_state(path: str) -> Optional[dict]:
    """The state document, or None when missing/unparseable (a fresh
    daemon starts empty; a torn file is impossible by the atomic-write
    discipline, so unparseable means "not ours")."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


def validate_state(doc) -> List[str]:
    """Schema violations of one state document (empty = valid v1)."""
    if not isinstance(doc, dict):
        return [f"not an object: {type(doc).__name__}"]
    errs: List[str] = []
    if doc.get("v") != STATE_VERSION:
        errs.append(f"unknown state version {doc.get('v')!r}")
    if doc.get("kind") != STATE_KIND:
        errs.append(f"unknown kind {doc.get('kind')!r}")
    if not isinstance(doc.get("t"), (int, float)):
        errs.append("t must be a number")
    if not isinstance(doc.get("draining"), bool):
        errs.append("draining must be a boolean")
    c = doc.get("counters")
    if not isinstance(c, dict):
        errs.append("counters must be an object")
    else:
        for fld in COUNTERS:
            v = c.get(fld)
            if isinstance(v, bool) or not isinstance(v, int):
                errs.append(f"counters.{fld} must be an integer")
    jobs = doc.get("jobs")
    if not isinstance(jobs, dict):
        errs.append("jobs must be an object")
        return errs
    for jid, j in jobs.items():
        if not isinstance(j, dict):
            errs.append(f"jobs[{jid}] must be an object")
            continue
        if j.get("state") not in JOB_STATES:
            errs.append(f"jobs[{jid}].state {j.get('state')!r} is not one "
                        f"of {JOB_STATES}")
        sd = j.get("steps_done")
        if isinstance(sd, bool) or not isinstance(sd, int):
            errs.append(f"jobs[{jid}].steps_done must be an integer")
        for fld in ("owner", "priority"):
            if not isinstance(j.get(fld), str):
                errs.append(f"jobs[{jid}].{fld} must be a string")
        seq = j.get("seq")
        if isinstance(seq, bool) or not isinstance(seq, int):
            errs.append(f"jobs[{jid}].seq must be an integer")
        if j.get("state") != "rejected" and not isinstance(
                j.get("spec"), dict):
            errs.append(f"jobs[{jid}].spec must be an object")
    return errs
