"""File-drop job intake: ``jobs/incoming/*.json`` -> claimed or quarantined.

The serving daemon's wire protocol is a directory (ROADMAP #4's
"file-drop or socket queue" — the file half; a socket front-end would
write the same files). A producer drops one JSON document per job,
ATOMICALLY (write a tmp file in the same directory, then rename — the
daemon must never read a half-written job; ``scripts/serve_loadgen.py``
is the reference writer). The daemon claims a job by renaming it into
``jobs/claimed/`` — rename is atomic on POSIX, so two pollers can race
and exactly one wins; a claimed file is never re-read, which is what
makes "never re-run a retired job" crash-safe end to end.

Malformed or duplicate jobs must never kill the daemon: they are
quarantined LOUDLY into ``jobs/bad/`` next to a ``<name>.reason.txt``
explaining the rejection, and the daemon emits a schema-valid
``serve.rejected`` record — the operator greps the reason file, the
dashboard counts the record, and serving continues.

Job document::

    {"job": "j-0001",              # unique id (becomes the tenant id)
     "size": 16 | [16, 16, 16],    # per-tenant box (x, y, z)
     "steps": 8,                   # tenant steps to run
     "tenant": "alice",            # owner for quotas (default: the job id)
     "workload": "jacobi",         # campaign WORKLOADS key
     "dtype": "float32", "seed": 0,
     "deadline_ms": 5.0,           # per-step p99 SLO (admission-priced)
     "priority": "high"|"normal"|"low"}
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..campaign.driver import WORKLOADS, TenantJob

# priority classes: lower rank schedules first; reordering applies to
# QUEUED jobs only — a running lane is never preempted (structural: the
# queue holds only unscheduled jobs)
PRIORITIES = {"high": 0, "normal": 1, "low": 2}
DTYPES = ("float32", "float64")

_REQUIRED = ("job", "size", "steps")
_KNOWN = _REQUIRED + ("tenant", "workload", "dtype", "seed", "deadline_ms",
                      "priority")


@dataclass
class ServeJob(TenantJob):
    """A :class:`TenantJob` plus its serving identity: the owning tenant
    (quota accounting), priority class, and admission sequence number
    (the FIFO tiebreak). Slots, lanes, backfill and snapshots see it as
    a plain TenantJob."""

    owner: str = ""
    priority: str = "normal"
    seq: int = 0
    # when the LIVE queue admitted the job (the aging clock's zero);
    # None until queued. Deliberately absent from spec_doc: a revived
    # job's wait restarts — aging measures THIS daemon's debt to it.
    admit_t: Optional[float] = None

    def order_key(self) -> Tuple[int, float, int]:
        """The LIVE queue's scheduling order: priority class, then
        deadline (tightest first — deadline-sorted bucket packing),
        then admission order."""
        d = (float(self.deadline_ms) if self.deadline_ms is not None
             else math.inf)
        return (PRIORITIES.get(self.priority, PRIORITIES["normal"]), d,
                self.seq)

    def spec_doc(self) -> dict:
        """The normalized job document (serve-state.json's ``spec`` —
        a revived daemon rebuilds the job from exactly this)."""
        return {
            "job": self.tid, "size": list(self.size), "steps": self.steps,
            "tenant": self.owner, "workload": self.workload,
            "dtype": self.dtype, "seed": self.seed,
            "deadline_ms": self.deadline_ms, "priority": self.priority,
        }


def validate_job(doc) -> List[str]:
    """Schema violations of one job document (empty = admissible shape).
    The single authority — intake, tests, and the loadgen writer agree
    through this."""
    if not isinstance(doc, dict):
        return [f"not an object: {type(doc).__name__}"]
    errs: List[str] = []
    for fld in _REQUIRED:
        if fld not in doc:
            errs.append(f"missing required field {fld!r}")
    unknown = sorted(set(doc) - set(_KNOWN))
    if unknown:
        errs.append(f"unknown fields {unknown}")
    jid = doc.get("job")
    if "job" in doc and (not isinstance(jid, str) or not jid
                         or "/" in jid or jid.startswith(".")):
        errs.append(f"job must be a non-empty path-safe string, got {jid!r}")
    size = doc.get("size")
    if "size" in doc:
        if isinstance(size, int) and not isinstance(size, bool):
            size = [size, size, size]
        if (not isinstance(size, (list, tuple)) or len(size) != 3
                or any(isinstance(v, bool) or not isinstance(v, int)
                       or v < 1 for v in size)):
            errs.append(f"size must be a positive int or [x, y, z], "
                        f"got {doc.get('size')!r}")
    steps = doc.get("steps")
    if "steps" in doc and (isinstance(steps, bool)
                           or not isinstance(steps, int) or steps < 1):
        errs.append(f"steps must be a positive integer, got {steps!r}")
    wl = doc.get("workload", "jacobi")
    if wl not in WORKLOADS:
        errs.append(f"unknown workload {wl!r} (known: {sorted(WORKLOADS)})")
    dt = doc.get("dtype", "float32")
    if dt not in DTYPES:
        errs.append(f"unknown dtype {dt!r} (known: {list(DTYPES)})")
    seed = doc.get("seed", 0)
    if isinstance(seed, bool) or not isinstance(seed, int):
        errs.append(f"seed must be an integer, got {seed!r}")
    tenant = doc.get("tenant")
    if tenant is not None and (not isinstance(tenant, str) or not tenant):
        errs.append(f"tenant must be a non-empty string, got {tenant!r}")
    dl = doc.get("deadline_ms")
    if dl is not None and (isinstance(dl, bool)
                           or not isinstance(dl, (int, float))
                           or not math.isfinite(dl) or dl <= 0):
        errs.append(f"deadline_ms must be a positive finite number, "
                    f"got {dl!r}")
    pri = doc.get("priority", "normal")
    if pri not in PRIORITIES:
        errs.append(f"unknown priority {pri!r} "
                    f"(known: {sorted(PRIORITIES)})")
    return errs


def job_from_doc(doc: dict, seq: int) -> ServeJob:
    """Build the queue entry from a VALIDATED job document."""
    size = doc["size"]
    if isinstance(size, int):
        size = [size, size, size]
    jid = doc["job"]
    return ServeJob(
        tid=jid,
        size=(int(size[0]), int(size[1]), int(size[2])),
        steps=int(doc["steps"]),
        dtype=doc.get("dtype", "float32"),
        seed=int(doc.get("seed", 0)),
        workload=doc.get("workload", "jacobi"),
        deadline_ms=(float(doc["deadline_ms"])
                     if doc.get("deadline_ms") is not None else None),
        owner=doc.get("tenant") or jid,
        priority=doc.get("priority", "normal"),
        seq=int(seq),
    )


class Intake:
    """The daemon side of the file-drop protocol: claim-by-rename from
    ``jobs/incoming/``, quarantine-with-reason into ``jobs/bad/``."""

    def __init__(self, serve_dir: str):
        self.incoming = os.path.join(serve_dir, "jobs", "incoming")
        self.claimed = os.path.join(serve_dir, "jobs", "claimed")
        self.bad = os.path.join(serve_dir, "jobs", "bad")
        for d in (self.incoming, self.claimed, self.bad):
            os.makedirs(d, exist_ok=True)

    def poll(self) -> List[Tuple[str, Optional[dict], List[str]]]:
        """Claim every currently-visible job file, oldest first. Returns
        ``[(claimed_path, doc | None, parse_errors), ...]`` — a doc of
        None means the file was not valid JSON (truncated drop, not an
        atomic writer); schema judgment is the admission layer's."""
        try:
            names = [n for n in os.listdir(self.incoming)
                     if n.endswith(".json") and not n.startswith(".")]
        except OSError:
            return []
        entries = []
        for n in names:
            src = os.path.join(self.incoming, n)
            try:
                entries.append((os.stat(src).st_mtime, n, src))
            except OSError:
                continue  # raced away
        out: List[Tuple[str, Optional[dict], List[str]]] = []
        for _, n, src in sorted(entries):
            dst = os.path.join(self.claimed, n)
            try:
                os.replace(src, dst)  # the atomic claim
            except OSError:
                continue  # another claimer won
            try:
                with open(dst) as f:
                    doc = json.load(f)
            except json.JSONDecodeError as e:
                out.append((dst, None, [f"not valid JSON: {e}"]))
                continue
            except OSError as e:
                out.append((dst, None, [f"unreadable: {e}"]))
                continue
            out.append((dst, doc if isinstance(doc, dict)
                        else None,
                        [] if isinstance(doc, dict)
                        else [f"not a JSON object: {type(doc).__name__}"]))
        return out

    def quarantine(self, claimed_path: str, reason: str) -> str:
        """Move a claimed file into ``jobs/bad/`` with a reason file —
        the loud half of "never kill the daemon". Returns the bad path."""
        name = os.path.basename(claimed_path)
        dst = os.path.join(self.bad, name)
        if os.path.exists(dst):  # a replayed file name: keep both
            stem, ext = os.path.splitext(name)
            i = 1
            while os.path.exists(dst):
                dst = os.path.join(self.bad, f"{stem}.{i}{ext}")
                i += 1
        try:
            os.replace(claimed_path, dst)
        except OSError:
            dst = claimed_path  # leave it claimed; the reason still lands
        try:
            with open(dst + ".reason.txt", "w") as f:
                f.write(reason.rstrip() + "\n")
        except OSError:
            pass  # quarantine is evidence, not the measurement
        return dst
