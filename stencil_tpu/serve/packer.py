"""Cross-bucket slot packing: score (bucket, width, jobs) and choose.

PR 19 formed slots with :func:`~.queue.pick_serve_slot`: the queue head
names the bucket, full stop. A mixed queue fragments under that rule —
the head's bucket may hold two jobs while another bucket could fill a
slot. :func:`pack_serve_slot` replaces it with a scored packing pass:

1. **Entitlement.** The fairness policy (stride shares + aging) names
   the class entitled to the slot; an URGENT job (waited past the aging
   bound) forces its bucket outright. Without a policy, the strict
   queue head leads — PR 19 order.
2. **Candidates.** Every bucket holding a job of the entitled class is
   a contender. Each gets its elastic width (``WidthPolicy.choose``
   against its own depth) and its prefix of queued jobs.
3. **Score.** Contenders are ranked by ledger-priced throughput (picked
   jobs per priced millisecond of slot wall), then fill fraction, then
   the lead job's queue key — so a mixed queue packs into fewer, fuller,
   faster slots, and the tie falls back to urgency order.
4. **Deadline slack veto.** If the winner's priced wall would push a
   losing contender's tightest completion budget negative while that
   contender, served first, leaves the winner feasible, the loser is
   promoted — packing never manufactures an SLO miss it can see.

The decision is returned whole (:class:`SlotPlan`, including the scored
candidate table) so the scheduler can emit it as one schema-valid
``serve.packed`` record naming what was chosen and why. Picked jobs are
removed from the queue in place and charged to their classes' stride
passes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .admission import BucketPricer, bucket_label
from .fairness import FairnessPolicy, WidthPolicy
from .intake import PRIORITIES, ServeJob
from .queue import ServeQueue


@dataclass
class SlotPlan:
    """One packing decision: the slot to form and the evidence for it."""

    bucket: Tuple
    width: int
    picked: List[ServeJob]
    reason: str          # "throughput" | "aging-override" | "deadline-slack"
    lead: str            # job id whose entitlement led the choice
    candidates: List[dict] = field(default_factory=list)


def _group_by_bucket(jobs: List[ServeJob]):
    groups: Dict[Tuple, List[ServeJob]] = {}
    order: List[Tuple] = []
    for j in jobs:
        b = j.bucket()
        if b not in groups:
            groups[b] = []
            order.append(b)
        groups[b].append(j)
    return groups, order


def _slot_wall_ms(cand: dict) -> Optional[float]:
    if cand["p99_ms"] is None:
        return None
    return cand["p99_ms"] * max(j.steps for j in cand["picked"])


def _tightest_slack_ms(cand: dict, wait_ms: float) -> Optional[float]:
    """Min completion slack over the candidate's deadline jobs if its
    slot starts after ``wait_ms`` (budget = deadline_ms * steps — the
    per-step SLO rolled up to the whole job)."""
    if cand["p99_ms"] is None:
        return None
    slacks = [
        float(j.deadline_ms) * j.steps
        - (wait_ms + cand["p99_ms"] * j.steps)
        for j in cand["picked"] if j.deadline_ms is not None
    ]
    return min(slacks) if slacks else None


def pack_serve_slot(queue: ServeQueue, width_policy: WidthPolicy, *,
                    pricer: Optional[BucketPricer] = None,
                    fairness: Optional[FairnessPolicy] = None,
                    now: Optional[float] = None) -> Optional[SlotPlan]:
    """Form the next slot from the LIVE queue. Removes the picked jobs
    in place (the queue stays live for mid-slot backfill) and charges
    them to the fairness shares. Returns None on an empty queue."""
    if fairness is not None and now is None:
        now = fairness.clock()
    jobs = queue.jobs(now)
    if not jobs:
        return None
    groups, order = _group_by_bucket(jobs)

    # 1. entitlement: who leads the slot
    reason = "throughput"
    forced: Optional[Tuple] = None
    if fairness is not None:
        classes = {j.priority if j.priority in PRIORITIES else "normal"
                   for j in jobs}
        fairness.note_backlog(classes)
        overdue = [j for j in jobs if fairness.urgent(j, now)]
        if overdue:
            lead = min(overdue, key=lambda j: j.seq)  # oldest admitted
            forced = lead.bucket()
            reason = "aging-override"
        else:
            c_star = fairness.lead_class(classes)
            lead = next(j for j in jobs if (j.priority
                                            if j.priority in PRIORITIES
                                            else "normal") == c_star)
    else:
        lead = jobs[0]

    # 2. contenders: the forced bucket, or every bucket holding a job
    # of the entitled class (strict head-rank ties without a policy)
    if forced is not None:
        contenders = [forced]
    elif fairness is not None:
        contenders = [b for b in order
                      if any(j.priority == lead.priority
                             for j in groups[b])]
    else:
        lead_rank = PRIORITIES.get(lead.priority, PRIORITIES["normal"])
        contenders = [b for b in order
                      if PRIORITIES.get(groups[b][0].priority,
                                        PRIORITIES["normal"]) <= lead_rank]

    # 3. score: priced throughput, fill, lead urgency
    cands: List[dict] = []
    for b in contenders:
        g = groups[b]
        width = width_policy.choose(len(g))
        picked = g[:width]
        p99_ms = None
        source = None
        if pricer is not None:
            priced = pricer.price(b, width=width)
            if priced is not None:
                p99_ms, source = priced
        wall = p99_ms * max(j.steps for j in picked) if p99_ms else None
        cands.append({
            "bucket": b, "label": bucket_label(b), "width": width,
            "picked": picked, "p99_ms": p99_ms, "priced_from": source,
            "throughput": (len(picked) / wall) if wall else 0.0,
            "fill": len(picked) / float(width),
        })

    def urgency(c):
        j = c["picked"][0]
        return (fairness.queue_key(j, now) if fairness is not None
                else j.order_key())

    cands.sort(key=lambda c: (-c["throughput"], -c["fill"], urgency(c),
                              c["label"]))
    best = cands[0]

    # 4. deadline slack veto
    if len(cands) > 1:
        wall = _slot_wall_ms(best)
        if wall is not None:
            for c in cands[1:]:
                s_wait = _tightest_slack_ms(c, wall)
                if s_wait is None or s_wait >= 0:
                    continue
                c_wall = _slot_wall_ms(c)
                s_best = _tightest_slack_ms(best, c_wall or 0.0)
                if s_best is None or s_best >= 0:
                    best = c
                    reason = "deadline-slack"
                    break

    for j in best["picked"]:
        queue.remove(j)
        if fairness is not None:
            fairness.charge(j.priority)
    table = [{"label": c["label"], "width": c["width"],
              "jobs": len(c["picked"]), "p99_ms": c["p99_ms"],
              "throughput": c["throughput"], "fill": c["fill"]}
             for c in cands]
    return SlotPlan(bucket=best["bucket"], width=best["width"],
                    picked=best["picked"], reason=reason,
                    lead=lead.tid, candidates=table)
