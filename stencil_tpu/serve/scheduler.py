"""The always-on scheduler: continuous batching over the campaign driver.

:class:`ServeScheduler` subclasses :class:`~..campaign.driver.
CampaignDriver` and overrides its serving hooks — the batch campaign's
machinery (bucketed slots, guarded segments, eviction, per-tenant
snapshots) is reused verbatim; what changes is WHERE jobs come from and
WHEN they may enter:

- **Live intake.** ``_refresh_queue`` (called by the driver before every
  backfill scan and once per chunk) claims ``jobs/incoming/`` drops,
  runs admission, and grows the LIVE queue — so a job that arrives
  while a slot is mid-flight lands in the very next freed lane, with no
  slot-wide barrier. That is the continuous-batching extension: the
  driver's backfill path, promoted from drain-time to steady-state.
- **Deadline-sorted packing.** Slot selection is
  :func:`~.queue.pick_serve_slot`: the most urgent queued job names the
  bucket, same-bucket jobs fill the slot tightest-deadline-first.
- **SLO pressure.** ``_observe_chunk`` prices every chunk into the
  :class:`~.admission.BucketPricer`; when a queued or running job's
  deadline falls under the bucket's online p99, the scheduler emits a
  first-class ``replan.requested`` (reason ``slo-pressure``) and
  latches the :class:`~..plan.replan.ReplanController` — the hot-swap
  fires at the next slot boundary, exactly like a sentinel anomaly.
- **Result streaming.** ``_on_result`` writes ``results/<job>.json``
  atomically the moment a tenant retires (or faults out), emits
  ``serve.retired``, and promotes deferred jobs into freed quota.
- **Drain + revival.** ``request_drain`` (the SIGTERM handler's one
  call) parks every live lane as a revivable snapshot at the next
  segment boundary; ``serve-state.json`` (serve/state.py, atomic)
  always knows which jobs are owed work, so a killed-and-revived
  daemon resumes admitted-but-unserved jobs and never re-runs retired
  ones — whole-service crash-revival rides the PR 3 watchdog.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from ..campaign.driver import CampaignDriver, TenantResult
from ..obs import ledger as ledger_mod
from ..obs import telemetry
from ..utils import logging as log
from ..utils.statistics import percentile
from . import state as state_mod
from .admission import AdmissionController, BucketPricer, bucket_label
from .intake import Intake, ServeJob, job_from_doc, validate_job
from .queue import ServeQueue, pick_serve_slot


class ServeScheduler(CampaignDriver):
    """A persistent :class:`CampaignDriver` fed by file-drop intake.

    ``serve_dir`` owns the whole service: ``jobs/`` (intake),
    ``campaign/`` (slot machinery + tenant snapshots), ``results/``
    (streamed per-tenant results), ``serve-state.json``. ``quota`` is
    the per-tenant cap on live jobs (0 = unlimited);
    ``admission_ledger`` seeds deadline pricing and receives the run's
    per-bucket p99 back at exit; ``max_idle_s`` > 0 exits after that
    long with an empty queue (0 = serve until drained by signal);
    ``max_wall_s`` > 0 is a total-budget self-drain."""

    def __init__(self, serve_dir: str, slot_size: int, *,
                 quota: int = 0, admission_ledger: Optional[str] = None,
                 poll_s: float = 0.2, max_idle_s: float = 0.0,
                 max_wall_s: float = 0.0, **kw):
        kw.setdefault("resume", True)  # revival is the serving default
        super().__init__([], slot_size,
                         os.path.join(serve_dir, "campaign"), **kw)
        self.serve_dir = serve_dir
        self.results_dir = os.path.join(serve_dir, "results")
        self.state_path = os.path.join(serve_dir, "serve-state.json")
        self.intake = Intake(serve_dir)
        self.pricer = BucketPricer(admission_ledger)
        self.admission = AdmissionController(quota=quota, pricer=self.pricer)
        self.admission_ledger = admission_ledger or None
        self.poll_s = max(0.01, float(poll_s))
        self.max_idle_s = float(max_idle_s)
        self.max_wall_s = float(max_wall_s)
        self.queue = ServeQueue()
        self.state = state_mod.make_state()
        self.results: Dict[str, TenantResult] = {}
        self._deferred: List[ServeJob] = []
        self._jobs_by_id: Dict[str, ServeJob] = {}
        self._running: set = set()
        self._drain = False
        self._drain_reason = ""
        self._pressure_sent: set = set()
        self._all_lat: List[float] = []
        self._retired_run = 0
        self._seq = 0
        self._last_bucket: Optional[Tuple] = None

    # -- drain (the SIGTERM handler calls exactly this) -----------------------
    def request_drain(self, reason: str) -> None:
        """Stop claiming intake, park live lanes at the next segment
        boundary, persist everything, exit cleanly. Signal-safe: plain
        assignments only — the serve loop does the work."""
        self._drain = True
        if not self._drain_reason:
            self._drain_reason = str(reason)

    # -- durable state --------------------------------------------------------
    def _flush_state(self) -> None:
        self.state["draining"] = self._drain
        state_mod.write_state(self.state_path, self.state)

    def _counters(self) -> dict:
        return self.state["counters"]

    def queue_stat(self) -> dict:
        """The status snapshot's ``queue`` section (obs/status.py)."""
        c = self._counters()
        return {
            "depth": len(self.queue),
            "admitted": c["admitted"],
            "rejected": c["rejected"],
            "backfills": c["backfills"],
            "deferred": len(self._deferred),
            "retired": c["retired"],
        }

    def _live_by_owner(self) -> Dict[str, int]:
        """Live (queued + running) job counts per owning tenant — the
        quota denominator. Deferred jobs do not count (a tenant's own
        holding pen must not block its promotions)."""
        live: Dict[str, int] = {}
        for j in self.state["jobs"].values():
            if j["state"] in ("queued", "running"):
                live[j["owner"]] = live.get(j["owner"], 0) + 1
        return live

    # -- revival --------------------------------------------------------------
    def _revive(self) -> int:
        """Load serve-state.json and re-queue every job the previous
        daemon still owed work: queued/running -> the live queue
        (running tenants resume from their newest snapshot — the ckpt
        bit-identity contract), deferred -> the holding pen. Terminal
        jobs (done/fault/rejected) are never touched."""
        doc = state_mod.read_state(self.state_path)
        if doc is None:
            return 0
        errs = state_mod.validate_state(doc)
        if errs:
            raise ValueError(
                f"corrupt serve-state at {self.state_path}: "
                + "; ".join(errs[:3]))
        self.state = doc
        n = 0
        jobs = sorted(doc["jobs"].items(),
                      key=lambda kv: kv[1].get("seq", 0))
        for jid, j in jobs:
            self._seq = max(self._seq, int(j.get("seq", 0)) + 1)
            if j["state"] not in state_mod.LIVE_STATES:
                continue
            job = job_from_doc(j["spec"], int(j.get("seq", 0)))
            n += 1
            if j["state"] == "deferred":
                self._deferred.append(job)
                self._register(job)
            else:
                j["state"] = "queued"  # running-at-crash resumes
                self._enqueue(job, revived=True)
        if n:
            telemetry.get().meta(
                "serve.revived", jobs=n, queued=len(self.queue),
                deferred=len(self._deferred))
            log.info(f"serve: revived {n} unserved job(s) from "
                     f"{self.state_path}")
        self._promote()
        return n

    # -- admission ------------------------------------------------------------
    def _next_seq(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    def _register(self, job: ServeJob) -> None:
        self._jobs_by_id[job.tid] = job
        self.jobs.append(job)  # driver-level registry (injector, summary)

    def _enqueue(self, job: ServeJob, *, revived: bool = False,
                 promoted: bool = False) -> None:
        self.queue.admit(job)
        if job.tid not in self._jobs_by_id:
            self._register(job)
        st = self.state["jobs"].setdefault(job.tid, {
            "steps_done": 0, "owner": job.owner, "priority": job.priority,
            "seq": job.seq, "spec": job.spec_doc(),
        })
        st["state"] = "queued"
        if not revived:
            self._counters()["admitted"] += 1
            telemetry.get().meta(
                "serve.admitted", job=job.tid, tenant=job.owner,
                priority=job.priority, seq=job.seq,
                deadline_ms=job.deadline_ms, promoted=promoted,
                bucket=bucket_label(job.bucket()))

    def _quarantine(self, path: str, jid: str, reason: str) -> None:
        bad = self.intake.quarantine(path, reason)
        self._counters()["rejected"] += 1
        telemetry.get().meta("serve.rejected", job=jid, reason=reason,
                             file=bad)
        log.warn(f"serve: REJECTED job {jid!r}: {reason} "
                 f"(quarantined: {bad})")

    def _admit_one(self, path: str, doc, errs: List[str]) -> None:
        stem = os.path.splitext(os.path.basename(path))[0]
        if doc is None or errs:
            self._quarantine(path, stem, "; ".join(errs) or "unreadable")
            return
        verrs = validate_job(doc)
        jid = doc.get("job") if isinstance(doc.get("job"), str) else None
        if verrs:
            self._quarantine(path, jid or stem, "; ".join(verrs))
            return
        prior = self.state["jobs"].get(jid)
        if prior is not None:
            self._quarantine(
                path, jid,
                f"duplicate job id {jid!r} (already {prior['state']}); "
                "a replayed job is never re-run")
            return
        job = job_from_doc(doc, self._next_seq())
        verdict, reason = self.admission.decide(job, self._live_by_owner())
        if verdict == "reject":
            self.state["jobs"][jid] = {
                "state": "rejected", "steps_done": 0, "owner": job.owner,
                "priority": job.priority, "seq": job.seq, "reason": reason,
            }
            self._quarantine(path, jid, reason)
            return
        if verdict == "defer":
            self._deferred.append(job)
            self._register(job)
            self.state["jobs"][jid] = {
                "state": "deferred", "steps_done": 0, "owner": job.owner,
                "priority": job.priority, "seq": job.seq,
                "spec": job.spec_doc(), "reason": reason,
            }
            self._counters()["deferred"] += 1
            telemetry.get().meta("serve.deferred", job=jid, reason=reason)
            log.info(f"serve: deferred job {jid!r}: {reason}")
            return
        self._enqueue(job)

    def _promote(self) -> bool:
        """Move deferred jobs whose owner has quota headroom into the
        queue (priority/deadline order) — the QUEUES-not-rejects half of
        quota exhaustion."""
        changed = False
        live = self._live_by_owner()
        for job in sorted(self._deferred, key=ServeJob.order_key):
            q = self.admission.quota
            if q and live.get(job.owner, 0) >= q:
                continue
            self._deferred.remove(job)
            live[job.owner] = live.get(job.owner, 0) + 1
            self._enqueue(job, promoted=True)
            changed = True
        return changed

    # -- the driver's serving hooks -------------------------------------------
    def _refresh_queue(self, queue) -> None:
        """The steady-state intake pump (driver calls: per chunk, before
        every backfill scan). Draining stops claiming — undropped jobs
        stay in ``incoming/`` for the next daemon."""
        if self._drain:
            return
        polled = self.intake.poll()
        if not polled and not self._deferred:
            return
        for path, doc, errs in polled:
            self._admit_one(path, doc, errs)
        promoted = self._promote()
        if polled or promoted:
            self._flush_state()
            telemetry.get().gauge("serve.queue_depth",
                                  float(len(self.queue)), phase="serve")

    def _observe_chunk(self, bucket, per: float, done_now: int) -> None:
        self.pricer.observe(bucket, per)
        self._all_lat.append(per)
        self._check_pressure(bucket, done_now)
        if self.status is not None:
            # staged; run_guarded's per-chunk update flushes atomically
            self.status.set(queue=self.queue_stat())

    def _check_pressure(self, bucket, done_now: int) -> None:
        """Deadline-at-risk -> a first-class replan trigger: any queued
        or RUNNING job of this bucket whose deadline sits under the
        online p99 latches the ReplanController (once per bucket per
        swap window — pressure is a condition, not a siren)."""
        label = bucket_label(bucket)
        if label in self._pressure_sent:
            return
        priced = self.pricer.price(bucket)
        if priced is None:
            return
        p99_ms, source = priced
        candidates = list(self.queue) + [
            self._jobs_by_id[t] for t in sorted(self._running)
            if t in self._jobs_by_id]
        at_risk = sorted(j.tid for j in candidates
                         if j.bucket() == bucket and j.deadline_ms is not None
                         and float(j.deadline_ms) < p99_ms)
        if not at_risk:
            return
        self._pressure_sent.add(label)
        telemetry.get().meta(
            "replan.requested", reason="slo-pressure", step=int(done_now),
            bucket=label, p99_ms=float(p99_ms), jobs=at_risk,
            priced_from=source)
        log.warn(f"serve: SLO PRESSURE on bucket {label}: p99 "
                 f"{p99_ms:.4g} ms puts {at_risk} at deadline risk "
                 "(replan requested)")
        if self.replan is not None:
            self.replan.request({"metric": "slo-pressure", "bucket": label,
                                 "p99_ms": float(p99_ms),
                                 "step": int(done_now), "jobs": at_risk})

    def _mark_running(self, job: ServeJob) -> None:
        self._running.add(job.tid)
        st = self.state["jobs"].get(job.tid)
        if st is not None:
            st["state"] = "running"

    def _on_backfill(self, job, lane_idx: int, slot_step: int) -> None:
        self._counters()["backfills"] += 1
        self._mark_running(job)
        self._flush_state()

    def _on_result(self, r: TenantResult) -> None:
        """Stream the result the moment it exists: atomic
        ``results/<job>.json``, a ``serve.retired`` record, quota
        promotion, durable state."""
        self._running.discard(r.tid)
        st = self.state["jobs"].get(r.tid)
        if st is not None:
            st["state"] = r.outcome  # "done" | "fault"
            st["steps_done"] = int(r.steps)
        self._counters()["retired"] += 1
        self._retired_run += 1
        job = self._jobs_by_id.get(r.tid)
        self._write_result_doc(r, job)
        telemetry.get().meta(
            "serve.retired", job=r.tid, outcome=r.outcome,
            steps=int(r.steps), snapshot_dir=r.snapshot_dir,
            tenant=job.owner if job is not None else r.tid)
        self._promote()
        self._flush_state()

    def _write_result_doc(self, r: TenantResult,
                          job: Optional[ServeJob]) -> None:
        doc = {
            "v": 1, "kind": "serve-result", "job": r.tid,
            "tenant": job.owner if job is not None else r.tid,
            "outcome": r.outcome, "steps": int(r.steps),
            "snapshot_dir": r.snapshot_dir, "evidence": r.evidence,
            "t": time.time(),
        }
        os.makedirs(self.results_dir, exist_ok=True)
        tmp = os.path.join(self.results_dir,
                           f".tmp-{r.tid}.json-{os.getpid()}")
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.results_dir,
                                         f"{r.tid}.json"))
        except OSError:
            pass  # streaming is evidence; the snapshot dir is the truth

    def _segment_end(self, slot_step: int, end: int) -> int:
        # chunk-granular segments: the park check (and backfill scan)
        # runs every fused chunk, so SIGTERM drains at the next chunk
        # boundary instead of waiting out a whole tenant's remaining
        # steps — drain latency is one chunk, bounded and small
        return min(end, slot_step + self.chunk)

    def _should_park(self) -> bool:
        return self._drain

    def _on_park(self, job, tenant_step: int) -> None:
        self._running.discard(job.tid)
        st = self.state["jobs"].get(job.tid)
        if st is not None:
            st["state"] = "queued"
            st["steps_done"] = int(tenant_step)
        # back into the live queue: the in-memory view must agree with
        # the durable state (the drain log and summary count it as owed)
        self.queue.admit(job)
        telemetry.get().meta("serve.parked", job=job.tid,
                             step=int(tenant_step))
        log.info(f"serve: parked job {job.tid} at step {tenant_step} "
                 "(revivable)")

    # -- the serve loop -------------------------------------------------------
    def serve(self) -> dict:
        rec = telemetry.get()
        os.makedirs(self.campaign_dir, exist_ok=True)
        os.makedirs(self.results_dir, exist_ok=True)
        revived = self._revive()
        # the summary reports THIS run; the state counters (and the
        # status queue section) stay cumulative across revivals
        c0 = dict(self._counters())
        results = self.results
        lat: List[float] = []
        cell_steps = 0
        wall = 0.0
        slot_idx = 0
        t0 = time.perf_counter()
        idle_since: Optional[float] = None
        self._flush_state()
        if self.status is not None:
            self.status.update(queue=self.queue_stat())
        while True:
            if (self.max_wall_s > 0
                    and time.perf_counter() - t0 >= self.max_wall_s):
                self.request_drain("max-wall")
            self._refresh_queue(self.queue)
            if self._drain:
                break
            if not self.queue:
                now = time.monotonic()
                if idle_since is None:
                    idle_since = now
                if (self.max_idle_s > 0
                        and now - idle_since >= self.max_idle_s):
                    break
                if self.status is not None:
                    self.status.update(queue=self.queue_stat())
                time.sleep(self.poll_s)
                continue
            idle_since = None
            bucket, picked = pick_serve_slot(self.queue, self.slot_size)
            self._last_bucket = bucket
            for j in picked:
                self._mark_running(j)
            self._flush_state()
            stats = self._run_slot(slot_idx, bucket, picked, self.queue,
                                   results)
            lat.extend(stats["latency_samples"])
            cell_steps += stats["cell_steps"]
            wall += stats["wall_s"]
            slot_idx += 1
            if self.replan is not None and self.replan.pending:
                # between slots — the campaign's swap boundary; a swap
                # re-arms the per-bucket pressure latch
                self.replan.maybe_swap(None, slot_idx)
                self._pressure_sent.clear()

        outcome = "drained" if self._drain else "idle"
        if self._drain:
            rec.meta("serve.drain", reason=self._drain_reason or "requested",
                     queued=len(self.queue), deferred=len(self._deferred))
            log.info(f"serve: drained ({self._drain_reason}): "
                     f"{len(self.queue)} queued + {len(self._deferred)} "
                     "deferred job(s) persisted for revival")
        if self.admission_ledger:
            entries = self.pricer.ledger_entries(
                platform=self.devices[0].platform,
                label=rec.run_id or "serve")
            if entries:
                ledger_mod.append_entries(self.admission_ledger, entries)
        total_wall = time.perf_counter() - t0
        tph = (self._retired_run / total_wall * 3600.0
               if total_wall > 0 else 0.0)
        p50 = percentile(self._all_lat, 50) if self._all_lat else None
        p99 = percentile(self._all_lat, 99) if self._all_lat else None
        if self._retired_run and rec.enabled:
            rec.gauge("serve.tenants_per_hour", tph, phase="serve")
        if p99 is not None and rec.enabled:
            rec.gauge("serve.p99_ms", p99 * 1e3, phase="serve", unit="ms")
        c = self._counters()
        summary = {
            "outcome": outcome,
            "revived": revived,
            "slots": slot_idx,
            "retired": self._retired_run,
            "admitted": c["admitted"] - c0["admitted"],
            "rejected": c["rejected"] - c0["rejected"],
            "deferred": c["deferred"] - c0["deferred"],
            "backfills": c["backfills"] - c0["backfills"],
            "queued_remaining": len(self.queue) + len(self._deferred),
            "tenants_per_hour": tph,
            "p50_step_s": p50,
            "p99_step_s": p99,
            "evicted": sorted(t for t, r in results.items()
                              if r.outcome == "fault"),
            "slo_violations": sorted(self._slo_violated),
            "anomalies": (self.sentinel.detected_total
                          if self.sentinel is not None else 0),
            "cell_steps": cell_steps,
            "step_wall_s": wall,
            "total_wall_s": total_wall,
            "aggregate_mcells_per_s": (cell_steps / wall / 1e6
                                       if wall > 0 else 0.0),
            "cache": self.cache.stats(),
            "results": results,
        }
        self._flush_state()
        if self.status is not None:
            self.status.update(outcome=outcome, queue=self.queue_stat())
        return summary
