"""The always-on scheduler: continuous batching over the campaign driver.

:class:`ServeScheduler` subclasses :class:`~..campaign.driver.
CampaignDriver` and overrides its serving hooks — the batch campaign's
machinery (bucketed slots, guarded segments, eviction, per-tenant
snapshots) is reused verbatim; what changes is WHERE jobs come from and
WHEN they may enter:

- **Live intake.** ``_refresh_queue`` (called by the driver before every
  backfill scan and once per chunk) claims ``jobs/incoming/`` drops,
  runs admission, and grows the LIVE queue — so a job that arrives
  while a slot is mid-flight lands in the very next freed lane, with no
  slot-wide barrier. That is the continuous-batching extension: the
  driver's backfill path, promoted from drain-time to steady-state.
- **Deadline-sorted packing.** Baseline slot selection is
  :func:`~.queue.pick_serve_slot`: the most urgent queued job names the
  bucket, same-bucket jobs fill the slot tightest-deadline-first. With
  the CAPACITY ENGINE on (packing / fairness / elastic width — see
  below), selection is :func:`~.packer.pack_serve_slot` instead.
- **Capacity engine** (all opt-in; the bare constructor is the PR 19
  fixed-slot scheduler, which is also the A/B baseline):
  ``slot_min``/``slot_max`` make the slot width ELASTIC — each slot is
  sized to its bucket's queue depth on a power-of-two ladder
  (:class:`~.fairness.WidthPolicy`), a mid-slot surge GROWS the running
  slot by parking it at a chunk boundary (bit-identical snapshots) and
  re-forming it wider, and the pricer learns per-(bucket, width) cost
  rows so a B=64 slot is never priced with B=8 p99s. ``fairness`` swaps
  the strict priority sort for stride-weighted shares with
  deadline-aware aging (:class:`~.fairness.FairnessPolicy`) — sustained
  ``high`` load degrades ``low`` smoothly instead of starving it.
  ``packing`` scores every contender bucket by ledger-priced throughput
  and deadline slack (:func:`~.packer.pack_serve_slot`). ``preempt``
  lets a queued ``high`` job whose completion budget cannot survive
  waiting out the running slot PARK that slot mid-flight — priced
  against the victims' resume cost, so a preemption that buys less than
  it spends is vetoed (``serve.preempt_veto``), and thrashing is
  structurally impossible. Every decision lands as a schema-valid
  record: ``serve.packed``, ``serve.resized``, ``serve.preempted``,
  ``serve.preempt_veto``.
- **SLO pressure.** ``_observe_chunk`` prices every chunk into the
  :class:`~.admission.BucketPricer`; when a queued or running job's
  deadline falls under the bucket's online p99, the scheduler emits a
  first-class ``replan.requested`` (reason ``slo-pressure``) and
  latches the :class:`~..plan.replan.ReplanController` — the hot-swap
  fires at the next slot boundary, exactly like a sentinel anomaly.
- **Result streaming.** ``_on_result`` writes ``results/<job>.json``
  atomically the moment a tenant retires (or faults out), emits
  ``serve.retired``, and promotes deferred jobs into freed quota.
- **Drain + revival.** ``request_drain`` (the SIGTERM handler's one
  call) parks every live lane as a revivable snapshot at the next
  segment boundary; ``serve-state.json`` (serve/state.py, atomic)
  always knows which jobs are owed work, so a killed-and-revived
  daemon resumes admitted-but-unserved jobs and never re-runs retired
  ones — whole-service crash-revival rides the PR 3 watchdog.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from ..campaign.driver import CampaignDriver, TenantResult
from ..obs import ledger as ledger_mod
from ..obs import telemetry
from ..utils import logging as log
from ..utils.statistics import percentile
from . import state as state_mod
from .admission import AdmissionController, BucketPricer, bucket_label
from .fairness import FairnessPolicy, WidthPolicy
from .intake import Intake, ServeJob, job_from_doc, validate_job
from .packer import pack_serve_slot
from .queue import ServeQueue, pick_serve_slot


class ServeScheduler(CampaignDriver):
    """A persistent :class:`CampaignDriver` fed by file-drop intake.

    ``serve_dir`` owns the whole service: ``jobs/`` (intake),
    ``campaign/`` (slot machinery + tenant snapshots), ``results/``
    (streamed per-tenant results), ``serve-state.json``. ``quota`` is
    the per-tenant cap on live jobs (0 = unlimited);
    ``admission_ledger`` seeds deadline pricing and receives the run's
    per-bucket p99 back at exit; ``max_idle_s`` > 0 exits after that
    long with an empty queue (0 = serve until drained by signal);
    ``max_wall_s`` > 0 is a total-budget self-drain."""

    def __init__(self, serve_dir: str, slot_size: int, *,
                 quota: int = 0, admission_ledger: Optional[str] = None,
                 poll_s: float = 0.2, max_idle_s: float = 0.0,
                 max_wall_s: float = 0.0,
                 slot_min: Optional[int] = None,
                 slot_max: Optional[int] = None,
                 packing: bool = False, preempt: bool = False,
                 fairness: bool = False,
                 fair_weights: Optional[Dict[str, float]] = None,
                 aging_s: float = 30.0,
                 preempt_cost_chunks: float = 1.0, **kw):
        kw.setdefault("resume", True)  # revival is the serving default
        super().__init__([], slot_size,
                         os.path.join(serve_dir, "campaign"), **kw)
        self.serve_dir = serve_dir
        self.results_dir = os.path.join(serve_dir, "results")
        self.state_path = os.path.join(serve_dir, "serve-state.json")
        self.intake = Intake(serve_dir)
        self.pricer = BucketPricer(admission_ledger)
        self.admission = AdmissionController(quota=quota, pricer=self.pricer)
        self.admission_ledger = admission_ledger or None
        self.poll_s = max(0.01, float(poll_s))
        self.max_idle_s = float(max_idle_s)
        self.max_wall_s = float(max_wall_s)
        # -- the capacity engine (all OFF by default: the bare
        # constructor is the PR 19 fixed-slot scheduler, the A/B
        # baseline; apps/serve.py turns the engine on) ---------------------
        self.width_policy = WidthPolicy(
            slot_size if slot_min is None else slot_min,
            slot_size if slot_max is None else slot_max)
        self.fairness = (FairnessPolicy(fair_weights, aging_s=aging_s)
                         if fairness else None)
        self.packing = bool(packing)
        self.preempt = bool(preempt)
        self.preempt_cost_chunks = float(preempt_cost_chunks)
        self.queue = ServeQueue(policy=self.fairness)
        self.state = state_mod.make_state()
        self.results: Dict[str, TenantResult] = {}
        self._deferred: List[ServeJob] = []
        self._jobs_by_id: Dict[str, ServeJob] = {}
        self._running: set = set()
        self._drain = False
        self._drain_reason = ""
        self._pressure_sent: set = set()
        self._all_lat: List[float] = []
        self._retired_run = 0
        self._seq = 0
        self._last_bucket: Optional[Tuple] = None
        # capacity-engine state: the park reason distinguishes a
        # capacity park (preempt/resize — the serve loop continues) from
        # a drain (it exits); preemption latches once per slot and per
        # vetoed beneficiary so the per-chunk check is not a siren
        self._park_reason: Optional[str] = None
        self._preempt_for: Optional[str] = None
        self._preempted_this_slot = False
        self._preempt_vetoed: set = set()
        self._preemptions = 0
        self._resizes = 0
        self._last_width: Dict[str, int] = {}
        self._lat_by_pri: Dict[str, List[float]] = {}

    # -- drain (the SIGTERM handler calls exactly this) -----------------------
    def request_drain(self, reason: str) -> None:
        """Stop claiming intake, park live lanes at the next segment
        boundary, persist everything, exit cleanly. Signal-safe: plain
        assignments only — the serve loop does the work."""
        self._drain = True
        if not self._drain_reason:
            self._drain_reason = str(reason)

    # -- durable state --------------------------------------------------------
    def _flush_state(self) -> None:
        self.state["draining"] = self._drain
        state_mod.write_state(self.state_path, self.state)

    def _counters(self) -> dict:
        return self.state["counters"]

    def queue_stat(self) -> dict:
        """The status snapshot's ``queue`` section (obs/status.py)."""
        c = self._counters()
        return {
            "depth": len(self.queue),
            "admitted": c["admitted"],
            "rejected": c["rejected"],
            "backfills": c["backfills"],
            "deferred": len(self._deferred),
            "retired": c["retired"],
            "preempted": self._preemptions,
            "resized": self._resizes,
            "width": int(self._cur_width),
        }

    def _live_by_owner(self) -> Dict[str, int]:
        """Live (queued + running) job counts per owning tenant — the
        quota denominator. Deferred jobs do not count (a tenant's own
        holding pen must not block its promotions)."""
        live: Dict[str, int] = {}
        for j in self.state["jobs"].values():
            if j["state"] in ("queued", "running"):
                live[j["owner"]] = live.get(j["owner"], 0) + 1
        return live

    # -- revival --------------------------------------------------------------
    def _revive(self) -> int:
        """Load serve-state.json and re-queue every job the previous
        daemon still owed work: queued/running -> the live queue
        (running tenants resume from their newest snapshot — the ckpt
        bit-identity contract), deferred -> the holding pen. Terminal
        jobs (done/fault/rejected) are never touched."""
        doc = state_mod.read_state(self.state_path)
        if doc is None:
            return 0
        errs = state_mod.validate_state(doc)
        if errs:
            raise ValueError(
                f"corrupt serve-state at {self.state_path}: "
                + "; ".join(errs[:3]))
        self.state = doc
        n = 0
        jobs = sorted(doc["jobs"].items(),
                      key=lambda kv: kv[1].get("seq", 0))
        for jid, j in jobs:
            self._seq = max(self._seq, int(j.get("seq", 0)) + 1)
            if j["state"] not in state_mod.LIVE_STATES:
                continue
            job = job_from_doc(j["spec"], int(j.get("seq", 0)))
            n += 1
            if j["state"] == "deferred":
                self._deferred.append(job)
                self._register(job)
            else:
                j["state"] = "queued"  # running-at-crash resumes
                self._enqueue(job, revived=True)
        if n:
            telemetry.get().meta(
                "serve.revived", jobs=n, queued=len(self.queue),
                deferred=len(self._deferred))
            log.info(f"serve: revived {n} unserved job(s) from "
                     f"{self.state_path}")
        self._promote()
        return n

    # -- admission ------------------------------------------------------------
    def _next_seq(self) -> int:
        s = self._seq
        self._seq += 1
        return s

    def _register(self, job: ServeJob) -> None:
        self._jobs_by_id[job.tid] = job
        self.jobs.append(job)  # driver-level registry (injector, summary)

    def _enqueue(self, job: ServeJob, *, revived: bool = False,
                 promoted: bool = False) -> None:
        self.queue.admit(job)
        if job.tid not in self._jobs_by_id:
            self._register(job)
        st = self.state["jobs"].setdefault(job.tid, {
            "steps_done": 0, "owner": job.owner, "priority": job.priority,
            "seq": job.seq, "spec": job.spec_doc(),
        })
        st["state"] = "queued"
        if not revived:
            self._counters()["admitted"] += 1
            telemetry.get().meta(
                "serve.admitted", job=job.tid, tenant=job.owner,
                priority=job.priority, seq=job.seq,
                deadline_ms=job.deadline_ms, promoted=promoted,
                bucket=bucket_label(job.bucket()))

    def _quarantine(self, path: str, jid: str, reason: str) -> None:
        bad = self.intake.quarantine(path, reason)
        self._counters()["rejected"] += 1
        telemetry.get().meta("serve.rejected", job=jid, reason=reason,
                             file=bad)
        log.warn(f"serve: REJECTED job {jid!r}: {reason} "
                 f"(quarantined: {bad})")

    def _admit_one(self, path: str, doc, errs: List[str]) -> None:
        stem = os.path.splitext(os.path.basename(path))[0]
        if doc is None or errs:
            self._quarantine(path, stem, "; ".join(errs) or "unreadable")
            return
        verrs = validate_job(doc)
        jid = doc.get("job") if isinstance(doc.get("job"), str) else None
        if verrs:
            self._quarantine(path, jid or stem, "; ".join(verrs))
            return
        prior = self.state["jobs"].get(jid)
        if prior is not None:
            self._quarantine(
                path, jid,
                f"duplicate job id {jid!r} (already {prior['state']}); "
                "a replayed job is never re-run")
            return
        job = job_from_doc(doc, self._next_seq())
        # price the slot width this job would actually run at (the
        # elastic ladder rung covering its bucket's depth + itself)
        depth = 1 + sum(1 for q in self.queue.jobs()
                        if q.bucket() == job.bucket())
        verdict, reason = self.admission.decide(
            job, self._live_by_owner(),
            width_hint=self.width_policy.choose(depth))
        if verdict == "reject":
            self.state["jobs"][jid] = {
                "state": "rejected", "steps_done": 0, "owner": job.owner,
                "priority": job.priority, "seq": job.seq, "reason": reason,
            }
            self._quarantine(path, jid, reason)
            return
        if verdict == "defer":
            self._deferred.append(job)
            self._register(job)
            self.state["jobs"][jid] = {
                "state": "deferred", "steps_done": 0, "owner": job.owner,
                "priority": job.priority, "seq": job.seq,
                "spec": job.spec_doc(), "reason": reason,
            }
            self._counters()["deferred"] += 1
            telemetry.get().meta("serve.deferred", job=jid, reason=reason)
            log.info(f"serve: deferred job {jid!r}: {reason}")
            return
        self._enqueue(job)

    def _promote(self) -> bool:
        """Move deferred jobs whose owner has quota headroom into the
        queue (priority/deadline order) — the QUEUES-not-rejects half of
        quota exhaustion."""
        changed = False
        live = self._live_by_owner()
        for job in sorted(self._deferred, key=ServeJob.order_key):
            q = self.admission.quota
            if q and live.get(job.owner, 0) >= q:
                continue
            self._deferred.remove(job)
            live[job.owner] = live.get(job.owner, 0) + 1
            self._enqueue(job, promoted=True)
            changed = True
        return changed

    # -- the driver's serving hooks -------------------------------------------
    def _refresh_queue(self, queue) -> None:
        """The steady-state intake pump (driver calls: per chunk, before
        every backfill scan). Draining stops claiming — undropped jobs
        stay in ``incoming/`` for the next daemon."""
        if self._drain:
            return
        polled = self.intake.poll()
        if not polled and not self._deferred:
            return
        for path, doc, errs in polled:
            self._admit_one(path, doc, errs)
        promoted = self._promote()
        if polled or promoted:
            self._flush_state()
            telemetry.get().gauge("serve.queue_depth",
                                  float(len(self.queue)), phase="serve")

    def _observe_chunk(self, bucket, per: float, done_now: int) -> None:
        self.pricer.observe(bucket, per, width=self._cur_width)
        self._all_lat.append(per)
        # every live lane stepped together, so the chunk's per-step wall
        # is a sample for each lane's priority class — the split
        # report.py folds by the `priority` tag
        for lane in self._cur_lanes:
            if lane.tenant is not None:
                pri = getattr(lane.tenant, "priority", "normal")
                self._lat_by_pri.setdefault(pri, []).append(per)
        self._check_pressure(bucket, done_now)
        self._maybe_resize(bucket, done_now)
        self._maybe_preempt(bucket, done_now)
        if self.status is not None:
            # staged; run_guarded's per-chunk update flushes atomically
            self.status.set(queue=self.queue_stat())

    def _check_pressure(self, bucket, done_now: int) -> None:
        """Deadline-at-risk -> a first-class replan trigger: any queued
        or RUNNING job of this bucket whose deadline sits under the
        online p99 latches the ReplanController (once per bucket per
        swap window — pressure is a condition, not a siren)."""
        label = bucket_label(bucket)
        if label in self._pressure_sent:
            return
        priced = self.pricer.price(bucket)
        if priced is None:
            return
        p99_ms, source = priced
        candidates = list(self.queue) + [
            self._jobs_by_id[t] for t in sorted(self._running)
            if t in self._jobs_by_id]
        at_risk = sorted(j.tid for j in candidates
                         if j.bucket() == bucket and j.deadline_ms is not None
                         and float(j.deadline_ms) < p99_ms)
        if not at_risk:
            return
        self._pressure_sent.add(label)
        telemetry.get().meta(
            "replan.requested", reason="slo-pressure", step=int(done_now),
            bucket=label, p99_ms=float(p99_ms), jobs=at_risk,
            priced_from=source)
        log.warn(f"serve: SLO PRESSURE on bucket {label}: p99 "
                 f"{p99_ms:.4g} ms puts {at_risk} at deadline risk "
                 "(replan requested)")
        if self.replan is not None:
            self.replan.request({"metric": "slo-pressure", "bucket": label,
                                 "p99_ms": float(p99_ms),
                                 "step": int(done_now), "jobs": at_risk})

    # -- chunk-boundary capacity decisions ------------------------------------
    def _live_lanes(self) -> list:
        return [l for l in self._cur_lanes if l.tenant is not None]

    def _slot_remaining_ms(self, bucket,
                           done_now: int) -> Optional[Tuple[float, str]]:
        """The RUNNING slot's priced remaining wall ``(ms, source)``, or
        None when the pricer has no row — capacity decisions never
        guess."""
        lanes = self._live_lanes()
        if not lanes:
            return None
        priced = self.pricer.price(bucket, width=self._cur_width)
        if priced is None:
            return None
        p99_ms, source = priced
        rem = max(l.tenant.steps - l.tenant_step(done_now) for l in lanes)
        return max(0, rem) * p99_ms, source

    def _maybe_resize(self, bucket, done_now: int) -> None:
        """GROW the running slot mid-flight: when the same-bucket
        backlog would fill a larger ladder rung AND the priced remaining
        wall amortizes the park/revive, park the slot (bit-identical
        snapshots) so the next pack re-forms it wider. Shrinking needs
        no park — the next slot simply chooses a smaller rung."""
        if (self.width_policy.fixed or self._drain
                or self._park_reason is not None):
            return
        lanes = self._live_lanes()
        if not lanes or self._cur_width >= self.width_policy.slot_max:
            return
        queued_same = sum(1 for j in self.queue.jobs()
                          if j.bucket() == bucket)
        depth = len(lanes) + queued_same
        want = self.width_policy.choose(depth)
        # grow only when the backlog would otherwise cost at least one
        # whole extra slot at the current width
        if want <= self._cur_width or queued_same < self._cur_width:
            return
        rem = self._slot_remaining_ms(bucket, done_now)
        if rem is None:
            return  # unpriced growth is a guess — decline
        rem_ms, source = rem
        priced = self.pricer.price(bucket, width=self._cur_width)
        cost_ms = self.preempt_cost_chunks * self.chunk * priced[0]
        if rem_ms <= cost_ms:
            return  # the slot is nearly done; let it finish
        self._park_reason = "resize"
        self._resizes += 1
        telemetry.get().meta(
            "serve.resized", bucket=bucket_label(bucket),
            from_width=int(self._cur_width), to_width=int(want),
            reason="grow", depth=int(depth), remaining_ms=float(rem_ms),
            cost_ms=float(cost_ms), priced_from=source)
        log.info(f"serve: RESIZE bucket {bucket_label(bucket)} "
                 f"B={self._cur_width} -> {want} (depth {depth}, "
                 f"remaining {rem_ms:.4g} ms > resize cost "
                 f"{cost_ms:.4g} ms)")

    def _maybe_preempt(self, bucket, done_now: int) -> None:
        """Park the running slot for a queued ``high`` deadline job of a
        DIFFERENT bucket that cannot make its completion budget waiting
        in queue — but only when the wait avoided exceeds the victims'
        priced resume cost, so thrashing is structurally impossible
        (each preemption must buy more than it spends, and at most one
        fires per slot)."""
        if (not self.preempt or self._drain
                or self._park_reason is not None
                or self._preempted_this_slot):
            return
        cands = [j for j in self.queue.jobs()
                 if j.priority == "high" and j.deadline_ms is not None
                 and j.bucket() != bucket
                 and j.tid not in self._preempt_vetoed]
        if not cands:
            return
        rem = self._slot_remaining_ms(bucket, done_now)
        if rem is None:
            return  # unpriced victims: preemption never guesses
        rem_ms, source = rem
        victims = [l.tenant for l in self._live_lanes()]
        if any(getattr(v, "priority", "normal") == "high"
               for v in victims):
            return  # only a strictly lower-value lane-set is parkable
        victim_p99 = self.pricer.price(bucket, width=self._cur_width)[0]
        resume_cost_ms = (self.preempt_cost_chunks * self.chunk
                          * victim_p99 * len(victims))
        rec = telemetry.get()
        for j in sorted(cands, key=lambda j: (float(j.deadline_ms)
                                              * j.steps, j.seq)):
            jw = self.width_policy.choose(1)
            priced_j = self.pricer.price(j.bucket(), width=jw)
            if priced_j is None:
                continue  # can't price the beneficiary either
            budget_ms = float(j.deadline_ms) * j.steps
            wait_budget_ms = budget_ms - priced_j[0] * j.steps
            if rem_ms <= wait_budget_ms:
                continue  # feasible in queue — no preemption needed
            gain_ms = rem_ms - max(0.0, wait_budget_ms)
            if gain_ms <= resume_cost_ms:
                self._preempt_vetoed.add(j.tid)
                rec.meta("serve.preempt_veto", job=j.tid,
                         bucket=bucket_label(j.bucket()),
                         victim_bucket=bucket_label(bucket),
                         gain_ms=float(gain_ms),
                         resume_cost_ms=float(resume_cost_ms),
                         remaining_ms=float(rem_ms), priced_from=source)
                log.info(f"serve: preempt VETO for {j.tid}: gain "
                         f"{gain_ms:.4g} ms <= victim resume cost "
                         f"{resume_cost_ms:.4g} ms")
                continue
            self._park_reason = "preempt"
            self._preempt_for = j.tid
            self._preempted_this_slot = True
            self._preemptions += 1
            rec.meta("serve.preempted", job=j.tid,
                     bucket=bucket_label(j.bucket()),
                     victim_bucket=bucket_label(bucket),
                     victims=sorted(v.tid for v in victims),
                     gain_ms=float(gain_ms),
                     resume_cost_ms=float(resume_cost_ms),
                     remaining_ms=float(rem_ms), priced_from=source)
            log.warn(f"serve: PREEMPT slot bucket "
                     f"{bucket_label(bucket)} for high job {j.tid}: "
                     f"waiting {rem_ms:.4g} ms breaks its budget "
                     f"{budget_ms:.4g} ms (gain {gain_ms:.4g} ms > "
                     f"resume cost {resume_cost_ms:.4g} ms)")
            return

    def _mark_running(self, job: ServeJob) -> None:
        self._running.add(job.tid)
        st = self.state["jobs"].get(job.tid)
        if st is not None:
            st["state"] = "running"

    def _backfill_gate(self, bucket) -> bool:
        """The aging bound's second half: packing alone cannot bound a
        different-bucket job's wait when a same-bucket stream keeps the
        slot alive via backfill — so once any queued job is URGENT
        (waited past ``aging_s * (rank + 1)``) and belongs to another
        bucket, freed lanes stop refilling, the slot drains, and the
        next pack's aging override serves the overdue job."""
        if self.fairness is None:
            return True
        now = self.fairness.clock()
        return not any(j.bucket() != bucket
                       for j in self.queue.jobs(now)
                       if self.fairness.urgent(j, now))

    def _on_backfill(self, job, lane_idx: int, slot_step: int) -> None:
        self._counters()["backfills"] += 1
        if self.fairness is not None:
            # a backfilled job was never packed: charge its class here
            self.fairness.charge(getattr(job, "priority", "normal"))
        self._mark_running(job)
        self._flush_state()

    def _on_result(self, r: TenantResult) -> None:
        """Stream the result the moment it exists: atomic
        ``results/<job>.json``, a ``serve.retired`` record, quota
        promotion, durable state."""
        self._running.discard(r.tid)
        st = self.state["jobs"].get(r.tid)
        if st is not None:
            st["state"] = r.outcome  # "done" | "fault"
            st["steps_done"] = int(r.steps)
        self._counters()["retired"] += 1
        self._retired_run += 1
        job = self._jobs_by_id.get(r.tid)
        self._write_result_doc(r, job)
        telemetry.get().meta(
            "serve.retired", job=r.tid, outcome=r.outcome,
            steps=int(r.steps), snapshot_dir=r.snapshot_dir,
            tenant=job.owner if job is not None else r.tid)
        self._promote()
        self._flush_state()

    def _write_result_doc(self, r: TenantResult,
                          job: Optional[ServeJob]) -> None:
        doc = {
            "v": 1, "kind": "serve-result", "job": r.tid,
            "tenant": job.owner if job is not None else r.tid,
            "outcome": r.outcome, "steps": int(r.steps),
            "snapshot_dir": r.snapshot_dir, "evidence": r.evidence,
            "t": time.time(),
        }
        os.makedirs(self.results_dir, exist_ok=True)
        tmp = os.path.join(self.results_dir,
                           f".tmp-{r.tid}.json-{os.getpid()}")
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.results_dir,
                                         f"{r.tid}.json"))
        except OSError:
            pass  # streaming is evidence; the snapshot dir is the truth

    def _segment_end(self, slot_step: int, end: int) -> int:
        # chunk-granular segments: the park check (and backfill scan)
        # runs every fused chunk, so SIGTERM drains at the next chunk
        # boundary instead of waiting out a whole tenant's remaining
        # steps — drain latency is one chunk, bounded and small
        return min(end, slot_step + self.chunk)

    def _should_park(self) -> bool:
        # drain parks to EXIT; a capacity park (preempt/resize) parks to
        # re-form the slot — the serve loop continues
        return self._drain or self._park_reason is not None

    def _on_park(self, job, tenant_step: int) -> None:
        self._running.discard(job.tid)
        st = self.state["jobs"].get(job.tid)
        if st is not None:
            st["state"] = "queued"
            st["steps_done"] = int(tenant_step)
        # back into the live queue: the in-memory view must agree with
        # the durable state (the drain log and summary count it as owed)
        self.queue.admit(job)
        if self.fairness is not None:
            # parked, not served: refund the share charged at pack time
            self.fairness.charge(getattr(job, "priority", "normal"), -1)
        telemetry.get().meta("serve.parked", job=job.tid,
                             step=int(tenant_step),
                             reason=self._park_reason or "drain")
        log.info(f"serve: parked job {job.tid} at step {tenant_step} "
                 f"({self._park_reason or 'drain'}, revivable)")

    # -- the serve loop -------------------------------------------------------
    def serve(self) -> dict:
        rec = telemetry.get()
        os.makedirs(self.campaign_dir, exist_ok=True)
        os.makedirs(self.results_dir, exist_ok=True)
        revived = self._revive()
        # the summary reports THIS run; the state counters (and the
        # status queue section) stay cumulative across revivals
        c0 = dict(self._counters())
        results = self.results
        lat: List[float] = []
        cell_steps = 0
        wall = 0.0
        slot_idx = 0
        t0 = time.perf_counter()
        idle_since: Optional[float] = None
        self._flush_state()
        if self.status is not None:
            self.status.update(queue=self.queue_stat())
        while True:
            if (self.max_wall_s > 0
                    and time.perf_counter() - t0 >= self.max_wall_s):
                self.request_drain("max-wall")
            self._refresh_queue(self.queue)
            if self._drain:
                break
            if not self.queue:
                now = time.monotonic()
                if idle_since is None:
                    idle_since = now
                if (self.max_idle_s > 0
                        and now - idle_since >= self.max_idle_s):
                    break
                if self.status is not None:
                    self.status.update(queue=self.queue_stat())
                time.sleep(self.poll_s)
                continue
            idle_since = None
            engine = (self.packing or self.fairness is not None
                      or not self.width_policy.fixed)
            if engine:
                plan = pack_serve_slot(self.queue, self.width_policy,
                                       pricer=self.pricer,
                                       fairness=self.fairness)
                bucket, picked, width = plan.bucket, plan.picked, plan.width
                label = bucket_label(bucket)
                prev_w = self._last_width.get(label)
                if prev_w is not None and prev_w != width:
                    self._resizes += 1
                    rec.meta("serve.resized", bucket=label,
                             from_width=int(prev_w), to_width=int(width),
                             reason=("shrink" if width < prev_w
                                     else "grow"),
                             depth=len(picked) + len(self.queue))
                self._last_width[label] = width
                rec.meta(
                    "serve.packed", bucket=label, width=int(width),
                    jobs=[j.tid for j in picked], lead=plan.lead,
                    reason=plan.reason, candidates=plan.candidates,
                    fairness=(self.fairness.snapshot()
                              if self.fairness is not None else None))
                rec.gauge("serve.slot_width", float(width), phase="serve",
                          bucket=label)
            else:
                bucket, picked = pick_serve_slot(self.queue,
                                                 self.slot_size)
                width = self.slot_size
            self._last_bucket = bucket
            for j in picked:
                self._mark_running(j)
            self._flush_state()
            stats = self._run_slot(slot_idx, bucket, picked, self.queue,
                                   results, width=width)
            lat.extend(stats["latency_samples"])
            cell_steps += stats["cell_steps"]
            wall += stats["wall_s"]
            slot_idx += 1
            if self._park_reason is not None:
                # a capacity park, not a drain: the parked jobs are back
                # in the queue; the next pack re-forms the slot (wider,
                # or around the preempting high job)
                self._park_reason = None
                self._preempt_for = None
                self._preempted_this_slot = False
                self._preempt_vetoed.clear()
            if self.replan is not None and self.replan.pending:
                # between slots — the campaign's swap boundary; a swap
                # re-arms the per-bucket pressure latch
                self.replan.maybe_swap(None, slot_idx)
                self._pressure_sent.clear()

        outcome = "drained" if self._drain else "idle"
        if self._drain:
            rec.meta("serve.drain", reason=self._drain_reason or "requested",
                     queued=len(self.queue), deferred=len(self._deferred))
            log.info(f"serve: drained ({self._drain_reason}): "
                     f"{len(self.queue)} queued + {len(self._deferred)} "
                     "deferred job(s) persisted for revival")
        if self.admission_ledger:
            entries = self.pricer.ledger_entries(
                platform=self.devices[0].platform,
                label=rec.run_id or "serve")
            if entries:
                ledger_mod.append_entries(self.admission_ledger, entries)
        total_wall = time.perf_counter() - t0
        tph = (self._retired_run / total_wall * 3600.0
               if total_wall > 0 else 0.0)
        p50 = percentile(self._all_lat, 50) if self._all_lat else None
        p99 = percentile(self._all_lat, 99) if self._all_lat else None
        if self._retired_run and rec.enabled:
            rec.gauge("serve.tenants_per_hour", tph, phase="serve")
        if p99 is not None and rec.enabled:
            rec.gauge("serve.p99_ms", p99 * 1e3, phase="serve", unit="ms")
        # the per-class split: a folded p99 averages high and low lanes
        # into a number that describes neither; report.py keeps these
        # separate via the `priority` tag
        p99_by_pri = {pri: percentile(v, 99) * 1e3
                      for pri, v in sorted(self._lat_by_pri.items()) if v}
        if rec.enabled:
            for pri, v_ms in p99_by_pri.items():
                rec.gauge("serve.p99_ms", v_ms, phase="serve", unit="ms",
                          priority=pri)
        c = self._counters()
        summary = {
            "outcome": outcome,
            "revived": revived,
            "slots": slot_idx,
            "retired": self._retired_run,
            "admitted": c["admitted"] - c0["admitted"],
            "rejected": c["rejected"] - c0["rejected"],
            "deferred": c["deferred"] - c0["deferred"],
            "backfills": c["backfills"] - c0["backfills"],
            "queued_remaining": len(self.queue) + len(self._deferred),
            "tenants_per_hour": tph,
            "p50_step_s": p50,
            "p99_step_s": p99,
            "p99_ms_by_priority": p99_by_pri,
            "preemptions": self._preemptions,
            "resizes": self._resizes,
            "fairness": (self.fairness.snapshot()
                         if self.fairness is not None else None),
            "evicted": sorted(t for t, r in results.items()
                              if r.outcome == "fault"),
            "slo_violations": sorted(self._slo_violated),
            "anomalies": (self.sentinel.detected_total
                          if self.sentinel is not None else 0),
            "cell_steps": cell_steps,
            "step_wall_s": wall,
            "total_wall_s": total_wall,
            "aggregate_mcells_per_s": (cell_steps / wall / 1e6
                                       if wall > 0 else 0.0),
            "cache": self.cache.stats(),
            "results": results,
        }
        self._flush_state()
        if self.status is not None:
            self.status.update(outcome=outcome, queue=self.queue_stat())
        return summary
