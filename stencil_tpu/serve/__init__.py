"""Always-on campaign serving: intake, admission, continuous batching.

The batch campaign (``stencil_tpu/campaign/``) answers "run this fixed
job list to completion"; this package turns the same driver into a
persistent daemon — jobs arrive as file drops while slots are running,
admission control prices deadlines from the performance ledger, retired
lanes are backfilled from a LIVE queue with no slot-wide barrier, and a
killed daemon revives from ``serve-state.json`` owing exactly the jobs
it had admitted but not retired. ``stencil-tpu serve`` (apps/serve.py)
is the CLI front-end.
"""

from .admission import (AdmissionController, BucketPricer,  # noqa: F401
                        LEDGER_METRIC, bucket_label)
from .fairness import (DEFAULT_WEIGHTS, FairnessPolicy,  # noqa: F401
                       WidthPolicy)
from .intake import (Intake, PRIORITIES, ServeJob,  # noqa: F401
                     job_from_doc, validate_job)
from .packer import SlotPlan, pack_serve_slot  # noqa: F401
from .queue import ServeQueue, pick_serve_slot  # noqa: F401
from .scheduler import ServeScheduler  # noqa: F401
from .state import (JOB_STATES, LIVE_STATES, make_state,  # noqa: F401
                    read_state, validate_state, write_state)
