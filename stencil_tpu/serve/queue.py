"""The LIVE priority queue and the deadline-sorted slot packer.

The batch driver's queue is a deque fixed at launch; this one grows
while slots run. It keeps the driver's backfill protocol — iteration
and ``remove`` — so ``CampaignDriver``'s backfill closure pulls from it
unchanged, but its iteration ORDER is the serving policy: priority
class, then deadline (tightest first), then admission order. Because
the queue holds only unscheduled jobs, priority reordering can only
ever affect QUEUED tenants — a running lane is structurally
unpreemptable.

:func:`pick_serve_slot` is :func:`~..campaign.driver.pick_slot`'s
serving twin: the head (most urgent job) names the bucket, same-bucket
jobs fill the slot in queue order — deadline-sorted bucket packing. It
removes the picked jobs IN PLACE so the queue object stays live for
mid-slot backfill.
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

from .intake import ServeJob


class ServeQueue:
    """A small always-sorted job list (serving queues are tens of jobs;
    sort-on-access keeps every scan trivially in policy order).

    With no ``policy`` the order is :meth:`ServeJob.order_key` — strict
    priority, PR 19's rule. A :class:`~.fairness.FairnessPolicy` makes
    the order TIME-DEPENDENT (aged rank decays while a job waits), so
    the queue re-sorts on access rather than only on admit; admission
    also stamps ``job.admit_t``, the aging clock's zero."""

    def __init__(self, policy=None):
        self._items: List[ServeJob] = []
        self._policy = policy

    def _sort(self, now=None) -> None:
        if self._policy is not None:
            self._items.sort(
                key=lambda j: self._policy.queue_key(j, now))
        else:
            self._items.sort(key=ServeJob.order_key)

    def admit(self, job: ServeJob) -> None:
        if self._policy is not None and job.admit_t is None:
            job.admit_t = self._policy.clock()
        self._items.append(job)
        self._sort()

    def remove(self, job: ServeJob) -> None:
        self._items.remove(job)

    def peek(self) -> ServeJob:
        if not self._items:
            raise RuntimeError("peek on an empty serve queue")
        self._sort()
        return self._items[0]

    def jobs(self, now=None) -> List[ServeJob]:
        self._sort(now)
        return list(self._items)

    def __iter__(self) -> Iterator[ServeJob]:
        return iter(self.jobs())

    def __len__(self) -> int:
        return len(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)


def pick_serve_slot(queue: ServeQueue,
                    slot_size: int) -> Tuple[Tuple, List[ServeJob]]:
    """Pop the next slot's jobs from the LIVE queue: the most urgent
    job's bucket, same-bucket jobs pulled in queue order (priority,
    deadline, arrival) until the slot fills. Returns ``(bucket,
    picked)``; the queue keeps everything else."""
    bucket = queue.peek().bucket()
    picked = [j for j in queue if j.bucket() == bucket][:slot_size]
    for j in picked:
        queue.remove(j)
    return bucket, picked
