"""Elastic checkpoint/restart for distributed grid state.

- :mod:`snapshot`: per-block sharded snapshots with a JSON manifest,
  crash-safe rename protocol, retention, async double-buffered writes.
- :mod:`restore`: validation + elastic restore onto a different
  partition/mesh (global reassembly, re-split, halo exchange).

The user-facing surface is ``DistributedDomain.save_checkpoint`` /
``restore_checkpoint`` (api.py) and ``apps/ckpt_tool.py``.
"""

from .snapshot import (  # noqa: F401
    LATEST_NAME,
    MANIFEST_NAME,
    MANIFEST_VERSION,
    AsyncCheckpointer,
    host_snapshot,
    list_snapshots,
    prune,
    read_latest,
    snapshot_name,
    step_of,
    write_snapshot,
)
from .restore import (  # noqa: F401
    QUARANTINE_PREFIX,
    assemble_global,
    check_compatible,
    find_resume,
    load_manifest,
    quarantine_snapshot,
    validate_manifest,
    validate_snapshot,
)
