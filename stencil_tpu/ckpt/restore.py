"""Restore sharded snapshots onto the *current* domain — elastically.

The read side of the checkpoint subsystem (snapshot.py is the writer).
A snapshot stores per-block compute interiors plus a manifest; nothing in
it presumes the restoring run's mesh. Restore therefore works across
partition changes: the saved blocks are reassembled into the global
interior (pure numpy, no jax needed until the scatter), re-split with the
current ``GridSpec`` (``shard_blocks``), and one halo exchange rebuilds
the exteriors — so a (2,2,2)x8-device snapshot restores onto (1,2,4),
onto 4 devices with resident oversubscription, or onto a single device,
bit-identically (tests/test_ckpt.py pins all three).

Validation layers (cheap to deep):

- ``validate_manifest``: structural schema of the manifest dict;
- ``validate_snapshot``: files exist + byte counts (+ SHA-256 unless
  ``deep=False``) + the blocks exactly tile the recorded global grid;
- ``find_resume``: the auto-resume policy — try ``LATEST`` first, then
  every other snapshot newest-step-first, returning the first VALID one
  (a truncated/partial snapshot is skipped with a warning, falling back
  to the previous good manifest, never crashing the revival).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import logging as log
from .snapshot import (
    LATEST_NAME,
    MANIFEST_NAME,
    MANIFEST_VERSION,
    _sha256,
    _write_latest,
    list_snapshots,
    read_latest,
    step_of,
)

#: Prefix of quarantined snapshot dirs — ``list_snapshots``/``find_resume``
#: never look at them again (``step_of`` only parses ``step-`` names).
QUARANTINE_PREFIX = "quarantine-"


def load_manifest(snapshot_dir: str) -> dict:
    """Parse ``manifest.json`` (raises OSError/ValueError on a bad one)."""
    with open(os.path.join(snapshot_dir, MANIFEST_NAME)) as f:
        m = json.load(f)
    if not isinstance(m, dict):
        raise ValueError(f"manifest is not an object: {snapshot_dir}")
    return m


def validate_manifest(m: dict) -> List[str]:
    """Structural schema check; returns the list of violations."""
    errs: List[str] = []
    if not isinstance(m, dict):
        return ["manifest is not an object"]
    if m.get("v") != MANIFEST_VERSION:
        errs.append(f"unknown manifest version {m.get('v')!r}")
    if m.get("kind") != "stencil-ckpt":
        errs.append(f"unknown manifest kind {m.get('kind')!r}")
    if not isinstance(m.get("step"), int) or m.get("step", -1) < 0:
        errs.append("step must be a non-negative integer")
    for key in ("global", "partition"):
        v = m.get(key)
        if not (isinstance(v, dict)
                and all(isinstance(v.get(a), int) and v.get(a, 0) >= 1
                        for a in ("x", "y", "z"))):
            errs.append(f"{key} must map x/y/z to positive integers")
    qs = m.get("quantities")
    if not (isinstance(qs, list) and qs
            and all(isinstance(q, dict) and q.get("name") and q.get("dtype")
                    for q in qs)):
        errs.append("quantities must be a non-empty list of {name, dtype}")
    fs = m.get("files")
    if not (isinstance(fs, list) and fs):
        errs.append("files must be a non-empty list")
    else:
        for i, fe in enumerate(fs):
            if not (isinstance(fe, dict) and fe.get("path")
                    and isinstance(fe.get("bytes"), int)
                    and isinstance(fe.get("sha256"), str)
                    and isinstance(fe.get("block"), list)
                    and isinstance(fe.get("origin"), list)
                    and isinstance(fe.get("size"), list)):
                errs.append(f"files[{i}] missing path/bytes/sha256/block/"
                            "origin/size")
    return errs


def validate_snapshot(snapshot_dir: str, deep: bool = True) -> List[str]:
    """Full integrity check of one snapshot directory.

    Returns the list of problems (empty = valid): manifest schema, every
    payload present with the recorded byte count (and SHA-256 when
    ``deep``), and the blocks exactly tiling the recorded global grid.
    """
    try:
        m = load_manifest(snapshot_dir)
    except (OSError, ValueError) as e:
        return [f"unreadable manifest: {e}"]
    errs = validate_manifest(m)
    if errs:
        return errs
    g = m["global"]
    cover = np.zeros((g["z"], g["y"], g["x"]), dtype=np.uint8)
    for fe in m["files"]:
        path = os.path.join(snapshot_dir, fe["path"])
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            errs.append(f"missing payload {fe['path']}")
            continue
        if nbytes != fe["bytes"]:
            errs.append(
                f"payload {fe['path']} is {nbytes} bytes, manifest says "
                f"{fe['bytes']} (truncated?)"
            )
            continue
        if deep and _sha256(path) != fe["sha256"]:
            errs.append(f"payload {fe['path']} SHA-256 mismatch")
            continue
        o, s = fe["origin"], fe["size"]
        cover[o[2]:o[2] + s[2], o[1]:o[1] + s[1], o[0]:o[0] + s[0]] += 1
    if not errs:
        if cover.min() < 1:
            errs.append("blocks do not cover the global grid")
        if cover.max() > 1:
            errs.append("blocks overlap")
    return errs


def find_resume(
    ckpt_dir: str, deep: bool = True, accept=None
) -> Optional[Tuple[str, dict]]:
    """Locate the newest VALID snapshot — the auto-resume policy.

    Candidates are tried newest-step-first — NOT ``LATEST`` first: a
    crash between publishing a snapshot and moving the pointer leaves an
    intact step newer than ``LATEST``, and resuming from the pointer
    would silently discard it (``LATEST`` is the durability floor, not
    the ceiling). ``accept(manifest) -> list-of-problems`` (e.g.
    :func:`check_compatible` curried on the target domain) extends the
    fallback to snapshots that are intact but unusable HERE — a valid
    snapshot from a different domain shape must not shadow an older
    compatible one. Returns (snapshot_dir, manifest) or None when
    nothing usable exists.
    """
    if not os.path.isdir(ckpt_dir):
        return None
    candidates = list(reversed(list_snapshots(ckpt_dir)))
    latest = read_latest(ckpt_dir)
    if latest and latest not in candidates:
        log.warn(f"ckpt: {LATEST_NAME} names missing snapshot {latest}")
    for name in candidates:
        snap = os.path.join(ckpt_dir, name)
        errs = validate_snapshot(snap, deep=deep)
        if errs:
            log.warn(
                f"ckpt: skipping invalid snapshot {name}: {errs[0]}"
                + (f" (+{len(errs)-1} more)" if len(errs) > 1 else "")
            )
            continue
        manifest = load_manifest(snap)
        if accept is not None:
            errs = accept(manifest)
            if errs:
                log.warn(f"ckpt: skipping incompatible snapshot {name}: "
                         f"{errs[0]}")
                continue
        return snap, manifest
    return None


def quarantine_snapshot(ckpt_dir: str, name: str,
                        reason: str = "") -> Optional[str]:
    """Rename an invalid/poisoned snapshot aside (``quarantine-<name>-…``)
    so :func:`find_resume` stops re-validating — and re-warning about —
    it on every restart, while the bytes stay on disk as post-mortem
    evidence. If ``LATEST`` named the quarantined snapshot, the pointer
    is repointed at the newest remaining snapshot (or removed when none
    is left — ``LATEST`` must never dangle *because of us*).

    Returns the quarantine directory, or None when ``name`` does not
    exist under ``ckpt_dir``.
    """
    src = os.path.join(ckpt_dir, name)
    if not os.path.isdir(src):
        return None
    stamp = time.strftime("%Y%m%dT%H%M%S")
    dest = os.path.join(ckpt_dir, f"{QUARANTINE_PREFIX}{name}-{stamp}")
    n = 0
    while os.path.exists(dest):  # same-second double quarantine
        n += 1
        dest = os.path.join(
            ckpt_dir, f"{QUARANTINE_PREFIX}{name}-{stamp}-{n}")
    os.rename(src, dest)
    try:  # best-effort breadcrumb for the post-mortem reader
        with open(os.path.join(dest, "QUARANTINED.txt"), "w") as f:
            f.write(f"quarantined {time.strftime('%Y-%m-%dT%H:%M:%S')}: "
                    f"{reason or 'failed validation'}\n")
    except OSError:
        pass
    if read_latest(ckpt_dir) == name:
        remaining = list_snapshots(ckpt_dir)
        if remaining:
            _write_latest(ckpt_dir, remaining[-1])
        else:
            try:
                os.remove(os.path.join(ckpt_dir, LATEST_NAME))
            except OSError:
                pass
    log.warn(f"ckpt: quarantined snapshot {name} -> "
             f"{os.path.basename(dest)}"
             + (f" ({reason})" if reason else ""))
    from ..obs import telemetry  # lazy: keep ckpt_tool's import graph lean

    telemetry.get().counter("ckpt.quarantined", value=1, phase="ckpt",
                            snapshot=name, reason=reason or None)
    return dest


def assemble_global(
    snapshot_dir: str, manifest: dict, name: str, dtype=None
) -> np.ndarray:
    """Reassemble one quantity's global interior [z,y,x] from the saved
    blocks (pure numpy — usable without a jax backend)."""
    g = manifest["global"]
    want = {q["name"]: q["dtype"] for q in manifest["quantities"]}
    if name not in want:
        raise KeyError(
            f"quantity {name!r} not in snapshot (has {sorted(want)})"
        )
    out = np.empty((g["z"], g["y"], g["x"]),
                   dtype=dtype or np.dtype(want[name]))
    for fe in manifest["files"]:
        with np.load(os.path.join(snapshot_dir, fe["path"])) as z:
            block = z[name]
        o, s = fe["origin"], fe["size"]
        if block.shape != (s[2], s[1], s[0]):
            raise ValueError(
                f"payload {fe['path']}[{name}] shape {block.shape} != "
                f"manifest size {(s[2], s[1], s[0])}"
            )
        out[o[2]:o[2] + s[2], o[1]:o[1] + s[1], o[0]:o[0] + s[0]] = block
    return out


def check_compatible(manifest: dict, size, names, dtypes) -> List[str]:
    """Elasticity rules: what MUST match between snapshot and the target
    domain (everything else — partition, mesh, device count, radius,
    alignment — may differ). Returns the list of mismatches."""
    errs: List[str] = []
    g = manifest["global"]
    if (g["x"], g["y"], g["z"]) != (size.x, size.y, size.z):
        errs.append(
            f"global size mismatch: snapshot ({g['x']},{g['y']},{g['z']}) "
            f"vs domain ({size.x},{size.y},{size.z})"
        )
    have = {q["name"]: q["dtype"] for q in manifest["quantities"]}
    want = dict(zip(names, dtypes))
    if set(have) != set(want):
        errs.append(
            f"quantity set mismatch: snapshot {sorted(have)} vs domain "
            f"{sorted(want)}"
        )
    else:
        for n in sorted(want):
            if np.dtype(have[n]) != np.dtype(want[n]):
                errs.append(
                    f"dtype mismatch for {n!r}: snapshot {have[n]} vs "
                    f"domain {want[n]} (bit-exact restore requires equal "
                    "dtypes)"
                )
    return errs
