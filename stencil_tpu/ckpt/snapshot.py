"""Sharded, crash-safe snapshots of distributed grid state.

The write side of the elastic checkpoint/restart subsystem (restore.py is
the read side). Multi-level checkpoint/restart in the spirit of SCR
(Moody et al., SC'10) and the async sharded-manifest design of Orbax —
but *grid-shaped*: the durable unit is the per-block compute interior, so
a snapshot taken on one partition can be restored onto any other
(restore.py reassembles the global interior and re-splits it).

What one snapshot ``<ckpt_dir>/step-<k>/`` contains:

- ``block_z_y_x.npz`` per partition block: one array per quantity holding
  that block's compute interior (no halos, no alignment pad — halos are
  rebuilt by the halo exchange after restore, exactly like fresh state).
- ``manifest.json``: schema version, step, global/partition geometry,
  radius, quantity names + dtypes, and per-file byte counts + SHA-256 —
  the integrity authority ``ckpt_tool validate`` and auto-resume check.

Crash-safety discipline (the SCR/Orbax rename protocol):

1. payloads + manifest are written into ``<ckpt_dir>/.tmp-...`` and every
   file is fsync'd;
2. the tmp dir is atomically renamed to ``step-<k>`` and the parent
   directory fsync'd — a crash before this leaves only a ``.tmp-`` dir
   that restore ignores;
3. only then is the ``LATEST`` pointer replaced (tmp + atomic rename), so
   ``LATEST`` can never name a partial snapshot;
4. retention prunes the oldest snapshots beyond ``keep``, never the one
   ``LATEST`` names.

:class:`AsyncCheckpointer` double-buffers the write: the device_get
snapshot copy happens on the caller's thread (cheap, and it must — the
step loop donates its buffers), then hashing/serialization/fsync run on a
writer thread while the step loop keeps running. At most one write is in
flight; a second save drains the first (double buffering, not an
unbounded queue).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..utils import logging as log

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"
LATEST_NAME = "LATEST"
PAYLOAD_FORMAT = "npz-v1"
_TMP_PREFIX = ".tmp-"


def snapshot_name(step: int) -> str:
    return f"step-{step:08d}"


def step_of(name: str) -> Optional[int]:
    """Parse a snapshot dir name back to its step (None if not one)."""
    base = os.path.basename(os.path.normpath(name))
    if not base.startswith("step-"):
        return None
    try:
        return int(base[len("step-"):], 10)
    except ValueError:
        return None


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # e.g. a platform without O_RDONLY dirs; rename is still atomic
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _radius_dirs(radius) -> List[List[int]]:
    """Serialize a Radius as [[dx,dy,dz,r], ...] (saver-side record only —
    restore uses the *target* domain's radius)."""
    return [[d[0], d[1], d[2], r] for d, r in sorted(radius._r.items())]


def host_snapshot(spec, arrays: Dict[str, "object"]) -> Dict[str, np.ndarray]:
    """The device_get side of a save: fetch each stacked quantity to host
    memory. This is the "snapshot copy" handed to the writer thread — after
    it returns, the step loop may donate/overwrite the device buffers."""
    import jax

    return {name: np.asarray(jax.device_get(a)) for name, a in arrays.items()}


def write_snapshot(
    ckpt_dir: str,
    step: int,
    spec,
    host_state: Dict[str, np.ndarray],
    dtypes: Optional[Dict[str, str]] = None,
    keep: int = 3,
    extra_meta: Optional[dict] = None,
) -> str:
    """Write one durable snapshot; returns the final snapshot directory.

    ``host_state`` maps quantity name -> host copy of the stacked array
    (``(bz,by,bx,pz,py,px)``, see :func:`host_snapshot`). ``dtypes`` pins
    the manifest dtype per quantity (defaults to each array's dtype).
    """
    from ..obs import telemetry

    rec = telemetry.get()
    t0 = time.perf_counter()
    os.makedirs(ckpt_dir, exist_ok=True)
    final = os.path.join(ckpt_dir, snapshot_name(step))
    tmp = os.path.join(ckpt_dir, f"{_TMP_PREFIX}{snapshot_name(step)}-{os.getpid()}")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    off = spec.compute_offset()
    names = sorted(host_state)
    files = []
    total_bytes = 0
    for iz in range(spec.dim.z):
        for iy in range(spec.dim.y):
            for ix in range(spec.dim.x):
                o = spec.block_origin((ix, iy, iz))
                s = spec.block_size((ix, iy, iz))
                payload = {}
                for name in names:
                    arr = host_state[name]
                    payload[name] = np.ascontiguousarray(
                        arr[
                            iz, iy, ix,
                            off.z : off.z + s.z,
                            off.y : off.y + s.y,
                            off.x : off.x + s.x,
                        ]
                    )
                fname = f"block_{iz}_{iy}_{ix}.npz"
                fpath = os.path.join(tmp, fname)
                with open(fpath, "wb") as f:
                    np.savez(f, **payload)
                    f.flush()
                    os.fsync(f.fileno())
                nbytes = os.path.getsize(fpath)
                total_bytes += nbytes
                files.append(
                    {
                        "path": fname,
                        "bytes": nbytes,
                        "sha256": _sha256(fpath),
                        "block": [ix, iy, iz],
                        "origin": [o.x, o.y, o.z],
                        "size": [s.x, s.y, s.z],
                    }
                )

    g, d = spec.global_size, spec.dim
    manifest = {
        "v": MANIFEST_VERSION,
        "kind": "stencil-ckpt",
        "payload": PAYLOAD_FORMAT,
        "step": int(step),
        "written_t": time.time(),
        "global": {"x": g.x, "y": g.y, "z": g.z},
        "partition": {"x": d.x, "y": d.y, "z": d.z},
        "radius": _radius_dirs(spec.radius),
        "quantities": [
            {
                "name": name,
                "dtype": str((dtypes or {}).get(name, host_state[name].dtype)),
            }
            for name in names
        ],
        "files": files,
    }
    if extra_meta:
        manifest["meta"] = extra_meta
    mpath = os.path.join(tmp, MANIFEST_NAME)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())

    # atomic publish: rename the complete dir into place, then the pointer.
    # An existing snapshot of the same step is MOVED aside first (rename,
    # not rmtree): deleting it before the replacement lands would reopen
    # the exact crash window the rename protocol closes — a kill between
    # the two renames leaves the old state on disk (as an ignored .tmp-
    # dir) instead of losing the newest durable step outright.
    displaced = None
    if os.path.isdir(final):
        displaced = os.path.join(
            ckpt_dir, f"{_TMP_PREFIX}{snapshot_name(step)}-old-{os.getpid()}"
        )
        if os.path.isdir(displaced):
            shutil.rmtree(displaced)
        os.rename(final, displaced)
    os.rename(tmp, final)
    _fsync_dir(ckpt_dir)
    if displaced is not None:
        shutil.rmtree(displaced, ignore_errors=True)
    _write_latest(ckpt_dir, snapshot_name(step))
    prune(ckpt_dir, keep=keep)

    rec.emit("span", "ckpt.write", phase="ckpt",
             seconds=time.perf_counter() - t0, step=int(step))
    rec.counter("ckpt.bytes_written", bytes=total_bytes, phase="ckpt",
                step=int(step))
    rec.counter("ckpt.files_written", value=len(files), phase="ckpt",
                step=int(step))
    log.debug(f"checkpoint step {step}: {len(files)} files, "
              f"{total_bytes} bytes -> {final}")
    return final


def _write_latest(ckpt_dir: str, name: str) -> None:
    tmp = os.path.join(ckpt_dir, f"{_TMP_PREFIX}LATEST-{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(name + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(ckpt_dir, LATEST_NAME))
    _fsync_dir(ckpt_dir)


def read_latest(ckpt_dir: str) -> Optional[str]:
    """The snapshot name ``LATEST`` points at (None when absent/empty)."""
    try:
        with open(os.path.join(ckpt_dir, LATEST_NAME)) as f:
            name = f.read().strip()
    except OSError:
        return None
    return name or None


def list_snapshots(ckpt_dir: str) -> List[str]:
    """Snapshot dir names under ``ckpt_dir``, oldest step first. Tmp dirs
    (in-flight or crashed writes) are never listed."""
    try:
        entries = os.listdir(ckpt_dir)
    except OSError:
        return []
    out = [
        e for e in entries
        if step_of(e) is not None and os.path.isdir(os.path.join(ckpt_dir, e))
    ]
    return sorted(out, key=step_of)


def prune(ckpt_dir: str, keep: int) -> List[str]:
    """Delete the oldest snapshots beyond ``keep`` (``keep <= 0`` keeps
    everything); never the one LATEST names. Stale ``.tmp-`` leftovers
    from crashed writers (dirs AND files — the LATEST tmp is a file) are
    garbage-collected either way. Returns the removed snapshot names."""
    removed: List[str] = []
    if keep > 0:
        snaps = list_snapshots(ckpt_dir)
        latest = read_latest(ckpt_dir)
        excess = len(snaps) - keep
        for name in snaps:
            if excess <= 0:
                break
            if name == latest:
                continue
            shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)
            removed.append(name)
            excess -= 1
    for e in os.listdir(ckpt_dir) if os.path.isdir(ckpt_dir) else []:
        if e.startswith(_TMP_PREFIX):
            p = os.path.join(ckpt_dir, e)
            try:
                age = time.time() - os.stat(p).st_mtime
            except OSError:
                continue
            if age > 3600:  # only stale ones: a live writer owns recent tmps
                if os.path.isdir(p):
                    shutil.rmtree(p, ignore_errors=True)
                else:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
    return removed


class AsyncCheckpointer:
    """Double-buffered asynchronous snapshot writer.

    ``save(spec, arrays, step)`` fetches the device state to host on the
    caller's thread (the snapshot copy — after that the step loop may
    donate the buffers) and hands it to a writer thread. At most one write
    is in flight; a save issued while one is pending blocks until the
    previous write is durable (double buffering). ``flush()`` waits for
    the in-flight write; ``close()`` flushes and stops the thread.

    A failed write is logged + recorded as telemetry and re-raised from
    the *next* ``save``/``flush``/``close`` — checkpointing must never
    tear down the step loop mid-flight, but persistent failure must not
    stay silent either.
    """

    def __init__(self, ckpt_dir: str, keep: int = 3,
                 dtypes: Optional[Dict[str, str]] = None):
        self.ckpt_dir = ckpt_dir
        self.keep = keep
        self.dtypes = dict(dtypes or {})
        self._pending: Optional[tuple] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._idle = threading.Condition(self._lock)
        self._stop = False
        self.last_step: Optional[int] = None
        self._thread = threading.Thread(
            target=self._run, name="stencil-ckpt-writer", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while True:
            with self._lock:
                while self._pending is None and not self._stop:
                    self._work.wait()
                if self._pending is None and self._stop:
                    return
                spec, host_state, step, extra_meta = self._pending
            try:
                write_snapshot(
                    self.ckpt_dir, step, spec, host_state,
                    dtypes=self.dtypes, keep=self.keep,
                    extra_meta=extra_meta,
                )
                err = None
            except BaseException as e:  # surfaced on the next save/flush
                err = e
            with self._lock:
                if err is None:
                    self.last_step = step
                else:
                    self._error = err
                    log.warn(f"async checkpoint write failed: {err}")
                self._pending = None
                self._idle.notify_all()

    def _raise_pending_error(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def save(self, spec, arrays: Dict[str, "object"], step: int,
             extra_meta: Optional[dict] = None) -> None:
        """Snapshot ``arrays`` (name -> stacked device array) at ``step``.
        ``extra_meta`` lands under the manifest's ``meta`` key (e.g. the
        exchange-plan provenance resume checks)."""
        from ..obs import telemetry

        with telemetry.get().span("ckpt.save", phase="ckpt", step=int(step)):
            host_state = host_snapshot(spec, arrays)
            with self._lock:
                while self._pending is not None:
                    self._idle.wait()
                self._raise_pending_error()
                self._pending = (spec, host_state, step, extra_meta)
                self._work.notify()

    def flush(self) -> None:
        """Block until the in-flight write (if any) is durable."""
        with self._lock:
            while self._pending is not None:
                self._idle.wait()
            self._raise_pending_error()

    def close(self) -> None:
        with self._lock:
            while self._pending is not None:
                self._idle.wait()
            self._stop = True
            self._work.notify()
        self._thread.join(timeout=60)
        with self._lock:
            self._raise_pending_error()
