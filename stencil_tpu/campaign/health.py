"""Per-lane numerical health for batched tenant slots.

The single-domain :class:`~stencil_tpu.fault.health.HealthGuard` reduces
every quantity to ONE (all-finite, max|u|) pair — right for one domain,
wrong for a batch slot, where one tenant's NaN must never condemn its B-1
siblings. :class:`SlotHealthGuard` keeps the guard's contract (one fused
jitted reduction, one host round-trip per check, a ``health.check`` span,
zero step-loop HLO change) but reduces per LANE: each quantity yields
``(B,)`` finite flags and ``(B,)`` max magnitudes, and a failed check
raises :class:`TenantFault` naming the tenant, its lane, and its
tenant-relative step — what the campaign driver's eviction policy
dispatches on.

Dead lanes (padding when the queue drained, or a just-evicted slot
position) are excluded: their zeros are trivially healthy, and nothing
should ever be attributed to them.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..fault.health import DIVERGENCE, NONFINITE, HealthGuard, NumericalFault
from ..obs import telemetry


class TenantFault(NumericalFault):
    """A :class:`NumericalFault` attributed to one tenant lane.

    ``step`` (the base class field) is the SLOT step the failed check
    observed — what ``fault/recover.run_guarded`` keys its rollback
    budget on; ``tenant_step`` is the tenant-relative step (lanes
    backfilled mid-slot run offset from the slot clock)."""

    def __init__(self, kind: str, quantity: str, step: int, *, lane: int,
                 tenant: str, tenant_step: int,
                 value: Optional[float] = None):
        super().__init__(kind, quantity, step, value=value)
        self.lane = int(lane)
        self.tenant = str(tenant)
        self.tenant_step = int(tenant_step)


class SlotHealthGuard(HealthGuard):
    """Per-lane fused health check over ``{name: (B, ...)}`` slot state.

    ``bind(active_fn, tenant_step_fn)`` installs the driver's live lane
    view: ``active_fn(lane) -> tenant id | None`` and
    ``tenant_step_fn(lane, slot_step) -> tenant step``. The driver
    re-binds nothing on backfill — the callables read its mutable lane
    table."""

    def __init__(self, every: int = 1, max_abs: Optional[float] = None):
        super().__init__(every=every, max_abs=max_abs)
        self._active_fn: Callable[[int], Optional[str]] = lambda lane: None
        self._tstep_fn: Callable[[int, int], int] = lambda lane, step: step

    def bind(self, active_fn, tenant_step_fn) -> None:
        self._active_fn = active_fn
        self._tstep_fn = tenant_step_fn

    @staticmethod
    def _build(state):
        names = sorted(state)
        finite, amax = [], []
        for n in names:
            x = state[n]
            axes = tuple(range(1, x.ndim))
            if jnp.issubdtype(x.dtype, jnp.inexact):
                finite.append(jnp.isfinite(x).all(axis=axes))
                # f32 is enough for the ceiling verdict (HealthGuard._build)
                amax.append(
                    jnp.max(jnp.abs(x), axis=axes).astype(jnp.float32))
            else:  # integer quantities are trivially healthy
                finite.append(jnp.ones((x.shape[0],), bool))
                amax.append(jnp.zeros((x.shape[0],), jnp.float32))
        return jnp.stack(finite), jnp.stack(amax)

    def check(self, state, step: int) -> None:
        """Run the fused per-lane reduction; raise :class:`TenantFault`
        for the first unhealthy ACTIVE lane (lowest lane index — the
        deterministic order eviction evidence relies on)."""
        if not state:
            return
        rec = telemetry.get()
        self.checks += 1
        with rec.span("health.check", phase="health", step=int(step),
                      quantities=len(state)):
            finite, amax = self._reduce(dict(state))
            finite = np.asarray(jax.device_get(finite))
            amax = np.asarray(jax.device_get(amax))
        names = sorted(state)
        nlanes = finite.shape[1] if finite.ndim == 2 else 1
        for b in range(nlanes):
            tid = self._active_fn(b)
            if tid is None:
                continue  # dead/padding lane: nothing to attribute
            for i, name in enumerate(names):
                kind = None
                if not bool(finite[i, b]):
                    kind = NONFINITE
                elif (self.max_abs is not None
                      and float(amax[i, b]) > self.max_abs):
                    kind = DIVERGENCE
                if kind is None:
                    continue
                value = float(amax[i, b])
                tstep = int(self._tstep_fn(b, int(step)))
                rec.meta("health.fault", fault_kind=kind, quantity=name,
                         step=int(step),
                         value=value if math.isfinite(value) else None,
                         ceiling=self.max_abs, tenant=tid, lane=b,
                         tenant_step=tstep)
                raise TenantFault(
                    kind, name, int(step), lane=b, tenant=tid,
                    tenant_step=tstep,
                    value=value if math.isfinite(value) else None)
