"""Multi-tenant batched campaigns: one compiled program, thousands of
small domains.

Every other layer of this repo scales ONE big domain; production traffic
from many users is the inverse workload — floods of small-to-medium
*independent* simulations (ROADMAP #4). This driver serves that shape:

- **Queue -> slots.** Tenant jobs queue FIFO; the driver packs them into
  fixed-size batch slots of ``slot_size`` lanes, bucketed by shape
  (grid, dtype): a slot's compiled program depends only on the bucket,
  never on the tenants in it. When the queue drains below a full slot,
  the empty lanes are DEAD tenants (zeros — finite, never attributed).
- **Batched stepping.** A slot's state is one ``(B, pz, py, px)`` stacked
  array sharded over a 1-D device mesh on the batch axis
  (``ops/jacobi.make_batched_jacobi_loop``): each tenant is its own
  periodic box (halos self-wrap per tenant, never across the batch
  axis), the program has ZERO collectives, and one jit serves every
  same-shape slot through the :class:`~.compile_cache.CompileCache`
  (``compile.cache_hit`` / ``compile.build_s`` telemetry).
- **Guarded slots.** Each slot segment runs through
  ``fault/recover.run_guarded`` — the SAME engine the apps use — with a
  per-lane :class:`~.health.SlotHealthGuard` and an optional per-tenant
  :class:`~.inject.SlotInjector`. A transient fault rolls the whole slot
  back to the last health-checked stash (deterministic recompute keeps
  every lane bit-identical); a tenant that exhausts ``max_rollbacks``
  raises through as the rc-43 ``fault`` outcome and is EVICTED: its
  evidence bundle moves into its tenant dir, its last healthy state is
  written as a revivable snapshot, its lane is backfilled from the queue
  (or dies), and the surviving lanes resume from the stash — the slot
  never stalls, and survivors finish bit-identical to an uninjected
  campaign (tests/test_campaign.py, scripts/ci_campaign_gate.py).
- **Per-tenant durable state.** Every tenant owns a snapshot dir
  ``<campaign_dir>/tenants/<tid>`` (ckpt/ subsystem: crash-safe rename
  protocol, manifests, retention). ``ckpt_every`` > 0 checkpoints every
  active lane at the cadence; completion and eviction always persist a
  final/last-healthy snapshot, so evicted tenants are revivable
  (``resume=True`` packs a tenant from its newest valid snapshot).

The sequential baseline (:func:`run_sequential`) serves the same jobs
one tenant at a time through the standard ``DistributedDomain`` +
``make_jacobi_loop`` machinery on the same devices — the A/B behind the
tracked ``campaign_batched_over_sequential`` bench leg (aggregate
Mcells/s and p50/p99 per-tenant step latency, utils/statistics
percentiles).
"""

from __future__ import annotations

import os
import shutil
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..ckpt import assemble_global, check_compatible, find_resume, write_snapshot
from ..domain.grid import GridSpec
from ..fault import RecoveryExhausted, RecoveryPolicy, chunk_plan, run_guarded
from ..fault.inject import FaultPlan
from ..geometry import Dim3, Radius
from ..obs import telemetry
from ..obs.watchdog import FAULT_RC
from ..ops.jacobi import INIT_TEMP, make_batched_jacobi_loop, sphere_sel
from ..utils import logging as log
from ..utils.statistics import percentile
from ..utils.sync import hard_sync
from .compile_cache import CompileCache, cache_key
from .health import SlotHealthGuard, TenantFault
from .inject import SlotInjector

QUANTITY = "temperature"


@dataclass
class TenantJob:
    """One queued simulation: an independent periodic jacobi box."""

    tid: str
    size: Tuple[int, int, int]      # (x, y, z)
    steps: int
    dtype: str = "float32"
    seed: int = 0

    def bucket(self) -> Tuple[Tuple[int, int, int], str]:
        """The shape bucket: jobs in one slot must share it (the compiled
        program and the compile-cache key depend on nothing else)."""
        return (tuple(int(v) for v in self.size), str(self.dtype))


@dataclass
class TenantResult:
    tid: str
    outcome: str                    # "done" | "fault"
    steps: int                      # tenant steps completed
    snapshot_dir: str
    evidence: Optional[str] = None
    final: Optional[np.ndarray] = None   # global [z,y,x] interior ("done")


@dataclass
class Lane:
    """One slot position: which tenant occupies it and the step anchors
    mapping the slot clock to the tenant clock (backfilled lanes run
    offset from the slot's step counter)."""

    idx: int
    tenant: Optional[TenantJob] = None
    start_slot_step: int = 0
    start_tenant_step: int = 0

    def tenant_step(self, slot_step: int) -> int:
        return self.start_tenant_step + (slot_step - self.start_slot_step)

    def end_slot_step(self) -> int:
        assert self.tenant is not None
        return self.start_slot_step + (self.tenant.steps
                                       - self.start_tenant_step)


def tenant_init_field(job: TenantJob) -> np.ndarray:
    """The ONE authority for a tenant's initial temperature field
    (``[z, y, x]``): the jacobi lukewarm baseline plus a seeded
    perturbation so tenants are distinguishable — the driver, the
    sequential baseline, revival, and the parity tests all regenerate a
    tenant's step-0 state from this."""
    x, y, z = job.size
    rng = np.random.RandomState(job.seed & 0x7FFFFFFF)
    f = INIT_TEMP + 0.05 * rng.standard_normal((z, y, x))
    return f.astype(job.dtype)


def pick_slot(queue: deque,
              slot_size: int) -> Tuple[Tuple, List[TenantJob], deque]:
    """Pop the next slot's jobs: the queue head's bucket, same-bucket
    jobs pulled forward FIFO until the slot fills. Returns ``(bucket,
    picked, remaining-queue)`` — the ONE packing policy, shared by the
    driver and the :func:`plan_slots` preview."""
    bucket = queue[0].bucket()
    picked: List[TenantJob] = []
    rest: List[TenantJob] = []
    for j in queue:
        if j.bucket() == bucket and len(picked) < slot_size:
            picked.append(j)
        else:
            rest.append(j)
    return bucket, picked, deque(rest)


def plan_slots(jobs: Sequence[TenantJob],
               slot_size: int) -> List[Tuple[Tuple, List[str]]]:
    """Deterministic packing preview: ``[(bucket, [tids...]), ...]`` in
    the order the driver forms slots (:func:`pick_slot`). Pure (no
    devices, no state): the packing-determinism pin of
    tests/test_campaign.py."""
    queue = deque(jobs)
    out: List[Tuple[Tuple, List[str]]] = []
    while queue:
        bucket, picked, queue = pick_slot(queue, slot_size)
        out.append((bucket, [j.tid for j in picked]))
    return out


def batch_devices(slot_size: int, devices: Sequence) -> List:
    """The largest device prefix that divides the batch axis evenly."""
    for n in range(min(slot_size, len(devices)), 0, -1):
        if slot_size % n == 0:
            return list(devices[:n])
    return list(devices[:1])


class CampaignDriver:
    """Serve a queue of tenant jobs through fixed-size batch slots."""

    def __init__(
        self,
        jobs: Sequence[TenantJob],
        slot_size: int,
        campaign_dir: str,
        *,
        devices: Optional[Sequence] = None,
        radius: int = 1,
        chunk: int = 2,
        ckpt_every: int = 0,
        ckpt_keep: int = 3,
        health_every: int = 0,
        max_abs: Optional[float] = None,
        max_rollbacks: int = 2,
        rollback_backoff: float = 0.05,
        inject: Optional[str] = None,
        inject_seed: Optional[int] = None,
        resume: bool = False,
        cache: Optional[CompileCache] = None,
        use_pallas: bool = False,
    ):
        assert slot_size >= 1
        tids = [j.tid for j in jobs]
        assert len(set(tids)) == len(tids), "tenant ids must be unique"
        self.jobs = list(jobs)
        self.slot_size = int(slot_size)
        self.campaign_dir = campaign_dir
        self.devices = (list(devices) if devices is not None
                        else jax.devices())
        self.radius = int(radius)
        self.chunk = max(1, int(chunk))
        self.ckpt_every = int(ckpt_every)
        self.ckpt_keep = int(ckpt_keep)
        self.health_every = int(health_every) or self.chunk
        self.max_abs = max_abs
        self.policy = RecoveryPolicy(max_rollbacks=max_rollbacks,
                                     backoff_s=rollback_backoff)
        self.inject_spec = inject or None
        self.inject_seed = inject_seed
        self.resume = bool(resume)
        self.cache = cache if cache is not None else CompileCache()
        self.use_pallas = bool(use_pallas)

    # -- per-tenant durable state ---------------------------------------------
    def tenant_dir(self, tid: str) -> str:
        return os.path.join(self.campaign_dir, "tenants", tid)

    def _write_tenant_snapshot(self, job: TenantJob, spec: GridSpec,
                               lane_state: np.ndarray, step: int) -> None:
        p = spec.padded()
        arr6 = np.ascontiguousarray(
            lane_state.reshape(1, 1, 1, p.z, p.y, p.x))
        write_snapshot(self.tenant_dir(job.tid), step, spec,
                       {QUANTITY: arr6}, dtypes={QUANTITY: job.dtype},
                       keep=self.ckpt_keep)

    def _resume_tenant(self, job: TenantJob) -> Optional[Tuple[int, np.ndarray]]:
        """The newest valid compatible snapshot of a revived tenant:
        ``(tenant_step, global [z,y,x])`` or None (fresh start)."""
        if not self.resume:
            return None
        x, y, z = job.size
        found = find_resume(
            self.tenant_dir(job.tid),
            accept=lambda m: check_compatible(
                m, Dim3(x, y, z), [QUANTITY], [job.dtype]),
        )
        if found is None:
            return None
        snap, manifest = found
        g = assemble_global(snap, manifest, QUANTITY, dtype=job.dtype)
        log.info(f"campaign: revived tenant {job.tid} from step "
                 f"{manifest['step']} ({snap})")
        return int(manifest["step"]), g

    # -- compiled programs ----------------------------------------------------
    def _loop(self, spec: GridSpec, bucket, iters: int, sharding,
              sel_sharding, devs: Sequence):
        from ..plan.ir import PlanConfig

        (size, dtype) = bucket
        cfg = PlanConfig.make(Dim3(*size), spec.radius, [dtype], len(devs),
                              self.devices[0].platform)
        # device IDENTITY joins the key, not just the count: the jitted
        # loop's in_shardings pin a concrete mesh, and a shared cache
        # serving two drivers on disjoint same-sized device sets must
        # never hand one the other's program
        key = cache_key(cfg, workload="jacobi-batched",
                        batch=self.slot_size, iters=int(iters),
                        pallas=self.use_pallas,
                        devices=[d.id for d in devs])
        return self.cache.get(key, lambda: make_batched_jacobi_loop(
            spec, iters, sharding=sharding, sel_sharding=sel_sharding,
            use_pallas=self.use_pallas,
            batch=self.slot_size if self.use_pallas else None))

    # -- the campaign ---------------------------------------------------------
    def run(self) -> dict:
        rec = telemetry.get()
        os.makedirs(self.campaign_dir, exist_ok=True)
        queue = deque(self.jobs)
        results: Dict[str, TenantResult] = {}
        lat: List[float] = []        # per-chunk per-step wall samples
        cell_steps = 0
        wall = 0.0
        slot_idx = 0
        t0 = time.perf_counter()
        while queue:
            bucket, picked, queue = pick_slot(queue, self.slot_size)
            stats = self._run_slot(slot_idx, bucket, picked, queue, results)
            lat.extend(stats["latency_samples"])
            cell_steps += stats["cell_steps"]
            wall += stats["wall_s"]
            slot_idx += 1
        agg = cell_steps / wall / 1e6 if wall > 0 else 0.0
        summary = {
            "results": results,
            "tenants": len(self.jobs),
            "slots": slot_idx,
            "cell_steps": cell_steps,
            "step_wall_s": wall,
            "total_wall_s": time.perf_counter() - t0,
            "aggregate_mcells_per_s": agg,
            "p50_step_s": percentile(lat, 50) if lat else float("nan"),
            "p99_step_s": percentile(lat, 99) if lat else float("nan"),
            "evicted": sorted(t for t, r in results.items()
                              if r.outcome == "fault"),
            "cache": self.cache.stats(),
        }
        rec.meta("campaign.summary", slots=slot_idx,
                 tenants=len(self.jobs), evicted=len(summary["evicted"]),
                 cache_hits=self.cache.hits, cache_misses=self.cache.misses)
        return summary

    def _run_slot(self, slot_idx: int, bucket, initial: List[TenantJob],
                  queue: deque, results: Dict[str, TenantResult]) -> dict:
        rec = telemetry.get()
        (size, dtype) = bucket
        x, y, z = size
        cells = x * y * z
        spec = GridSpec(Dim3(x, y, z), Dim3(1, 1, 1),
                        Radius.constant(self.radius),
                        aligned=self.use_pallas)
        p = spec.padded()
        off = spec.compute_offset()
        B = self.slot_size
        devs = batch_devices(B, self.devices)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(devs), ("b",))
        sh = NamedSharding(mesh, P("b"))
        shr = NamedSharding(mesh, P())

        # sel: the standard hot/cold spheres, shared across lanes (every
        # tenant of one bucket sees the same geometry); the Pallas path
        # wants the per-tenant stacked layout its kernel indexes
        sel_np = np.zeros((p.z, p.y, p.x), np.int32)
        sel_np[off.z:off.z + z, off.y:off.y + y, off.x:off.x + x] = (
            sphere_sel((x, y, z)))
        if self.use_pallas:
            sel = jax.device_put(
                jnp.asarray(np.broadcast_to(sel_np, (B,) + sel_np.shape)
                            .copy()), sh)
            sel_sh = sh
        else:
            sel = jax.device_put(jnp.asarray(sel_np), shr)
            sel_sh = shr

        lanes = [Lane(i) for i in range(B)]

        def lane_init(job: TenantJob) -> Tuple[int, np.ndarray]:
            revived = self._resume_tenant(job)
            t0_step, g = revived if revived is not None else (
                0, tenant_init_field(job))
            padded = np.zeros((p.z, p.y, p.x), dtype)
            padded[off.z:off.z + z, off.y:off.y + y, off.x:off.x + x] = g
            return t0_step, padded

        curr_np = np.zeros((B, p.z, p.y, p.x), dtype)
        for i, job in enumerate(initial):
            t0_step, padded = lane_init(job)
            if t0_step >= job.steps:
                # revived past its target: report done, leave the lane to
                # a later backfill pass
                g = padded[off.z:off.z + z, off.y:off.y + y, off.x:off.x + x]
                results[job.tid] = TenantResult(
                    job.tid, "done", job.steps, self.tenant_dir(job.tid),
                    final=np.ascontiguousarray(g))
                continue
            lanes[i].tenant = job
            lanes[i].start_slot_step = 0
            lanes[i].start_tenant_step = t0_step
            curr_np[i] = padded
        curr = jax.device_put(jnp.asarray(curr_np), sh)
        nxt0 = jax.device_put(jnp.zeros_like(curr), sh)
        del curr_np

        guard = SlotHealthGuard(every=self.health_every, max_abs=self.max_abs)
        guard.bind(
            lambda lane: (lanes[lane].tenant.tid
                          if lanes[lane].tenant is not None else None),
            lambda lane, step: lanes[lane].tenant_step(step),
        )
        injector = None
        if self.inject_spec:
            plan = FaultPlan.from_spec(self.inject_spec,
                                       seed=self.inject_seed)
            if plan is not None:
                injector = SlotInjector(plan, spec, lambda: lanes,
                                        known_tenants=[j.tid
                                                       for j in self.jobs])
        rec.meta("campaign.slot", slot=slot_idx,
                 tenants=[l.tenant.tid for l in lanes if l.tenant],
                 bucket={"size": list(size), "dtype": dtype},
                 devices=len(devs))

        def backfill(lane: Lane, slot_step: int, state_arr):
            """Replace a retired/evicted lane from the queue (same bucket
            only) or mark it dead (zeros)."""
            job = None
            for cand in list(queue):
                if cand.bucket() == bucket:
                    job = cand
                    queue.remove(cand)
                    break
            if job is None:
                lane.tenant = None
                return state_arr.at[lane.idx].set(
                    jnp.zeros((p.z, p.y, p.x), dtype))
            t0_step, padded = lane_init(job)
            if t0_step >= job.steps:
                g = padded[off.z:off.z + z, off.y:off.y + y,
                           off.x:off.x + x]
                results[job.tid] = TenantResult(
                    job.tid, "done", job.steps, self.tenant_dir(job.tid),
                    final=np.ascontiguousarray(g))
                return backfill(lane, slot_step, state_arr)
            lane.tenant = job
            lane.start_slot_step = slot_step
            lane.start_tenant_step = t0_step
            rec.meta("campaign.backfill", tenant=job.tid, lane=lane.idx,
                     slot=slot_idx, slot_step=int(slot_step))
            return state_arr.at[lane.idx].set(jnp.asarray(padded))

        # -- the guarded slot loop -------------------------------------------
        slot_step = 0
        stash: Tuple[int, dict] = (0, {QUANTITY: curr})
        lat: List[float] = []
        cell_steps = 0
        wall = 0.0

        def step_fn(st, k):
            loop = self._loop(spec, bucket, k, sh, sel_sh, devs)
            c, _scratch = loop(st[QUANTITY], nxt0, sel)
            hard_sync(c)
            return {QUANTITY: c}

        def on_chunk(st, k, per, done_now):
            nonlocal cell_steps, wall
            n_active = sum(1 for l in lanes if l.tenant is not None)
            lat.append(per)
            cell_steps += k * n_active * cells
            wall += per * k
            rec.gauge("campaign.step_latency_s", per, phase="step",
                      unit="s", mode="batched", slot=slot_idx, iters=k)

        def save_fn(s, st):
            nonlocal stash
            stash = (s, dict(st))
            host = np.asarray(jax.device_get(st[QUANTITY]))
            for l in lanes:
                if l.tenant is None:
                    continue
                self._write_tenant_snapshot(l.tenant, spec, host[l.idx],
                                            l.tenant_step(s))

        def restore_fn():
            s, st = stash
            return s, dict(st)

        while any(l.tenant is not None for l in lanes):
            end = min(l.end_slot_step() for l in lanes
                      if l.tenant is not None)
            state = {QUANTITY: curr}
            stash = (slot_step, dict(state))

            def plan_fn(s):
                return chunk_plan(
                    s, end, self.chunk,
                    every=(self.ckpt_every, guard.every),
                    at=injector.steps() if injector is not None else (),
                )

            try:
                state, done = run_guarded(
                    state, start=slot_step, iters=end, plan_fn=plan_fn,
                    step_fn=step_fn, guard=guard, injector=injector,
                    policy=self.policy,
                    save_fn=save_fn if self.ckpt_every > 0 else None,
                    ckpt_every=self.ckpt_every, restore_fn=restore_fn,
                    on_chunk=on_chunk, spec=None,
                    ckpt_dir=self.campaign_dir,
                    evidence_dir=self.campaign_dir, app="campaign",
                )
            except RecoveryExhausted as e:
                curr = self._evict(e, spec, lanes, stash, backfill,
                                   results, slot_idx)
                slot_step = stash[0]
                continue
            slot_step = done
            curr = state[QUANTITY]
            # segment end passed a health check (run_guarded checks at
            # done >= iters): retire every lane whose tenant is complete
            host = np.asarray(jax.device_get(curr))
            for l in lanes:
                if l.tenant is None:
                    continue
                if l.tenant_step(slot_step) < l.tenant.steps:
                    continue
                job = l.tenant
                g = host[l.idx, off.z:off.z + z, off.y:off.y + y,
                         off.x:off.x + x]
                self._write_tenant_snapshot(job, spec, host[l.idx],
                                            job.steps)
                results[job.tid] = TenantResult(
                    job.tid, "done", job.steps, self.tenant_dir(job.tid),
                    final=np.ascontiguousarray(g))
                rec.meta("campaign.retire", tenant=job.tid,
                         step=int(job.steps), lane=l.idx, slot=slot_idx)
                curr = backfill(l, slot_step, curr)

        return {"latency_samples": lat, "cell_steps": cell_steps,
                "wall_s": wall}

    def _evict(self, e: RecoveryExhausted, spec: GridSpec,
               lanes: List[Lane], stash, backfill, results,
               slot_idx: int):
        """The rc-43 eviction path: evidence moves to the tenant dir, the
        tenant's last healthy state becomes a revivable snapshot, the
        lane is backfilled, and the slot resumes from the stash."""
        rec = telemetry.get()
        f = e.fault
        if not isinstance(f, TenantFault):
            raise e  # unattributable: nothing sane to evict
        lane = lanes[f.lane]
        if lane.tenant is None or lane.tenant.tid != f.tenant:
            raise e  # the lane moved under us: refuse to evict blindly
        job = lane.tenant
        tdir = self.tenant_dir(job.tid)
        os.makedirs(tdir, exist_ok=True)
        evidence = None
        if e.evidence_path and os.path.isfile(e.evidence_path):
            evidence = os.path.join(tdir, "fault-evidence.json")
            shutil.move(e.evidence_path, evidence)
        sstep, sstate = stash
        host = np.asarray(jax.device_get(sstate[QUANTITY]))
        healthy_tstep = lane.tenant_step(sstep)
        # revivable: persist the last health-checked state BEFORE the
        # lane is overwritten by the backfill
        self._write_tenant_snapshot(job, spec, host[lane.idx],
                                    healthy_tstep)
        results[job.tid] = TenantResult(
            job.tid, "fault", healthy_tstep, tdir, evidence=evidence)
        rec.meta("campaign.evict", tenant=job.tid,
                 step=int(f.tenant_step), lane=lane.idx, slot=slot_idx,
                 rc=FAULT_RC, healthy_step=int(healthy_tstep),
                 evidence=evidence)
        log.warn(f"campaign: evicted tenant {job.tid} (lane {lane.idx}) "
                 f"after {e.rollbacks} rollback(s) at tenant step "
                 f"{f.tenant_step}; slot resumes from step {sstep}")
        return backfill(lane, sstep, sstate[QUANTITY])


# -- the sequential baseline ---------------------------------------------------


def run_sequential(jobs: Sequence[TenantJob], *,
                   devices: Optional[Sequence] = None, radius: int = 1,
                   chunk: int = 2,
                   cache: Optional[CompileCache] = None) -> dict:
    """Serve the same jobs one tenant at a time through the standard
    single-domain machinery (``DistributedDomain`` partitioned over ALL
    the given devices + ``make_jacobi_loop``): the honest baseline of
    ``campaign_batched_over_sequential``. One domain + compiled loop is
    reused per shape bucket (sequential serving amortizes compiles too —
    the ratio measures batching, not compilation); timing covers the
    stepping loop, and per-chunk per-step latencies feed the same
    p50/p99 statistics as the batched driver."""
    from ..api import DistributedDomain
    from ..ops.jacobi import make_jacobi_loop
    from ..parallel.exchange import shard_blocks
    from ..plan.ir import PlanConfig

    devices = list(devices) if devices is not None else jax.devices()
    cache = cache if cache is not None else CompileCache()
    rec = telemetry.get()
    results: Dict[str, TenantResult] = {}
    lat: List[float] = []
    cell_steps = 0
    wall = 0.0
    t0 = time.perf_counter()

    by_bucket: Dict[Tuple, List[TenantJob]] = {}
    order: List[Tuple] = []
    for j in jobs:
        b = j.bucket()
        if b not in by_bucket:
            by_bucket[b] = []
            order.append(b)
        by_bucket[b].append(j)

    for bucket in order:
        (size, dtype) = bucket
        x, y, z = size
        cells = x * y * z
        dd = DistributedDomain(x, y, z)
        dd.set_radius(radius)
        dd.set_devices(devices)
        h = dd.add_data(QUANTITY, dtype)
        dd.realize()
        sel = shard_blocks(sphere_sel((x, y, z)), dd.spec, dd.mesh)
        shape = dd.spec.stacked_shape_zyx()
        cfg = PlanConfig.make(Dim3(x, y, z), dd.spec.radius, [dtype],
                              len(devices), devices[0].platform)

        def loop_for(k):
            key = cache_key(cfg, workload="jacobi-sequential",
                            iters=int(k),
                            partition=[dd.spec.dim.x, dd.spec.dim.y,
                                       dd.spec.dim.z],
                            devices=[d.id for d in devices])
            return cache.get(
                key, lambda: make_jacobi_loop(dd.halo_exchange, k))

        for job in by_bucket[bucket]:
            dd.set_curr_global(h, tenant_init_field(job))
            c = dd.get_curr(h)
            n2 = jax.device_put(jnp.zeros(shape, dtype), dd.sharding())
            done = 0
            for k in chunk_plan(0, job.steps, chunk):
                loop = loop_for(k)
                t1 = time.perf_counter()
                c, n2 = loop(c, n2, sel)
                hard_sync(c)
                per = (time.perf_counter() - t1) / k
                done += k
                lat.append(per)
                cell_steps += k * cells
                wall += per * k
                rec.gauge("campaign.step_latency_s", per, phase="step",
                          unit="s", mode="sequential", iters=k)
            dd.set_curr(h, c)
            results[job.tid] = TenantResult(
                job.tid, "done", done, "",
                final=np.ascontiguousarray(dd.get_curr_global(h)))

    agg = cell_steps / wall / 1e6 if wall > 0 else 0.0
    return {
        "results": results,
        "tenants": len(jobs),
        "slots": 0,
        "cell_steps": cell_steps,
        "step_wall_s": wall,
        "total_wall_s": time.perf_counter() - t0,
        "aggregate_mcells_per_s": agg,
        "p50_step_s": percentile(lat, 50) if lat else float("nan"),
        "p99_step_s": percentile(lat, 99) if lat else float("nan"),
        "evicted": [],
        "cache": cache.stats(),
    }
