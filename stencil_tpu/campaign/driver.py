"""Multi-tenant batched campaigns: one compiled program, thousands of
small domains.

Every other layer of this repo scales ONE big domain; production traffic
from many users is the inverse workload — floods of small-to-medium
*independent* simulations (ROADMAP #4). This driver serves that shape:

- **Queue -> slots.** Tenant jobs queue FIFO; the driver packs them into
  fixed-size batch slots of ``slot_size`` lanes, bucketed by shape
  (grid, dtype): a slot's compiled program depends only on the bucket,
  never on the tenants in it. When the queue drains below a full slot,
  the empty lanes are DEAD tenants (zeros — finite, never attributed).
- **Batched stepping.** A slot's state is one ``(B, pz, py, px)`` stacked
  array sharded over a 1-D device mesh on the batch axis
  (``ops/jacobi.make_batched_jacobi_loop``): each tenant is its own
  periodic box (halos self-wrap per tenant, never across the batch
  axis), the program has ZERO collectives, and one jit serves every
  same-shape slot through the :class:`~.compile_cache.CompileCache`
  (``compile.cache_hit`` / ``compile.build_s`` telemetry).
- **Guarded slots.** Each slot segment runs through
  ``fault/recover.run_guarded`` — the SAME engine the apps use — with a
  per-lane :class:`~.health.SlotHealthGuard` and an optional per-tenant
  :class:`~.inject.SlotInjector`. A transient fault rolls the whole slot
  back to the last health-checked stash (deterministic recompute keeps
  every lane bit-identical); a tenant that exhausts ``max_rollbacks``
  raises through as the rc-43 ``fault`` outcome and is EVICTED: its
  evidence bundle moves into its tenant dir, its last healthy state is
  written as a revivable snapshot, its lane is backfilled from the queue
  (or dies), and the surviving lanes resume from the stash — the slot
  never stalls, and survivors finish bit-identical to an uninjected
  campaign (tests/test_campaign.py, scripts/ci_campaign_gate.py).
- **Per-tenant durable state.** Every tenant owns a snapshot dir
  ``<campaign_dir>/tenants/<tid>`` (ckpt/ subsystem: crash-safe rename
  protocol, manifests, retention). ``ckpt_every`` > 0 checkpoints every
  active lane at the cadence; completion and eviction always persist a
  final/last-healthy snapshot, so evicted tenants are revivable
  (``resume=True`` packs a tenant from its newest valid snapshot).

The sequential baseline (:func:`run_sequential`) serves the same jobs
one tenant at a time through the standard ``DistributedDomain`` +
``make_jacobi_loop`` machinery on the same devices — the A/B behind the
tracked ``campaign_batched_over_sequential`` bench leg (aggregate
Mcells/s and p50/p99 per-tenant step latency, utils/statistics
percentiles).
"""

from __future__ import annotations

import os
import shutil
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..ckpt import assemble_global, check_compatible, find_resume, write_snapshot
from ..domain.grid import GridSpec
from ..fault import RecoveryExhausted, RecoveryPolicy, chunk_plan, run_guarded
from ..fault.inject import FaultPlan
from ..geometry import Dim3, Radius
from ..obs import telemetry
from ..obs.watchdog import FAULT_RC
from ..ops.jacobi import INIT_TEMP, make_batched_jacobi_loop, sphere_sel
from ..utils import logging as log
from ..utils.statistics import percentile
from ..utils.sync import hard_sync
from .compile_cache import CompileCache, cache_key
from .health import SlotHealthGuard, TenantFault
from .inject import SlotInjector

QUANTITY = "temperature"


@dataclass
class TenantJob:
    """One queued simulation: an independent periodic box of one
    workload — ``"jacobi"`` (single-quantity heat) or ``"astaroth"``
    (8-field MHD through ``make_batched_astaroth_step``)."""

    tid: str
    size: Tuple[int, int, int]      # (x, y, z)
    steps: int
    dtype: str = "float32"
    seed: int = 0
    workload: str = "jacobi"
    # Optional per-step latency SLO (milliseconds): while the tenant's
    # lane is live, its ONLINE p99 step latency is tracked against this
    # deadline and a breach emits one `slo.violation` record (the
    # SLO-aware scheduling of ROADMAP #4 consumes these; here the
    # tracking + evidence land). Never joins the bucket — a deadline is
    # a contract, not a shape.
    deadline_ms: Optional[float] = None

    def bucket(self) -> Tuple[Tuple[int, int, int], str, str]:
        """The shape bucket: jobs in one slot must share it (the compiled
        program and the compile-cache key depend on nothing else).
        Workload joins the bucket — a slot's program is the workload's."""
        return (tuple(int(v) for v in self.size), str(self.dtype),
                str(self.workload))


@dataclass
class TenantResult:
    tid: str
    outcome: str                    # "done" | "fault"
    steps: int                      # tenant steps completed
    snapshot_dir: str
    evidence: Optional[str] = None
    final: Optional[np.ndarray] = None   # global [z,y,x] interior ("done",
    #                                      the workload's FIRST quantity)
    finals: Optional[Dict[str, np.ndarray]] = None  # every quantity ("done")


@dataclass
class Lane:
    """One slot position: which tenant occupies it and the step anchors
    mapping the slot clock to the tenant clock (backfilled lanes run
    offset from the slot's step counter)."""

    idx: int
    tenant: Optional[TenantJob] = None
    start_slot_step: int = 0
    start_tenant_step: int = 0

    def tenant_step(self, slot_step: int) -> int:
        return self.start_tenant_step + (slot_step - self.start_slot_step)

    def end_slot_step(self) -> int:
        if self.tenant is None:
            raise RuntimeError("end_slot_step on an empty (dead) lane")
        return self.start_slot_step + (self.tenant.steps
                                       - self.start_tenant_step)


def tenant_init_field(job: TenantJob) -> np.ndarray:
    """The ONE authority for a tenant's initial temperature field
    (``[z, y, x]``): the jacobi lukewarm baseline plus a seeded
    perturbation so tenants are distinguishable — the driver, the
    sequential baseline, revival, and the parity tests all regenerate a
    tenant's step-0 state from this."""
    x, y, z = job.size
    rng = np.random.RandomState(job.seed & 0x7FFFFFFF)
    f = INIT_TEMP + 0.05 * rng.standard_normal((z, y, x))
    return f.astype(job.dtype)


def astaroth_init_state(job: TenantJob) -> Dict[str, np.ndarray]:
    """The one authority for an astaroth tenant's step-0 fields: small
    seeded perturbations per field, lnrho offset to a positive density —
    the same fixture shape the batched-step parity suite uses. Any code
    path (driver, revival, parity tests) regenerates a tenant from
    this."""
    from ..astaroth.integrate import FIELDS

    x, y, z = job.size
    rng = np.random.RandomState((job.seed ^ 0x5A57A407) & 0x7FFFFFFF)
    state = {}
    for k in FIELDS:
        f = rng.standard_normal((z, y, x)) * 0.05
        if k == "lnrho":
            f = f + 0.5
        state[k] = f.astype(job.dtype)
    return state


class _JacobiWorkload:
    """The original campaign workload: single-quantity periodic heat."""

    name = "jacobi"
    default_radius = 1
    needs_sel = True

    def quantity_names(self, job_dtype: str):
        return [QUANTITY]

    def init_state(self, job: TenantJob) -> Dict[str, np.ndarray]:
        return {QUANTITY: tenant_init_field(job)}

    def build_loop(self, spec, iters: int, sharding, sel_sharding,
                   batch: int, use_pallas: bool):
        return make_batched_jacobi_loop(
            spec, iters, sharding=sharding, sel_sharding=sel_sharding,
            use_pallas=use_pallas, batch=batch if use_pallas else None)

    def step(self, loop, state: Dict, scratch: Dict, sel) -> Dict:
        c, _scratch = loop(state[QUANTITY], scratch[QUANTITY], sel)
        return {QUANTITY: c}


class _AstarothWorkload:
    """8-field MHD tenants through ``make_batched_astaroth_step`` —
    the ROADMAP #4 follow-up: the batched astaroth step existed (PR 9);
    this routes whole astaroth campaigns through the same queue/slot/
    guard/evict machinery the jacobi tenants use. No sel (no sphere
    sources), radius 3 (6th-order cross stencils), one reference
    swap-per-iteration RK3 step per slot step."""

    name = "astaroth"
    default_radius = 3
    needs_sel = False
    dt = 1e-8

    def quantity_names(self, job_dtype: str):
        from ..astaroth.integrate import FIELDS

        return list(FIELDS)

    def init_state(self, job: TenantJob) -> Dict[str, np.ndarray]:
        return astaroth_init_state(job)

    def _info(self, spec):
        from ..astaroth import config as ac_config

        info = ac_config.AcMeshInfo()
        conf = os.path.join(os.path.dirname(__file__), "..", "astaroth",
                            "astaroth.conf")
        with open(conf) as f:
            ac_config.parse_config(f.read(), info)
        b = spec.base
        info.int_params["AC_nx"] = int(b.x)
        info.int_params["AC_ny"] = int(b.y)
        info.int_params["AC_nz"] = int(b.z)
        info.update_builtin_params()
        return info

    def build_loop(self, spec, iters: int, sharding, sel_sharding,
                   batch: int, use_pallas: bool):
        from ..astaroth.integrate import make_batched_astaroth_step

        if use_pallas:
            raise ValueError(
                "astaroth campaigns run the XLA batched step (the batched "
                "Pallas substep is a hardware-session follow-up)"
            )
        return make_batched_astaroth_step(spec, self._info(spec),
                                          dt=self.dt, iters=iters,
                                          sharding=sharding)

    def step(self, loop, state: Dict, scratch: Dict, sel) -> Dict:
        curr, _out = loop(state, scratch)
        return curr


WORKLOADS = {"jacobi": _JacobiWorkload(), "astaroth": _AstarothWorkload()}


def pick_slot(queue: deque,
              slot_size: int) -> Tuple[Tuple, List[TenantJob], deque]:
    """Pop the next slot's jobs: the queue head's bucket, same-bucket
    jobs pulled forward FIFO until the slot fills. Returns ``(bucket,
    picked, remaining-queue)`` — the ONE packing policy, shared by the
    driver and the :func:`plan_slots` preview."""
    bucket = queue[0].bucket()
    picked: List[TenantJob] = []
    rest: List[TenantJob] = []
    for j in queue:
        if j.bucket() == bucket and len(picked) < slot_size:
            picked.append(j)
        else:
            rest.append(j)
    return bucket, picked, deque(rest)


def plan_slots(jobs: Sequence[TenantJob],
               slot_size: int) -> List[Tuple[Tuple, List[str]]]:
    """Deterministic packing preview: ``[(bucket, [tids...]), ...]`` in
    the order the driver forms slots (:func:`pick_slot`). Pure (no
    devices, no state): the packing-determinism pin of
    tests/test_campaign.py."""
    queue = deque(jobs)
    out: List[Tuple[Tuple, List[str]]] = []
    while queue:
        bucket, picked, queue = pick_slot(queue, slot_size)
        out.append((bucket, [j.tid for j in picked]))
    return out


def batch_devices(slot_size: int, devices: Sequence) -> List:
    """The largest device prefix that divides the batch axis evenly."""
    for n in range(min(slot_size, len(devices)), 0, -1):
        if slot_size % n == 0:
            return list(devices[:n])
    return list(devices[:1])


class CampaignDriver:
    """Serve a queue of tenant jobs through fixed-size batch slots."""

    def __init__(
        self,
        jobs: Sequence[TenantJob],
        slot_size: int,
        campaign_dir: str,
        *,
        devices: Optional[Sequence] = None,
        radius: Optional[int] = None,
        chunk: int = 2,
        ckpt_every: int = 0,
        ckpt_keep: int = 3,
        health_every: int = 0,
        max_abs: Optional[float] = None,
        max_rollbacks: int = 2,
        rollback_backoff: float = 0.05,
        inject: Optional[str] = None,
        inject_seed: Optional[int] = None,
        resume: bool = False,
        cache: Optional[CompileCache] = None,
        use_pallas: bool = False,
        sentinel=None,
        status=None,
        slo_min_samples: int = 3,
        replan=None,
    ):
        if slot_size < 1:
            raise ValueError(f"slot_size must be >= 1, got {slot_size}")
        tids = [j.tid for j in jobs]
        if len(set(tids)) != len(tids):
            raise ValueError("tenant ids must be unique")
        self.jobs = list(jobs)
        self.slot_size = int(slot_size)
        self.campaign_dir = campaign_dir
        self.devices = (list(devices) if devices is not None
                        else jax.devices())
        # None = each slot uses its workload's default (jacobi 1,
        # astaroth 3 — the 6th-order cross stencils)
        self.radius = None if radius is None else int(radius)
        for j in self.jobs:
            if j.workload not in WORKLOADS:
                raise ValueError(
                    f"tenant {j.tid}: unknown workload {j.workload!r} "
                    f"(known: {sorted(WORKLOADS)})")
        self.chunk = max(1, int(chunk))
        self.ckpt_every = int(ckpt_every)
        self.ckpt_keep = int(ckpt_keep)
        self.health_every = int(health_every) or self.chunk
        self.max_abs = max_abs
        self.policy = RecoveryPolicy(max_rollbacks=max_rollbacks,
                                     backoff_s=rollback_backoff)
        self.inject_spec = inject or None
        self.inject_seed = inject_seed
        self.resume = bool(resume)
        self.cache = cache if cache is not None else CompileCache()
        self.use_pallas = bool(use_pallas)
        # live observability (obs/live.py + obs/status.py): the sentinel
        # watches per-slot chunk-cycle latencies (keyed per bucket — two
        # shapes legitimately run at different cadences), the status
        # writer gets the per-lane tenant table each chunk
        self.sentinel = sentinel
        self.status = status
        # the campaign's plan hot-swap (ROADMAP #6, between slots): a
        # slot's compiled program is bucket-keyed and must not change
        # under a running slot, so the swap point is the slot boundary —
        # a latched replan.requested re-tunes there and the next slot's
        # programs consult the re-tuned plan (plan/replan.py)
        self.replan = replan
        # a tenant's online p99 is judged against its deadline only once
        # this many latency samples exist (a single cold-cache chunk must
        # not condemn a tenant)
        self.slo_min_samples = max(1, int(slo_min_samples))
        # per-tenant online latency samples (bounded — streaming p50/p99
        # over recent history, the obs/live window discipline) and the
        # once-per-tenant violation latch
        self._lane_lat: Dict[str, deque] = {}
        self._slo_violated: set = set()
        # the RUNNING slot's lanes and width, published for the serving
        # layer's chunk-boundary capacity decisions (preemption pricing
        # needs the victims; per-width latency pricing needs the B that
        # produced each sample). Batch campaigns run at slot_size.
        self._cur_lanes: List[Lane] = []
        self._cur_width: int = self.slot_size

    # -- serving extension points (stencil_tpu/serve/) ------------------------
    # The always-on scheduler (serve/scheduler.py) subclasses the driver
    # and overrides these hooks; the batch campaign is the degenerate
    # case (a queue fixed at launch, no intake, no parking). Every hook
    # sits at a point the slot machinery already treats as safe: queue
    # scans, chunk boundaries, result assignment, segment boundaries.

    def _refresh_queue(self, queue) -> None:
        """Grow ``queue`` IN PLACE from an external intake. Called before
        every backfill scan and once per chunk — the point where
        backfill stops being a drain-time convenience and becomes
        steady-state continuous batching: a job admitted here lands in a
        RUNNING slot's next freed lane, never behind a slot barrier."""

    def _observe_chunk(self, bucket, per: float, done_now: int) -> None:
        """Per-chunk serving observation (latency pricing, SLO pressure,
        queue status staging). ``per`` is the chunk's per-step wall."""

    def _publish(self, results: Dict[str, "TenantResult"],
                 r: "TenantResult") -> None:
        """The ONE place a tenant's terminal result lands — every retire
        / evict / revived-complete path funnels through here so a
        serving layer can stream results as they happen."""
        results[r.tid] = r
        self._on_result(r)

    def _on_result(self, r: "TenantResult") -> None:
        """A tenant result just published (serve streams it to disk)."""

    def _on_backfill(self, job: "TenantJob", lane_idx: int,
                     slot_step: int) -> None:
        """A queued tenant just took over a freed lane mid-slot."""

    def _backfill_gate(self, bucket) -> bool:
        """May a freed lane refill from the queue right now? Serving
        vetoes (False) when a job of a DIFFERENT bucket has aged past
        its starvation bound: continuous batching would otherwise keep
        a sustained same-bucket stream's slot alive forever, and the
        waiting job could never enter. A veto lets the lane die so the
        slot drains and the next packing pass serves the overdue job."""
        return True

    def _segment_end(self, slot_step: int, end: int) -> int:
        """Cap a guarded segment's end step (must return in
        ``(slot_step, end]``). The batch campaign runs each segment to
        the earliest lane event; serving caps it to one fused chunk so
        a drain request parks at the next CHUNK boundary instead of
        waiting out a whole tenant."""
        return end

    def _should_park(self) -> bool:
        """True = stop the slot at the next segment boundary and park
        every live lane as a revivable snapshot (graceful drain)."""
        return False

    def _on_park(self, job: "TenantJob", tenant_step: int) -> None:
        """A live lane was parked at ``tenant_step`` (snapshot already
        durable) — the serving layer re-queues it for a later daemon."""

    # -- per-tenant durable state ---------------------------------------------
    def tenant_dir(self, tid: str) -> str:
        return os.path.join(self.campaign_dir, "tenants", tid)

    def _write_tenant_snapshot(self, job: TenantJob, spec: GridSpec,
                               lane_state: Dict[str, np.ndarray],
                               step: int) -> None:
        p = spec.padded()
        arrs = {
            name: np.ascontiguousarray(a.reshape(1, 1, 1, p.z, p.y, p.x))
            for name, a in lane_state.items()
        }
        write_snapshot(self.tenant_dir(job.tid), step, spec, arrs,
                       dtypes={name: job.dtype for name in arrs},
                       keep=self.ckpt_keep)

    def _resume_tenant(self, job: TenantJob
                       ) -> Optional[Tuple[int, Dict[str, np.ndarray]]]:
        """The newest valid compatible snapshot of a revived tenant:
        ``(tenant_step, {quantity: global [z,y,x]})`` or None (fresh)."""
        if not self.resume:
            return None
        names = WORKLOADS[job.workload].quantity_names(job.dtype)
        x, y, z = job.size
        found = find_resume(
            self.tenant_dir(job.tid),
            accept=lambda m: check_compatible(
                m, Dim3(x, y, z), names, [job.dtype] * len(names)),
        )
        if found is None:
            return None
        snap, manifest = found
        g = {name: assemble_global(snap, manifest, name, dtype=job.dtype)
             for name in names}
        log.info(f"campaign: revived tenant {job.tid} from step "
                 f"{manifest['step']} ({snap})")
        return int(manifest["step"]), g

    # -- compiled programs ----------------------------------------------------
    def _loop(self, spec: GridSpec, bucket, iters: int, sharding,
              sel_sharding, devs: Sequence, batch: Optional[int] = None):
        from ..plan.ir import PlanConfig

        (size, dtype, workload) = bucket
        wl = WORKLOADS[workload]
        b = int(batch) if batch else self.slot_size
        nq = len(wl.quantity_names(dtype))
        cfg = PlanConfig.make(Dim3(*size), spec.radius, [dtype] * nq,
                              len(devs), self.devices[0].platform)
        # device IDENTITY joins the key, not just the count: the jitted
        # loop's in_shardings pin a concrete mesh, and a shared cache
        # serving two drivers on disjoint same-sized device sets must
        # never hand one the other's program. batch= keys the slot
        # WIDTH, so an elastic daemon holds one program per (bucket,
        # width) rung and a width revisit is a cache hit by construction
        key = cache_key(cfg, workload=f"{workload}-batched",
                        batch=b, iters=int(iters),
                        pallas=self.use_pallas,
                        devices=[d.id for d in devs])
        return self.cache.get(key, lambda: wl.build_loop(
            spec, iters, sharding, sel_sharding,
            batch=b, use_pallas=self.use_pallas))

    # -- the campaign ---------------------------------------------------------
    def run(self) -> dict:
        rec = telemetry.get()
        os.makedirs(self.campaign_dir, exist_ok=True)
        queue = deque(self.jobs)
        results: Dict[str, TenantResult] = {}
        lat: List[float] = []        # per-chunk per-step wall samples
        cell_steps = 0
        wall = 0.0
        slot_idx = 0
        t0 = time.perf_counter()
        while queue:
            bucket, picked, queue = pick_slot(queue, self.slot_size)
            stats = self._run_slot(slot_idx, bucket, picked, queue, results)
            lat.extend(stats["latency_samples"])
            cell_steps += stats["cell_steps"]
            wall += stats["wall_s"]
            slot_idx += 1
            if self.replan is not None and self.replan.pending:
                # between slots: the same swap the guarded single-domain
                # loop performs between chunks (run_guarded's replan=),
                # at the campaign's own safe boundary
                self.replan.maybe_swap(None, slot_idx)
        agg = cell_steps / wall / 1e6 if wall > 0 else 0.0
        summary = {
            "results": results,
            "tenants": len(self.jobs),
            "slots": slot_idx,
            "cell_steps": cell_steps,
            "step_wall_s": wall,
            "total_wall_s": time.perf_counter() - t0,
            "aggregate_mcells_per_s": agg,
            "p50_step_s": percentile(lat, 50) if lat else float("nan"),
            "p99_step_s": percentile(lat, 99) if lat else float("nan"),
            "evicted": sorted(t for t, r in results.items()
                              if r.outcome == "fault"),
            "slo_violations": sorted(self._slo_violated),
            "anomalies": (self.sentinel.detected_total
                          if self.sentinel is not None else 0),
            "cache": self.cache.stats(),
        }
        if self.sentinel is not None:
            # the campaign's in-run instability lands in the ledger via
            # the standard gauge-trimean ingest (perf_tool)
            rec.gauge("live.anomaly_count",
                      float(self.sentinel.detected_total), phase="live")
        rec.meta("campaign.summary", slots=slot_idx,
                 tenants=len(self.jobs), evicted=len(summary["evicted"]),
                 slo_violations=len(summary["slo_violations"]),
                 cache_hits=self.cache.hits, cache_misses=self.cache.misses)
        return summary

    def _run_slot(self, slot_idx: int, bucket, initial: List[TenantJob],
                  queue: deque, results: Dict[str, TenantResult],
                  width: Optional[int] = None) -> dict:
        """Run one slot. ``width`` overrides ``slot_size`` for THIS slot
        only — the elastic serving path sizes each slot to its queue
        depth; batch campaigns never pass it."""
        rec = telemetry.get()
        (size, dtype, workload) = bucket
        wl = WORKLOADS[workload]
        names = wl.quantity_names(dtype)
        radius = (self.radius if self.radius is not None
                  else wl.default_radius)
        x, y, z = size
        cells = x * y * z
        spec = GridSpec(Dim3(x, y, z), Dim3(1, 1, 1),
                        Radius.constant(radius),
                        aligned=self.use_pallas)
        p = spec.padded()
        off = spec.compute_offset()
        B = int(width) if width else self.slot_size
        devs = batch_devices(B, self.devices)
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.asarray(devs), ("b",))
        sh = NamedSharding(mesh, P("b"))
        shr = NamedSharding(mesh, P())

        # sel (jacobi only): the standard hot/cold spheres, shared across
        # lanes (every tenant of one bucket sees the same geometry); the
        # Pallas path wants the per-tenant stacked layout its kernel
        # indexes. Astaroth has no source geometry — no sel at all.
        sel = None
        sel_sh = shr
        if wl.needs_sel:
            sel_np = np.zeros((p.z, p.y, p.x), np.int32)
            sel_np[off.z:off.z + z, off.y:off.y + y, off.x:off.x + x] = (
                sphere_sel((x, y, z)))
            if self.use_pallas:
                sel = jax.device_put(
                    jnp.asarray(np.broadcast_to(sel_np, (B,) + sel_np.shape)
                                .copy()), sh)
                sel_sh = sh
            else:
                sel = jax.device_put(jnp.asarray(sel_np), shr)
                sel_sh = shr

        lanes = [Lane(i) for i in range(B)]
        self._cur_lanes = lanes
        self._cur_width = B

        def interior(padded: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
            return {
                name: np.ascontiguousarray(
                    a[off.z:off.z + z, off.y:off.y + y, off.x:off.x + x])
                for name, a in padded.items()
            }

        def lane_init(job: TenantJob) -> Tuple[int, Dict[str, np.ndarray]]:
            revived = self._resume_tenant(job)
            t0_step, g = revived if revived is not None else (
                0, wl.init_state(job))
            padded = {}
            for name in names:
                a = np.zeros((p.z, p.y, p.x), dtype)
                a[off.z:off.z + z, off.y:off.y + y, off.x:off.x + x] = g[name]
                padded[name] = a
            return t0_step, padded

        curr_np = {name: np.zeros((B, p.z, p.y, p.x), dtype)
                   for name in names}
        for i, job in enumerate(initial):
            t0_step, padded = lane_init(job)
            if t0_step >= job.steps:
                # revived past its target: report done, leave the lane to
                # a later backfill pass
                fins = interior(padded)
                self._publish(results, TenantResult(
                    job.tid, "done", job.steps, self.tenant_dir(job.tid),
                    final=fins[names[0]], finals=fins))
                continue
            lanes[i].tenant = job
            lanes[i].start_slot_step = 0
            lanes[i].start_tenant_step = t0_step
            for name in names:
                curr_np[name][i] = padded[name]
        curr = {name: jax.device_put(jnp.asarray(a), sh)
                for name, a in curr_np.items()}
        scratch = {name: jax.device_put(jnp.zeros_like(curr[name]), sh)
                   for name in names}
        del curr_np

        guard = SlotHealthGuard(every=self.health_every, max_abs=self.max_abs)
        guard.bind(
            lambda lane: (lanes[lane].tenant.tid
                          if lanes[lane].tenant is not None else None),
            lambda lane, step: lanes[lane].tenant_step(step),
        )
        injector = None
        if self.inject_spec:
            plan = FaultPlan.from_spec(self.inject_spec,
                                       seed=self.inject_seed)
            if plan is not None:
                injector = SlotInjector(plan, spec, lambda: lanes,
                                        known_tenants=[j.tid
                                                       for j in self.jobs])
        rec.meta("campaign.slot", slot=slot_idx,
                 tenants=[l.tenant.tid for l in lanes if l.tenant],
                 bucket={"size": list(size), "dtype": dtype,
                         "workload": workload},
                 devices=len(devs), width=B)

        def backfill(lane: Lane, slot_step: int, state: Dict):
            """Replace a retired/evicted lane from the queue (same bucket
            only) or mark it dead (zeros). Takes and returns the whole
            quantity dict — every quantity's lane moves together."""
            self._refresh_queue(queue)
            job = None
            if self._backfill_gate(bucket):
                for cand in list(queue):
                    if cand.bucket() == bucket:
                        job = cand
                        queue.remove(cand)
                        break
            if job is None:
                lane.tenant = None
                return {
                    name: state[name].at[lane.idx].set(
                        jnp.zeros((p.z, p.y, p.x), dtype))
                    for name in names
                }
            t0_step, padded = lane_init(job)
            if t0_step >= job.steps:
                fins = interior(padded)
                self._publish(results, TenantResult(
                    job.tid, "done", job.steps, self.tenant_dir(job.tid),
                    final=fins[names[0]], finals=fins))
                return backfill(lane, slot_step, state)
            lane.tenant = job
            lane.start_slot_step = slot_step
            lane.start_tenant_step = t0_step
            rec.meta("campaign.backfill", tenant=job.tid, lane=lane.idx,
                     slot=slot_idx, slot_step=int(slot_step))
            self._on_backfill(job, lane.idx, int(slot_step))
            return {
                name: state[name].at[lane.idx].set(
                    jnp.asarray(padded[name]))
                for name in names
            }

        # -- the guarded slot loop -------------------------------------------
        slot_step = 0
        stash: Tuple[int, dict] = (0, dict(curr))
        lat: List[float] = []
        cell_steps = 0
        wall = 0.0

        def step_fn(st, k):
            loop = self._loop(spec, bucket, k, sh, sel_sh, devs, B)
            out = wl.step(loop, st, scratch, sel)
            hard_sync(out)
            return out

        def lane_stats(lane: Lane):
            """(p50_ms, p99_ms) of the lane's tenant over its online
            latency window, or (None, None) before any sample."""
            if lane.tenant is None:
                return None, None
            samples = self._lane_lat.get(lane.tenant.tid)
            if not samples:
                return None, None
            return (percentile(samples, 50) * 1e3,
                    percentile(samples, 99) * 1e3)

        def check_slo(done_now: int) -> None:
            """Judge every live lane's online p99 against its deadline;
            a breach emits ONE slo.violation (latched per tenant — the
            evidence record, not a siren)."""
            for l in lanes:
                job = l.tenant
                if job is None or job.deadline_ms is None:
                    continue
                samples = self._lane_lat.get(job.tid)
                if (not samples or len(samples) < self.slo_min_samples
                        or job.tid in self._slo_violated):
                    continue
                p50_ms, p99_ms = lane_stats(l)
                if p99_ms > job.deadline_ms:
                    self._slo_violated.add(job.tid)
                    rec.meta("slo.violation", tenant=job.tid,
                             step=int(l.tenant_step(done_now)),
                             lane=l.idx, slot=slot_idx, phase="slo",
                             deadline_ms=float(job.deadline_ms),
                             p99_ms=p99_ms, p50_ms=p50_ms,
                             samples=len(samples))
                    log.warn(
                        f"campaign: SLO VIOLATION tenant {job.tid} "
                        f"(lane {l.idx}): online p99 {p99_ms:.3g} ms > "
                        f"deadline {job.deadline_ms:g} ms")

        def lane_table(done_now: int):
            rows = []
            for l in lanes:
                job = l.tenant
                p50_ms, p99_ms = lane_stats(l)
                rows.append({
                    "lane": l.idx,
                    "tenant": job.tid if job else None,
                    "step": int(l.tenant_step(done_now)) if job else None,
                    "steps": job.steps if job else None,
                    "p50_ms": p50_ms,
                    "p99_ms": p99_ms,
                    "deadline_ms": job.deadline_ms if job else None,
                    "slo": (None if job is None or job.deadline_ms is None
                            else ("violated" if job.tid in self._slo_violated
                                  else "ok")),
                })
            return rows

        def on_chunk(st, k, per, done_now):
            nonlocal cell_steps, wall
            n_active = sum(1 for l in lanes if l.tenant is not None)
            lat.append(per)
            cell_steps += k * n_active * cells
            wall += per * k
            rec.gauge("campaign.step_latency_s", per, phase="step",
                      unit="s", mode="batched", slot=slot_idx, iters=k)
            # per-tenant online latency: every live lane of the slot
            # stepped together, so the chunk's per-step wall is each
            # live tenant's sample
            for l in lanes:
                if l.tenant is not None:
                    self._lane_lat.setdefault(
                        l.tenant.tid, deque(maxlen=256)).append(per)
            # steady-state serving: pull any newly-arrived jobs into the
            # LIVE queue every chunk (so a retire later in this same
            # slot backfills them — no slot-wide barrier), then let the
            # serving layer observe the chunk (pricing, SLO pressure)
            self._refresh_queue(queue)
            self._observe_chunk(bucket, per, done_now)
            check_slo(done_now)
            if self.status is not None:
                # stage only: run_guarded's per-chunk update (which runs
                # right after on_chunk) flushes these sections in the
                # same atomic write
                self.status.set(
                    lanes=lane_table(done_now),
                    slo={"violations": sorted(self._slo_violated)})

        def save_fn(s, st):
            nonlocal stash
            stash = (s, dict(st))
            host = {name: np.asarray(jax.device_get(st[name]))
                    for name in names}
            for l in lanes:
                if l.tenant is None:
                    continue
                self._write_tenant_snapshot(
                    l.tenant, spec,
                    {name: host[name][l.idx] for name in names},
                    l.tenant_step(s))

        def restore_fn():
            s, st = stash
            return s, dict(st)

        while any(l.tenant is not None for l in lanes):
            if self._should_park():
                # graceful drain: every live lane's current state becomes
                # a revivable snapshot (the eviction persistence path,
                # minus the eviction) and the slot ends here — a later
                # daemon resumes each tenant from exactly this step
                host = {name: np.asarray(jax.device_get(curr[name]))
                        for name in names}
                for l in lanes:
                    if l.tenant is None:
                        continue
                    tstep = l.tenant_step(slot_step)
                    self._write_tenant_snapshot(
                        l.tenant, spec,
                        {name: host[name][l.idx] for name in names}, tstep)
                    self._on_park(l.tenant, tstep)
                    l.tenant = None
                break
            end = min(l.end_slot_step() for l in lanes
                      if l.tenant is not None)
            end = self._segment_end(slot_step, end)
            state = dict(curr)
            stash = (slot_step, dict(state))

            def plan_fn(s):
                return chunk_plan(
                    s, end, self.chunk,
                    every=(self.ckpt_every, guard.every),
                    at=injector.steps() if injector is not None else (),
                )

            try:
                state, done = run_guarded(
                    state, start=slot_step, iters=end, plan_fn=plan_fn,
                    step_fn=step_fn, guard=guard, injector=injector,
                    policy=self.policy,
                    save_fn=save_fn if self.ckpt_every > 0 else None,
                    ckpt_every=self.ckpt_every, restore_fn=restore_fn,
                    on_chunk=on_chunk, spec=None,
                    ckpt_dir=self.campaign_dir,
                    evidence_dir=self.campaign_dir, app="campaign",
                    sentinel=self.sentinel,
                    # per-bucket key: two shape buckets run at honestly
                    # different cadences; base_metric() strips the tag so
                    # "*"/"step.latency_s" config still applies
                    sentinel_key=("step.latency_s["
                                  f"{x}x{y}x{z},{dtype},{workload}]"),
                    status=self.status,
                )
            except RecoveryExhausted as e:
                curr = self._evict(e, spec, lanes, stash, backfill,
                                   results, slot_idx, names)
                slot_step = stash[0]
                continue
            slot_step = done
            curr = dict(state)
            # segment end passed a health check (run_guarded checks at
            # done >= iters): retire every lane whose tenant is complete
            host = {name: np.asarray(jax.device_get(curr[name]))
                    for name in names}
            for l in lanes:
                if l.tenant is None:
                    continue
                if l.tenant_step(slot_step) < l.tenant.steps:
                    continue
                job = l.tenant
                lane_host = {name: host[name][l.idx] for name in names}
                self._write_tenant_snapshot(job, spec, lane_host,
                                            job.steps)
                fins = interior(lane_host)
                self._publish(results, TenantResult(
                    job.tid, "done", job.steps, self.tenant_dir(job.tid),
                    final=fins[names[0]], finals=fins))
                rec.meta("campaign.retire", tenant=job.tid,
                         step=int(job.steps), lane=l.idx, slot=slot_idx)
                curr = backfill(l, slot_step, curr)

        self._cur_lanes = []
        return {"latency_samples": lat, "cell_steps": cell_steps,
                "wall_s": wall}

    def _evict(self, e: RecoveryExhausted, spec: GridSpec,
               lanes: List[Lane], stash, backfill, results,
               slot_idx: int, names: Sequence[str]):
        """The rc-43 eviction path: evidence moves to the tenant dir, the
        tenant's last healthy state becomes a revivable snapshot, the
        lane is backfilled, and the slot resumes from the stash."""
        rec = telemetry.get()
        f = e.fault
        if not isinstance(f, TenantFault):
            raise e  # unattributable: nothing sane to evict
        lane = lanes[f.lane]
        if lane.tenant is None or lane.tenant.tid != f.tenant:
            raise e  # the lane moved under us: refuse to evict blindly
        job = lane.tenant
        tdir = self.tenant_dir(job.tid)
        os.makedirs(tdir, exist_ok=True)
        evidence = None
        if e.evidence_path and os.path.isfile(e.evidence_path):
            evidence = os.path.join(tdir, "fault-evidence.json")
            shutil.move(e.evidence_path, evidence)
        sstep, sstate = stash
        host = {name: np.asarray(jax.device_get(sstate[name]))
                for name in names}
        healthy_tstep = lane.tenant_step(sstep)
        # revivable: persist the last health-checked state BEFORE the
        # lane is overwritten by the backfill
        self._write_tenant_snapshot(
            job, spec, {name: host[name][lane.idx] for name in names},
            healthy_tstep)
        self._publish(results, TenantResult(
            job.tid, "fault", healthy_tstep, tdir, evidence=evidence))
        rec.meta("campaign.evict", tenant=job.tid,
                 step=int(f.tenant_step), lane=lane.idx, slot=slot_idx,
                 rc=FAULT_RC, healthy_step=int(healthy_tstep),
                 evidence=evidence)
        log.warn(f"campaign: evicted tenant {job.tid} (lane {lane.idx}) "
                 f"after {e.rollbacks} rollback(s) at tenant step "
                 f"{f.tenant_step}; slot resumes from step {sstep}")
        return backfill(lane, sstep, dict(sstate))


# -- the sequential baseline ---------------------------------------------------


def run_sequential(jobs: Sequence[TenantJob], *,
                   devices: Optional[Sequence] = None, radius: int = 1,
                   chunk: int = 2,
                   cache: Optional[CompileCache] = None,
                   kernel_variant: Optional[str] = None,
                   temporal_k: Optional[int] = None) -> dict:
    """Serve the same jobs one tenant at a time through the standard
    single-domain machinery (``DistributedDomain`` partitioned over ALL
    the given devices + ``make_jacobi_loop``): the honest baseline of
    ``campaign_batched_over_sequential``. One domain + compiled loop is
    reused per shape bucket (sequential serving amortizes compiles too —
    the ratio measures batching, not compilation); timing covers the
    stepping loop, and per-chunk per-step latencies feed the same
    p50/p99 statistics as the batched driver.

    ``kernel_variant`` selects the REMOTE_DMA exchange variant for the
    tenant domains — ``"fused"`` (overlap kernel) or ``"persistent"``
    (whole-chunk temporal fusion, ops/persistent_stencil.py; needs
    ``temporal_k >= 2`` — domains realize ``radius * temporal_k`` halos
    and each compiled loop exchanges once per ``temporal_k``-step chunk,
    the dispatch-dominated small-domain regime ROADMAP #7 targets)."""
    from ..api import DistributedDomain
    from ..ops.jacobi import make_jacobi_loop
    from ..parallel import Method
    from ..parallel.exchange import shard_blocks
    from ..plan.ir import PlanConfig

    if kernel_variant not in (None, "fused", "persistent"):
        raise ValueError(
            f"unknown kernel_variant {kernel_variant!r}: valid values "
            "are 'fused' and 'persistent'")
    if kernel_variant == "persistent" and (temporal_k is None
                                           or temporal_k < 2):
        raise ValueError(
            "kernel_variant='persistent' needs temporal_k >= 2 (the "
            f"chunk depth; got {temporal_k!r})")
    devices = list(devices) if devices is not None else jax.devices()
    cache = cache if cache is not None else CompileCache()
    rec = telemetry.get()
    results: Dict[str, TenantResult] = {}
    lat: List[float] = []
    cell_steps = 0
    wall = 0.0
    t0 = time.perf_counter()
    for j in jobs:
        if j.workload != "jacobi":
            raise NotImplementedError(
                f"run_sequential serves jacobi tenants only (tenant "
                f"{j.tid} is {j.workload!r}); the astaroth sequential "
                "baseline is a B=1 slot through the batched driver"
            )

    by_bucket: Dict[Tuple, List[TenantJob]] = {}
    order: List[Tuple] = []
    for j in jobs:
        b = j.bucket()
        if b not in by_bucket:
            by_bucket[b] = []
            order.append(b)
        by_bucket[b].append(j)

    for bucket in order:
        (size, dtype, _workload) = bucket
        x, y, z = size
        cells = x * y * z
        dd = DistributedDomain(x, y, z)
        if kernel_variant == "persistent":
            # deep-halo realize: radius*k exteriors feed each k-step chunk
            dd.set_radius(radius * temporal_k)
            dd.set_methods(Method.REMOTE_DMA)
            dd.set_persistent_exchange(True)
        elif kernel_variant == "fused":
            dd.set_radius(radius)
            dd.set_methods(Method.REMOTE_DMA)
            dd.set_fused_exchange(True)
        else:
            dd.set_radius(radius)
        dd.set_devices(devices)
        h = dd.add_data(QUANTITY, dtype)
        dd.realize()
        sel = shard_blocks(sphere_sel((x, y, z)), dd.spec, dd.mesh)
        shape = dd.spec.stacked_shape_zyx()
        cfg = PlanConfig.make(Dim3(x, y, z), dd.spec.radius, [dtype],
                              len(devices), devices[0].platform)

        def loop_for(k):
            key = cache_key(cfg, workload="jacobi-sequential",
                            iters=int(k),
                            partition=[dd.spec.dim.x, dd.spec.dim.y,
                                       dd.spec.dim.z],
                            devices=[d.id for d in devices],
                            variant=kernel_variant or "")
            return cache.get(
                key, lambda: make_jacobi_loop(dd.halo_exchange, k,
                                              temporal_k=temporal_k))

        for job in by_bucket[bucket]:
            dd.set_curr_global(h, tenant_init_field(job))
            c = dd.get_curr(h)
            n2 = jax.device_put(jnp.zeros(shape, dtype), dd.sharding())
            done = 0
            for k in chunk_plan(0, job.steps, chunk):
                loop = loop_for(k)
                t1 = time.perf_counter()
                c, n2 = loop(c, n2, sel)
                hard_sync(c)
                per = (time.perf_counter() - t1) / k
                done += k
                lat.append(per)
                cell_steps += k * cells
                wall += per * k
                rec.gauge("campaign.step_latency_s", per, phase="step",
                          unit="s", mode="sequential", iters=k)
            dd.set_curr(h, c)
            fin = np.ascontiguousarray(dd.get_curr_global(h))
            results[job.tid] = TenantResult(
                job.tid, "done", done, "", final=fin,
                finals={QUANTITY: fin})

    agg = cell_steps / wall / 1e6 if wall > 0 else 0.0
    return {
        "results": results,
        "tenants": len(jobs),
        "slots": 0,
        "cell_steps": cell_steps,
        "step_wall_s": wall,
        "total_wall_s": time.perf_counter() - t0,
        "aggregate_mcells_per_s": agg,
        "p50_step_s": percentile(lat, 50) if lat else float("nan"),
        "p99_step_s": percentile(lat, 99) if lat else float("nan"),
        "evicted": [],
        "cache": cache.stats(),
    }
