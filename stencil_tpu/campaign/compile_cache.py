"""Shape-bucketed compile cache: the serving asset of the campaign layer.

A multi-tenant campaign's economics hinge on one fact: the step program
depends only on the SHAPE of the work — tenant grid, radius, dtype,
batch size, fused-chunk length, device count — never on which tenants
occupy the slot. The pjit mechanism behind this is the SNIPPETS.md note
the ROADMAP cites: the mesh is resolved at call site, so one compiled
program serves every same-shape slot. This cache makes that reuse
explicit and MEASURABLE: every lookup records a ``compile.cache_hit``
gauge (1/0) and every miss wraps its build in a ``compile.build`` span +
``compile.build_s`` gauge, so "the second slot ran with zero
recompilation" is a telemetry pin (CI: scripts/ci_campaign_gate.py;
tests/test_campaign.py), not a hope.

Keys are canonicalized exactly like the plan DB's problem key
(``plan/ir.PlanConfig`` — grid, radius dirs, dtype multiset, ndev,
platform; plan/db.py stores tuned plans under the same string), extended
with the campaign-shape fields (batch size, chunk length, workload,
partition). Two slots whose tenants differ but whose shapes agree map to
the same key by construction.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, Optional

from ..obs import telemetry


def cache_key(config, **extra) -> str:
    """Canonical string key: a ``plan.ir.PlanConfig`` (the plan DB's
    problem key) plus campaign-shape extras (``batch=``, ``chunk=``,
    ``workload=``, ...). Sorted-key JSON, like ``PlanConfig.key()``."""
    obj = dict(config.to_json())
    obj.update(extra)
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class CompileCache:
    """In-process program cache with hit/build telemetry.

    ``get(key, build)`` returns the cached program for ``key`` or builds
    it via ``build()`` (recording the build wall as ``compile.build`` /
    ``compile.build_s``). Either way a ``compile.cache_hit`` gauge lands,
    so a metrics file states exactly how many programs a campaign
    compiled and how many slots they served.
    """

    def __init__(self):
        self._progs: Dict[str, Any] = {}
        self.hits = 0
        self.misses = 0
        # every key that caused a build, in build order — the elastic
        # resize gate's "zero post-warmup recompiles" pin reads this
        # (a width revisit must NOT append here)
        self.built_keys: list = []

    def __len__(self) -> int:
        return len(self._progs)

    def stats(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "programs": len(self._progs)}

    def get(self, key: str, build: Callable[[], Any]):
        rec = telemetry.get()
        hit = key in self._progs
        if hit:
            self.hits += 1
        else:
            self.misses += 1
            self.built_keys.append(key)
            t0 = time.perf_counter()
            with rec.span("compile.build", phase="compile", key=key):
                self._progs[key] = build()
            rec.gauge("compile.build_s", time.perf_counter() - t0,
                      phase="compile", unit="s", key=key)
        rec.gauge("compile.cache_hit", 1 if hit else 0, phase="compile",
                  key=key)
        return self._progs[key]
