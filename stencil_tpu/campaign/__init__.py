"""Multi-tenant batched campaigns (ROADMAP #4).

One compiled program serving thousands of small independent domains:
``driver.CampaignDriver`` packs queued tenant jobs into fixed-size batch
slots, steps each slot as one ``(B, z, y, x)`` stacked program through
``fault/recover.run_guarded`` (per-lane health, rc-43 eviction with
backfill, per-tenant ckpt/ durable state), and ``compile_cache`` makes
the one-program-many-slots economics measurable
(``compile.cache_hit`` / ``compile.build_s``).

The user-facing surface is ``apps/campaign.py`` and the tracked
``campaign_batched_over_sequential`` bench leg.
"""

from .compile_cache import CompileCache, cache_key  # noqa: F401
from .driver import (  # noqa: F401
    WORKLOADS,
    CampaignDriver,
    Lane,
    TenantJob,
    TenantResult,
    astaroth_init_state,
    batch_devices,
    plan_slots,
    run_sequential,
    tenant_init_field,
)
from .health import SlotHealthGuard, TenantFault  # noqa: F401
from .inject import SlotInjector  # noqa: F401
