"""Deterministic fault injection scoped to one tenant lane.

Adapts the seeded registry of ``fault/inject.py`` to a batch slot: the
spec grammar is unchanged (``kind@step[:k=v...]``, parsed by
``fault.inject.parse_spec``) plus the ``tenant=ID`` option that pins an
injection to one tenant's lane — ``nan@3:tenant=t2:repeat=always`` is
the campaign eviction test's whole script. Steps are TENANT-relative
(``nan@3`` = the tenant's own step 3), so an injection follows its
tenant wherever the packer placed it and whenever it entered the slot.

Only the state kinds make sense per-lane: ``nan``/``inf`` burst a
``cells``-sided cube into the target tenant's compute interior (seeded
placement keyed on (seed, kind, step, tenant) ONLY — a re-fire after a
rollback corrupts the SAME cells, the fault/inject.py determinism rule),
and ``slow`` sleeps. Process-wide kinds (stall/crash/ckpt-truncate) are
REJECTED at construction: a campaign spec that could not possibly fire
per-tenant must fail loudly, not run the campaign un-faulted.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..fault.inject import FaultPlan, Injection
from ..obs import telemetry
from ..utils import logging as log

SLOT_KINDS = ("nan", "inf", "slow")


class SlotInjector:
    """The active per-lane injection schedule of one batch slot.

    Duck-type compatible with ``fault.inject.FaultPlan`` where
    ``fault/recover.run_guarded`` touches it (``steps()``,
    ``fire_due(state, prev, step, spec=, ckpt_dir=, ckpt_flush=)``).
    ``lanes_fn()`` returns the driver's live lane table (objects with
    ``idx``, ``tenant`` (``.tid``) and the slot/tenant step anchors), so
    backfills and evictions retarget injections without rewiring.
    """

    def __init__(self, plan: FaultPlan, spec, lanes_fn: Callable[[], Sequence],
                 known_tenants: Optional[Sequence[str]] = None):
        bad = [i.kind for i in plan.injections if i.kind not in SLOT_KINDS]
        if bad:
            raise ValueError(
                f"campaign injection supports kinds {SLOT_KINDS}, got "
                f"{sorted(set(bad))} (process-wide kinds cannot be scoped "
                "to one tenant lane)")
        if known_tenants is not None:
            missing = [i.tenant for i in plan.injections
                       if i.tenant and i.tenant not in known_tenants]
            if missing:
                raise ValueError(
                    f"campaign injection targets unknown tenant(s) "
                    f"{sorted(set(missing))}")
        self.plan = plan
        self.spec = spec
        self._lanes_fn = lanes_fn

    @property
    def seed(self) -> int:
        return self.plan.seed

    def describe(self) -> List[dict]:
        return self.plan.describe()

    # -- lane resolution ------------------------------------------------------
    def _lane_for(self, inj: Injection):
        lanes = [l for l in self._lanes_fn() if l.tenant is not None]
        if not lanes:
            return None
        if inj.tenant is not None:
            for l in lanes:
                if l.tenant.tid == inj.tenant:
                    return l
            return None  # target not resident (evicted / not packed yet)
        # untargeted: deterministic seeded choice among resident tenants
        rng = random.Random(repr((self.seed, inj.kind, inj.step)))
        tid = rng.choice(sorted(l.tenant.tid for l in lanes))
        return next(l for l in lanes if l.tenant.tid == tid)

    def _slot_step(self, inj: Injection, lane) -> int:
        return lane.start_slot_step + (inj.step - lane.start_tenant_step)

    def steps(self) -> List[int]:
        """Slot-step breakpoints for ``chunk_plan`` — injections must land
        at their exact tenant step regardless of chunking. Exhausted
        injections and unresolvable targets are excluded (a re-entered
        segment must not warn about steps that already fired)."""
        out = set()
        for inj in self.plan.injections:
            if inj.repeat >= 0 and inj.fired >= inj.repeat:
                continue
            lane = self._lane_for(inj)
            if lane is None:
                continue
            out.add(self._slot_step(inj, lane))
        return sorted(out)

    # -- firing ---------------------------------------------------------------
    def fire_due(self, state: Dict[str, "object"], prev_step: int,
                 step: int, spec=None, ckpt_dir=None, ckpt_flush=None):
        for inj in self.plan.injections:
            if inj.repeat >= 0 and inj.fired >= inj.repeat:
                continue
            lane = self._lane_for(inj)
            if lane is None:
                continue
            due_at = self._slot_step(inj, lane)
            if not (prev_step < due_at <= step):
                continue
            inj.fired += 1
            state = self._apply(inj, state, lane)
        return state

    def _apply(self, inj: Injection, state, lane):
        rec = telemetry.get()
        if inj.kind == "slow":
            rec.meta("fault.injected", fault_kind=inj.kind,
                     step=int(inj.step), phase="fault",
                     tenant=lane.tenant.tid, lane=lane.idx,
                     seconds=inj.seconds)
            log.warn(f"fault: slow@{inj.step} (tenant {lane.tenant.tid}) "
                     f"sleeping {inj.seconds:g}s")
            time.sleep(inj.seconds)
            return state
        # nan/inf: a cells^3 burst inside the tenant's compute interior —
        # placement keyed on (seed, kind, step, tenant) only, so a re-fire
        # after rollback corrupts the SAME cells (fault/inject.py rule)
        rng = random.Random(
            repr((self.seed, inj.kind, inj.step, lane.tenant.tid)))
        names = sorted(state)
        name = inj.quantity if inj.quantity in state else rng.choice(names)
        val = float("nan") if inj.kind == "nan" else float("inf")
        b, off = self.spec.base, self.spec.compute_offset()
        c = max(1, min(inj.cells, b.x, b.y, b.z))
        x0 = off.x + rng.randrange(b.x - c + 1)
        y0 = off.y + rng.randrange(b.y - c + 1)
        z0 = off.z + rng.randrange(b.z - c + 1)
        state = dict(state)
        state[name] = state[name].at[
            lane.idx, z0:z0 + c, y0:y0 + c, x0:x0 + c].set(val)
        rec.meta("fault.injected", fault_kind=inj.kind, step=int(inj.step),
                 phase="fault", quantity=name, cells=c ** 3,
                 tenant=lane.tenant.tid, lane=lane.idx,
                 origin=[x0, y0, z0])
        log.warn(f"fault: {inj.kind}@{inj.step} burst {c}^3 cells into "
                 f"{name!r} of tenant {lane.tenant.tid} (lane {lane.idx})")
        return state
