"""ctypes loader for the native components (native/qap.cpp).

The shared library is built by ``make -C native`` (a plain g++ -shared
build); if it is missing, this module builds it on first import when a
compiler is available, else raises so callers fall back to the pure-Python
implementations. The C ABI is the stable boundary — no pybind11 needed.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libstencil_native.so")
_NATIVE_SRC = os.path.join(os.path.dirname(os.path.dirname(_DIR)), "native")


def _build() -> None:
    subprocess.run(
        ["make", "-s", "-C", _NATIVE_SRC],
        check=True,
        capture_output=True,
        timeout=120,
    )


def _load() -> ctypes.CDLL:
    try:
        # make's mtime tracking rebuilds after qap.cpp edits; no-op when fresh
        _build()
    except Exception:
        if not os.path.exists(_SO):
            raise
    lib = ctypes.CDLL(_SO)
    dp = ctypes.POINTER(ctypes.c_double)
    sp = ctypes.POINTER(ctypes.c_size_t)
    lib.stencil_qap_solve.argtypes = [ctypes.c_int, dp, dp, ctypes.c_double, sp, dp]
    lib.stencil_qap_solve.restype = ctypes.c_int
    lib.stencil_qap_solve_catch.argtypes = [ctypes.c_int, dp, dp, sp, dp]
    lib.stencil_qap_solve_catch.restype = ctypes.c_int
    # optional symbol: a stale prebuilt .so (no compiler to rebuild) must
    # not take down the QAP entry points with it
    pw = getattr(lib, "stencil_paraview_write", None)
    if pw is not None:
        pw.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.POINTER(dp),
        ]
        pw.restype = ctypes.c_int
    return lib


_LIB = _load()


def paraview_write(path: str, header: str, origin, size, qs) -> None:
    """Stream one block's CSV rows (Z,Y,X,q0,...) from C++.

    ``origin``/``size`` are (z, y, x) tuples; ``qs`` is a list of dense
    [sz, sy, sx] float64 arrays. Emits byte-identical output to the
    Python fallback (shortest-round-trip floats, Python-repr rules)."""
    if getattr(_LIB, "stencil_paraview_write", None) is None:
        raise OSError(
            "libstencil_native.so predates the paraview writer; "
            "rebuild with `make -C native`"
        )
    arrs = [np.ascontiguousarray(q, dtype=np.float64) for q in qs]
    dp = ctypes.POINTER(ctypes.c_double)
    ptrs = (dp * len(arrs))(*[a.ctypes.data_as(dp) for a in arrs])
    rc = _LIB.stencil_paraview_write(
        path.encode(), header.encode(),
        int(origin[0]), int(origin[1]), int(origin[2]),
        int(size[0]), int(size[1]), int(size[2]),
        len(arrs), ptrs,
    )
    if rc != 0:
        raise OSError(f"stencil_paraview_write({path!r}) failed rc={rc}")


class qap_native:
    """Native QAP entry points mirroring stencil_tpu.parallel.qap."""

    @staticmethod
    def solve(w: np.ndarray, d: np.ndarray, timeout_s: float) -> Tuple[List[int], float]:
        n = w.shape[0]
        w = np.ascontiguousarray(w, dtype=np.float64)
        d = np.ascontiguousarray(d, dtype=np.float64)
        f = np.zeros(n, dtype=np.uintp)
        c = ctypes.c_double()
        dp = ctypes.POINTER(ctypes.c_double)
        sp = ctypes.POINTER(ctypes.c_size_t)
        timed_out = _LIB.stencil_qap_solve(
            n,
            w.ctypes.data_as(dp),
            d.ctypes.data_as(dp),
            timeout_s,
            f.ctypes.data_as(sp),
            ctypes.byref(c),
        )
        if timed_out:
            from ..utils import logging as log

            log.warn("qap.solve (native) timed out; result is best-so-far")
        return [int(i) for i in f], float(c.value)

    @staticmethod
    def solve_catch(w: np.ndarray, d: np.ndarray) -> Tuple[List[int], float]:
        n = w.shape[0]
        w = np.ascontiguousarray(w, dtype=np.float64)
        d = np.ascontiguousarray(d, dtype=np.float64)
        f = np.zeros(n, dtype=np.uintp)
        c = ctypes.c_double()
        dp = ctypes.POINTER(ctypes.c_double)
        sp = ctypes.POINTER(ctypes.c_size_t)
        _LIB.stencil_qap_solve_catch(
            n,
            w.ctypes.data_as(dp),
            d.ctypes.data_as(dp),
            f.ctypes.data_as(sp),
            ctypes.byref(c),
        )
        return [int(i) for i in f], float(c.value)
