"""Dynamic-offset boundary shells: comm/compute overlap on uneven partitions.

The reference computes per-LocalDomain interior/exterior regions for uneven
subdomains as a matter of course (reference: src/stencil.cu:878-977 — each
rank owns its own extents, so the slabs are just different constants per
rank). Under ``shard_map`` one program is traced for every block, so
per-block extents cannot be Python constants — but they ARE static per
block *index*: along each axis the remainder rule makes trailing blocks one
cell smaller (domain/grid.py:_axis_sizes). This module turns that into
traced-but-shape-static geometry:

- :func:`dyn_block_sizes` reads this block's logical sizes with
  ``lax.axis_index`` lookups into the per-axis size tables (a scalar gather,
  free next to the stencil);
- :func:`shell_regions` lists the boundary shells (one per side of each
  included axis) as ``(lo, size)`` pairs where ``size`` is static (slab
  thickness = that side's radius, cross-section = the base extents) and
  ``lo`` is traced only on the high side of an uneven axis;
- :func:`interior_mask` is the masked-interior-write companion: a boolean
  over the (static) compute extents that is True where a stencil of the
  face radii reads no halo cell of an included axis.

Shells overlap at edges/corners; every patch recomputes from the same
exchanged source, so double-written cells get identical values and the
patch order is immaterial. Cross-sections span the *base* extents: on an
uneven partner axis the overhang lands in the block's dead pad tail
(grid.py:39), never in another block's data.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from jax import lax

from ..domain.grid import GridSpec


def dyn_block_sizes(spec: GridSpec):
    """This block's logical (z, y, x) sizes inside ``shard_map``: traced
    table lookups on uneven axes, Python ints elsewhere."""
    from ..parallel.mesh import AXIS_X, AXIS_Y, AXIS_Z

    out = []
    for name, d, szs, base in (
        (AXIS_Z, spec.dim.z, spec.sizes_z, spec.base.z),
        (AXIS_Y, spec.dim.y, spec.sizes_y, spec.base.y),
        (AXIS_X, spec.dim.x, spec.sizes_x, spec.base.x),
    ):
        if d > 1 and min(szs) != max(szs):
            out.append(jnp.asarray(szs, jnp.int32)[lax.axis_index(name)])
        else:
            out.append(base)
    return tuple(out)


def shell_regions(spec: GridSpec, sizes, include: Sequence[bool]):
    """Boundary shells to re-sweep from exchanged halos.

    ``sizes`` is :func:`dyn_block_sizes`'s (z, y, x); ``include`` is a
    (z, y, x) boolean triple — which axes' sides need patching (all axes for
    paths whose pre-exchange pass read stale periodic halos; multi-block
    axes only when self-wrap is filled in-kernel). Returns ``(lo, size)``
    pairs in array (z, y, x) order; ``size`` entries are Python ints."""
    off = spec.compute_offset()
    o = (off.z, off.y, off.x)
    base = (spec.base.z, spec.base.y, spec.base.x)
    r = spec.radius
    rad = (r.z, r.y, r.x)
    regs = []
    for ax in range(3):
        if not include[ax]:
            continue
        r_lo, r_hi = rad[ax](-1), rad[ax](1)
        if r_lo > 0:
            lo = list(o)
            size = list(base)
            size[ax] = r_lo
            regs.append((_i32(lo), tuple(size)))
        if r_hi > 0:
            lo = list(o)
            size = list(base)
            lo[ax] = o[ax] + sizes[ax] - r_hi
            size[ax] = r_hi
            regs.append((_i32(lo), tuple(size)))
    return regs


def _i32(lo):
    # uniform start dtype: mixed Python-int / traced-int32 starts trip
    # dynamic_slice's same-dtype requirement under x64 (cf. exchange._starts)
    return tuple(jnp.asarray(v, jnp.int32) for v in lo)


def interior_mask(spec: GridSpec, sizes, include: Sequence[bool]):
    """Boolean over the (base.z, base.y, base.x) compute extents: True where
    a face-radius stencil reads no halo of an included axis. The
    masked-interior write (out = where(mask, new, old)) replaces the
    shrunk-extent interior sweep when extents are per-block."""
    shape = (spec.base.z, spec.base.y, spec.base.x)
    r = spec.radius
    rad = (r.z, r.y, r.x)
    m = jnp.ones(shape, jnp.bool_)
    for ax in range(3):
        if not include[ax]:
            continue
        rel = lax.broadcasted_iota(jnp.int32, shape, ax)
        m = m & (rel >= rad[ax](-1)) & (rel < sizes[ax] - rad[ax](1))
    return m


def include_axes(spec: GridSpec, multi_block_only: bool) -> Tuple[bool, bool, bool]:
    """(z, y, x) axis-include triple for :func:`shell_regions` /
    :func:`interior_mask`."""
    if not multi_block_only:
        return (True, True, True)
    return (spec.dim.z > 1, spec.dim.y > 1, spec.dim.x > 1)
