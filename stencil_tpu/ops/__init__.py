from .jacobi import (
    jacobi6_block,
    jacobi_reference,
    jacobi_sweep,
    make_jacobi_loop,
    make_jacobi_step,
    sphere_masks,
    sphere_sel,
)

__all__ = [
    "jacobi6_block",
    "jacobi_reference",
    "jacobi_sweep",
    "make_jacobi_loop",
    "make_jacobi_step",
    "sphere_masks",
    "sphere_sel",
]
