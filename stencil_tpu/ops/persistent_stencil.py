"""Persistent whole-chunk mega-kernel: a k-step chunk in ONE kernel.

PR 14's fused kernel moved one exchange+substep into a single
``pallas_call``, but every step still pays a kernel launch and a host
dispatch round-trip — the floor that bounds small-domain and campaign
throughput (ROADMAP #7: the B=64 32^3 campaign p50 sits in
dispatch-dominated territory). This module takes the §5.8
kernel-initiated idea to its endpoint: ONE persistent kernel per k-step
chunk, with deep-halo (radius*k) staging trading redundant boundary
compute for k-fold fewer wire rounds — the classic communication-
avoiding temporal fusion. Launch count drops from O(steps) to
O(chunks).

The chunk schedule (both lowerings):

1. exchange radius*k-deep halos ONCE — in-kernel per-direction
   ``pltpu.make_async_remote_copy``s behind a neighbor barrier
   semaphore on TPU, the host-orchestrated plain REMOTE_DMA emulation
   elsewhere (``parallel/remote_emu.RemoteDmaEmulation`` at the deep
   radius the driver realized);
2. run k substeps with NO further exchange: substep s sweeps the
   region grown ``k - 1 - s`` cells beyond the compute region on every
   side — the shrinking valid strip of ``plan_multistep_staging``'s
   deep-halo math (ops/pallas_stencil.py). Grown-region cells are
   REDUNDANT recomputes of neighbor cells: the halo coordinate system
   is seamless (a halo cell at index ``off + n + j`` IS neighbor cell
   ``j``), and the sweep expression/operand order is byte-for-byte
   :func:`~stencil_tpu.ops.jacobi.jacobi_sweep`'s, so every redundant
   cell reproduces the neighbor's value bit-exactly — which is why the
   chunk output is bit-identical to the composed per-step baseline
   (tests/test_persistent_stencil.py pins it, uneven partitions and
   guarded rollbacks included).

Inter-chunk safety on TPU: the barrier semaphore at kernel start means
a neighbor's NEXT chunk cannot begin landing slabs into our halos
until every ring neighbor (including us) has entered its next kernel —
by which point this chunk's reads are done. Between substeps no data
crosses devices at all (the deep halo covers the whole chunk), so no
in-chunk barrier exists — that is the communication avoidance.

The ``sel`` contract: both lowerings read hot/cold sel values at
GROWN-region cells, so ``sel`` must arrive with its halos filled to
the realized radius — one ``ex(sel)`` per loop build (sel is
step-invariant; the step compilers in ops/jacobi.py do this).

This container has no TPU (no Pallas cross-device interpret mode) —
the PR 10/14 discipline applies: the all-self-wrap (single device)
form of the mega-kernel runs in interpret mode, parity-pinned against
the XLA chunk program including uneven z extents whose mod-3 plane
ring wraps mid-window; the crossing form is exercised on hardware via
``scripts/probe_persistent.py`` (item-1 queue). Correctness on the CPU
mesh is owned by :func:`make_persistent_chunk_body` + the plain
REMOTE_DMA emulation (ops/jacobi._compile_jacobi_persistent).

First-cut scope, loud: uniform partitions and one resident block per
device for the TPU kernel (the CPU emulation owns uneven); multistep
depth k >= 2 (k == 1 IS the fused kernel — plan/ir.build_plan refuses
the degenerate combination).
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..geometry import DIRECTIONS_26, Dim3, Rect3


def persistent_kernel_supported(spec, resident) -> bool:
    """What the persistent TPU mega-kernel handles today: UNIFORM
    partitions, one resident block per device (static per-direction
    extents in-kernel). Uneven single-resident chunks run the
    host-orchestrated emulation; oversubscription is loud infeasibility
    at HaloExchange construction."""
    return spec.is_uniform() and resident == Dim3(1, 1, 1)


def chunk_schedule(iters: int, k: int) -> List[int]:
    """The chunk depths a ``iters``-step persistent loop runs: full
    depth-``k`` chunks plus one shallower tail chunk for the remainder
    (the tail reuses the same machinery at a smaller depth — still one
    exchange + one chunk program). Drives both the step loops and the
    launch-count census (2 host dispatches per entry)."""
    if k < 1:
        raise ValueError(f"persistent chunk depth must be >= 1, got {k}")
    if iters < 0:
        raise ValueError(f"iters must be >= 0, got {iters}")
    n, rem = divmod(iters, k)
    return [k] * n + ([rem] if rem else [])


def check_chunk_depth(spec, depth: int) -> None:
    """Loud refusal when the realized halo cannot feed a depth-``depth``
    chunk: substep 0 reads ``depth`` cells into the halo on every side,
    so every face radius must be >= depth. The planner refuses the same
    configurations statically (plan/cost.py ``feasible``'s scaled-radius
    check); this guards direct driver use."""
    r = spec.radius
    rmin = min(r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1))
    if rmin < depth:
        raise ValueError(
            f"persistent chunk depth {depth} needs radius >= {depth} on "
            f"every side (realized min face radius is {rmin}): realize "
            "the spec at radius*k before building the chunk"
        )
    if min(spec.base.x, spec.base.y, spec.base.z) < depth:
        raise ValueError(
            f"persistent chunk depth {depth} exceeds a {spec.base} block "
            "interior: the shrinking valid strip would go negative "
            "(plan/cost.py prices this infeasible)"
        )


def make_persistent_chunk_body(spec, depth: int):
    """The XLA chunk program body: ``chunk(curr, nxt, sel) -> (out,
    scratch)`` over one exchange-filled padded block inside
    ``shard_map`` — ``depth`` substeps, NO exchange, substep ``s``
    sweeping the region grown ``depth - 1 - s`` cells per side. This is
    what ``_compile_jacobi_persistent`` compiles per mesh (ONE program
    dispatch per chunk) and what the interpret-mode mega-kernel is
    parity-pinned against.

    Works on uneven partitions with the SAME static base-extent rects:
    halo cells sit immediately adjacent to a block's true extent, so a
    grown sweep recomputes neighbor cells at the right coordinates;
    cells beyond ``true_size + grow`` compute garbage that nothing ever
    reads (the next substep's reads stop exactly at the valid edge, and
    the next chunk's exchange rewrites the halos)."""
    from .jacobi import jacobi_sweep

    check_chunk_depth(spec, depth)
    off = spec.compute_offset()
    base = spec.base

    def chunk(curr, nxt, sel):
        masks = (sel == 1, sel == 2)
        c, n = curr, nxt
        for s in range(depth):
            g = depth - 1 - s
            rect = Rect3(
                Dim3(off.x - g, off.y - g, off.z - g),
                Dim3(off.x + base.x + g, off.y + base.y + g,
                     off.z + base.z + g),
            )
            n = jacobi_sweep(c, n, rect, masks)
            c, n = n, c
        return c, n

    return chunk


def _deep_dir_phases(spec, mesh_dim):
    """Per-direction message records at the spec's FULL (deep) radius on
    a uniform partition: ``(direction, src, dst, shape, crossing)`` in
    (z, y, x) block-local coordinates — the DIRECT26 exact-extent
    geometry (faces, edges, AND corners: grown substeps read corner
    halos, unlike the per-step jacobi). Exact extents fill disjoint
    halo regions, so message order is free and all remote copies start
    concurrently (the fused kernel's argument, ops/fused_stencil.py)."""
    r = spec.radius
    off = spec.compute_offset()
    b = spec.base
    multi = {"z": mesh_dim.z > 1, "y": mesh_dim.y > 1, "x": mesh_dim.x > 1}
    out = []
    for d in DIRECTIONS_26:
        if spec.radius.dir(-d) == 0:
            continue
        src, dst, shape = [], [], []
        for axis, dc, o, s, rm, rp in (
            ("z", d.z, off.z, b.z, r.z(-1), r.z(1)),
            ("y", d.y, off.y, b.y, r.y(-1), r.y(1)),
            ("x", d.x, off.x, b.x, r.x(-1), r.x(1)),
        ):
            if dc == 1:
                src.append(o + s - rm)
                dst.append(o - rm)
                shape.append(rm)
            elif dc == -1:
                src.append(o)
                dst.append(o + s)
                shape.append(rp)
            else:
                src.append(o)
                dst.append(o)
                shape.append(s)
        crossing = any(
            comp != 0 and multi[axis]
            for axis, comp in (("z", d.z), ("y", d.y), ("x", d.x))
        )
        out.append((d, tuple(src), tuple(dst), tuple(shape), crossing))
    return out


def make_persistent_jacobi_kernel(spec, plan, k: int, dtype=jnp.float32,
                                  collective_id: int = 0,
                                  interpret: bool = False):
    """The whole-chunk mega-kernel: ``fn(curr, nxt, sel) -> (curr',
    out', sel')`` — ONE ``pallas_call`` per k-step chunk:

    barrier with every ring neighbor → start every per-direction deep
    (radius*k) remote copy concurrently + local self-wrap hand-offs →
    wait the recv semaphores → k plane-streamed substeps over the
    shrinking grown regions, with a mod-3 ring-indexed 3-plane VMEM
    window per substep (PR 1's modular-slot machinery: each input plane
    loads exactly once per substep, no plane copies) and the substeps
    ping-ponging between the two aliased HBM buffers.

    ``curr'``/``out'``/``sel'`` alias ``curr``/``nxt``/``sel`` in
    place; after k substeps the final field sits in ``out'`` when k is
    odd and in ``curr'`` when k is even — the host wrapper in
    ops/jacobi.py resolves the parity. ``sel`` must arrive halo-filled
    (see the module docstring); the kernel never exchanges it.

    In interpret mode only the all-self-wrap (single device) form runs
    — no remote copies exist — which parity-pins the substep ring, the
    shrinking extents, and the deep self-wrap fills against
    :func:`make_persistent_chunk_body` on any host, including z extents
    that wrap the mod-3 plane ring mid-window (``nz % 3 != 0``)."""
    from .jacobi import COLD_TEMP, HOT_TEMP

    if not spec.is_uniform():
        raise ValueError(
            "the persistent TPU mega-kernel takes uniform partitions "
            "today; uneven persistent runs the host-orchestrated chunk "
            "(ops/jacobi._compile_jacobi_persistent)"
        )
    if k < 2:
        raise ValueError(
            "persistent chunks need k >= 2 (a depth-1 chunk IS the "
            "fused substep kernel — use kernel_variant='fused')"
        )
    check_chunk_depth(spec, k)
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    off = spec.compute_offset()
    b = spec.base
    nz, ny, nx = b.z, b.y, b.x
    zo, yo, xo = off.z, off.y, off.x
    md = Dim3(plan.mesh_dim[0], plan.mesh_dim[1], plan.mesh_dim[2]) \
        if not isinstance(plan.mesh_dim, Dim3) else plan.mesh_dim
    phases = _deep_dir_phases(spec, md)
    crossing = [ph for ph in phases if ph[4]]
    local = [ph for ph in phases if not ph[4]]
    n_cross = len(crossing)
    if interpret and n_cross:
        raise ValueError(
            "interpret mode runs the all-self-wrap (single device) "
            "persistent kernel only — remote copies have no interpreter"
        )
    multi = {"z": md.z > 1, "y": md.y > 1, "x": md.x > 1}

    def dslice(starts, shape):
        return tuple(pl.ds(s, w) for s, w in zip(starts, shape))

    def kernel(curr, nxt, sel, curr_o, out_o, sel_o, *scratch):
        sends = scratch[0:n_cross]
        lands = scratch[n_cross: 2 * n_cross]
        (planes, sel_pl, out_pl, send_sems, recv_sems, copy_sem) = \
            scratch[2 * n_cross: 2 * n_cross + 6]

        idx = {a: lax.axis_index(a) if multi[a] else 0
               for a in ("z", "y", "x")}
        ring = {"z": md.z, "y": md.y, "x": md.x}

        def neighbor(d):
            out = {}
            for axis, comp in (("z", d.z), ("y", d.y), ("x", d.x)):
                if comp and multi[axis]:
                    out[axis] = (idx[axis] + comp) % ring[axis]
            return out

        rdmas = []
        if n_cross:
            # 1. barrier: one signal per crossing direction — a
            # neighbor entering its chunk kernel proves our previous
            # chunk's reads of its landings are complete (launch order
            # per device is serial), so the deep slabs may land
            barrier = pltpu.get_barrier_semaphore()
            for d, _s, _d2, _sh, _c in crossing:
                pltpu.semaphore_signal(
                    barrier, inc=1, device_id=neighbor(d),
                    device_id_type=pltpu.DeviceIdType.MESH,
                )
            pltpu.semaphore_wait(barrier, n_cross)

            # 2. stage + START every deep remote copy concurrently
            for i, (d, src, _dst, shape, _c) in enumerate(crossing):
                cp = pltpu.make_async_copy(
                    curr.at[dslice(src, shape)], sends[i], copy_sem)
                cp.start()
                cp.wait()
                rdma = pltpu.make_async_remote_copy(
                    src_ref=sends[i], dst_ref=lands[i],
                    send_sem=send_sems.at[i], recv_sem=recv_sems.at[i],
                    device_id=neighbor(d),
                    device_id_type=pltpu.DeviceIdType.MESH,
                )
                rdma.start()
                rdmas.append(rdma)

        # self-wrap hand-offs: deep local copies behind the sends
        for _d, src, dst, shape, _c in local:
            cp = pltpu.make_async_copy(
                curr.at[dslice(src, shape)],
                curr_o.at[dslice(dst, shape)], copy_sem)
            cp.start()
            cp.wait()

        # sel rides through aliased (already halo-filled by the caller)
        if n_cross:
            for rdma in rdmas:
                rdma.wait()
            for i, (_d, _src, dst, shape, _c) in enumerate(crossing):
                cp = pltpu.make_async_copy(
                    lands[i], curr_o.at[dslice(dst, shape)], copy_sem)
                cp.start()
                cp.wait()

        def substep(src_ref, dst_ref, g):
            """One grown-region plane-streamed sweep: z planes
            [zo - g, zo + nz + g), y/x extents grown g per side, with
            the mod-3 ring window — plane z+1 loads into slot
            (z+1) % 3 while z-1/z are already resident (each plane
            loads once; the ring offset wraps mid-window whenever the
            grown z extent is not a multiple of 3)."""
            z0 = zo - g
            z1 = zo + nz + g
            ys = slice(yo - g, yo + ny + g)
            xs = slice(xo - g, xo + nx + g)
            ysm = slice(yo - g - 1, yo + ny + g - 1)
            ysp = slice(yo - g + 1, yo + ny + g + 1)
            xsm = slice(xo - g - 1, xo + nx + g - 1)
            xsp = slice(xo - g + 1, xo + nx + g + 1)

            def load_plane(z):
                slot = lax.rem(z, 3)
                cp = pltpu.make_async_copy(
                    src_ref.at[pl.ds(z, 1)], planes.at[slot], copy_sem)
                cp.start()
                cp.wait()

            load_plane(z0 - 1)
            load_plane(z0)

            def body(i, _):
                z = z0 + i
                load_plane(z + 1)
                cp = pltpu.make_async_copy(
                    sel.at[pl.ds(z, 1)], sel_pl, copy_sem)
                cp.start()
                cp.wait()
                cp = pltpu.make_async_copy(
                    dst_ref.at[pl.ds(z, 1)], out_pl, copy_sem)
                cp.start()
                cp.wait()
                c = planes[lax.rem(z, 3), 0]
                lo = planes[lax.rem(z - 1 + 3, 3), 0]
                hi = planes[lax.rem(z + 1, 3), 0]
                avg = (
                    c[ys, xsm] + c[ys, xsp]
                    + c[ysm, xs] + c[ysp, xs]
                    + lo[ys, xs] + hi[ys, xs]
                ) / 6
                sl = sel_pl[0][ys, xs]
                avg = jnp.where(sl == 1, HOT_TEMP,
                                jnp.where(sl == 2, COLD_TEMP, avg))
                out_pl[0, ys, xs] = avg.astype(dtype)
                cp = pltpu.make_async_copy(
                    out_pl, dst_ref.at[pl.ds(z, 1)], copy_sem)
                cp.start()
                cp.wait()
                return 0

            lax.fori_loop(0, z1 - z0, body, 0)

        # k substeps, unrolled (static grown extents per substep),
        # ping-ponging the aliased HBM buffers: even substeps read the
        # exchanged curr_o, odd read out_o
        for s in range(k):
            g = k - 1 - s
            if s % 2 == 0:
                substep(curr_o, out_o, g)
            else:
                substep(out_o, curr_o, g)

    block = jax.ShapeDtypeStruct((pz, py, px), dtype)
    sel_block = jax.ShapeDtypeStruct((pz, py, px), jnp.int32)
    scratch_shapes = (
        [pltpu.VMEM(sh, dtype) for _d, _s, _d2, sh, _c in crossing]  # sends
        + [pltpu.VMEM(sh, dtype) for _d, _s, _d2, sh, _c in crossing]  # lands
        + [
            pltpu.VMEM((3, 1, py, px), dtype),   # mod-3 plane ring
            pltpu.VMEM((1, py, px), jnp.int32),  # sel plane
            pltpu.VMEM((1, py, px), dtype),      # out plane (RMW)
            pltpu.SemaphoreType.DMA((max(1, n_cross),)),
            pltpu.SemaphoreType.DMA((max(1, n_cross),)),
            pltpu.SemaphoreType.DMA(()),
        ]
    )
    return pl.pallas_call(
        kernel,
        grid=(1,),
        out_shape=(block, block, sel_block),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        scratch_shapes=scratch_shapes,
        input_output_aliases={0: 0, 1: 1, 2: 2},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            has_side_effects=True,
            collective_id=collective_id,
        ),
        interpret=interpret,
    )
