"""Kernel-initiated halo exchange: per-neighbor async remote DMA (TPU).

The TPU analogue of the reference's fastest transport family —
``tx_colocated`` / ``ColocatedDirectAccessSender`` (PAPER.md L5, §5.8):
one GPU writes directly into its neighbor's halo, skipping the MPI
staging entirely. Here the staging being skipped is the XLA collective
path: instead of handing boundary slabs to ``lax.ppermute`` (one ~0.66 ms
dispatch per collective on the recorded CPU-mesh economics, and the
round-7/10 censuses showed per-collective overhead — not bytes —
dominates this stack), the carrier kernel below issues
``pltpu.make_async_remote_copy`` from INSIDE the kernel, so a compiled
``Method.REMOTE_DMA`` exchange contains ZERO collective-permutes.

Per axis phase (the composed x→y→z slab geometry, straight from the
plan's ``RemoteDmaPhaseIR``), every device runs the same kernel:

1. barrier with its two ring neighbors (their landing buffers must be
   quiescent before anyone writes into them);
2. stage its outbound boundary slabs into VMEM and START the remote
   copies toward both neighbors — boundary-first: the sends are in
   flight before anything else runs, so interior compute scheduled
   around the kernel overlaps the wire time;
3. wait the inbound copies and write the received slabs into its own
   halo (``input_output_aliases`` — the in-place unpack of the
   reference's peer-access writes).

The packed ``(Q, …slab)`` carrier is PR-5's per-dtype batching: the DMA
count per exchange is Q-independent (≤ 2 per phase per dtype group).
``wire_dtype`` (bf16-on-the-wire) narrows the staged carrier before the
send and widens on unpack — only wire-crossing bytes pay precision.

This container has no TPU (jax 0.4.37, no Pallas cross-device interpret
mode), so this module is exercised on hardware via
``scripts/probe_remote_dma.py``; the CPU emulation
(``parallel/remote_emu.py``) pins the semantics bit-identically to
AXIS_COMPOSED, and the plan-level claims (0 ppermutes, wire bytes) are
pinned against the emulation's census in tests/test_remote_dma.py.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.halo_fill import wire_narrow_dtype


def remote_kernel_supported(spec, resident) -> bool:
    """What the carrier kernel handles: one resident block per device.
    Uniform AND uneven (remainder) partitions are supported — on an
    uneven ring the slab extents (rm/rp × full padded orthogonals) are
    identical across participants and only the hi-side slab's start
    offset varies, so the kernel reads it from the static per-ring size
    table at its own ``axis_index`` (the same size-table discipline as
    the dynamic overlap shells). Oversubscribed REMOTE_DMA stays with
    the CPU emulation's geometry until a hardware session extends the
    kernel — loud infeasibility, never a silent fallback."""
    from ..geometry import Dim3

    return resident == Dim3(1, 1, 1)


def make_remote_axis_kernel(spec, phase, nq: int, dtype,
                            wire_dtype: Optional[str] = None,
                            collective_id: int = 0):
    """Build the per-phase carrier kernel: ``fn(*blocks) -> blocks`` over
    ``nq`` same-dtype (pz, py, px) padded blocks inside ``shard_map``,
    delivering both boundary slabs of one axis phase via remote DMA.
    ``phase`` is the plan's RemoteDmaPhaseIR; ``phase.ring > 1`` required
    (self-wrap phases are pure local copies — no DMA to issue)."""
    if not (phase.ring > 1 and phase.active):
        raise ValueError(
            "remote axis kernel needs a multi-device active phase "
            "(self-wrap phases are pure local copies — no DMA to issue)"
        )
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    rm, rp, off = phase.rm, phase.rp, phase.offset
    # uneven rings share every slab EXTENT (rm/rp x full padded
    # orthogonals); only the hi-side start offset depends on this
    # device's block size, read from the static size table in-kernel
    uniform = phase.uniform
    sz = phase.sizes[0]
    sizes_tbl = phase.sizes  # static per-ring ints from the plan IR
    axis = phase.axis
    # slab shapes (z, y, x) with the phase axis narrowed to the radius
    def slab_shape(r):
        return {
            "x": (nq, pz, py, r),
            "y": (nq, pz, r, px),
            "z": (nq, r, py, px),
        }[axis]

    # data-dim index of the phase axis within a (pz, py, px) block
    ddim = {"z": 0, "y": 1, "x": 2}[axis]
    wire = wire_narrow_dtype(dtype, wire_dtype)
    wdt = wire if wire is not None else dtype

    def dslice(start, width):
        idx = [slice(None)] * 3
        idx[ddim] = pl.ds(start, width)
        return tuple(idx)

    def kernel(*refs):
        ins = refs[:nq]
        outs = refs[nq: 2 * nq]
        (comm_lo, comm_hi, send_lo, send_hi, stage_rm, stage_rp,
         send_sems, recv_sems, copy_sem) = refs[2 * nq:]
        my = lax.axis_index(axis)
        m = phase.ring
        fwd = (my + 1) % m
        bwd = (my - 1 + m) % m
        sz_my = (sz if uniform
                 else jnp.asarray(sizes_tbl, jnp.int32)[my])

        def stage_in(src_ref, sl, dst_buf, stage, q):
            """HBM slab -> wire-dtype VMEM staging. A DMA cannot cast,
            so the compression path round-trips through a native-dtype
            staging buffer (sized per SIDE — rm and rp slabs differ
            under asymmetric radii) and casts vector-side."""
            if wire is None:
                cp = pltpu.make_async_copy(src_ref.at[sl], dst_buf.at[q],
                                           copy_sem)
                cp.start()
                cp.wait()
            else:
                cp = pltpu.make_async_copy(src_ref.at[sl], stage.at[q],
                                           copy_sem)
                cp.start()
                cp.wait()
                dst_buf[q] = stage[q].astype(wdt)

        def stage_out(src_buf, stage, q, dst_ref, sl):
            """Wire-dtype VMEM landing -> HBM halo (widen on unpack)."""
            if wire is None:
                cp = pltpu.make_async_copy(src_buf.at[q], dst_ref.at[sl],
                                           copy_sem)
                cp.start()
                cp.wait()
            else:
                stage[q] = src_buf[q].astype(dtype)
                cp = pltpu.make_async_copy(stage.at[q], dst_ref.at[sl],
                                           copy_sem)
                cp.start()
                cp.wait()

        # 1. neighbor barrier: both landing buffers quiescent
        barrier = pltpu.get_barrier_semaphore()
        for nbr in (fwd, bwd):
            pltpu.semaphore_signal(
                barrier, inc=1, device_id={axis: nbr},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        pltpu.semaphore_wait(barrier, 2)

        # 2. stage + SEND, boundary-first: both remote copies are in
        # flight before any local work below
        rdmas = []
        if rm:
            for q in range(nq):
                stage_in(ins[q], dslice(off + sz_my - rm, rm), send_hi,
                         stage_rm, q)
            rdma = pltpu.make_async_remote_copy(
                src_ref=send_hi, dst_ref=comm_lo,
                send_sem=send_sems.at[0], recv_sem=recv_sems.at[0],
                device_id={axis: fwd},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rdma.start()
            rdmas.append(rdma)
        if rp:
            for q in range(nq):
                stage_in(ins[q], dslice(off, rp), send_lo, stage_rp, q)
            rdma = pltpu.make_async_remote_copy(
                src_ref=send_lo, dst_ref=comm_hi,
                send_sem=send_sems.at[1], recv_sem=recv_sems.at[1],
                device_id={axis: bwd},
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rdma.start()
            rdmas.append(rdma)

        # 3. wait + unpack into the halos (in place)
        for rdma in rdmas:
            rdma.wait()
        if rm:
            for q in range(nq):
                stage_out(comm_lo, stage_rm, q, outs[q],
                          dslice(off - rm, rm))
        if rp:
            for q in range(nq):
                stage_out(comm_hi, stage_rp, q, outs[q],
                          dslice(off + sz_my, rp))

    block = jax.ShapeDtypeStruct((pz, py, px), dtype)
    return pl.pallas_call(
        kernel,
        grid=(1,),
        out_shape=(block,) * nq,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
        scratch_shapes=[
            # packed (Q, …slab) carriers: landing buffers (what the
            # neighbors' remote copies write) and send staging; the
            # native cast-staging buffers are PER SIDE — rm and rp slab
            # shapes differ under asymmetric radii, and a DMA requires
            # identical src/dst shapes
            pltpu.VMEM(slab_shape(max(rm, 1)), wdt),   # comm_lo landing
            pltpu.VMEM(slab_shape(max(rp, 1)), wdt),   # comm_hi landing
            pltpu.VMEM(slab_shape(max(rp, 1)), wdt),   # send_lo staging
            pltpu.VMEM(slab_shape(max(rm, 1)), wdt),   # send_hi staging
            pltpu.VMEM(slab_shape(max(rm, 1)), dtype),  # rm cast staging
            pltpu.VMEM(slab_shape(max(rp, 1)), dtype),  # rp cast staging
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={q: q for q in range(nq)},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            has_side_effects=True,
            collective_id=collective_id,
        ),
    )


class RemoteDmaExchange:
    """The all-TPU REMOTE_DMA transport of one :class:`HaloExchange`:
    a jitted ``shard_map`` program whose wire movement is carrier
    kernels (above) on ring phases and plain local slab copies on
    self-wrap phases — no ``lax.ppermute`` anywhere, so the compiled
    census reads 0 collective-permutes (the same pin the CPU emulation
    carries)."""

    def __init__(self, ex):
        from ..parallel.mesh import BLOCK_PSPEC

        if not remote_kernel_supported(ex.spec, ex.resident):
            raise ValueError(
                "Method.REMOTE_DMA's TPU carrier kernel supports uniform "
                "single-resident partitions today (uneven/oversubscribed "
                "REMOTE_DMA is staged for a hardware session; use "
                "AXIS_COMPOSED there)"
            )
        self.ex = ex
        self._pspec = BLOCK_PSPEC
        self._kernels = {}

    def _phase_kernel(self, phase, nq, dtype, cid):
        key = (phase.axis, nq, str(jnp.dtype(dtype)))
        if key not in self._kernels:
            self._kernels[key] = make_remote_axis_kernel(
                self.ex.spec, phase, nq, dtype,
                wire_dtype=self.ex.wire_dtype, collective_id=cid,
            )
        return self._kernels[key]

    def _blocks_body(self, state):
        """Per-block body (inside shard_map): composed x→y→z phase
        order, each phase's wire movement a remote-DMA kernel call."""
        from ..ops.halo_fill import dtype_groups

        ex = self.ex
        p = ex.spec.padded()
        if not isinstance(state, dict):
            state = {0: state}
            unwrap = True
        else:
            unwrap = False
        out = dict(state)
        # per-dtype packed carriers (PR-5 geometry, Q-independent DMA
        # count); with batching off, each quantity is its own carrier —
        # the per-quantity baseline the plan's dmas_per_exchange models
        # and the CPU emulation mirrors
        if ex.batch_quantities:
            groups = dtype_groups(out)
        else:
            groups = [(out[k].dtype, [k]) for k in out]
        for cid, (rphase, aphase) in enumerate(
                zip(ex.plan.remote_phases, ex.plan.axis_phases)):
            if not rphase.active:
                continue
            for dt, keys in groups:
                if rphase.ring <= 1:
                    # self-wrap: pure local slab copy — the composed
                    # batched body at n == 1 IS that program (no permute)
                    blocks = ex._axis_phase_batched(
                        [out[k] for k in keys], aphase)
                else:
                    kern = self._phase_kernel(rphase, len(keys), dt, cid)
                    shaped = [out[k].reshape(p.z, p.y, p.x) for k in keys]
                    res = kern(*shaped)
                    # a tuple out_shape comes back as a tuple even at
                    # length 1 — wrap only a bare array, never double-wrap
                    if not isinstance(res, (tuple, list)):
                        res = (res,)
                    blocks = [r.reshape(out[k].shape)
                              for r, k in zip(res, keys)]
                for k, b in zip(keys, blocks):
                    out[k] = b
        return out[0] if unwrap else out

    def __call__(self, state):
        return self._compiled(state)

    @property
    def _compiled(self):
        if "_compiled_fn" not in self.__dict__:
            fn = jax.shard_map(
                self._blocks_body, mesh=self.ex.mesh,
                in_specs=self._pspec, out_specs=self._pspec,
            )
            self.__dict__["_compiled_fn"] = jax.jit(fn, donate_argnums=0)
        return self.__dict__["_compiled_fn"]

    def make_loop(self, iters: int):
        def many(state):
            return lax.fori_loop(
                0, iters, lambda _, s: self._blocks_body(s), state)

        fn = jax.shard_map(many, mesh=self.ex.mesh,
                           in_specs=self._pspec, out_specs=self._pspec)
        return jax.jit(fn, donate_argnums=0)

    def collective_census(self, state):
        from ..utils.hlo_check import collective_census

        txt = self._compiled.lower(state).compile().as_text()
        return collective_census(txt)
