"""Pallas TPU kernel for the Astaroth RK3 substep (all 8 fields).

XLA's codegen for the unfused substep materializes the shifted-slice
operands of 60+ derivative pencils in HBM (measured ~266 ms per 256^3 fp32
substep triple on v5e, vs a ~5 GB/substep traffic roofline of ~6 ms). This
kernel streams (tz, ty)-row slabs of all 8 fields HBM->VMEM with
double-buffered DMA (the pipeline structure of ops/pallas_stencil.py),
evaluates every derivative and the four MHD right-hand sides entirely in
VMEM, applies the Williamson RK3 stage update, and streams finished tiles
back.

The math is NOT duplicated: derivative pencils come from
``astaroth.fd.field_data`` and the physics from ``astaroth.equations`` —
the same functions the XLA path executes — applied to VMEM refs through a
slab-local view adapter. Parity between the two paths is therefore
structural (pinned by tests/test_pallas_astaroth.py in interpret mode).

Layout contract: padded fp32 blocks with TPU-aligned planes
(GridSpec(aligned=True)), face radii >= 3, exchanged halos (including the
xy/yz/xz edge halos the cross-derivatives read — AXIS_COMPOSED phase
composition provides them). The kernel writes compute rows only: out's
x-halo columns in written rows carry the curr value (refreshed by the next
exchange before any read), y/z halo rows/planes keep their prior contents.

Buffering: ``in_v`` is double-buffered (tile t+1's field slabs load during
tile t's compute). ``out_v`` is TRIPLE-buffered because three parties touch
a slot: the out-read DMA of tile t (prefetched at t-1, substep > 0), the
compute of tile t, and the write-back of tile t which drains while tiles
t+1/t+2 proceed; slot t%3 is safe to reload once the write-back of tile
t-3 has drained (waited in the prefetch path).

Reference parity: the fused integrate of astaroth/kernels.cu:62-87
(``solve<step>`` over the full subdomain) with the block-size autotuning of
astaroth/integration.cuh:130-215 replaced by the VMEM-budget tile pick.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..domain.grid import GridSpec
from ..geometry import Rect3, Dim3
from ..astaroth.fd import field_data
from ..astaroth.equations import Constants, continuity, entropy, induction, momentum

FIELDS = ("lnrho", "uux", "uuy", "uuz", "ax", "ay", "az", "entropy")
NF = len(FIELDS)

# Williamson (1980) low-storage coefficients (reference: integration.cuh:19-21)
RK3_ALPHA = (0.0, -5.0 / 9.0, -153.0 / 128.0)
RK3_BETA = (1.0 / 3.0, 15.0 / 16.0, 8.0 / 15.0)

# VMEM budget for the explicit scratch buffers (v5e-measured: ~34 MB of
# scratch still compiles, ~45 MB does not once Mosaic's expression
# temporaries for the tile DAG are added; 22 MB leaves solid headroom).
_SCRATCH_BUDGET = 22 * 1024 * 1024
_HALO = 3  # 6th-order stencils, fixed (reference: astaroth.h STENCIL_ORDER 6)


def _divisors(n: int, cands) -> list:
    return [c for c in cands if c <= n and n % c == 0]


def pick_tiles(spec: GridSpec) -> Tuple[int, int]:
    """(tz, ty) under the scratch budget (the autotuner analogue,
    integration.cuh:130-215). Wide-y tiles measured fastest on v5e (the
    derivative pencils' sublane rotates amortize over more rows):
    256^3 sweep gave (2,64) 18.3 ms vs (4,8) 25.6 ms per substep — so the
    key prefers the largest ty, then the smallest slab read
    amplification."""
    p = spec.padded()
    nz, ny = spec.base.z, spec.base.y
    best = None
    for tz in _divisors(nz, (16, 12, 8, 6, 4, 3, 2, 1)):
        for ty in _divisors(ny, (64, 48, 32, 24, 16, 8)):
            in_bytes = 2 * NF * (tz + 2 * _HALO) * (ty + 16) * p.x * 4
            out_bytes = 3 * NF * tz * ty * p.x * 4
            if in_bytes + out_bytes > _SCRATCH_BUDGET:
                continue
            amp = ((tz + 2 * _HALO) * (ty + 16)) / (tz * ty)
            key = (-min(ty, 64), amp, -(tz * ty))
            if best is None or key < best[0]:
                best = (key, (tz, ty))
    return best[1] if best else (0, 0)


def substep_supported(spec: GridSpec, dtype) -> bool:
    """Whether the fused kernel handles this block layout."""
    if not spec.aligned or dtype != jnp.float32:
        return False
    r = spec.radius
    if min(r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1)) < _HALO:
        return False
    o = spec.compute_offset()
    p = spec.padded()
    b = spec.base
    if b.y % 8 or o.y % 8 or o.y < 8 or o.y + b.y + 8 > p.y:
        return False
    if o.z < _HALO or o.z + b.z + _HALO > p.z:
        return False
    if o.x < _HALO or o.x + b.x + _HALO > p.x:
        return False
    return pick_tiles(spec) != (0, 0)


class _SlabView:
    """Adapter letting fd.field_data slice a (slot, field) slab of the VMEM
    scratch ref as if it were a plain [z, y, x] array."""

    __slots__ = ("ref", "pre")

    def __init__(self, ref, pre):
        self.ref = ref
        self.pre = pre

    def __getitem__(self, idx):
        assert isinstance(idx, tuple) and idx[0] is Ellipsis, idx
        return self.ref[self.pre + idx[1:]]


def make_pallas_substep(
    spec: GridSpec,
    c: Constants,
    inv_ds: Sequence[float],
    substep: int,
    dt: float,
    interpret: bool = False,
    vma=None,
    tiles: Tuple[int, int] = None,
):
    """Build ``fn(curr8, out8) -> out8`` over padded (pz, py, px) fp32
    blocks: one RK3 stage for all fields, out buffers updated in place.

    ``curr8``/``out8`` are tuples ordered like :data:`FIELDS`."""
    assert substep_supported(spec, jnp.float32)
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    off = spec.compute_offset()
    zo, yo, xo = off.z, off.y, off.x
    nz, ny, nx = spec.base.z, spec.base.y, spec.base.x
    tz, ty = tiles if tiles is not None else pick_tiles(spec)
    assert tz >= 1 and nz % tz == 0 and ny % ty == 0 and ty % 8 == 0, (tz, ty)
    n_tz, n_ty = nz // tz, ny // ty
    n_tiles = n_tz * n_ty
    rows_in = ty + 16  # y window [y0-8, y0+ty+8): +-3 halo rows, 8-aligned
    H = _HALO
    beta = RK3_BETA[substep]
    alpha_over_pb = RK3_ALPHA[substep] / RK3_BETA[substep - 1] if substep else 0.0
    ids = tuple(float(v) for v in inv_ds)
    # slab-local region the rates are produced over
    rect = Rect3(Dim3(xo, 8, H), Dim3(xo + nx, 8 + ty, H + tz))
    xs = slice(xo, xo + nx)

    def kernel(*refs):
        curr_hbm = refs[:NF]
        oin_hbm = refs[NF : 2 * NF]
        out_hbm = refs[2 * NF : 3 * NF]
        in_v, out_v, s_in, s_oin, s_out = refs[3 * NF :]
        t = pl.program_id(0)
        slot = t % 2  # in_v slot
        s3 = t % 3  # out_v slot
        n3 = (t + 1) % 3

        def tile_zy(ti):
            return zo + (ti // n_ty) * tz, yo + (ti % n_ty) * ty

        def in_dma(s, ti, f):
            z0, y0 = tile_zy(ti)
            return pltpu.make_async_copy(
                curr_hbm[f].at[pl.ds(z0 - H, tz + 2 * H), pl.ds(y0 - 8, rows_in)],
                in_v.at[s, f],
                s_in.at[s],
            )

        def oin_dma(s, ti, f):
            z0, y0 = tile_zy(ti)
            return pltpu.make_async_copy(
                oin_hbm[f].at[pl.ds(z0, tz), pl.ds(y0, ty)],
                out_v.at[s, f],
                s_oin.at[s],
            )

        def out_dma(s, ti, f):
            z0, y0 = tile_zy(ti)
            return pltpu.make_async_copy(
                out_v.at[s, f],
                out_hbm[f].at[pl.ds(z0, tz), pl.ds(y0, ty)],
                s_out.at[s],
            )

        def start_in(s, ti):
            for f in range(NF):
                in_dma(s, ti, f).start()

        def start_oin(s, ti):
            if substep:
                for f in range(NF):
                    oin_dma(s, ti, f).start()

        # pipeline: tile t+1's loads overlap tile t's compute
        @pl.when(t == 0)
        def _():
            start_in(slot, t)
            start_oin(s3, t)

        @pl.when(t + 1 < n_tiles)
        def _():
            start_in((t + 1) % 2, t + 1)
            if substep:
                # out_v[(t+1)%3] was the write-back source of tile t-2
                # ((t+1) - 3); that store must drain before reloading
                @pl.when(t >= 2)
                def _():
                    for f in range(NF):
                        out_dma(n3, t - 2, f).wait()

                for f in range(NF):
                    oin_dma(n3, t + 1, f).start()

        for f in range(NF):
            in_dma(slot, t, f).wait()
        if substep:
            for f in range(NF):
                oin_dma(s3, t, f).wait()
        else:
            # no oin reload: compute itself reuses out_v[t%3], last drained
            # as tile t-3's write-back source
            @pl.when(t >= 3)
            def _():
                for f in range(NF):
                    out_dma(s3, t - 3, f).wait()

        # derivatives + physics over the tile, via the shared fd/equations
        # implementation (reference: solve<step>, user_kernels.h:437-469)
        fds = [field_data(_SlabView(in_v, (slot, f)), rect, ids) for f in range(NF)]
        lnrho, uux, uuy, uuz, ax, ay, az, ss = fds
        uu = (uux, uuy, uuz)
        aa = (ax, ay, az)
        rates = [None] * NF
        rates[0] = continuity(uu, lnrho)
        mom = momentum(c, uu, lnrho, ss, aa)
        ind = induction(c, uu, aa)
        rates[1], rates[2], rates[3] = mom
        rates[4], rates[5], rates[6] = ind
        rates[7] = entropy(c, ss, uu, lnrho, aa)

        for f in range(NF):
            curr_c = in_v[slot, f, H : H + tz, 8 : 8 + ty, :]
            if substep:
                old = out_v[s3, f, :, :, xs]
                new = curr_c[:, :, xs] + beta * (
                    alpha_over_pb * (curr_c[:, :, xs] - old) + rates[f] * dt
                )
            else:
                new = curr_c[:, :, xs] + beta * dt * rates[f]
            # non-compute columns carry curr so the store covers whole rows
            out_v[s3, f] = curr_c
            out_v[s3, f, :, :, xs] = new

        for f in range(NF):
            out_dma(s3, t, f).start()

        # final drain: write-backs of tiles t-2, t-1, t are still pending
        # (earlier ones were waited in the prefetch / pre-compute paths)
        @pl.when(t == n_tiles - 1)
        def _():
            for f in range(NF):
                if n_tiles >= 3:
                    out_dma((t - 2) % 3, t - 2, f).wait()
                if n_tiles >= 2:
                    out_dma((t - 1) % 3, t - 1, f).wait()
                out_dma(s3, t, f).wait()

    shape = jax.ShapeDtypeStruct(
        (pz, py, px), jnp.float32, vma=frozenset(vma) if vma is not None else None
    )
    fn = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        out_shape=(shape,) * NF,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (2 * NF),
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * NF,
        scratch_shapes=[
            pltpu.VMEM((2, NF, tz + 2 * H, rows_in, px), jnp.float32),
            pltpu.VMEM((3, NF, tz, ty, px), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        input_output_aliases={NF + f: f for f in range(NF)},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            has_side_effects=True,
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    def apply(curr8, out8):
        return fn(*curr8, *out8)

    return apply
