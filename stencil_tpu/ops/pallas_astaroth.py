"""Pallas TPU kernel for the Astaroth RK3 substep (all 8 fields).

XLA's codegen for the unfused substep materializes the shifted-slice
operands of 60+ derivative pencils in HBM (measured ~266 ms per 256^3 fp32
substep triple on v5e, vs a ~5 GB/substep traffic roofline of ~6 ms). This
kernel walks each (ty)-row strip of the block in z with a **sliding window
of field planes held in VMEM**: per z-tile only the ``tz`` fresh planes are
fetched from HBM (prefetched into a parity-double-buffered stage while the
previous tile computes), the window shifts down in VMEM, and every
derivative and the four MHD right-hand sides are evaluated entirely in
VMEM before the Williamson RK3 stage update streams finished tiles back.

The round-2 version re-fetched the full (tz + 6)-plane halo slab per tile,
a (tz+6)/tz = 4x z-read amplification at the VMEM-forced tz=2 (measured
18.3 ms/substep at 256^3 against a ~7 ms traffic roofline). The sliding
window reads each input plane once per strip, so z-amplification falls to
(nz+6)/nz; the remaining input amplification is the 8-row-aligned y
window ((ty+16)/ty) times the x lane padding px/nx — which the tight-x
layout (Radius.without_x: px == nx, x pencils via lane rolls) reduces
to 1.

The math is NOT duplicated: derivative pencils come from
``astaroth.fd.field_data`` and the physics from ``astaroth.equations`` —
the same functions the XLA path executes — applied to VMEM refs through a
window-local view adapter. Parity between the two paths is therefore
structural (pinned by tests/test_pallas_astaroth.py in interpret mode).

Layout contract: padded fp32 blocks with TPU-aligned planes
(GridSpec(aligned=True)), face radii >= 3, exchanged halos (including the
xy/yz/xz edge halos the cross-derivatives read — AXIS_COMPOSED phase
composition provides them). The kernel writes compute cells only: out's
halo columns/rows/planes keep their prior contents (refreshed by the next
exchange before any read).

Window discipline — two selectable variants (``variant=``):

- ``"shift"`` (the round-3 kernel): the window is kept physically ordered
  in VMEM; every non-strip-start tile copies the 2*H halo planes down
  (``win[f, 0:2H] = win[f, tz:tz+2H]``) before appending the fresh planes.
- ``"ring"``: shift-free modular-slot rotation — the same math the jacobi
  multistep uses for its plane slots (ops/pallas_stencil.py). Window plane
  j of tile zi lives at physical slot ``(zi*tz + j) % W``; the append
  stores the fresh planes into the recycled slots (planes tile zi-1 read
  last — the lag-1 rule holds trivially for in-body VMEM stores) and the
  compute reads per-plane at dynamic slots, reassembled by concatenation.
  Eliminates NF*2H plane copies per tile at the price of dynamic-index
  addressing; built to settle the round-5 floor contradiction (the
  12.7 ms standalone window-shift leg vs the 0.4 ms in-situ probe —
  VERDICT r5 weak #1, scripts/probe_ring_substep.py is the on-chip A/B).

Buffering discipline (the documented lag-1 rule: a DMA started at grid
step t may write a buffer last touched by compute at step t-1, never one
step t itself reads):

- ``win`` (single buffer, per strip): the strip-start DMA filling it is
  issued at the strip's first tile, one step after the previous strip's
  last compute read it.
- ``stage`` (2 slots by z-tile parity): tile zi's compute consumes slot
  zi%2 while the DMA for tile zi+1 fills slot (zi+1)%2.
- ``out_v`` (3 slots): the out-read DMA of tile t (prefetched at t-1,
  substep > 0), the compute of tile t, and the write-back of tile t which
  drains while tiles t+1/t+2 proceed; slot t%3 is safe to reload once the
  write-back of tile t-3 has drained.

Reference parity: the fused integrate of astaroth/kernels.cu:62-87
(``solve<step>`` over the full subdomain) with the block-size autotuning of
astaroth/integration.cuh:130-215 replaced by the VMEM-budget tile pick.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..domain.grid import GridSpec
from ..geometry import Rect3, Dim3
from ..astaroth.fd import field_data
from ..astaroth.equations import Constants, continuity, entropy, induction, momentum

FIELDS = ("lnrho", "uux", "uuy", "uuz", "ax", "ay", "az", "entropy")
NF = len(FIELDS)

# Williamson (1980) low-storage coefficients (reference: integration.cuh:19-21)
RK3_ALPHA = (0.0, -5.0 / 9.0, -153.0 / 128.0)
RK3_BETA = (1.0 / 3.0, 15.0 / 16.0, 8.0 / 15.0)

# VMEM budget for the explicit scratch buffers (v5e-measured: ~34 MB of
# scratch still compiles, ~45 MB does not once Mosaic's expression
# temporaries for the tile DAG are added; see scripts/probe_r03.py).
_SCRATCH_BUDGET = 22 * 1024 * 1024
_HALO = 3  # 6th-order stencils, fixed (reference: astaroth.h STENCIL_ORDER 6)


def _divisors(n: int, cands) -> list:
    return [c for c in cands if c <= n and n % c == 0]


def scratch_bytes(spec: GridSpec, tz: int, ty: int) -> int:
    """Explicit VMEM scratch of the sliding-window substep at (tz, ty):
    all buffers carry full px-wide rows (px == nx under the tight-x
    layout, px == round_up(nx + 6, 128) inline) — exactly the
    ``scratch_shapes`` allocation."""
    px = spec.padded().x
    rows_in = ty + 16
    win = NF * (tz + 2 * _HALO) * rows_in * px
    stage = 2 * NF * tz * rows_in * px
    out = 3 * NF * tz * ty * px
    return 4 * (win + stage + out)


def pick_tiles(spec: GridSpec) -> Tuple[int, int]:
    """(tz, ty) under the scratch budget (the autotuner analogue,
    integration.cuh:130-215). Input amplification is (ty+16)/ty — z reads
    are amortized by the sliding window — so the key prefers the largest
    ty, then the largest tz (fewer tiles: fewer DMA descriptors and less
    window-shift work per output plane)."""
    nz, ny = spec.base.z, spec.base.y
    best = None
    for tz in _divisors(nz, (16, 12, 8, 6, 4, 3, 2, 1)):
        for ty in _divisors(ny, (128, 96, 64, 48, 32, 24, 16, 8)):
            if scratch_bytes(spec, tz, ty) > _SCRATCH_BUDGET:
                continue
            key = (-ty, -tz)
            if best is None or key < best[0]:
                best = (key, (tz, ty))
    return best[1] if best else (0, 0)


def substep_supported(spec: GridSpec, dtype) -> bool:
    """Whether the fused kernel handles this block layout. The tight-x
    layout (Radius.without_x: zero x radius, no halo columns) is supported
    on a single-block lane-aligned x axis — x pencils become lane rolls."""
    if not spec.aligned or dtype != jnp.float32:
        return False
    r = spec.radius
    if min(r.y(-1), r.y(1), r.z(-1), r.z(1)) < _HALO:
        return False
    o = spec.compute_offset()
    p = spec.padded()
    b = spec.base
    if b.y % 8 or o.y % 8 or o.y < 8 or o.y + b.y + 8 > p.y:
        return False
    if o.z < _HALO or o.z + b.z + _HALO > p.z:
        return False
    if r.x(-1) == 0 and r.x(1) == 0:
        if spec.dim.x != 1 or b.x % 128 or o.x != 0:
            return False
    elif min(r.x(-1), r.x(1)) < _HALO:
        return False
    elif o.x < _HALO or o.x + b.x + _HALO > p.x:
        return False
    return pick_tiles(spec) != (0, 0)


class _SlabView:
    """Adapter letting fd.field_data slice a field's plane window of the
    VMEM scratch ref as if it were a plain [z, y, x] array.

    ``wrap_nx``: tight-x layout — the window carries exactly nx columns
    with no halos, and x-shifted pencil reads become in-VMEM lane rolls
    (out[j] = base[(j + dx) mod nx], the periodic neighborhood).

    ``zmap``: ring-indexed window — maps a logical window plane j to its
    (traced) physical slot. Slices over z are then read plane-by-plane at
    dynamic slots and reassembled by concatenation (the slot math of the
    jacobi multistep, ops/pallas_stencil.py)."""

    __slots__ = ("ref", "pre", "wrap_nx", "zmap")

    def __init__(self, ref, pre, wrap_nx=None, zmap=None):
        self.ref = ref
        self.pre = pre
        self.wrap_nx = wrap_nx
        self.zmap = zmap

    def _read(self, zidx, ysl, xsl):
        nx = self.wrap_nx
        if nx is not None:
            dx = xsl.start  # tight layout: xsl == slice(dx, nx + dx)
            assert xsl.stop - dx == nx, (xsl, nx)
            if dx != 0:
                base = self.ref[self.pre + (zidx, ysl, slice(0, nx))]
                return pltpu.roll(base, (-dx) % nx, 2)
        return self.ref[self.pre + (zidx, ysl, xsl)]

    def __getitem__(self, idx):
        assert isinstance(idx, tuple) and idx[0] is Ellipsis, idx
        zsl, ysl, xsl = idx[1:]
        if self.zmap is None:
            return self._read(zsl, ysl, xsl)
        parts = [
            self._read(pl.ds(self.zmap(j), 1), ysl, xsl)
            for j in range(zsl.start, zsl.stop)
        ]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, 0)


def make_pallas_substep(
    spec: GridSpec,
    c: Constants,
    inv_ds: Sequence[float],
    substep: int,
    dt: float,
    interpret: bool = False,
    vma=None,
    tiles: Tuple[int, int] = None,
    _skip_shift: bool = False,  # timing probe only: wrong results
    variant: str = "shift",
):
    """Build ``fn(curr8, out8) -> out8`` over padded (pz, py, px) fp32
    blocks: one RK3 stage for all fields, out buffers updated in place.

    ``curr8``/``out8`` are tuples ordered like :data:`FIELDS`.
    ``variant``: ``"shift"`` (plane-copy window shifts) or ``"ring"``
    (shift-free modular-slot rotation) — see the module docstring."""
    if not substep_supported(spec, jnp.float32):
        raise ValueError("pallas astaroth substep unsupported on this spec")
    if variant not in ("shift", "ring"):
        raise ValueError(f"unknown substep variant {variant!r}")
    ring = variant == "ring"
    if ring and _skip_shift:
        raise ValueError("_skip_shift probes the shift variant")
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    off = spec.compute_offset()
    zo, yo, xo = off.z, off.y, off.x
    nz, ny, nx = spec.base.z, spec.base.y, spec.base.x
    tz, ty = tiles if tiles is not None else pick_tiles(spec)
    if not (tz >= 1 and nz % tz == 0 and ny % ty == 0 and ty % 8 == 0):
        raise ValueError(
            f"tile sizes ({tz}, {ty}) must divide block "
            f"({nz}, {ny}) with ty a multiple of 8"
        )
    n_tz, n_ty = nz // tz, ny // ty
    n_tiles = n_tz * n_ty
    rows_in = ty + 16  # y window [y0-8, y0+ty+8): +-3 halo rows, 8-aligned
    H = _HALO
    W = tz + 2 * H  # window planes per field
    # tight-x layout (Radius.without_x, single-block x): px == nx, off.x
    # == 0, no x halo columns exist — slabs are full rows with zero lane
    # padding and the periodic x pencils come from in-VMEM lane rolls
    # (Mosaic requires DMA x-slice offsets AND widths to be 128-aligned,
    # so slicing an inline-halo layout tighter is not expressible; the
    # layout change is)
    tight_x = spec.radius.x(-1) == 0 and spec.radius.x(1) == 0
    beta = RK3_BETA[substep]
    alpha_over_pb = RK3_ALPHA[substep] / RK3_BETA[substep - 1] if substep else 0.0
    ids = tuple(float(v) for v in inv_ds)
    # window-local region the rates are produced over
    rect = Rect3(Dim3(xo, 8, H), Dim3(xo + nx, 8 + ty, H + tz))
    wxs = slice(xo, xo + nx)  # compute columns within a window row

    def kernel(*refs):
        curr_hbm = refs[:NF]
        oin_hbm = refs[NF : 2 * NF]
        out_hbm = refs[2 * NF : 3 * NF]
        win, stage, out_v, s_win, s_stage, s_oin, s_out = refs[3 * NF :]
        yi = pl.program_id(0)
        zi = pl.program_id(1)
        t = yi * n_tz + zi
        s3 = t % 3  # out_v slot
        n3 = (t + 1) % 3
        y0 = yo + yi * ty
        z0 = zo + zi * tz
        # ring variant: logical window plane j of tile zi lives at physical
        # slot (zi*tz + j) % W; a strip start (zi == 0) is offset 0, so the
        # full-window DMA below needs no variant-specific handling
        zmap = (lambda j: jnp.mod(zi * tz + j, W)) if ring else None

        def win_planes(f, j0, ysl, xsl):
            """win[f, j0:j0+tz, ysl, xsl] in logical window order."""
            if not ring:
                return win[f, j0 : j0 + tz, ysl, xsl]
            parts = [
                win[f, pl.ds(zmap(j0 + i), 1), ysl, xsl] for i in range(tz)
            ]
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts, 0)

        def tile_zy(ti):
            return zo + (ti % n_tz) * tz, yo + (ti // n_tz) * ty

        def win_dma(f):
            # full window for a strip's first tile: planes [z0-H, z0+tz+H)
            return pltpu.make_async_copy(
                curr_hbm[f].at[pl.ds(z0 - H, W), pl.ds(y0 - 8, rows_in)],
                win.at[f],
                s_win,
            )

        def stage_dma(sl, znext, f):
            # fresh planes for tile znext of this strip: [z0' + H, z0' + tz + H)
            return pltpu.make_async_copy(
                curr_hbm[f].at[
                    pl.ds(zo + znext * tz + H, tz), pl.ds(y0 - 8, rows_in)
                ],
                stage.at[sl, f],
                s_stage.at[sl],
            )

        def oin_dma(sl, ti, f):
            tz0, ty0 = tile_zy(ti)
            return pltpu.make_async_copy(
                oin_hbm[f].at[pl.ds(tz0, tz), pl.ds(ty0, ty)],
                out_v.at[sl, f],
                s_oin.at[sl],
            )

        def out_dma(sl, ti, f):
            tz0, ty0 = tile_zy(ti)
            return pltpu.make_async_copy(
                out_v.at[sl, f],
                out_hbm[f].at[pl.ds(tz0, tz), pl.ds(ty0, ty)],
                s_out.at[sl],
            )

        # input pipeline: strip starts load the whole window; later tiles
        # consume the stage prefetched during the previous tile
        @pl.when(zi == 0)
        def _():
            for f in range(NF):
                win_dma(f).start()

        @pl.when(zi + 1 < n_tz)
        def _():
            for f in range(NF):
                stage_dma((zi + 1) % 2, zi + 1, f).start()

        # oin prefetch (substep > 0): tile t+1's out-read into slot n3,
        # which requires tile t-2's write-back (same slot) drained
        if substep:
            @pl.when(t == 0)
            def _():
                for f in range(NF):
                    oin_dma(s3, 0, f).start()

            @pl.when(t + 1 < n_tiles)
            def _():
                @pl.when(t >= 2)
                def _():
                    for f in range(NF):
                        out_dma(n3, t - 2, f).wait()

                for f in range(NF):
                    oin_dma(n3, t + 1, f).start()

        @pl.when(zi == 0)
        def _():
            for f in range(NF):
                win_dma(f).wait()

        @pl.when(zi > 0)
        def _():
            for f in range(NF):
                stage_dma(zi % 2, zi, f).wait()
            for f in range(NF):
                if ring:
                    # shift-free: store the fresh planes into the recycled
                    # ring slots (planes tile zi-1 read last)
                    for i in range(tz):
                        win[f, zmap(2 * H + i)] = stage[zi % 2, f, i]
                else:
                    # shift the window down by tz planes, then append the
                    # fresh planes (the RHS loads fully before the store,
                    # so the overlapping ranges are safe)
                    if not _skip_shift:
                        win[f, 0 : 2 * H] = win[f, tz : tz + 2 * H]
                    win[f, 2 * H : 2 * H + tz] = stage[zi % 2, f]

        if substep:
            for f in range(NF):
                oin_dma(s3, t, f).wait()
        else:
            # no oin reload: compute itself reuses out_v[t%3], last drained
            # as tile t-3's write-back source
            @pl.when(t >= 3)
            def _():
                for f in range(NF):
                    out_dma(s3, t - 3, f).wait()

        # derivatives + physics over the tile, via the shared fd/equations
        # implementation (reference: solve<step>, user_kernels.h:437-469)
        fds = [
            field_data(
                _SlabView(
                    win, (f,), wrap_nx=nx if tight_x else None, zmap=zmap
                ),
                rect,
                ids,
            )
            for f in range(NF)
        ]
        lnrho, uux, uuy, uuz, ax, ay, az, ss = fds
        uu = (uux, uuy, uuz)
        aa = (ax, ay, az)
        rates = [None] * NF
        rates[0] = continuity(uu, lnrho)
        mom = momentum(c, uu, lnrho, ss, aa)
        ind = induction(c, uu, aa)
        rates[1], rates[2], rates[3] = mom
        rates[4], rates[5], rates[6] = ind
        rates[7] = entropy(c, ss, uu, lnrho, aa)

        for f in range(NF):
            curr_c = win_planes(f, H, slice(8, 8 + ty), wxs)
            if substep:
                old = out_v[s3, f, :, :, wxs]
                new = curr_c + beta * (
                    alpha_over_pb * (curr_c - old) + rates[f] * dt
                )
            else:
                new = curr_c + beta * dt * rates[f]
            if tight_x:
                out_v[s3, f] = new  # full rows ARE the compute columns
            else:
                # non-compute columns carry curr so the store covers whole
                # aligned rows
                out_v[s3, f] = win_planes(f, H, slice(8, 8 + ty), slice(None))
                out_v[s3, f, :, :, wxs] = new

        for f in range(NF):
            out_dma(s3, t, f).start()

        # final drain: write-backs of tiles t-2, t-1, t are still pending
        # (earlier ones were waited in the prefetch / pre-compute paths)
        @pl.when(t == n_tiles - 1)
        def _():
            for f in range(NF):
                if n_tiles >= 3:
                    out_dma((t - 2) % 3, t - 2, f).wait()
                if n_tiles >= 2:
                    out_dma((t - 1) % 3, t - 1, f).wait()
                out_dma(s3, t, f).wait()

    shape = jax.ShapeDtypeStruct(
        (pz, py, px), jnp.float32, vma=frozenset(vma) if vma is not None else None
    )
    fn = pl.pallas_call(
        kernel,
        grid=(n_ty, n_tz),
        out_shape=(shape,) * NF,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * (2 * NF),
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * NF,
        scratch_shapes=[
            pltpu.VMEM((NF, W, rows_in, px), jnp.float32),
            pltpu.VMEM((2, NF, tz, rows_in, px), jnp.float32),
            pltpu.VMEM((3, NF, tz, ty, px), jnp.float32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        input_output_aliases={NF + f: f for f in range(NF)},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            has_side_effects=True,
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )

    def apply(curr8, out8):
        return fn(*curr8, *out8)

    return apply
