"""Pallas TPU kernel for the 7-point Jacobi sweep.

XLA's codegen for a 3D shifted-slice stencil materializes the shifted
operands (measured ~16 ms per 512^3 fp32 sweep on v5e, vs a ~1.3 ms HBM
roofline). This kernel streams z-plane slabs HBM->VMEM with explicit DMA,
computes the 6-neighbor average entirely in VMEM, and DMAs the finished
planes back — one read + one write of the array per sweep plus a
(TZ+2)/TZ input overlap factor.

Layout contract: padded blocks with TPU-aligned planes
(GridSpec(aligned=True): py % 8 == 0, px % 128 == 0) — slab DMA requires
aligned plane dims. The hot/cold sphere fix-up (reference:
bin/jacobi3d.cu:56-63) reads an int32 ``sel`` array (0 = stencil,
1 = hot, 2 = cold) only for z-tiles that intersect the sphere z-range.

Reference parity: computes exactly what ops/jacobi.jacobi_sweep computes
over the full compute region (kernel equivalence is pinned by tests both in
interpret mode and against the XLA path).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..domain.grid import GridSpec
from ..geometry import Dim3
from .jacobi import COLD_TEMP, HOT_TEMP

# VMEM budget for slabs (of ~16 MB per core, leave room for the compiler)
_VMEM_BUDGET = 11 * 1024 * 1024


def _pick_tz(nz: int, py: int, px: int, itemsize: int = 4) -> int:
    plane = py * px * itemsize
    for tz in (8, 4, 2, 1):
        if nz % tz:
            continue
        need = (tz + 2) * plane + tz * plane + tz * py * px * 4  # in + out + sel
        if need <= _VMEM_BUDGET:
            return tz
    return 1


def make_pallas_jacobi_sweep(
    spec: GridSpec,
    sel_z_range: Tuple[int, int],
    interpret: bool = False,
    vma=None,
    wrap: Tuple[bool, bool, bool] = (False, False, False),
):
    """Build ``sweep(curr, nxt, sel) -> new_next`` over one padded block
    (pz, py, px) fp32, writing the compute region of ``nxt``.

    ``sel_z_range`` is the allocation-local [lo, hi) z-range where ``sel``
    may be nonzero (the spheres' bounding planes); tiles outside skip the
    sel DMA and select entirely.

    ``wrap`` = (wz, wy, wx): axes whose periodic halo the kernel fills
    itself from the opposite side (valid only when that mesh axis has a
    single block — the self-wrap case). This removes the ``ppermute`` +
    halo-materialization pass entirely for those axes; jacobi reads only
    face neighbors, so filling faces (no corners) suffices.
    """
    assert spec.aligned, "pallas sweep requires GridSpec(aligned=True)"
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    r = spec.radius
    zo, yo, xo = r.z(-1), r.y(-1), r.x(-1)
    nz, ny, nx = spec.base.z, spec.base.y, spec.base.x
    tz = _pick_tz(nz, py, px)
    sel_lo, sel_hi = sel_z_range
    wz, wy, wx = wrap

    ys = slice(yo, yo + ny)
    xs = slice(xo, xo + nx)
    n_tiles = nz // tz

    def kernel(curr_hbm, nxt_hbm, sel_hbm, out_hbm, in_v, out_v, sel_v, s_in, s_out, s_sel, s_wrap):
        i = pl.program_id(0)
        z0 = i * tz + zo  # first output plane of this tile
        cp_in = pltpu.make_async_copy(curr_hbm.at[pl.ds(z0 - 1, tz + 2)], in_v, s_in)
        cp_in.start()
        touches_sel = jnp.logical_and(z0 < sel_hi, z0 + tz > sel_lo)

        @pl.when(touches_sel)
        def _():
            cp_sel = pltpu.make_async_copy(sel_hbm.at[pl.ds(z0, tz)], sel_v, s_sel)
            cp_sel.start()
            cp_sel.wait()

        cp_in.wait()
        if wz:
            # first/last tile: overwrite the stale z-halo plane of the slab
            # with the wrapped source plane (after the slab DMA so the two
            # writes to in_v cannot race)
            @pl.when(i == 0)
            def _():
                cpw = pltpu.make_async_copy(
                    curr_hbm.at[pl.ds(zo + nz - 1, 1)], in_v.at[pl.ds(0, 1)], s_wrap
                )
                cpw.start()
                cpw.wait()

            @pl.when(i == n_tiles - 1)
            def _():
                cpw = pltpu.make_async_copy(
                    curr_hbm.at[pl.ds(zo, 1)], in_v.at[pl.ds(tz + 1, 1)], s_wrap
                )
                cpw.start()
                cpw.wait()

        if wy:
            # fill y face halos from the opposite compute rows, in VMEM
            in_v[:, yo - 1, xs] = in_v[:, yo + ny - 1, xs]
            in_v[:, yo + ny, xs] = in_v[:, yo, xs]
        if wx:
            in_v[:, ys, xo - 1] = in_v[:, ys, xo + nx - 1]
            in_v[:, ys, xo + nx] = in_v[:, ys, xo]
        x = in_v[:]
        mid = x[1:-1]
        avg = (
            mid[:, ys, xo - 1 : xo + nx - 1]
            + mid[:, ys, xo + 1 : xo + nx + 1]
            + mid[:, yo - 1 : yo + ny - 1, xs]
            + mid[:, yo + 1 : yo + ny + 1, xs]
            + x[:-2, ys, xs]
            + x[2:, ys, xs]
        ) / 6.0  # divide, not *(1/6): bit-parity with ops.jacobi.jacobi_sweep
        # carry the input's halo/pad ring so the output planes are fully
        # defined, then overwrite the compute window
        out_v[:] = mid

        @pl.when(touches_sel)
        def _():
            sel = sel_v[:, ys, xs]
            out_v[:, ys, xs] = jnp.where(
                sel == 1, HOT_TEMP, jnp.where(sel == 2, COLD_TEMP, avg)
            )

        @pl.when(jnp.logical_not(touches_sel))
        def _():
            out_v[:, ys, xs] = avg

        cp_out = pltpu.make_async_copy(out_v, out_hbm.at[pl.ds(z0, tz)], s_out)
        cp_out.start()
        cp_out.wait()

    grid = (nz // tz,)
    if vma is None:
        out_shape = jax.ShapeDtypeStruct((pz, py, px), jnp.float32)
    else:
        # inside shard_map, declare the output varying over the mesh axes
        out_shape = jax.ShapeDtypeStruct((pz, py, px), jnp.float32, vma=frozenset(vma))
    fn = pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((tz + 2, py, px), jnp.float32),
            pltpu.VMEM((tz, py, px), jnp.float32),
            pltpu.VMEM((tz, py, px), jnp.int32),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={1: 0},  # nxt buffer is updated in place
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            has_side_effects=True,
            # scratch slabs are large; default scoped-vmem limit is 16 MB
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return fn


def sel_z_range(spec: GridSpec) -> Tuple[int, int]:
    """Allocation-local z-range that may contain sphere cells, valid for
    every block (conservative union over blocks): the spheres span global
    z in [zc - R, zc + R] (reference geometry, bin/jacobi3d.cu:44-49)."""
    global_size = spec.global_size
    zc = global_size.z // 2
    R = global_size.x // 10
    zo = spec.radius.z(-1)
    glo, ghi = zc - R, zc + R + 1
    # conservative: if any block covers part of [glo, ghi), its local range
    # is within [zo, zo + base.z); compute the tightest uniform bound
    lo = spec.padded().z
    hi = 0
    for iz in range(spec.dim.z):
        o = sum(spec.sizes_z[:iz])
        s = spec.sizes_z[iz]
        blo = max(glo - o, 0)
        bhi = min(ghi - o, s)
        if blo < bhi:
            lo = min(lo, zo + blo)
            hi = max(hi, zo + bhi)
    if hi <= lo:
        return (0, 0)
    return (lo, hi)
