"""Pallas TPU kernel for the 7-point Jacobi sweep.

XLA's codegen for a 3D shifted-slice stencil materializes the shifted
operands (measured ~16 ms per 512^3 fp32 sweep on v5e, vs a ~1.7 ms HBM
roofline at 819 GB/s). This kernel tiles the block into (tz, ty)-plane-row
slabs, streams them HBM->VMEM with *double-buffered* DMA (tile i+1's loads
overlap tile i's compute — the round-1 kernel serialized DMA and compute
and ran at ~64 GB/s), computes the 6-neighbor average in VMEM, and streams
finished tiles back.

Mosaic tiling constraint (the reason for the slab row shapes): VMEM
references are (8, 128)-tiled in their minor two dims, so DMA slices of
VMEM buffers must be tile-aligned there; HBM-side slices are
unconstrained. Row-tiled slabs therefore carry ``ty + 8`` rows (the +-1
halo plus 6 dead rows) instead of ``ty + 2``; z is an untiled dim and
slices freely.

Layout contract: padded blocks with TPU-aligned planes
(GridSpec(aligned=True): py % 8 == 0, px % 128 == 0). The hot/cold sphere
fix-up (reference: bin/jacobi3d.cu:56-63) reads an int32 ``sel`` array
(0 = stencil, 1 = hot, 2 = cold) only for z-tiles that intersect the
sphere z-range.

``wrap`` support: axes whose partition has a single block are periodic
onto themselves; the kernel fills those halos directly from the opposite
face (tiny extra DMAs on edge tiles for z/y, an in-VMEM column copy for
x), replacing the ppermute + halo-update pass entirely for those axes.

Reference parity: computes exactly what ops/jacobi.jacobi_sweep computes
over the compute region (pinned by tests in interpret mode and against the
XLA path on the same device). The output aliases the ``nxt`` buffer;
non-compute cells in the written row range carry the input's values.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..domain.grid import GridSpec
from ..geometry import Dim3
from .jacobi import COLD_TEMP, HOT_TEMP

# VMEM scratch budget (~16 MB/core on v5e; leave headroom for the compiler)
_VMEM_BUDGET = 12 * 1024 * 1024

# multistep input ring: 3 live planes + 1 in flight
_N_IN = 4

# row-strip candidates for the row-tiled multistep staging (largest first:
# wider strips mean fewer strip-start pipeline restarts and less overlap
# recompute at uneven splits)
_ROW_CANDS = (512, 384, 256, 192, 128, 96, 64, 48, 32, 24, 16, 8)


def _round8(v: int) -> int:
    return (v + 7) // 8 * 8


def _divisors_desc(n: int, cands) -> list:
    out = [c for c in cands if c <= n and n % c == 0]
    if n not in out:
        out.append(n)
    return out


def _pick_tiles(nz: int, ny: int, yo: int, py: int, px: int) -> Tuple[int, int]:
    """Choose (tz, ty) minimizing read amplification subject to the
    double-buffered scratch fitting in the VMEM budget.

    ``ty == ny`` means full-plane slabs (py rows, arbitrary ny). ``ty < ny``
    requires 8-aligned row tiling: ty % 8 == 0, the compute y-origin on a
    tile boundary (yo % 8 == 0, GridSpec aligned layout), and the slab
    window [y0 - 8, y0 - 8 + ty + 16) inside the padded extent.
    """
    best = None
    for tz in _divisors_desc(nz, (32, 16, 8, 4, 2, 1)):
        for ty in _divisors_desc(ny, (256, 128, 64, 32, 16, 8)):
            if ty == ny:
                rows_in = rows_out = py
            else:
                if ty % 8 or yo % 8 or yo < 8 or yo + ny + 8 > py:
                    continue
                rows_in, rows_out = ty + 16, ty
            need = 4 * (2 * (tz + 2) * rows_in + 4 * tz * rows_out) * px
            if need > _VMEM_BUDGET:
                continue
            amp = ((tz + 2) * rows_in) / (tz * ty)
            key = (amp, -(tz * ty))
            if best is None or key < best[0]:
                best = (key, (tz, ty))
    if best is None:
        return (1, ny)  # tiny blocks always fit
    return best[1]


def _tight_x_layout(wrap_x: bool, nx: int, xo: int, px: int):
    """``(tight, kx, xo_k)`` — whether slabs can carry exactly the nx
    compute columns. Mosaic proves 128-divisibility of minor-dim tile
    indices on BOTH sides of a DMA (offsets and widths), so tight slabs
    require the zero-x-radius layout (``Radius.without_x``: xo == 0,
    px == nx); the periodic x neighborhood then comes from lane rolls.
    Measured 1.36x on the one-step sweep at 512^3 (BASELINE.md round 3,
    scripts/probe_xhalo.py)."""
    tight = wrap_x and nx % 128 == 0 and xo % 128 == 0
    return tight, (nx if tight else px), (0 if tight else xo)


def _roll_x_pair(arr, nx: int, axis: int):
    """Periodic (x-1, x+1) neighbor planes of ``arr`` by lane roll."""
    return pltpu.roll(arr, 1, axis), pltpu.roll(arr, nx - 1, axis)


def make_pallas_jacobi_sweep(
    spec: GridSpec,
    sel_z_range: Tuple[int, int],
    interpret: bool = False,
    vma=None,
    wrap: Tuple[bool, bool, bool] = (False, False, False),
    batch: Optional[int] = None,
):
    """Build ``sweep(curr, nxt, sel) -> new_next`` over one padded block
    (pz, py, px) fp32, writing the compute region of ``nxt`` in place.

    ``sel_z_range`` is the allocation-local [lo, hi) z-range where ``sel``
    may be nonzero (the spheres' bounding planes); tiles outside skip the
    sel DMA and select entirely.

    ``wrap`` = (wz, wy, wx): axes whose periodic halo the kernel fills
    itself from the opposite face (valid only when that mesh axis has a
    single block — the self-wrap case). Jacobi reads only face neighbors,
    so filling faces (no edges/corners) suffices.

    ``batch`` stacks B independent tenant blocks on a leading axis: all
    operands become ``(B, pz, py, px)`` and the grid grows a leading
    batch dimension — one full tile pass per tenant, each tenant's halos
    wrapped onto ITSELF (the multi-tenant campaign's fast path,
    ops/jacobi.make_batched_jacobi_loop). The per-tile pipeline is
    self-contained per batch step: the t==0 prologue re-primes the
    double-buffered DMAs and the final tile drains both outstanding
    stores before the next tenant's pass begins, so no DMA crosses the
    batch axis.
    """
    if not spec.aligned:
        raise ValueError("pallas sweep requires GridSpec(aligned=True)")
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    off = spec.compute_offset()
    zo, yo, xo = off.z, off.y, off.x
    nz, ny, nx = spec.base.z, spec.base.y, spec.base.x
    sel_lo, sel_hi = sel_z_range
    wz, wy, wx = wrap

    tight_x, kx, xo_k = _tight_x_layout(wx, nx, xo, px)
    tz, ty = _pick_tiles(nz, ny, yo, py, kx)

    n_tz = nz // tz
    n_ty = ny // ty
    n_tiles = n_tz * n_ty
    full_rows = n_ty == 1
    rows_in = py if full_rows else ty + 16
    rows_out = py if full_rows else ty
    # slab-local row index of the first output row (row-tiled slabs fetch
    # from y0 - 8, the nearest tile boundary carrying the -1 halo row)
    oy = yo if full_rows else 8
    xs = slice(xo_k, xo_k + nx)

    def kernel(curr_hbm, nxt_hbm, sel_hbm, out_hbm, in_v, out_v, sel_v, wy_v, s_in, s_out, s_sel, s_wrap):
        if batch is None:
            t = pl.program_id(0)
        else:
            b = pl.program_id(0)
            t = pl.program_id(1)
        slot = t % 2
        nslot = (t + 1) % 2

        def _ix(*sl):
            # batched operands carry the tenant index on the leading axis
            return sl if batch is None else (b, *sl)

        def tile_zy(ti):
            zi = ti // n_ty
            yi = ti % n_ty
            return zo + zi * tz, yo + yi * ty  # first output plane / row

        def _xsl():
            return pl.ds(xo, nx) if tight_x else slice(None)

        def in_dma(s, ti):
            z0, y0 = tile_zy(ti)
            ys = slice(None) if full_rows else pl.ds(y0 - 8, rows_in)
            src = curr_hbm.at[_ix(pl.ds(z0 - 1, tz + 2), ys, _xsl())]
            return pltpu.make_async_copy(src, in_v.at[s], s_in.at[s])

        def sel_dma(s, ti):
            z0, y0 = tile_zy(ti)
            ys = slice(None) if full_rows else pl.ds(y0, ty)
            src = sel_hbm.at[_ix(pl.ds(z0, tz), ys, _xsl())]
            return pltpu.make_async_copy(src, sel_v.at[s], s_sel.at[s])

        def out_dma(s, ti):
            z0, y0 = tile_zy(ti)
            ys = slice(None) if full_rows else pl.ds(y0, ty)
            dst = out_hbm.at[_ix(pl.ds(z0, tz), ys, _xsl())]
            return pltpu.make_async_copy(out_v.at[s], dst, s_out.at[s])

        def touches_sel(ti):
            z0 = zo + (ti // n_ty) * tz
            return jnp.logical_and(z0 < sel_hi, z0 + tz > sel_lo)

        # pipeline: tile t+1's input DMAs are issued before tile t's compute
        @pl.when(t == 0)
        def _():
            in_dma(slot, t).start()

            @pl.when(touches_sel(t))
            def _():
                sel_dma(slot, t).start()

        @pl.when(t + 1 < n_tiles)
        def _():
            in_dma(nslot, t + 1).start()

            @pl.when(touches_sel(t + 1))
            def _():
                sel_dma(nslot, t + 1).start()

        in_dma(slot, t).wait()

        # self-wrap halo fills (edge tiles only; after the main slab DMA so
        # the writes to in_v cannot race it)
        z0, y0 = tile_zy(t)
        zi = t // n_ty
        yi = t % n_ty
        if wz:

            @pl.when(zi == 0)
            def _():
                ys = slice(None) if full_rows else pl.ds(y0 - 8, rows_in)
                src = curr_hbm.at[_ix(pl.ds(zo + nz - 1, 1), ys, _xsl())]
                cp = pltpu.make_async_copy(src, in_v.at[slot, pl.ds(0, 1)], s_wrap)
                cp.start()
                cp.wait()

            @pl.when(zi == n_tz - 1)
            def _():
                ys = slice(None) if full_rows else pl.ds(y0 - 8, rows_in)
                src = curr_hbm.at[_ix(pl.ds(zo, 1), ys, _xsl())]
                cp = pltpu.make_async_copy(src, in_v.at[slot, pl.ds(tz + 1, 1)], s_wrap)
                cp.start()
                cp.wait()

        if wy and full_rows:
            # the wrapped rows are already resident: in-VMEM copies
            in_v[slot, :, yo - 1, xs] = in_v[slot, :, yo + ny - 1, xs]
            in_v[slot, :, yo + ny, xs] = in_v[slot, :, yo, xs]
        elif wy:
            # wrapped row lives in another tile's rows: stage 8 rows through
            # scratch (VMEM DMA slices must be 8-row aligned), then copy the
            # one needed row in VMEM
            @pl.when(yi == 0)
            def _():
                cp = pltpu.make_async_copy(
                    curr_hbm.at[_ix(pl.ds(z0, tz), pl.ds(yo + ny - 8, 8), _xsl())],
                    wy_v, s_wrap
                )
                cp.start()
                cp.wait()
                in_v[slot, 1 : tz + 1, oy - 1, :] = wy_v[:, 7, :]

            @pl.when(yi == n_ty - 1)
            def _():
                cp = pltpu.make_async_copy(
                    curr_hbm.at[_ix(pl.ds(z0, tz), pl.ds(yo, 8), _xsl())],
                    wy_v, s_wrap
                )
                cp.start()
                cp.wait()
                in_v[slot, 1 : tz + 1, oy + ty, :] = wy_v[:, 0, :]

        if wx and not tight_x:
            in_v[slot, :, :, xo - 1] = in_v[slot, :, :, xo + nx - 1]
            in_v[slot, :, :, xo + nx] = in_v[slot, :, :, xo]

        ctr = slice(oy, oy + ty)  # output rows within the in slab's center
        if tight_x:
            # periodic x neighborhood by lane roll — no halo columns exist
            x_lo, x_hi = _roll_x_pair(in_v[slot, 1 : tz + 1, ctr, :], nx, 2)
        else:
            x_lo = in_v[slot, 1 : tz + 1, ctr, xo - 1 : xo + nx - 1]
            x_hi = in_v[slot, 1 : tz + 1, ctr, xo + 1 : xo + nx + 1]
        avg = (
            x_lo
            + x_hi
            + in_v[slot, 1 : tz + 1, oy - 1 : oy + ty - 1, xs]
            + in_v[slot, 1 : tz + 1, oy + 1 : oy + ty + 1, xs]
            + in_v[slot, 0:tz, ctr, xs]
            + in_v[slot, 2 : tz + 2, ctr, xs]
        ) / 6.0  # divide, not *(1/6): bit-parity with ops.jacobi.jacobi_sweep

        # the same out slot was last used by tile t-2; its store must have
        # drained before we overwrite the buffer
        @pl.when(t >= 2)
        def _():
            out_dma(slot, t - 2).wait()

        # non-compute cells in the written range carry the input's values so
        # the store can cover whole aligned rows (tight-x stores span
        # exactly the compute columns — no x carries exist)
        oys = slice(oy, oy + ty) if full_rows else slice(None)
        if full_rows:
            out_v[slot, :, 0:oy, :] = in_v[slot, 1 : tz + 1, 0:oy, :]
            out_v[slot, :, oy + ty :, :] = in_v[slot, 1 : tz + 1, oy + ty : rows_out, :]
        if not tight_x:
            out_v[slot, :, oys, 0:xo] = in_v[slot, 1 : tz + 1, ctr, 0:xo]
            out_v[slot, :, oys, xo + nx :] = in_v[slot, 1 : tz + 1, ctr, xo + nx : px]

        @pl.when(touches_sel(t))
        def _():
            sel_dma(slot, t).wait()
            sel = sel_v[slot, :, oys, xs] if full_rows else sel_v[slot, :, :, xs]
            out_v[slot, :, oys, xs] = jnp.where(
                sel == 1, HOT_TEMP, jnp.where(sel == 2, COLD_TEMP, avg)
            )

        @pl.when(jnp.logical_not(touches_sel(t)))
        def _():
            out_v[slot, :, oys, xs] = avg

        out_dma(slot, t).start()

        # final tile: drain the last two outstanding stores
        @pl.when(t == n_tiles - 1)
        def _():
            if n_tiles >= 2:
                out_dma(nslot, t - 1).wait()
            out_dma(slot, t).wait()

    shape = (pz, py, px) if batch is None else (batch, pz, py, px)
    if vma is None:
        out_shape = jax.ShapeDtypeStruct(shape, jnp.float32)
    else:
        # inside shard_map, declare the output varying over the mesh axes
        out_shape = jax.ShapeDtypeStruct(shape, jnp.float32, vma=frozenset(vma))
    fn = pl.pallas_call(
        kernel,
        grid=(n_tiles,) if batch is None else (batch, n_tiles),
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.VMEM((2, tz + 2, rows_in, kx), jnp.float32),
            pltpu.VMEM((2, tz, rows_out, kx), jnp.float32),
            pltpu.VMEM((2, tz, rows_out, kx), jnp.int32),
            pltpu.VMEM((tz, 8, kx), jnp.float32),  # wy staging
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={1: 0},  # nxt buffer is updated in place
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=(
                ("arbitrary",) if batch is None
                else ("arbitrary", "arbitrary")
            ),
            has_side_effects=True,
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    )
    return fn


def valid_strip_rows(spec: GridSpec, k: int, ty: int) -> bool:
    """Whether ``ty``-row strips can stage the depth-``k`` multistep over
    this block: 8-aligned strips at least one wrap-pad (``round8(k)``)
    tall, and — whenever more than one strip exists — enough slack that
    every slab fetch (edge strips reach ``hp`` rows past their output
    rows; with an overlapped final strip the bound tightens to the last
    interior strip) stays inside the valid [yo, yo + ny) rows."""
    if spec.dim.y > 1:
        return False  # strips replace the y self-wrap ring: single-block y
    ny = spec.base.y
    if ty % 8 or ty > ny:
        return False
    hp = _round8(k)
    if ty < hp:
        return False
    n_ty = -(-ny // ty)
    return n_ty == 1 or (n_ty - 1) * ty + hp <= ny


def plan_multistep_staging(spec: GridSpec, k_want: int, budget: int):
    """``(k, rows)``: the deepest temporal depth <= ``k_want`` whose VMEM
    staging fits ``budget`` bytes, and the row-strip height that achieves
    it (``None`` = full-plane staging, the legacy layout).

    Full planes are preferred while they reach ``k_want`` (no strip
    overlap recompute, no per-strip pipeline restarts). Row tiling engages
    only when full planes self-cap the depth — the 768^3 regime where
    ``(py, px)`` planes held the multistep at k=4 (VERDICT r5 weak #2) —
    and requires a single-block y axis (the strip machinery replaces the
    y self-wrap ring; deep-halo y keeps full planes)."""
    if k_want < 2:
        return k_want, None
    p = spec.padded()
    off = spec.compute_offset()
    nx, ny = spec.base.x, spec.base.y
    mx = spec.dim.x > 1
    _, kx, _ = _tight_x_layout(not mx, nx, off.x, p.x)
    k_full = (budget // (p.y * kx * 4) - (_N_IN + 2)) // 3 + 1
    if k_full >= k_want or spec.dim.y > 1:
        return max(0, min(k_want, k_full)), None
    for k in range(k_want, max(k_full, 1), -1):
        hp = _round8(k)
        for ty in _ROW_CANDS:
            if not valid_strip_rows(spec, k, ty):
                continue
            need = 4 * kx * (
                (_N_IN + 3 * (k - 1)) * (ty + 2 * hp) + 2 * ty
            )
            if need <= budget:
                return k, ty
    return max(0, k_full), None


def make_pallas_jacobi_multistep(
    spec: GridSpec,
    k: int,
    interpret: bool = False,
    vma=None,
    _skip_yfill: bool = False,
    rows: Optional[int] = None,
):
    """Temporal-blocked Jacobi: advance the field ``k`` steps in ONE pass
    over HBM.

    A z-wavefront streams planes through VMEM: when input plane j arrives,
    stage 1 computes plane j-1, stage 2 plane j-2, ..., stage k (the
    output) plane j-k. HBM traffic per step drops from (1 read + 1 write)
    to ((1 + eps) read + 1 write) / k — the communication-avoiding scheme
    that matters on a machine where the stencil is purely memory-bound.

    Axis handling is derived per axis from ``spec.dim``:

    - single-block axes are periodic onto themselves: wrapped plane indices
      on the input fetch (z), in-VMEM ring copies on every stage plane
      (y/x) — no exchange at all, the original single-block behavior;
    - multi-block axes use **deep halos**: the caller exchanges radius-k
      halos ONCE, then stage s computes extents extended (k - s) cells
      into the halo ring, shrinking to the owned region at stage k. One
      exchange per k steps — temporal blocking that survives weak scaling
      (the deep-halo composition of the reference's wrap math,
      dim3.hpp:208-230, with its exchange loop, bin/jacobi3d.cu:296-368).

    Multi-block (uniform partitions only) requires radius >= k on both
    sides of every multi-block axis; the returned ``fn(org, curr, nxt)``
    then takes a (3,) int32 of this block's global (z, y, x) origin
    (scalar prefetch) so the sphere fix-up stays coordinate-exact.
    Single-block keeps the legacy ``fn(curr, nxt)`` signature.

    The hot/cold sphere fix-up is computed inline from integer coordinates:
    the reference's ``int64(sqrtf(d2)) <= R`` (bin/jacobi3d.cu:30-32,49) is
    exactly ``d2 < (R+1)^2`` for exact integer d2 (f32 sqrt of an exact
    integer < 2^24 cannot cross an integer boundary), so no sel array is
    read at all.

    ``rows`` selects **row-tiled staging** (``None`` = the legacy
    full-plane layout): all VMEM staging carries ``rows + 2*round8(k)``-row
    strips instead of full ``(py, px)`` planes, so temporal depth no
    longer collapses with plane size (k>=8 survives 768^3 — VERDICT r5
    weak #2). The grid becomes (n_strips, wavefront): each y-strip runs
    its own z-wavefront; stage s computes ``k - s`` extra rows each side
    (recomputed overlap between strips, the same shrinking-extent math the
    deep-halo ``ext()`` uses), the periodic y neighborhood of edge strips
    arrives via wrap-row DMAs from the opposite face (replacing the y-ring
    fills), and a final strip at ``ny % rows != 0`` is re-anchored to
    ``yo + ny - rows`` — its overlap with the previous strip recomputes
    identical values, so the overlapping writes are idempotent. Requires a
    single-block y axis (use :func:`plan_multistep_staging` /
    :func:`valid_strip_rows` to pick a legal height).

    ``_skip_yfill`` is a TIMING-PROBE knob (scripts/probe_noyfill.py): it
    skips the per-stage y-ring fills, so the kernel computes WRONG results.
    """
    if rows is not None:
        if _skip_yfill:
            raise ValueError("_skip_yfill probes the full-plane y rings")
        return _make_multistep_row_tiled(
            spec, k, rows, interpret=interpret, vma=vma
        )
    if _skip_yfill:
        from ..utils import logging as _log

        _log.warn("make_pallas_jacobi_multistep(_skip_yfill=True): "
                  "TIMING PROBE ONLY — results are WRONG by construction")
    if not spec.aligned:
        raise ValueError("pallas multistep requires GridSpec(aligned=True)")
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    off = spec.compute_offset()
    zo, yo, xo = off.z, off.y, off.x
    nz, ny, nx = spec.base.z, spec.base.y, spec.base.x
    mz, my, mx = spec.dim.z > 1, spec.dim.y > 1, spec.dim.x > 1
    use_org = mz or my or mx
    r = spec.radius
    if use_org:
        if not spec.is_uniform():
            raise ValueError(
                "deep-halo multistep requires a uniform partition")
        for m, rl, rh in (
            (mz, r.z(-1), r.z(1)), (my, r.y(-1), r.y(1)), (mx, r.x(-1), r.x(1))
        ):
            if m and (rl < k or rh < k):
                raise ValueError(
                    "deep-halo multistep needs radius >= k on "
                    "multi-block axes"
                )
    if nz < 2 * k + 1:
        raise ValueError("domain too shallow for this temporal depth")
    J = nz + 2 * k  # pipeline steps: input vplanes -k .. nz+k-1
    g = spec.global_size
    hot_c = (g.x // 3, g.y // 2, g.z // 2)
    cold_c = (g.x * 2 // 3, g.y // 2, g.z // 2)
    thresh = (g.x // 10 + 1) ** 2
    tight_x, kx, xo_k = _tight_x_layout(not mx, nx, xo, px)
    xs = slice(xo_k, xo_k + nx)
    N_IN = _N_IN  # input ring: 3 live planes + 1 in flight

    def ext(s):
        """(ey, ex) compute-extent extension of stage s into the halo ring
        (stage 0 = the exchanged deep-halo input)."""
        return ((k - s) if my else 0, (k - s) if mx else 0)

    def kernel(*refs):
        if use_org:
            org, curr_hbm, nxt_hbm, out_hbm, in_v, st_v, out_v, s_in, s_out = refs
            ozv = org[0] if mz else 0
            oyv = org[1] if my else 0
            oxv = org[2] if mx else 0
        else:
            curr_hbm, nxt_hbm, out_hbm, in_v, st_v, out_v, s_in, s_out = refs
            ozv = oyv = oxv = 0
        j = pl.program_id(0)

        def _xsl():
            return pl.ds(xo, nx) if tight_x else slice(None)

        def out_dma(step):
            ph = zo + (step - 2 * k)
            return pltpu.make_async_copy(
                out_v.at[pl.ds(jnp.mod(step, 2), 1)],
                out_hbm.at[pl.ds(ph, 1), slice(None), _xsl()],
                s_out.at[jnp.mod(step, 2)],
            )

        def in_dma(step):
            if mz:
                ph = zo - k + step  # deep-halo plane, no wrap
            else:
                ph = zo + jnp.mod(step - k, nz)  # wrapped physical plane
            return pltpu.make_async_copy(
                curr_hbm.at[pl.ds(ph, 1), slice(None), _xsl()],
                in_v.at[pl.ds(jnp.mod(step, N_IN), 1)],
                s_in.at[jnp.mod(step, N_IN)],
            )

        @pl.when(j == 0)
        def _():
            in_dma(0).start()

        @pl.when(j + 1 < J)
        def _():
            in_dma(j + 1).start()

        in_dma(j).wait()

        def fill_wrap(ref, slot, ey, ex):
            """Periodic rings of the self-wrap axes on a plane whose valid
            extents are extended (ey, ex) into the halo (multi-block axes);
            the ring spans the full valid extent so the next stage's
            shifted reads stay within filled cells."""
            xw = slice(xo_k - ex, xo_k + nx + ex)
            if not my and not _skip_yfill:
                ref[slot, yo - 1, xw] = ref[slot, yo + ny - 1, xw]
                ref[slot, yo + ny, xw] = ref[slot, yo, xw]
            if not mx and not tight_x:
                ry = 0 if my else 1
                yw = slice(yo - ey - ry, yo + ny + ey + ry)
                ref[slot, yw, xo - 1] = ref[slot, yw, xo + nx - 1]
                ref[slot, yw, xo + nx] = ref[slot, yw, xo]

        fill_wrap(in_v, jnp.mod(j, N_IN), *ext(0))

        for s in range(1, k + 1):
            @pl.when(j >= 2 * s)
            def _(s=s):
                v = j - k - s  # this stage's output vplane
                ey, ex = ext(s)

                def prev_plane(u):
                    """(ref, slot) holding stage s-1 (or input) vplane u."""
                    if s == 1:
                        return in_v, jnp.mod(u + k, N_IN)
                    return st_v, jnp.mod(u, 3)

                def rd(u, ys, xsl):
                    ref, slot = prev_plane(u)
                    if s == 1:
                        return ref[slot, ys, xsl]
                    return ref[s - 2, slot, ys, xsl]

                cy = slice(yo - ey, yo + ny + ey)
                cx = slice(xo_k - ex, xo_k + nx + ex)
                if tight_x:
                    x_lo, x_hi = _roll_x_pair(rd(v, cy, cx), nx, 1)
                else:
                    x_lo = rd(v, cy, slice(xo_k - ex - 1, xo_k + nx + ex - 1))
                    x_hi = rd(v, cy, slice(xo_k - ex + 1, xo_k + nx + ex + 1))
                avg = (
                    x_lo
                    + x_hi
                    + rd(v, slice(yo - ey - 1, yo + ny + ey - 1), cx)
                    + rd(v, slice(yo - ey + 1, yo + ny + ey + 1), cx)
                    + rd(v - 1, cy, cx)
                    + rd(v + 1, cy, cx)
                ) / 6.0  # divide: bit-parity with ops.jacobi.jacobi_sweep
                if s == k:
                    # the same out slot was last used at step j-2; drain it
                    @pl.when(j >= 2 * k + 2)
                    def _():
                        out_dma(j - 2).wait()

                def write(plane):
                    if s == k:
                        out_v[jnp.mod(j, 2), yo:yo + ny, xs] = plane
                    else:
                        st_v[s - 1, jnp.mod(v, 3), cy, cx] = plane

                # sphere fix-up only on planes intersecting the spheres
                # (both share the same z center and radius). Halo-extended
                # cells of a multi-block axis can sit beyond the global
                # extent (v < 0 / index >= g); their true coordinate is the
                # periodic wrap — without it a boundary-crossing sphere
                # would clamp differently here than on the owning block.
                zg = jnp.mod(ozv + v, g.z) if mz else jnp.mod(v, nz)
                near = jnp.abs(zg - hot_c[2]) <= g.x // 10

                @pl.when(near)
                def _():
                    shape = (ny + 2 * ey, nx + 2 * ex)
                    row = jax.lax.broadcasted_iota(jnp.int32, shape, 0) + (oyv - ey)
                    col = jax.lax.broadcasted_iota(jnp.int32, shape, 1) + (oxv - ex)
                    if my:
                        row = jnp.mod(row, g.y)
                    if mx:
                        col = jnp.mod(col, g.x)
                    dz2 = (zg - hot_c[2]) ** 2
                    hot = (row - hot_c[1]) ** 2 + (col - hot_c[0]) ** 2 + dz2 < thresh
                    cold = jnp.logical_and(
                        jnp.logical_not(hot),
                        (row - cold_c[1]) ** 2 + (col - cold_c[0]) ** 2 + dz2 < thresh,
                    )
                    write(jnp.where(hot, HOT_TEMP, jnp.where(cold, COLD_TEMP, avg)))

                @pl.when(jnp.logical_not(near))
                def _():
                    write(avg)

                if s < k:
                    fill_wrap(st_v.at[s - 1], jnp.mod(v, 3), ey, ex)

        @pl.when(j >= 2 * k)
        def _():
            out_dma(j).start()

        @pl.when(j == J - 1)
        def _():
            out_dma(j - 1).wait()
            out_dma(j).wait()

    if vma is None:
        out_shape = jax.ShapeDtypeStruct((pz, py, px), jnp.float32)
    else:
        out_shape = jax.ShapeDtypeStruct((pz, py, px), jnp.float32, vma=frozenset(vma))
    scratch = [
        pltpu.VMEM((N_IN, py, kx), jnp.float32),
        pltpu.VMEM((max(k - 1, 1), 3, py, kx), jnp.float32),
        pltpu.VMEM((2, py, kx), jnp.float32),
        pltpu.SemaphoreType.DMA((N_IN,)),
        pltpu.SemaphoreType.DMA((2,)),
    ]
    params = pltpu.CompilerParams(
        dimension_semantics=("arbitrary",),
        has_side_effects=True,
        vmem_limit_bytes=100 * 1024 * 1024,
    )
    if use_org:
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(J,),
                in_specs=[
                    pl.BlockSpec(memory_space=pl.ANY),
                    pl.BlockSpec(memory_space=pl.ANY),
                ],
                out_specs=pl.BlockSpec(memory_space=pl.ANY),
                scratch_shapes=scratch,
            ),
            out_shape=out_shape,
            input_output_aliases={2: 0},  # (org, curr, nxt) -> nxt
            compiler_params=params,
            interpret=interpret,
        )
    return pl.pallas_call(
        kernel,
        grid=(J,),
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch,
        input_output_aliases={1: 0},
        compiler_params=params,
        interpret=interpret,
    )


def _make_multistep_row_tiled(
    spec: GridSpec,
    k: int,
    ty: int,
    interpret: bool = False,
    vma=None,
):
    """Row-tiled staging body of :func:`make_pallas_jacobi_multistep`.

    Grid (n_ty, J): strip-major, wavefront-minor. Slab row r of a strip
    anchored at output row ``y0`` holds virtual row ``y0 - hp + r``
    (``hp = round8(k)`` wrap-pad rows each side); virtual rows outside
    [yo, yo + ny) are the periodic wrap, delivered to edge strips by a
    second hp-row DMA from the opposite face (both HBM row offsets and the
    8-aligned VMEM offsets 0 / hp / hp + ty are DMA-legal, so no staged
    single-row copies are needed). Stage s computes rows
    [hp - (k-s), hp + ty + (k-s)) — interior strips recompute up to k rows
    each side of their output rows instead of reading a neighbor strip,
    which is what unchains the staging footprint from the plane size."""
    assert spec.aligned
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    off = spec.compute_offset()
    zo, yo, xo = off.z, off.y, off.x
    nz, ny, nx = spec.base.z, spec.base.y, spec.base.x
    mz, my, mx = spec.dim.z > 1, spec.dim.y > 1, spec.dim.x > 1
    assert not my, "row-tiled multistep staging needs a single-block y axis"
    assert valid_strip_rows(spec, k, ty), (k, ty, ny)
    use_org = mz or mx
    r = spec.radius
    if use_org:
        assert spec.is_uniform(), "deep-halo multistep requires a uniform partition"
        for m, rl, rh in ((mz, r.z(-1), r.z(1)), (mx, r.x(-1), r.x(1))):
            assert not m or (rl >= k and rh >= k), (
                "deep-halo multistep needs radius >= k on multi-block axes"
            )
    assert nz >= 2 * k + 1, "domain too shallow for this temporal depth"
    hp = _round8(k)
    R = ty + 2 * hp
    n_ty = -(-ny // ty)
    J = nz + 2 * k  # wavefront steps per strip: input vplanes -k .. nz+k-1
    g = spec.global_size
    hot_c = (g.x // 3, g.y // 2, g.z // 2)
    cold_c = (g.x * 2 // 3, g.y // 2, g.z // 2)
    thresh = (g.x // 10 + 1) ** 2
    tight_x, kx, xo_k = _tight_x_layout(not mx, nx, xo, px)
    xs = slice(xo_k, xo_k + nx)

    def kernel(*refs):
        if use_org:
            org, curr_hbm, nxt_hbm, out_hbm, in_v, st_v, out_v, s_in, s_out, s_wrap = refs
            ozv = org[0] if mz else 0
            oxv = org[2] if mx else 0
        else:
            curr_hbm, nxt_hbm, out_hbm, in_v, st_v, out_v, s_in, s_out, s_wrap = refs
            ozv = oxv = 0
        yi = pl.program_id(0)
        j = pl.program_id(1)
        y0 = yo + jnp.minimum(yi * ty, ny - ty)  # uneven final strip re-anchors

        def _xsl():
            return pl.ds(xo, nx) if tight_x else slice(None)

        def in_plane(step):
            if mz:
                return zo - k + step  # deep-halo plane, no wrap
            return zo + jnp.mod(step - k, nz)  # wrapped physical plane

        def in_event(step, go):
            """Start or wait the main slab DMA of input ``step``. Edge
            strips skip the rows the wrap DMAs deliver, so every VMEM
            destination offset/extent stays 8-row aligned and no fetch
            leaves the valid [yo, yo + ny) rows."""
            ph = in_plane(step)
            slot = jnp.mod(step, _N_IN)

            def cp(src_lo, n_rows, dst_off):
                return pltpu.make_async_copy(
                    curr_hbm.at[pl.ds(ph, 1), pl.ds(src_lo, n_rows), _xsl()],
                    in_v.at[pl.ds(slot, 1), pl.ds(dst_off, n_rows)],
                    s_in.at[slot],
                )

            if n_ty == 1:
                go(cp(y0, ty, hp))
                return

            @pl.when(yi == 0)
            def _():
                go(cp(y0, ty + hp, hp))

            @pl.when(yi == n_ty - 1)
            def _():
                go(cp(y0 - hp, hp + ty, 0))

            if n_ty > 2:
                @pl.when(jnp.logical_and(yi > 0, yi < n_ty - 1))
                def _():
                    go(cp(y0 - hp, R, 0))

        def out_dma(step):
            ph = zo + (step - 2 * k)
            return pltpu.make_async_copy(
                out_v.at[pl.ds(jnp.mod(step, 2), 1)],
                out_hbm.at[pl.ds(ph, 1), pl.ds(y0, ty), _xsl()],
                s_out.at[jnp.mod(step, 2)],
            )

        @pl.when(j == 0)
        def _():
            in_event(0, lambda c: c.start())

        @pl.when(j + 1 < J)
        def _():
            in_event(j + 1, lambda c: c.start())

        in_event(j, lambda c: c.wait())

        # periodic y: edge strips receive the opposite face's rows (after
        # the main slab DMA so the writes cannot race it)
        slot_j = jnp.mod(j, _N_IN)
        ph_j = in_plane(j)

        def wrap_cp(src_lo, dst_off):
            return pltpu.make_async_copy(
                curr_hbm.at[pl.ds(ph_j, 1), pl.ds(src_lo, hp), _xsl()],
                in_v.at[pl.ds(slot_j, 1), pl.ds(dst_off, hp)],
                s_wrap,
            )

        def run_sync(cp):
            cp.start()
            cp.wait()

        if n_ty == 1:
            run_sync(wrap_cp(yo + ny - hp, 0))
            run_sync(wrap_cp(yo, hp + ty))
        else:
            @pl.when(yi == 0)
            def _():
                run_sync(wrap_cp(yo + ny - hp, 0))

            @pl.when(yi == n_ty - 1)
            def _():
                run_sync(wrap_cp(yo, hp + ty))

        def fill_wrap_x(ref, slot, es):
            """Periodic x ring of a plane whose valid row extent is
            [hp - es, hp + ty + es) — covers the next stage's x-shifted
            reads (its rows shrink by one)."""
            if not mx and not tight_x:
                yw = slice(hp - es, hp + ty + es)
                ref[slot, yw, xo - 1] = ref[slot, yw, xo + nx - 1]
                ref[slot, yw, xo + nx] = ref[slot, yw, xo]

        fill_wrap_x(in_v, slot_j, k)

        for s in range(1, k + 1):
            @pl.when(j >= 2 * s)
            def _(s=s):
                v = j - k - s  # this stage's output vplane
                es = k - s
                ex = es if mx else 0

                def rd(u, ys, xsl):
                    if s == 1:
                        return in_v[jnp.mod(u + k, _N_IN), ys, xsl]
                    return st_v[s - 2, jnp.mod(u, 3), ys, xsl]

                cy = slice(hp - es, hp + ty + es)
                cx = slice(xo_k - ex, xo_k + nx + ex)
                if tight_x:
                    x_lo, x_hi = _roll_x_pair(rd(v, cy, cx), nx, 1)
                else:
                    x_lo = rd(v, cy, slice(xo_k - ex - 1, xo_k + nx + ex - 1))
                    x_hi = rd(v, cy, slice(xo_k - ex + 1, xo_k + nx + ex + 1))
                avg = (
                    x_lo
                    + x_hi
                    + rd(v, slice(hp - es - 1, hp + ty + es - 1), cx)
                    + rd(v, slice(hp - es + 1, hp + ty + es + 1), cx)
                    + rd(v - 1, cy, cx)
                    + rd(v + 1, cy, cx)
                ) / 6.0  # divide: bit-parity with ops.jacobi.jacobi_sweep
                if s == k:
                    # the same out slot was last used at step j-2; drain it
                    @pl.when(j >= 2 * k + 2)
                    def _():
                        out_dma(j - 2).wait()

                def write(plane):
                    if s == k:
                        out_v[jnp.mod(j, 2), :, xs] = plane
                    else:
                        st_v[s - 1, jnp.mod(v, 3), cy, cx] = plane

                # sphere fix-up from global coordinates; strip rows (and the
                # wrap-pad of edge strips) sit at their wrapped global y
                zg = jnp.mod(ozv + v, g.z) if mz else jnp.mod(v, nz)
                near = jnp.abs(zg - hot_c[2]) <= g.x // 10

                @pl.when(near)
                def _():
                    shape = (ty + 2 * es, nx + 2 * ex)
                    row = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
                    row = jnp.mod(row + (y0 - yo) - es, g.y)
                    col = jax.lax.broadcasted_iota(jnp.int32, shape, 1) + (oxv - ex)
                    if mx:
                        col = jnp.mod(col, g.x)
                    dz2 = (zg - hot_c[2]) ** 2
                    hot = (row - hot_c[1]) ** 2 + (col - hot_c[0]) ** 2 + dz2 < thresh
                    cold = jnp.logical_and(
                        jnp.logical_not(hot),
                        (row - cold_c[1]) ** 2 + (col - cold_c[0]) ** 2 + dz2 < thresh,
                    )
                    write(jnp.where(hot, HOT_TEMP, jnp.where(cold, COLD_TEMP, avg)))

                @pl.when(jnp.logical_not(near))
                def _():
                    write(avg)

                if s < k:
                    fill_wrap_x(st_v.at[s - 1], jnp.mod(v, 3), es)

        @pl.when(j >= 2 * k)
        def _():
            out_dma(j).start()

        @pl.when(j == J - 1)
        def _():
            out_dma(j - 1).wait()
            out_dma(j).wait()

    if vma is None:
        out_shape = jax.ShapeDtypeStruct((pz, py, px), jnp.float32)
    else:
        out_shape = jax.ShapeDtypeStruct((pz, py, px), jnp.float32, vma=frozenset(vma))
    scratch = [
        pltpu.VMEM((_N_IN, R, kx), jnp.float32),
        pltpu.VMEM((max(k - 1, 1), 3, R, kx), jnp.float32),
        pltpu.VMEM((2, ty, kx), jnp.float32),
        pltpu.SemaphoreType.DMA((_N_IN,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA(()),
    ]
    params = pltpu.CompilerParams(
        dimension_semantics=("arbitrary", "arbitrary"),
        has_side_effects=True,
        vmem_limit_bytes=100 * 1024 * 1024,
    )
    if use_org:
        return pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(n_ty, J),
                in_specs=[
                    pl.BlockSpec(memory_space=pl.ANY),
                    pl.BlockSpec(memory_space=pl.ANY),
                ],
                out_specs=pl.BlockSpec(memory_space=pl.ANY),
                scratch_shapes=scratch,
            ),
            out_shape=out_shape,
            input_output_aliases={2: 0},  # (org, curr, nxt) -> nxt
            compiler_params=params,
            interpret=interpret,
        )
    return pl.pallas_call(
        kernel,
        grid=(n_ty, J),
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch,
        input_output_aliases={1: 0},
        compiler_params=params,
        interpret=interpret,
    )


def sel_z_range(spec: GridSpec) -> Tuple[int, int]:
    """Allocation-local z-range that may contain sphere cells, valid for
    every block (conservative union over blocks): the spheres span global
    z in [zc - R, zc + R] (reference geometry, bin/jacobi3d.cu:44-49)."""
    global_size = spec.global_size
    zc = global_size.z // 2
    R = global_size.x // 10
    zo = spec.radius.z(-1)
    glo, ghi = zc - R, zc + R + 1
    # conservative: if any block covers part of [glo, ghi), its local range
    # is within [zo, zo + base.z); compute the tightest uniform bound
    lo = spec.padded().z
    hi = 0
    for iz in range(spec.dim.z):
        o = sum(spec.sizes_z[:iz])
        s = spec.sizes_z[iz]
        blo = max(glo - o, 0)
        bhi = min(ghi - o, s)
        if blo < bhi:
            lo = min(lo, zo + blo)
            hi = max(hi, zo + bhi)
    if hi <= lo:
        return (0, 0)
    return (lo, hi)
