"""7-point Jacobi heat-diffusion stencil and its fused distributed step.

TPU-native re-design of the reference demo kernel and iteration structure
(reference: bin/jacobi3d.cu:30-85 kernel, :296-377 overlap loop): each
compute cell becomes the average of its six face neighbors; a "hot" sphere
(value 1) fixed at x = 1/3 and a "cold" sphere (value 0) at x = 2/3 of the
global domain, radius X/10, are re-imposed every step. Initial condition is
0.5 everywhere (bin/jacobi3d.cu:25).

The kernel is shifted array slices over the halo-padded block — XLA fuses
the adds, divide, and sphere masks into one elementwise pass (the analogue
of the reference's single CUDA kernel). The comm/compute overlap of the
reference (interior kernel on its own stream, CPU-polled exchange, then
exterior kernels, src/stencil.cu:1002-1186) becomes *dataflow*: inside one
jitted step the interior sweep depends only on pre-exchange data, so XLA is
free to run the halo ``ppermute``s concurrently with it, then the exterior
slabs consume the exchanged halos. No host polling exists.

Sphere masks are precomputed host-side from global coordinates and sharded
alongside the quantity (step-invariant).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..geometry import Dim3, Radius, Rect3, exterior_regions, interior_region
from ..parallel.exchange import BLOCK_PSPEC, HaloExchange, Method
from ..utils import timer

HOT_TEMP = 1.0
COLD_TEMP = 0.0
INIT_TEMP = (HOT_TEMP + COLD_TEMP) / 2


def _rect_slices(rect: Rect3, dz=0, dy=0, dx=0):
    return (
        slice(rect.lo.z + dz, rect.hi.z + dz),
        slice(rect.lo.y + dy, rect.hi.y + dy),
        slice(rect.lo.x + dx, rect.hi.x + dx),
    )


def jacobi_sweep(src, out, rect: Rect3, masks=None):
    """Write the 6-neighbor average of ``src`` into region ``rect`` of
    ``out`` (allocation-local coords; leading dims allowed). ``masks`` is an
    optional ``(hot, cold)`` pair of bool arrays shaped like ``src``."""
    avg = (
        src[(..., *_rect_slices(rect, dx=-1))]
        + src[(..., *_rect_slices(rect, dx=1))]
        + src[(..., *_rect_slices(rect, dy=-1))]
        + src[(..., *_rect_slices(rect, dy=1))]
        + src[(..., *_rect_slices(rect, dz=-1))]
        + src[(..., *_rect_slices(rect, dz=1))]
    ) / 6
    if masks is not None:
        hot, cold = masks
        sl = (..., *_rect_slices(rect))
        avg = jnp.where(hot[sl], HOT_TEMP, jnp.where(cold[sl], COLD_TEMP, avg))
    return out.at[(..., *_rect_slices(rect))].set(avg.astype(out.dtype))


def _sweep_shell_wrap_x(src, out, rect: Rect3, masks=None):
    """:func:`jacobi_sweep` for a shell rect spanning the FULL x extent of
    a tight-x block (``Radius.without_x``: no x halo columns exist, the x
    axis is single-block periodic): the x neighborhood comes from rolls.
    Operand order matches the Pallas kernel's (x_lo + x_hi + y + z) so
    overlap-patched cells are bit-identical to serialized ones."""
    c = src[(..., *_rect_slices(rect))]
    avg = (
        jnp.roll(c, 1, -1)
        + jnp.roll(c, -1, -1)
        + src[(..., *_rect_slices(rect, dy=-1))]
        + src[(..., *_rect_slices(rect, dy=1))]
        + src[(..., *_rect_slices(rect, dz=-1))]
        + src[(..., *_rect_slices(rect, dz=1))]
    ) / 6
    if masks is not None:
        hot, cold = masks
        sl = (..., *_rect_slices(rect))
        avg = jnp.where(hot[sl], HOT_TEMP, jnp.where(cold[sl], COLD_TEMP, avg))
    return out.at[(..., *_rect_slices(rect))].set(avg.astype(out.dtype))


def _patch_x_edges_sidebuf(src, out, compute: Rect3, xlo, xhi, masks=None):
    """Recompute the two x-edge columns of the compute region from
    exchanged side buffers (multi-block tight-x: the kernel's lane rolls
    wrapped onto the block's OWN columns, wrong at block edges). Operand
    order matches the kernel's x_lo + x_hi + y + z sum for bit parity."""
    lo, hi = compute.lo, compute.hi
    zy = (slice(lo.z, hi.z), slice(lo.y, hi.y))

    def col(x0, dz=0, dy=0):
        return src[(..., slice(lo.z + dz, hi.z + dz),
                    slice(lo.y + dy, hi.y + dy), slice(x0, x0 + 1))]

    for edge, x_lo, x_hi in (
        (lo.x, xlo[(..., *zy, slice(-1, None))], col(lo.x + 1)),
        (hi.x - 1, col(hi.x - 2), xhi[(..., *zy, slice(0, 1))]),
    ):
        avg = (
            x_lo + x_hi
            + col(edge, dy=-1) + col(edge, dy=1)
            + col(edge, dz=-1) + col(edge, dz=1)
        ) / 6
        dst = (..., *zy, slice(edge, edge + 1))
        if masks is not None:
            hot, cold = masks
            avg = jnp.where(hot[dst], HOT_TEMP,
                            jnp.where(cold[dst], COLD_TEMP, avg))
        out = out.at[dst].set(avg.astype(out.dtype))
    return out


def _sweep_slab_dyn(src3, o3, sel3, lo, size):
    """Re-sweep one dynamic-offset boundary shell ``[lo, lo + size)`` of a
    (pz, py, px) block from exchanged data ``src3`` into ``o3``. ``size`` is
    static; ``lo`` entries may be traced (uneven-partition hi-side shells).
    Bit-parity with :func:`jacobi_sweep`: same operand order, same divide."""
    lz, ly, lx = lo
    sz, sy, sx = size
    slab = lax.dynamic_slice(
        src3, (lz - 1, ly - 1, lx - 1), (sz + 2, sy + 2, sx + 2)
    )
    avg = (
        slab[1 : sz + 1, 1 : sy + 1, 0:sx]
        + slab[1 : sz + 1, 1 : sy + 1, 2 : sx + 2]
        + slab[1 : sz + 1, 0:sy, 1 : sx + 1]
        + slab[1 : sz + 1, 2 : sy + 2, 1 : sx + 1]
        + slab[0:sz, 1 : sy + 1, 1 : sx + 1]
        + slab[2 : sz + 2, 1 : sy + 1, 1 : sx + 1]
    ) / 6
    selc = lax.dynamic_slice(sel3, lo, size)
    avg = jnp.where(selc == 1, HOT_TEMP, jnp.where(selc == 2, COLD_TEMP, avg))
    return lax.dynamic_update_slice(o3, avg.astype(o3.dtype), lo)


def _patch_shells_dyn(spec, src, out, sel, multi_block_only: bool):
    """Patch every boundary shell of an uneven-partition block from the
    exchanged state (the dynamic-extent exterior pass; see ops/shells.py)."""
    from .shells import dyn_block_sizes, include_axes, shell_regions

    p = spec.padded()
    shp = out.shape
    s3 = src.reshape(p.z, p.y, p.x)
    o3 = out.reshape(p.z, p.y, p.x)
    sel3 = sel.reshape(p.z, p.y, p.x)
    sizes = dyn_block_sizes(spec)
    for lo, size in shell_regions(spec, sizes, include_axes(spec, multi_block_only)):
        o3 = _sweep_slab_dyn(s3, o3, sel3, lo, size)
    return o3.reshape(shp)


def jacobi6_block(block, radius: Radius, masks=None):
    """One full-compute-region Jacobi sweep over a padded block, in place of
    the halo ring (reference kernel over the whole region,
    bin/jacobi3d.cu:343-360)."""
    if min(radius.x(-1), radius.x(1), radius.y(-1), radius.y(1),
           radius.z(-1), radius.z(1)) < 1:
        raise ValueError("jacobi needs face radius >= 1")
    *_, pz, py, px = block.shape
    off = Dim3(radius.x(-1), radius.y(-1), radius.z(-1))
    hi = Dim3(px - radius.x(1), py - radius.y(1), pz - radius.z(1))
    return jacobi_sweep(block, block, Rect3(off, hi), masks)


def make_jacobi_step(ex: HaloExchange, overlap: bool = True, use_pallas=None,
                     standard_spheres: bool = True, interpret: bool = False):
    """Build the jitted distributed iteration: exchange + stencil + swap.

    Returns ``step(curr, nxt, hot, cold) -> (new_curr, new_next)`` over
    stacked sharded arrays; buffers are donated (the double-buffer swap of
    the reference, src/local_domain.cu:67-84, as input/output aliasing).

    ``overlap=True`` replicates the reference's interior/exterior split
    (bin/jacobi3d.cu:296-368): the interior sweep reads pre-exchange data
    (it never touches halos, src/stencil.cu:878-921), the ≤6 exterior slabs
    read exchanged halos. On an uneven partition the exterior slabs become
    dynamic-offset shells (ops/shells.py) — per-block extents are static per
    block index, so the overlap structure survives uneven splits exactly as
    the reference's per-LocalDomain regions do (src/stencil.cu:878-977).
    """
    # host-side build phase (kernel selection + closure construction); the
    # first invocation's XLA compile lands in the caller's warmup span
    with timer.timed("jacobi.build"), timer.trace_range("jacobi.build"):
        return _compile_jacobi(ex, overlap, iters=None, use_pallas=use_pallas,
                               standard_spheres=standard_spheres,
                               interpret=interpret)


def make_jacobi_loop(ex: HaloExchange, iters: int, overlap: bool = True, use_pallas=None,
                     standard_spheres: bool = True, interpret: bool = False,
                     temporal_k: Optional[int] = None,
                     multistep_rows: Optional[int] = None):
    """Like :func:`make_jacobi_step` but runs ``iters`` iterations inside one
    compiled program (``lax.fori_loop``) — one host dispatch per chunk.

    This is the ``USE_CUDA_GRAPH`` analogue taken further: where the
    reference graph-captures one exchange (packer.cu:96-103), XLA compiles
    the whole iteration loop, which also removes the per-call host
    round-trip of the tunneled TPU platform.

    ``standard_spheres`` declares that the ``sel`` argument will be the
    standard jacobi3d hot/cold spheres (``sphere_sel(global_size)``). Only
    then may the temporal-blocked kernel engage, because it re-derives the
    spheres from coordinates instead of reading ``sel``. Pass ``False``
    when driving the step with a custom or empty ``sel``.

    ``temporal_k`` caps the temporal-blocking depth explicitly. Weak-scaling
    comparisons need it: a single-block mesh has no radius bound and would
    run the full default depth (k=12) while an N-chip deep-halo run is
    capped at the realized radius,
    conflating temporal depth with scaling in the efficiency column
    (ADVICE r3).

    ``multistep_rows`` forces the multistep's row-strip height (None =
    :func:`~stencil_tpu.ops.pallas_stencil.plan_multistep_staging` picks:
    full planes while they reach the depth, row strips beyond) — the
    probing knob behind ``jacobi3d --multistep-rows``.
    """
    # same build-phase accounting as make_jacobi_step: the multistep plan
    # (staging/row-tiling decisions) is constructed here, on the host
    with timer.timed("jacobi.build"), timer.trace_range("jacobi.build"):
        return _compile_jacobi(ex, overlap, iters=iters, use_pallas=use_pallas,
                               standard_spheres=standard_spheres,
                               interpret=interpret, temporal_k=temporal_k,
                               multistep_rows=multistep_rows)


def _want_pallas(ex: HaloExchange, use_pallas) -> bool:
    if use_pallas is not None:
        return bool(use_pallas)
    devs = ex.mesh.devices.flatten()
    # resident (oversubscribed) shards stack whole padded blocks along the
    # leading block dims: the per-block kernels run once per resident
    # (VERDICT r4 item 7). Uneven + resident keeps the XLA path (the
    # dynamic-shell machinery is single-resident).
    if ex.oversubscribed and not ex.spec.is_uniform():
        return False
    return ex.spec.aligned and all(d.platform == "tpu" for d in devs)


def _compile_jacobi_auto(ex: HaloExchange, overlap: bool, iters,
                         temporal_k: Optional[int] = None,
                         multistep_rows: Optional[int] = None):
    """The AUTO_SPMD iteration: ONE global jitted program over the sharded
    stacked arrays, with no shard_map and no hand-written collectives — the
    halo fill is the exchange's :meth:`~HaloExchange.auto_fill` slab program
    and the sweep is the same shifted-slice kernel applied with its leading
    block dims intact, so the SPMD partitioner synthesizes every
    collective-permute (the bench_mpi_pack question asked of the whole
    step, not just the exchange). The reference overlap structure survives
    as dataflow exactly as in the manual path: on uniform partitions the
    interior sweep reads pre-exchange data and only the exterior slabs
    consume the exchanged halos; uneven partitions serialize (the dynamic
    shells need per-device axis_index, a shard_map concept). Bit parity
    with the AXIS_COMPOSED XLA path is pinned in tests/test_auto_spmd.py.
    """
    spec = ex.spec
    r = spec.radius
    assert min(
        r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1)
    ) >= 1, (
        "the AUTO_SPMD jacobi path needs face radius >= 1 on every side "
        "(no Pallas in-kernel x wrap exists in the global program)"
    )
    if temporal_k is not None or multistep_rows is not None:
        # an explicit temporal request must never be conflated with the
        # per-step program this path compiles (the ADVICE-r3 rule the
        # temporal_k knob exists for)
        from ..utils import logging as log

        log.warn(
            f"temporal_k={temporal_k} multistep_rows={multistep_rows} "
            "ignored: the temporal multistep is a Pallas/shard_map "
            "construct; the AUTO_SPMD path runs per-step global sweeps"
        )
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)
    interior = interior_region(compute, r)
    exteriors = exterior_regions(compute, interior)
    use_overlap = overlap and spec.is_uniform()

    def body(curr, nxt, sel):
        masks = (sel == 1, sel == 2)
        if use_overlap:
            # overlap as dataflow: the interior never touches halos, so the
            # partitioner is free to run its synthesized permutes
            # concurrently with it; the exterior slabs read exchanged halos
            out = jacobi_sweep(curr, nxt, interior, masks)
            cur2 = ex.auto_fill(curr)
            for rect in exteriors:
                out = jacobi_sweep(cur2, out, rect, masks)
        else:
            # serialized (or uneven): exchange, then sweep the full base
            # extent — cells past an uneven block's true size are dead pad
            cur2 = ex.auto_fill(curr)
            out = jacobi_sweep(cur2, nxt, compute, masks)
        return out, cur2

    def entry_fn(curr, nxt, sel):
        if iters is None:
            return body(curr, nxt, sel)
        return jax.lax.fori_loop(
            0, iters, lambda _, cn: body(cn[0], cn[1], sel), (curr, nxt)
        )

    sh = ex.sharding()
    return jax.jit(
        entry_fn, in_shardings=(sh,) * 3, out_shardings=(sh, sh),
        donate_argnums=(0, 1),
    )


def _compile_jacobi_fused(ex: HaloExchange, iters,
                          temporal_k: Optional[int] = None,
                          multistep_rows: Optional[int] = None,
                          interpret: bool = False):
    """The FUSED REMOTE_DMA iteration (ROADMAP #5): one substep =
    pack boundary slabs → START every per-neighbor copy → interior
    compute while the DMAs fly → wait → boundary compute.

    On an all-TPU mesh with an aligned uniform spec, the whole substep
    is ONE Pallas mega-kernel (ops/fused_stencil.make_fused_jacobi_kernel)
    inside a shard_map'd ``fori_loop`` — wire time hides behind interior
    FLOPs *inside* the kernel. Everywhere else (the CPU mesh, uneven
    partitions) the SAME schedule runs host-orchestrated: the fused
    emulation's start/wait/finish split
    (parallel/remote_emu.FusedRemoteEmulation) brackets compiled
    collective-free sweeps — the interior sweep dispatches while the
    emulated copies fly, so the overlap is real wall-clock overlap, and
    the step output is bit-identical to the AXIS_COMPOSED overlap step
    (tests/test_fused_stencil.py pins it, wire compression included).

    The host path narrates itself: ``fused.pack`` / ``fused.interior`` /
    ``fused.dma_wait`` / ``fused.boundary`` spans (variant-tagged, so
    report aggregation splits them per kernel variant) plus the
    ``fused.overlap_fraction`` gauge — interior-compute time over total
    substep time, the overlap split the PR-12 live sentinel and the
    trace export see."""
    spec = ex.spec
    r = spec.radius
    assert min(
        r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1)
    ) >= 1, "jacobi needs face radius >= 1 on every side"
    if temporal_k is not None or multistep_rows is not None:
        from ..utils import logging as log

        log.warn(
            f"temporal_k={temporal_k} multistep_rows={multistep_rows} "
            "ignored: the temporal multistep composes with in-step "
            "ppermute exchanges; the FUSED path runs one fused "
            "exchange+sweep substep per step"
        )
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)
    interior = interior_region(compute, r)
    exteriors = exterior_regions(compute, interior)
    on_tpu = all(d.platform == "tpu" for d in ex.mesh.devices.flatten())

    if (on_tpu and spec.is_uniform() and spec.aligned and not interpret
            and not ex.hierarchical):
        # the mega-kernel path: exchange+sweep in ONE pallas_call
        # (hierarchical plans fall through: the in-kernel exchange
        # addresses the full ring, so the DCN level must ride the
        # host-orchestrated schedule below)
        from .fused_stencil import make_fused_jacobi_kernel

        p = spec.padded()
        kern = make_fused_jacobi_kernel(
            spec, ex.plan, wire_dtype=ex.wire_dtype)

        def body(curr, nxt, sel):
            c2, out = kern(
                curr.reshape(p.z, p.y, p.x),
                nxt.reshape(p.z, p.y, p.x),
                sel.reshape(p.z, p.y, p.x),
            )
            return out.reshape(curr.shape), c2.reshape(curr.shape)

        def entry_fn(curr, nxt, sel):
            if iters is None:
                return body(curr, nxt, sel)
            return lax.fori_loop(
                0, iters, lambda _, cn: body(cn[0], cn[1], sel),
                (curr, nxt))

        fn = jax.shard_map(
            entry_fn, mesh=ex.mesh,
            in_specs=(BLOCK_PSPEC,) * 3,
            out_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
        )
        return jax.jit(fn, donate_argnums=(0, 1))

    # host-orchestrated fused schedule: compiled collective-free sweeps
    # slotted between the emulation's start/wait/finish
    uniform = spec.is_uniform()

    def interior_body(curr, nxt, sel):
        masks = (sel == 1, sel == 2)
        if uniform:
            return jacobi_sweep(curr, nxt, interior, masks)
        # uneven: full-region sweep on pre-exchange data (boundary
        # cells re-swept from the exchanged state below)
        return jacobi_sweep(curr, nxt, compute, masks)

    def boundary_body(cur2, out, sel):
        if uniform:
            masks = (sel == 1, sel == 2)
            for rect in exteriors:
                out = jacobi_sweep(cur2, out, rect, masks)
            return out
        return _patch_shells_dyn(spec, cur2, out, sel,
                                 multi_block_only=False)

    interior_fn = jax.jit(jax.shard_map(
        interior_body, mesh=ex.mesh,
        in_specs=(BLOCK_PSPEC,) * 3, out_specs=BLOCK_PSPEC))
    boundary_fn = jax.jit(jax.shard_map(
        boundary_body, mesh=ex.mesh,
        in_specs=(BLOCK_PSPEC,) * 3, out_specs=BLOCK_PSPEC))

    def loop(curr, nxt, sel):
        from ..obs import telemetry
        from ..parallel.remote_emu import run_fused_substep

        rec = telemetry.get()
        emu = ex._fused_host_schedule
        # hierarchical (ICI+DCN) plans: the fused inner messages wrap
        # within each host segment (remote_emu._seg_wrap), and the
        # cross-host slabs ride the sequential DCN schedule as a
        # post-finish fix-up before the boundary compute
        hier = ex._compiled if ex.hierarchical else None
        dcn = (None if hier is None
               else (lambda c2: hier.dcn_apply(c2, hier.dcn_start(c2))))
        if hier is not None:
            hier.last_transfer_count = 0
            hier.last_transfer_bytes = 0
        t_interior = 0.0
        t_total = 0.0
        for _ in range(iters or 1):
            cur2, out, t_int, t_tot = run_fused_substep(
                emu, curr,
                interior=lambda: interior_fn(curr, nxt, sel),
                boundary=lambda c2, o: boundary_fn(c2, o, sel),
                rec=rec, dcn=dcn,
            )
            t_interior += t_int
            t_total += t_tot
            curr, nxt = out, cur2  # the reference double-buffer swap
        if rec.enabled and t_total > 0:
            rec.gauge("fused.overlap_fraction", t_interior / t_total,
                      phase="exchange", variant="fused")
        return curr, nxt

    return loop


def _compile_jacobi_remote(ex: HaloExchange, iters,
                           temporal_k: Optional[int] = None,
                           multistep_rows: Optional[int] = None):
    """The REMOTE_DMA iteration: the exchange is NOT a ppermute program
    that can inline into the shard_map'd step — on TPU it is the carrier-
    kernel program (ops/remote_dma.py), off-TPU the host-orchestrated
    emulation (parallel/remote_emu.py) — so the step is a host-chunked
    serialized loop: one compiled exchange dispatch + one compiled
    collective-free sweep per iteration. Values are bit-identical to the
    AXIS_COMPOSED paths (the exchange fills the same cells; the sweep is
    the same shifted-slice program reading the same exchanged state —
    tests/test_remote_dma.py pins the full step). Fusing the carrier
    into the substep kernel itself (the §5.8 endgame) is the hardware
    session's follow-up, staged behind scripts/probe_remote_dma.py."""
    spec = ex.spec
    r = spec.radius
    assert min(
        r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1)
    ) >= 1, "jacobi needs face radius >= 1 on every side"
    if temporal_k is not None or multistep_rows is not None:
        from ..utils import logging as log

        log.warn(
            f"temporal_k={temporal_k} multistep_rows={multistep_rows} "
            "ignored: the temporal multistep composes with in-step "
            "ppermute exchanges; the REMOTE_DMA path runs per-step "
            "exchange + sweep dispatches"
        )
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)

    def sweep_body(curr, nxt, sel):
        masks = (sel == 1, sel == 2)
        return jacobi_sweep(curr, nxt, compute, masks)

    sweep = jax.jit(jax.shard_map(
        sweep_body, mesh=ex.mesh,
        in_specs=(BLOCK_PSPEC,) * 3, out_specs=BLOCK_PSPEC,
    ))

    def loop(curr, nxt, sel):
        for _ in range(iters or 1):
            curr = ex(curr)        # kernel-initiated / emulated exchange
            out = sweep(curr, nxt, sel)
            curr, nxt = out, curr  # the reference double-buffer swap
        return curr, nxt

    return loop


def _compile_jacobi_persistent(ex: HaloExchange, iters,
                               temporal_k: Optional[int] = None,
                               multistep_rows: Optional[int] = None,
                               interpret: bool = False):
    """The PERSISTENT whole-chunk iteration (ROADMAP #7): one k-step
    chunk = ONE deep (radius*k) exchange + ONE chunk program — k
    substeps over shrinking grown regions with no further exchange
    (ops/persistent_stencil.py owns the chunk math and the parity
    argument). Launch count drops from O(steps) to O(chunks): 2 host
    dispatches per chunk on the host-orchestrated schedule (the CPU
    emulation and this container's pin), ONE mega-kernel per chunk on
    an all-TPU aligned uniform mesh (the in-kernel exchange; item-1
    recalibrates the plan's conservative 2-dispatch model there).

    The driver realizes the spec at radius*k (exactly the deep-halo
    multistep opt-in, see the temporal-blocking comment below) and
    passes ``temporal_k=k``; without it the depth defaults to the
    realized min face radius. ``sel`` is exchanged ONCE per loop call
    (step-invariant) so grown-region sweeps re-impose neighbor sphere
    cells bit-identically.

    The measured launch census lands in ``ex.last_launches_per_chunk``
    after every loop call — what record_exchange_truth reports and
    analysis/verify_plan.py audits against the plan's
    ``launches_per_chunk`` prediction."""
    from .persistent_stencil import (chunk_schedule,
                                     make_persistent_chunk_body,
                                     persistent_kernel_supported)

    spec = ex.spec
    r = spec.radius
    rmin = min(r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1))
    if rmin < 1:
        raise ValueError("jacobi needs face radius >= 1 on every side")
    k = int(temporal_k) if temporal_k is not None else rmin
    if k < 1:
        raise ValueError(f"persistent temporal_k must be >= 1, got {k}")
    if multistep_rows is not None:
        from ..utils import logging as log

        log.warn(
            f"multistep_rows={multistep_rows} ignored: row-strip staging "
            "is the composed multistep's knob; the persistent chunk "
            "re-sweeps whole grown regions"
        )
    sched = chunk_schedule(iters or 1, k)
    on_tpu = all(d.platform == "tpu" for d in ex.mesh.devices.flatten())
    use_kernel = (on_tpu and spec.aligned and not interpret
                  and persistent_kernel_supported(spec, ex.resident))
    p = spec.padded()

    # one compiled chunk program per distinct depth (a shallow tail
    # chunk reuses the same machinery at its own depth)
    chunk_fns = {}
    kernel_depths = set()
    for d in set(sched):
        if use_kernel and d >= 2:
            from .persistent_stencil import make_persistent_jacobi_kernel

            kern = make_persistent_jacobi_kernel(spec, ex.plan, d)

            def kbody(curr, nxt, sel, _kern=kern, _d=d):
                c2, o2, _s2 = _kern(
                    curr.reshape(p.z, p.y, p.x),
                    nxt.reshape(p.z, p.y, p.x),
                    sel.reshape(p.z, p.y, p.x),
                )
                fin, scr = (o2, c2) if _d % 2 else (c2, o2)
                return fin.reshape(curr.shape), scr.reshape(curr.shape)

            chunk_fns[d] = jax.jit(jax.shard_map(
                kbody, mesh=ex.mesh,
                in_specs=(BLOCK_PSPEC,) * 3,
                out_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
            ))
            kernel_depths.add(d)
        else:
            body = make_persistent_chunk_body(spec, d)
            chunk_fns[d] = jax.jit(jax.shard_map(
                body, mesh=ex.mesh,
                in_specs=(BLOCK_PSPEC,) * 3,
                out_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
            ))

    def loop(curr, nxt, sel):
        # sel halos once per loop call (step-invariant; excluded from
        # the per-chunk census — a loop invariant, not a chunk cost)
        sel2 = ex(sel)
        launches = 0
        for d in sched:
            if d in kernel_depths:
                # the mega-kernel exchanges in-kernel: ONE dispatch
                out, scratch = chunk_fns[d](curr, nxt, sel2)
                launches += 1
            else:
                curr = ex(curr)  # deep halo, once per chunk
                out, scratch = chunk_fns[d](curr, nxt, sel2)
                launches += 2
            curr, nxt = out, scratch
        ex.last_launches_per_chunk = launches // len(sched)
        return curr, nxt

    return loop


def _compile_jacobi(ex: HaloExchange, overlap: bool, iters, use_pallas=None,
                    standard_spheres: bool = True, interpret: bool = False,
                    temporal_k: Optional[int] = None,
                    multistep_rows: Optional[int] = None):
    spec = ex.spec
    r = spec.radius
    if ex.method == Method.AUTO_SPMD:
        return _compile_jacobi_auto(ex, overlap, iters, temporal_k,
                                    multistep_rows)
    if ex.method == Method.REMOTE_DMA:
        if getattr(ex, "persistent", False):
            return _compile_jacobi_persistent(ex, iters, temporal_k,
                                              multistep_rows, interpret)
        if getattr(ex, "fused", False):
            return _compile_jacobi_fused(ex, iters, temporal_k,
                                         multistep_rows, interpret)
        return _compile_jacobi_remote(ex, iters, temporal_k, multistep_rows)
    if ex.hierarchical:
        # hierarchical AXIS_COMPOSED: the DCN level is host-orchestrated
        # (parallel/hierarchy.py), so the cross-host slabs cannot inline
        # into one compiled shard_map step program. The step serializes
        # exactly like REMOTE_DMA — one hierarchical exchange dispatch
        # (which overlaps the DCN copies behind the compiled DCN-axis
        # phase internally) + one compiled collective-free sweep per
        # step; bit-identical to the inline composed step because the
        # sweep reads the same fully-exchanged state.
        return _compile_jacobi_remote(ex, iters, temporal_k, multistep_rows)
    assert min(r.y(-1), r.y(1), r.z(-1), r.z(1)) >= 1, (
        "jacobi needs face radius >= 1 on every side"
    )
    tight_x = min(r.x(-1), r.x(1)) < 1
    # tight-x on a MULTI-BLOCK x axis: kernels still roll x block-locally
    # (wrong at block edges) and the exchange delivers the true neighbor
    # columns as side buffers, from which the two x-edge columns are
    # patched (VERDICT r3 item 5; reference pack-to-buffer economics,
    # src/pack_kernel.cu:3-54)
    side_x = tight_x and spec.dim.x > 1
    if tight_x:
        # zero-x-radius layout (Radius.without_x): no x halo columns exist;
        # only the Pallas kernels can form the x neighborhood (lane rolls).
        # Single-block x wraps periodically in-kernel; multi-block x takes
        # side buffers. Multi-block y/z overlap shells span the full x
        # extent and take the roll-aware sweep (_sweep_shell_wrap_x).
        assert spec.base.x % 128 == 0, (
            "zero x radius requires lane-aligned per-block x extents"
        )
        assert spec.is_uniform(), (
            "tight-x with multi-block axes requires uniform splits (dynamic "
            "shells keep inline halos)"
        )
        assert _want_pallas(ex, use_pallas), (
            "zero x radius requires the Pallas fast path (in-kernel x wrap)"
        )
        assert ex.resident.x == 1, (
            "tight-x does not support x residency (side buffers are "
            "single-resident along x)"
        )
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)
    interior = interior_region(compute, r)
    exteriors = exterior_regions(compute, interior)
    use_overlap = overlap and spec.is_uniform()
    # uneven partitions overlap too — via dynamic-offset shells instead of
    # static exterior rects (per-block extents are static per block index).
    # Resident (oversubscribed) shards carry a stacked leading block dim the
    # shell machinery's (pz,py,px) reshape cannot express — those fall back
    # to the serialized exchange-then-sweep path instead of crashing at
    # trace time (ADVICE r3).
    use_dyn_overlap = overlap and not spec.is_uniform() and not ex.oversubscribed

    pallas_sweep = None
    pallas_axes = None
    if _want_pallas(ex, use_pallas):
        from .pallas_stencil import make_pallas_jacobi_sweep, sel_z_range
        from ..parallel.mesh import AXIS_X, AXIS_Y, AXIS_Z, MESH_AXES

        # axes with a single block are periodic onto themselves: the kernel
        # fills those halos from the opposite face (wrap), and the exchange
        # runs only on the multi-block axes (engages exchange_block's axis
        # subsetting, AXIS_COMPOSED only). On one chip the exchange
        # vanishes entirely.
        if ex.method == Method.AXIS_COMPOSED:
            # side_x: the kernel rolls x block-locally exactly like a
            # self-wrap axis; the block-edge columns are patched from the
            # exchanged side buffers afterwards
            wrap = (spec.dim.z == 1, spec.dim.y == 1,
                    spec.dim.x == 1 or side_x)
            pallas_axes = tuple(
                name for name, w in zip((AXIS_Z, AXIS_Y, AXIS_X), wrap) if not w
            )
        else:
            assert not side_x, (
                "multi-block tight-x requires Method.AXIS_COMPOSED "
                "(side buffers compose with axis phases)"
            )
            wrap = (False, False, False)
            pallas_axes = None  # DIRECT26 has no axis phases to subset
        # interpret mode (CI integration tests): the pallas HLO interpreter
        # cannot propagate varying-manual-axes metadata
        pallas_sweep = make_pallas_jacobi_sweep(
            spec, sel_z_range(spec),
            vma=None if interpret else MESH_AXES,
            wrap=wrap, interpret=interpret,
        )

    # shells to re-sweep from exchanged halos when the Pallas fast path
    # overlaps comm with compute: only the sides whose axis actually has
    # multiple blocks (self-wrap sides are filled inside the kernel). The
    # redundant compute is the shell volume (~6 r-thick faces, <1% at
    # benchmark sizes) — the price of making the full-region kernel the
    # "interior" of the reference's overlap structure
    # (bin/jacobi3d.cu:296-368) without a second kernel variant.
    pallas_shells = []
    if pallas_sweep is not None and pallas_axes:
        shrink_lo = Dim3(
            r.x(-1) if spec.dim.x > 1 else 0,
            r.y(-1) if spec.dim.y > 1 else 0,
            r.z(-1) if spec.dim.z > 1 else 0,
        )
        shrink_hi = Dim3(
            r.x(1) if spec.dim.x > 1 else 0,
            r.y(1) if spec.dim.y > 1 else 0,
            r.z(1) if spec.dim.z > 1 else 0,
        )
        inner = Rect3(compute.lo + shrink_lo, compute.hi - shrink_hi)
        pallas_shells = exterior_regions(compute, inner)

    nres = ex.resident.flatten()

    def body(curr, nxt, sel):
        if pallas_sweep is not None:
            p = spec.padded()

            def sweep3(c, n):
                if nres == 1:
                    return pallas_sweep(
                        c.reshape(p.z, p.y, p.x),
                        n.reshape(p.z, p.y, p.x),
                        sel.reshape(p.z, p.y, p.x),
                    ).reshape(nxt.shape)
                # resident (oversubscribed) shard: the leading block dims
                # stack whole padded blocks, each with exchange-filled
                # halos — the per-block kernel runs once per resident
                cf = c.reshape(nres, p.z, p.y, p.x)
                nf = n.reshape(nres, p.z, p.y, p.x)
                sf = sel.reshape(nres, p.z, p.y, p.x)
                return jnp.stack(
                    [pallas_sweep(cf[j], nf[j], sf[j]) for j in range(nres)]
                ).reshape(nxt.shape)

            if pallas_axes is None:  # DIRECT26: no axis phases to subset
                cur2 = ex.exchange_block(curr)
                return sweep3(cur2, nxt), cur2
            if side_x:
                # multi-block x without inline halos: the kernel's x rolls
                # wrap onto the block's own columns; the exchange delivers
                # the true neighbor columns as side buffers and the two
                # edge columns are re-swept from them (after any y/z
                # shells, so edge cells inside shells are also correct)
                masks = (sel == 1, sel == 2)
                if use_overlap:
                    out = sweep3(curr, nxt)
                    cur2 = ex.exchange_block(curr)
                    xlo, xhi = ex.x_side_buffers(curr, 1)
                    for rect in pallas_shells:
                        out = _sweep_shell_wrap_x(cur2, out, rect, masks)
                else:
                    # FULL exchange (self-wrap fills included): the edge
                    # patch reads y/z halo rows of the edge columns, which
                    # the axis-subset exchange would leave stale
                    cur2 = ex.exchange_block(curr)
                    xlo, xhi = ex.x_side_buffers(cur2, 1)
                    out = sweep3(cur2, nxt)
                out = _patch_x_edges_sidebuf(cur2, out, compute, xlo, xhi, masks)
                return out, cur2
            if not pallas_axes:  # every axis self-wraps: no exchange at all
                return sweep3(curr, nxt), curr
            if use_overlap:
                # overlap as dataflow (reference: interior kernel concurrent
                # with the exchange, src/stencil.cu:1002-1186): the full
                # sweep reads PRE-exchange data — XLA is free to schedule
                # the ppermutes concurrently — then the multi-block-axis
                # shells are re-swept from the exchanged halos. The shells'
                # stencils also read self-wrap-axis halos, which the kernel
                # normally wraps internally, so this path runs the FULL
                # exchange (self-wrap fills included), not the subset
                out = sweep3(curr, nxt)
                cur2 = ex.exchange_block(curr)
                masks = (sel == 1, sel == 2)
                shell_sweep = _sweep_shell_wrap_x if tight_x else jacobi_sweep
                for rect in pallas_shells:
                    out = shell_sweep(cur2, out, rect, masks)
                return out, cur2
            if use_dyn_overlap:
                # same structure, uneven partition: the kernel still wraps
                # self-wrap axes internally, so only multi-block-axis shells
                # need patching — at dynamic offsets (hi side of an uneven
                # axis sits at off + this_block_size - r)
                out = sweep3(curr, nxt)
                cur2 = ex.exchange_block(curr)
                out = _patch_shells_dyn(spec, cur2, out, sel, multi_block_only=True)
                return out, cur2
            cur2 = ex.exchange_block(curr, axes=pallas_axes)
            return sweep3(cur2, nxt), cur2
        masks = (sel == 1, sel == 2)
        if use_overlap:
            out = jacobi_sweep(curr, nxt, interior, masks)
            cur2 = ex.exchange_block(curr)
            for rect in exteriors:
                out = jacobi_sweep(cur2, out, rect, masks)
        elif use_dyn_overlap:
            # uneven: full-region sweep on PRE-exchange data (cells within r
            # of a boundary read stale halos and are re-swept below; jacobi
            # never reads the out buffer, so the over-write is harmless),
            # exchange concurrent by dataflow, then dynamic-offset shells on
            # every side (self-wrap halos are stale pre-exchange too)
            out = jacobi_sweep(curr, nxt, compute, masks)
            cur2 = ex.exchange_block(curr)
            out = _patch_shells_dyn(spec, cur2, out, sel, multi_block_only=False)
        else:
            cur2 = ex.exchange_block(curr)
            out = jacobi_sweep(cur2, nxt, compute, masks)
        # swap: computed buffer becomes curr, old curr becomes scratch
        return out, cur2

    # temporal blocking: advance k steps per HBM pass when the loop is
    # fused — the stencil is purely memory-bound, so HBM traffic drops
    # ~1/k. The depth cap is re-measured whenever the kernels change
    # (STENCIL_TEMPORAL_K_CAP probes deeper): the pre-tight-x kernels
    # plateaued at k=10 (3.20 ms/step, round 2); the tight-x kernels
    # plateau at k=12 (512^3 round 5: k=10 1.752, k=12 1.695, k=13 1.696
    # ms/iter — scripts/r05_logs/k512.log). Depth is further bounded by
    # the z extent (pipeline needs nz >= 2k+1) and by the staging planes
    # fitting the VMEM budget ((k-1)*3 + 6 full planes). On a single
    # block every axis self-wraps in-kernel; on a uniform multi-block
    # mesh the same kernel runs in deep-halo mode — one radius-k exchange
    # per k steps (the communication-avoiding scheme; k is then also
    # bounded by the realized multi-block-axis radii, so drivers opt in
    # by realizing with radius k).
    multistep = None
    deep_halo = False
    TEMPORAL_K = 0
    STRIP_ROWS = None
    # side_x is excluded: its empty/partial pallas_axes would read as
    # "self-wrap" to the multistep, whose in-kernel x wrap is wrong at
    # block edges (deep-halo x needs radius >= k, which tight-x lacks)
    if (pallas_sweep is not None and pallas_axes is not None and not side_x
            and standard_spheres and iters and spec.is_uniform()):
        import os

        from .pallas_stencil import plan_multistep_staging

        budget = 46 * 1024 * 1024  # measured compile ceiling minus headroom
        try:
            hard_cap = int(os.environ.get("STENCIL_TEMPORAL_K_CAP", "12"))
        except ValueError as e:
            raise ValueError(
                "STENCIL_TEMPORAL_K_CAP must be an integer, got "
                f"{os.environ['STENCIL_TEMPORAL_K_CAP']!r}"
            ) from e
        k_want = max(0, min(hard_cap, (spec.base.z - 1) // 2, iters))
        if temporal_k is not None:
            k_want = min(k_want, temporal_k)
        if pallas_axes:
            # multi-block: the fused multistep subsumes the overlap
            # structure, so it only engages when overlap was requested —
            # overlap=False must keep timing the serialized reference
            # structure (the A/B knob the benchmarks rely on)
            r_mb = [
                rr for m, rl, rh in (
                    (spec.dim.z > 1, r.z(-1), r.z(1)),
                    (spec.dim.y > 1, r.y(-1), r.y(1)),
                    (spec.dim.x > 1, r.x(-1), r.x(1)),
                ) if m for rr in (rl, rh)
            ]
            k_want = min(k_want, *r_mb)
        # staging plan: full planes while they reach k_want, row strips
        # when the plane size would otherwise self-cap the depth (the
        # 768^3 regime: k=4 full-plane -> k=12 row-tiled)
        k_cap, STRIP_ROWS = plan_multistep_staging(spec, k_want, budget)
        if multistep_rows is not None:
            from .pallas_stencil import valid_strip_rows

            assert valid_strip_rows(spec, k_cap, multistep_rows), (
                f"multistep_rows={multistep_rows} illegal for k={k_cap}, "
                f"ny={spec.base.y}"
            )
            STRIP_ROWS = multistep_rows
        if pallas_axes:
            deep_halo = overlap and k_cap >= 2
            TEMPORAL_K = k_cap if deep_halo else 0
        else:
            TEMPORAL_K = k_cap
    if multistep_rows is not None and TEMPORAL_K < 2:
        # a probe run must never attribute legacy-path numbers to row
        # tiling because the multistep quietly failed to engage
        from ..utils import logging as log

        log.warn(
            f"multistep_rows={multistep_rows} ignored: the temporal "
            "multistep did not engage (overlap off, non-uniform partition, "
            "side-buffer tight-x, iters/radius too small, or non-Pallas "
            "path) — timings reflect the per-step kernels"
        )
    if TEMPORAL_K >= 2:
        from .pallas_stencil import make_pallas_jacobi_multistep
        from ..parallel.mesh import MESH_AXES

        multistep = make_pallas_jacobi_multistep(
            spec, TEMPORAL_K,
            vma=None if interpret else MESH_AXES, interpret=interpret,
            rows=STRIP_ROWS,
        )

    def entry_fn(curr, nxt, sel):
        if multistep is not None:
            p = spec.padded()
            res = (ex.resident.z, ex.resident.y, ex.resident.x)
            if deep_halo:
                from ..parallel.mesh import AXIS_X, AXIS_Y, AXIS_Z

                idx = [
                    lax.axis_index(n) if d > 1 else 0
                    for n, d in ((AXIS_Z, spec.dim.z), (AXIS_Y, spec.dim.y),
                                 (AXIS_X, spec.dim.x))
                ]

                def origin(jz, jy, jx):
                    # global block index = device index * residents + j
                    # (leading block dims shard in contiguous chunks)
                    return jnp.stack([
                        jnp.asarray((idx[0] * res[0] + jz) * spec.base.z, jnp.int32),
                        jnp.asarray((idx[1] * res[1] + jy) * spec.base.y, jnp.int32),
                        jnp.asarray((idx[2] * res[2] + jx) * spec.base.x, jnp.int32),
                    ])

            def run_multi(c, x):
                if nres == 1:
                    if deep_halo:
                        return multistep(
                            origin(0, 0, 0), c.reshape(p.z, p.y, p.x),
                            x.reshape(p.z, p.y, p.x),
                        ).reshape(c.shape)
                    return multistep(
                        c.reshape(p.z, p.y, p.x), x.reshape(p.z, p.y, p.x)
                    ).reshape(c.shape)
                # resident shard: one multistep per stacked block, each at
                # its own global origin (residency implies multi-block axes,
                # so this is always the deep-halo form)
                assert deep_halo
                cf = c.reshape(nres, p.z, p.y, p.x)
                xf = x.reshape(nres, p.z, p.y, p.x)
                outs = []
                for j in range(nres):
                    jz, rem = divmod(j, res[1] * res[2])
                    jy, jx = divmod(rem, res[2])
                    outs.append(multistep(origin(jz, jy, jx), cf[j], xf[j]))
                return jnp.stack(outs).reshape(c.shape)

            def mbody(cn):
                c, x = cn
                if deep_halo:
                    # one radius-k exchange feeds k fused steps; self-wrap
                    # axes are still wrapped inside the kernel
                    c = ex.exchange_block(c, axes=pallas_axes)
                return (run_multi(c, x), c)

            n_multi, n_single = divmod(iters, TEMPORAL_K)
            cn = (curr, nxt)
            if n_multi:
                cn = jax.lax.fori_loop(0, n_multi, lambda _, c: mbody(c), cn)
            for _ in range(n_single):
                cn = body(cn[0], cn[1], sel)
            return cn
        if iters is None:
            return body(curr, nxt, sel)
        return jax.lax.fori_loop(
            0, iters, lambda _, cn: body(cn[0], cn[1], sel), (curr, nxt)
        )

    fn = jax.shard_map(
        entry_fn,
        mesh=ex.mesh,
        in_specs=(BLOCK_PSPEC,) * 3,
        out_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
        check_vma=not interpret,
    )
    return jax.jit(fn, donate_argnums=(0, 1))


def make_batched_jacobi_loop(spec, iters: int, *, sharding=None,
                             sel_sharding=None, use_pallas: bool = False,
                             batch: Optional[int] = None,
                             interpret: bool = False):
    """The multi-tenant batched iteration: ``loop(curr, nxt, sel) ->
    (new_curr, new_next)`` over ``(B, pz, py, px)`` stacked tenant states,
    advancing every tenant ``iters`` steps inside ONE compiled program.

    ``spec`` describes ONE tenant as a single-block domain
    (``GridSpec(size, Dim3(1, 1, 1), radius)``); the leading batch axis
    stacks B independent tenants. Each tenant is its own periodic box:
    halos self-wrap per tenant (ops/halo_fill.wrap_fill_batched — the
    composed x->y->z fill order of a single-block HaloExchange), NEVER
    across the batch axis, and the sweep is the same
    :func:`jacobi_sweep` (leading dims ride the ``...`` slices), so each
    lane is bit-identical to running that tenant through the standard
    single-domain machinery (pinned by tests/test_campaign.py).

    The program is embarrassingly batch-parallel — zero collectives —
    so ``sharding`` (a ``NamedSharding`` splitting axis 0 over a 1-D
    device mesh) serves B tenants across the whole mesh under one jit:
    the serving program of the campaign driver
    (stencil_tpu/campaign/driver.py). ``sel_sharding`` covers the sel
    argument (pass a replicated sharding for a shared ``(pz, py, px)``
    sel, or reuse ``sharding`` for per-tenant sel).

    ``use_pallas=True`` swaps the XLA shifted-slice sweep for the Pallas
    kernel with a leading batch grid dimension and all-axes in-kernel
    wrap (``make_pallas_jacobi_sweep(batch=...)``) — the TPU fast path;
    it requires ``batch`` (static), an aligned spec, and a per-tenant
    ``(B, pz, py, px)`` sel. Buffers are NOT donated: the campaign
    driver keeps live references across rollbacks (fault/recover.py
    stash semantics), which donation would invalidate.
    """
    from ..geometry import Dim3 as _D3

    if spec.dim != _D3(1, 1, 1):
        raise ValueError(
            "batched tenants are single-block domains; got partition "
            f"{spec.dim} (spatial decomposition and tenant batching do "
            "not compose yet)"
        )
    r = spec.radius
    if min(r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1)) < 1:
        raise ValueError("jacobi needs face radius >= 1 on every side")
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)

    pallas_sweep = None
    if use_pallas:
        from .pallas_stencil import make_pallas_jacobi_sweep, sel_z_range

        if batch is None or batch < 1:
            raise ValueError("use_pallas needs the static batch size")
        pallas_sweep = make_pallas_jacobi_sweep(
            spec, sel_z_range(spec), wrap=(True, True, True),
            batch=batch, interpret=interpret,
        )

    from .halo_fill import wrap_fill_batched

    def body(curr, nxt, sel):
        if pallas_sweep is not None:
            # all three axes wrap in-kernel (each tenant is periodic onto
            # itself); jacobi reads only face halos, which the kernel
            # fills — no separate fill pass exists on this path
            out = pallas_sweep(curr, nxt, sel)
            return out, curr
        cur2 = wrap_fill_batched(spec, curr)
        masks = (sel == 1, sel == 2)
        out = jacobi_sweep(cur2, nxt, compute, masks)
        return out, cur2

    def entry_fn(curr, nxt, sel):
        if iters == 1:
            return body(curr, nxt, sel)
        return jax.lax.fori_loop(
            0, iters, lambda _, cn: body(cn[0], cn[1], sel), (curr, nxt)
        )

    with timer.timed("jacobi.build"), timer.trace_range("jacobi.build"):
        if sharding is None:
            return jax.jit(entry_fn)
        return jax.jit(
            entry_fn,
            in_shardings=(sharding, sharding, sel_sharding or sharding),
            out_shardings=(sharding, sharding),
        )


def sphere_masks(global_size) -> Tuple[np.ndarray, np.ndarray]:
    """Hot/cold sphere masks over the global [z,y,x] grid.

    Bit-parity with the reference's integer-truncated distance
    (bin/jacobi3d.cu:30-32,49): dist = int64(sqrtf(dx^2+dy^2+dz^2)),
    hot iff dist(hotCenter) <= X/10."""
    g = Dim3.of(global_size)
    hot_c = (g.x // 3, g.y // 2, g.z // 2)
    cold_c = (g.x * 2 // 3, g.y // 2, g.z // 2)
    rad = g.x // 10
    # sparse (broadcastable) coordinate axes: only the final dense d2 array
    # is full-size, not three int64 coordinate cubes
    z, y, x = np.meshgrid(
        np.arange(g.z), np.arange(g.y), np.arange(g.x), indexing="ij", sparse=True
    )

    def dist(c):
        d2 = (x - c[0]) ** 2 + (y - c[1]) ** 2 + (z - c[2]) ** 2
        return np.sqrt(d2.astype(np.float32)).astype(np.int64)

    hot = dist(hot_c) <= rad
    cold = (~hot) & (dist(cold_c) <= rad)
    return hot, cold


def sphere_sel(global_size) -> np.ndarray:
    """Hot/cold spheres packed into one int32 array: 0 stencil, 1 hot,
    2 cold — the layout both compute paths consume."""
    hot, cold = sphere_masks(global_size)
    sel = np.zeros(hot.shape, np.int32)
    sel[hot] = 1
    sel[cold] = 2
    return sel


def jacobi_reference(field: np.ndarray, masks, iters: int) -> np.ndarray:
    """Slow numpy reference with periodic wrap for correctness checks
    (the CPU reference of BASELINE.json config 1)."""
    hot, cold = masks
    f = field.astype(np.float64)
    for _ in range(iters):
        avg = (
            np.roll(f, 1, 2) + np.roll(f, -1, 2)
            + np.roll(f, 1, 1) + np.roll(f, -1, 1)
            + np.roll(f, 1, 0) + np.roll(f, -1, 0)
        ) / 6
        f = np.where(hot, HOT_TEMP, np.where(cold, COLD_TEMP, avg))
    return f
