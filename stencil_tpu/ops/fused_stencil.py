"""Fused compute+exchange mega-kernel: overlap REMOTE_DMA behind tiles.

The §5.8 endgame of the kernel-initiated transport (ops/remote_dma.py,
PR 10): that carrier runs as a SEPARATE ``pallas_call`` serialized with
the sweep, so its zero-ppermute DMAs buy zero overlap. This module fuses
them — ONE Pallas kernel per exchange+sweep substep that

1. barriers with every ring neighbor, packs the boundary slabs, and
   STARTs all per-neighbor ``pltpu.make_async_remote_copy``s
   boundary-first (every send is in flight before any compute);
2. computes interior tiles while the DMAs fly;
3. waits the recv semaphores and unpacks the landings into the halos
   (``input_output_aliases`` — in-place, the reference's peer-access
   write);
4. computes the boundary tiles from the freshly exchanged halos.

So wire time hides behind interior FLOPs instead of preceding them — the
TPU analogue of the reference's L5 colocated peer-access transports and
the comm/compute-overlap thesis of the whole paper (src/stencil.cu:
1002-1186 overlap engine + tx_colocated.cu concurrent per-neighbor
writes).

Geometry: the composed x→y→z slab phases CANNOT start boundary-first (a
y slab carries x-halo data, so phase y's send depends on phase x's
receive). The fused schedule therefore moves one EXACT-extent message
per active direction — the plan's ``FusedPhaseIR`` records (plan/ir.py),
the DIRECT26 geometry re-transported as kernel-initiated copies: every
message reads only sender compute-region cells, so all of them start
concurrently and together they fill every declared halo cell
bit-identically to AXIS_COMPOSED. ``wire_dtype`` (bf16 or the fp8
``float8_e4m3fn`` tier) narrows wire-crossing carriers exactly like the
axis carrier; self-wrap hand-offs stay lossless.

This container has no TPU (no Pallas cross-device interpret mode), so —
the PR-10 discipline — the kernels here are exercised on hardware via
``scripts/probe_remote_dma.py``'s fused leg, while the host-orchestrated
emulation (``parallel/remote_emu.FusedRemoteEmulation``) pins the fused
schedule's semantics bit-identically to AXIS_COMPOSED on the CPU mesh
(tests/test_fused_stencil.py, scripts/ci_fused_gate.py). The one piece
that DOES run here is the all-self-wrap (single device) form of the
jacobi mega-kernel in interpret mode: no remote copies exist, so the
interior/boundary split and in-kernel wrap fills are parity-pinned
against the XLA step on any host.

First-cut scope (loud, never silent): single resident block per device;
the jacobi mega-kernel additionally wants uniform partitions (the
emulation owns uneven); the boundary pass re-streams whole planes —
exact but unturned, the hardware session's refinement. The astaroth
multistep folds in host-side (astaroth/integrate.make_fused_astaroth_loop
slots the ring-indexed substep kernels between the fused start/wait).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.halo_fill import wire_narrow_dtype


def fused_kernel_supported(spec, resident) -> bool:
    """What the fused TPU kernels handle today: UNIFORM partitions, one
    resident block per device (the per-direction extents are static in
    the kernel). Uneven single-resident fused runs the host-orchestrated
    schedule (``HaloExchange._fused_host_schedule`` — the step loops use
    it directly); oversubscription is loud infeasibility at HaloExchange
    construction. Extending the TPU carrier to uneven size-tables, like
    ops/remote_dma.py's axis carrier, is the hardware session's
    follow-up."""
    from ..geometry import Dim3

    return spec.is_uniform() and resident == Dim3(1, 1, 1)


def _dir_geometry(spec, phase):
    """Static (src starts, dst starts, extents) in (z, y, x) block-local
    coordinates for one FusedPhaseIR on a UNIFORM partition."""
    assert phase.src is not None and phase.dst is not None, (
        "fused TPU kernels take uniform partitions (the emulation owns "
        "uneven geometry)"
    )
    return phase.src, phase.dst, phase.shape


def _device_id_for(phase):
    """Mesh-axis device_id dict targeting the +direction neighbor."""
    dx, dy, dz = phase.direction
    out = {}
    for axis, comp in (("z", dz), ("y", dy), ("x", dx)):
        if comp:
            out[axis] = comp  # resolved to axis_index + comp in-kernel
    return out


def make_fused_exchange_kernel(spec, plan, nq: int, dtype,
                               wire_dtype: Optional[str] = None,
                               collective_id: int = 0):
    """The exchange-only fused carrier: ``fn(*blocks) -> blocks`` over
    ``nq`` same-dtype (pz, py, px) padded blocks inside ``shard_map``,
    delivering EVERY active direction's message in one kernel — all
    remote copies started before any local work, local hand-offs and
    unpacks behind them. This is what ``HaloExchange(fused=True)``
    compiles per dtype group on TPU (exchange loops, probes); the
    compute-fused jacobi form is :func:`make_fused_jacobi_kernel`."""
    if not spec.is_uniform():
        raise ValueError(
            "the fused TPU carrier takes uniform partitions today; "
            "uneven fused stays with the CPU emulation until the "
            "hardware session extends it"
        )
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    wire = wire_narrow_dtype(dtype, wire_dtype)
    wdt = wire if wire is not None else dtype
    phases = list(plan.fused_phases)
    crossing = [ph for ph in phases if ph.crossing]
    local = [ph for ph in phases if not ph.crossing]
    n_cross = len(crossing)
    if n_cross == 0:
        raise ValueError(
            "fused exchange kernel needs at least one wire-crossing "
            "direction (an all-self-wrap mesh exchanges locally)"
        )

    def dslice(starts, shape):
        return tuple(pl.ds(s, w) for s, w in zip(starts, shape))

    def kernel(*refs):
        ins = refs[:nq]
        outs = refs[nq: 2 * nq]
        scratch = refs[2 * nq:]
        sends = scratch[0:n_cross]
        lands = scratch[n_cross: 2 * n_cross]
        stages = scratch[2 * n_cross: 3 * n_cross] if wire is not None else ()
        base = 3 * n_cross if wire is not None else 2 * n_cross
        send_sems, recv_sems, copy_sem = scratch[base: base + 3]

        idx = {a: lax.axis_index(a) for a in ("z", "y", "x")}
        ring = {"z": plan.mesh_dim[2], "y": plan.mesh_dim[1],
                "x": plan.mesh_dim[0]}

        def neighbor(ph):
            did = {}
            for axis, comp in _device_id_for(ph).items():
                did[axis] = (idx[axis] + comp) % ring[axis]
            return did

        # 1. barrier: every neighbor this kernel writes into must be
        # quiescent; each device receives exactly one signal per
        # crossing direction (wrap rings make the count symmetric)
        barrier = pltpu.get_barrier_semaphore()
        for ph in crossing:
            pltpu.semaphore_signal(
                barrier, inc=1, device_id=neighbor(ph),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
        pltpu.semaphore_wait(barrier, n_cross)

        # 2. stage + START every remote copy, boundary-first
        rdmas = []
        for i, ph in enumerate(crossing):
            src, _dst, shape = _dir_geometry(spec, ph)
            for q in range(nq):
                if wire is None:
                    cp = pltpu.make_async_copy(
                        ins[q].at[dslice(src, shape)], sends[i].at[q],
                        copy_sem)
                    cp.start()
                    cp.wait()
                else:
                    cp = pltpu.make_async_copy(
                        ins[q].at[dslice(src, shape)], stages[i].at[q],
                        copy_sem)
                    cp.start()
                    cp.wait()
                    sends[i][q] = stages[i][q].astype(wdt)
            rdma = pltpu.make_async_remote_copy(
                src_ref=sends[i], dst_ref=lands[i],
                send_sem=send_sems.at[i], recv_sem=recv_sems.at[i],
                device_id=neighbor(ph),
                device_id_type=pltpu.DeviceIdType.MESH,
            )
            rdma.start()
            rdmas.append(rdma)

        # self-wrap hand-offs: pure local copies, lossless, overlapped
        # behind the in-flight sends
        for ph in local:
            src, dst, shape = _dir_geometry(spec, ph)
            for q in range(nq):
                cp = pltpu.make_async_copy(
                    ins[q].at[dslice(src, shape)],
                    outs[q].at[dslice(dst, shape)], copy_sem)
                cp.start()
                cp.wait()

        # 3. wait + unpack (widen) into the halos, in place
        for rdma in rdmas:
            rdma.wait()
        for i, ph in enumerate(crossing):
            _src, dst, shape = _dir_geometry(spec, ph)
            for q in range(nq):
                if wire is None:
                    cp = pltpu.make_async_copy(
                        lands[i].at[q], outs[q].at[dslice(dst, shape)],
                        copy_sem)
                    cp.start()
                    cp.wait()
                else:
                    stages[i][q] = lands[i][q].astype(dtype)
                    cp = pltpu.make_async_copy(
                        stages[i].at[q], outs[q].at[dslice(dst, shape)],
                        copy_sem)
                    cp.start()
                    cp.wait()

    block = jax.ShapeDtypeStruct((pz, py, px), dtype)
    scratch_shapes = (
        [pltpu.VMEM((nq,) + ph.shape, wdt) for ph in crossing]    # sends
        + [pltpu.VMEM((nq,) + ph.shape, wdt) for ph in crossing]  # lands
        + ([pltpu.VMEM((nq,) + ph.shape, dtype) for ph in crossing]
           if wire is not None else [])                           # cast stage
        + [
            pltpu.SemaphoreType.DMA((n_cross,)),
            pltpu.SemaphoreType.DMA((n_cross,)),
            pltpu.SemaphoreType.DMA(()),
        ]
    )
    return pl.pallas_call(
        kernel,
        grid=(1,),
        out_shape=(block,) * nq,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
        scratch_shapes=scratch_shapes,
        input_output_aliases={q: q for q in range(nq)},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            has_side_effects=True,
            collective_id=collective_id,
        ),
    )


def make_fused_jacobi_kernel(spec, plan, dtype=jnp.float32,
                             wire_dtype: Optional[str] = None,
                             collective_id: int = 0,
                             interpret: bool = False):
    """The jacobi mega-kernel: ``fn(curr, nxt, sel) -> (curr', out)`` —
    ONE ``pallas_call`` per substep running the full fused schedule:

    barrier → stage+start every remote copy → local self-wrap fills →
    full-region sweep on pre-exchange data (the "interior": its stencil
    reads stale wire halos only at boundary cells, re-swept below) →
    wait recv semaphores + unpack → re-sweep the boundary planes.

    ``curr'`` is the exchanged state (halos filled, aliased in place),
    ``out`` the swept field (aliased to ``nxt``). Plane-streamed: whole
    padded (py, px) planes ride HBM↔VMEM DMAs (tile-aligned by
    construction), the 6-neighbor average runs vector-side. The boundary
    pass re-streams the affected planes whole — exact (re-swept interior
    cells recompute identical values) but untuned; shell-extent staging
    is the hardware session's refinement.

    In interpret mode only the all-self-wrap (single device) form runs —
    no remote copies exist there — which parity-pins the sweep and the
    in-kernel wrap fills against the XLA step on any host
    (tests/test_fused_stencil.py)."""
    from ..geometry import Dim3
    from .jacobi import COLD_TEMP, HOT_TEMP

    if not spec.is_uniform():
        raise ValueError(
            "the fused jacobi mega-kernel takes uniform partitions "
            "today; uneven fused jacobi runs the host-orchestrated "
            "schedule (ops/jacobi._compile_jacobi_fused)"
        )
    r = spec.radius
    if min(r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1)) < 1:
        raise ValueError("jacobi needs face radius >= 1")
    p = spec.padded()
    pz, py, px = p.z, p.y, p.x
    off = spec.compute_offset()
    b = spec.base
    nz, ny, nx = b.z, b.y, b.x
    zo, yo, xo = off.z, off.y, off.x
    wire = wire_narrow_dtype(dtype, wire_dtype)
    wdt = wire if wire is not None else dtype
    phases = list(plan.fused_phases)
    crossing = [ph for ph in phases if ph.crossing]
    local = [ph for ph in phases if not ph.crossing]
    n_cross = len(crossing)
    if interpret and n_cross:
        raise ValueError(
            "interpret mode runs the all-self-wrap (single device) fused "
            "kernel only — remote copies have no interpreter"
        )
    multi = {"z": plan.mesh_dim[2] > 1, "y": plan.mesh_dim[1] > 1,
             "x": plan.mesh_dim[0] > 1}

    def dslice(starts, shape):
        return tuple(pl.ds(s, w) for s, w in zip(starts, shape))

    def kernel(curr, nxt, sel, curr_o, out_o, *scratch):
        sends = scratch[0:n_cross]
        lands = scratch[n_cross: 2 * n_cross]
        stages = scratch[2 * n_cross: 3 * n_cross] if wire is not None else ()
        base = 3 * n_cross if wire is not None else 2 * n_cross
        (planes, sel_pl, out_pl, send_sems, recv_sems, copy_sem) = \
            scratch[base: base + 6]

        idx = {a: lax.axis_index(a) if multi[a] else 0
               for a in ("z", "y", "x")}
        ring = {"z": plan.mesh_dim[2], "y": plan.mesh_dim[1],
                "x": plan.mesh_dim[0]}

        def neighbor(ph):
            return {axis: (idx[axis] + comp) % ring[axis]
                    for axis, comp in _device_id_for(ph).items()}

        rdmas = []
        if n_cross:
            # 1. barrier with every neighbor this kernel writes into
            barrier = pltpu.get_barrier_semaphore()
            for ph in crossing:
                pltpu.semaphore_signal(
                    barrier, inc=1, device_id=neighbor(ph),
                    device_id_type=pltpu.DeviceIdType.MESH,
                )
            pltpu.semaphore_wait(barrier, n_cross)

            # 2. stage + START every remote copy, boundary-first
            for i, ph in enumerate(crossing):
                src, _dst, shape = _dir_geometry(spec, ph)
                if wire is None:
                    cp = pltpu.make_async_copy(
                        curr.at[dslice(src, shape)], sends[i], copy_sem)
                    cp.start()
                    cp.wait()
                else:
                    cp = pltpu.make_async_copy(
                        curr.at[dslice(src, shape)], stages[i], copy_sem)
                    cp.start()
                    cp.wait()
                    sends[i][...] = stages[i][...].astype(wdt)
                rdma = pltpu.make_async_remote_copy(
                    src_ref=sends[i], dst_ref=lands[i],
                    send_sem=send_sems.at[i], recv_sem=recv_sems.at[i],
                    device_id=neighbor(ph),
                    device_id_type=pltpu.DeviceIdType.MESH,
                )
                rdma.start()
                rdmas.append(rdma)

        # self-wrap hand-offs: local, lossless, behind the in-flight sends
        for ph in local:
            src, dst, shape = _dir_geometry(spec, ph)
            cp = pltpu.make_async_copy(
                curr.at[dslice(src, shape)],
                curr_o.at[dslice(dst, shape)], copy_sem)
            cp.start()
            cp.wait()

        def load_plane(slot, z):
            cp = pltpu.make_async_copy(
                curr_o.at[pl.ds(z, 1)], planes.at[slot], copy_sem)
            cp.start()
            cp.wait()

        def sweep_plane(z):
            """One full compute plane: load z-1, z, z+1 + sel + the out
            plane, average vector-side, merge, store the plane back."""
            for s, dz in enumerate((-1, 0, 1)):
                load_plane(s, z + dz)
            cp = pltpu.make_async_copy(
                sel.at[pl.ds(z, 1)], sel_pl, copy_sem)
            cp.start()
            cp.wait()
            cp = pltpu.make_async_copy(
                nxt.at[pl.ds(z, 1)], out_pl, copy_sem)
            cp.start()
            cp.wait()
            c = planes[1, 0]
            ys = slice(yo, yo + ny)
            xs = slice(xo, xo + nx)
            avg = (
                c[ys, slice(xo - 1, xo + nx - 1)]
                + c[ys, slice(xo + 1, xo + nx + 1)]
                + c[slice(yo - 1, yo + ny - 1), xs]
                + c[slice(yo + 1, yo + ny + 1), xs]
                + planes[0, 0][ys, xs]
                + planes[2, 0][ys, xs]
            ) / 6
            sl = sel_pl[0][ys, xs]
            avg = jnp.where(sl == 1, HOT_TEMP,
                            jnp.where(sl == 2, COLD_TEMP, avg))
            out_pl[0, ys, xs] = avg.astype(dtype)
            cp = pltpu.make_async_copy(
                out_pl, out_o.at[pl.ds(z, 1)], copy_sem)
            cp.start()
            cp.wait()

        # interior: the full-region sweep on pre-exchange data — every
        # plane whose stencil never reads a wire halo is final here
        def body(i, _):
            sweep_plane(zo + i)
            return 0

        lax.fori_loop(0, nz, body, 0)

        if n_cross:
            # 3. wait + unpack the landings into the halos, in place
            for rdma in rdmas:
                rdma.wait()
            for i, ph in enumerate(crossing):
                _src, dst, shape = _dir_geometry(spec, ph)
                if wire is None:
                    cp = pltpu.make_async_copy(
                        lands[i], curr_o.at[dslice(dst, shape)], copy_sem)
                    cp.start()
                    cp.wait()
                else:
                    stages[i][...] = lands[i][...].astype(dtype)
                    cp = pltpu.make_async_copy(
                        stages[i], curr_o.at[dslice(dst, shape)], copy_sem)
                    cp.start()
                    cp.wait()

            # 4. boundary: re-sweep the planes whose stencils read wire
            # halos. Re-swept interior cells recompute identical values,
            # so whole-plane re-sweeps are exact; z-only meshes (the
            # z-heavy NodePartition default) touch just 2 planes.
            if multi["x"] or multi["y"]:
                lax.fori_loop(0, nz, body, 0)
            else:
                sweep_plane(zo)
                sweep_plane(zo + nz - 1)

    block = jax.ShapeDtypeStruct((pz, py, px), dtype)
    sel_block = jax.ShapeDtypeStruct((pz, py, px), jnp.int32)
    scratch_shapes = (
        [pltpu.VMEM(ph.shape, wdt) for ph in crossing]    # sends
        + [pltpu.VMEM(ph.shape, wdt) for ph in crossing]  # lands
        + ([pltpu.VMEM(ph.shape, dtype) for ph in crossing]
           if wire is not None else [])                   # cast staging
        + [
            pltpu.VMEM((3, 1, py, px), dtype),   # in-plane window
            pltpu.VMEM((1, py, px), jnp.int32),  # sel plane
            pltpu.VMEM((1, py, px), dtype),      # out plane (RMW)
            pltpu.SemaphoreType.DMA((max(1, n_cross),)),
            pltpu.SemaphoreType.DMA((max(1, n_cross),)),
            pltpu.SemaphoreType.DMA(()),
        ]
    )
    return pl.pallas_call(
        kernel,
        grid=(1,),
        out_shape=(block, block),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 3,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
        scratch_shapes=scratch_shapes,
        input_output_aliases={0: 0, 1: 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            has_side_effects=True,
            collective_id=collective_id,
        ),
        interpret=interpret,
    )


class FusedRemoteDmaExchange:
    """The all-TPU FUSED transport of one ``HaloExchange(fused=True)``:
    a jitted ``shard_map`` program whose wire movement is ONE
    :func:`make_fused_exchange_kernel` call per dtype group — every
    direction's copy in flight concurrently, zero ``lax.ppermute``
    anywhere (the same census pin as ops/remote_dma.RemoteDmaExchange,
    which this replaces when the plan carries the fused variant). The
    compute-fused jacobi substep wires the same schedule through
    :func:`make_fused_jacobi_kernel` instead (ops/jacobi)."""

    def __init__(self, ex):
        from ..parallel.mesh import BLOCK_PSPEC

        if not fused_kernel_supported(ex.spec, ex.resident):
            raise ValueError(
                "the fused TPU carrier supports uniform single-resident "
                "partitions today (uneven fused runs the "
                "host-orchestrated schedule via the fused step loops; "
                "use AXIS_COMPOSED for oversubscription)"
            )
        self.ex = ex
        self._pspec = BLOCK_PSPEC
        self._kernels = {}

    def _group_kernel(self, nq, dtype, cid):
        key = (nq, str(jnp.dtype(dtype)))
        if key not in self._kernels:
            self._kernels[key] = make_fused_exchange_kernel(
                self.ex.spec, self.ex.plan, nq, dtype,
                wire_dtype=self.ex.wire_dtype, collective_id=cid,
            )
        return self._kernels[key]

    def _blocks_body(self, state):
        from ..ops.halo_fill import dtype_groups

        ex = self.ex
        p = ex.spec.padded()
        if not isinstance(state, dict):
            state = {0: state}
            unwrap = True
        else:
            unwrap = False
        out = dict(state)
        if ex.batch_quantities:
            groups = dtype_groups(out)
        else:
            groups = [(out[k].dtype, [k]) for k in out]
        for cid, (dt, keys) in enumerate(groups):
            kern = self._group_kernel(len(keys), dt, cid)
            shaped = [out[k].reshape(p.z, p.y, p.x) for k in keys]
            res = kern(*shaped)
            # a tuple out_shape comes back as a tuple even at length 1 —
            # wrap only a bare array, never double-wrap
            if not isinstance(res, (tuple, list)):
                res = (res,)
            for k, blk in zip(keys, res):
                out[k] = blk.reshape(state[k].shape)
        return out[0] if unwrap else out

    def __call__(self, state):
        return self._compiled(state)

    @property
    def _compiled(self):
        if "_compiled_fn" not in self.__dict__:
            fn = jax.shard_map(
                self._blocks_body, mesh=self.ex.mesh,
                in_specs=self._pspec, out_specs=self._pspec,
            )
            self.__dict__["_compiled_fn"] = jax.jit(fn, donate_argnums=0)
        return self.__dict__["_compiled_fn"]

    def make_loop(self, iters: int):
        def many(state):
            return lax.fori_loop(
                0, iters, lambda _, s: self._blocks_body(s), state)

        fn = jax.shard_map(many, mesh=self.ex.mesh,
                           in_specs=self._pspec, out_specs=self._pspec)
        return jax.jit(fn, donate_argnums=0)

    def collective_census(self, state):
        from ..utils.hlo_check import collective_census

        txt = self._compiled.lower(state).compile().as_text()
        return collective_census(txt)
