"""In-place periodic halo fills for self-wrap axes (Pallas, TPU).

The TPU-native analogue of the reference's pack/unpack + same-device
``PeerAccessSender`` transport (reference: src/pack_kernel.cu:3-103,
tx_cuda.cuh:41-113): on an axis whose partition has a single block, the
periodic halo source is the block itself, so the exchange phase is a pure
intra-HBM data movement. Expressing it as ``dynamic_update_slice`` makes
XLA materialize tile-padded slab arrays and full-array copies (measured
~22 ms for what is ~50 MB of logical movement at 512^3 r3 x4); these
kernels instead update the halo regions *in place* (``input_output_aliases``)
touching only the affected (8, 128) tiles.

Axis economics per quantity (512^3, r=3, fp32):
- z: halo planes are whole (py, px) slabs — 6 plane copies, ~16 MB.
- y: halo rows live in one 8-row tile per side — RMW of 4 row-tiles, ~84 MB.
- x: halo columns live inside one 128-lane tile per side — RMW of both
  edge lane-tiles (~0.55 GB; the 128-lane tile is the minimum write
  granularity, a ~42x amplification that any layout storing x halos
  inline must pay).

Used by ``HaloExchange`` for AXIS_COMPOSED phases with a single block on
the axis; multi-block phases keep the ppermute + update path. Phase
ordering (x, then y, then z) is preserved because each axis is a separate
kernel call — later phases read the earlier phases' filled halos.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..domain.grid import GridSpec

_LANE = 128
_SUB = 8


# -- quantity grouping / packed carriers --------------------------------------
# Shared between the fused multi-quantity fill kernels below and the
# quantity-batched exchange phases (parallel/exchange.py): a multi-quantity
# state is processed per same-dtype GROUP (never bitcast), and a group's
# boundary slabs ride one packed (Q, ...) carrier per data movement — the
# ppermute analogue of the reference's per-neighbor multi-quantity message
# (reference: packer.cu:10-26, the DevicePacker laying q quantities into one
# contiguous buffer).


def dtype_groups(state):
    """``[(dtype, [keys])]`` of a quantity dict, grouped by dtype in
    first-appearance order. The grouping unit for packed carriers and
    fused fills: quantities in one group share every slab shape and may
    be stacked without bitcasting; distinct dtypes exchange separately."""
    groups = {}
    for k, v in state.items():
        groups.setdefault(jnp.dtype(v.dtype), []).append(k)
    return list(groups.items())


def pack_slabs(slabs):
    """Stack a same-dtype group's boundary slabs into the packed
    ``(Q, ...slab)`` carrier that rides one collective (packer.cu's
    per-neighbor message re-expressed for ``lax.ppermute``).

    A single-slab group degenerates to the slab itself (no leading unit
    axis), so the batched phase bodies at Q=1 compile the exact historical
    per-quantity program — they ARE the per-quantity implementation then."""
    return slabs[0] if len(slabs) == 1 else jnp.stack(slabs)


def unpack_slabs(carrier, nq: int):
    """Scatter a packed ``(Q, ...slab)`` carrier back into per-quantity
    slabs (static leading index — XLA fuses these into the halo updates);
    inverse of :func:`pack_slabs`, including the Q=1 degeneration."""
    return [carrier] if nq == 1 else [carrier[q] for q in range(nq)]


def wire_narrow_dtype(native, wire_dtype):
    """The dtype a wire-crossing carrier of ``native`` data travels as
    under the bf16-on-the-wire compression knob, or None when the
    carrier stays native: compression only ever NARROWS a floating
    carrier (fp32 -> bf16/f16, fp64 -> f32/bf16/...), never widens,
    never touches integer quantities, and never bitcasts — the cast is a
    rounding ``astype`` on the send side and a lossless widen on unpack.
    Local copies (self-wrap fills, resident-neighbor shifts) are never
    compressed: only bytes that actually cross the interconnect pay the
    precision for the bandwidth."""
    if wire_dtype is None:
        return None
    native = jnp.dtype(native)
    wire = jnp.dtype(wire_dtype)
    if not (jnp.issubdtype(native, jnp.floating)
            and jnp.issubdtype(wire, jnp.floating)):
        return None
    if wire.itemsize >= native.itemsize:
        return None
    return wire


def wrap_fill_batched(spec: GridSpec, a):
    """Periodic self-wrap halo fill of every *leading-dim* block: ``a`` is
    ``(..., pz, py, px)`` — e.g. the multi-tenant campaign's stacked
    ``(B, pz, py, px)`` tenant states — and every trailing (pz, py, px)
    block is an INDEPENDENT single-block periodic domain whose halos wrap
    onto itself. Nothing ever crosses the leading axes: the slice
    assignments below touch only the trailing three dims.

    Fill order is the composed x -> y -> z phase order of
    ``parallel/exchange.py`` (AXIS_ORDER), each later axis copying the
    full extent of the earlier axes including their just-filled halos, so
    edges and corners come out identical to a single-block
    ``HaloExchange`` self-wrap — the bit-parity anchor of the batched
    campaign step programs (tests/test_campaign.py)."""
    off = spec.compute_offset()
    b = spec.base
    r = spec.radius
    xo, yo, zo = off.x, off.y, off.z
    nx, ny, nz = b.x, b.y, b.z
    rxm, rxp = r.x(-1), r.x(1)
    rym, ryp = r.y(-1), r.y(1)
    rzm, rzp = r.z(-1), r.z(1)
    if rxm:
        a = a.at[..., :, :, xo - rxm:xo].set(a[..., :, :, xo + nx - rxm:xo + nx])
    if rxp:
        a = a.at[..., :, :, xo + nx:xo + nx + rxp].set(a[..., :, :, xo:xo + rxp])
    if rym:
        a = a.at[..., :, yo - rym:yo, :].set(a[..., :, yo + ny - rym:yo + ny, :])
    if ryp:
        a = a.at[..., :, yo + ny:yo + ny + ryp, :].set(a[..., :, yo:yo + ryp, :])
    if rzm:
        a = a.at[..., zo - rzm:zo, :, :].set(a[..., zo + nz - rzm:zo + nz, :, :])
    if rzp:
        a = a.at[..., zo + nz:zo + nz + rzp, :, :].set(a[..., zo:zo + rzp, :, :])
    return a


def _axis_geom(spec: GridSpec, axis: str) -> Tuple[int, int, int]:
    """(offset, size, (rm, rp)) along one axis."""
    off = spec.compute_offset()
    r = spec.radius
    if axis == "x":
        return off.x, spec.base.x, (r.x(-1), r.x(1))
    if axis == "y":
        return off.y, spec.base.y, (r.y(-1), r.y(1))
    return off.z, spec.base.z, (r.z(-1), r.z(1))


# VMEM scratch budget for a fill kernel (kernels pass vmem_limit_bytes to
# lift the 16 MB default scoped limit; leave headroom for Mosaic).
_VMEM_BUDGET = 24 * 1024 * 1024


def _x_tzb(spec: GridSpec, nq: int = 1, z_stack: int = 1) -> int:
    """z-batch depth of the x kernel: deepest of 16/8/4/2 whose 8 buffers
    (x nq quantities) fit the budget (v5e-measured at 256^3: TZB=16
    4.25 ms vs TZB=4 6.01 ms — bigger DMAs amortize per-batch latency)."""
    p = spec.padded()
    pz = p.z * z_stack
    tzb = 16
    while tzb > 2 and (8 * nq * tzb * p.y * _LANE * 4 > _VMEM_BUDGET or tzb > pz):
        tzb //= 2
    return tzb


def max_fill_group(spec: GridSpec) -> int:
    """Largest quantity count a fused x fill can carry under the VMEM
    budget (callers chunk larger quantity sets)."""
    nq = 1
    while nq < 16 and 8 * (nq + 1) * 2 * spec.padded().y * _LANE * 4 <= _VMEM_BUDGET:
        nq += 1
    return nq


def _scratch_bytes(spec: GridSpec, axis: str, z_stack: int = 1) -> int:
    """VMEM scratch the kernel for ``axis`` would allocate (see make_self_fill)."""
    p = spec.padded()
    o, sz, (rm, rp) = _axis_geom(spec, axis)
    if axis == "z":
        return max(rm, rp, 1) * p.y * p.x * 4
    if axis == "y":
        spans = []
        for a, b in ((o - rm, o), (o + sz, o + sz + rp), (o, o + rp), (o + sz - rm, o + sz)):
            t = (a // _SUB) * _SUB
            spans.append(-(-(b - t) // _SUB) * _SUB)
        return 2 * 8 * max(spans) * p.x * 4
    # x (nq=1): 4 double-buffered 2-slot buffers
    return 8 * _x_tzb(spec, z_stack=z_stack) * p.y * _LANE * 4


def self_fill_supported(spec: GridSpec, axis: str, dtype, z_stack: int = 1) -> bool:
    """Whether the in-place fill kernel handles this configuration.

    ``z_stack > 1``: the kernel targets a (z_stack, pz, py, px) resident
    z-stack viewed as one contiguous (z_stack*pz, py, px) array. Valid for
    x/y fills only — they act within each z plane, so resident block
    boundaries along z are transparent; the z fill's plane copies are not.
    """
    if z_stack > 1 and axis == "z":
        return False
    if not spec.aligned or dtype != jnp.float32:
        return False
    o, sz, (rm, rp) = _axis_geom(spec, axis)
    if rm == 0 and rp == 0:
        return False
    p = spec.padded()
    # x/y kernels stream fixed-depth z batches; thinner blocks would slice
    # out of range (z0 = min(i*TZB, pz-TZB) goes negative)
    if axis == "x" and p.z * z_stack < 4:
        return False
    if axis == "y" and p.z * z_stack < 8:
        return False
    if _scratch_bytes(spec, axis, z_stack) > _VMEM_BUDGET:
        return False
    if axis == "x":
        # halo and wrap-source columns must each sit inside the two edge
        # lane-tiles the kernel rewrites
        lo_t = 0
        hi_t = ((o + sz) // _LANE) * _LANE
        if hi_t + _LANE > p.x or hi_t <= lo_t:
            return False
        cols = [(o - rm, o), (o, o + rp), (o + sz - rm, o + sz), (o + sz, o + sz + rp)]
        homes = [lo_t, lo_t, hi_t, hi_t]
        for (a, b), home in zip(cols, homes):
            if a < home or b > home + _LANE:
                return False
        return True
    if axis == "y":
        # halo rows and wrap-source rows each within one 8-row tile span
        return rm <= _SUB and rp <= _SUB
    return True  # z: untiled dim, plane copies always work


def make_self_fill(spec: GridSpec, axis: str, vma=None, interpret: bool = False,
                   nq: int = 1, z_stack: int = 1):
    """Build the in-place periodic fill for one self-wrap axis of fp32
    (pz, py, px) blocks. ``nq == 1``: ``fill(block) -> block``; ``nq > 1``:
    ``fill(b0, .., b{nq-1}) -> (b0', ..)`` — one kernel fills every
    quantity's halo (the multi-quantity pack analogue, packer.cu:10-26),
    amortizing per-kernel and per-batch overheads across quantities.

    ``z_stack > 1`` (x/y axes only): the fill runs over a resident z-stack
    of ``z_stack`` whole padded blocks viewed as one contiguous
    ``(z_stack*pz, py, px)`` array — x/y halos act within each z plane, so
    one kernel fills every resident block's halo in place (VERDICT r4
    item 7; the reference runs its same-GPU fast path under
    oversubscription too, tx_cuda.cuh:41-113)."""
    if not self_fill_supported(spec, axis, jnp.float32, z_stack):
        raise ValueError(
            f"self-wrap fill unsupported for axis {axis!r} on this spec "
            f"(z_stack={z_stack})"
        )
    if axis == "x" and not 1 <= nq <= max_fill_group(spec):
        raise ValueError(
            f"x-phase fill group size {nq} outside "
            f"[1, {max_fill_group(spec)}]"
        )
    p = spec.padded()
    pz, py, px = p.z * z_stack, p.y, p.x
    o, sz, (rm, rp) = _axis_geom(spec, axis)
    shape = jax.ShapeDtypeStruct(
        (pz, py, px), jnp.float32, vma=frozenset(vma) if vma is not None else None
    )
    _out_shape = (shape,) * nq
    _aliases = {q: q for q in range(nq)}

    def _wrap(fn):
        if nq == 1:
            return lambda block: fn(block)[0]
        return fn

    if axis == "z":
        def kernel(*refs):
            outs = refs[nq : 2 * nq]
            v, sem = refs[2 * nq :]

            def copy(out, src, dst, n):
                cp = pltpu.make_async_copy(out.at[pl.ds(src, n)], v.at[pl.ds(0, n)], sem)
                cp.start()
                cp.wait()
                cp = pltpu.make_async_copy(v.at[pl.ds(0, n)], out.at[pl.ds(dst, n)], sem)
                cp.start()
                cp.wait()

            for q in range(nq):
                if rm:
                    copy(outs[q], o + sz - rm, o - rm, rm)  # top planes -> low halo
                if rp:
                    copy(outs[q], o, o + sz, rp)  # first planes -> high halo

        nstage = max(rm, rp, 1)
        return _wrap(pl.pallas_call(
            kernel,
            grid=(1,),
            out_shape=_out_shape,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
            scratch_shapes=[
                pltpu.VMEM((nstage, py, px), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
            ],
            input_output_aliases=_aliases,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
                has_side_effects=True,
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        ))

    TZB = 8
    n_b = -(-pz // TZB)  # overlapping last batch: z is untiled, restart anywhere

    if axis == "y":
        # dest/source row-tile windows (lo halo, hi halo)
        lo_t = ((o - rm) // _SUB) * _SUB
        lo_span = -(-(o - lo_t) // _SUB) * _SUB
        hi_t = ((o + sz) // _SUB) * _SUB
        hi_span = -(-(o + sz + rp - hi_t) // _SUB) * _SUB
        hi_span = min(hi_span, py - hi_t)
        src_lo_t = (o // _SUB) * _SUB  # wrap source rows [o, o+rp)
        src_lo_span = -(-(o + rp - src_lo_t) // _SUB) * _SUB
        src_hi_t = ((o + sz - rm) // _SUB) * _SUB
        src_hi_span = -(-(o + sz - src_hi_t) // _SUB) * _SUB
        spans = (lo_span, hi_span, src_lo_span, src_hi_span)
        vspan = max(spans)

        def kernel(*refs):
            outs = refs[nq : 2 * nq]
            dv, sv, sem = refs[2 * nq :]
            i = pl.program_id(0)
            z0 = jnp.minimum(i * TZB, pz - TZB)

            def rd(out, base, span, buf):
                cp = pltpu.make_async_copy(
                    out.at[pl.ds(z0, TZB), pl.ds(base, span)], buf.at[:, pl.ds(0, span)], sem
                )
                cp.start()
                cp.wait()

            def wr(out, base, span, buf):
                cp = pltpu.make_async_copy(
                    buf.at[:, pl.ds(0, span)], out.at[pl.ds(z0, TZB), pl.ds(base, span)], sem
                )
                cp.start()
                cp.wait()

            for q in range(nq):
                out = outs[q]
                if rm:
                    rd(out, lo_t, lo_span, dv)
                    rd(out, src_hi_t, src_hi_span, sv)
                    # rows [o-rm, o) <- rows [o+sz-rm, o+sz)
                    dv[:, o - rm - lo_t : o - lo_t, :] = sv[
                        :, o + sz - rm - src_hi_t : o + sz - src_hi_t, :
                    ]
                    wr(out, lo_t, lo_span, dv)
                if rp:
                    rd(out, hi_t, hi_span, dv)
                    rd(out, src_lo_t, src_lo_span, sv)
                    # rows [o+sz, o+sz+rp) <- rows [o, o+rp)
                    dv[:, o + sz - hi_t : o + sz + rp - hi_t, :] = sv[
                        :, o - src_lo_t : o + rp - src_lo_t, :
                    ]
                    wr(out, hi_t, hi_span, dv)

        return _wrap(pl.pallas_call(
            kernel,
            grid=(n_b,),
            out_shape=_out_shape,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
            out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
            scratch_shapes=[
                pltpu.VMEM((TZB, vspan, px), jnp.float32),
                pltpu.VMEM((TZB, vspan, px), jnp.float32),
                pltpu.SemaphoreType.DMA(()),
            ],
            input_output_aliases=_aliases,
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",),
                has_side_effects=True,
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        ))

    # axis == "x": rewrite both edge lane-tiles, double-buffered over z.
    # 8 buffers (rd/wr x lo/hi x 2 slots); depth picked by the VMEM budget
    TZB = _x_tzb(spec, nq, z_stack)
    n_b = -(-pz // TZB)
    lo_t = 0
    hi_t = ((o + sz) // _LANE) * _LANE

    # batches are disjoint except the clamped last one, whose z-range
    # overlaps the previous batch's when pz % TZB != 0 — that read must
    # not be prefetched past the overlapping write
    tail_overlaps = (pz % TZB) != 0
    prefetch_limit = n_b - 1 if tail_overlaps else n_b

    def kernel(*refs):
        outs = refs[nq : 2 * nq]
        rd_lo, rd_hi, wr_lo, wr_hi, s_rlo, s_rhi, s_wlo, s_whi = refs[2 * nq :]
        i = pl.program_id(0)
        slot = jnp.mod(i, 2)
        nslot = jnp.mod(i + 1, 2)

        def z_of(step):
            return jnp.minimum(step * TZB, pz - TZB)

        def rd(s, q, step, buf, sem, col):
            return pltpu.make_async_copy(
                outs[q].at[pl.ds(z_of(step), TZB), :, pl.ds(col, _LANE)],
                buf.at[s, q],
                sem.at[s],
            )

        def wr(s, q, step, buf, sem, col):
            return pltpu.make_async_copy(
                buf.at[s, q],
                outs[q].at[pl.ds(z_of(step), TZB), :, pl.ds(col, _LANE)],
                sem.at[s],
            )

        def rd_both(s, step):
            for q in range(nq):
                rd(s, q, step, rd_lo, s_rlo, lo_t).start()
                rd(s, q, step, rd_hi, s_rhi, hi_t).start()

        def wr_start(s, step):
            for q in range(nq):
                wr(s, q, step, wr_lo, s_wlo, lo_t).start()
                wr(s, q, step, wr_hi, s_whi, hi_t).start()

        def wr_wait(s, step):
            for q in range(nq):
                wr(s, q, step, wr_lo, s_wlo, lo_t).wait()
                wr(s, q, step, wr_hi, s_whi, hi_t).wait()

        @pl.when(i == 0)
        def _():
            rd_both(slot, i)

        @pl.when(i + 1 < prefetch_limit)
        def _():
            rd_both(nslot, i + 1)

        if tail_overlaps:
            @pl.when(jnp.logical_and(i == prefetch_limit, i >= 1))
            def _():
                # non-prefetched tail batch: the overlapping previous write
                # must land before reading
                wr_wait(nslot, i - 1)
                rd_both(slot, i)

        for q in range(nq):
            rd(slot, q, i, rd_lo, s_rlo, lo_t).wait()
            rd(slot, q, i, rd_hi, s_rhi, hi_t).wait()

        # the write buffers of batch i-2 (same slot) must have drained
        @pl.when(i >= 2)
        def _():
            wr_wait(slot, i - 2)

        for q in range(nq):
            wr_lo[slot, q] = rd_lo[slot, q]
            wr_hi[slot, q] = rd_hi[slot, q]
            if rm:  # cols [o-rm, o) <- [o+sz-rm, o+sz) (hi tile)
                wr_lo[slot, q, :, :, o - rm - lo_t : o - lo_t] = rd_hi[
                    slot, q, :, :, o + sz - rm - hi_t : o + sz - hi_t
                ]
            if rp:  # cols [o+sz, o+sz+rp) <- [o, o+rp) (lo tile)
                wr_hi[slot, q, :, :, o + sz - hi_t : o + sz + rp - hi_t] = rd_lo[
                    slot, q, :, :, o - lo_t : o + rp - lo_t
                ]
        wr_start(slot, i)

        @pl.when(i == n_b - 1)
        def _():
            # wr(n_b-2): the overlap tail branch waited it; otherwise here
            if n_b >= 2 and not tail_overlaps:
                wr_wait(nslot, i - 1)
            wr_wait(slot, i)

    return _wrap(pl.pallas_call(
        kernel,
        grid=(n_b,),
        out_shape=_out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
        out_specs=[pl.BlockSpec(memory_space=pl.ANY)] * nq,
        scratch_shapes=[
            pltpu.VMEM((2, nq, TZB, py, _LANE), jnp.float32),
            pltpu.VMEM((2, nq, TZB, py, _LANE), jnp.float32),
            pltpu.VMEM((2, nq, TZB, py, _LANE), jnp.float32),
            pltpu.VMEM((2, nq, TZB, py, _LANE), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        input_output_aliases=_aliases,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            has_side_effects=True,
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
        interpret=interpret,
    ))
