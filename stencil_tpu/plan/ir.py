"""ExchangePlan IR — the declarative form of a halo exchange.

Historically ``parallel/exchange.py`` branched three ways on ``Method``
and recomputed its geometry (axis tables, permute pairs, slab extents)
inline in each lowering body. This module lifts that geometry into a
small declarative plan — phases, directions, pack-group policy, carrier
dtypes, permute pairs — that AXIS_COMPOSED, DIRECT26 *and* AUTO_SPMD all
lower from (the reference analogue: the 26-direction transport plan
``realize`` builds before any sender exists, src/stencil.cu:327-464).

Why an IR at all: the autotuner (plan/cost.py, plan/autotune.py)
searches (partition shape x method x quantity batching x temporal k x
kernel variant). With the plan as data, a candidate is *described and
costed without compiling it* — collective counts and on-wire bytes fall
out of the phase list — and the lowering stays a single code path per
phase kind. ROADMAP #2's ``Method.REMOTE_DMA`` becomes another lowering
of the same phases.

The IR is pure geometry: building a plan touches no jax and no devices,
so the cost model can enumerate hundreds of candidates cheaply. The
lowering in ``HaloExchange`` is required to compile bit-identically to
the historical method branches — pinned by the census pins and parity
fixtures in tests/test_plan_ir.py and tests/test_exchange*.py.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

from ..geometry import DIRECTIONS_26, Dim3, Radius

# Method value strings (mirrors parallel.exchange.Method — the IR must not
# import the lowering module, which imports this one).
AXIS_COMPOSED = "axis-composed"
DIRECT26 = "direct26"
AUTO_SPMD = "auto-spmd"
REMOTE_DMA = "remote-dma"
METHODS = (AXIS_COMPOSED, DIRECT26, AUTO_SPMD, REMOTE_DMA)

# The fused compute+exchange kernel variant (ROADMAP #5): still
# Method.REMOTE_DMA — same kernel-initiated transport, zero ppermutes —
# but ONE kernel per substep starts every neighbor copy boundary-first,
# computes interior tiles while the DMAs fly, waits the recv semaphores,
# then computes the boundary tiles. A PlanChoice carries it as
# ``kernel_variant == FUSED_VARIANT`` so the autotuner searches it and
# the plan DB persists it like any other point in the space.
FUSED_VARIANT = "fused"

# The persistent whole-chunk mega-kernel variant (ROADMAP #7): still
# Method.REMOTE_DMA transport, but ONE kernel executes an entire k-step
# chunk — deep-halo (radius*k) exteriors staged once per chunk, the
# shrinking valid strip re-swept each substep with ring-indexed window
# rotation, neighbor barrier semaphores between substeps — dropping the
# launch count from O(steps) to O(chunks) at the price of redundant
# boundary compute the cost model prices. A PlanChoice carries it as
# ``kernel_variant == PERSISTENT_VARIANT`` (``multistep_k`` is the chunk
# depth, so persistent requires k >= 2 — at k == 1 it IS the fused
# kernel).
PERSISTENT_VARIANT = "persistent"

# Wire-compression itemsizes the IR can model without importing jax/numpy
# (bfloat16 / float8_* are not numpy dtype names; everything else resolves
# lazily). The fp8 tier (float8_e4m3fn) quarters fp32 on-wire bytes the
# way bfloat16 halves them — same narrowing policy, one more row.
_WIRE_ITEMSIZE = {"bfloat16": 2, "float16": 2, "float32": 4, "float64": 8,
                  "float8_e4m3fn": 1, "float8_e5m2": 1}


def wire_itemsize(wire_dtype: Optional[str]) -> Optional[int]:
    """Bytes per cell a wire-compressed carrier pays (None = native)."""
    if wire_dtype is None:
        return None
    if wire_dtype in _WIRE_ITEMSIZE:
        return _WIRE_ITEMSIZE[wire_dtype]
    import numpy as np

    return np.dtype(wire_dtype).itemsize

# (axis name, stacked-array data dim, block dim) in exchange-phase order —
# the one authority for phase order; exchange.py consumes it via the plan.
AXIS_ORDER = (("x", 5, 2), ("y", 4, 1), ("z", 3, 0))


@dataclass(frozen=True)
class AxisPhaseIR:
    """One composed axis phase (or one AUTO_SPMD roll phase).

    ``sizes`` is the full per-axis block-size table (length ``ring *
    resident``); ``ring`` is the number of permute participants along the
    mesh axis; ``resident`` the oversubscription factor (blocks stacked
    per device). ``fwd``/``bwd`` are the literal ``lax.ppermute`` pair
    lists toward +axis/-axis (empty when the phase is local-only or the
    schedule is partitioner-synthesized).
    """

    axis: str               # 'x' | 'y' | 'z' (mesh axis name)
    adim: int               # stacked-array data dim
    bdim: int               # stacked-array block dim
    ring: int               # permute participants along this axis
    resident: int           # blocks resident per device along this axis
    rm: int                 # low-side radius (data received from -axis)
    rp: int                 # high-side radius
    offset: int             # allocation-local compute origin on this axis
    sizes: Tuple[int, ...]  # per-block logical sizes (full table)
    fwd: Tuple[Tuple[int, int], ...]
    bwd: Tuple[Tuple[int, int], ...]
    wire_cells: int         # cells permuted per exchange per quantity (all devices)
    local_cells: int        # cells moved locally (self-wrap / resident shifts)

    @property
    def blocks(self) -> int:
        return self.ring * self.resident

    @property
    def uniform(self) -> bool:
        return len(set(self.sizes)) == 1

    @property
    def active(self) -> bool:
        return self.rm > 0 or self.rp > 0

    def collectives(self) -> int:
        """ppermutes one lowering of this phase emits (per carrier)."""
        if self.ring <= 1 or not self.active:
            return 0
        return (1 if self.rm > 0 else 0) + (1 if self.rp > 0 else 0)


@dataclass(frozen=True)
class DirectPhaseIR:
    """One DIRECT26 direction message.

    ``src``/``dst`` are static allocation-local (z, y, x) starts on a
    uniform partition; on uneven partitions they are traced per-block
    size-table lookups at lowering time, and ``shape`` is the base-padded
    static carrier extent every permute participant shares. ``pairs`` is
    the flattened 26-neighbor permutation when the mesh matches the
    partition (no oversubscription); with residents the lowering composes
    per-axis rolls instead (see HaloExchange._roll_blocks).
    """

    direction: Tuple[int, int, int]       # (dx, dy, dz)
    shape: Tuple[int, int, int]           # carrier extent (z, y, x)
    src: Optional[Tuple[int, int, int]]   # uniform-only static starts (z, y, x)
    dst: Optional[Tuple[int, int, int]]
    pairs: Tuple[Tuple[int, int], ...]    # flattened permute pairs (may be ())
    collective_count: int                 # permutes per carrier for this message
    wire_cells: int
    local_cells: int

    def collectives(self) -> int:
        return self.collective_count


@dataclass(frozen=True)
class RemoteDmaPhaseIR:
    """One kernel-initiated axis phase of a ``REMOTE_DMA`` plan.

    Same composed-phase slab geometry as :class:`AxisPhaseIR` (full
    padded extents, x→y→z order, edges/corners composing across phases —
    the wire model is shared), but the boundary slabs move as
    per-neighbor async remote copies issued from inside the kernel
    (``pltpu.make_async_remote_copy`` on TPU; host-initiated
    device-to-device copies in the CPU emulation) instead of
    ``lax.ppermute``: the XLA collective path is bypassed entirely, so
    :meth:`collectives` is ZERO by construction — the census pin — and
    :meth:`dmas` counts the async copies one carrier pays (≤ 2 per
    phase: one toward each neighbor; Q-independent under the PR-5
    per-dtype packed-carrier geometry). ``fwd``/``bwd`` are the neighbor
    rings the DMAs target (the same pairs the composed permutes use)."""

    axis: str               # 'x' | 'y' | 'z' (mesh axis name)
    adim: int               # stacked-array data dim
    bdim: int               # stacked-array block dim
    ring: int               # DMA participants along this axis
    resident: int           # blocks resident per device along this axis
    rm: int                 # low-side radius
    rp: int                 # high-side radius
    offset: int             # allocation-local compute origin
    sizes: Tuple[int, ...]  # per-block logical sizes (full table)
    fwd: Tuple[Tuple[int, int], ...]   # +axis neighbor ring (DMA targets)
    bwd: Tuple[Tuple[int, int], ...]
    wire_cells: int         # cells DMA'd per exchange per quantity (all devices)
    local_cells: int        # cells moved locally (self-wrap / resident shifts)

    @property
    def blocks(self) -> int:
        return self.ring * self.resident

    @property
    def uniform(self) -> bool:
        return len(set(self.sizes)) == 1

    @property
    def active(self) -> bool:
        return self.rm > 0 or self.rp > 0

    def collectives(self) -> int:
        """Always 0: the DMAs live inside the kernel custom-call, not on
        the XLA collective path — nothing for a ppermute census to see."""
        return 0

    def dmas(self) -> int:
        """Async remote copies one carrier pays for this phase."""
        if self.ring <= 1 or not self.active:
            return 0
        return (1 if self.rm > 0 else 0) + (1 if self.rp > 0 else 0)


@dataclass(frozen=True)
class FusedPhaseIR:
    """One per-direction message of a FUSED compute+exchange substep.

    The fused kernel cannot use the composed x→y→z phase geometry: a
    composed y slab carries x-halo data, so phase y's send depends on
    phase x's receive — nothing could start boundary-first. Instead the
    fused schedule sends one EXACT-extent message per active direction
    (the DIRECT26 geometry re-transported): every message reads only the
    sender's compute-region cells, so all of them start concurrently
    before any compute, the interior tiles run while they fly, and the
    boundary tiles run after the recv semaphores — the reference's 26
    concurrent peer-access writes (§5.8), with the XLA collective path
    bypassed exactly like :class:`RemoteDmaPhaseIR` (:meth:`collectives`
    is ZERO by construction; :meth:`dmas` is 1 for a wire-crossing
    direction, 0 for a self-wrap hand-off).

    ``shape`` is the exact carrier extent (z, y, x) on a uniform
    partition (radius along the direction's nonzero axes, block size on
    the orthogonal ones); on uneven partitions the per-device extents
    come from the size tables at lowering time and ``shape`` records the
    base-block figure the byte model prices."""

    direction: Tuple[int, int, int]       # (dx, dy, dz)
    shape: Tuple[int, int, int]           # carrier extent (z, y, x)
    src: Optional[Tuple[int, int, int]]   # uniform-only static starts (z, y, x)
    dst: Optional[Tuple[int, int, int]]
    crossing: bool                        # leaves the device (any ring axis)
    wire_cells: int
    local_cells: int

    def collectives(self) -> int:
        """Always 0: kernel-initiated copies, nothing on the XLA
        collective path (the same pin as RemoteDmaPhaseIR)."""
        return 0

    def dmas(self) -> int:
        """Async remote copies one carrier pays for this direction."""
        return 1 if self.crossing else 0


@dataclass(frozen=True)
class DcnPhaseIR:
    """The outer (cross-host) level of a hierarchical exchange plan.

    One hierarchy = one outer split along ONE mesh axis (the "DCN
    axis"): ``hosts`` contiguous segments of ``seg = ring // hosts``
    devices each. The inner program's DCN-axis phase wraps within each
    segment (:func:`_segmented_ring_pairs` — same collective count as
    flat, nothing crosses a host), and this phase moves the host-
    boundary slabs across the DCN instead: for each of the ``hosts``
    periodic segment boundaries, every device in the boundary axis-slice
    (``slice_devices`` of them, one per orthogonal mesh position) sends
    its boundary slab to the peer device on the far side, as a
    host-orchestrated device-to-device copy (the PR-10 emulation
    machinery in-process; a real DCN transport on a pod).

    Like :class:`RemoteDmaPhaseIR`, nothing here rides the XLA
    collective path — :meth:`collectives` is ZERO by construction, so
    the inner census/byte pins are untouched and the DCN level is
    audited through :meth:`transfers` (the executed copy count) and its
    own byte model instead. The slabs span the FULL padded orthogonal
    extents (stale edge/corner strips included — later inner phases
    overwrite them), exactly the composed slab geometry."""

    axis: str            # 'x' | 'y' | 'z' (the DCN mesh axis)
    hosts: int           # outer segments (emulated or real hosts)
    ring: int            # inner mesh extent along the axis
    seg: int             # devices per host along the axis
    slice_devices: int   # devices per boundary axis-slice (orth positions)
    rm: int              # low-side radius (data received from -axis)
    rp: int              # high-side radius
    wire_cells: int      # cells crossing the DCN per exchange per quantity
    local_cells: int = 0

    @property
    def active(self) -> bool:
        return self.hosts > 1 and (self.rm > 0 or self.rp > 0)

    def collectives(self) -> int:
        """Always 0: host-orchestrated copies, nothing on the XLA
        collective path (the same pin as RemoteDmaPhaseIR)."""
        return 0

    def transfers(self) -> int:
        """Cross-host copies one carrier pays per exchange: one per
        active direction per segment boundary per orthogonal mesh
        position — the count the hierarchy transport measures and
        verify_plan audits."""
        if not self.active:
            return 0
        dirs = (1 if self.rm > 0 else 0) + (1 if self.rp > 0 else 0)
        return dirs * self.hosts * self.slice_devices


@dataclass(frozen=True)
class ExchangePlan:
    """The full declarative exchange program for one (spec, mesh, method).

    ``pack_groups`` is the carrier policy: ``"dtype"`` packs every
    same-dtype quantity's slab into one carrier per collective (PR 5's
    batched bodies — the collective count is Q-independent),
    ``"quantity"`` is the historical one-collective-per-quantity program.
    AUTO_SPMD plans are ``synthesized``: the phase list describes the
    slab program handed to the SPMD partitioner, which owns the actual
    collective schedule (and emits per-quantity permutes today — the
    round-7 census).
    """

    method: str
    pack_groups: str                      # 'dtype' | 'quantity'
    partition: Tuple[int, int, int]       # blocks (x, y, z)
    mesh_dim: Tuple[int, int, int]        # devices (x, y, z)
    resident: Tuple[int, int, int]
    axis_phases: Tuple[AxisPhaseIR, ...]  # always built (composed geometry)
    direct_phases: Tuple[DirectPhaseIR, ...] = ()
    remote_phases: Tuple[RemoteDmaPhaseIR, ...] = ()
    # the fused compute+exchange variant's per-direction messages (only
    # built when ``fused``; REMOTE_DMA-only — see FusedPhaseIR)
    fused_phases: Tuple[FusedPhaseIR, ...] = ()
    fused: bool = False
    # the persistent whole-chunk variant (REMOTE_DMA only): the phase
    # geometry stays the deep-halo composed slab program (remote_phases
    # built against the radius*k spec); what changes is the launch
    # economics — see :meth:`launches_per_chunk`.
    persistent: bool = False
    # hierarchical (ICI+DCN) decomposition: (axis, hosts) of the outer
    # split, or None for the flat single-level plan. When set, the inner
    # DCN-axis phase carries host-local wrap pairs and ``dcn_phases``
    # describes the cross-host level the planner prices separately.
    hierarchy: Optional[Tuple[str, int]] = None
    dcn_phases: Tuple["DcnPhaseIR", ...] = ()
    synthesized: bool = False
    # bf16-on-the-wire halo compression: wire-crossing carriers narrow to
    # this dtype before the send and widen on unpack (None = native).
    # Applies to the packed-carrier methods (composed/direct26/remote-dma);
    # local copies and self-wrap fills always stay native/lossless.
    wire_dtype: Optional[str] = None

    @property
    def batch_quantities(self) -> bool:
        return self.pack_groups == "dtype"

    @property
    def phases(self) -> Tuple:
        if self.method == DIRECT26:
            return self.direct_phases
        if self.method == REMOTE_DMA:
            return self.fused_phases if self.fused else self.remote_phases
        return self.axis_phases

    def collectives_per_exchange(self, quantities: int = 1,
                                 dtype_groups: int = 1) -> int:
        """Predicted collective-permute count of one compiled exchange —
        the number the census pins (6 composed / <=26 direct26 on a
        one-block-per-device mesh; Q-independent when pack_groups='dtype').
        AUTO_SPMD is predicted from the round-7 finding: the partitioner
        reinvents the composed schedule, per quantity."""
        carriers = dtype_groups if self.batch_quantities else quantities
        if self.synthesized:
            carriers = quantities  # the partitioner packs nothing today
        return sum(p.collectives() for p in self.phases) * carriers

    def dmas_per_exchange(self, quantities: int = 1,
                          dtype_groups: int = 1) -> int:
        """Predicted kernel-initiated async remote copies of one
        REMOTE_DMA exchange (0 for the ppermute methods): ≤ 2 per axis
        phase per carrier, Q-independent under per-dtype packing — the
        DMA analogue of :meth:`collectives_per_exchange`."""
        if self.method != REMOTE_DMA:
            return 0
        carriers = dtype_groups if self.batch_quantities else quantities
        phases = self.fused_phases if self.fused else self.remote_phases
        return sum(p.dmas() for p in phases) * carriers

    def dcn_transfers_per_exchange(self, quantities: int = 1,
                                   dtype_groups: int = 1) -> int:
        """Predicted cross-host (DCN-level) copies of one hierarchical
        exchange — 0 for flat plans. Like DMAs, these bypass the XLA
        collective path entirely; the hierarchy transport counts its
        executed copies and verify_plan pins this prediction against
        that count."""
        carriers = dtype_groups if self.batch_quantities else quantities
        return sum(p.transfers() for p in self.dcn_phases) * carriers

    def dcn_wire_bytes(self, itemsizes: Sequence[int],
                       floating: Optional[Sequence[bool]] = None) -> int:
        """Estimated bytes crossing the DCN per exchange (all
        quantities) — the outer level's own byte model, priced against
        the ``dcn`` calibration row (latency + bandwidth >> ICI). NOT
        part of :meth:`wire_bytes`: the census only sees the inner
        program, so the inner byte pin stays exact."""
        w = wire_itemsize(self.wire_dtype)
        if w is None:
            per_cell = sum(itemsizes)
        else:
            fl = ([True] * len(itemsizes) if floating is None
                  else list(floating))
            per_cell = sum(min(i, w) if f else i
                           for i, f in zip(itemsizes, fl))
        return sum(p.wire_cells for p in self.dcn_phases) * per_cell

    def launches_per_chunk(self, k: int = 1) -> int:
        """Predicted device-program launches one k-step chunk pays — the
        figure ``exchange.launches_per_chunk`` gauges and verify_plan
        audits against the runtime's dispatch counters, exactly like
        collectives and DMA bytes.

        The unit is host-visible program dispatches of the REMOTE_DMA
        runtime (the kernel-per-dispatch regime the reference's §5.8
        peer-access kernels live in; the CPU emulation counts the same
        thing):

        - ``persistent``: 2 per chunk, k-independent — ONE deep-halo
          staging exchange + ONE whole-chunk program (on TPU the chunk
          program is a single mega-kernel launch). O(chunks).
        - plain / fused REMOTE_DMA: 2 per substep — an exchange program
          and a sweep program each step. O(steps).
        - permute methods and AUTO_SPMD: 1 — the chunk compiles into one
          XLA program; its in-module kernel count (O(k), censused by
          ``utils.hlo_check.kernel_launch_census``) is a different unit
          and is not this prediction's subject.
        """
        if int(k) < 1:
            raise ValueError(f"launches_per_chunk needs k >= 1, got {k}")
        if self.method != REMOTE_DMA:
            return 1
        if self.persistent:
            return 2
        return 2 * int(k)

    def wire_bytes(self, itemsizes: Sequence[int],
                   floating: Optional[Sequence[bool]] = None) -> int:
        """Estimated bytes on the interconnect per exchange (all
        quantities). Exact on one-block-per-device meshes; under
        oversubscription DIRECT26 carriers are counted whole although
        resident-internal shifts stay local (a deliberate overestimate —
        the census remains the compile-time truth). With ``wire_dtype``
        set, wire-crossing cells pay the narrowed itemsize (the bf16
        compression halves fp32 on-wire bytes; local bytes stay native).
        ``floating`` flags which quantities can narrow at all — the
        lowering (halo_fill.wire_narrow_dtype) never compresses integer
        carriers, so their wire bytes must stay native; omitted, every
        quantity is assumed floating (this framework's default)."""
        w = wire_itemsize(self.wire_dtype) if not self.synthesized else None
        if w is None:
            per_cell = sum(itemsizes)
        else:
            fl = ([True] * len(itemsizes) if floating is None
                  else list(floating))
            per_cell = sum(min(i, w) if f else i
                           for i, f in zip(itemsizes, fl))
        return sum(p.wire_cells for p in self.phases) * per_cell

    def local_bytes(self, itemsizes: Sequence[int]) -> int:
        """Estimated bytes moved without touching the interconnect
        (self-wrap fills, resident-neighbor shifts)."""
        per_cell = sum(itemsizes)
        return sum(p.local_cells for p in self.phases) * per_cell

    def describe(self) -> str:
        """Human-readable plan dump (plan_tool explain)."""
        lines = [
            f"method={self.method} pack_groups={self.pack_groups} "
            f"partition={self.partition} mesh={self.mesh_dim} "
            f"resident={self.resident}"
            + (" (schedule synthesized by the SPMD partitioner)"
               if self.synthesized else "")
            + (" (fused compute+exchange kernel)" if self.fused else "")
            + (" (persistent whole-chunk kernel)" if self.persistent
               else "")
            + (f" hierarchy={self.hierarchy[1]} hosts on "
               f"{self.hierarchy[0]}" if self.hierarchy else "")
            + (f" wire_dtype={self.wire_dtype}" if self.wire_dtype else ""),
        ]
        for p in self.dcn_phases:
            lines.append(
                f"  dcn {p.axis}: hosts={p.hosts} seg={p.seg} "
                f"slice_devices={p.slice_devices} rm={p.rm} rp={p.rp} "
                f"permutes=0 transfers={p.transfers()} "
                f"wire_cells={p.wire_cells}"
            )
        for p in self.phases:
            if isinstance(p, FusedPhaseIR):
                lines.append(
                    f"  dir {p.direction}: shape(zyx)={p.shape} permutes=0 "
                    f"dmas={p.dmas()} wire_cells={p.wire_cells} "
                    f"local_cells={p.local_cells}"
                )
            elif isinstance(p, RemoteDmaPhaseIR):
                lines.append(
                    f"  axis {p.axis}: ring={p.ring} resident={p.resident} "
                    f"rm={p.rm} rp={p.rp} permutes=0 dmas={p.dmas()} "
                    f"wire_cells={p.wire_cells} local_cells={p.local_cells}"
                )
            elif isinstance(p, AxisPhaseIR):
                lines.append(
                    f"  axis {p.axis}: ring={p.ring} resident={p.resident} "
                    f"rm={p.rm} rp={p.rp} permutes={p.collectives()} "
                    f"wire_cells={p.wire_cells} local_cells={p.local_cells}"
                )
            else:
                lines.append(
                    f"  dir {p.direction}: shape(zyx)={p.shape} "
                    f"permutes={p.collectives()} wire_cells={p.wire_cells}"
                )
        lines.append(
            f"  total permutes/exchange (1 group): "
            f"{self.collectives_per_exchange()}"
        )
        if self.method == REMOTE_DMA:
            lines.append(
                f"  total async remote copies/exchange (1 group): "
                f"{self.dmas_per_exchange()} (kernel-initiated — the "
                "census sees 0 ppermutes)"
            )
        if self.dcn_phases:
            lines.append(
                f"  total cross-host copies/exchange (1 group): "
                f"{self.dcn_transfers_per_exchange()} "
                f"({self.dcn_wire_bytes([4])} bytes at 1 fp32 quantity; "
                "host-orchestrated — the census sees 0 ppermutes)"
            )
        if self.wire_dtype and not self.synthesized:
            import dataclasses

            native = dataclasses.replace(self, wire_dtype=None)
            lines.append(
                f"  wire bytes (1 fp32 quantity): {self.wire_bytes([4])} "
                f"({self.wire_dtype} on the wire; {native.wire_bytes([4])} "
                "native)"
            )
        return "\n".join(lines)


# -- plan construction --------------------------------------------------------


def spec_axis(spec, name: str):
    """(per-index sizes, low radius, high radius, compute offset) along
    one axis — THE axis-geometry accessor: the plan builder below and the
    lowering in parallel/exchange.py both import this one function, so
    predicted and lowered geometry cannot desynchronize. The offset can
    exceed the low radius in aligned layouts (the y compute origin is
    rounded to the 8-row tile); the halo always sits immediately adjacent
    to the compute region, at [offset - rm, offset)."""
    off = spec.compute_offset()
    if name == "x":
        return spec.sizes_x, spec.radius.x(-1), spec.radius.x(1), off.x
    if name == "y":
        return spec.sizes_y, spec.radius.y(-1), spec.radius.y(1), off.y
    return spec.sizes_z, spec.radius.z(-1), spec.radius.z(1), off.z


def _ring_pairs(n: int) -> Tuple[Tuple[Tuple[int, int], ...],
                                 Tuple[Tuple[int, int], ...]]:
    fwd = tuple((i, (i + 1) % n) for i in range(n))
    bwd = tuple((i, (i - 1) % n) for i in range(n))
    return fwd, bwd


def _segmented_ring_pairs(n: int, hosts: int
                          ) -> Tuple[Tuple[Tuple[int, int], ...],
                                     Tuple[Tuple[int, int], ...]]:
    """Host-local wrap pairs: the ring of ``n`` positions split into
    ``hosts`` contiguous segments, each wrapping WITHIN itself. Still a
    full permutation of all ``n`` participants — the compiled program
    emits exactly as many ppermutes as the flat ring (the inner census
    pin) — but no pair crosses a segment boundary, so the inner ICI
    program never reaches across hosts; the cross-host slabs ride the
    DCN level instead (see :class:`DcnPhaseIR`). A boundary receiver's
    wrap value is garbage by construction and is overwritten by the DCN
    apply."""
    if n % hosts:
        raise ValueError(f"{hosts} hosts do not divide ring extent {n}")
    seg = n // hosts
    fwd, bwd = [], []
    for h in range(hosts):
        base = h * seg
        for j in range(seg):
            fwd.append((base + j, base + (j + 1) % seg))
            bwd.append((base + j, base + (j - 1) % seg))
    return tuple(fwd), tuple(bwd)


def _perm26(dim: Dim3, d: Dim3) -> Tuple[Tuple[int, int], ...]:
    """Flattened (z, y, x)-major permutation sending toward ``d`` (one
    block per device — mesh dims == partition dims)."""
    pairs = []
    for iz in range(dim.z):
        for iy in range(dim.y):
            for ix in range(dim.x):
                src = (iz * dim.y + iy) * dim.x + ix
                jz = (iz + d.z) % dim.z
                jy = (iy + d.y) % dim.y
                jx = (ix + d.x) % dim.x
                pairs.append((src, (jz * dim.y + jy) * dim.x + jx))
    return tuple(pairs)


def _axis_phases(spec, mesh_dim: Dim3, resident: Dim3,
                 synthesized: bool) -> Tuple[AxisPhaseIR, ...]:
    p = spec.padded()
    orth = {  # padded cells orthogonal to each axis, per block
        "x": p.y * p.z,
        "y": p.x * p.z,
        "z": p.x * p.y,
    }
    res = {"x": resident.x, "y": resident.y, "z": resident.z}
    md = {"x": mesh_dim.x, "y": mesh_dim.y, "z": mesh_dim.z}
    nblocks = spec.num_blocks()
    phases = []
    for name, adim, bdim in AXIS_ORDER:
        sizes, rm, rp, off = spec_axis(spec, name)
        c = 1 if synthesized else res[name]
        ring = len(sizes) if synthesized else md[name]
        if ring > 1 and not synthesized:
            fwd, bwd = _ring_pairs(ring)
        else:
            fwd, bwd = (), ()
        slab_cells = (rm + rp) * orth[name] * nblocks  # every block's slabs
        if ring > 1:
            if c > 1:
                # only the two boundary slabs of each device's resident
                # stack ride the permute; the rest shift locally
                wire = (rm + rp) * orth[name] * (nblocks // c)
            else:
                wire = slab_cells
        else:
            wire = 0
        phases.append(AxisPhaseIR(
            axis=name, adim=adim, bdim=bdim, ring=ring, resident=c,
            rm=rm, rp=rp, offset=off, sizes=tuple(sizes),
            fwd=fwd if not synthesized else (),
            bwd=bwd if not synthesized else (),
            wire_cells=wire, local_cells=slab_cells - wire,
        ))
    return tuple(phases)


def _direct_phases(spec, mesh_dim: Dim3,
                   resident: Dim3) -> Tuple[DirectPhaseIR, ...]:
    r = spec.radius
    off = spec.compute_offset()
    base = spec.base
    uniform = spec.is_uniform()
    oversub = resident != Dim3(1, 1, 1)
    nblocks = spec.num_blocks()
    dirs = [d for d in DIRECTIONS_26 if r.dir(-d) != 0]
    if not uniform:
        # face -> edge -> corner apply order (stable within each rank)
        dirs.sort(key=lambda d: abs(d.x) + abs(d.y) + abs(d.z))
    phases = []
    for d in dirs:
        shape, src, dst = [], [], []
        for dc, s, rmin, rplus, o in zip(
            (d.z, d.y, d.x),
            (base.z, base.y, base.x),
            (r.z(-1), r.y(-1), r.x(-1)),
            (r.z(1), r.y(1), r.x(1)),
            (off.z, off.y, off.x),
        ):
            if dc == 1:
                shape.append(rmin)
                src.append(o + s - rmin)
                dst.append(o - rmin)
            elif dc == -1:
                shape.append(rplus)
                src.append(o)
                dst.append(o + s)
            else:
                shape.append(s)
                src.append(o)
                dst.append(o)
        if any(e == 0 for e in shape):
            continue
        if oversub:
            # per-axis composition: one permute per nonzero component
            # whose mesh axis actually has >1 device
            md = {"z": mesh_dim.z, "y": mesh_dim.y, "x": mesh_dim.x}
            comp = {"z": d.z, "y": d.y, "x": d.x}
            count = sum(1 for a in ("z", "y", "x")
                        if comp[a] != 0 and md[a] > 1)
            pairs: Tuple[Tuple[int, int], ...] = ()
        else:
            count = 1
            pairs = _perm26(spec.dim, d)
        cells = shape[0] * shape[1] * shape[2] * nblocks
        phases.append(DirectPhaseIR(
            direction=(d.x, d.y, d.z), shape=tuple(shape),
            src=tuple(src) if uniform else None,
            dst=tuple(dst) if uniform else None,
            pairs=pairs, collective_count=count,
            wire_cells=cells if count else 0,
            local_cells=0 if count else cells,
        ))
    return tuple(phases)


def _remote_phases(axis_phases: Tuple[AxisPhaseIR, ...]
                   ) -> Tuple[RemoteDmaPhaseIR, ...]:
    """REMOTE_DMA phases from the composed geometry: identical slab
    extents, sizes, and neighbor rings — only the transport differs
    (kernel-initiated DMAs instead of ppermutes), so the wire model is
    literally the composed one and parity vs AXIS_COMPOSED is a
    geometry-free claim about data movement."""
    return tuple(
        RemoteDmaPhaseIR(
            axis=p.axis, adim=p.adim, bdim=p.bdim, ring=p.ring,
            resident=p.resident, rm=p.rm, rp=p.rp, offset=p.offset,
            sizes=p.sizes, fwd=p.fwd, bwd=p.bwd,
            wire_cells=p.wire_cells, local_cells=p.local_cells,
        )
        for p in axis_phases
    )


def _fused_phases(spec, mesh_dim: Dim3) -> Tuple[FusedPhaseIR, ...]:
    """Fused-substep messages: the DIRECT26 exact-extent direction set,
    re-transported as kernel-initiated copies. Every message reads only
    sender compute-region cells — no message depends on another, so the
    fused kernel starts all of them boundary-first and hides the wire
    time behind interior tiles. ``crossing`` (and hence :meth:`dmas`) is
    a plan-level fact: a direction crosses iff any of its nonzero axes
    has more than one device; self-wrap directions are local hand-offs
    (lossless under wire compression, exactly like composed self-wrap
    phases). Face → edge → corner order (stable within each rank) so the
    uneven-partition lowering can layer padded writes like DIRECT26."""
    r = spec.radius
    base = spec.base
    off = spec.compute_offset()
    uniform = spec.is_uniform()
    nblocks = spec.num_blocks()
    md = {"z": mesh_dim.z, "y": mesh_dim.y, "x": mesh_dim.x}
    dirs = [d for d in DIRECTIONS_26 if r.dir(-d) != 0]
    dirs.sort(key=lambda d: abs(d.x) + abs(d.y) + abs(d.z))
    phases = []
    for d in dirs:
        shape, src, dst = [], [], []
        for dc, s, rmin, rplus, o in zip(
            (d.z, d.y, d.x),
            (base.z, base.y, base.x),
            (r.z(-1), r.y(-1), r.x(-1)),
            (r.z(1), r.y(1), r.x(1)),
            (off.z, off.y, off.x),
        ):
            if dc == 1:
                shape.append(rmin)
                src.append(o + s - rmin)
                dst.append(o - rmin)
            elif dc == -1:
                shape.append(rplus)
                src.append(o)
                dst.append(o + s)
            else:
                shape.append(s)
                src.append(o)
                dst.append(o)
        if any(e == 0 for e in shape):
            continue
        comp = {"z": d.z, "y": d.y, "x": d.x}
        crossing = any(comp[a] != 0 and md[a] > 1 for a in ("z", "y", "x"))
        cells = shape[0] * shape[1] * shape[2] * nblocks
        phases.append(FusedPhaseIR(
            direction=(d.x, d.y, d.z), shape=tuple(shape),
            src=tuple(src) if uniform else None,
            dst=tuple(dst) if uniform else None,
            crossing=crossing,
            wire_cells=cells if crossing else 0,
            local_cells=0 if crossing else cells,
        ))
    return tuple(phases)


def validate_hierarchy(hierarchy, mesh_dim) -> Optional[str]:
    """The one hierarchy-shape authority: ``None`` (flat) or an
    ``(axis, hosts)`` pair naming the outer DCN split. ``hosts`` must
    divide the mesh extent along ``axis`` so every host owns the same
    contiguous segment of the axis ring. Returns an error string, or
    None when valid."""
    if hierarchy is None:
        return None
    try:
        axis, hosts = hierarchy
        axis = str(axis)
        hosts = int(hosts)
    except (TypeError, ValueError):
        return (f"hierarchy must be an (axis, hosts) pair, "
                f"got {hierarchy!r}")
    if axis not in ("x", "y", "z"):
        return f"hierarchy axis must be 'x'|'y'|'z', got {axis!r}"
    if hosts < 1:
        return f"hierarchy needs hosts >= 1, got {hosts}"
    md = Dim3.of(mesh_dim)
    n = {"x": md.x, "y": md.y, "z": md.z}[axis]
    if n % hosts:
        return (f"{hosts} hosts do not divide the {axis} mesh extent "
                f"{n}")
    return None


def _dcn_phases(spec, mesh_dim: Dim3, axis: str,
                hosts: int) -> Tuple[DcnPhaseIR, ...]:
    """The outer DCN level: one phase for the hierarchy axis. Slabs use
    the composed geometry (radius-deep along the axis, FULL padded
    orthogonal extents), sent only by the ``hosts * slice_devices``
    segment-boundary devices per direction; with oversubscription only
    the edge resident block of each boundary device crosses (the rest
    shifted locally by the inner phase, exactly the composed wire
    accounting)."""
    p = spec.padded()
    orth = {"x": p.y * p.z, "y": p.x * p.z, "z": p.x * p.y}[axis]
    md = {"x": mesh_dim.x, "y": mesh_dim.y, "z": mesh_dim.z}
    _sizes, rm, rp, _off = spec_axis(spec, axis)
    ring = md[axis]
    slice_devices = (mesh_dim.x * mesh_dim.y * mesh_dim.z) // ring
    dirs = (1 if rm > 0 else 0) + (1 if rp > 0 else 0)
    wire = 0
    if hosts > 1:
        wire = ((rm + rp) * orth * hosts * slice_devices)
    return (DcnPhaseIR(
        axis=axis, hosts=hosts, ring=ring, seg=ring // hosts,
        slice_devices=slice_devices, rm=rm, rp=rp,
        wire_cells=wire if dirs else 0,
    ),)


def build_plan(spec, mesh_dim, method, batch_quantities: bool = True,
               resident: Optional[Dim3] = None,
               wire_dtype: Optional[str] = None,
               fused: bool = False,
               persistent: bool = False,
               hierarchy: Optional[Tuple[str, int]] = None) -> ExchangePlan:
    """Build the ExchangePlan of one (GridSpec, mesh shape, method).

    Pure geometry — no jax, no devices. ``method`` may be the enum from
    ``parallel.exchange`` or its value string. ``mesh_dim`` is the device
    grid (x, y, z); ``resident`` (blocks stacked per device) defaults to
    ``spec.dim / mesh_dim`` and must divide it exactly. ``wire_dtype``
    narrows wire-crossing carriers in the byte model (the bf16/fp8
    on-the-wire halo compression knob). ``fused`` builds the fused
    compute+exchange variant's per-direction message set (REMOTE_DMA
    only, single-resident only — loud infeasibility otherwise);
    ``persistent`` marks the whole-chunk mega-kernel variant (same
    constraints; the phase geometry stays the composed slab program
    against the caller's deep-halo radius*k spec). ``hierarchy`` is the
    outer DCN split ``(axis, hosts)``: the inner DCN-axis phase gets
    host-local wrap pairs (same collective count, nothing crossing a
    host) and ``dcn_phases`` describes the cross-host slab level.
    """
    mval = getattr(method, "value", method)
    if mval not in METHODS:
        raise ValueError(f"unknown exchange method {method!r}")
    err = validate_hierarchy(hierarchy, mesh_dim)
    if err is not None:
        raise ValueError(err)
    if hierarchy is not None and mval == AUTO_SPMD:
        # the partitioner owns the synthesized schedule — there is no
        # seam to segment, so a hierarchical AUTO_SPMD plan would claim
        # an inner/outer split the compiled program does not have
        raise ValueError(
            "hierarchical decomposition is not available for auto-spmd: "
            "the SPMD partitioner synthesizes the collective schedule "
            "and cannot be constrained to host-local rings"
        )
    if hierarchy is not None:
        hierarchy = (str(hierarchy[0]), int(hierarchy[1]))
    if fused and mval != REMOTE_DMA:
        raise ValueError(
            "the fused compute+exchange variant is a REMOTE_DMA lowering "
            f"(kernel-initiated copies); got method {mval!r}"
        )
    if persistent and mval != REMOTE_DMA:
        raise ValueError(
            "the persistent whole-chunk variant is a REMOTE_DMA lowering "
            f"(kernel-initiated copies); got method {mval!r}"
        )
    if persistent and fused:
        raise ValueError(
            "fused and persistent are distinct kernel variants of one "
            "plan — choose one (persistent at k == 1 IS the fused kernel)"
        )
    md = Dim3.of(mesh_dim)
    if spec.dim.x % md.x or spec.dim.y % md.y or spec.dim.z % md.z:
        raise ValueError(
            f"mesh {md} does not divide partition {spec.dim}"
        )
    if resident is None:
        resident = Dim3(spec.dim.x // md.x, spec.dim.y // md.y,
                        spec.dim.z // md.z)
    if fused and resident != Dim3(1, 1, 1):
        raise ValueError(
            "the fused compute+exchange kernel supports single-resident "
            f"partitions only (got resident {resident}); use the plain "
            "REMOTE_DMA carrier or AXIS_COMPOSED for oversubscription"
        )
    if persistent and resident != Dim3(1, 1, 1):
        raise ValueError(
            "the persistent whole-chunk kernel supports single-resident "
            f"partitions only (got resident {resident}); use the plain "
            "REMOTE_DMA carrier or AXIS_COMPOSED for oversubscription"
        )
    if hierarchy is not None and mval == DIRECT26:
        raise ValueError(
            "hierarchical decomposition is not available for direct26: "
            "its 26-direction permutation crosses hosts diagonally; use "
            "a composed-geometry inner method (axis-composed/remote-dma)"
        )
    synthesized = mval == AUTO_SPMD
    axis_phases = _axis_phases(spec, md, resident, synthesized)
    if hierarchy is not None and hierarchy[1] > 1:
        # the inner DCN-axis phase wraps within each host segment: same
        # ppermute count and carrier bytes as the flat ring (the census
        # pins), but no pair crosses a host — the boundary slabs ride
        # the DCN level instead
        import dataclasses

        h_axis, h_hosts = hierarchy
        axis_phases = tuple(
            dataclasses.replace(
                p, fwd=_segmented_ring_pairs(p.ring, h_hosts)[0],
                bwd=_segmented_ring_pairs(p.ring, h_hosts)[1])
            if p.axis == h_axis and p.ring > 1 else p
            for p in axis_phases
        )
    direct_phases = (
        _direct_phases(spec, md, resident) if mval == DIRECT26 else ()
    )
    remote_phases = _remote_phases(axis_phases) if mval == REMOTE_DMA else ()
    fused_phases = _fused_phases(spec, md) if fused else ()
    dcn_phases = (
        _dcn_phases(spec, md, hierarchy[0], hierarchy[1])
        if hierarchy is not None else ()
    )
    return ExchangePlan(
        method=mval,
        pack_groups="dtype" if batch_quantities else "quantity",
        partition=(spec.dim.x, spec.dim.y, spec.dim.z),
        mesh_dim=(md.x, md.y, md.z),
        resident=(resident.x, resident.y, resident.z),
        axis_phases=axis_phases,
        direct_phases=direct_phases,
        remote_phases=remote_phases,
        fused_phases=fused_phases,
        fused=fused,
        persistent=persistent,
        hierarchy=hierarchy,
        dcn_phases=dcn_phases,
        synthesized=synthesized,
        wire_dtype=wire_dtype,
    )


# -- planner vocabulary: config keys and plan choices -------------------------


def radius_dirs(radius: Radius) -> Tuple[Tuple[int, int, int, int], ...]:
    """Canonical nonzero-direction serialization of a Radius — the same
    [[dx,dy,dz,r], ...] convention the ckpt manifests record."""
    return tuple(
        (d[0], d[1], d[2], r) for d, r in sorted(radius._r.items())
        if r and d != (0, 0, 0)  # the center cell never exchanges
    )


def radius_from_dirs(dirs) -> Radius:
    r = Radius.constant(0)
    for dx, dy, dz, v in dirs:
        r.set_dir((dx, dy, dz), v)
    return r


@dataclass(frozen=True)
class PlanConfig:
    """Canonical problem key: what a tuned plan is valid FOR.

    ``quantities`` is a dtype *multiset* — ``(("float32", 4),)`` — sorted
    by dtype name, so permuting a domain's quantity declaration order
    never changes the key (or, by construction, the cost ranking:
    tests/test_plan_cost.py pins the invariance).
    """

    grid: Tuple[int, int, int]                       # (x, y, z)
    radius: Tuple[Tuple[int, int, int, int], ...]    # radius_dirs()
    quantities: Tuple[Tuple[str, int], ...]          # sorted (dtype, count)
    ndev: int
    platform: str = "cpu"

    @classmethod
    def make(cls, size, radius: Radius, dtypes: Sequence[str], ndev: int,
             platform: str = "cpu") -> "PlanConfig":
        size = Dim3.of(size)
        counts: Dict[str, int] = {}
        for dt in dtypes:
            counts[str(dt)] = counts.get(str(dt), 0) + 1
        return cls(
            grid=(size.x, size.y, size.z),
            radius=radius_dirs(radius),
            quantities=tuple(sorted(counts.items())),
            ndev=int(ndev),
            platform=str(platform),
        )

    @property
    def num_quantities(self) -> int:
        return sum(n for _dt, n in self.quantities)

    @property
    def dtype_group_count(self) -> int:
        return max(1, len(self.quantities))

    def itemsizes(self) -> Tuple[int, ...]:
        import numpy as np

        out = []
        for dt, n in self.quantities:
            out.extend([np.dtype(dt).itemsize] * n)
        return tuple(out)

    def floating_flags(self) -> Tuple[bool, ...]:
        """Per-quantity floatness, aligned with :meth:`itemsizes` — the
        wire-compression eligibility mask for ``ExchangePlan.wire_bytes``
        (integer carriers never narrow)."""
        import numpy as np

        out = []
        for dt, n in self.quantities:
            out.extend([np.issubdtype(np.dtype(dt), np.floating)] * n)
        return tuple(out)

    def radius_obj(self) -> Radius:
        return radius_from_dirs(self.radius)

    def key(self) -> str:
        """Stable string key for the plan DB."""
        return json.dumps({
            "grid": list(self.grid),
            "radius": [list(t) for t in self.radius],
            "quantities": [list(t) for t in self.quantities],
            "ndev": self.ndev,
            "platform": self.platform,
        }, sort_keys=True, separators=(",", ":"))

    def to_json(self) -> dict:
        return json.loads(self.key())

    @classmethod
    def from_json(cls, obj: dict) -> "PlanConfig":
        return cls(
            grid=tuple(obj["grid"]),
            radius=tuple(tuple(t) for t in obj["radius"]),
            quantities=tuple((str(d), int(n)) for d, n in obj["quantities"]),
            ndev=int(obj["ndev"]),
            platform=str(obj.get("platform", "cpu")),
        )


def validate_placement(placement, ndev: int) -> Optional[str]:
    """The one placement-shape authority: ``None`` (identity) or a
    permutation of ``range(ndev)`` mapping mesh position i (row-major
    z, y, x over the mesh grid) to the index of the device that hosts it
    in the original device list — the reference's ``qap::solve``
    assignment vector. Returns an error string, or None when valid."""
    if placement is None:
        return None
    try:
        f = [int(v) for v in placement]
    except (TypeError, ValueError):
        return f"placement must be a sequence of ints, got {placement!r}"
    if len(f) != ndev:
        return (f"placement has {len(f)} entries for {ndev} mesh "
                "positions")
    if sorted(f) != list(range(ndev)):
        return f"placement {f} is not a permutation of range({ndev})"
    return None


@dataclass(frozen=True)
class PlanChoice:
    """One point in the search space — what the autotuner picks and the
    DB persists: partition shape x exchange method x quantity batching x
    temporal depth k x kernel variant x block placement.

    ``placement`` is the topology-aware block→device assignment
    (reference: ``NodeAware``/``qap::solve``): ``placement[i]`` is the
    index (into the original device list) of the device hosting mesh
    position i, row-major (z, y, x) over the mesh grid. ``None`` is the
    identity assignment — the historical block order = device order —
    and is what every pre-placement DB entry deserializes to (the
    schema-migration default: an absent field IS identity).

    ``hierarchy`` is the outer DCN split ``(axis, hosts)`` — ``None``
    (and every pre-hierarchy DB entry / ckpt meta, via the same
    absent-field default) is the flat single-level plan.
    ``host_placement`` is the outer QAP's blocks→hosts assignment
    (``host_placement[s]`` = the host index serving host-slot s), the
    two-level analogue of ``placement``; the composed per-device
    permutation still lives in ``placement`` so realize() applies it
    through the existing machinery unchanged."""

    partition: Tuple[int, int, int]   # blocks (x, y, z)
    method: str                       # METHODS value string
    batch_quantities: bool = True
    multistep_k: int = 1
    kernel_variant: Optional[str] = None
    placement: Optional[Tuple[int, ...]] = None
    hierarchy: Optional[Tuple[str, int]] = None
    host_placement: Optional[Tuple[int, ...]] = None

    def to_json(self) -> dict:
        return {
            "partition": list(self.partition),
            "method": self.method,
            "batch_quantities": self.batch_quantities,
            "multistep_k": self.multistep_k,
            "kernel_variant": self.kernel_variant,
            "placement": (None if self.placement is None
                          else list(self.placement)),
            "hierarchy": (None if self.hierarchy is None
                          else [self.hierarchy[0], self.hierarchy[1]]),
            "host_placement": (None if self.host_placement is None
                               else list(self.host_placement)),
        }

    @classmethod
    def from_json(cls, obj: dict) -> "PlanChoice":
        placement = obj.get("placement")
        hierarchy = obj.get("hierarchy")
        host_placement = obj.get("host_placement")
        return cls(
            partition=tuple(obj["partition"]),
            method=str(obj["method"]),
            batch_quantities=bool(obj.get("batch_quantities", True)),
            multistep_k=int(obj.get("multistep_k", 1)),
            kernel_variant=obj.get("kernel_variant"),
            placement=(None if placement is None
                       else tuple(int(v) for v in placement)),
            hierarchy=(None if hierarchy is None
                       else (str(hierarchy[0]), int(hierarchy[1]))),
            host_placement=(None if host_placement is None
                            else tuple(int(v) for v in host_placement)),
        )

    @property
    def is_fused(self) -> bool:
        """The fused compute+exchange mega-kernel variant of REMOTE_DMA."""
        return self.kernel_variant == FUSED_VARIANT

    @property
    def is_persistent(self) -> bool:
        """The persistent whole-chunk mega-kernel variant of REMOTE_DMA
        (deep-halo temporal fusion; ``multistep_k`` is the chunk depth)."""
        return self.kernel_variant == PERSISTENT_VARIANT

    @property
    def is_placed(self) -> bool:
        """True when the choice carries a non-identity block placement."""
        return (self.placement is not None
                and list(self.placement) != list(range(len(self.placement))))

    @property
    def is_hierarchical(self) -> bool:
        """True when the choice carries a real (multi-host) outer split."""
        return self.hierarchy is not None and self.hierarchy[1] > 1

    def fingerprint(self) -> str:
        """Short stable content hash of the choice (12 hex chars of the
        sha256 of its canonical JSON). The observatory's join key: a
        telemetry/ledger/bench record stamped with it is attributable to
        exactly this plan, where ``label()`` elides identity placements
        and default fields for readability."""
        blob = json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:12]

    def label(self) -> str:
        px, py, pz = self.partition
        s = f"{px}x{py}x{pz}/{self.method}"
        s += "/batched" if self.batch_quantities else "/per-quantity"
        if self.multistep_k > 1:
            s += f"/k={self.multistep_k}"
        if self.kernel_variant:
            s += f"/{self.kernel_variant}"
        if self.hierarchy is not None:
            s += f"/h={self.hierarchy[0]}{self.hierarchy[1]}"
        if self.host_placement is not None and \
                list(self.host_placement) != \
                list(range(len(self.host_placement))):
            s += "/hp=" + "-".join(str(v) for v in self.host_placement)
        if self.is_placed:
            s += "/p=" + "-".join(str(v) for v in self.placement)
        return s
