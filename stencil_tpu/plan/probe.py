"""Measured refinement: time the top static candidates, briefly.

The static model (plan/cost.py) orders the search space; this module
buys the truth for the few candidates that matter. Each probe reuses
``apps/_bench_common.time_exchange`` — the SAME harness every exchange
bench runs, so a probe emits the same telemetry-JSONL evidence
(census counters, ``exchange.trimean_s`` gauges) as a full bench leg,
plus ``plan.probe`` spans and a ``plan.probe_trimean_s`` gauge tagged
with the candidate label.

Probes measure the exchange program of a candidate: its partition shape,
method, quantity batching, and the DEEPENED radius of its temporal k
(the k-step multistep exchanges radius*k halos once per k steps, so the
probed per-step exchange cost is trimean/k). Kernel-variant candidates
share the exchange probe — the variant's compute delta rides the static
model until app-level probes exist (ROADMAP #1's TPU ledger) — EXCEPT the
fused compute+exchange variant, whose exchange program itself differs
(concurrent per-direction kernel-initiated transport) and is probed as
such via ``time_exchange(fused=True)``. The persistent whole-chunk
variant's EXCHANGE program is the deep-halo plain REMOTE_DMA slab
program at radius*k — precisely what the scaled-radius probe above
measures — so it shares that probe; its launch-count saving rides the
static model's MODELED constants until scripts/probe_persistent.py runs
on silicon (item 1).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

from ..geometry import Dim3
from .cost import scale_radius
from .ir import PlanChoice, PlanConfig


def probe_choice(config: PlanConfig, choice: PlanChoice,
                 iters: int = 4, devices=None,
                 chunk: Optional[int] = None) -> dict:
    """Time one candidate's exchange; returns a probe record
    (label/trimean_s/per_step_s/gb_per_s + the census the run recorded).
    Raises on an unrealizable candidate — callers filter with
    cost.feasible first."""
    import jax

    from ..apps._bench_common import time_exchange
    from ..obs import telemetry
    from ..parallel import Method

    devices = list(devices) if devices is not None else \
        jax.devices()[: config.ndev]
    # probe the dominant dtype at the full quantity count: mixed-dtype
    # configs group per dtype at lowering time either way, and the
    # collective economics under test are count-driven
    dtype = max(config.quantities, key=lambda t: (t[1], t[0]))[0]
    radius = scale_radius(config.radius_obj(), choice.multistep_k)
    rec = telemetry.get()
    label = choice.label()
    t0 = time.perf_counter()
    with rec.span("plan.probe", phase="plan", plan=label):
        r = time_exchange(
            Dim3.of(config.grid), radius, iters,
            method=Method(choice.method), devices=devices,
            quantities=config.num_quantities, dtype=dtype,
            chunk=chunk if chunk is not None else min(iters, 5),
            batch_quantities=choice.batch_quantities,
            partition=choice.partition,
            fused=choice.is_fused,
            # a placed candidate probes on its placed mesh — the tuned
            # assignment must be what the measurement measured
            placement=choice.placement,
            # a hierarchical candidate probes the two-level transport on
            # the live host fabric (its composed placement above is what
            # aligns each segment onto one host)
            hierarchy=choice.hierarchy,
        )
    trimean = r["trimean_s"]
    rec.gauge("plan.probe_trimean_s", trimean, phase="plan", unit="s",
              plan=label)
    return {
        "label": label,
        "choice": choice.to_json(),
        "trimean_s": trimean,
        "per_step_s": trimean / choice.multistep_k,
        "gb_per_s": r["gb_per_s"],
        "iters": iters,
        "wall_s": time.perf_counter() - t0,
    }


def refine(config: PlanConfig,
           ranked: Sequence[Tuple[object, PlanChoice]],
           top_n: int = 3, iters: int = 4,
           devices=None) -> Tuple[Optional[PlanChoice], List[dict]]:
    """Probe the ``top_n`` cheapest static candidates and return
    (measured winner by per-step seconds, probe records). A probe that
    raises is recorded as failed and skipped — a candidate the backend
    cannot realize must not kill the tuning run."""
    from ..utils import logging as log

    probes: List[dict] = []
    best: Optional[PlanChoice] = None
    best_s = float("inf")
    for _cost, choice in list(ranked)[:top_n]:
        try:
            p = probe_choice(config, choice, iters=iters, devices=devices)
        except Exception as e:  # noqa: BLE001 — evidence, then next candidate
            log.warn(f"plan probe {choice.label()} failed: "
                     f"{type(e).__name__}: {e}")
            probes.append({
                "label": choice.label(), "choice": choice.to_json(),
                "error": f"{type(e).__name__}: {e}"[:400],
            })
            continue
        probes.append(p)
        if p["per_step_s"] < best_s:
            best_s = p["per_step_s"]
            best = choice
    return best, probes
