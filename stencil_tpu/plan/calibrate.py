"""Fit the cost model's constants from measured attribution records.

The predict→measure→refit loop's REFIT third: ``obs/attribution.py``
maps each run's measured exchange-phase seconds onto the ExchangePlan
IR's predictions (``plan.attrib.phase`` records); this module turns the
accumulated samples back into calibration constants — per-method
per-collective overhead and wire bandwidth — by least squares over the
cost model's own linear form (plan/cost.score's permute branch):

    measured_s  ≈  overhead[method] * collectives  +  wire_bytes / bw

For ``remote-dma`` samples the ``collectives`` field carries the plan's
DMA count (cost.score prices per-copy overhead there), so the same
design matrix recovers the per-copy constant; on a cpu-platform fit it
lands in ``remote_dma.cpu_emulation_overhead_s``, on tpu in
``remote_dma.dma_overhead_s`` — the platform split score() already
prices.

Pure stdlib by design (normal equations + Gaussian elimination on a
handful of unknowns): a calibrate run must work backend-less, exactly
like ``plan_tool show``. Degenerate input is refused loudly
(:class:`CalibrationError`): a single sample cannot separate overhead
from bandwidth, and a silently garbage fit would mis-rank every plan
the DB serves afterwards. When every sample shares one (collectives,
wire_bytes) point — the common one-config case — the bandwidth
direction is unidentifiable; the fit then PINS bandwidth at the base
calibration's value and fits only the overheads, which is exactly the
information the data contains.

The fitted row persists in the plan DB (plan/db.py ``calibrations``
section) with provenance ``fitted(n=…, r2=…)`` — the middle rung of the
provenance ladder MODELED → fitted → measured — and ``plan/autotune.py``
auto-installs it for the matching platform on every tuning run.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from .cost import DEFAULT_CALIBRATION
from .ir import AUTO_SPMD, AXIS_COMPOSED, DIRECT26, METHODS, REMOTE_DMA

ATTRIB_NAME = "plan.attrib.phase"
PERMUTE_METHODS = (AXIS_COMPOSED, DIRECT26, AUTO_SPMD)


class CalibrationError(ValueError):
    """Degenerate or non-physical calibration input — refused loudly."""


@dataclass(frozen=True)
class Sample:
    """One measured attribution point (one ``plan.attrib.phase`` record)."""

    method: str
    collectives: int      # permute count, or DMA count for remote-dma
    wire_bytes: int
    measured_s: float
    phase: str = ""

    def validate(self) -> Optional[str]:
        if self.method not in METHODS:
            return f"unknown method {self.method!r}"
        if self.collectives < 0 or self.wire_bytes < 0:
            return "negative collectives/wire_bytes"
        if not (self.measured_s == self.measured_s
                and self.measured_s > 0.0):  # NaN-safe positivity
            return f"non-positive measured_s {self.measured_s!r}"
        return None


def provenance_string(n: int, r2: float) -> str:
    return f"fitted(n={n}, r2={r2:.3f})"


def samples_from_records(records: Sequence[dict]) -> List[Sample]:
    """Extract attribution samples from telemetry records (the
    ``--metrics-out`` JSONL, already schema-validated by the caller).
    Malformed attribution records raise — a fit over silently dropped
    samples would claim an n it does not have."""
    out: List[Sample] = []
    for r in records:
        if r.get("kind") != "meta" or r.get("name") != ATTRIB_NAME:
            continue
        s = Sample(method=str(r["method"]),
                   collectives=int(r["collectives"]),
                   wire_bytes=int(r["wire_bytes"]),
                   measured_s=float(r["measured_s"]),
                   phase=str(r.get("phase", "")))
        err = s.validate()
        if err:
            raise CalibrationError(f"bad attribution record: {err}")
        out.append(s)
    return out


def samples_from_ledger(entries: Sequence[dict]) -> List[Sample]:
    """Reconstruct samples from ledger entries (the ``plan.attrib.*``
    rows obs/ledger ingest writes). Lower resolution than
    ``samples_from_records``: the ledger folds a run's samples into one
    trimean per (phase, method) and dedups by entry key, so a fit from
    the ledger sees one point per run/config where the metrics file had
    several."""
    out: List[Sample] = []
    for e in entries:
        if not str(e.get("metric", "")).startswith("plan.attrib."):
            continue
        d = e.get("detail") or {}
        if not {"method", "collectives", "wire_bytes"} <= set(d):
            continue
        s = Sample(method=str(d["method"]),
                   collectives=int(d["collectives"]),
                   wire_bytes=int(d["wire_bytes"]),
                   measured_s=float(e["value"]),
                   phase=str(d.get("phase", "")))
        err = s.validate()
        if err:
            raise CalibrationError(f"bad ledger attribution entry: {err}")
        out.append(s)
    return out


# -- the least-squares core (pure stdlib) -------------------------------------


def _solve(a: List[List[float]], b: List[float]) -> Optional[List[float]]:
    """Gaussian elimination with partial pivoting on a tiny system;
    None when singular (rank-deficient within tolerance)."""
    n = len(a)
    m = [row[:] + [b[i]] for i, row in enumerate(a)]
    scale = max((abs(v) for row in a for v in row), default=0.0)
    if scale == 0.0:
        return None
    eps = 1e-12 * scale
    for col in range(n):
        piv = max(range(col, n), key=lambda r: abs(m[r][col]))
        if abs(m[piv][col]) <= eps:
            return None
        m[col], m[piv] = m[piv], m[col]
        for r in range(n):
            if r == col:
                continue
            f = m[r][col] / m[col][col]
            for c in range(col, n + 1):
                m[r][c] -= f * m[col][c]
    return [m[i][n] / m[i][i] for i in range(n)]


def _lstsq(rows: List[List[float]], b: List[float]) -> Optional[List[float]]:
    """min ||Ax - b|| via normal equations (the design has <= 5 columns;
    conditioning is a non-issue at these sizes). None when singular."""
    if not rows:
        return None
    ncol = len(rows[0])
    # column scaling: collectives are O(1..100), wire bytes O(1e5..1e9);
    # raw normal equations would read the bandwidth column as "singular"
    # purely on magnitude. Scale each column to unit max first.
    scales = [max(abs(r[c]) for r in rows) or 1.0 for c in range(ncol)]
    srows = [[r[c] / scales[c] for c in range(ncol)] for r in rows]
    ata = [[sum(r[i] * r[j] for r in srows) for j in range(ncol)]
           for i in range(ncol)]
    atb = [sum(r[i] * bi for r, bi in zip(srows, b)) for i in range(ncol)]
    x = _solve(ata, atb)
    if x is None:
        return None
    return [x[c] / scales[c] for c in range(ncol)]


def _r2(predicted: Sequence[float], measured: Sequence[float]) -> float:
    mean = sum(measured) / len(measured)
    ss_tot = sum((v - mean) ** 2 for v in measured)
    ss_res = sum((p - v) ** 2 for p, v in zip(predicted, measured))
    if ss_tot <= 0.0:
        # all samples identical: the model either nails the point or not
        return 1.0 if ss_res <= 1e-18 else 0.0
    return 1.0 - ss_res / ss_tot


def fit(samples: Sequence[Sample], *, platform: str = "cpu",
        base: Optional[dict] = None) -> dict:
    """Fit a calibration override from attribution samples.

    Returns a plan-DB calibration row::

        {"calibration": {...score() override dict...},
         "provenance": "fitted(n=…, r2=…)",
         "n": int, "r2": float, "platform": str,
         "bandwidth_fit": bool,   # False when pinned at the base value
         "written_t": float}

    Raises :class:`CalibrationError` on degenerate input: fewer than two
    samples (a single point cannot separate overhead from bandwidth),
    zero-collective samples, or a fit that comes out non-physical
    (overhead <= 0 — garbage in, refused out)."""
    samples = list(samples)
    if len(samples) < 2:
        raise CalibrationError(
            f"need >= 2 attribution samples to fit, got {len(samples)} — "
            "a single sample cannot separate per-collective overhead from "
            "wire bandwidth")
    for s in samples:
        err = s.validate()
        if err:
            raise CalibrationError(f"bad sample: {err}")
        if s.collectives == 0:
            raise CalibrationError(
                f"sample for {s.method} has 0 collectives/DMAs — its "
                "overhead column is unidentifiable")
    base = base or DEFAULT_CALIBRATION
    base_bw = float(base.get("wire_bytes_per_s",
                             DEFAULT_CALIBRATION["wire_bytes_per_s"]))
    methods = sorted({s.method for s in samples})

    rows = [[float(s.collectives) if s.method == m else 0.0
             for m in methods] + [float(s.wire_bytes)] for s in samples]
    b = [s.measured_s for s in samples]
    x = _lstsq(rows, b)
    bandwidth_fit = x is not None and x[-1] > 0.0
    if not bandwidth_fit:
        # the bandwidth direction is unidentifiable (every sample at one
        # (collectives, bytes) point) or came out non-physical: pin it
        # at the base calibration and fit only what the data determines
        rows = [[float(s.collectives) if s.method == m else 0.0
                 for m in methods] for s in samples]
        b = [s.measured_s - s.wire_bytes / base_bw for s in samples]
        x = _lstsq(rows, b)
        if x is None:
            raise CalibrationError(
                "rank-deficient attribution set: the per-method overhead "
                "columns are not independent (need samples from distinct "
                "methods or distinct collective counts)")
        x = x + [1.0 / base_bw]

    overheads = dict(zip(methods, x[:-1]))
    inv_bw = x[-1]
    for m, ov in overheads.items():
        if not (ov == ov and ov > 0.0):
            raise CalibrationError(
                f"non-physical fit: overhead {ov!r} s/collective for "
                f"{m} — refusing to install (check the attribution "
                "samples; measured time below the modeled wire time?)")
    wire_bps = 1.0 / inv_bw

    predicted = [overheads[s.method] * s.collectives
                 + s.wire_bytes / wire_bps for s in samples]
    r2 = _r2(predicted, [s.measured_s for s in samples])

    cal: Dict[str, object] = {}
    permute = {m: overheads[m] for m in methods if m in PERMUTE_METHODS}
    if permute:
        cal["permute_overhead_s"] = permute
    n = len(samples)
    prov = provenance_string(n, r2)
    if REMOTE_DMA in overheads:
        key = ("dma_overhead_s" if platform == "tpu"
               else "cpu_emulation_overhead_s")
        cal["remote_dma"] = {key: overheads[REMOTE_DMA],
                             "provenance": prov}
    if bandwidth_fit:
        cal["wire_bytes_per_s"] = wire_bps
    cal["provenance"] = prov
    return {
        "calibration": cal,
        "provenance": prov,
        "n": n,
        "r2": r2,
        "platform": platform,
        "bandwidth_fit": bandwidth_fit,
        "written_t": time.time(),
    }


def diff_rows(fitted: dict, base: Optional[dict] = None
              ) -> List[Tuple[str, float, float]]:
    """(constant, fitted value, base value) per fitted scalar — the
    ``plan_tool calibration diff`` table."""
    base = base or DEFAULT_CALIBRATION
    cal = fitted.get("calibration", fitted)
    out: List[Tuple[str, float, float]] = []
    for m, v in sorted((cal.get("permute_overhead_s") or {}).items()):
        out.append((f"permute_overhead_s[{m}]", float(v),
                    float(base["permute_overhead_s"].get(m, float("nan")))))
    rd = cal.get("remote_dma") or {}
    for k in ("dma_overhead_s", "cpu_emulation_overhead_s"):
        if k in rd:
            out.append((f"remote_dma.{k}", float(rd[k]),
                        float(base["remote_dma"][k])))
    if "wire_bytes_per_s" in cal:
        out.append(("wire_bytes_per_s", float(cal["wire_bytes_per_s"]),
                    float(base["wire_bytes_per_s"])))
    return out
