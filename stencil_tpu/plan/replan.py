"""Mid-run plan hot-swap: the consumer of ``replan.requested``.

PR 12's :class:`~stencil_tpu.obs.live.LiveSentinel` detects that a run
got slow *while it is still running* and fires ``replan.requested``
through its ``on_replan`` hook — which, until now, nothing attached to.
This module is the missing half of ROADMAP #6: a
:class:`ReplanController` latches the request (the hook runs inside the
sentinel's observe path and must stay cheap and non-throwing), and the
guarded loop (``fault/recover.run_guarded``) finishes its current chunk,
then asks the controller to swap:

1. ``retune_fn()`` re-probes the autotuner (``plan/autotune.autotune``
   with ``force=True`` — the compile cache makes re-realizing a
   previously-seen program cheap) and returns the winning
   :class:`~stencil_tpu.plan.ir.PlanChoice`;
2. ``apply_fn(choice, state)`` installs the new compiled plan —
   typically :meth:`DistributedDomain.replan`, the in-memory elastic
   reshard — and returns the re-sharded state (or None to keep the
   caller's);
3. the swap emits ``replan.applied`` with the old/new choice labels and
   the static model's predicted gain, and resets the sentinel's windows
   (the old band described the old plan's latencies);
4. ANY exception in retune/apply emits ``replan.rejected`` and the run
   continues on the old plan — a throwing autotuner must never turn a
   slow run into a dead one.

The campaign driver runs the same controller between slots (a slot's
compiled program is bucket-keyed, so its swap point is the slot
boundary, not the chunk boundary).

State across the swap is bit-identical by construction: the swap is the
elastic checkpoint restore without the disk (scripts/ci_replan_gate.py
pins a swapped run's final field against an unswapped one).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ..utils import logging as log

REPLAN_APPLIED = "replan.applied"
REPLAN_REJECTED = "replan.rejected"


class ReplanController:
    """Latches ``replan.requested`` events and performs the plan swap
    between chunks.

    - ``retune_fn() -> PlanChoice`` re-runs the autotuner and returns
      the plan to install;
    - ``apply_fn(choice, state) -> state | None`` installs it (None
      keeps the caller's state object — the campaign's between-slot
      swap has no state to transform);
    - ``current_choice`` is what the run is executing now (a retune
      that returns it is a rejected no-op, not a swap);
    - ``sentinel`` (optional) gets ``reset()`` after an applied swap;
    - ``config``/``calibration``/``link_costs`` (optional) let the
      controller attach the static model's predicted gain
      (old modeled total / new modeled total) to ``replan.applied``;
    - ``max_swaps`` bounds the run's swap budget: a plan oscillation
      must converge, not flap — beyond the budget further requests are
      rejected loudly.
    """

    def __init__(
        self,
        retune_fn: Callable[[], object],
        apply_fn: Callable[[object, Optional[Dict]], Optional[Dict]],
        *,
        current_choice=None,
        sentinel=None,
        config=None,
        calibration: Optional[dict] = None,
        link_costs=None,
        max_swaps: int = 3,
        rec=None,
    ):
        self.retune_fn = retune_fn
        self.apply_fn = apply_fn
        self.current_choice = current_choice
        self.sentinel = sentinel
        self.config = config
        self.calibration = calibration
        self.link_costs = link_costs
        self.max_swaps = int(max_swaps)
        self._rec = rec
        self.swaps = 0
        self.rejected = 0
        self._pending: Optional[dict] = None

    def _recorder(self):
        if self._rec is not None:
            return self._rec
        from ..obs import telemetry

        return telemetry.get()

    # -- the sentinel hook ----------------------------------------------------
    def request(self, event: dict) -> None:
        """The ``LiveSentinel(on_replan=...)`` hook: latch the request.
        Cheap and non-throwing by contract — the swap itself runs later,
        between chunks, where a rebuild cannot tear a step."""
        self._pending = dict(event or {})

    @property
    def pending(self) -> bool:
        return self._pending is not None

    # -- the swap -------------------------------------------------------------
    def _modeled_gain(self, old, new) -> Optional[float]:
        if self.config is None or old is None or new is None:
            return None
        try:
            from .cost import score

            so = score(self.config, old, self.calibration,
                       link_costs=self.link_costs)
            sn = score(self.config, new, self.calibration,
                       link_costs=self.link_costs)
            if so is None or sn is None or sn.total_s <= 0:
                return None
            return so.total_s / sn.total_s
        except Exception:  # the gain is garnish, never a failure mode
            return None

    def maybe_swap(self, state: Optional[Dict], step: int) -> Optional[Dict]:
        """Perform the latched swap, if any. Returns the (possibly
        re-sharded) state to continue with, or None when the caller's
        state is unchanged — on a rejected swap the run ALWAYS continues
        on the old plan."""
        ev = self._pending
        if ev is None:
            return None
        self._pending = None
        rec = self._recorder()
        step = int(step)
        reason = str(ev.get("metric") or ev.get("reason") or "anomaly")
        old = self.current_choice
        old_label = old.label() if old is not None else "untuned"
        if self.swaps >= self.max_swaps:
            self.rejected += 1
            rec.meta(REPLAN_REJECTED, step=step, phase="plan",
                     reason=f"swap budget ({self.max_swaps}) exhausted",
                     old=old_label, trigger=reason)
            log.warn(f"replan: swap budget ({self.max_swaps}) exhausted; "
                     "continuing on the current plan")
            return None
        t0 = time.perf_counter()
        try:
            new = self.retune_fn()
            if new is None:
                raise ValueError("retune returned no choice")
            if old is not None and new == old:
                self.rejected += 1
                rec.meta(REPLAN_REJECTED, step=step, phase="plan",
                         reason="retune confirmed the current choice",
                         old=old_label, trigger=reason)
                log.info(f"replan: retune confirmed {old_label}; no swap")
                # the anomaly stands but the plan is already the best
                # known — reset the window so one excursion does not
                # re-request every subsequent chunk
                if self.sentinel is not None:
                    self.sentinel.reset()
                return None
            new_state = self.apply_fn(new, state)
        except Exception as e:  # noqa: BLE001 — degrade loudly, keep running
            self.rejected += 1
            rec.meta(REPLAN_REJECTED, step=step, phase="plan",
                     reason=f"{type(e).__name__}: {e}"[:400],
                     old=old_label, trigger=reason)
            log.warn(f"replan: swap failed ({type(e).__name__}: {e}); "
                     "continuing on the old plan")
            return None
        self.swaps += 1
        gain = self._modeled_gain(old, new)
        self.current_choice = new
        rec.meta(REPLAN_APPLIED, step=step, phase="plan",
                 old=old_label, new=new.label(), trigger=reason,
                 modeled_gain=gain,
                 swap_wall_s=time.perf_counter() - t0)
        log.warn(
            f"replan: APPLIED {old_label} -> {new.label()} at step {step}"
            + (f" (modeled gain {gain:.3g}x)" if gain else ""))
        if self.sentinel is not None:
            # the old window's band judged the OLD plan; restart from
            # warmup so the swap-compile spike and the new latency level
            # are learned, not condemned
            self.sentinel.reset()
        return new_state
