"""Static exchange-plan cost model — rank candidates without compiling.

The model scores one :class:`~stencil_tpu.plan.ir.PlanChoice` for one
:class:`~stencil_tpu.plan.ir.PlanConfig` from the ExchangePlan IR alone:
collective-permute count, estimated on-wire bytes, and local slab bytes
fall out of the phase list (plan/ir.py), and the per-collective overhead
constants are calibrated from the censuses + wall-clocks this repo has
RECORDED (BASELINE.md rounds 7/10, 8-device CPU mesh, jax 0.4.37):

- Round 10 quantity-batching A/B (128^3, 2x2x2, fp32): Q=8 batched
  42.9 ms / 6 permutes vs per-quantity 70.6 ms / 48 permutes — the
  42-permute delta prices one composed ppermute at ~0.66 ms.
- Round 7 ablation (same leg, Q=4): composed 47.6 ms / 24 permutes /
  12.48 MB on-wire. Subtracting 24 x 0.66 ms leaves ~32 ms for the
  payload -> ~390 MB/s effective wire bandwidth.
- direct26: 200.7 ms / 104 permutes / 6.69 MB. With the same wire rate,
  the residual prices a direct26 permute at ~1.76 ms — the exact-extent
  messages are small and strided, so their per-collective overhead is
  ~2.7x the slab phases' (the reference found the same economics for
  many small MPI messages vs packed slabs).
- auto-spmd: 49.5 ms for the identical 24-permute/12.48 MB schedule ->
  ~0.73 ms per synthesized permute (manual wins ~4%).

These are RANKING constants, not performance claims: per-collective
overhead dominating payload is the recorded regime on this stack, and the
model's job is ordering candidates for the measured refinement pass
(plan/probe.py). A TPU-measured recalibration is the ROADMAP #1 ledger's
follow-up; ``calibration=`` overrides let a probe session supply one.

This module is jax-free: scoring builds GridSpecs and ExchangePlans
(pure geometry), so enumerating hundreds of candidates costs
milliseconds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..domain.grid import GridSpec
from ..geometry import Dim3, Radius, stack_residents
from .ir import (
    AUTO_SPMD,
    AXIS_COMPOSED,
    DIRECT26,
    FUSED_VARIANT,
    METHODS,
    REMOTE_DMA,
    PlanChoice,
    PlanConfig,
    build_plan,
)

# Calibration provenance: BASELINE.md rounds 7/10 (see module docstring).
DEFAULT_CALIBRATION: Dict[str, object] = {
    "permute_overhead_s": {
        AXIS_COMPOSED: 6.6e-4,
        DIRECT26: 1.76e-3,
        AUTO_SPMD: 7.3e-4,
    },
    "wire_bytes_per_s": 3.9e8,
    "local_bytes_per_s": 4.0e9,
    # per-cell update cost for the multistep redundant-compute tradeoff
    # (order-of-magnitude CPU figure; the probe pass owns the truth)
    "cell_update_s": 1.0e-9,
    # relative compute factor per kernel variant (unknown -> 1.0: the
    # static model deliberately ties variants and lets the probes decide)
    "variant_factor": {},
    # Method.REMOTE_DMA: kernel-initiated per-neighbor async copies
    # bypass the XLA collective path entirely (0 ppermutes). Provenance:
    # MODELED, pending the item-1 TPU recalibration session — no ICI
    # measurement of this transport exists yet. dma_overhead_s is the
    # modeled per-copy issue+sync cost on TPU (the whole point of the
    # method: a fraction of a ppermute's ~0.66 ms dispatch);
    # cpu_emulation_overhead_s prices the CPU lowering honestly — each
    # emulated copy is a host-orchestrated device_put round-trip, so on
    # a cpu-platform config REMOTE_DMA ranks BELOW the ppermute methods
    # (the probes confirm; on tpu configs the model lets it compete).
    "remote_dma": {
        "dma_overhead_s": 8.0e-5,
        "cpu_emulation_overhead_s": 4.0e-3,
        "wire_bytes_per_s": 3.9e8,
        "provenance": "modeled, pending item-1 TPU recalibration",
    },
    # The fused compute+exchange mega-kernel (kernel_variant == "fused"
    # on a REMOTE_DMA choice): the substep's wall-clock is
    # max(interior_compute, dma) + boundary_compute — wire time hides
    # behind interior FLOPs. Scored against candidates whose totals omit
    # the (common) sweep compute, the fused EXCHANGE-attributable cost is
    # that expression minus the full sweep: per-copy issue overhead plus
    # only the UNHIDDEN wire time, max(0, dma - interior_compute).
    # Provenance: MODELED, pending the item-1 TPU session — no silicon
    # measurement of the overlap exists yet; probe_remote_dma.py's fused
    # leg is the measurement that flips this to measured.
    "fused": {
        "provenance": "modeled, pending item-1 TPU recalibration",
    },
}


@dataclass(frozen=True)
class PlanCost:
    """Static score of one candidate, per simulation step."""

    total_s: float          # the ranking key
    exchange_s: float       # one exchange's predicted wall-clock
    collectives: int        # permutes per exchange (census-comparable)
    wire_bytes: int         # estimated interconnect bytes per exchange
    local_bytes: int        # estimated local slab bytes per exchange
    compute_overhead_s: float  # multistep redundant-compute price per step
    dmas: int = 0           # kernel-initiated async copies (REMOTE_DMA only)

    def to_json(self) -> dict:
        return {
            "total_s": self.total_s,
            "exchange_s": self.exchange_s,
            "collectives": self.collectives,
            "wire_bytes": self.wire_bytes,
            "local_bytes": self.local_bytes,
            "compute_overhead_s": self.compute_overhead_s,
            "dmas": self.dmas,
        }


def scale_radius(radius: Radius, k: int) -> Radius:
    """The radius a temporal-depth-k multistep realizes: every direction's
    halo (and diagonal gate) scaled by k, so one exchange feeds k steps."""
    if k == 1:
        return radius
    out = Radius.constant(0)
    for d, r in radius._r.items():
        out.set_dir(d, r * k)
    return out


def feasible(config: PlanConfig, choice: PlanChoice) -> Optional[Tuple]:
    """(spec, mesh_dim, resident) when the candidate can realize on this
    config, else None. Mirrors realize()'s constraints exactly: the
    partition's block count must be a multiple of ndev (residents stacked
    by the same z-heavy factorization), and no block may be thinner than
    the effective radius. The fused compute+exchange variant is a
    REMOTE_DMA-only, single-resident lowering — any other combination is
    infeasible here (the loud-infeasibility contract: realize() raises
    the same constraints)."""
    if choice.kernel_variant == FUSED_VARIANT:
        if choice.method != REMOTE_DMA:
            return None
        if choice.multistep_k != 1:
            # the fused lowering runs ONE fused exchange per step and
            # ignores temporal_k (ops/jacobi._compile_jacobi_fused warns
            # and proceeds per-step) — scoring k>1 would amortize an
            # exchange the realized program pays every step
            return None
    dim = Dim3.of(choice.partition)
    g = Dim3.of(config.grid)
    if g.x < dim.x or g.y < dim.y or g.z < dim.z:
        return None
    nb = dim.flatten()
    if nb % config.ndev:
        return None
    radius = scale_radius(config.radius_obj(), choice.multistep_k)
    try:
        spec = GridSpec(g, dim, radius)
    except (AssertionError, ValueError):
        return None
    c = nb // config.ndev
    if c == 1:
        mesh_dim = dim
    else:
        try:
            mesh_dim = stack_residents(dim, c)
        except ValueError:
            return None
    for sizes, rm, rp in (
        (spec.sizes_x, radius.x(-1), radius.x(1)),
        (spec.sizes_y, radius.y(-1), radius.y(1)),
        (spec.sizes_z, radius.z(-1), radius.z(1)),
    ):
        if min(sizes) < max(rm, rp):
            return None  # halo would span multiple blocks
    resident = Dim3(dim.x // mesh_dim.x, dim.y // mesh_dim.y,
                    dim.z // mesh_dim.z)
    if choice.kernel_variant == FUSED_VARIANT and resident != Dim3(1, 1, 1):
        return None  # the fused kernel is single-resident (build_plan raises)
    return spec, mesh_dim, resident


def score(config: PlanConfig, choice: PlanChoice,
          calibration: Optional[dict] = None) -> Optional[PlanCost]:
    """Static per-step cost of one candidate (None when infeasible).

    The score is a function of the dtype MULTISET only — a config whose
    quantity list is a permutation of another's scores identically, so
    the ranking is invariant under quantity-dtype permutation
    (tests/test_plan_cost.py pins this)."""
    cal = dict(DEFAULT_CALIBRATION)
    for k, v in (calibration or {}).items():
        # dict-valued keys (per-method overheads, variant factors) merge
        # per entry so a partial override falls back to the defaults for
        # every method it does not mention
        if isinstance(v, dict) and isinstance(cal.get(k), dict):
            cal[k] = {**cal[k], **v}
        else:
            cal[k] = v
    feas = feasible(config, choice)
    if feas is None:
        return None
    spec, mesh_dim, resident = feas
    fused = choice.kernel_variant == FUSED_VARIANT
    plan = build_plan(spec, mesh_dim, choice.method,
                      batch_quantities=choice.batch_quantities,
                      resident=resident, fused=fused)
    itemsizes = config.itemsizes()
    nq = config.num_quantities
    ngroups = config.dtype_group_count
    collectives = plan.collectives_per_exchange(nq, ngroups)
    wire = plan.wire_bytes(itemsizes, floating=config.floating_flags())
    local = plan.local_bytes(itemsizes)
    dmas = plan.dmas_per_exchange(nq, ngroups)
    if fused:
        # overlap-aware: the fused substep runs
        #   max(interior_compute, dma) + boundary_compute
        # — wire time hides behind interior FLOPs. Candidates' totals
        # omit the common full-sweep compute, so the fused cost charged
        # here is that expression minus (interior + boundary): the
        # per-copy issue overhead plus only the UNHIDDEN wire time.
        # Per-copy overhead stays platform-split like plain REMOTE_DMA
        # (the CPU schedule is host-orchestrated and must never win a
        # cpu ranking on a TPU-modeled constant); provenance of all of
        # it is cal["fused"]["provenance"] — MODELED until item 1's
        # TPU session runs probe_remote_dma.py's fused leg.
        rd = cal["remote_dma"]
        per_dma = (rd["dma_overhead_s"] if config.platform == "tpu"
                   else rd["cpu_emulation_overhead_s"])
        wire_s = wire / rd.get("wire_bytes_per_s", cal["wire_bytes_per_s"])
        b = spec.base
        r0 = config.radius_obj()
        shrink = [
            (rm + rp) if n > 1 else 0
            for n, rm, rp in (
                (mesh_dim.x, r0.x(-1), r0.x(1)),
                (mesh_dim.y, r0.y(-1), r0.y(1)),
                (mesh_dim.z, r0.z(-1), r0.z(1)),
            )
        ]
        interior_cells = (max(0, b.x - shrink[0]) * max(0, b.y - shrink[1])
                          * max(0, b.z - shrink[2]))
        interior_s = interior_cells * nq * cal["cell_update_s"]
        exchange_s = (
            dmas * per_dma
            + max(0.0, wire_s - interior_s)
            + local / cal["local_bytes_per_s"]
        )
    elif choice.method == REMOTE_DMA:
        # kernel-initiated copies: no ppermute dispatch at all; the
        # per-copy cost is platform-dependent (the CPU lowering is a
        # host-orchestrated emulation and must never win a cpu ranking
        # on the strength of a TPU-modeled constant)
        rd = cal["remote_dma"]
        per_dma = (rd["dma_overhead_s"] if config.platform == "tpu"
                   else rd["cpu_emulation_overhead_s"])
        exchange_s = (
            dmas * per_dma
            + wire / rd.get("wire_bytes_per_s", cal["wire_bytes_per_s"])
            + local / cal["local_bytes_per_s"]
        )
    else:
        overhead = cal["permute_overhead_s"][choice.method]
        exchange_s = (
            collectives * overhead
            + wire / cal["wire_bytes_per_s"]
            + local / cal["local_bytes_per_s"]
        )
    k = choice.multistep_k
    compute_overhead_s = 0.0
    if k > 1:
        # deep halos trade collective count for redundant edge compute:
        # each of the k-1 interior steps re-updates a shrinking halo
        # shell; the average extra shell is ~ (k-1)/2 radius-deep over
        # every block face
        b = spec.base
        r0 = config.radius_obj()
        rbar = (r0.x(-1) + r0.x(1) + r0.y(-1) + r0.y(1)
                + r0.z(-1) + r0.z(1)) / 6.0
        surface = 2 * (b.x * b.y + b.x * b.z + b.y * b.z) * spec.num_blocks()
        extra_cells = surface * rbar * (k - 1) / 2.0
        compute_overhead_s = extra_cells * nq * cal["cell_update_s"]
    vf = cal["variant_factor"].get(choice.kernel_variant, 1.0)
    total = exchange_s / k + compute_overhead_s * vf
    return PlanCost(
        total_s=total, exchange_s=exchange_s, collectives=collectives,
        wire_bytes=wire, local_bytes=local,
        compute_overhead_s=compute_overhead_s, dmas=dmas,
    )


def candidate_partitions(config: PlanConfig,
                         oversubscribe: Sequence[int] = (1,)) -> List[Tuple[int, int, int]]:
    """All (px, py, pz) block grids with ndev * c blocks (c in
    ``oversubscribe``), unfiltered for radius feasibility (score() is the
    gate). Ordered deterministically."""
    out = []
    for c in oversubscribe:
        n = config.ndev * c
        for px in range(1, n + 1):
            if n % px:
                continue
            nyz = n // px
            for py in range(1, nyz + 1):
                if nyz % py:
                    continue
                out.append((px, py, nyz // py))
    return out


# The default kernel-variant set, as an identity-comparable sentinel:
# enumerate_candidates() grows it with REMOTE_DMA's fused variant, while
# any EXPLICITLY passed variant list — (None,) included — is honored
# verbatim (plan_tool --variants none tunes plain remote-dma only).
DEFAULT_VARIANTS: Tuple[Optional[str], ...] = (None,)


def enumerate_candidates(
    config: PlanConfig,
    methods: Iterable[str] = METHODS,
    batch_options: Iterable[bool] = (True, False),
    ks: Iterable[int] = (1,),
    variants: Iterable[Optional[str]] = DEFAULT_VARIANTS,
    oversubscribe: Sequence[int] = (1,),
) -> List[PlanChoice]:
    """The search space: partition shape x method x quantity batching x
    temporal depth k x kernel variant. Batching only branches when the
    config has more than one quantity (at Q=1 the two programs are
    identical — PR 5's degeneration contract). With the DEFAULT variant
    set, REMOTE_DMA additionally branches on the fused compute+exchange
    variant (kernel_variant == "fused") so the autotuner searches the
    overlap lever out of the box; an EXPLICIT ``variants`` restriction —
    ``(None,)`` included — is honored verbatim (the sentinel comparison
    is by identity with :data:`DEFAULT_VARIANTS`). Infeasible fused
    points (oversubscribed partitions) fall out at score() like every
    other constraint."""
    if config.num_quantities <= 1:
        batch_options = (True,)
    default_variants = variants is DEFAULT_VARIANTS
    out = []
    for part in candidate_partitions(config, oversubscribe):
        for method in methods:
            vlist = list(variants)
            if (method == REMOTE_DMA and default_variants
                    and FUSED_VARIANT not in vlist):
                vlist.append(FUSED_VARIANT)
            for batch in batch_options:
                for k in ks:
                    for variant in vlist:
                        out.append(PlanChoice(
                            partition=part, method=method,
                            batch_quantities=batch, multistep_k=k,
                            kernel_variant=variant,
                        ))
    return out


def rank(config: PlanConfig, candidates: Iterable[PlanChoice],
         calibration: Optional[dict] = None) -> List[Tuple[PlanCost, PlanChoice]]:
    """Feasible candidates sorted cheapest-first. Ties break on the
    choice label so the order is total and deterministic (the
    permutation-invariance property needs a stable ranking)."""
    scored = []
    for choice in candidates:
        c = score(config, choice, calibration)
        if c is not None:
            scored.append((c, choice))
    scored.sort(key=lambda t: (t[0].total_s, t[1].label()))
    return scored
