"""Static exchange-plan cost model — rank candidates without compiling.

The model scores one :class:`~stencil_tpu.plan.ir.PlanChoice` for one
:class:`~stencil_tpu.plan.ir.PlanConfig` from the ExchangePlan IR alone:
collective-permute count, estimated on-wire bytes, and local slab bytes
fall out of the phase list (plan/ir.py), and the per-collective overhead
constants are calibrated from the censuses + wall-clocks this repo has
RECORDED (BASELINE.md rounds 7/10, 8-device CPU mesh, jax 0.4.37):

- Round 10 quantity-batching A/B (128^3, 2x2x2, fp32): Q=8 batched
  42.9 ms / 6 permutes vs per-quantity 70.6 ms / 48 permutes — the
  42-permute delta prices one composed ppermute at ~0.66 ms.
- Round 7 ablation (same leg, Q=4): composed 47.6 ms / 24 permutes /
  12.48 MB on-wire. Subtracting 24 x 0.66 ms leaves ~32 ms for the
  payload -> ~390 MB/s effective wire bandwidth.
- direct26: 200.7 ms / 104 permutes / 6.69 MB. With the same wire rate,
  the residual prices a direct26 permute at ~1.76 ms — the exact-extent
  messages are small and strided, so their per-collective overhead is
  ~2.7x the slab phases' (the reference found the same economics for
  many small MPI messages vs packed slabs).
- auto-spmd: 49.5 ms for the identical 24-permute/12.48 MB schedule ->
  ~0.73 ms per synthesized permute (manual wins ~4%).

These are RANKING constants, not performance claims: per-collective
overhead dominating payload is the recorded regime on this stack, and the
model's job is ordering candidates for the measured refinement pass
(plan/probe.py). A TPU-measured recalibration is the ROADMAP #1 ledger's
follow-up; ``calibration=`` overrides let a probe session supply one.

This module is jax-free: scoring builds GridSpecs and ExchangePlans
(pure geometry), so enumerating hundreds of candidates costs
milliseconds.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..domain.grid import GridSpec
from ..geometry import DIRECTIONS_26, Dim3, Radius, halo_extent, stack_residents
from .ir import (
    AUTO_SPMD,
    AXIS_COMPOSED,
    DIRECT26,
    FUSED_VARIANT,
    METHODS,
    PERSISTENT_VARIANT,
    REMOTE_DMA,
    PlanChoice,
    PlanConfig,
    build_plan,
    validate_hierarchy,
    validate_placement,
)

# Calibration provenance: BASELINE.md rounds 7/10 (see module docstring).
DEFAULT_CALIBRATION: Dict[str, object] = {
    "permute_overhead_s": {
        AXIS_COMPOSED: 6.6e-4,
        DIRECT26: 1.76e-3,
        AUTO_SPMD: 7.3e-4,
    },
    "wire_bytes_per_s": 3.9e8,
    "local_bytes_per_s": 4.0e9,
    # per-cell update cost for the multistep redundant-compute tradeoff
    # (order-of-magnitude CPU figure; the probe pass owns the truth)
    "cell_update_s": 1.0e-9,
    # relative compute factor per kernel variant (unknown -> 1.0: the
    # static model deliberately ties variants and lets the probes decide)
    "variant_factor": {},
    # Method.REMOTE_DMA: kernel-initiated per-neighbor async copies
    # bypass the XLA collective path entirely (0 ppermutes). Provenance:
    # MODELED, pending the item-1 TPU recalibration session — no ICI
    # measurement of this transport exists yet. dma_overhead_s is the
    # modeled per-copy issue+sync cost on TPU (the whole point of the
    # method: a fraction of a ppermute's ~0.66 ms dispatch);
    # cpu_emulation_overhead_s prices the CPU lowering honestly — each
    # emulated copy is a host-orchestrated device_put round-trip, so on
    # a cpu-platform config REMOTE_DMA ranks BELOW the ppermute methods
    # (the probes confirm; on tpu configs the model lets it compete).
    "remote_dma": {
        "dma_overhead_s": 8.0e-5,
        "cpu_emulation_overhead_s": 4.0e-3,
        "wire_bytes_per_s": 3.9e8,
        "provenance": "modeled, pending item-1 TPU recalibration",
    },
    # The fused compute+exchange mega-kernel (kernel_variant == "fused"
    # on a REMOTE_DMA choice): the substep's wall-clock is
    # max(interior_compute, dma) + boundary_compute — wire time hides
    # behind interior FLOPs. Scored against candidates whose totals omit
    # the (common) sweep compute, the fused EXCHANGE-attributable cost is
    # that expression minus the full sweep: per-copy issue overhead plus
    # only the UNHIDDEN wire time, max(0, dma - interior_compute).
    # Provenance: MODELED, pending the item-1 TPU session — no silicon
    # measurement of the overlap exists yet; probe_remote_dma.py's fused
    # leg is the measurement that flips this to measured.
    "fused": {
        "provenance": "modeled, pending item-1 TPU recalibration",
    },
    # The persistent whole-chunk mega-kernel (kernel_variant ==
    # "persistent" on a REMOTE_DMA choice, multistep_k >= 2): one kernel
    # launch executes the whole k-step chunk behind a single deep-halo
    # (radius*k) exchange, so the chunk pays 2 program launches instead
    # of the per-step lowering's 2k (plan/ir.ExchangePlan.
    # launches_per_chunk — the same figure the launch census pins). The
    # per-launch constants below price that saving: launch_overhead_s is
    # the modeled TPU kernel-dispatch floor; cpu_dispatch_s is the
    # host-orchestrated emulation's jit-call round-trip, priced honestly
    # so persistent never wins a cpu ranking on a TPU-modeled constant.
    # The redundant-compute side of the trade is the shared k>1
    # shrinking-shell term below (cell_update_s). Provenance: MODELED,
    # pending the item-1 TPU session — scripts/probe_persistent.py is the
    # measurement that flips this to measured.
    "persistent": {
        "launch_overhead_s": 5.0e-6,
        "cpu_dispatch_s": 2.0e-4,
        "provenance": "modeled, pending item-1 TPU recalibration",
    },
    # The outer (cross-host DCN) level of a hierarchical plan: boundary
    # slabs leaving the per-host ICI mesh pay a per-transfer latency and
    # a bandwidth FAR below the ICI's — the defining economics of the
    # hierarchy (the whole point of hiding DCN wire behind intra-host
    # work). transfer_latency_s is the modeled per-copy DCN issue+rtt
    # floor on a pod; cpu_emulation_overhead_s prices the virtual-host
    # emulation honestly (each emulated DCN copy is a host-orchestrated
    # device_put round-trip, like remote_dma's). Provenance: MODELED —
    # no DCN measurement exists in this repo yet; scripts/probe_dcn.py
    # is staged for the item-1 hardware session that flips this row to
    # measured.
    "dcn": {
        "transfer_latency_s": 1.0e-3,
        "wire_bytes_per_s": 2.5e7,
        "cpu_emulation_overhead_s": 4.0e-3,
        "provenance": "modeled, pending item-1 hardware "
                      "(scripts/probe_dcn.py)",
    },
}


@dataclass(frozen=True)
class PlanCost:
    """Static score of one candidate, per simulation step."""

    total_s: float          # the ranking key
    exchange_s: float       # one exchange's predicted wall-clock
    collectives: int        # permutes per exchange (census-comparable)
    wire_bytes: int         # estimated interconnect bytes per exchange
    local_bytes: int        # estimated local slab bytes per exchange
    compute_overhead_s: float  # multistep redundant-compute price per step
    dmas: int = 0           # kernel-initiated async copies (REMOTE_DMA only)
    dcn_transfers: int = 0  # cross-host copies (hierarchical plans only)
    dcn_wire_bytes: int = 0  # bytes crossing the DCN per exchange

    def to_json(self) -> dict:
        return {
            "total_s": self.total_s,
            "exchange_s": self.exchange_s,
            "collectives": self.collectives,
            "wire_bytes": self.wire_bytes,
            "local_bytes": self.local_bytes,
            "compute_overhead_s": self.compute_overhead_s,
            "dmas": self.dmas,
            "dcn_transfers": self.dcn_transfers,
            "dcn_wire_bytes": self.dcn_wire_bytes,
        }


def scale_radius(radius: Radius, k: int) -> Radius:
    """The radius a temporal-depth-k multistep realizes: every direction's
    halo (and diagonal gate) scaled by k, so one exchange feeds k steps."""
    if k == 1:
        return radius
    out = Radius.constant(0)
    for d, r in radius._r.items():
        out.set_dir(d, r * k)
    return out


# -- topology-aware placement (the reference's NodeAware/qap::solve leg) ------
#
# The reference's L3 places blocks by measured inter-GPU bandwidth: a QAP
# over (communication volume x link distance) decides which physical
# device hosts which subdomain (qap.hpp, partition.hpp:525-831). Here the
# same leg is a PlanChoice dimension: the wire-volume matrix between MESH
# positions falls out of the same halo_extent geometry the ExchangePlan
# IR's wire_bytes model prices, the link-cost matrix comes from the
# device objects (parallel/topology.link_cost_matrix — ICI hop distance
# on TPU, process-boundary penalty elsewhere), and the product prices a
# placement relative to identity.


def placement_wire_matrix(spec: GridSpec, mesh_dim,
                          per_cell_bytes: int = 1):
    """Pairwise wire-volume matrix between MESH positions (row-major
    z, y, x — the same linearization the placement assignment uses).

    Built from the exact halo_extent geometry the IR's ``wire_cells``
    model prices: every active direction's halo slab of every block,
    attributed to the (sender-slot, receiver-slot) pair, with self-wrap
    and resident-internal (same-device) traffic excluded — those never
    touch the interconnect, so a placement cannot change their cost
    (the reference's comm matrix, partition.hpp:722-752, aggregated to
    device granularity). Pure geometry, jax-free."""
    import numpy as np

    md = Dim3.of(mesh_dim)
    if spec.dim.x % md.x or spec.dim.y % md.y or spec.dim.z % md.z:
        raise ValueError(f"mesh {md} does not divide partition {spec.dim}")
    c = Dim3(spec.dim.x // md.x, spec.dim.y // md.y, spec.dim.z // md.z)
    n = md.flatten()
    m = np.zeros((n, n), dtype=np.float64)

    def slot(b: Dim3) -> int:
        return (b.x // c.x) + (b.y // c.y) * md.x + (b.z // c.z) * md.x * md.y

    for iz in range(spec.dim.z):
        for iy in range(spec.dim.y):
            for ix in range(spec.dim.x):
                src = Dim3(ix, iy, iz)
                sz = spec.block_size(src)
                for d in DIRECTIONS_26:
                    # send-extent rule: data toward d fills the receiver's
                    # -d halo, active iff radius.dir(-d) != 0
                    if spec.radius.dir(-d) == 0:
                        continue
                    dst = (src + d).wrap(spec.dim)
                    if dst == src:
                        continue  # self-wrap: no inter-device traffic
                    ss, ds = slot(src), slot(dst)
                    if ss == ds:
                        continue  # resident neighbors: local shifts
                    m[ss, ds] += (halo_extent(-d, sz, spec.radius).flatten()
                                  * per_cell_bytes)
    return m


# rank() scores every (method x batching x k x variant) candidate of a
# partition, and each placed one needs the SAME wire matrix — a pure-
# Python O(blocks x 26) halo_extent sweep that must not be rebuilt per
# candidate (nor per between-chunk replan retune). Bounded: the key
# space is tiny (partitions of one tuning pass) but a long-lived service
# retuning many configs must not grow without bound.
_WIRE_MATRIX_CACHE: Dict[Tuple, object] = {}
_WIRE_MATRIX_CACHE_MAX = 128


def _cached_wire_matrix(spec: GridSpec, mesh_dim, config: PlanConfig,
                        multistep_k: int):
    key = (config.grid, config.radius, int(multistep_k),
           (spec.dim.x, spec.dim.y, spec.dim.z),
           (mesh_dim.x, mesh_dim.y, mesh_dim.z))
    w = _WIRE_MATRIX_CACHE.get(key)
    if w is None:
        if len(_WIRE_MATRIX_CACHE) >= _WIRE_MATRIX_CACHE_MAX:
            _WIRE_MATRIX_CACHE.clear()
        w = _WIRE_MATRIX_CACHE[key] = placement_wire_matrix(spec, mesh_dim)
    return w


def placement_cost(w, link_costs, placement=None) -> float:
    """Assignment cost ``sum_ab w[a,b] * link[f[a],f[b]]`` with the
    reference's ``0 * inf == 0`` rule (qap.hpp cost_product) — pinned
    equal to ``parallel.qap.cost`` by tests/test_plan_placement.py but
    implemented here so the jax-free cost model never imports the
    parallel package. ``placement=None`` is the identity assignment."""
    import numpy as np

    w = np.asarray(w, dtype=np.float64)
    d = np.asarray(link_costs, dtype=np.float64)
    n = w.shape[0]
    f = np.arange(n) if placement is None else np.asarray(placement,
                                                          dtype=np.intp)
    dperm = d[np.ix_(f, f)]
    prod = w * dperm
    prod[(w == 0) | (dperm == 0)] = 0.0
    return float(prod.sum())


def uniform_link_costs(link_costs) -> bool:
    """True when every off-diagonal link costs the same — placement is
    then cost-neutral and the QAP search is skipped (identity optimal)."""
    import numpy as np

    d = np.asarray(link_costs, dtype=np.float64)
    n = d.shape[0]
    if n < 2:
        return True
    off = d[~np.eye(n, dtype=bool)]
    return bool(np.all(off == off[0]))


# Exhaustive-search size limit for the placement QAP: at n <= 6 the full
# 720-permutation sweep completes in milliseconds even in pure Python, so
# the answer is deterministic and budget-independent; beyond it the
# greedy best-pairwise-swap descent (qap.hpp:87-180) runs instead — a
# timed-out partial exhaustive search would make the tuned plan depend on
# host load, which a persisted DB entry must never do.
PLACEMENT_EXACT_LIMIT = 6


def solve_placement(w, link_costs,
                    exact_limit: int = PLACEMENT_EXACT_LIMIT,
                    timeout_s: float = 10.0) -> Optional[Tuple[int, ...]]:
    """The QAP-optimal placement for (wire volumes, link costs), or None
    when identity is already (modeled) optimal — uniform links included.
    Dispatches to ``parallel.qap``: exhaustive ``solve`` at small n,
    greedy ``solve_catch`` beyond (see :data:`PLACEMENT_EXACT_LIMIT`).
    Imported lazily — the solvers are numpy-only but live in the
    parallel package; static-only callers that never search placements
    (plan_tool explain) stay jax-free."""
    import numpy as np

    if uniform_link_costs(link_costs):
        return None
    from ..parallel import qap

    w = np.asarray(w, dtype=np.float64)
    d = np.asarray(link_costs, dtype=np.float64)
    n = w.shape[0]
    if n <= exact_limit:
        f, cost = qap.solve(w, d, timeout_s=timeout_s)
    else:
        f, cost = qap.solve_catch(w, d)
    identity = placement_cost(w, d)
    if f == list(range(n)) or cost >= identity:
        return None  # identity is optimal (or the solver found nothing better)
    return tuple(f)


# -- two-level (hierarchical) placement: blocks->hosts, then blocks->chips ----
#
# The reference's NodeAware places at two granularities: subdomains to
# NODES by the rank-boundary-penalized comm matrix, then to GPUs within
# each node (partition.hpp:525-831). Here the outer level aggregates the
# mesh-slot wire matrix to host slots (the hierarchy's contiguous
# DCN-axis segments) and prices it against the host-to-host link matrix
# (mean cross-group device distance — 7x on the process/virtual-host
# ladder); the inner level re-runs the same QAP per host over the
# intra-host sub-matrices. The composed flat device permutation is what
# PlanChoice.placement carries, so realize() applies it through the
# existing single-level machinery unchanged.


def hierarchy_slot_hosts(mesh_dim, hierarchy) -> List[int]:
    """Host slot of each mesh position (row-major x-fastest slot order,
    matching :func:`placement_wire_matrix`): a position's host slot is
    its DCN-axis coordinate divided by the segment length."""
    axis, hosts = str(hierarchy[0]), int(hierarchy[1])
    md = Dim3.of(mesh_dim)
    n_ax = {"x": md.x, "y": md.y, "z": md.z}[axis]
    if n_ax % hosts:
        raise ValueError(
            f"{hosts} hosts do not divide the {axis} mesh extent {n_ax}")
    seg = n_ax // hosts
    out = []
    for z in range(md.z):
        for y in range(md.y):
            for x in range(md.x):
                c = {"x": x, "y": y, "z": z}[axis]
                out.append(c // seg)
    return out


def host_wire_matrix(w, mesh_dim, hierarchy):
    """The outer QAP's H x H wire matrix: every cross-host-slot entry of
    the mesh-slot wire matrix aggregated to its (sender host slot,
    receiver host slot) pair; intra-host wire is excluded — it rides the
    ICI whichever host serves the slot, so the outer assignment cannot
    change its cost."""
    import numpy as np

    sh = hierarchy_slot_hosts(mesh_dim, hierarchy)
    hosts = int(hierarchy[1])
    w = np.asarray(w, dtype=np.float64)
    out = np.zeros((hosts, hosts), dtype=np.float64)
    for a in range(w.shape[0]):
        for b in range(w.shape[1]):
            if sh[a] != sh[b]:
                out[sh[a], sh[b]] += w[a, b]
    return out


def host_link_matrix(link_costs, hosts: int, host_map=None):
    """The outer QAP's H x H link-cost matrix: mean pairwise device
    distance between host groups (0 diagonal). ``host_map`` gives each
    device index's host; omitted, the contiguous equal split of the
    device list is assumed — the id-sorted layout both the virtual-host
    fabric (device_topo.host_assignment) and a process-contiguous
    ``jax.devices()`` produce."""
    import numpy as np

    d = np.asarray(link_costs, dtype=np.float64)
    n = d.shape[0]
    if n % hosts:
        raise ValueError(f"{hosts} hosts do not divide {n} devices")
    if host_map is None:
        g = n // hosts
        host_map = [i // g for i in range(n)]
    idx = {h: [i for i in range(n) if host_map[i] == h]
           for h in range(hosts)}
    out = np.zeros((hosts, hosts), dtype=np.float64)
    for p in range(hosts):
        for q in range(hosts):
            if p == q or not idx[p] or not idx[q]:
                continue
            out[p, q] = float(np.mean(
                [d[i, j] for i in idx[p] for j in idx[q]]))
    return out


def solve_two_level_placement(w, link_costs, mesh_dim, hierarchy,
                              host_map=None):
    """The hierarchical ``NodeAware``: ``(host_placement, placement)``.

    Outer: blocks->hosts over (:func:`host_wire_matrix`,
    :func:`host_link_matrix`) — ``host_placement[s]`` is the host group
    serving host slot s (None = identity, which a uniform fabric solves
    to by design). Inner: blocks->chips per host slot, the same QAP over
    the intra-host sub-matrices. ``placement`` is the composed flat
    device permutation (None when the composition is identity) — the
    form realize() already applies. ``host_map`` as in
    :func:`host_link_matrix`; a scrambled map (devices interleaved
    across hosts) makes even the identity outer assignment compose to a
    non-identity flat permutation, because each host slot's positions
    must land on ITS host's devices — the alignment the hierarchy's
    lowering requires."""
    import numpy as np

    hosts = int(hierarchy[1])
    w = np.asarray(w, dtype=np.float64)
    d = np.asarray(link_costs, dtype=np.float64)
    n = w.shape[0]
    if n % hosts:
        return None, None
    g = n // hosts
    if host_map is None:
        host_map = [i // g for i in range(n)]
    groups = {h: [i for i in range(n) if host_map[i] == h]
              for h in sorted(set(host_map))}
    if len(groups) != hosts or any(len(v) != g for v in groups.values()):
        return None, None  # uneven or mis-counted fabric: no hierarchy
    order = sorted(groups)
    sh = hierarchy_slot_hosts(mesh_dim, hierarchy)
    wh = host_wire_matrix(w, mesh_dim, hierarchy)
    dh = host_link_matrix(link_costs, hosts,
                          host_map=[order.index(h) for h in host_map])
    outer = solve_placement(wh, dh)
    hp = list(outer) if outer is not None else list(range(hosts))
    placement = [0] * n
    for hs in range(hosts):
        slots = [s for s in range(n) if sh[s] == hs]
        devs = groups[order[hp[hs]]]
        wsub = w[np.ix_(slots, slots)]
        dsub = d[np.ix_(devs, devs)]
        f = solve_placement(wsub, dsub)
        fl = list(f) if f is not None else list(range(len(slots)))
        for r, s in enumerate(slots):
            placement[s] = devs[fl[r]]
    host_placement = tuple(hp) if outer is not None else None
    if placement == list(range(n)):
        return host_placement, None
    return host_placement, tuple(placement)


def feasible(config: PlanConfig, choice: PlanChoice) -> Optional[Tuple]:
    """(spec, mesh_dim, resident) when the candidate can realize on this
    config, else None. Mirrors realize()'s constraints exactly: the
    partition's block count must be a multiple of ndev (residents stacked
    by the same z-heavy factorization), and no block may be thinner than
    the effective radius — for a multistep choice that radius is
    ``radius * k``, so a deep-halo depth whose staging would exceed a
    block's interior extent (a negative valid strip) is refused HERE,
    before any kernel is planned. The fused compute+exchange variant is
    a REMOTE_DMA-only, single-resident, k == 1 lowering; the persistent
    whole-chunk variant is REMOTE_DMA-only, single-resident, k >= 2 —
    any other combination is infeasible here (the loud-infeasibility
    contract: realize() raises the same constraints). A ``placement`` must be a permutation of the
    config's ``ndev`` mesh positions (plan/ir.validate_placement — the
    same check realize() raises on)."""
    if validate_placement(choice.placement, config.ndev) is not None:
        return None
    if choice.kernel_variant == FUSED_VARIANT:
        if choice.method != REMOTE_DMA:
            return None
        if choice.multistep_k != 1:
            # the fused lowering runs ONE fused exchange per step and
            # ignores temporal_k (ops/jacobi._compile_jacobi_fused warns
            # and proceeds per-step) — scoring k>1 would amortize an
            # exchange the realized program pays every step
            return None
    if choice.kernel_variant == PERSISTENT_VARIANT:
        if choice.method != REMOTE_DMA:
            return None
        if choice.multistep_k < 2:
            # persistent IS communication-avoiding temporal fusion: the
            # chunk depth is multistep_k, and at k == 1 the whole-chunk
            # kernel degenerates to the fused per-step kernel — scoring
            # it would duplicate that point under a second label
            return None
    dim = Dim3.of(choice.partition)
    g = Dim3.of(config.grid)
    if g.x < dim.x or g.y < dim.y or g.z < dim.z:
        return None
    nb = dim.flatten()
    if nb % config.ndev:
        return None
    radius = scale_radius(config.radius_obj(), choice.multistep_k)
    try:
        spec = GridSpec(g, dim, radius)
    except (AssertionError, ValueError):
        return None
    c = nb // config.ndev
    if c == 1:
        mesh_dim = dim
    else:
        try:
            mesh_dim = stack_residents(dim, c)
        except ValueError:
            return None
    for sizes, rm, rp in (
        (spec.sizes_x, radius.x(-1), radius.x(1)),
        (spec.sizes_y, radius.y(-1), radius.y(1)),
        (spec.sizes_z, radius.z(-1), radius.z(1)),
    ):
        if min(sizes) < max(rm, rp):
            return None  # halo would span multiple blocks
    resident = Dim3(dim.x // mesh_dim.x, dim.y // mesh_dim.y,
                    dim.z // mesh_dim.z)
    if choice.kernel_variant == FUSED_VARIANT and resident != Dim3(1, 1, 1):
        return None  # the fused kernel is single-resident (build_plan raises)
    if (choice.kernel_variant == PERSISTENT_VARIANT
            and resident != Dim3(1, 1, 1)):
        return None  # the persistent kernel is single-resident too
    if choice.hierarchy is not None:
        # the hierarchy's inner program is composed-geometry only
        # (build_plan rejects direct26/auto-spmd loudly; here the
        # candidate is just infeasible), the hosts must divide the
        # DCN-axis mesh extent, and a host_placement must permute the
        # hierarchy's host slots
        if choice.method not in (AXIS_COMPOSED, REMOTE_DMA):
            return None
        if validate_hierarchy(choice.hierarchy, mesh_dim) is not None:
            return None
        if (choice.host_placement is not None
                and validate_placement(choice.host_placement,
                                       int(choice.hierarchy[1])) is not None):
            return None
    elif choice.host_placement is not None:
        return None  # a host placement without a hierarchy is meaningless
    return spec, mesh_dim, resident


def score(config: PlanConfig, choice: PlanChoice,
          calibration: Optional[dict] = None,
          link_costs=None) -> Optional[PlanCost]:
    """Static per-step cost of one candidate (None when infeasible).

    The score is a function of the dtype MULTISET only — a config whose
    quantity list is a permutation of another's scores identically, so
    the ranking is invariant under quantity-dtype permutation
    (tests/test_plan_cost.py pins this).

    ``link_costs`` (an ndev x ndev per-device-pair distance matrix —
    parallel/topology.link_cost_matrix) prices the choice's block
    placement: the wire term scales by the QAP cost ratio
    ``placement_cost(w, link, f) / placement_cost(w, link, identity)``,
    so on a mesh with non-uniform links a topology-matched placement
    scores strictly cheaper than identity while the calibrated
    ``wire_bytes_per_s`` keeps its identity-baseline meaning. Without
    link costs every placement prices identically and the deterministic
    label tie-break keeps identity first."""
    cal = dict(DEFAULT_CALIBRATION)
    for k, v in (calibration or {}).items():
        # dict-valued keys (per-method overheads, variant factors) merge
        # per entry so a partial override falls back to the defaults for
        # every method it does not mention
        if isinstance(v, dict) and isinstance(cal.get(k), dict):
            cal[k] = {**cal[k], **v}
        else:
            cal[k] = v
    feas = feasible(config, choice)
    if feas is None:
        return None
    spec, mesh_dim, resident = feas
    fused = choice.kernel_variant == FUSED_VARIANT
    persistent = choice.kernel_variant == PERSISTENT_VARIANT
    plan = build_plan(spec, mesh_dim, choice.method,
                      batch_quantities=choice.batch_quantities,
                      resident=resident, fused=fused,
                      persistent=persistent, hierarchy=choice.hierarchy)
    itemsizes = config.itemsizes()
    nq = config.num_quantities
    ngroups = config.dtype_group_count
    collectives = plan.collectives_per_exchange(nq, ngroups)
    wire = plan.wire_bytes(itemsizes, floating=config.floating_flags())
    local = plan.local_bytes(itemsizes)
    dmas = plan.dmas_per_exchange(nq, ngroups)
    # placement pricing: wire time scales by the QAP cost ratio vs the
    # identity assignment (1.0 when no link costs are known, when the
    # links are uniform, or when nothing crosses the wire)
    pratio = 1.0
    if link_costs is not None and choice.placement is not None and wire:
        w = _cached_wire_matrix(spec, mesh_dim, config, choice.multistep_k)
        base = placement_cost(w, link_costs)
        if base > 0:
            pratio = placement_cost(w, link_costs, choice.placement) / base
    # REMOTE_DMA-family launch economics: the per-step lowering pays 2
    # program launches per substep (exchange + sweep), the persistent
    # whole-chunk kernel pays 2 per CHUNK — plan.launches_per_chunk(k)
    # is that prediction (the launch census audits it), and the
    # per-launch constant is platform-split like the per-copy one.
    # The permute methods compile the chunk into one XLA program whose
    # dispatch cost is already inside their measured permute constants,
    # so no launch term applies there (launches_per_chunk == 1).
    launch_s = 0.0
    if choice.method == REMOTE_DMA:
        ps = cal["persistent"]
        per_launch = (ps["launch_overhead_s"] if config.platform == "tpu"
                      else ps["cpu_dispatch_s"])
        launch_s = plan.launches_per_chunk(choice.multistep_k) * per_launch
    if fused:
        # overlap-aware: the fused substep runs
        #   max(interior_compute, dma) + boundary_compute
        # — wire time hides behind interior FLOPs. Candidates' totals
        # omit the common full-sweep compute, so the fused cost charged
        # here is that expression minus (interior + boundary): the
        # per-copy issue overhead plus only the UNHIDDEN wire time.
        # Per-copy overhead stays platform-split like plain REMOTE_DMA
        # (the CPU schedule is host-orchestrated and must never win a
        # cpu ranking on a TPU-modeled constant); provenance of all of
        # it is cal["fused"]["provenance"] — MODELED until item 1's
        # TPU session runs probe_remote_dma.py's fused leg.
        rd = cal["remote_dma"]
        per_dma = (rd["dma_overhead_s"] if config.platform == "tpu"
                   else rd["cpu_emulation_overhead_s"])
        wire_s = (wire / rd.get("wire_bytes_per_s", cal["wire_bytes_per_s"])
                  * pratio)
        b = spec.base
        r0 = config.radius_obj()
        shrink = [
            (rm + rp) if n > 1 else 0
            for n, rm, rp in (
                (mesh_dim.x, r0.x(-1), r0.x(1)),
                (mesh_dim.y, r0.y(-1), r0.y(1)),
                (mesh_dim.z, r0.z(-1), r0.z(1)),
            )
        ]
        interior_cells = (max(0, b.x - shrink[0]) * max(0, b.y - shrink[1])
                          * max(0, b.z - shrink[2]))
        interior_s = interior_cells * nq * cal["cell_update_s"]
        exchange_s = (
            dmas * per_dma
            + max(0.0, wire_s - interior_s)
            + local / cal["local_bytes_per_s"]
            + launch_s
        )
    elif choice.method == REMOTE_DMA:
        # kernel-initiated copies: no ppermute dispatch at all; the
        # per-copy cost is platform-dependent (the CPU lowering is a
        # host-orchestrated emulation and must never win a cpu ranking
        # on the strength of a TPU-modeled constant)
        rd = cal["remote_dma"]
        per_dma = (rd["dma_overhead_s"] if config.platform == "tpu"
                   else rd["cpu_emulation_overhead_s"])
        # the persistent whole-chunk variant shares this branch: its wire
        # model IS the deep-halo composed slab program (same dmas, same
        # bytes), and its whole advantage is the launch term — 2 per
        # chunk instead of 2k — plus the /k exchange amortization below;
        # its price is the shared k>1 redundant-compute term
        exchange_s = (
            dmas * per_dma
            + (wire / rd.get("wire_bytes_per_s", cal["wire_bytes_per_s"])
               * pratio)
            + local / cal["local_bytes_per_s"]
            + launch_s
        )
    else:
        overhead = cal["permute_overhead_s"][choice.method]
        exchange_s = (
            collectives * overhead
            + wire / cal["wire_bytes_per_s"] * pratio
            + local / cal["local_bytes_per_s"]
        )
    # the outer (DCN) level of a hierarchical plan: boundary slabs cross
    # hosts on their own calibration row (latency + bandwidth >> ICI).
    # With the composed inner program the hierarchy's lowering schedules
    # the DCN copies boundary-first and runs the intra-host phases while
    # they fly — the overlap credit prices the exchange at
    # max(inner, outer); the sequential schedule (REMOTE_DMA-family
    # inner, whose program is an opaque host-orchestrated loop) pays the
    # sum. A host_placement scales the DCN byte term by its outer QAP
    # cost ratio, mirroring the inner pratio.
    dcn_transfers = plan.dcn_transfers_per_exchange(nq, ngroups)
    dcn_bytes = plan.dcn_wire_bytes(itemsizes,
                                    floating=config.floating_flags())
    if dcn_transfers:
        dc = cal["dcn"]
        per_transfer = (dc["transfer_latency_s"] if config.platform == "tpu"
                        else dc["cpu_emulation_overhead_s"])
        hratio = 1.0
        if (link_costs is not None and choice.host_placement is not None
                and dcn_bytes):
            w = _cached_wire_matrix(spec, mesh_dim, config,
                                    choice.multistep_k)
            wh = host_wire_matrix(w, mesh_dim, choice.hierarchy)
            dh = host_link_matrix(link_costs, int(choice.hierarchy[1]))
            base = placement_cost(wh, dh)
            if base > 0:
                hratio = placement_cost(wh, dh,
                                        choice.host_placement) / base
        outer_s = (dcn_transfers * per_transfer
                   + dcn_bytes / dc["wire_bytes_per_s"] * hratio)
        if choice.method == AXIS_COMPOSED:
            exchange_s = max(exchange_s, outer_s)
        else:
            exchange_s += outer_s
    k = choice.multistep_k
    compute_overhead_s = 0.0
    if k > 1:
        # deep halos trade collective count for redundant edge compute:
        # each of the k-1 interior steps re-updates a shrinking halo
        # shell; the average extra shell is ~ (k-1)/2 radius-deep over
        # every block face
        b = spec.base
        r0 = config.radius_obj()
        rbar = (r0.x(-1) + r0.x(1) + r0.y(-1) + r0.y(1)
                + r0.z(-1) + r0.z(1)) / 6.0
        surface = 2 * (b.x * b.y + b.x * b.z + b.y * b.z) * spec.num_blocks()
        extra_cells = surface * rbar * (k - 1) / 2.0
        compute_overhead_s = extra_cells * nq * cal["cell_update_s"]
    vf = cal["variant_factor"].get(choice.kernel_variant, 1.0)
    total = exchange_s / k + compute_overhead_s * vf
    return PlanCost(
        total_s=total, exchange_s=exchange_s, collectives=collectives,
        wire_bytes=wire, local_bytes=local,
        compute_overhead_s=compute_overhead_s, dmas=dmas,
        dcn_transfers=dcn_transfers, dcn_wire_bytes=dcn_bytes,
    )


def candidate_partitions(config: PlanConfig,
                         oversubscribe: Sequence[int] = (1,)) -> List[Tuple[int, int, int]]:
    """All (px, py, pz) block grids with ndev * c blocks (c in
    ``oversubscribe``), unfiltered for radius feasibility (score() is the
    gate). Ordered deterministically."""
    out = []
    for c in oversubscribe:
        n = config.ndev * c
        for px in range(1, n + 1):
            if n % px:
                continue
            nyz = n // px
            for py in range(1, nyz + 1):
                if nyz % py:
                    continue
                out.append((px, py, nyz // py))
    return out


# The default kernel-variant set, as an identity-comparable sentinel:
# enumerate_candidates() grows it with REMOTE_DMA's fused and persistent
# variants, while any EXPLICITLY passed variant list — (None,) included —
# is honored verbatim (plan_tool --variants none tunes plain remote-dma
# only).
DEFAULT_VARIANTS: Tuple[Optional[str], ...] = (None,)


def enumerate_candidates(
    config: PlanConfig,
    methods: Iterable[str] = METHODS,
    batch_options: Iterable[bool] = (True, False),
    ks: Iterable[int] = (1,),
    variants: Iterable[Optional[str]] = DEFAULT_VARIANTS,
    oversubscribe: Sequence[int] = (1,),
    link_costs=None,
    hierarchy_hosts: Optional[int] = None,
    host_map: Optional[Sequence[int]] = None,
) -> List[PlanChoice]:
    """The search space: partition shape x method x quantity batching x
    temporal depth k x kernel variant x block placement. Batching only
    branches when the config has more than one quantity (at Q=1 the two
    programs are identical — PR 5's degeneration contract). With the
    DEFAULT variant set, REMOTE_DMA additionally branches on the fused
    compute+exchange variant (kernel_variant == "fused") and — whenever
    ``ks`` reaches depth 2 — the persistent whole-chunk variant
    (kernel_variant == "persistent") so the autotuner searches both the
    overlap and the temporal-fusion levers out of the box; an EXPLICIT
    ``variants`` restriction — ``(None,)`` included — is honored
    verbatim (the sentinel comparison is by identity with
    :data:`DEFAULT_VARIANTS`). Infeasible variant points (oversubscribed
    partitions, fused at k > 1, persistent at k < 2) fall out at score()
    like every other constraint.

    With ``link_costs`` (non-uniform), every single-resident partition
    additionally branches on its QAP-solved placement
    (:func:`solve_placement` over :func:`placement_wire_matrix` — one
    placed candidate beside identity, never the factorial permutation
    space; the reference's NodeAware does exactly this). Uniform links
    solve to identity and add nothing, so the CPU-mesh search space is
    byte-identical to the pre-placement one.

    With ``hierarchy_hosts`` > 1 (the fabric has host structure — real
    processes or the STENCIL_VIRTUAL_HOSTS emulation), every partition
    additionally branches on the hierarchical decomposition: for each
    mesh axis the host count divides, an ``(axis, hosts)`` outer split
    beside the flat plan — so the search prices outer-axis choice x
    inner partition JOINTLY — carrying the two-level QAP's
    ``host_placement`` and composed ``placement``
    (:func:`solve_two_level_placement`; ``host_map`` names each device
    index's host for the link aggregation, contiguous split when
    omitted). Composed-geometry inner methods only (the hierarchy has
    no direct26/auto-spmd lowering)."""
    if config.num_quantities <= 1:
        batch_options = (True,)
    default_variants = variants is DEFAULT_VARIANTS
    ks = tuple(ks)  # consumed once per method below, plus the k>=2 probe
    feas_by_part: Dict[Tuple[int, int, int], Optional[Tuple]] = {}
    placements_by_part: Dict[Tuple[int, int, int],
                             Optional[Tuple[int, ...]]] = {}

    def part_feas(part) -> Optional[Tuple]:
        if part not in feas_by_part:
            feas_by_part[part] = feasible(
                config, PlanChoice(partition=part, method=AXIS_COMPOSED))
        return feas_by_part[part]

    def placed_for(part) -> Optional[Tuple[int, ...]]:
        if link_costs is None:
            return None
        if part not in placements_by_part:
            placements_by_part[part] = None
            feas = part_feas(part)
            if feas is not None:
                spec, mesh_dim, resident = feas
                if resident == Dim3(1, 1, 1):
                    # single-resident only: the placement permutes mesh
                    # positions, and probing an oversubscribed placed
                    # mesh is a follow-up (the search default does not
                    # oversubscribe anyway)
                    w = _cached_wire_matrix(spec, mesh_dim, config, 1)
                    placements_by_part[part] = solve_placement(w, link_costs)
        return placements_by_part[part]

    def variant_list(method) -> List[Optional[str]]:
        vlist = list(variants)
        if method == REMOTE_DMA and default_variants:
            if FUSED_VARIANT not in vlist:
                vlist.append(FUSED_VARIANT)
            if (PERSISTENT_VARIANT not in vlist
                    and any(k >= 2 for k in ks)):
                vlist.append(PERSISTENT_VARIANT)
        return vlist

    out = []
    for part in candidate_partitions(config, oversubscribe):
        placements: Tuple[Optional[Tuple[int, ...]], ...] = (None,)
        placed = placed_for(part)
        if placed is not None:
            placements = (None, placed)
        for method in methods:
            vlist = variant_list(method)
            for batch in batch_options:
                for k in ks:
                    for variant in vlist:
                        for placement in placements:
                            out.append(PlanChoice(
                                partition=part, method=method,
                                batch_quantities=batch, multistep_k=k,
                                kernel_variant=variant,
                                placement=placement,
                            ))
        if not hierarchy_hosts or hierarchy_hosts <= 1:
            continue
        feas = part_feas(part)
        if feas is None:
            continue
        spec, mesh_dim, resident = feas
        mdm = {"x": mesh_dim.x, "y": mesh_dim.y, "z": mesh_dim.z}
        for axis in ("x", "y", "z"):
            if mdm[axis] % hierarchy_hosts:
                continue
            hier = (axis, int(hierarchy_hosts))
            hp: Optional[Tuple[int, ...]] = None
            hpl: Optional[Tuple[int, ...]] = None
            if link_costs is not None and resident == Dim3(1, 1, 1):
                w = _cached_wire_matrix(spec, mesh_dim, config, 1)
                hp, hpl = solve_two_level_placement(
                    w, link_costs, mesh_dim, hier, host_map=host_map)
            for method in methods:
                if method not in (AXIS_COMPOSED, REMOTE_DMA):
                    continue
                for batch in batch_options:
                    for k in ks:
                        for variant in variant_list(method):
                            out.append(PlanChoice(
                                partition=part, method=method,
                                batch_quantities=batch, multistep_k=k,
                                kernel_variant=variant,
                                placement=hpl, hierarchy=hier,
                                host_placement=hp,
                            ))
    return out


def rank(config: PlanConfig, candidates: Iterable[PlanChoice],
         calibration: Optional[dict] = None,
         link_costs=None) -> List[Tuple[PlanCost, PlanChoice]]:
    """Feasible candidates sorted cheapest-first. Ties break on the
    choice label so the order is total and deterministic (the
    permutation-invariance property needs a stable ranking; an identity
    placement's label is a strict prefix of its placed sibling's, so
    identity wins exact ties — placement must EARN its slot)."""
    scored = []
    for choice in candidates:
        c = score(config, choice, calibration, link_costs=link_costs)
        if c is not None:
            scored.append((c, choice))
    scored.sort(key=lambda t: (t[0].total_s, t[1].label()))
    return scored
