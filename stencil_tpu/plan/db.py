"""On-disk plan DB: tuned exchange plans keyed by canonical config.

The serving-stack analogue of an inference engine's tuned-config cache:
``autotune`` persists each winning :class:`~stencil_tpu.plan.ir.PlanChoice`
under its :class:`~stencil_tpu.plan.ir.PlanConfig` key, so production
runs replay plans with ZERO probe runs (the ``plan.cache_hit`` gauge is
the proof; scripts/ci_plan_gate.py pins it).

Format: one JSON file, schema v1, validated like the metrics JSONL
(one schema authority, :func:`validate_db`):

    {"v": 1, "kind": "stencil-plan-db",
     "entries": {"<canonical config key>": {
        "config":   {...PlanConfig.to_json()...},
        "choice":   {...PlanChoice.to_json()...},
        "source":   "probe" | "static" | "seed" | "legacy",
        "static_cost_s": float | null,
        "measured_s":    float | null,     # per-exchange trimean (probe/seed)
        "probes":   [{"label": ..., "trimean_s": ...}, ...],
        "written_t": float,
        "note":     str | null}},
     "calibrations": {"<platform>": {        # optional; absent = modeled
        "calibration": {...score() override...},
        "provenance": "fitted(n=…, r2=…)", "n": int, "r2": float, ...}}}

Discipline mirrors ckpt/snapshot.py: writes are tmp + fsync + atomic
rename (a crash never leaves a torn DB), corrupt or future-versioned
files are REJECTED (:class:`PlanDBError`) rather than silently emptied,
and the known legacy layout (v0: a flat ``{key: choice}`` mapping from
the pre-schema prototype) is migrated forward on load.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

from .ir import METHODS, PlanChoice, PlanConfig, validate_placement

DB_VERSION = 1
DB_KIND = "stencil-plan-db"
SOURCES = ("probe", "static", "seed", "legacy")
_TMP_PREFIX = ".tmp-"


class PlanDBError(ValueError):
    """Corrupt, unparseable, or future-versioned plan DB."""


def empty_db() -> dict:
    return {"v": DB_VERSION, "kind": DB_KIND, "entries": {}}


def make_entry(config: PlanConfig, choice: PlanChoice, source: str,
               static_cost_s: Optional[float] = None,
               measured_s: Optional[float] = None,
               probes: Optional[list] = None,
               note: Optional[str] = None) -> dict:
    if source not in SOURCES:
        raise ValueError(f"unknown plan source {source!r} "
                         f"(known: {', '.join(SOURCES)})")
    return {
        "config": config.to_json(),
        "choice": choice.to_json(),
        "source": source,
        "static_cost_s": static_cost_s,
        "measured_s": measured_s,
        "probes": list(probes or []),
        "written_t": time.time(),
        "note": note,
    }


def validate_entry(key: str, entry) -> List[str]:
    errs: List[str] = []
    if not isinstance(entry, dict):
        return [f"entry {key!r} is not an object"]
    try:
        cfg = PlanConfig.from_json(entry["config"])
    except (KeyError, TypeError, ValueError) as e:
        return [f"entry {key!r}: bad config ({e})"]
    if cfg.key() != key:
        errs.append(f"entry {key!r}: key does not match its config "
                    f"(canonical {cfg.key()!r})")
    try:
        choice = PlanChoice.from_json(entry["choice"])
    except (KeyError, TypeError, ValueError) as e:
        return errs + [f"entry {key!r}: bad choice ({e})"]
    if choice.method not in METHODS:
        errs.append(f"entry {key!r}: unknown method {choice.method!r}")
    if len(choice.partition) != 3 or any(
            not isinstance(p, int) or p < 1 for p in choice.partition):
        errs.append(f"entry {key!r}: partition must be 3 positive ints")
    if choice.multistep_k < 1:
        errs.append(f"entry {key!r}: multistep_k must be >= 1")
    # placement rides schema v1: an ABSENT field is the identity
    # assignment (every pre-placement entry — legacy v0 migrations
    # included — deserializes to None and replays unchanged); a present
    # one must be a permutation of the config's mesh positions
    perr = validate_placement(choice.placement, cfg.ndev)
    if perr is not None:
        errs.append(f"entry {key!r}: {perr}")
    # hierarchy/host_placement ride the same absent-field migration:
    # every pre-hierarchy entry deserializes to None (flat) and replays
    # unchanged; a present hierarchy must be a valid (axis, hosts) split
    # of the choice's partition, a present host_placement a permutation
    # of range(hosts)
    if choice.hierarchy is not None:
        from ..geometry import Dim3
        from .ir import validate_hierarchy

        px, py, pz = choice.partition
        herr = validate_hierarchy(choice.hierarchy, Dim3(px, py, pz))
        if herr is not None:
            errs.append(f"entry {key!r}: {herr}")
    if choice.host_placement is not None:
        hp = list(choice.host_placement)
        hosts = choice.hierarchy[1] if choice.hierarchy is not None else None
        if hosts is None:
            errs.append(f"entry {key!r}: host_placement without hierarchy")
        elif sorted(hp) != list(range(hosts)):
            errs.append(f"entry {key!r}: host_placement {hp} is not a "
                        f"permutation of range({hosts})")
    if entry.get("source") not in SOURCES:
        errs.append(f"entry {key!r}: unknown source {entry.get('source')!r}")
    for fld in ("static_cost_s", "measured_s"):
        v = entry.get(fld)
        if v is not None and not isinstance(v, (int, float)):
            errs.append(f"entry {key!r}: {fld} must be numeric or null")
    return errs


def validate_calibration_row(platform: str, row) -> List[str]:
    """Violations of one fitted-calibration row (``calibrations``
    section). The row is what :func:`stencil_tpu.plan.calibrate.fit`
    returns: the score() override dict plus its fit provenance."""
    pfx = f"calibration {platform!r}"
    if not isinstance(row, dict):
        return [f"{pfx} is not an object"]
    errs: List[str] = []
    if not isinstance(row.get("calibration"), dict):
        errs.append(f"{pfx}: missing calibration override dict")
    if not isinstance(row.get("provenance"), str) or not row.get("provenance"):
        errs.append(f"{pfx}: provenance must be a non-empty string")
    n = row.get("n")
    if not isinstance(n, int) or isinstance(n, bool) or n < 2:
        errs.append(f"{pfx}: n must be an int >= 2 (a fit from fewer "
                    "samples is refused at fit time, never persisted)")
    if not isinstance(row.get("r2"), (int, float)):
        errs.append(f"{pfx}: r2 must be numeric")
    return errs


def validate_db(obj) -> List[str]:
    """Schema violations of a parsed DB (empty = valid v1)."""
    if not isinstance(obj, dict):
        return [f"not an object: {type(obj).__name__}"]
    errs: List[str] = []
    if obj.get("kind") != DB_KIND:
        errs.append(f"unknown kind {obj.get('kind')!r}")
    if obj.get("v") != DB_VERSION:
        errs.append(f"unknown schema version {obj.get('v')!r}")
    entries = obj.get("entries")
    if not isinstance(entries, dict):
        errs.append("entries must be an object")
        return errs
    for key, entry in entries.items():
        errs.extend(validate_entry(key, entry))
    # "calibrations" rides schema v1 the way placement rides entries: an
    # ABSENT section is "no fitted rows, DEFAULT_CALIBRATION applies"
    # (every pre-observatory DB loads unchanged); a present one maps
    # platform -> fitted row
    if "calibrations" in obj:
        cals = obj["calibrations"]
        if not isinstance(cals, dict):
            errs.append("calibrations must be an object")
        else:
            for platform, row in cals.items():
                errs.extend(validate_calibration_row(platform, row))
    return errs


def migrate_db(obj: dict) -> dict:
    """Bring a stale-schema DB forward to v1.

    Known legacy layout (v0, the pre-schema prototype): a flat
    ``{config-key: choice-json}`` mapping with no version envelope. Its
    entries become v1 entries with ``source="legacy"`` and no recorded
    cost — a lookup hit still replays them, and ``plan_tool prune
    --source legacy`` clears them once re-tuned. Anything newer than
    DB_VERSION is refused (a downgrade must not silently rewrite a
    future DB)."""
    if not isinstance(obj, dict):
        raise PlanDBError(f"plan DB is not an object: {type(obj).__name__}")
    v = obj.get("v")
    if v == DB_VERSION and obj.get("kind") == DB_KIND:
        return obj
    if isinstance(v, int) and v > DB_VERSION:
        raise PlanDBError(
            f"plan DB schema v{v} is newer than this build's v{DB_VERSION}"
        )
    if "v" not in obj and "kind" not in obj:
        # v0 flat mapping: every value must parse as a choice
        entries = {}
        for key, val in obj.items():
            try:
                cfg = PlanConfig.from_json(json.loads(key))
                choice = PlanChoice.from_json(val)
            except (KeyError, TypeError, ValueError,
                    json.JSONDecodeError) as e:
                raise PlanDBError(f"legacy plan DB entry {key!r}: {e}")
            entries[cfg.key()] = make_entry(
                cfg, choice, "legacy", note="migrated from v0 flat layout"
            )
        return {"v": DB_VERSION, "kind": DB_KIND, "entries": entries}
    raise PlanDBError(
        f"unrecognized plan DB envelope (v={obj.get('v')!r}, "
        f"kind={obj.get('kind')!r})"
    )


def load_db(path: str) -> dict:
    """Parse + migrate + validate; missing file -> empty DB. Corruption
    raises :class:`PlanDBError` — callers decide whether to degrade
    (autotune warns and runs un-persisted) or fail (the CI gate)."""
    if not os.path.exists(path):
        return empty_db()
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise PlanDBError(f"unreadable plan DB {path}: {e}")
    obj = migrate_db(obj)
    errs = validate_db(obj)
    if errs:
        raise PlanDBError(
            f"invalid plan DB {path}: {errs[0]}"
            + (f" (+{len(errs) - 1} more)" if len(errs) > 1 else "")
        )
    return obj


def save_db(path: str, db: dict) -> None:
    """Atomic write: tmp + fsync + rename (ckpt rename discipline)."""
    errs = validate_db(db)
    if errs:
        raise PlanDBError(f"refusing to write invalid plan DB: {errs[0]}")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f"{_TMP_PREFIX}{os.path.basename(path)}-{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(db, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def lookup(db: dict, config: PlanConfig) -> Optional[dict]:
    """The entry tuned for ``config`` (exact canonical-key match)."""
    return db["entries"].get(config.key())


def record(db: dict, entry: dict) -> dict:
    """Insert/replace ``entry`` under its config's canonical key."""
    key = PlanConfig.from_json(entry["config"]).key()
    db["entries"][key] = entry
    return entry


def record_calibration(db: dict, platform: str, row: dict) -> dict:
    """Install/replace the fitted calibration row for ``platform``."""
    errs = validate_calibration_row(platform, row)
    if errs:
        raise PlanDBError(f"refusing to record calibration: {errs[0]}")
    db.setdefault("calibrations", {})[platform] = row
    return row


def lookup_calibration(db: dict, platform: str) -> Optional[dict]:
    """The fitted calibration row for ``platform``, or None (the
    absent-section default: DEFAULT_CALIBRATION, provenance modeled)."""
    return (db.get("calibrations") or {}).get(platform)


def prune_db(db: dict, platform: Optional[str] = None,
             source: Optional[str] = None,
             older_than_s: Optional[float] = None) -> int:
    """Drop entries matching every given filter; returns the count.
    At least one filter is required — "prune everything" must be an
    explicit ``source=...``/``platform=...`` decision, not a default."""
    if platform is None and source is None and older_than_s is None:
        raise ValueError("prune_db requires at least one filter")
    now = time.time()
    doomed = []
    for key, entry in db["entries"].items():
        if platform is not None and entry["config"].get("platform") != platform:
            continue
        if source is not None and entry.get("source") != source:
            continue
        if older_than_s is not None and (
                now - entry.get("written_t", 0)) < older_than_s:
            continue
        doomed.append(key)
    for key in doomed:
        del db["entries"][key]
    return len(doomed)
