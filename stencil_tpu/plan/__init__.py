"""Exchange planning: the ExchangePlan IR, the partition/method autotuner,
and the on-disk plan DB.

This package is the production analogue of the reference's entire L3 —
``RankPartition``/``NodePartition`` searching partition shapes and the
``NodeAware`` placement costing candidates by link bandwidth (reference:
include/stencil/partition.hpp, placement.hpp). Four pieces:

- :mod:`ir` — the declarative ExchangePlan every exchange method lowers
  from (phases, directions, pack groups, permute pairs). The planner
  searches *plans*, not code paths; ``parallel/exchange.py`` is the
  lowering.
- :mod:`cost` — a static cost model fed by the plan's collective counts /
  on-wire bytes and the per-collective overhead ratios recorded in
  BASELINE.md rounds 7/10.
- :mod:`probe` — short measured refinement probes (reusing
  ``apps/_bench_common.time_exchange``) over the top static candidates.
- :mod:`db` — the on-disk JSON plan DB keyed by canonical config, so
  production runs replay tuned plans with zero probe runs.

Only :mod:`ir` is imported eagerly (pure geometry, no jax at import
time); import the tuner explicitly (``from stencil_tpu.plan.autotune
import autotune``) — a package-level alias would be shadowed by the
submodule of the same name as soon as anything imports it.
"""

from .ir import (
    AxisPhaseIR,
    DirectPhaseIR,
    ExchangePlan,
    PlanChoice,
    PlanConfig,
    RemoteDmaPhaseIR,
    build_plan,
    validate_placement,
)

__all__ = [
    "AxisPhaseIR",
    "DirectPhaseIR",
    "ExchangePlan",
    "PlanChoice",
    "PlanConfig",
    "RemoteDmaPhaseIR",
    "build_plan",
    "validate_placement",
]
