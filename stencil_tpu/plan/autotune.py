"""The partition/method autotuner: static rank -> probe top-N -> persist.

One call answers "which exchange plan should THIS config run?" the way
the reference's L3 answers it with ``RankPartition``/``NodePartition``
search + ``NodeAware`` placement costing (PAPER.md §2.4) — except the
winners persist: the on-disk plan DB (plan/db.py) is consulted first,
and a hit replays the tuned choice with ZERO probe runs. The telemetry
trail proves which path ran:

- ``plan.cache_hit`` gauge: 1 on a pure DB hit, 0 on a tuning run;
- ``plan.probes_run`` counter: measured probes this call executed;
- ``plan.candidates`` gauge: feasible static candidates ranked;
- ``plan.chosen`` meta: the winning choice + its provenance.

scripts/ci_plan_gate.py pins the contract end-to-end: autotune twice at
the same config — the second run must be a pure DB hit — and the chosen
plan must produce bit-identical halos to the default program.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..geometry import Dim3, Radius
from ..utils import logging as log
from . import db as plandb
from .cost import DEFAULT_VARIANTS, enumerate_candidates, rank
from .ir import METHODS, PlanChoice, PlanConfig


@dataclass
class AutotuneResult:
    config: PlanConfig
    choice: PlanChoice
    source: str                 # 'db' | 'probe' | 'static' | 'seed'...
    cache_hit: bool
    probes_run: int
    candidates: int
    entry: Optional[dict] = None
    ranked: List[Tuple[object, PlanChoice]] = field(default_factory=list)
    probes: List[dict] = field(default_factory=list)
    # what priced the ranking: the override dict (None = defaults) and
    # its provenance string — stamped into plan.chosen and the run's
    # plan.fingerprint meta so ledger entries say which constants ranked
    calibration: Optional[dict] = None
    calibration_provenance: str = "modeled(default)"


def default_choice(config: PlanConfig) -> PlanChoice:
    """What a plan-less realize() would do: NodePartition's min-interface
    split on every device, AXIS_COMPOSED, batching on — the baseline the
    ``plan_autotuned_over_default`` bench leg compares against."""
    from ..geometry import NodePartition

    part = NodePartition(Dim3.of(config.grid), config.radius_obj(),
                         1, config.ndev)
    d = part.dim()
    return PlanChoice(partition=(d.x, d.y, d.z), method="axis-composed",
                      batch_quantities=True)


def autotune(
    size,
    radius: Radius,
    dtypes: Sequence[str],
    ndev: Optional[int] = None,
    devices=None,
    db_path: Optional[str] = None,
    platform: Optional[str] = None,
    top_n: int = 3,
    probe_iters: int = 4,
    probe: bool = True,
    force: bool = False,
    methods: Sequence[str] = METHODS,
    ks: Sequence[int] = (1,),
    variants: Sequence[Optional[str]] = DEFAULT_VARIANTS,
    calibration: Optional[dict] = None,
    link_costs=None,
    rec=None,
) -> AutotuneResult:
    """Choose (and persist) the exchange plan for one config.

    ``probe=False`` keeps the run static-only (no compiles — usable
    backend-less); ``force=True`` re-tunes through an existing DB entry
    (the entry is replaced). A corrupt DB degrades loudly: the tuning
    still runs, but nothing is persisted over the damaged file.

    ``link_costs`` feeds the topology-aware placement search (an ndev x
    ndev device-pair distance matrix; ``plan/cost.enumerate_candidates``
    grows each partition's QAP-solved placement candidate from it and
    ``score`` prices wire time through it). When omitted and live
    ``devices`` were given, it is derived from them
    (``parallel/topology.link_cost_matrix`` — ICI hops on TPU, process
    boundaries elsewhere); a uniform matrix (the single-process CPU
    mesh) changes nothing."""
    import importlib

    from ..obs import telemetry

    rec = rec or telemetry.get()
    if devices is not None:
        devices = list(devices)
        ndev = len(devices)
        platform = platform or devices[0].platform
    if ndev is None or platform is None:
        # resolve from the live backend only when the caller gave neither
        jax = importlib.import_module("jax")
        devs = jax.devices()
        if devices is None:
            devices = devs
        ndev = ndev if ndev is not None else len(devs)
        platform = platform or devs[0].platform
    config = PlanConfig.make(size, radius, dtypes, ndev, platform)
    if link_costs is None and devices is not None:
        from ..parallel.topology import link_cost_matrix

        link_costs = link_cost_matrix(devices)
    # host structure of the live fabric (real processes, or the
    # STENCIL_VIRTUAL_HOSTS emulation): >1 host opens the hierarchical
    # (ICI+DCN) half of the candidate space — outer splits along each
    # dividing axis, placed by the two-level QAP
    hierarchy_hosts = None
    host_map = None
    if devices is not None:
        from ..parallel.device_topo import host_assignment

        host_map = [int(h) for h in host_assignment(devices)]
        nhosts = len(set(host_map))
        if nhosts > 1:
            hierarchy_hosts = nhosts
        else:
            host_map = None

    db = None
    db_ok = False
    if db_path:
        try:
            db = plandb.load_db(db_path)
            db_ok = True
        except plandb.PlanDBError as e:
            log.warn(f"plan DB {db_path} rejected ({e}); tuning without "
                     "persistence — fix or remove the file")
    # the observatory loop's install half: a fitted calibration row in
    # the DB (plan_tool calibrate) prices this platform's rankings until
    # the caller overrides it explicitly
    cal_provenance = ("modeled(default)" if calibration is None
                      else str(calibration.get("provenance", "override")))
    if calibration is None and db is not None:
        cal_row = plandb.lookup_calibration(db, platform)
        if cal_row is not None:
            calibration = cal_row["calibration"]
            cal_provenance = str(cal_row.get("provenance", "fitted"))
            log.info(f"plan calibration: {cal_provenance} "
                     f"(from {db_path})")
    if db is not None and not force:
        entry = plandb.lookup(db, config)
        if entry is not None:
            choice = PlanChoice.from_json(entry["choice"])
            rec.gauge("plan.cache_hit", 1, phase="plan")
            rec.counter("plan.probes_run", value=0, phase="plan")
            rec.meta("plan.chosen", choice=entry["choice"], source="db",
                     db_source=entry.get("source"), key=config.key(),
                     calibration=cal_provenance)
            log.info(f"plan DB hit: {choice.label()} "
                     f"(tuned by {entry.get('source')}) — zero probes")
            return AutotuneResult(
                config=config, choice=choice, source="db", cache_hit=True,
                probes_run=0, candidates=0, entry=entry,
                calibration=calibration,
                calibration_provenance=cal_provenance,
            )

    with rec.span("plan.autotune", phase="plan"):
        candidates = enumerate_candidates(config, methods=methods,
                                          ks=ks, variants=variants,
                                          link_costs=link_costs,
                                          hierarchy_hosts=hierarchy_hosts,
                                          host_map=host_map)
        ranked = rank(config, candidates, calibration,
                      link_costs=link_costs)
        if not ranked:
            raise ValueError(
                f"no feasible exchange plan for {config.key()} — grid too "
                f"small for every partition of {config.ndev} devices?"
            )
        rec.gauge("plan.candidates", len(ranked), phase="plan")
        probes: List[dict] = []
        measured = None
        if probe:
            from .probe import refine

            measured, probes = refine(config, ranked, top_n=top_n,
                                      iters=probe_iters, devices=devices)
        n_probes = sum(1 for p in probes if "trimean_s" in p)
        rec.counter("plan.probes_run", value=n_probes, phase="plan")
        rec.gauge("plan.cache_hit", 0, phase="plan")
        if measured is not None:
            choice, source = measured, "probe"
            measured_s = min(p["trimean_s"] for p in probes
                             if "trimean_s" in p
                             and p["label"] == choice.label())
        else:
            choice, source = ranked[0][1], "static"
            measured_s = None
        static_cost = next(
            (c.total_s for c, ch in ranked if ch == choice), None)
        rec.meta("plan.chosen", choice=choice.to_json(), source=source,
                 key=config.key(), calibration=cal_provenance)
        log.info(f"plan autotuned: {choice.label()} via {source} "
                 f"({n_probes} probes over {len(ranked)} candidates)")

    entry = plandb.make_entry(config, choice, source,
                              static_cost_s=static_cost,
                              measured_s=measured_s, probes=probes)
    if db is not None and db_ok:
        plandb.record(db, entry)
        plandb.save_db(db_path, db)
    return AutotuneResult(
        config=config, choice=choice, source=source, cache_hit=False,
        probes_run=n_probes, candidates=len(ranked), entry=entry,
        ranked=ranked, probes=probes, calibration=calibration,
        calibration_provenance=cal_provenance,
    )
