"""Rect3 — an axis-aligned half-open box [lo, hi) in grid coordinates.

TPU-native analogue of the reference's ``Rect3`` (reference:
include/stencil/rect3.hpp:13-27). Used for compute regions and the
interior/exterior overlap decomposition.
"""

from __future__ import annotations

from dataclasses import dataclass

from .dim3 import Dim3


@dataclass(frozen=True)
class Rect3:
    lo: Dim3
    hi: Dim3

    @staticmethod
    def of(lo, hi) -> "Rect3":
        return Rect3(Dim3.of(lo), Dim3.of(hi))

    def extent(self) -> Dim3:
        """Size of the box (reference: rect3.hpp `extent`)."""
        return self.hi - self.lo

    def num_points(self) -> int:
        e = self.extent()
        return max(e.x, 0) * max(e.y, 0) * max(e.z, 0)

    def empty(self) -> bool:
        return self.num_points() == 0

    def contains(self, p: Dim3) -> bool:
        return (
            self.lo.x <= p.x < self.hi.x
            and self.lo.y <= p.y < self.hi.y
            and self.lo.z <= p.z < self.hi.z
        )

    def shifted(self, d: Dim3) -> "Rect3":
        return Rect3(self.lo + d, self.hi + d)

    def slices(self, origin: Dim3 = Dim3(0, 0, 0)) -> tuple[slice, slice, slice]:
        """Convert to numpy/JAX basic-index slices relative to ``origin``."""
        lo = self.lo - origin
        hi = self.hi - origin
        return (slice(lo.x, hi.x), slice(lo.y, hi.y), slice(lo.z, hi.z))

    def __repr__(self) -> str:
        return f"Rect3({self.lo.as_tuple()}..{self.hi.as_tuple()})"
