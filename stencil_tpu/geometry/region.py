"""Halo geometry: where halo/exterior regions live inside a padded block.

TPU-native re-implementation of the reference's LocalDomain halo math
(reference: src/local_domain.cu:86-129 ``halo_pos``,
include/stencil/local_domain.cuh:212-239 ``halo_extent``/``raw_size``)
and the DistributedDomain interior/exterior overlap decomposition
(reference: src/stencil.cu:878-977).

Coordinates are *allocation-local*: a padded block has shape
``raw_size = size + radius- + radius+`` per axis, with the compute region
offset by the negative-side face radii.
"""

from __future__ import annotations

from .dim3 import DIRECTIONS_26, Dim3
from .radius import Radius
from .rect3 import Rect3


def halo_extent(direction, size, radius: Radius) -> Dim3:
    """Point-extent of the halo region on side ``direction``.

    A zero component of ``direction`` spans the full compute size on that
    axis; a nonzero component spans that side's *face* radius
    (reference: local_domain.cuh:212-222).
    """
    d = Dim3.of(direction)
    sz = Dim3.of(size)
    return Dim3(
        sz.x if d.x == 0 else radius.x(d.x),
        sz.y if d.y == 0 else radius.y(d.y),
        sz.z if d.z == 0 else radius.z(d.z),
    )


def halo_pos(direction, size, radius: Radius, halo: bool) -> Dim3:
    """Allocation-local position of the halo (``halo=True``) or the matching
    boundary interior / "exterior" region (``halo=False``) on side
    ``direction``. Reference: src/local_domain.cu:86-129.
    """
    d = Dim3.of(direction)
    sz = Dim3.of(size)

    def axis(dc: int, s: int, rm: int) -> int:
        # rm is the negative-side face radius on this axis
        if dc == 1:
            return s + (rm if halo else 0)
        if dc == -1:
            return 0 if halo else rm
        return rm

    return Dim3(
        axis(d.x, sz.x, radius.x(-1)),
        axis(d.y, sz.y, radius.y(-1)),
        axis(d.z, sz.z, radius.z(-1)),
    )


def raw_size(size, radius: Radius) -> Dim3:
    """Padded allocation size: compute size plus both face radii per axis
    (reference: local_domain.cuh:236-239)."""
    sz = Dim3.of(size)
    return Dim3(
        sz.x + radius.x(-1) + radius.x(1),
        sz.y + radius.y(-1) + radius.y(1),
        sz.z + radius.z(-1) + radius.z(1),
    )


def compute_offset(radius: Radius) -> Dim3:
    """Allocation-local origin of the compute region."""
    return Dim3(radius.x(-1), radius.y(-1), radius.z(-1))


def halo_rect(direction, size, radius: Radius, halo: bool) -> Rect3:
    """Allocation-local Rect3 of the halo (``halo=True``) or the matching
    owned boundary region (``halo=False``) on side ``direction``.

    The owned region adjacent to side ``d`` is what gets *sent* toward
    ``d``, so it is sized by the receiver's opposite-side halo:
    ``halo_extent(-d)`` (the reference pairs ``halo_pos(d, false)`` with
    ``halo_extent(-d)``, src/packer.cu:80-81, test_cuda_local_domain.cu
    "case1"). With asymmetric per-axis radii the two extents differ.
    """
    d = Dim3.of(direction)
    pos = halo_pos(d, size, radius, halo)
    ext = halo_extent(d if halo else -d, size, radius)
    return Rect3(pos, pos + ext)


def interior_region(compute: Rect3, radius: Radius) -> Rect3:
    """Shrink the compute region so that a stencil read in any direction with
    nonzero radius stays inside owned data (reference: src/stencil.cu:878-921).

    Walks all 26 directions; a negative direction component with nonzero
    radius pulls the low face in, a positive one pulls the high face in.
    """
    lo = list(compute.lo.as_tuple())
    hi = list(compute.hi.as_tuple())
    clo = compute.lo.as_tuple()
    chi = compute.hi.as_tuple()
    for d in DIRECTIONS_26:
        r = radius.dir(d)
        if r == 0:
            continue
        for ax, dc in enumerate((d.x, d.y, d.z)):
            if dc < 0:
                lo[ax] = max(clo[ax] + r, lo[ax])
            elif dc > 0:
                hi[ax] = min(chi[ax] - r, hi[ax])
    return Rect3(Dim3(*lo), Dim3(*hi))


def exterior_regions(compute: Rect3, interior: Rect3) -> list[Rect3]:
    """Decompose (compute minus interior) into at most 6 non-overlapping
    slabs by sliding faces inward: +x, +y, +z, -x, -y, -z order
    (reference: src/stencil.cu:927-977)."""
    ret: list[Rect3] = []
    lo = list(compute.lo.as_tuple())
    hi = list(compute.hi.as_tuple())

    # positive faces: peel [interior.hi, hi) slab then slide hi in
    for ax, int_hi in enumerate(interior.hi.as_tuple()):
        if int_hi != hi[ax]:
            slab_lo = list(lo)
            slab_hi = list(hi)
            slab_lo[ax] = int_hi
            ret.append(Rect3(Dim3(*slab_lo), Dim3(*slab_hi)))
            hi[ax] = int_hi
    # negative faces: peel [lo, interior.lo) slab then slide lo in
    for ax, int_lo in enumerate(interior.lo.as_tuple()):
        if int_lo != lo[ax]:
            slab_lo = list(lo)
            slab_hi = list(hi)
            slab_hi[ax] = int_lo
            ret.append(Rect3(Dim3(*slab_lo), Dim3(*slab_hi)))
            lo[ax] = int_lo
    return ret
