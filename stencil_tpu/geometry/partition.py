"""Domain partitioners: split a global 3D extent into subdomains.

TPU-native re-implementation of the reference's partition math
(reference: include/stencil/partition.hpp:20-256). Two strategies:

- :class:`RankPartition` splits repeatedly along the *longest* axis by the
  prime factors of N (largest factor first).
- :class:`NodePartition` is a two-level split (hosts, then chips per host)
  that each step cuts the axis with the smallest radius-weighted interface
  area — the communication-minimizing split.

On TPU these decide the shape of the 3D device mesh
(``jax.sharding.Mesh``) and the per-shard logical sizes; the remainder
handling below reproduces the reference's uneven-split semantics exactly
(pinned by tests ported from test/test_cpu_partition.cpp).
"""

from __future__ import annotations

from .dim3 import Dim3
from .numeric import div_ceil, prime_factors
from .radius import Radius


def decompose_zy(p: int) -> Dim3:
    """TPU-first device decomposition: split over z and y ONLY, keeping
    the lane (x) axis whole.

    Three wins over the reference's 3-axis decomposition
    (astaroth.cu:263-276) on TPU hardware: (1) every chip keeps the
    tight-x layout — no x halo columns, periodic x via lane rolls
    (1.36-1.62x measured per chip, BASELINE.md round 3); (2) the exchange
    never slices the minor dim, so no slab pays (8,128) lane-tile
    amplification; (3) splitting two axes moves fewer halo bytes for the
    same shard volume (4 split faces instead of 6) and the 2D z x y mesh
    maps directly onto the v5e ICI torus. z grows first (matches the
    slowest-varying layout dim)."""
    y = z = 1
    for pf in prime_factors(max(p, 1)):
        if z <= y:
            z *= pf
        else:
            y *= pf
    return Dim3(1, y, z)


def stack_residents(dim: Dim3, c: int) -> Dim3:
    """Mesh dims for stacking ``c`` resident blocks per device onto
    partition ``dim``: the z-heaviest (cz, cy, cx) factorization of ``c``
    whose components divide the partition axes (exhaustive — divisor
    triples of c are few). Reference envelope: dd.set_gpus accepts any
    block multiset per device (stencil.hpp:154). Shared by
    ``api.realize`` and the plan cost model, which must predict the same
    mesh a realize() of the candidate would build."""
    best = None
    for cz in range(c, 0, -1):
        if c % cz or dim.z % cz:
            continue
        cyx = c // cz
        for cy in range(cyx, 0, -1):
            if cyx % cy or dim.y % cy:
                continue
            cx = cyx // cy
            if dim.x % cx:
                continue
            best = Dim3(dim.x // cx, dim.y // cy, dim.z // cz)
            break
        if best is not None:
            break
    if best is None:
        raise ValueError(
            f"cannot stack {c} resident blocks per device onto partition "
            f"{dim}: no divisor triple of {c} divides the axes"
        )
    return best


class RankPartition:
    """Split ``size`` into ``n`` subdomains along the longest axes.

    Reference: partition.hpp:28-115. Each prime factor of ``n`` (largest
    first) divides the currently-longest axis (ties: x wins over y wins
    over z). Remainders shrink trailing subdomains by one.
    """

    def __init__(self, size, n: int):
        size = Dim3.of(size)
        self._input = size
        dim = Dim3(1, 1, 1)
        sz = size
        for amt in prime_factors(max(n, 1)):
            if amt < 2:
                continue
            if sz.x >= sz.y and sz.x >= sz.z:
                sz = Dim3(div_ceil(sz.x, amt), sz.y, sz.z)
                dim = Dim3(dim.x * amt, dim.y, dim.z)
            elif sz.y >= sz.z:
                sz = Dim3(sz.x, div_ceil(sz.y, amt), sz.z)
                dim = Dim3(dim.x, dim.y * amt, dim.z)
            else:
                sz = Dim3(sz.x, sz.y, div_ceil(sz.z, amt))
                dim = Dim3(dim.x, dim.y, dim.z * amt)
        self._dim = dim
        self._size = sz
        self._rem = size % dim

    def dim(self) -> Dim3:
        return self._dim

    def base_size(self) -> Dim3:
        """The largest subdomain size (shards with idx < rem per axis)."""
        return self._size

    def subdomain_size(self, idx) -> Dim3:
        """Reference: partition.hpp:55-70 — trailing subdomains lose one."""
        idx = Dim3.of(idx)
        r = self._rem
        s = self._size
        return Dim3(
            s.x - (1 if (r.x != 0 and idx.x >= r.x) else 0),
            s.y - (1 if (r.y != 0 and idx.y >= r.y) else 0),
            s.z - (1 if (r.z != 0 and idx.z >= r.z) else 0),
        )

    def subdomain_origin(self, idx) -> Dim3:
        """Reference: partition.hpp:72-86."""
        idx = Dim3.of(idx)
        r = self._rem
        ret = self._size * idx
        return Dim3(
            ret.x - ((idx.x - r.x) if (r.x != 0 and idx.x >= r.x) else 0),
            ret.y - ((idx.y - r.y) if (r.y != 0 and idx.y >= r.y) else 0),
            ret.z - ((idx.z - r.z) if (r.z != 0 and idx.z >= r.z) else 0),
        )

    def is_uniform(self) -> bool:
        return self._rem == Dim3(0, 0, 0)

    def linearize(self, idx) -> int:
        """x-fastest linear index (reference: partition.hpp:89-101)."""
        idx = Dim3.of(idx)
        d = self._dim
        if not (0 <= idx.x < d.x and 0 <= idx.y < d.y and 0 <= idx.z < d.z):
            raise IndexError(f"block index {idx} outside partition {d}")
        return idx.x + idx.y * d.x + idx.z * d.y * d.x

    def dimensionize(self, i: int) -> Dim3:
        """Reference: partition.hpp:104-115."""
        d = self._dim
        if not 0 <= i < d.flatten():
            raise IndexError(f"linear index {i} outside partition {d}")
        x = i % d.x
        i //= d.x
        y = i % d.y
        i //= d.y
        return Dim3(x, y, i)


def _min_interface_split(sz: Dim3, dim: Dim3, radius: Radius, amt: int) -> tuple[Dim3, Dim3]:
    """One communication-minimizing cut (reference: partition.hpp:167-208).

    Chooses the axis whose interface area (orthogonal extent x sum of +/-
    face radii) is smallest; ties prefer x, then y.
    """
    x_iface = sz.y * sz.z * (radius.dir(1, 0, 0) + radius.dir(-1, 0, 0))
    y_iface = sz.x * sz.z * (radius.dir(0, 1, 0) + radius.dir(0, -1, 0))
    z_iface = sz.x * sz.y * (radius.dir(0, 0, 1) + radius.dir(0, 0, -1))
    if x_iface <= y_iface and x_iface <= z_iface:
        return Dim3(div_ceil(sz.x, amt), sz.y, sz.z), Dim3(dim.x * amt, dim.y, dim.z)
    elif y_iface <= z_iface:
        return Dim3(sz.x, div_ceil(sz.y, amt), sz.z), Dim3(dim.x, dim.y * amt, dim.z)
    else:
        return Dim3(sz.x, sz.y, div_ceil(sz.z, amt)), Dim3(dim.x, dim.y, dim.z * amt)


class NodePartition:
    """Two-level communication-minimizing partition.

    Reference: partition.hpp:120-256. First splits among ``nodes`` (hosts /
    TPU slices), then among ``gpus`` (chips per host), each cut taken on the
    axis with the smallest radius-weighted interface. On TPU the outer level
    maps to DCN (multi-slice) and the inner level to ICI within a slice.
    """

    def __init__(self, size, radius: Radius, nodes: int, gpus: int):
        size = Dim3.of(size)
        sys_dim = Dim3(1, 1, 1)
        node_dim = Dim3(1, 1, 1)
        sz = size
        for amt in prime_factors(max(nodes, 1)):
            if amt < 2:
                continue
            sz, sys_dim = _min_interface_split(sz, sys_dim, radius, amt)
        for amt in prime_factors(max(gpus, 1)):
            if amt < 2:
                continue
            sz, node_dim = _min_interface_split(sz, node_dim, radius, amt)
        self._sys_dim = sys_dim
        self._node_dim = node_dim
        self._size = sz
        self._rem = size % (sys_dim * node_dim)

    def sys_dim(self) -> Dim3:
        return self._sys_dim

    def node_dim(self) -> Dim3:
        return self._node_dim

    def dim(self) -> Dim3:
        return self._sys_dim * self._node_dim

    def base_size(self) -> Dim3:
        return self._size

    def subdomain_size(self, idx) -> Dim3:
        """Reference: partition.hpp:221-236 (same remainder rule as
        RankPartition)."""
        idx = Dim3.of(idx)
        r = self._rem
        s = self._size
        return Dim3(
            s.x - (1 if (r.x != 0 and idx.x >= r.x) else 0),
            s.y - (1 if (r.y != 0 and idx.y >= r.y) else 0),
            s.z - (1 if (r.z != 0 and idx.z >= r.z) else 0),
        )

    def subdomain_origin(self, idx) -> Dim3:
        """Reference: partition.hpp:238-252."""
        idx = Dim3.of(idx)
        r = self._rem
        ret = self._size * idx
        return Dim3(
            ret.x - ((idx.x - r.x) if (r.x != 0 and idx.x >= r.x) else 0),
            ret.y - ((idx.y - r.y) if (r.y != 0 and idx.y >= r.y) else 0),
            ret.z - ((idx.z - r.z) if (r.z != 0 and idx.z >= r.z) else 0),
        )

    def is_uniform(self) -> bool:
        return self._rem == Dim3(0, 0, 0)

    @staticmethod
    def _dimensionize(i: int, dim: Dim3) -> Dim3:
        assert 0 <= i < dim.flatten()
        x = i % dim.x
        i //= dim.x
        y = i % dim.y
        i //= dim.y
        return Dim3(x, y, i)

    def sys_idx(self, i: int) -> Dim3:
        return self._dimensionize(i, self._sys_dim)

    def node_idx(self, i: int) -> Dim3:
        return self._dimensionize(i, self._node_dim)
