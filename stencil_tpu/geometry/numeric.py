"""Small integer helpers used by the partitioners.

TPU-native re-implementation of the reference's numeric utilities
(reference: include/stencil/numeric.hpp, src/numeric.cpp). These are pure
host-side integer math used at plan time, never traced by JAX.
"""

from __future__ import annotations


def prime_factors(n: int) -> list[int]:
    """Prime factorization of ``n``, sorted largest-first.

    The largest-first order matters: the partitioners split the domain by one
    prime factor at a time, and splitting by the biggest factor first yields
    the reference's exact subdomain shapes (reference: src/numeric.cpp:7-26).
    """
    if n < 1:
        raise ValueError(f"prime_factors requires n >= 1, got {n}")
    factors: list[int] = []
    remaining = n
    p = 2
    while p * p <= remaining:
        while remaining % p == 0:
            factors.append(p)
            remaining //= p
        p += 1
    if remaining > 1:
        factors.append(remaining)
    factors.sort(reverse=True)
    return factors


def div_ceil(n: int, d: int) -> int:
    """Ceiling division (reference: include/stencil/numeric.hpp:25)."""
    return -(-n // d)


def next_power_of_two(x: int) -> int:
    """Smallest power of two >= x (reference: include/stencil/numeric.hpp:9-19)."""
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def max_abs_error(a, b) -> float:
    """Largest elementwise absolute difference between two sequences
    (reference: include/stencil/numeric.hpp:27-33)."""
    return max((abs(x - y) for x, y in zip(a, b, strict=True)), default=0.0)
