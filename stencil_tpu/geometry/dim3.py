"""Dim3 — an integer 3-vector for grid geometry.

TPU-native analogue of the reference's ``Dim3`` (reference:
include/stencil/dim3.hpp). Used for extents, origins, partition indices and
direction vectors. Pure host-side math: JAX code receives plain tuples via
:meth:`Dim3.as_tuple` so everything stays static under ``jit``.

Note the reference's ``operator!=`` and ``max()`` carry known bugs
(SURVEY.md §2.5); this implementation is correct rather than bug-compatible.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator


@dataclass(frozen=True, order=False)
class Dim3:
    x: int = 0
    y: int = 0
    z: int = 0

    # -- constructors -------------------------------------------------------
    @staticmethod
    def of(v) -> "Dim3":
        if isinstance(v, Dim3):
            return v
        if isinstance(v, int):
            return Dim3(v, v, v)
        x, y, z = v
        return Dim3(int(x), int(y), int(z))

    # -- arithmetic ---------------------------------------------------------
    def _coerce(self, other) -> "Dim3":
        return Dim3.of(other)

    def __add__(self, other) -> "Dim3":
        o = self._coerce(other)
        return Dim3(self.x + o.x, self.y + o.y, self.z + o.z)

    def __sub__(self, other) -> "Dim3":
        o = self._coerce(other)
        return Dim3(self.x - o.x, self.y - o.y, self.z - o.z)

    def __mul__(self, other) -> "Dim3":
        o = self._coerce(other)
        return Dim3(self.x * o.x, self.y * o.y, self.z * o.z)

    def __floordiv__(self, other) -> "Dim3":
        o = self._coerce(other)
        return Dim3(self.x // o.x, self.y // o.y, self.z // o.z)

    def __mod__(self, other) -> "Dim3":
        o = self._coerce(other)
        return Dim3(self.x % o.x, self.y % o.y, self.z % o.z)

    def __neg__(self) -> "Dim3":
        return Dim3(-self.x, -self.y, -self.z)

    # -- queries ------------------------------------------------------------
    def flatten(self) -> int:
        """Number of points in the box (reference: dim3.hpp `flatten`)."""
        return self.x * self.y * self.z

    def all_ge(self, v: int) -> bool:
        return self.x >= v and self.y >= v and self.z >= v

    def all_lt(self, v: int) -> bool:
        return self.x < v and self.y < v and self.z < v

    def any_eq(self, v: int) -> bool:
        return self.x == v or self.y == v or self.z == v

    def min_elem(self) -> int:
        return min(self.x, self.y, self.z)

    def max_elem(self) -> int:
        return max(self.x, self.y, self.z)

    def wrap(self, lims: "Dim3") -> "Dim3":
        """Periodic wrap of each component into ``[0, lims)``
        (reference: dim3.hpp:208-230). Python's ``%`` already returns a
        non-negative result for positive moduli."""
        o = self._coerce(lims)
        return Dim3(self.x % o.x, self.y % o.y, self.z % o.z)

    # -- conversion / iteration --------------------------------------------
    def as_tuple(self) -> tuple[int, int, int]:
        return (self.x, self.y, self.z)

    def __iter__(self) -> Iterator[int]:
        yield self.x
        yield self.y
        yield self.z

    def __getitem__(self, i: int) -> int:
        return (self.x, self.y, self.z)[i]

    def __repr__(self) -> str:
        return f"Dim3({self.x},{self.y},{self.z})"


# The 26 non-zero directions of the 3x3x3 neighborhood, in the reference's
# planning order: z outer, y middle, x inner (reference: src/stencil.cu:331-333).
DIRECTIONS_26: tuple[Dim3, ...] = tuple(
    Dim3(x, y, z)
    for z in (-1, 0, 1)
    for y in (-1, 0, 1)
    for x in (-1, 0, 1)
    if (x, y, z) != (0, 0, 0)
)

FACE_DIRS: tuple[Dim3, ...] = tuple(d for d in DIRECTIONS_26 if abs(d.x) + abs(d.y) + abs(d.z) == 1)
EDGE_DIRS: tuple[Dim3, ...] = tuple(d for d in DIRECTIONS_26 if abs(d.x) + abs(d.y) + abs(d.z) == 2)
CORNER_DIRS: tuple[Dim3, ...] = tuple(d for d in DIRECTIONS_26 if abs(d.x) + abs(d.y) + abs(d.z) == 3)
