from .dim3 import CORNER_DIRS, DIRECTIONS_26, Dim3, EDGE_DIRS, FACE_DIRS
from .numeric import div_ceil, max_abs_error, next_power_of_two, prime_factors
from .partition import NodePartition, RankPartition, decompose_zy, stack_residents
from .radius import Radius
from .rect3 import Rect3
from .region import (
    compute_offset,
    exterior_regions,
    halo_extent,
    halo_pos,
    halo_rect,
    interior_region,
    raw_size,
)

__all__ = [
    "CORNER_DIRS",
    "DIRECTIONS_26",
    "Dim3",
    "EDGE_DIRS",
    "FACE_DIRS",
    "NodePartition",
    "decompose_zy",
    "RankPartition",
    "stack_residents",
    "Radius",
    "Rect3",
    "compute_offset",
    "div_ceil",
    "exterior_regions",
    "halo_extent",
    "halo_pos",
    "halo_rect",
    "interior_region",
    "max_abs_error",
    "next_power_of_two",
    "prime_factors",
    "raw_size",
]
