"""Per-direction stencil radius over the 27-cell neighborhood.

TPU-native analogue of the reference's ``Radius`` / ``DirectionMap``
(reference: include/stencil/radius.hpp:14-104,
include/stencil/direction_map.hpp:11-58).

Semantics pinned from the reference:
- ``dir(d)`` for a *face* direction is the halo width on that side; for edge
  and corner directions the stored value acts as an on/off gate for whether
  that diagonal exchange happens at all, and as a weight in the partitioner's
  interface cost — halo *extents* always use the face radii
  (reference: local_domain.cuh:212-222 uses ``radius.x(dir.x)`` etc.).
"""

from __future__ import annotations

from .dim3 import Dim3


class Radius:
    __slots__ = ("_r",)

    def __init__(self):
        # dict keyed by direction tuple (-1..1)^3
        self._r: dict[tuple[int, int, int], int] = {
            (x, y, z): 0 for x in (-1, 0, 1) for y in (-1, 0, 1) for z in (-1, 0, 1)
        }

    # -- accessors ----------------------------------------------------------
    def dir(self, x, y=None, z=None) -> int:
        if y is None:  # Dim3 or tuple
            d = Dim3.of(x)
            x, y, z = d.x, d.y, d.z
        return self._r[(x, y, z)]

    def set_dir(self, d, r: int) -> None:
        d = Dim3.of(d)
        self._r[(d.x, d.y, d.z)] = int(r)

    def x(self, d: int) -> int:
        """Face radius on the ±x side (reference: radius.hpp:25-30)."""
        return self._r[(d, 0, 0)]

    def y(self, d: int) -> int:
        return self._r[(0, d, 0)]

    def z(self, d: int) -> int:
        return self._r[(0, 0, d)]

    def __eq__(self, other) -> bool:
        return isinstance(other, Radius) and self._r == other._r

    def __hash__(self):
        return hash(tuple(sorted(self._r.items())))

    def without_x(self) -> "Radius":
        """Copy with every x-involving direction zeroed — the tight-x
        layout: no x halo columns are allocated or exchanged, the compute
        kernels form the periodic x neighborhood in-kernel (lane rolls).
        Valid only for single-block x axes with lane-aligned extents."""
        ret = Radius()
        for d, v in self._r.items():
            ret._r[d] = 0 if d[0] != 0 else v
        return ret

    # -- bulk setters (reference: radius.hpp:46-79) -------------------------
    def set_face(self, r: int) -> None:
        for d in ((1, 0, 0), (-1, 0, 0), (0, 1, 0), (0, -1, 0), (0, 0, 1), (0, 0, -1)):
            self._r[d] = int(r)

    def set_edge(self, r: int) -> None:
        for d in self._r:
            if sum(1 for c in d if c != 0) == 2:
                self._r[d] = int(r)

    def set_corner(self, r: int) -> None:
        for d in self._r:
            if sum(1 for c in d if c != 0) == 3:
                self._r[d] = int(r)

    # -- factories ----------------------------------------------------------
    @staticmethod
    def constant(r: int) -> "Radius":
        """All 26 directions get radius ``r`` (reference: radius.hpp:81-91).
        The center entry is also set to ``r`` to match the reference."""
        ret = Radius()
        for d in ret._r:
            ret._r[d] = int(r)
        return ret

    @staticmethod
    def face_edge_corner(face: int, edge: int, corner: int) -> "Radius":
        """Reference: radius.hpp:95-103 (center forced to 0)."""
        ret = Radius()
        ret.set_face(face)
        ret.set_edge(edge)
        ret.set_corner(corner)
        ret._r[(0, 0, 0)] = 0
        return ret

    # -- derived ------------------------------------------------------------
    def face_tuple(self, sign: int) -> tuple[int, int, int]:
        """(x, y, z) face radii on the ``sign`` side."""
        return (self.x(sign), self.y(sign), self.z(sign))

    def max_radius(self) -> int:
        return max(r for d, r in self._r.items() if d != (0, 0, 0))

    def __repr__(self) -> str:
        return (
            f"Radius(x={self.x(-1)}/{self.x(1)}, y={self.y(-1)}/{self.y(1)}, "
            f"z={self.z(-1)}/{self.z(1)})"
        )
