"""Global reductions over the distributed domain.

TPU-native analogue of Astaroth's three-phase device reductions
(reference: astaroth/reductions.cuh:1-60 — max/min/rms/sum over scalar
fields and vector magnitudes). On TPU a reduction is one jitted
``shard_map`` with a masked local reduce and a ``psum``/``pmax`` over the
mesh; the reference's multi-kernel tree reduction is XLA's job.

The pad-and-mask layout requires masking: pad-tail and halo cells must not
contribute. The mask is built from the per-axis logical sizes.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..domain.grid import GridSpec
from ..parallel.exchange import BLOCK_PSPEC, HaloExchange
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS_X, AXIS_Y, AXIS_Z

_AXES = (AXIS_Z, AXIS_Y, AXIS_X)


def compute_mask(spec: GridSpec) -> np.ndarray:
    """Stacked bool array marking owned compute cells of every block."""
    mask = np.zeros(spec.stacked_shape_zyx(), dtype=bool)
    off = spec.compute_offset()
    for iz in range(spec.dim.z):
        for iy in range(spec.dim.y):
            for ix in range(spec.dim.x):
                s = spec.block_size((ix, iy, iz))
                mask[
                    iz, iy, ix,
                    off.z : off.z + s.z,
                    off.y : off.y + s.y,
                    off.x : off.x + s.x,
                ] = True
    return mask


class Reductions:
    """Compiled scalar/vector reductions over a domain's stacked arrays."""

    def __init__(self, ex: HaloExchange):
        self.ex = ex
        self.mask = jax.device_put(
            jnp.asarray(compute_mask(ex.spec)), ex.sharding()
        )
        self._scal = jax.jit(self._build_scal())
        self._vec = jax.jit(self._build_vec())

    def _build_scal(self):
        def fn(arr, mask):
            m = mask
            neg_inf = -jnp.inf
            vmax = lax.pmax(jnp.max(jnp.where(m, arr, neg_inf)), _AXES)
            vmin = lax.pmin(jnp.min(jnp.where(m, arr, jnp.inf)), _AXES)
            vsum = lax.psum(jnp.sum(jnp.where(m, arr, 0.0)), _AXES)
            vsq = lax.psum(jnp.sum(jnp.where(m, arr * arr, 0.0)), _AXES)
            count = lax.psum(jnp.sum(m), _AXES)
            return vmax, vmin, vsum, jnp.sqrt(vsq / count)

        return jax.shard_map(
            fn,
            mesh=self.ex.mesh,
            in_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
            out_specs=(P(), P(), P(), P()),
        )

    def _build_vec(self):
        def fn(x, y, z, mask):
            mag = jnp.sqrt(x * x + y * y + z * z)
            m = mask
            vmax = lax.pmax(jnp.max(jnp.where(m, mag, -jnp.inf)), _AXES)
            vmin = lax.pmin(jnp.min(jnp.where(m, mag, jnp.inf)), _AXES)
            vsum = lax.psum(jnp.sum(jnp.where(m, mag, 0.0)), _AXES)
            vsq = lax.psum(jnp.sum(jnp.where(m, mag * mag, 0.0)), _AXES)
            count = lax.psum(jnp.sum(m), _AXES)
            return vmax, vmin, vsum, jnp.sqrt(vsq / count)

        return jax.shard_map(
            fn,
            mesh=self.ex.mesh,
            in_specs=(BLOCK_PSPEC,) * 4,
            out_specs=(P(), P(), P(), P()),
        )

    # reference: RTYPE_MAX / RTYPE_MIN / RTYPE_SUM / RTYPE_RMS
    def scal(self, arr):
        vmax, vmin, vsum, rms = self._scal(arr, self.mask)
        return {
            "max": float(vmax),
            "min": float(vmin),
            "sum": float(vsum),
            "rms": float(rms),
        }

    def vec(self, x, y, z):
        vmax, vmin, vsum, rms = self._vec(x, y, z, self.mask)
        return {
            "max": float(vmax),
            "min": float(vmin),
            "sum": float(vsum),
            "rms": float(rms),
        }
