"""The MHD right-hand sides: continuity, momentum, induction, entropy.

TPU-native re-derivation of Astaroth's generated DSL kernels (reference:
astaroth/user_kernels.h:376-428): isothermal-ish compressible MHD in
log-density / velocity / magnetic vector potential / specific entropy form.
All functions operate elementwise on :class:`FieldData` pytrees (value +
gradient + hessian per field) produced by :mod:`fd`; vectors are (x, y, z)
tuples of arrays. XLA fuses everything into the surrounding stencil pass.

Physics summary (same operators as the reference):
- continuity:  d lnrho/dt = -u . grad(lnrho) - div u
- induction:   d a/dt     = u x curl(a) + eta * lap(a)
- momentum:    d u/dt     = -(grad u) u - cs2*(grad ss / cp + grad lnrho)
                            + (1/rho) j x B
                            + nu*(lap u + (1/3) grad(div u) + 2 S.grad lnrho)
                            + zeta * grad(div u)
               with  cs2 = cs2_sound * exp(gamma*ss/cp + (gamma-1)*(lnrho-lnrho0)),
                     j = (grad(div a) - lap a)/mu0,  B = curl a
- entropy:     d ss/dt    = -u . grad(ss) + (1/(rho T)) * [ eta*mu0*j.j
                            + 2*rho*nu*contract(S) + zeta*rho*(div u)^2 ]
                            + heat_conduction(ss, lnrho)
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from .fd import FieldData

Vec = Tuple  # (x, y, z) of arrays


class Constants(NamedTuple):
    """The DCONST uniforms the equations read (reference: kernels.cu:9-31)."""

    cs2_sound: float
    gamma: float
    cp_sound: float
    lnrho0: float
    lnT0: float
    mu0: float
    eta: float
    nu_visc: float
    zeta: float
    chi: float = 0.001  # heat_conduction's hardcoded 0.001 (user_kernels.h:414)

    @classmethod
    def from_info(cls, info) -> "Constants":
        rp = info.real_params
        return cls(
            cs2_sound=rp["AC_cs2_sound"],
            gamma=rp["AC_gamma"],
            cp_sound=rp["AC_cp_sound"],
            lnrho0=rp["AC_lnrho0"],
            lnT0=rp["AC_lnT0"],
            mu0=rp["AC_mu0"],
            eta=rp["AC_eta"],
            nu_visc=rp["AC_nu_visc"],
            zeta=rp["AC_zeta"],
        )


# -- vector calculus on FieldData triples -------------------------------------

def vdot(a: Vec, b: Vec):
    return a[0] * b[0] + a[1] * b[1] + a[2] * b[2]


def vcross(a: Vec, b: Vec) -> Vec:
    return (
        a[1] * b[2] - a[2] * b[1],
        a[2] * b[0] - a[0] * b[2],
        a[0] * b[1] - a[1] * b[0],
    )


def value3(v: Tuple[FieldData, FieldData, FieldData]) -> Vec:
    return (v[0].value, v[1].value, v[2].value)


def divergence(v) -> "jnp.ndarray":
    """grad(v.x).x + grad(v.y).y + grad(v.z).z (user_kernels.h:230-233)."""
    return v[0].gx + v[1].gy + v[2].gz


def curl(v) -> Vec:
    """(dy vz - dz vy, dz vx - dx vz, dx vy - dy vx) (user_kernels.h:240-245)."""
    return (v[2].gy - v[1].gz, v[0].gz - v[2].gx, v[1].gx - v[0].gy)


def laplace_vec(v) -> Vec:
    return (v[0].laplace(), v[1].laplace(), v[2].laplace())


def gradient_of_divergence(v) -> Vec:
    """Column sums of the component hessians (user_kernels.h:246-251)."""
    return (
        v[0].hxx + v[1].hxy + v[2].hxz,
        v[0].hxy + v[1].hyy + v[2].hyz,
        v[0].hxz + v[1].hyz + v[2].hzz,
    )


def stress_tensor(v):
    """Traceless rate-of-strain tensor S (user_kernels.h:252-265).
    Returns the 6 unique entries as a dict."""
    sxx = (2.0 / 3.0) * v[0].gx - (1.0 / 3.0) * (v[1].gy + v[2].gz)
    sxy = 0.5 * (v[0].gy + v[1].gx)
    sxz = 0.5 * (v[0].gz + v[2].gx)
    syy = (2.0 / 3.0) * v[1].gy - (1.0 / 3.0) * (v[0].gx + v[2].gz)
    syz = 0.5 * (v[1].gz + v[2].gy)
    szz = (2.0 / 3.0) * v[2].gz - (1.0 / 3.0) * (v[0].gx + v[1].gy)
    return {"xx": sxx, "xy": sxy, "xz": sxz, "yy": syy, "yz": syz, "zz": szz}


def contract(s) -> "jnp.ndarray":
    """sum_i row_i . row_i of the symmetric S (user_kernels.h:266-275)."""
    return (
        s["xx"] ** 2 + s["yy"] ** 2 + s["zz"] ** 2
        + 2.0 * (s["xy"] ** 2 + s["xz"] ** 2 + s["yz"] ** 2)
    )


def mul_gradients(v, u: Vec) -> Vec:
    """(grad v) u — advection matrix-vector product, row i = grad(v_i) . u
    (user_kernels.h:376-381 gradients + math mul)."""
    return (
        vdot(v[0].gradient, u),
        vdot(v[1].gradient, u),
        vdot(v[2].gradient, u),
    )


# -- the four right-hand sides ------------------------------------------------

def continuity(uu, lnrho: FieldData):
    """(user_kernels.h:382-385)"""
    return -vdot(value3(uu), lnrho.gradient) - divergence(uu)


def induction(c: Constants, uu, aa) -> Vec:
    """(user_kernels.h:396-402)"""
    B = curl(aa)
    lap = laplace_vec(aa)
    uxB = vcross(value3(uu), B)
    return tuple(uxB[i] + c.eta * lap[i] for i in range(3))


def momentum(c: Constants, uu, lnrho: FieldData, ss: FieldData, aa) -> Vec:
    """(user_kernels.h:386-395)"""
    S = stress_tensor(uu)
    cs2 = c.cs2_sound * jnp.exp(
        c.gamma * ss.value / c.cp_sound + (c.gamma - 1.0) * (lnrho.value - c.lnrho0)
    )
    god_a = gradient_of_divergence(aa)
    lap_a = laplace_vec(aa)
    j = tuple((god_a[i] - lap_a[i]) / c.mu0 for i in range(3))
    B = curl(aa)
    inv_rho = jnp.exp(-lnrho.value)
    u = value3(uu)
    adv = mul_gradients(uu, u)
    jxB = vcross(j, B)
    lap_u = laplace_vec(uu)
    god_u = gradient_of_divergence(uu)
    # S . grad(lnrho), symmetric S
    g = lnrho.gradient
    S_g = (
        S["xx"] * g[0] + S["xy"] * g[1] + S["xz"] * g[2],
        S["xy"] * g[0] + S["yy"] * g[1] + S["yz"] * g[2],
        S["xz"] * g[0] + S["yz"] * g[1] + S["zz"] * g[2],
    )
    out = []
    for i in range(3):
        pressure = cs2 * (ss.gradient[i] / c.cp_sound + lnrho.gradient[i])
        visc = c.nu_visc * (lap_u[i] + god_u[i] / 3.0 + 2.0 * S_g[i])
        out.append(-adv[i] - pressure + inv_rho * jxB[i] + visc + c.zeta * god_u[i])
    return tuple(out)


def ln_temperature(c: Constants, ss: FieldData, lnrho: FieldData):
    """(user_kernels.h:403-406)"""
    return c.lnT0 + c.gamma * ss.value / c.cp_sound + (c.gamma - 1.0) * (
        lnrho.value - c.lnrho0
    )


def heat_conduction(c: Constants, ss: FieldData, lnrho: FieldData):
    """(user_kernels.h:407-416)"""
    inv_cp = 1.0 / c.cp_sound
    grad_ln_chi = tuple(-g for g in lnrho.gradient)
    first = c.gamma * inv_cp * ss.laplace() + (c.gamma - 1.0) * lnrho.laplace()
    second = tuple(
        c.gamma * inv_cp * ss.gradient[i] + (c.gamma - 1.0) * lnrho.gradient[i]
        for i in range(3)
    )
    third = tuple(
        c.gamma * (inv_cp * ss.gradient[i] + lnrho.gradient[i]) + grad_ln_chi[i]
        for i in range(3)
    )
    chi = c.chi * jnp.exp(-lnrho.value) / c.cp_sound
    return c.cp_sound * chi * (first + vdot(second, third))


def entropy(c: Constants, ss: FieldData, uu, lnrho: FieldData, aa):
    """(user_kernels.h:417-428)"""
    S = stress_tensor(uu)
    rho = jnp.exp(lnrho.value)
    inv_pT = 1.0 / (rho * jnp.exp(ln_temperature(c, ss, lnrho)))
    god_a = gradient_of_divergence(aa)
    lap_a = laplace_vec(aa)
    j = tuple((god_a[i] - lap_a[i]) / c.mu0 for i in range(3))
    div_u = divergence(uu)
    rhs = (
        c.eta * c.mu0 * vdot(j, j)
        + 2.0 * rho * c.nu_visc * contract(S)
        + c.zeta * rho * div_u * div_u
    )
    return -vdot(value3(uu), ss.gradient) + inv_pT * rhs + heat_conduction(c, ss, lnrho)
