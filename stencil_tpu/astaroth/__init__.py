"""Astaroth MHD mini-app — the "joint stencils over multiple data types"
workload (reference: astaroth/ in socal-ucr/stencil, a vendored, trimmed
copy of the Astaroth magnetohydrodynamics code driven by the halo-exchange
library).

Eight double-precision fields (lnrho, uux/y/z, ax/y/z, entropy), radius-3
halos, 6th-order centered finite differences, Williamson RK3 low-storage
integration, with the interior/exchange/exterior overlap structure per
substep."""

from .config import AcMeshInfo, load_config
from .fd import FieldData, field_data
from .integrate import make_astaroth_step, rk3_integrate

__all__ = [
    "AcMeshInfo",
    "FieldData",
    "field_data",
    "load_config",
    "make_astaroth_step",
    "rk3_integrate",
]
