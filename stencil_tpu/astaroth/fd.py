"""6th-order centered finite differences over halo-padded blocks.

TPU-native re-derivation of Astaroth's derivative stencils (reference:
astaroth/user_kernels.h:36-127 — first/second/cross derivative pencils of
STENCIL_ORDER 6). The reference gathers a 7-point pencil per thread; here
each derivative is a sum of shifted array slices over a whole region, which
XLA fuses into one bandwidth-bound pass (and prunes any derivative an
equation never consumes).

All functions take the full padded block (leading dims allowed, data dims
``[z, y, x]`` with >= 3 cells of halo) and a ``Rect3`` in allocation-local
coordinates selecting the cells to produce.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..geometry import Rect3

# centered-difference coefficients (reference: user_kernels.h:38-66)
FIRST_COEFFS = (3.0 / 4.0, -3.0 / 20.0, 1.0 / 60.0)
SECOND_CENTER = -49.0 / 18.0
SECOND_COEFFS = (3.0 / 2.0, -3.0 / 20.0, 1.0 / 90.0)
CROSS_COEFFS = (270.0 / 720.0, -27.0 / 720.0, 2.0 / 720.0)


def _sh(arr, rect: Rect3, dz: int, dy: int, dx: int):
    return arr[
        ...,
        slice(rect.lo.z + dz, rect.hi.z + dz),
        slice(rect.lo.y + dy, rect.hi.y + dy),
        slice(rect.lo.x + dx, rect.hi.x + dx),
    ]


def _first(arr, rect, axis_shift, inv_ds):
    """axis_shift(i) -> (dz, dy, dx) for offset i along the axis."""
    res = 0.0
    for i, c in enumerate(FIRST_COEFFS, start=1):
        res = res + c * (_sh(arr, rect, *axis_shift(i)) - _sh(arr, rect, *axis_shift(-i)))
    return res * inv_ds


def _second(arr, rect, axis_shift, inv_ds):
    res = SECOND_CENTER * _sh(arr, rect, 0, 0, 0)
    for i, c in enumerate(SECOND_COEFFS, start=1):
        res = res + c * (_sh(arr, rect, *axis_shift(i)) + _sh(arr, rect, *axis_shift(-i)))
    return res * inv_ds * inv_ds


def _cross(arr, rect, shift_a, shift_b, inv_ds_a, inv_ds_b):
    """Cross derivative from the two diagonal pencils
    (reference: user_kernels.h:62-75)."""
    res = 0.0
    for i, c in enumerate(CROSS_COEFFS, start=1):
        res = res + c * (
            _sh(arr, rect, *shift_a(i))
            + _sh(arr, rect, *shift_a(-i))
            - _sh(arr, rect, *shift_b(i))
            - _sh(arr, rect, *shift_b(-i))
        )
    return res * inv_ds_a * inv_ds_b


def derx(arr, rect, inv_dsx):
    return _first(arr, rect, lambda i: (0, 0, i), inv_dsx)


def dery(arr, rect, inv_dsy):
    return _first(arr, rect, lambda i: (0, i, 0), inv_dsy)


def derz(arr, rect, inv_dsz):
    return _first(arr, rect, lambda i: (i, 0, 0), inv_dsz)


def derxx(arr, rect, inv_dsx):
    return _second(arr, rect, lambda i: (0, 0, i), inv_dsx)


def deryy(arr, rect, inv_dsy):
    return _second(arr, rect, lambda i: (0, i, 0), inv_dsy)


def derzz(arr, rect, inv_dsz):
    return _second(arr, rect, lambda i: (i, 0, 0), inv_dsz)


def derxy(arr, rect, inv_dsx, inv_dsy):
    return _cross(
        arr, rect, lambda i: (0, i, i), lambda i: (0, -i, i), inv_dsx, inv_dsy
    )


def derxz(arr, rect, inv_dsx, inv_dsz):
    return _cross(
        arr, rect, lambda i: (i, 0, i), lambda i: (-i, 0, i), inv_dsx, inv_dsz
    )


def deryz(arr, rect, inv_dsy, inv_dsz):
    return _cross(
        arr, rect, lambda i: (i, i, 0), lambda i: (-i, i, 0), inv_dsy, inv_dsz
    )


@dataclass
class FieldData:
    """value + gradient + symmetric hessian of one scalar field over a
    region (reference: user_kernels.h AcRealData / read_data)."""

    value: Any
    gx: Any
    gy: Any
    gz: Any
    hxx: Any
    hxy: Any
    hxz: Any
    hyy: Any
    hyz: Any
    hzz: Any

    @property
    def gradient(self):
        return (self.gx, self.gy, self.gz)

    def laplace(self):
        """trace of the hessian (reference: user_kernels.h:226-229)."""
        return self.hxx + self.hyy + self.hzz


def field_data(arr, rect: Rect3, inv_ds) -> FieldData:
    """Build value/gradient/hessian for one field over ``rect``.

    ``inv_ds`` is (inv_dsx, inv_dsy, inv_dsz)."""
    ix, iy, iz = inv_ds
    return FieldData(
        value=_sh(arr, rect, 0, 0, 0),
        gx=derx(arr, rect, ix),
        gy=dery(arr, rect, iy),
        gz=derz(arr, rect, iz),
        hxx=derxx(arr, rect, ix),
        hxy=derxy(arr, rect, ix, iy),
        hxz=derxz(arr, rect, ix, iz),
        hyy=deryy(arr, rect, iy),
        hyz=deryz(arr, rect, iy, iz),
        hzz=derzz(arr, rect, iz),
    )
