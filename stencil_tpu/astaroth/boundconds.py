"""Symmetric / antisymmetric / periodic boundary conditions.

TPU-native counterpart of the reference's boundary-condition kernels
(reference: astaroth/boundconds.cuh). Semantics implemented as *intended*
by the reference's index math (``src = 2*bound - dst``, mirroring about
the first/last interior cell, sign +1 symmetric / -1 antisymmetric):

    ghost[b0 - g] = sign * field[b0 + g]      (low side,  g = 1..r)
    ghost[b1 + g] = sign * field[b1 - g]      (high side)

Two reference caveats, preserved here as documentation rather than
behavior: (a) the kernels are vestigial — ``astaroth.cu`` never calls
them, the driver is periodic-only via the stencil library's exchange;
(b) the reference's actual write line is
``vtxbuf[dst] = sign*vtxbuf[src] * 0.0 + 1.0`` (boundconds.cuh:127),
i.e. the mirror is multiplied away and the ghost is set to the constant
1.0 — a disabled/debug state. We implement the real mirror, which is what
any non-periodic Astaroth run needs.

These operate on a padded [.., z, y, x] block along axes whose partition
has a single block (a *domain* boundary is a *block* boundary only
there); multi-block non-periodic axes would need masked exchange and are
out of scope exactly as in the reference (Topology is periodic-only,
src/topology.cpp:10-17).
"""

from __future__ import annotations

from typing import Dict

from ..domain.grid import GridSpec
from ..ops.halo_fill import _axis_geom

SYMMETRIC = "symmetric"
ANTISYMMETRIC = "antisymmetric"
PERIODIC = "periodic"

_AXIS_DIM = {"z": -3, "y": -2, "x": -1}


def _take(arr, dim: int, idx: int):
    sl = [slice(None)] * arr.ndim
    sl[dim] = idx
    return arr[tuple(sl)]


def _put(arr, dim: int, idx: int, value):
    sl = [slice(None)] * arr.ndim
    sl[dim] = idx
    return arr.at[tuple(sl)].set(value)


def apply_mirror(arr, spec: GridSpec, axis: str, sign: int):
    """Fill both ghost zones of ``axis`` by mirroring about the boundary
    cells (reference: boundconds.cuh:44-111 index math).

    ``arr`` is a padded block with leading dims allowed; the axis must
    have a single block in the partition."""
    if axis == "x":
        n_blocks = spec.dim.x
    elif axis == "y":
        n_blocks = spec.dim.y
    else:
        n_blocks = spec.dim.z
    if n_blocks != 1:
        raise ValueError(
            f"non-periodic {axis} boundary needs a single block on that axis"
        )
    o, sz, (rm, rp) = _axis_geom(spec, axis)
    dim = arr.ndim + _AXIS_DIM[axis]
    b0 = o  # first interior cell (boundloc0, boundconds.cuh:31)
    b1 = o + sz - 1  # last interior cell (boundloc1)
    for g in range(1, rm + 1):
        arr = _put(arr, dim, b0 - g, sign * _take(arr, dim, b0 + g))
    for g in range(1, rp + 1):
        arr = _put(arr, dim, b1 + g, sign * _take(arr, dim, b1 - g))
    return arr


def symmetric(arr, spec: GridSpec, axis: str):
    """sign=+1 (reference: acKernelSymmetricBoundconds)."""
    return apply_mirror(arr, spec, axis, +1)


def antisymmetric(arr, spec: GridSpec, axis: str):
    """sign=-1 (reference: acKernelAntisymmetricBoundconds)."""
    return apply_mirror(arr, spec, axis, -1)


def apply_boundconds(arr, spec: GridSpec, kinds: Dict[str, str]):
    """Apply per-axis boundary conditions to a padded block.

    ``kinds`` maps axis name ('x'/'y'/'z') to SYMMETRIC/ANTISYMMETRIC/
    PERIODIC; PERIODIC axes are left to the halo exchange (the driver's
    only mode, astaroth.conf bcs)."""
    for axis, kind in kinds.items():
        if kind == PERIODIC:
            continue
        if kind == SYMMETRIC:
            arr = symmetric(arr, spec, axis)
        elif kind == ANTISYMMETRIC:
            arr = antisymmetric(arr, spec, axis)
        else:
            raise ValueError(f"unknown boundary condition {kind!r}")
    return arr
