"""Williamson RK3 integration and the fused distributed Astaroth step.

TPU-native re-design of the reference's integration driver (reference:
astaroth/integration.cuh:14-49 ``rk3_integrate``; astaroth/kernels.cu:62-87
``integrate_substep`` dispatch; astaroth/astaroth.cu:551-663 iteration
structure): per iteration, three RK3 substeps each do
{interior integrate -> halo exchange -> exterior integrate}, then the
in/out buffers swap once. The reference's 1 + 26 CUDA streams per domain
become dataflow inside one jitted program: the interior sweep of each
substep depends only on pre-exchange data, so XLA can overlap the halo
``ppermute``s with it.

Note on semantics: this vendored workload evaluates all three stage rates
on the same ``in`` state (buffers swap per *iteration*, not per substep —
astaroth.cu:642-648). We replicate that for benchmark parity; pass
``swap_per_substep=True`` for textbook low-storage RK3 feeding each stage
forward.

A consequence worth stating (but deliberately NOT exploited): with the in
buffers constant across substeps, all three stages compute the *same*
rate field, so the reference-mode iteration is algebraically one Euler
step ``out = curr + K*dt*rate(curr)`` with
``K = b2*(1 - a2*(1 - a1)) = 1.525``. Collapsing the three substeps to
one would make this benchmark ~3x faster while producing identical
output, but it would no longer perform the work the reference's driver
performs (three full kernel passes, astaroth.cu:556-641), so the
recorded numbers keep the 3-substep structure.
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..geometry import Dim3, Rect3, exterior_regions, interior_region
from ..parallel.exchange import BLOCK_PSPEC, HaloExchange
from .config import AcMeshInfo
from .equations import Constants, continuity, entropy, induction, momentum
from .fd import field_data

FIELDS = ("lnrho", "uux", "uuy", "uuz", "ax", "ay", "az", "entropy")

# the fused-substep sliding-window vocabulary (ops/pallas_astaroth.py);
# distinct from the exchange-plan kernel_variant ("fused"/"persistent")
_VARIANTS = ("shift", "ring")


def _check_variant(kernel_variant) -> None:
    """Loud validation of the substep window variant at step-BUILD time,
    env-var default included — off-TPU the Pallas kernel (which owns the
    in-kernel check) never builds, and a typo'd STENCIL_ASTAROTH_VARIANT
    must not silently run the default discipline."""
    v = kernel_variant or os.environ.get("STENCIL_ASTAROTH_VARIANT")
    if v is not None and v not in _VARIANTS:
        raise ValueError(
            f"unknown astaroth kernel variant {v!r} (--kernel-variant / "
            f"STENCIL_ASTAROTH_VARIANT): valid values are {_VARIANTS}")

# Williamson (1980) low-storage coefficients (reference: integration.cuh:19-21)
RK3_ALPHA = (0.0, -5.0 / 9.0, -153.0 / 128.0)
RK3_BETA = (1.0 / 3.0, 15.0 / 16.0, 8.0 / 15.0)


def rk3_integrate(step_number: int, state_previous, state_current, rate_of_change, dt):
    """One low-storage RK3 stage (reference: integration.cuh:14-38).

    ``state_previous`` is the out-buffer value (the previous stage's
    output), ``state_current`` the in-buffer value."""
    beta = RK3_BETA[step_number]
    if step_number == 0:
        return state_current + beta * rate_of_change * dt
    alpha = RK3_ALPHA[step_number]
    prev_beta = RK3_BETA[step_number - 1]
    return state_current + beta * (
        alpha / prev_beta * (state_current - state_previous) + rate_of_change * dt
    )


def _rects_slices(rect: Rect3):
    return (
        ...,
        slice(rect.lo.z, rect.hi.z),
        slice(rect.lo.y, rect.hi.y),
        slice(rect.lo.x, rect.hi.x),
    )


def _integrate_region(
    substep: int,
    rect: Rect3,
    inv_ds,
    c: Constants,
    dt,
    curr: Dict[str, jax.Array],
    out: Dict[str, jax.Array],
    mask=None,
) -> Dict[str, jax.Array]:
    """Integrate one region: read curr fields' derivatives over ``rect``,
    RK3-update the region in the out buffers (reference: solve<step> kernel,
    user_kernels.h:437-469). ``mask`` (broadcastable to the region) keeps
    ``out``'s prior value where False — the masked-interior write of the
    uneven-partition overlap path (shell extents are per-block there, so
    the interior cannot be a static shrunk rect)."""
    lnrho = field_data(curr["lnrho"], rect, inv_ds)
    uu = tuple(field_data(curr[k], rect, inv_ds) for k in ("uux", "uuy", "uuz"))
    aa = tuple(field_data(curr[k], rect, inv_ds) for k in ("ax", "ay", "az"))
    ss = field_data(curr["entropy"], rect, inv_ds)

    sl = _rects_slices(rect)
    rates = {"lnrho": continuity(uu, lnrho)}
    ind = induction(c, uu, aa)
    mom = momentum(c, uu, lnrho, ss, aa)
    for i, k in enumerate(("ax", "ay", "az")):
        rates[k] = ind[i]
    for i, k in enumerate(("uux", "uuy", "uuz")):
        rates[k] = mom[i]
    rates["entropy"] = entropy(c, ss, uu, lnrho, aa)

    new_out = {}
    for k in FIELDS:
        updated = rk3_integrate(substep, out[k][sl], curr[k][sl], rates[k], dt)
        if mask is not None:
            updated = jnp.where(mask, updated, out[k][sl])
        new_out[k] = out[k].at[sl].set(updated.astype(out[k].dtype))
    return new_out


def _integrate_region_dyn(spec, substep, lo, size, inv_ds, c, dt, curr, out,
                          out_read=None):
    """Integrate one dynamic-offset boundary shell ``[lo, lo + size)``
    (allocation-local z/y/x, ``lo`` may be traced — uneven partitions): the
    exterior pass when per-block extents are static only per block index.
    Slices a (size + 2·3)-halo slab of every field, runs the same
    :func:`_integrate_region` math over it, and writes the core back.

    ``out_read`` is the state_previous source. Dynamic shells overlap at
    edges/corners (cross-sections span the base extents), so all patches of
    one substep must read the SAME pre-patch out — overlapping writes then
    compute identical values, where reading the accumulating ``out`` would
    double-apply the RK3 stage at overlap cells for substeps > 0."""
    h = 3
    p = spec.padded()
    slab_lo = (lo[0] - h, lo[1] - h, lo[2] - h)
    slab_sz = (size[0] + 2 * h, size[1] + 2 * h, size[2] + 2 * h)

    def slab(a):
        return lax.dynamic_slice(a.reshape(p.z, p.y, p.x), slab_lo, slab_sz)

    curr_s = {k: slab(v) for k, v in curr.items()}
    out_s = {k: slab(v) for k, v in (out_read or out).items()}
    rect = Rect3(Dim3(h, h, h), Dim3(h + size[2], h + size[1], h + size[0]))
    new_s = _integrate_region(substep, rect, inv_ds, c, dt, curr_s, out_s)
    core = (slice(h, h + size[0]), slice(h, h + size[1]), slice(h, h + size[2]))
    res = {}
    for k in FIELDS:
        o3 = out[k].reshape(p.z, p.y, p.x)
        res[k] = lax.dynamic_update_slice(o3, new_s[k][core], lo).reshape(
            out[k].shape
        )
    return res


def _integrate_shell_wrap_x(substep, rect, inv_ds, c, dt, curr, out):
    """:func:`_integrate_region` for a shell rect spanning the FULL x
    extent of a tight-x block (``Radius.without_x``: no x halo columns, the
    x axis is single-block periodic): a thin x-wrapped slab is materialized
    for the shell's z/y reach and the same region math runs over it. Shells
    are r-thick faces, so the extended slab is small."""
    h = _H
    zsl = slice(rect.lo.z - h, rect.hi.z + h)
    ysl = slice(rect.lo.y - h, rect.hi.y + h)

    def ext(a):
        sl = a[(..., zsl, ysl, slice(None))]
        return jnp.concatenate([sl[..., -h:], sl, sl[..., :h]], axis=-1)

    curr_s = {k: ext(v) for k, v in curr.items()}
    out_s = {k: ext(v) for k, v in out.items()}
    dz = rect.hi.z - rect.lo.z
    dy = rect.hi.y - rect.lo.y
    nx = rect.hi.x - rect.lo.x
    rect_s = Rect3(Dim3(h, h, h), Dim3(h + nx, h + dy, h + dz))
    new_s = _integrate_region(substep, rect_s, inv_ds, c, dt, curr_s, out_s)
    res = {}
    core = (..., slice(h, h + dz), slice(h, h + dy), slice(h, h + nx))
    dst = (..., slice(rect.lo.z, rect.hi.z), slice(rect.lo.y, rect.hi.y),
           slice(rect.lo.x, rect.hi.x))
    for k in FIELDS:
        res[k] = out[k].at[dst].set(new_s[k][core].astype(out[k].dtype))
    return res


_H = 3  # 6th-order stencil reach (reference: astaroth.h STENCIL_ORDER 6)


def uses_pallas(ex: HaloExchange, use_pallas, dtype="float32") -> bool:
    """Whether :func:`make_astaroth_step` will take the fused Pallas path
    for fields of ``dtype`` (None = auto: TPU, fp32, aligned blocks;
    uneven partitions run the kernel over the padded base extents with
    dynamic-shell overlap). Resident (oversubscribed) shards keep the
    fused kernel — it runs once per stacked block (VERDICT r4 item 7;
    uneven + resident stays on the XLA path, the dynamic-shell machinery
    is single-resident)."""
    if use_pallas is not None:
        return bool(use_pallas)
    import jax.numpy as jnp

    from ..ops.pallas_astaroth import substep_supported

    devs = ex.mesh.devices.flatten()
    if ex.oversubscribed and not ex.spec.is_uniform():
        return False
    return (
        all(d.platform == "tpu" for d in devs)
        and substep_supported(ex.spec, jnp.dtype(dtype))
    )


def make_astaroth_step(
    ex: HaloExchange,
    info: AcMeshInfo,
    dt: float = 1e-8,
    overlap: bool = True,
    swap_per_substep: bool = False,
    iters: int = 1,
    use_pallas=None,
    dtype="float32",
    interpret: bool = False,
    kernel_variant: str = None,
):
    """Build the jitted iteration: ``fn(curr, nxt) -> (curr, nxt)`` where
    curr/nxt are dicts of stacked sharded field arrays. Runs ``iters``
    iterations of 3 substeps in one compiled program; the dt=1e-8 default
    matches the reference driver (astaroth.cu:578).

    ``use_pallas`` (None = auto, see :func:`uses_pallas`; ``dtype`` is the
    field dtype the step will be driven with) selects the fused VMEM
    substep kernel (ops/pallas_astaroth.py). The Pallas path exchanges
    once per iteration — legitimate because the in buffers do not change
    between substeps in reference swap-per-iteration mode, and
    re-exchanged before every substep in swap_per_substep mode. With
    ``overlap`` on a multi-block mesh, that one exchange is scheduled
    concurrently with substep 0's full-region kernel pass (which reads
    pre-exchange data); the multi-block-axis shells of substep 0 are then
    re-integrated from the exchanged halos — the reference's
    interior/exterior overlap re-expressed as dataflow with the fused
    kernel as the interior.

    ``kernel_variant`` selects the fused kernel's sliding-window
    discipline: ``"shift"`` (plane-copy window shifts) or ``"ring"``
    (shift-free modular-slot rotation — ops/pallas_astaroth.py module
    docstring). ``None`` reads ``STENCIL_ASTAROTH_VARIANT`` (default
    ``shift``) so the A/B runs without touching call sites."""
    spec = ex.spec
    r = spec.radius
    _check_variant(kernel_variant)
    if min(r.y(-1), r.y(1), r.z(-1), r.z(1)) < 3:
        raise ValueError("astaroth needs face radius >= 3 (6th-order "
                         "stencils)")
    pallas_on = uses_pallas(ex, use_pallas, dtype)
    tight_x = min(r.x(-1), r.x(1)) < 3
    if tight_x:
        # zero-x-radius tight layout (Radius.without_x): no x halo columns;
        # only the fused kernel can form the periodic x pencils (lane
        # rolls), and only on a single-BLOCK x axis — y/z may have any
        # number of blocks (their overlap shells integrate over x-wrapped
        # slabs, _integrate_shell_wrap_x)
        if not (r.x(-1) == 0 and r.x(1) == 0 and spec.dim.x == 1):
            raise ValueError(
                "x radius must be 3+ (inline halos) or exactly 0 (tight "
                "layout, single-block x axis)"
            )
        if not spec.is_uniform():
            raise ValueError(
                "tight-x with multi-block y/z requires uniform splits"
            )
        if not pallas_on:
            raise ValueError(
                "tight-x astaroth requires the fused Pallas path"
            )
    inv_ds = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    c = Constants.from_info(info)
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)
    interior = interior_region(compute, r)
    exteriors = exterior_regions(compute, interior)
    use_overlap = overlap and spec.is_uniform()
    # uneven partitions keep the overlap structure via per-block dynamic
    # geometry (ops/shells.py): masked interior write + dynamic-offset
    # shells, the analogue of the reference's per-LocalDomain regions
    # (src/stencil.cu:878-977). Resident (oversubscribed) shards carry a
    # stacked leading block dim the shell machinery's (pz,py,px) reshape
    # cannot express — serialized exchange-then-sweep instead of a
    # trace-time crash (ADVICE r3).
    use_dyn_overlap = overlap and not spec.is_uniform() and not ex.oversubscribed

    def _dyn_geometry():
        from ..ops.shells import dyn_block_sizes, interior_mask, shell_regions

        sizes = dyn_block_sizes(spec)
        inc = (True, True, True)  # pre-exchange halos are stale on all sides
        return interior_mask(spec, sizes, inc), shell_regions(spec, sizes, inc)

    if pallas_on:
        from ..ops.pallas_astaroth import make_pallas_substep
        from ..parallel.mesh import MESH_AXES

        variant = kernel_variant or os.environ.get(
            "STENCIL_ASTAROTH_VARIANT", "shift"
        )
        # interpret mode (CI integration tests): the pallas HLO interpreter
        # cannot propagate varying-manual-axes metadata, so drop the vma
        # annotations and disable shard_map's vma check for this step
        kernels = [
            make_pallas_substep(
                spec, c, inv_ds, s, dt,
                vma=None if interpret else MESH_AXES,
                interpret=interpret,
                variant=variant,
            )
            for s in range(3)
        ]
        p = spec.padded()
        nres = ex.resident.flatten()

        def to3(d):
            return tuple(d[k].reshape(p.z, p.y, p.x) for k in FIELDS)

        def untuple(vals, like):
            return {k: v.reshape(like[k].shape) for k, v in zip(FIELDS, vals)}

        def run_kernel(s, curr, out):
            """One fused substep over the shard. Resident (oversubscribed)
            shards stack whole padded blocks along the leading block dims;
            the per-block kernel runs once per resident, each block's
            halos filled by the resident-shift exchange phases (the
            reference's same-GPU fast path under oversubscription,
            tx_cuda.cuh:41-113)."""
            if nres == 1:
                return untuple(kernels[s](to3(curr), to3(out)), out)
            cf = tuple(curr[k].reshape(nres, p.z, p.y, p.x) for k in FIELDS)
            of = tuple(out[k].reshape(nres, p.z, p.y, p.x) for k in FIELDS)
            res = [
                kernels[s](tuple(c[j] for c in cf), tuple(o[j] for o in of))
                for j in range(nres)
            ]
            return {
                k: jnp.stack([res[j][i] for j in range(nres)]).reshape(
                    out[k].shape
                )
                for i, k in enumerate(FIELDS)
            }

        def exchange_all(curr):
            return ex.exchange_blocks(curr)

        # overlapped fast path: substep 0's kernel pass reads PRE-exchange
        # halos on EVERY axis (this kernel has no in-kernel wrap — the
        # wrap-in-kernel experiment was measured and removed, BASELINE.md),
        # so every side's shell must be re-integrated from the exchanged
        # state, self-wrap axes included: exactly the XLA path's
        # ``exteriors`` rects.
        multi_block = spec.dim.flatten() > 1

        def iteration(curr, out):
            if swap_per_substep:
                # textbook mode: every substep consumes a fresh exchange, so
                # nothing can be computed ahead of it (and substeps 1/2
                # would need the pre-update out at shell cells, which the
                # in-place kernel destroys) — exchange-then-compute
                for s in range(3):
                    curr = exchange_all(curr)
                    out = run_kernel(s, curr, out)
                    curr, out = out, curr
                return curr, out
            # reference swap-per-iteration mode: the in buffers are constant
            # across substeps, so the iteration's single exchange can fly
            # while substep 0 computes the full region from PRE-exchange
            # data (reference: interior integrate concurrent with
            # dd.exchange(), astaroth.cu:551-641). Substep 0's RK3 stage
            # never reads the out buffer, so re-integrating the
            # multi-block-axis shells from the exchanged halos afterwards
            # is exact; substeps 1 and 2 read post-exchange data directly.
            if use_overlap and multi_block:
                out = run_kernel(0, curr, out)
                curr = exchange_all(curr)
                for rect in exteriors:
                    if tight_x:
                        out = _integrate_shell_wrap_x(
                            0, rect, inv_ds, c, dt, curr, out
                        )
                    else:
                        out = _integrate_region(0, rect, inv_ds, c, dt, curr, out)
            elif use_dyn_overlap:
                # uneven partition: same structure, shells at per-block
                # dynamic offsets (substep 0 never reads out, so the full
                # kernel pass before the shells is exact)
                out = run_kernel(0, curr, out)
                curr = exchange_all(curr)
                _, shells = _dyn_geometry()
                for lo, size in shells:
                    out = _integrate_region_dyn(
                        spec, 0, lo, size, inv_ds, c, dt, curr, out
                    )
            else:
                curr = exchange_all(curr)
                out = run_kernel(0, curr, out)
            for s in (1, 2):
                out = run_kernel(s, curr, out)
            return out, curr  # one swap per iteration (astaroth.cu:642-648)

    else:
        def hoisted_overlap_iteration(curr, out):
            """Reference swap-per-iteration mode, XLA path: the SAME
            hoisted-exchange dataflow the Pallas iteration uses. Substep 0
            integrates the full region from PRE-exchange data (never reads
            out, so re-integrating boundary shells from the exchanged
            state afterwards is exact); the iteration's single exchange is
            free to fly concurrently; substeps 1-2 read post-exchange
            data. 9 integrate bodies per iteration instead of the
            per-substep structure's 21 — which is also what makes
            fp64-on-TPU OVERLAP compile: the round-3 bounded negative
            (32^3 fp64 overlap > 25 min compile, scripts/probe_f64_overlap
            .py) was the 7-region x 3-substep op-graph under f64's ~10x
            emulation expansion, not fp64 itself."""
            out = _integrate_region(0, compute, inv_ds, c, dt, curr, out)
            # exchange_blocks: the 8 same-dtype fields ride packed
            # quantity-batched carriers (one ppermute pair per axis phase
            # for the whole dict); reads pre-update curr only, so the
            # overlap-as-dataflow structure is unchanged
            curr = ex.exchange_blocks(curr)
            for rect in exteriors:
                out = _integrate_region(0, rect, inv_ds, c, dt, curr, out)
            for s in (1, 2):
                out = _integrate_region(s, compute, inv_ds, c, dt, curr, out)
            return out, curr  # one swap per iteration (astaroth.cu:642-648)

        def substep_block(substep, curr, out):
            if use_overlap:
                out = _integrate_region(substep, interior, inv_ds, c, dt, curr, out)
                curr = ex.exchange_blocks(curr)
                for rect in exteriors:
                    out = _integrate_region(substep, rect, inv_ds, c, dt, curr, out)
            elif use_dyn_overlap:
                # masked interior write (shell cells keep the pre-update out
                # that substeps > 0 read as state_previous), exchange, then
                # dynamic-offset shells from the exchanged halos
                imask, shells = _dyn_geometry()
                out = _integrate_region(
                    substep, compute, inv_ds, c, dt, curr, out, mask=imask
                )
                curr = ex.exchange_blocks(curr)
                out_read = out
                for lo, size in shells:
                    out = _integrate_region_dyn(
                        spec, substep, lo, size, inv_ds, c, dt, curr, out,
                        out_read=out_read,
                    )
            else:
                curr = ex.exchange_blocks(curr)
                out = _integrate_region(substep, compute, inv_ds, c, dt, curr, out)
            return curr, out

        def iteration(curr, out):
            if use_overlap and not swap_per_substep:
                return hoisted_overlap_iteration(curr, out)
            for substep in range(3):
                curr, out = substep_block(substep, curr, out)
                if swap_per_substep:
                    curr, out = out, curr
            if not swap_per_substep:
                # reference workload: one swap per iteration (astaroth.cu:642-648)
                curr, out = out, curr
            return curr, out

    def entry_fn(curr, out):
        if iters == 1:
            return iteration(curr, out)
        return lax.fori_loop(0, iters, lambda _, co: iteration(co[0], co[1]), (curr, out))

    fn = jax.shard_map(
        entry_fn,
        mesh=ex.mesh,
        in_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
        out_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
        check_vma=not interpret,
    )
    return jax.jit(fn, donate_argnums=(0, 1))


def make_fused_astaroth_loop(
    ex: HaloExchange,
    info: AcMeshInfo,
    iters: int = 1,
    dt: float = 1e-8,
    use_pallas=None,
    dtype="float32",
    interpret: bool = False,
    kernel_variant: str = None,
):
    """The FUSED REMOTE_DMA astaroth iteration (ROADMAP #5's 8-field
    fold-in): ``loop(curr, out) -> (curr, out)`` over field dicts,
    host-chunked like the jacobi fused path.

    Same hoisted dataflow as :func:`make_astaroth_step`'s reference
    swap-per-iteration overlap mode — substep 0's full-region pass reads
    PRE-exchange data, the iteration's single exchange flies behind it,
    substep 0's boundary shells re-integrate from the exchanged halos,
    substeps 1-2 read post-exchange data — but the exchange is the fused
    per-direction kernel-initiated schedule (``HaloExchange(fused=True)``;
    astaroth's 6th-order cross-derivative pencils read edge halos, which
    is exactly why the fused geometry is the 26-direction exact-extent
    message set: every diagonal ships concurrently too). Zero
    collective-permutes in every compiled piece; output bit-identical to
    the composed overlap step (tests/test_fused_stencil.py).

    On TPU the compute passes are the ring-indexed Pallas multistep
    kernels (``kernel_variant="ring"`` — ops/pallas_astaroth.py) run
    between the fused start/wait, so 8-field MHD overlaps the same way;
    off-TPU the XLA region math runs. Uniform single-resident partitions
    only (loud); the fused-into-one-kernel astaroth substep is the
    hardware session's follow-up, staged behind probe_remote_dma.py."""
    from ..parallel.exchange import Method

    spec = ex.spec
    r = spec.radius
    _check_variant(kernel_variant)
    if ex.method != Method.REMOTE_DMA or not getattr(ex, "fused", False):
        raise ValueError(
            "make_fused_astaroth_loop needs HaloExchange(Method.REMOTE_DMA,"
            " fused=True)"
        )
    if min(r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1)) < 3:
        raise ValueError("astaroth needs face radius >= 3 (6th-order "
                         "stencils; the fused path keeps inline halos)")
    if not spec.is_uniform() or ex.oversubscribed:
        raise ValueError(
            "the fused astaroth loop takes uniform single-resident "
            "partitions today (uneven/oversubscribed stay on the "
            "composed paths)"
        )
    inv_ds = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    c = Constants.from_info(info)
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)
    interior = interior_region(compute, r)
    exteriors = exterior_regions(compute, interior)
    pallas_on = uses_pallas(ex, use_pallas, dtype)

    if pallas_on:
        from ..ops.pallas_astaroth import make_pallas_substep
        from ..parallel.mesh import MESH_AXES

        variant = kernel_variant or os.environ.get(
            "STENCIL_ASTAROTH_VARIANT", "ring"
        )
        kernels = [
            make_pallas_substep(
                spec, c, inv_ds, s, dt,
                vma=None if interpret else MESH_AXES,
                interpret=interpret, variant=variant,
            )
            for s in range(3)
        ]
        p = spec.padded()

        def full_body(s, curr, out):
            vals = kernels[s](
                tuple(curr[k].reshape(p.z, p.y, p.x) for k in FIELDS),
                tuple(out[k].reshape(p.z, p.y, p.x) for k in FIELDS),
            )
            return {k: v.reshape(out[k].shape)
                    for k, v in zip(FIELDS, vals)}
    else:
        def full_body(s, curr, out):
            return _integrate_region(s, compute, inv_ds, c, dt, curr, out)

    def shells_body(curr, out):
        for rect in exteriors:
            out = _integrate_region(0, rect, inv_ds, c, dt, curr, out)
        return out

    def _smap(fn):
        return jax.jit(jax.shard_map(
            fn, mesh=ex.mesh,
            in_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
            out_specs=BLOCK_PSPEC, check_vma=not interpret,
        ))

    full_fns = [_smap(lambda cu, o, s=s: full_body(s, cu, o))
                for s in range(3)]
    shells_fn = _smap(shells_body)

    def loop(curr, out):
        from ..obs import telemetry
        from ..parallel.remote_emu import run_fused_substep

        rec = telemetry.get()
        emu = ex._fused_host_schedule
        t_interior = 0.0
        t_total = 0.0
        for _ in range(iters):
            cur2, out, t_int, t_tot = run_fused_substep(
                emu, curr,
                interior=lambda: full_fns[0](curr, out),
                boundary=lambda c2, o: shells_fn(c2, o),
                rec=rec,
            )
            for s in (1, 2):
                out = full_fns[s](cur2, out)
            t_interior += t_int
            t_total += t_tot
            # one swap per iteration (astaroth.cu:642-648)
            curr, out = out, cur2
        if rec.enabled and t_total > 0:
            rec.gauge("fused.overlap_fraction", t_interior / t_total,
                      phase="exchange", variant="fused")
        return curr, out

    return loop


def make_batched_astaroth_step(spec, info: AcMeshInfo, dt: float = 1e-8,
                               iters: int = 1, sharding=None):
    """The multi-tenant batched astaroth iteration (XLA path):
    ``fn(curr, out) -> (curr, out)`` over dicts of ``(B, pz, py, px)``
    stacked tenant fields, each tenant an independent single-block
    periodic MHD box.

    ``spec`` describes ONE tenant (``GridSpec(size, Dim3(1, 1, 1),
    Radius.constant(3))``); the leading batch axis stacks B tenants.
    Per iteration the reference swap-per-iteration structure runs once:
    the halo fill is the per-tenant periodic self-wrap
    (ops/halo_fill.wrap_fill_batched — composed x->y->z order, so the
    6th-order cross-stencils see edge/corner halos identical to a
    single-block ``HaloExchange``), substep 0 integrates the full
    compute region from the exchanged state, substeps 1-2 read the same
    in buffers, and the buffers swap once. ``_integrate_region`` already
    rides leading dims (its slices open with ``...``), so every lane is
    bit-identical to the single-domain ``make_astaroth_step`` hoisted
    overlap iteration (tests/test_campaign.py pins it).

    ``sharding`` splits the batch axis over a 1-D device mesh — the
    program has zero collectives, so one jit serves B tenants across the
    whole mesh. Buffers are not donated (campaign stash semantics)."""
    from ..geometry import Dim3 as _D3
    from ..ops.halo_fill import wrap_fill_batched

    r = spec.radius
    if spec.dim != _D3(1, 1, 1):
        raise ValueError(
            f"batched tenants are single-block domains; got partition "
            f"{spec.dim}"
        )
    if min(r.x(-1), r.x(1), r.y(-1), r.y(1), r.z(-1), r.z(1)) < 3:
        raise ValueError("astaroth needs face radius >= 3 (6th-order "
                         "stencils)")
    inv_ds = (
        info.real_params["AC_inv_dsx"],
        info.real_params["AC_inv_dsy"],
        info.real_params["AC_inv_dsz"],
    )
    c = Constants.from_info(info)
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)

    def iteration(curr, out):
        curr = {k: wrap_fill_batched(spec, v) for k, v in curr.items()}
        out = _integrate_region(0, compute, inv_ds, c, dt, curr, out)
        for s in (1, 2):
            out = _integrate_region(s, compute, inv_ds, c, dt, curr, out)
        return out, curr  # one swap per iteration (astaroth.cu:642-648)

    def entry_fn(curr, out):
        if iters == 1:
            return iteration(curr, out)
        return lax.fori_loop(
            0, iters, lambda _, co: iteration(co[0], co[1]), (curr, out))

    if sharding is None:
        return jax.jit(entry_fn)
    sh = {k: sharding for k in FIELDS}
    return jax.jit(entry_fn, in_shardings=(sh, sh), out_shardings=(sh, sh))
