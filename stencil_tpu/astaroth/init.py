"""Field initializers for the Astaroth workload, vectorized on host.

TPU-native re-implementation of the reference's init kernels
(reference: astaroth/astaroth.cu:20-245): hash-random (splitmix64-style
avalanche per coordinate), constant, sine wave, and the radial-explosion
velocity shell. All produce global [z, y, x] numpy arrays to be scattered
with ``shard_blocks``; values are bit-deterministic functions of the global
coordinate, so any partition yields the same field.
"""

from __future__ import annotations

import numpy as np

from ..geometry import Dim3


def _hash64(x: np.ndarray) -> np.ndarray:
    """splitmix64-style avalanche (reference: astaroth.cu:84-89)."""
    x = x.astype(np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def hash_init(global_size, dtype=np.float64) -> np.ndarray:
    """'Bad' deterministic random in [-1, 1] from hashed coordinates
    (reference: astaroth.cu:92-114)."""
    g = Dim3.of(global_size)
    z, y, x = np.meshgrid(
        np.arange(g.z, dtype=np.uint64),
        np.arange(g.y, dtype=np.uint64),
        np.arange(g.x, dtype=np.uint64),
        indexing="ij",
        sparse=True,
    )
    h = _hash64(x) ^ _hash64(y) ^ _hash64(z)
    # float32 quotient then double shift, like the reference's T=double path
    val = (h.astype(np.float32) / np.float32(np.uint64(0xFFFFFFFFFFFFFFFF))).astype(
        np.float64
    )
    return ((val - 0.5) * 2).astype(dtype)


def const_init(global_size, value, dtype=np.float64) -> np.ndarray:
    """(reference: astaroth.cu:117-133)"""
    g = Dim3.of(global_size)
    return np.full((g.z, g.y, g.x), value, dtype=dtype)


def sin_init(global_size, ampl=0.0001, period=16, dtype=np.float64) -> np.ndarray:
    """Sine wave along y (reference: astaroth.cu:53-75)."""
    g = Dim3.of(global_size)
    y = np.arange(g.y, dtype=dtype)
    val = ampl * np.sin(y.astype(np.float32) * 2 * np.pi / period)
    return np.broadcast_to(val[None, :, None], (g.z, g.y, g.x)).astype(dtype)


def radial_explosion_init(
    global_size,
    ds=(0.04908738521,) * 3,
    ampl_uu=1.0,
    shell_radius=0.8,
    width=0.2,
    origin=None,
    dtype=np.float64,
):
    """Gaussian velocity shell pointing radially outward; returns
    (uux, uuy, uuz) global arrays (reference: astaroth.cu:136-245).

    The reference computes spherical angles with quadrant case analysis and
    then converts back; the same result comes directly from the unit radial
    vector: uu_i = uu_radial * (r_i / |r|).
    """
    g = Dim3.of(global_size)
    dsx, dsy, dsz = ds
    if origin is None:
        origin = (0.01, 32 * dsy, 50 * dsz)  # reference: astaroth.cu:150
    z, y, x = np.meshgrid(
        np.arange(g.z, dtype=dtype),
        np.arange(g.y, dtype=dtype),
        np.arange(g.x, dtype=dtype),
        indexing="ij",
        sparse=True,
    )
    xx = x * dsx - origin[0]
    yy = y * dsy - origin[1]
    zz = z * dsz - origin[2]
    rr = np.sqrt(xx**2 + yy**2 + zz**2)
    uu_radial = ampl_uu * np.exp(-((rr - shell_radius) ** 2) / (2.0 * width**2))
    with np.errstate(invalid="ignore", divide="ignore"):
        inv_rr = np.where(rr > 0, 1.0 / np.where(rr > 0, rr, 1.0), 0.0)
    uu_radial = np.where(rr > 0, uu_radial, 0.0)
    uux = (uu_radial * xx * inv_rr).astype(dtype)
    uuy = (uu_radial * yy * inv_rr).astype(dtype)
    uuz = (uu_radial * zz * inv_rr).astype(dtype)
    return uux, uuy, uuz
