"""Astaroth configuration: key = value file parser with derived parameters
and an uninitialized-value check.

TPU-native re-implementation of the reference's config machinery
(reference: astaroth/astaroth_utils.cu:23-123 — ``parse_config``,
``acHostUpdateBuiltinParams`` derived params, and ``acLoadConfig``'s
0xFF-poison uninitialized detection; astaroth/astaroth.conf). Instead of
poisoning raw struct bytes, every known parameter starts as ``None`` and
``load_config`` reports which stayed unset.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, fields
from typing import Dict, List, Optional, Tuple

STENCIL_ORDER = 6  # reference: astaroth/astaroth.h:9

# Parameters read from astaroth.conf (reference: user_defines.h int/real
# param tables). Anything not listed is ignored with a warning, like
# find_str returning -1 in the reference parser.
INT_PARAMS = (
    "AC_nx", "AC_ny", "AC_nz",
    "AC_max_steps", "AC_save_steps", "AC_bin_steps", "AC_start_step",
    "AC_bc_type_top_x", "AC_bc_type_top_y", "AC_bc_type_top_z",
    "AC_bc_type_bot_x", "AC_bc_type_bot_y", "AC_bc_type_bot_z",
)
REAL_PARAMS = (
    "AC_dsx", "AC_dsy", "AC_dsz",
    "AC_dt", "AC_max_time", "AC_cdt", "AC_cdtv", "AC_cdts",
    "AC_nu_visc", "AC_cs_sound", "AC_zeta", "AC_eta", "AC_mu0", "AC_chi",
    "AC_relhel", "AC_forcing_magnitude", "AC_kmin", "AC_kmax",
    "AC_switch_accretion",
    "AC_cp_sound", "AC_gamma", "AC_lnT0", "AC_lnrho0",
    "AC_sink_pos_x", "AC_sink_pos_y", "AC_sink_pos_z",
    "AC_M_sink_Msun", "AC_soft", "AC_accretion_range",
    "AC_unit_velocity", "AC_unit_density", "AC_unit_length",
    "AC_ampl_lnrho", "AC_ampl_uu", "AC_bin_save_t",
)


@dataclass
class AcMeshInfo:
    """Parameter set with the reference's derived-parameter rules."""

    int_params: Dict[str, Optional[int]] = field(
        default_factory=lambda: {k: None for k in INT_PARAMS}
    )
    real_params: Dict[str, Optional[float]] = field(
        default_factory=lambda: {k: None for k in REAL_PARAMS}
    )

    def __getitem__(self, key: str):
        if key in self.int_params:
            return self.int_params[key]
        if key in self.real_params:
            return self.real_params[key]
        raise KeyError(key)

    def __setitem__(self, key: str, value) -> None:
        if key in self.int_params:
            self.int_params[key] = int(value)
        elif key in self.real_params:
            self.real_params[key] = float(value)
        else:
            raise KeyError(key)

    # derived params (reference: astaroth_utils.cu:52-88)
    def update_builtin_params(self) -> None:
        ip, rp = self.int_params, self.real_params
        if any(ip.get(k) is None for k in ("AC_nx", "AC_ny", "AC_nz")):
            return  # leave missing extents for the poison report
        ip["AC_mx"] = ip["AC_nx"] + STENCIL_ORDER
        ip["AC_my"] = ip["AC_ny"] + STENCIL_ORDER
        ip["AC_mz"] = ip["AC_nz"] + STENCIL_ORDER
        ip["AC_nx_min"] = STENCIL_ORDER // 2
        ip["AC_nx_max"] = ip["AC_nx_min"] + ip["AC_nx"]
        ip["AC_ny_min"] = STENCIL_ORDER // 2
        ip["AC_ny_max"] = ip["AC_ny"] + STENCIL_ORDER // 2
        ip["AC_nz_min"] = STENCIL_ORDER // 2
        ip["AC_nz_max"] = ip["AC_nz"] + STENCIL_ORDER // 2
        for a in ("x", "y", "z"):
            if rp.get(f"AC_ds{a}") is not None:
                rp[f"AC_inv_ds{a}"] = 1.0 / rp[f"AC_ds{a}"]
        ip["AC_mxy"] = ip["AC_mx"] * ip["AC_my"]
        ip["AC_nxy"] = ip["AC_nx"] * ip["AC_ny"]
        ip["AC_nxyz"] = ip["AC_nxy"] * ip["AC_nz"]
        # cs2 (reference: user_kernels.h AC_cs2_sound = cs^2)
        if rp.get("AC_cs_sound") is not None:
            rp["AC_cs2_sound"] = rp["AC_cs_sound"] ** 2

    def uninitialized(self) -> List[str]:
        """Names of parameters never set (the poison check,
        astaroth_utils.cu:100-120)."""
        missing = [k for k, v in self.int_params.items() if v is None]
        missing += [k for k, v in self.real_params.items() if v is None]
        return missing


_LINE_RE = re.compile(r"^\s*([A-Za-z_][A-Za-z0-9_]*)\s*=\s*([^\s/]+)")


def parse_config(text: str, info: AcMeshInfo) -> None:
    """Parse ``key = value`` lines; ``//`` and ``/* */`` comments ignored
    (reference: astaroth_utils.cu:23-48)."""
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    for line in text.splitlines():
        line = line.split("//")[0]
        m = _LINE_RE.match(line)
        if not m:
            continue
        key, value = m.group(1), m.group(2)
        if key in info.int_params:
            info.int_params[key] = int(float(value))
        elif key in info.real_params:
            info.real_params[key] = float(value)
        # unknown keys ignored, like the reference's find_str miss


def load_config(path: str) -> Tuple[AcMeshInfo, bool]:
    """Returns (info, ok). ``ok`` is False if any parameter stayed unset
    (the reference's AC_FAILURE poison result)."""
    info = AcMeshInfo()
    with open(path) as f:
        parse_config(f.read(), info)
    info.update_builtin_params()
    return info, not info.uninitialized()
