"""Static analysis of the repo's own load-bearing contracts.

The reference enforces its invariants mechanically — every CUDA/NVML
call goes through the ``CUDA_RUNTIME()``/``NVML()`` checking macros —
while this repo's contracts historically lived in prose and scattered
test pins. This package makes them machine-checked:

- :mod:`.astlint` — an AST-walking lint engine with repo-specific rules
  (pure-stdlib file-path-loaded modules, the telemetry name vocabulary,
  the tmp+fsync+rename atomic-write protocol, ``assert``-for-validation
  in public APIs, unprefixed ``{placeholder}`` strings at raise/log
  sites, host syncs inside traced step-loop code);
- :mod:`.verify_plan` — the ExchangePlan IR vs compiled-HLO conformance
  auditor: sweeps partition x method x dtype x Q configs and cross-checks
  the IR's census/byte/DMA predictions against the compiled truth;
- :mod:`.jit_audit` — the step-loop audit: a guarded loop run under
  ``jax.transfer_guard`` + a compile counter, failing on any post-warmup
  recompilation or implicit device-to-host transfer.

Front end: ``python -m stencil_tpu.apps.lint_tool {lint,verify-plan,
jit-audit,all}``; CI gate: ``scripts/ci_static_gate.py``.
"""

from .astlint import (  # noqa: F401
    Finding,
    RULES,
    lint_paths,
    load_baseline,
    write_baseline,
)
