"""ExchangePlan IR vs compiled-HLO conformance auditor.

The ExchangePlan IR (plan/ir.py) *predicts* what each lowering puts on
the interconnect — ``collectives_per_exchange``, ``wire_bytes``,
``dmas_per_exchange`` — and the autotuner ranks candidates on those
predictions without compiling them. The lowering (parallel/exchange.py)
is required to compile to exactly what the plan says; historically that
contract was pinned by a handful of hand-written counts in
tests/test_plan_ir.py. This module makes it a *sweepable gate*: for a
grid of partition x method x dtype x Q configs it compiles each lowering
and cross-checks the IR's predictions against the compiled truth:

- predicted ``collectives_per_exchange`` == the compiled program's
  ``collective-permute`` census count (``utils/hlo_check``), for every
  method — composed / direct26 / auto-spmd (the round-7 "partitioner
  reinvents the composed schedule per quantity" finding, encoded) /
  remote-dma (ZERO by construction, censused over every compiled piece);
- predicted ``wire_bytes`` == the census byte total for the ppermute
  methods (exact on one-block-per-device meshes — the scope this sweep
  stays in; the model documents its oversubscription overestimate);
- no collective kind beyond ``collective-permute`` ever appears;
- for REMOTE_DMA, the emulated per-neighbor transfer count equals
  ``dmas_per_exchange x ndev`` (each device issues the plan's per-device
  copies) and the census carries zero collective bytes;
- for the persistent whole-chunk variant (``remote-dma+persistent``,
  audited at chunk depth k = 2 with the radius*k deep halo), one real
  chunk additionally runs through the persistent loop and the MEASURED
  ``last_launches_per_chunk`` must equal the plan's
  ``launches_per_chunk(k)`` prediction — the launch-count census the
  cost model prices and the CI gate pins.

One schema-valid JSON verdict per config (``analysis.plan_verdict``
records through obs/telemetry when a recorder is attached; the same
dicts via :func:`run_sweep`'s return), so drift between plan/ir.py and
parallel/exchange.py trips a sweep instead of a post-mortem.

Infeasible configs (not enough local devices, radius too thick for the
partition) are SKIPPED loudly via ``plan/cost.feasible`` — the same
constraint authority realize() uses — and a sweep that analyzed nothing
is exit code 2 at the CLI, never a silent pass.

``perturb_*`` knobs offset a prediction before comparison — the CI
gate's proof that the auditor actually trips when the IR drifts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import telemetry

# Default sweep: every method on the canonical 2x2x2 partition plus an
# anisotropic (1, 2, 4) split (self-wrap x phase), at Q = 1, a batched
# Q = 3, and a mixed fp32+fp64 dict (two dtype groups) — the corners
# where the carrier-count predictions differ per method. All
# one-block-per-device: the scope where the byte model is exact.
DEFAULT_PARTITIONS: Tuple[Tuple[int, int, int], ...] = ((2, 2, 2), (1, 2, 4))
DEFAULT_QSETS: Tuple[Tuple[str, ...], ...] = (
    ("float32",),
    ("float32", "float32", "float32"),
    ("float32", "float32", "float64"),
)
DEFAULT_SIZE = 16
DEFAULT_RADIUS = 2


@dataclass
class Verdict:
    """One config's audit outcome. ``checks`` rows are
    ``{name, predicted, actual, ok}``; ``skipped`` configs carry the
    infeasibility reason instead."""

    label: str
    method: str
    ok: bool = True
    skipped: bool = False
    reason: str = ""
    checks: List[dict] = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "kind": "plan-verdict", "label": self.label,
            "method": self.method, "ok": self.ok,
            "skipped": self.skipped, "reason": self.reason,
            "checks": self.checks,
        }


# The fused compute+exchange variant audits as a fifth "method" label:
# method remote-dma with kernel_variant=fused (its lowering — the
# concurrent per-direction transport — has its own census/byte/DMA
# predictions to conform to).
FUSED_METHOD_LABEL = "remote-dma+fused"

# The persistent whole-chunk variant is the sixth label: method
# remote-dma with kernel_variant=persistent at multistep_k=2 (the
# minimum chunk depth — the spec realizes radius*2 halos through
# plan/cost.feasible exactly as realize() would). Beyond the shared
# zero-collective/DMA-count checks, its audit runs one real chunk loop
# and cross-checks the MEASURED ``ex.last_launches_per_chunk`` against
# the plan's ``launches_per_chunk(k)`` prediction — the launch census
# as a conformance-audited prediction, not just a telemetry gauge.
PERSISTENT_METHOD_LABEL = "remote-dma+persistent"


def sweep_configs(
    size: int = DEFAULT_SIZE,
    radius: int = DEFAULT_RADIUS,
    partitions: Sequence[Tuple[int, int, int]] = DEFAULT_PARTITIONS,
    methods: Optional[Sequence[str]] = None,
    qsets: Sequence[Sequence[str]] = DEFAULT_QSETS,
) -> List[dict]:
    """The sweep grid as plain dicts (label, size, radius, partition,
    method, dtypes). Default methods: every ``plan.ir.METHODS`` entry
    PLUS the variant labels ``remote-dma+fused`` and
    ``remote-dma+persistent``."""
    from ..plan.ir import METHODS

    known = tuple(METHODS) + (FUSED_METHOD_LABEL, PERSISTENT_METHOD_LABEL)
    methods = list(methods or known)
    unknown = sorted(set(methods) - set(known))
    if unknown:
        raise ValueError(f"unknown method(s): {', '.join(unknown)} "
                         f"(known: {', '.join(known)})")
    out = []
    for part in partitions:
        for dtypes in qsets:
            for method in methods:
                px, py, pz = part
                short = "+".join(
                    f"{n}x{dt.replace('float', 'f')}"
                    for dt, n in sorted(
                        {d: list(dtypes).count(d) for d in set(dtypes)}
                        .items()))
                out.append({
                    "label": f"{size}^3/{px}x{py}x{pz}/{method}/{short}",
                    "size": int(size), "radius": int(radius),
                    "partition": tuple(part), "method": method,
                    "dtypes": tuple(dtypes),
                })
    return out


def _check(checks: List[dict], name: str, predicted, actual) -> bool:
    ok = predicted == actual
    checks.append({"name": name, "predicted": predicted,
                   "actual": actual, "ok": ok})
    return ok


def audit_config(cfg: dict, devices=None,
                 perturb_collectives: int = 0,
                 perturb_wire: int = 0,
                 perturb_dmas: int = 0) -> Verdict:
    """Compile one config's exchange and cross-check the IR predictions.

    Feasibility goes through ``plan/cost.feasible`` (the realize()
    constraint authority): an infeasible config returns a skipped
    verdict with the reason, never a traceback.
    """
    import jax

    from ..parallel import HaloExchange, Method, grid_mesh
    from ..parallel.exchange import shard_blocks
    from ..plan.cost import feasible
    from ..plan.ir import (FUSED_VARIANT, PERSISTENT_VARIANT, PlanChoice,
                           PlanConfig, REMOTE_DMA)

    devices = list(devices) if devices is not None else jax.devices()
    v = Verdict(label=cfg["label"], method=cfg["method"])
    fused = cfg["method"] == FUSED_METHOD_LABEL
    persistent = cfg["method"] == PERSISTENT_METHOD_LABEL
    method = REMOTE_DMA if (fused or persistent) else cfg["method"]
    size, dtypes = cfg["size"], list(cfg["dtypes"])
    import numpy as np

    from ..geometry import Dim3, Radius

    radius = Radius.constant(cfg["radius"])
    nblocks = cfg["partition"][0] * cfg["partition"][1] * cfg["partition"][2]
    if nblocks > len(devices):
        v.skipped = True
        v.ok = False
        v.reason = (f"partition {cfg['partition']} needs {nblocks} "
                    f"devices; {len(devices)} available")
        return v
    config = PlanConfig.make(Dim3(size, size, size), radius, dtypes,
                             nblocks, devices[0].platform)
    choice = PlanChoice(
        partition=cfg["partition"], method=method,
        kernel_variant=(PERSISTENT_VARIANT if persistent
                        else FUSED_VARIANT if fused else None),
        # persistent IS temporal fusion: k=2 is its minimum depth, and
        # feasible() scales the realized radius to radius*k — the deep
        # halo the audited exchange actually stages
        multistep_k=2 if persistent else 1)
    feas = feasible(config, choice)
    if feas is None:
        v.skipped = True
        v.ok = False
        v.reason = (f"infeasible for this config (plan/cost.feasible: "
                    f"partition {cfg['partition']} with radius "
                    f"{cfg['radius']} on {nblocks} device(s))")
        return v
    spec, mesh_dim, _resident = feas
    mesh = grid_mesh(spec.dim, devices[:nblocks])
    ex = HaloExchange(spec, mesh, Method(method), fused=fused,
                      persistent=persistent)
    g = spec.global_size
    base = np.arange(g.x * g.y * g.z, dtype=np.float64).reshape(
        g.z, g.y, g.x)
    state = {i: shard_blocks((base + i).astype(dt), spec, mesh)
             for i, dt in enumerate(dtypes)}
    census = ex.collective_census(state)
    plan = ex.plan
    nq = len(dtypes)
    ngroups = len(set(dtypes))
    itemsizes = [np.dtype(d).itemsize for d in dtypes]
    floating = [bool(np.issubdtype(np.dtype(d), np.floating))
                for d in dtypes]

    predicted_coll = plan.collectives_per_exchange(nq, ngroups) \
        + perturb_collectives
    predicted_wire = plan.wire_bytes(itemsizes, floating=floating) \
        + perturb_wire
    predicted_dmas = plan.dmas_per_exchange(nq, ngroups) + perturb_dmas

    actual_coll = census.get("collective-permute", (0, 0))[0]
    actual_bytes = sum(b for _c, b in census.values())
    stray = {k: c for k, (c, _b) in census.items()
             if k != "collective-permute" and c}

    ok = _check(v.checks, "collectives_per_exchange",
                predicted_coll, actual_coll)
    ok &= _check(v.checks, "stray_collective_kinds", {}, stray)
    if method == REMOTE_DMA:
        # the transport bypasses XLA collectives entirely (fused
        # variant included): the census must carry ZERO bytes, and the
        # wire prediction is cross-checked through the emulated
        # per-neighbor transfer count instead
        ok &= _check(v.checks, "census_bytes", 0, actual_bytes)
        ex(state)  # one real (emulated) exchange counts its transfers
        actual_transfers = ex._remote.last_transfer_count
        ok &= _check(v.checks, "dma_transfers",
                     predicted_dmas * nblocks, actual_transfers)
        if persistent:
            # the launch census as a conformance-audited PREDICTION:
            # run one real k=2 chunk through the persistent loop and
            # require the measured dispatches-per-chunk to equal the
            # plan's launches_per_chunk(k) — the figure cost.score
            # prices and the CI gate pins
            from ..ops.jacobi import make_jacobi_loop

            import jax.numpy as jnp

            loop = make_jacobi_loop(ex, 2, standard_spheres=False,
                                    temporal_k=2)
            sel = shard_blocks(
                np.zeros((g.z, g.y, g.x), dtype=np.int32), spec, mesh)
            loop(state[0], jnp.zeros_like(state[0]), sel)
            ok &= _check(v.checks, "launches_per_chunk",
                         plan.launches_per_chunk(2),
                         ex.last_launches_per_chunk)
    else:
        ok &= _check(v.checks, "wire_bytes", predicted_wire, actual_bytes)
    v.ok = bool(ok)
    return v


# -- placement conformance (the topology-aware PlanChoice leg) ---------------


def placement_permutations(ndev: int, count: int = 3):
    """``count`` deterministic NON-identity permutations of ``ndev``
    mesh positions: reversal, rotation by one, and pairwise swaps —
    the fixed fixture set the placement-parity gate sweeps (no RNG: a
    CI failure must reproduce)."""
    from ..plan.ir import validate_placement

    perms = []
    rev = tuple(range(ndev - 1, -1, -1))
    rot = tuple((i + 1) % ndev for i in range(ndev))
    swap = list(range(ndev))
    for i in range(0, ndev - 1, 2):
        # adjacent pairs swap; an odd ndev leaves the tail FIXED (the
        # naive i+1/i-1 formula maps the last even index out of range —
        # not a permutation at all)
        swap[i], swap[i + 1] = swap[i + 1], swap[i]
    candidates = [rev, rot, tuple(swap)]
    k = 2
    while k < ndev:
        candidates.append(tuple((i + k) % ndev for i in range(ndev)))
        k += 1
    for p in candidates:
        if len(perms) >= count:
            break
        # a broken fixture must never reach the auditor as a FAILED
        # verdict on a healthy build
        if (p != tuple(range(ndev)) and p not in perms
                and validate_placement(p, ndev) is None):
            perms.append(p)
    return perms


def _expected_flat_pairs(plan, mesh_dim):
    """The compiled program's predicted collective-permute pair sets —
    one frozenset of flattened (src, tgt) logical ids per expected op —
    derived from the plan's axis phases (the logical schedule is
    placement-INVARIANT: a placement rebinds which physical device sits
    behind each logical id, never the schedule). AXIS_COMPOSED,
    single-resident scope."""
    from ..geometry import Dim3

    md = Dim3.of(mesh_dim)

    def lin(x, y, z):
        return x + y * md.x + z * md.x * md.y

    out = []
    axis_n = {"x": md.x, "y": md.y, "z": md.z}
    for phase in plan.axis_phases:
        if axis_n[phase.axis] <= 1 or not phase.active:
            continue
        for step, active in ((1, phase.rm > 0), (-1, phase.rp > 0)):
            if not active:
                continue
            pairs = set()
            for z in range(md.z):
                for y in range(md.y):
                    for x in range(md.x):
                        c = {"x": x, "y": y, "z": z}
                        d = dict(c)
                        d[phase.axis] = ((c[phase.axis] + step)
                                         % axis_n[phase.axis])
                        pairs.add((lin(x, y, z),
                                   lin(d["x"], d["y"], d["z"])))
            out.append(frozenset(pairs))
    return out


def audit_placement(size: int, radius: int,
                    partition: Tuple[int, int, int],
                    placement: Tuple[int, ...],
                    devices=None) -> Verdict:
    """One permutation's placement-conformance audit (AXIS_COMPOSED):

    - the realized mesh's device order IS the permuted assignment
      (mesh position i hosts ``devices[placement[i]]``);
    - the compiled ``source_target_pairs`` match the plan's predicted
      logical pair sets — so pair (s, t) rides the physical link
      ``devices[placement[s]] -> devices[placement[t]]``, i.e. the
      compiled schedule lands exactly on the permuted assignment;
    - the exchanged field is bit-identical to the identity placement
      (placement moves BLOCKS, never values).
    """
    import jax
    import numpy as np

    from ..geometry import Dim3, Radius
    from ..parallel import HaloExchange, Method, grid_mesh
    from ..parallel.exchange import shard_blocks, unshard_blocks
    from ..utils.hlo_check import collective_permute_pairs

    devices = list(devices) if devices is not None else jax.devices()
    px, py, pz = partition
    ndev = px * py * pz
    label = (f"{size}^3/{px}x{py}x{pz}/placement="
             + "-".join(str(v) for v in placement))
    v = Verdict(label=label, method="axis-composed")
    if ndev > len(devices):
        v.skipped = True
        v.ok = False
        v.reason = (f"partition {partition} needs {ndev} devices; "
                    f"{len(devices)} available")
        return v
    from ..domain.grid import GridSpec

    spec = GridSpec(Dim3(size, size, size), Dim3(*partition),
                    Radius.constant(radius))
    base = devices[:ndev]
    arranged = [base[placement[i]] for i in range(ndev)]
    mesh = grid_mesh(spec.dim, arranged, ordered=True)
    mesh_id = grid_mesh(spec.dim, base, ordered=True)

    actual_order = [d.id for d in mesh.devices.flatten()]
    expected_order = [base[placement[i]].id for i in range(ndev)]
    ok = _check(v.checks, "mesh_device_order", expected_order,
                actual_order)

    ex = HaloExchange(spec, mesh, Method.AXIS_COMPOSED)
    ex_id = HaloExchange(spec, mesh_id, Method.AXIS_COMPOSED)
    g = spec.global_size
    field = np.arange(g.x * g.y * g.z, dtype=np.float32).reshape(
        g.z, g.y, g.x)
    state = {0: shard_blocks(field, spec, mesh)}
    state_id = {0: shard_blocks(field, spec, mesh_id)}

    txt = ex._compiled.lower(state).compile().as_text()
    actual_pairs = sorted(collective_permute_pairs(txt),
                          key=lambda s: sorted(s))
    expected_pairs = sorted(_expected_flat_pairs(ex.plan, spec.dim),
                            key=lambda s: sorted(s))
    ok &= _check(v.checks, "source_target_pairs",
                 [sorted(p) for p in expected_pairs],
                 [sorted(p) for p in actual_pairs])

    out = unshard_blocks(ex(state)[0], spec)
    out_id = unshard_blocks(ex_id(state_id)[0], spec)
    ok &= _check(v.checks, "bit_identical_to_identity", True,
                 bool(out.tobytes() == out_id.tobytes()))
    v.ok = bool(ok)
    return v


def run_placement_sweep(count: int = 3, size: int = DEFAULT_SIZE,
                        radius: int = DEFAULT_RADIUS,
                        partition: Tuple[int, int, int] = (2, 2, 2),
                        devices=None,
                        rec: Optional["telemetry.Recorder"] = None) -> Dict:
    """Audit ``count`` non-identity placements (the ISSUE-15 gate:
    census pairs must match the permuted assignment, results bit-
    identical). Emits the same ``analysis.plan_verdict`` vocabulary as
    the method sweep."""
    rec = rec or telemetry.get()
    ndev = partition[0] * partition[1] * partition[2]
    verdicts: List[Verdict] = []
    for perm in placement_permutations(ndev, count):
        with rec.span("analysis.verify_plan", phase="analysis",
                      method="axis-composed"):
            try:
                v = audit_placement(size, radius, partition, perm,
                                    devices=devices)
            except Exception as e:  # an auditor crash is a FAILED config
                v = Verdict(
                    label=f"placement={'-'.join(str(i) for i in perm)}",
                    method="axis-composed", ok=False,
                    reason=f"{type(e).__name__}: {e}")
        verdicts.append(v)
        rec.meta("analysis.plan_verdict", method=v.method, ok=int(v.ok),
                 label=v.label, skipped=int(v.skipped),
                 reason=v.reason or None)
        if not v.ok and not v.skipped:
            rec.counter("analysis.plan_mismatch", value=1,
                        phase="analysis", method=v.method)
    checked = [v for v in verdicts if not v.skipped]
    failed = [v for v in checked if not v.ok]
    skipped = [v for v in verdicts if v.skipped]
    rec.meta("analysis.plan_sweep", checked=len(checked),
             failed=len(failed), skipped=len(skipped))
    return {
        "verdicts": verdicts,
        "checked": len(checked),
        "failed": len(failed),
        "skipped": len(skipped),
    }


# -- hierarchy conformance (the ISSUE-17 ICI+DCN leg) ------------------------

# Hierarchical inner methods the DCN audit sweeps: the overlapped
# composed schedule plus the sequential REMOTE_DMA family (the fused
# variant's exchange program included). The persistent variant's
# EXCHANGE program is the plain REMOTE_DMA one, so it rides that row.
HIERARCHY_INNER_METHODS: Tuple[str, ...] = (
    "axis-composed", "remote-dma", FUSED_METHOD_LABEL)


def hierarchy_sweep_configs(
    size: int = DEFAULT_SIZE,
    radius: int = DEFAULT_RADIUS,
    partitions: Sequence[Tuple[int, int, int]] = DEFAULT_PARTITIONS,
    hosts: int = 2,
    methods: Optional[Sequence[str]] = None,
    qsets: Sequence[Sequence[str]] = DEFAULT_QSETS,
) -> List[dict]:
    """The hierarchical sweep grid: every partition whose z extent the
    host count divides (z is the slowest-varying mesh coordinate, so the
    identity device order groups each z segment onto one contiguous
    host — no composed placement needed for the audit fixture), crossed
    with the hierarchical inner methods and dtype sets."""
    methods = list(methods or HIERARCHY_INNER_METHODS)
    unknown = sorted(set(methods) - set(HIERARCHY_INNER_METHODS))
    if unknown:
        raise ValueError(
            f"unknown hierarchical method(s): {', '.join(unknown)} "
            f"(known: {', '.join(HIERARCHY_INNER_METHODS)})")
    if hosts < 2:
        raise ValueError(f"hierarchy audit needs hosts >= 2, got {hosts}")
    out = []
    for part in partitions:
        px, py, pz = part
        if pz % hosts:
            continue  # the z split must land whole segments per host
        for dtypes in qsets:
            for method in methods:
                short = "+".join(
                    f"{n}x{dt.replace('float', 'f')}"
                    for dt, n in sorted(
                        {d: list(dtypes).count(d) for d in set(dtypes)}
                        .items()))
                out.append({
                    "label": (f"{size}^3/{px}x{py}x{pz}/h=z{hosts}"
                              f"/{method}/{short}"),
                    "size": int(size), "radius": int(radius),
                    "partition": tuple(part), "method": method,
                    "dtypes": tuple(dtypes),
                    "hierarchy": ("z", int(hosts)),
                })
    return out


def audit_hierarchy(cfg: dict, devices=None,
                    perturb_dcn: int = 0) -> Verdict:
    """Audit one hierarchical config's DCN level against its plan.

    Requires a multi-host fabric (real processes or
    ``STENCIL_VIRTUAL_HOSTS`` — :func:`run_hierarchy_sweep` sets the
    emulation up). Checks, on top of compiling both levels:

    - predicted ``dcn_transfers_per_exchange x carriers`` equals the
      transport's executed cross-host copy count
      (``last_transfer_count`` — the DCN analogue of the DMA audit);
    - predicted ``dcn_wire_bytes`` equals the executed carrier bytes
      (exact on the one-block-per-device meshes this sweep stays in);
    - the INNER census pins are unchanged: the hierarchical census's
      collective-permute (count, bytes) equals the flat plan's, and no
      stray collective kind appears (the DCN level compiles zero
      collectives);
    - for the REMOTE_DMA family, the inner emulated transfer count
      still equals ``dmas_per_exchange x ndev`` (host-segmented wrap
      pairs move exactly what the flat ring moved);
    - the exchanged field is bit-identical to the flat lowering for
      every quantity (hierarchy moves the SAME halos, only over a
      different transport).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..geometry import Dim3, Radius
    from ..parallel import HaloExchange, Method, grid_mesh
    from ..parallel.exchange import shard_blocks, unshard_blocks
    from ..plan.cost import feasible
    from ..plan.ir import (FUSED_VARIANT, PlanChoice, PlanConfig,
                           REMOTE_DMA, validate_hierarchy)

    devices = list(devices) if devices is not None else jax.devices()
    v = Verdict(label=cfg["label"], method=cfg["method"])
    fused = cfg["method"] == FUSED_METHOD_LABEL
    method = REMOTE_DMA if fused else cfg["method"]
    size, dtypes = cfg["size"], list(cfg["dtypes"])
    hierarchy = tuple(cfg["hierarchy"])
    radius = Radius.constant(cfg["radius"])
    nblocks = cfg["partition"][0] * cfg["partition"][1] * cfg["partition"][2]
    if nblocks > len(devices):
        v.skipped = True
        v.ok = False
        v.reason = (f"partition {cfg['partition']} needs {nblocks} "
                    f"devices; {len(devices)} available")
        return v
    config = PlanConfig.make(Dim3(size, size, size), radius, dtypes,
                             nblocks, devices[0].platform)
    choice = PlanChoice(
        partition=cfg["partition"], method=method,
        kernel_variant=FUSED_VARIANT if fused else None,
        hierarchy=hierarchy)
    feas = feasible(config, choice)
    if feas is None:
        v.skipped = True
        v.ok = False
        v.reason = (f"infeasible for this config (plan/cost.feasible: "
                    f"partition {cfg['partition']} with radius "
                    f"{cfg['radius']} on {nblocks} device(s))")
        return v
    spec, mesh_dim, _resident = feas
    herr = validate_hierarchy(hierarchy, mesh_dim)
    if herr is not None:
        v.skipped = True
        v.ok = False
        v.reason = herr
        return v
    mesh = grid_mesh(spec.dim, devices[:nblocks])
    ex_h = HaloExchange(spec, mesh, Method(method), fused=fused,
                        hierarchy=hierarchy)
    ex_f = HaloExchange(spec, mesh, Method(method), fused=fused)
    g = spec.global_size
    base = np.arange(g.x * g.y * g.z, dtype=np.float64).reshape(
        g.z, g.y, g.x)
    state = {i: shard_blocks((base + i).astype(dt), spec, mesh)
             for i, dt in enumerate(dtypes)}
    plan = ex_h.plan
    nq = len(dtypes)
    ngroups = len(set(dtypes))
    itemsizes = [np.dtype(d).itemsize for d in dtypes]
    floating = [bool(np.issubdtype(np.dtype(d), np.floating))
                for d in dtypes]

    # the census first (it runs one exchange on an internal copy and
    # compiles every piece — inner programs plus DCN take/updates)
    census = ex_h.collective_census(state)
    census_f = ex_f.collective_census(state)
    stray = {k: c for k, (c, _b) in census.items()
             if k != "collective-permute" and c}
    ok = _check(v.checks, "inner_census_pin",
                list(census_f.get("collective-permute", (0, 0))),
                list(census.get("collective-permute", (0, 0))))
    ok &= _check(v.checks, "stray_collective_kinds", {}, stray)

    # one real exchange, counted: the executed DCN schedule vs the IR
    out_h = ex_h(jax.tree.map(jnp.copy, state))
    predicted_dcn = plan.dcn_transfers_per_exchange(nq, ngroups) \
        + perturb_dcn
    ok &= _check(v.checks, "dcn_transfers", predicted_dcn,
                 ex_h._compiled.last_transfer_count)
    ok &= _check(v.checks, "dcn_wire_bytes",
                 plan.dcn_wire_bytes(itemsizes, floating=floating),
                 ex_h._compiled.last_transfer_bytes)
    if method == REMOTE_DMA:
        # the sequential schedule ran the full inner program first: its
        # host-segmented wrap pairs move the flat count — EXCEPT when a
        # segment is a single device along the DCN axis, where the
        # host-local wrap pair degenerates to a self hand-off and the
        # pure-axis carriers leave the transport entirely (the DCN
        # apply owns that whole halo side)
        ax, hosts_n = hierarchy
        ax_i = {"x": 0, "y": 1, "z": 2}[ax]
        seg = {"x": mesh_dim.x, "y": mesh_dim.y,
               "z": mesh_dim.z}[ax] // hosts_n
        phases = plan.fused_phases if fused else plan.remote_phases
        if seg > 1:
            kept = list(phases)
        elif fused:
            kept = [p for p in phases
                    if any(c for j, c in enumerate(p.direction)
                           if j != ax_i)]
        else:
            kept = [p for p in phases if p.axis != ax]
        carriers = ngroups if plan.batch_quantities else nq
        ok &= _check(v.checks, "inner_dma_transfers",
                     sum(p.dmas() for p in kept) * carriers * nblocks,
                     ex_h._remote.last_transfer_count)

    # hierarchy must be invisible in the data: bit parity with the flat
    # lowering, every quantity
    out_f = ex_f(jax.tree.map(jnp.copy, state))
    parity = all(
        unshard_blocks(out_h[i], spec).tobytes()
        == unshard_blocks(out_f[i], spec).tobytes()
        for i in range(nq))
    ok &= _check(v.checks, "bit_identical_to_flat", True, bool(parity))
    v.ok = bool(ok)
    return v


def run_hierarchy_sweep(
    hosts: int = 2,
    size: int = DEFAULT_SIZE,
    radius: int = DEFAULT_RADIUS,
    partitions: Sequence[Tuple[int, int, int]] = DEFAULT_PARTITIONS,
    methods: Optional[Sequence[str]] = None,
    qsets: Sequence[Sequence[str]] = DEFAULT_QSETS,
    devices=None,
    perturb_dcn: int = 0,
    rec: Optional["telemetry.Recorder"] = None,
) -> Dict:
    """Audit the DCN level across the hierarchical sweep grid (the
    ISSUE-17 gate). Runs on the ``STENCIL_VIRTUAL_HOSTS`` emulation:
    the env knob is set to ``hosts`` for the duration and restored
    after, exactly like :func:`run_sweep`'s x64 flip — a real
    multi-process fabric audits the same way with ``hosts`` matching
    ``jax.process_count()``. Emits the same ``analysis.plan_verdict``/
    ``plan_mismatch``/``plan_sweep`` vocabulary as the method sweep."""
    import os

    rec = rec or telemetry.get()
    configs = hierarchy_sweep_configs(size=size, radius=radius,
                                      partitions=partitions, hosts=hosts,
                                      methods=methods, qsets=qsets)
    x64_prev = None
    if any("64" in dt for cfg in configs for dt in cfg["dtypes"]):
        import jax

        x64_prev = bool(jax.config.jax_enable_x64)
        jax.config.update("jax_enable_x64", True)
    vh_prev = os.environ.get("STENCIL_VIRTUAL_HOSTS")
    os.environ["STENCIL_VIRTUAL_HOSTS"] = str(hosts)
    try:
        verdicts: List[Verdict] = []
        for cfg in configs:
            with rec.span("analysis.verify_plan", phase="analysis",
                          method=cfg["method"]):
                try:
                    v = audit_hierarchy(cfg, devices=devices,
                                        perturb_dcn=perturb_dcn)
                except Exception as e:  # an auditor crash is a FAILED config
                    v = Verdict(label=cfg["label"], method=cfg["method"],
                                ok=False,
                                reason=f"{type(e).__name__}: {e}")
            verdicts.append(v)
            rec.meta("analysis.plan_verdict", method=v.method,
                     ok=int(v.ok), label=v.label,
                     skipped=int(v.skipped), reason=v.reason or None)
            if not v.ok and not v.skipped:
                rec.counter("analysis.plan_mismatch", value=1,
                            phase="analysis", method=v.method)
        checked = [v for v in verdicts if not v.skipped]
        failed = [v for v in checked if not v.ok]
        skipped = [v for v in verdicts if v.skipped]
        rec.meta("analysis.plan_sweep", checked=len(checked),
                 failed=len(failed), skipped=len(skipped))
        return {
            "verdicts": verdicts,
            "checked": len(checked),
            "failed": len(failed),
            "skipped": len(skipped),
        }
    finally:
        if vh_prev is None:
            os.environ.pop("STENCIL_VIRTUAL_HOSTS", None)
        else:
            os.environ["STENCIL_VIRTUAL_HOSTS"] = vh_prev
        if x64_prev is False:
            import jax

            jax.config.update("jax_enable_x64", False)


# -- timed audit (the ISSUE-18 drift leg: seconds, not just structure) -------


def audit_time(cfg: dict, devices=None, iters: int = 6,
               calibration: Optional[dict] = None,
               mad_k: float = 3.0, rel_tol: float = 0.75,
               rec: Optional["telemetry.Recorder"] = None,
               slow_s: float = 0.0) -> Verdict:
    """Time one config's exchange and judge the cost model's PREDICTION
    against the measured samples' band (``obs/attribution.judge_drift``
    — the perf_tool band authority). The structural audits check what
    the lowering puts on the wire; this one checks the seconds the
    autotuner ranked it by.

    The default ``rel_tol`` is wide (0.75 — "within [0.25x, 1.75x] of
    measured"): a handful of in-process samples on a shared CPU box
    judges multiple-x calibration staleness, not 5% drift; tighten it
    on quiet fabrics, but keep it below 1 (at 1 the low band edge hits
    zero and an under-prediction can never trip).
    ``slow_s`` sleeps that long inside ONE timed iteration — the CI
    proof knob that the timed auditor trips, like ``perturb_*`` for the
    structural checks."""
    import time as _time

    import jax
    import numpy as np

    from ..geometry import Dim3, Radius
    from ..obs import attribution
    from ..parallel import HaloExchange, Method, grid_mesh
    from ..parallel.exchange import shard_blocks
    from ..plan.cost import feasible
    from ..plan.ir import (FUSED_VARIANT, PERSISTENT_VARIANT, PlanChoice,
                           PlanConfig, REMOTE_DMA)
    from ..utils.sync import hard_sync

    rec = rec or telemetry.get()
    devices = list(devices) if devices is not None else jax.devices()
    v = Verdict(label=cfg["label"], method=cfg["method"])
    fused = cfg["method"] == FUSED_METHOD_LABEL
    persistent = cfg["method"] == PERSISTENT_METHOD_LABEL
    method = REMOTE_DMA if (fused or persistent) else cfg["method"]
    size, dtypes = cfg["size"], list(cfg["dtypes"])
    radius = Radius.constant(cfg["radius"])
    nblocks = cfg["partition"][0] * cfg["partition"][1] * cfg["partition"][2]
    if nblocks > len(devices):
        v.skipped = True
        v.ok = False
        v.reason = (f"partition {cfg['partition']} needs {nblocks} "
                    f"devices; {len(devices)} available")
        return v
    config = PlanConfig.make(Dim3(size, size, size), radius, dtypes,
                             nblocks, devices[0].platform)
    choice = PlanChoice(
        partition=cfg["partition"], method=method,
        kernel_variant=(PERSISTENT_VARIANT if persistent
                        else FUSED_VARIANT if fused else None),
        multistep_k=2 if persistent else 1)
    feas = feasible(config, choice)
    if feas is None:
        v.skipped = True
        v.ok = False
        v.reason = "infeasible for this config (plan/cost.feasible)"
        return v
    pred = attribution.predict_exchange(config, choice, calibration)
    if pred is None:
        v.skipped = True
        v.ok = False
        v.reason = "cost model prices this choice as infeasible"
        return v
    spec, mesh_dim, _resident = feas
    mesh = grid_mesh(spec.dim, devices[:nblocks])
    ex = HaloExchange(spec, mesh, Method(method), fused=fused,
                      persistent=persistent)
    g = spec.global_size
    base = np.arange(g.x * g.y * g.z, dtype=np.float64).reshape(
        g.z, g.y, g.x)
    state = {i: shard_blocks((base + i).astype(dt), spec, mesh)
             for i, dt in enumerate(dtypes)}
    state = ex(state)  # compile + warm outside the timed window
    hard_sync(state)
    samples: List[float] = []
    for i in range(max(2, iters)):
        t0 = _time.perf_counter()
        state = ex(state)
        hard_sync(state)
        if slow_s and i == 0:
            _time.sleep(slow_s)  # the seeded-staleness proof knob
        samples.append(_time.perf_counter() - t0)
        attribution.emit_phase(rec, pred, samples[-1],
                               phase="stencil.exchange",
                               kernel_variant=choice.kernel_variant)
    dv = attribution.judge_drift("stencil.exchange", pred.predicted_s,
                                 samples, mad_k=mad_k, rel_tol=rel_tol)
    attribution.emit_drift(rec, dv)
    v.checks.append({
        "name": "predicted_s_within_band",
        "predicted": f"{dv.predicted_s:.3e}s",
        "actual": f"measured band [{dv.lo:.3e}, {dv.hi:.3e}] "
                  f"(center {dv.center:.3e}s, n={dv.n})",
        "ok": dv.ok,
    })
    v.ok = bool(dv.ok)
    if not dv.ok:
        v.reason = dv.describe()
    return v


def run_time_sweep(configs: Sequence[dict], devices=None,
                   iters: int = 6, calibration: Optional[dict] = None,
                   mad_k: float = 3.0, rel_tol: float = 0.75,
                   slow_s: float = 0.0,
                   rec: Optional["telemetry.Recorder"] = None) -> Dict:
    """Timed-audit every config; same result/telemetry shape as
    :func:`run_sweep` (one ``analysis.plan_verdict`` per config, the
    ``analysis.plan_sweep`` rollup)."""
    rec = rec or telemetry.get()
    verdicts: List[Verdict] = []
    for cfg in configs:
        with rec.span("analysis.verify_plan", phase="analysis",
                      method=cfg["method"]):
            try:
                v = audit_time(cfg, devices=devices, iters=iters,
                               calibration=calibration, mad_k=mad_k,
                               rel_tol=rel_tol, rec=rec, slow_s=slow_s)
            except Exception as e:  # an auditor crash is a FAILED config
                v = Verdict(label=cfg["label"], method=cfg["method"],
                            ok=False, reason=f"{type(e).__name__}: {e}")
        verdicts.append(v)
        rec.meta("analysis.plan_verdict", method=v.method,
                 ok=int(v.ok), label=v.label,
                 skipped=int(v.skipped), reason=v.reason or None)
        if not v.ok and not v.skipped:
            rec.counter("analysis.plan_mismatch", value=1,
                        phase="analysis", method=v.method)
    checked = [v for v in verdicts if not v.skipped]
    failed = [v for v in checked if not v.ok]
    skipped = [v for v in verdicts if v.skipped]
    rec.meta("analysis.plan_sweep", checked=len(checked),
             failed=len(failed), skipped=len(skipped))
    return {
        "verdicts": verdicts,
        "checked": len(checked),
        "failed": len(failed),
        "skipped": len(skipped),
    }


def run_sweep(configs: Sequence[dict], devices=None,
              perturb_collectives: int = 0, perturb_wire: int = 0,
              perturb_dmas: int = 0,
              rec: Optional["telemetry.Recorder"] = None) -> Dict:
    """Audit every config; returns ``{verdicts, checked, failed,
    skipped}`` and emits the ``analysis.*`` telemetry vocabulary when a
    recorder is attached."""
    rec = rec or telemetry.get()
    # without x64, fp64 state silently downcasts to fp32 and the whole
    # dtype-group prediction audits the wrong program; restored after
    # the sweep so the flip never leaks into the rest of the process
    # (jit-audit in the same `lint_tool all` run must audit the apps'
    # actual fp32 programs)
    x64_prev = None
    if any("64" in dt for cfg in configs for dt in cfg["dtypes"]):
        import jax

        x64_prev = bool(jax.config.jax_enable_x64)
        jax.config.update("jax_enable_x64", True)
    try:
        return _run_sweep(configs, devices, perturb_collectives,
                          perturb_wire, perturb_dmas, rec)
    finally:
        if x64_prev is False:
            import jax

            jax.config.update("jax_enable_x64", False)


def _run_sweep(configs, devices, perturb_collectives, perturb_wire,
               perturb_dmas, rec) -> Dict:
    verdicts: List[Verdict] = []
    for cfg in configs:
        with rec.span("analysis.verify_plan", phase="analysis",
                      method=cfg["method"]):
            try:
                v = audit_config(
                    cfg, devices=devices,
                    perturb_collectives=perturb_collectives,
                    perturb_wire=perturb_wire, perturb_dmas=perturb_dmas)
            except Exception as e:  # an auditor crash is a FAILED config
                v = Verdict(label=cfg["label"], method=cfg["method"],
                            ok=False,
                            reason=f"{type(e).__name__}: {e}")
        verdicts.append(v)
        rec.meta("analysis.plan_verdict", method=v.method,
                 ok=int(v.ok), label=v.label,
                 skipped=int(v.skipped), reason=v.reason or None)
        if not v.ok and not v.skipped:
            rec.counter("analysis.plan_mismatch", value=1,
                        phase="analysis", method=v.method)
    checked = [v for v in verdicts if not v.skipped]
    failed = [v for v in checked if not v.ok]
    skipped = [v for v in verdicts if v.skipped]
    rec.meta("analysis.plan_sweep", checked=len(checked),
             failed=len(failed), skipped=len(skipped))
    return {
        "verdicts": verdicts,
        "checked": len(checked),
        "failed": len(failed),
        "skipped": len(skipped),
    }
