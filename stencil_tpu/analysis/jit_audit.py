"""Step-loop audit: no recompiles, no host syncs, after warmup.

The two canonical silent perf bugs of any jit-compiled step loop:

1. **post-warmup recompilation** — a shape/dtype/static-arg churn makes
   XLA compile *inside the timed region*. The repo's discipline is "one
   chunk-size plan drives both warmup and the timed loop, so no compile
   can land in a timed region" (PR 4); this audit enforces it
   mechanically with a compile counter fed by ``jax.monitoring``'s
   ``backend_compile`` events.
2. **implicit host transfer** — a stray ``.item()``/``np.asarray``/
   print pulls a device value mid-loop, serializing the pipeline. The
   audited chunks run under ``jax.transfer_guard("disallow")``; the
   loop's ONE sanctioned sync (``utils/sync.hard_sync``, per chunk)
   runs *outside* the guard, so anything else that touches the host
   trips it.

The audited loop is the real thing: a jacobi domain built through
``DistributedDomain``, stepped with ``ops/jacobi.make_jacobi_loop``
fused chunks on the local device mesh — the same programs the apps
time. ``inject="recompile"`` skips warming the tail chunk size (the
exact historical bug class) and ``inject="host-sync"`` pulls a value
inside the guard; both must FAIL the audit — the CI gate's proof that
it can detect what it claims to.

Results land as the schema-valid ``analysis.jit_audit`` telemetry
record; the CLI front end is ``lint_tool jit-audit``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..obs import telemetry

INJECT_MODES = ("recompile", "host-sync")

# -- compile counter (jax.monitoring backend_compile events) ------------------

_compile_count = 0
_listener_installed = False


def _ensure_compile_listener() -> None:
    """Install the process-wide compile-event counter once (listeners
    cannot be unregistered portably, so it stays — counting is cheap)."""
    global _listener_installed
    if _listener_installed:
        return
    import jax

    def _on_event(event, *args, **kwargs):
        global _compile_count
        if "backend_compile" in str(event):
            _compile_count += 1

    jax.monitoring.register_event_duration_secs_listener(_on_event)
    _listener_installed = True


def compile_count() -> int:
    """Backend compiles observed since the listener was installed."""
    return _compile_count


@dataclass
class AuditResult:
    ok: bool
    recompiles: int
    transfer_trips: List[str] = field(default_factory=list)
    steps: int = 0
    chunks: int = 0
    warmup_compiles: int = 0
    inject: Optional[str] = None

    def to_json(self) -> dict:
        return {
            "kind": "jit-audit", "ok": self.ok,
            "recompiles": self.recompiles,
            "transfer_trips": self.transfer_trips,
            "steps": self.steps, "chunks": self.chunks,
            "warmup_compiles": self.warmup_compiles,
            "inject": self.inject,
        }


def run_audit(size: int = 16, iters: int = 10, chunk: int = 4,
              inject: Optional[str] = None, devices=None,
              rec: Optional["telemetry.Recorder"] = None) -> AuditResult:
    """Audit the jacobi guarded chunk loop on the local mesh.

    Warmup compiles every distinct chunk size of the plan (the apps'
    checkpointed-run discipline), then the audited chunks run under
    ``transfer_guard("disallow")`` with the compile counter armed. Any
    post-warmup ``backend_compile`` event or disallowed transfer fails
    the audit.
    """
    if inject is not None and inject not in INJECT_MODES:
        raise ValueError(f"unknown inject mode {inject!r} "
                         f"(known: {', '.join(INJECT_MODES)})")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..api import DistributedDomain
    from ..fault.recover import chunk_plan
    from ..ops.jacobi import INIT_TEMP, make_jacobi_loop, make_jacobi_step, \
        sphere_sel
    from ..parallel.exchange import shard_blocks
    from ..utils.sync import hard_sync

    _ensure_compile_listener()
    rec = rec or telemetry.get()
    devices = list(devices) if devices is not None else jax.devices()

    dd = DistributedDomain(size, size, size)
    dd.set_radius(1)
    dd.set_devices(devices)
    h = dd.add_data("temperature")
    dd.realize()
    sharding = dd.sharding()
    shape = dd.spec.stacked_shape_zyx()
    curr = jax.device_put(jnp.full(shape, INIT_TEMP, jnp.float32), sharding)
    nxt = jax.device_put(jnp.zeros(shape, jnp.float32), sharding)
    sel = shard_blocks(sphere_sel(dd.spec.global_size), dd.spec, dd.mesh)

    chunk = max(1, min(chunk, iters))
    plan = chunk_plan(0, iters, chunk)
    loops = {}

    def get_loop(k: int):
        if k not in loops:
            loops[k] = (make_jacobi_loop(dd.halo_exchange, k)
                        if k > 1 else
                        make_jacobi_step(dd.halo_exchange))
        return loops[k]

    # warmup: every distinct chunk size of the plan — UNLESS the
    # injected-recompile fixture is on, which deliberately leaves the
    # tail size cold (the historical compile-in-a-timed-region bug)
    warm_sizes = list(dict.fromkeys(plan))
    if inject == "recompile":
        warm_sizes = warm_sizes[:1]
        if len(set(plan)) < 2:
            raise ValueError(
                f"inject='recompile' needs a chunk plan with >= 2 "
                f"distinct sizes; iters={iters} chunk={chunk} gives "
                f"{plan} — pick iters not divisible by chunk")
    c0 = compile_count()
    with rec.span("analysis.jit_warmup", phase="compile"):
        for k in warm_sizes:
            curr, nxt = get_loop(k)(curr, nxt, sel)
        # hard_sync's scalar-fetch program must also be warm, or its
        # first gather compile would read as a step-loop recompile
        hard_sync(curr)
    warmup_compiles = compile_count() - c0

    trips: List[str] = []
    baseline = compile_count()
    done = 0
    with rec.span("analysis.jit_audit_loop", phase="step"):
        for i, k in enumerate(plan):
            loop = get_loop(k)
            try:
                with jax.transfer_guard("disallow"):
                    curr, nxt = loop(curr, nxt, sel)
                    if inject == "host-sync" and i == 1:
                        # the injected bug: a mid-loop scalar pull
                        # (float(x[0,...]) — the .item() bug class). The
                        # guard trips on the un-jitted host interaction
                        # (on CPU, the index upload; on TPU, the pull
                        # itself)
                        float(curr[(0,) * curr.ndim])
            except Exception as e:
                msg = str(e)
                if "isallow" in msg or "transfer" in msg.lower():
                    trips.append(
                        f"chunk {i} (k={k}): {msg.splitlines()[0][:200]}")
                    continue  # the chunk is evidence; keep auditing
                raise
            hard_sync(curr)  # the ONE sanctioned sync, outside the guard
            done += k
    recompiles = compile_count() - baseline

    ok = recompiles == 0 and not trips
    result = AuditResult(ok=ok, recompiles=recompiles,
                         transfer_trips=trips, steps=done,
                         chunks=len(plan), warmup_compiles=warmup_compiles,
                         inject=inject)
    rec.meta("analysis.jit_audit", ok=int(ok), recompiles=int(recompiles),
             transfers=len(trips), steps=done, inject=inject)
    return result
