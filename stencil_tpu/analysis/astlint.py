"""AST lint engine + the repo-specific rule set.

The engine walks Python ASTs (stdlib ``ast`` only — no jax, no imports
of the linted code) and runs registered rules over each file. Each rule
has a name, a severity, and a docstring that IS its user-facing
description (``lint_tool lint --rules`` prints them).

Suppression: an inline ``# lint: disable=<rule>[,<rule>...]`` comment on
the finding's line (or on the line directly above it) suppresses those
rules there. A disable naming an unknown rule is itself a loud
``bad-pragma`` error — a typo'd suppression must never silently disable
nothing.

Baseline: a committed JSON file of finding fingerprints
(:func:`load_baseline` / :func:`write_baseline`). Fingerprints hash the
rule + file basename + source-line text (+ an occurrence index), so
unrelated edits that shift line numbers do not invalidate the baseline,
while editing the offending line re-surfaces the finding. ``lint_tool``
exits 1 only on findings NOT in the baseline.

The shipped rules encode contracts PRs 3-12 stated in prose:

- ``pure-stdlib``     obs/watchdog.py, obs/ledger.py, obs/status.py are
                      loaded BY FILE PATH (bench.py parent, watchdog
                      supervisors) and must import only the stdlib, at
                      any nesting depth; bench.py's module top level too.
- ``telemetry-vocab`` literal metric names at Recorder record sites must
                      be in obs/telemetry.KNOWN_NAMES (typos validate
                      silently otherwise — schema v1 constrains shape,
                      not names). Dynamic names are explicitly generic.
- ``atomic-write``    json.dump through a plain ``open(path, "w")`` with
                      no tmp+rename in scope: a crash mid-write leaves a
                      torn artifact where every other writer in this
                      repo (ckpt, ledger, status, plan DB) guarantees
                      atomic replacement.
- ``no-bare-assert``  ``assert`` used for validation in PUBLIC library
                      functions vanishes under ``python -O`` (the PR 12
                      hazard); raise ValueError/RuntimeError instead.
- ``fstring-placeholder`` a plain string containing ``{name}`` fed to
                      raise/log without the f-prefix (the PR 6 bug
                      class): the reader gets the placeholder, not the
                      value.
- ``host-sync-in-hot-loop`` ``.item()``/``float()``/``np.asarray``/
                      ``time.time()`` etc. inside functions traced into
                      the fused step loops: a host sync serializes the
                      device pipeline, and ``time.time()`` burns in a
                      trace-time constant.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

SEVERITIES = ("error", "warning")

# Repo files under the pure-stdlib contract: loaded by file path, so any
# non-stdlib (or relative) import, however deeply nested, breaks them.
# Matched by path SUFFIX, so a fixture obs/watchdog.py in a temp dir is
# held to the same contract (the CI gate's fires-on-bad proof).
PURE_STDLIB_FILES = (
    "obs/watchdog.py",
    "obs/ledger.py",
    "obs/status.py",
    # the serving daemon's durable queue state: read by revival tooling
    # and ops scripts that must never wait on a jax import
    "serve/state.py",
    "scripts/serve_loadgen.py",
)
# bench.py's PARENT is pure-stdlib at module level only: the child code
# paths (same file, function scope) import jax after the re-exec.
PURE_STDLIB_TOP_LEVEL = ("bench.py",)

# Directories never linted by default (tests use asserts and ad-hoc
# metric names legitimately; generated caches are not source).
EXCLUDE_DIR_NAMES = ("__pycache__", ".git", ".claude")
EXCLUDE_PREFIXES = ("tests/", "native/")

DEFAULT_PATHS = ("stencil_tpu", "scripts", "bench.py", "__graft_entry__.py")


@dataclass(frozen=True)
class Finding:
    """One lint finding; ``fingerprint`` is assigned by the engine (rule +
    file basename + offending line text + occurrence index)."""

    rule: str
    path: str           # repo-relative, forward slashes
    line: int
    col: int
    message: str
    severity: str = "error"
    snippet: str = ""   # stripped source line (fingerprint input)
    fingerprint: str = ""

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "col": self.col, "severity": self.severity,
            "message": self.message, "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}/{self.severity}] {self.message}")


@dataclass
class FileContext:
    """Everything a rule sees about one file."""

    relpath: str            # repo-relative, forward slashes
    src: str
    lines: List[str]
    tree: ast.AST

    def finding(self, rule: "Rule", node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = (self.lines[line - 1].strip()
                   if 0 < line <= len(self.lines) else "")
        return Finding(rule=rule.name, path=self.relpath, line=line,
                       col=col, message=message, severity=rule.severity,
                       snippet=snippet)


@dataclass(frozen=True)
class Rule:
    name: str
    severity: str
    doc: str
    check: Callable[["FileContext"], List[Finding]]
    applies: Callable[[str], bool]


RULES: Dict[str, Rule] = {}


def rule(name: str, severity: str = "error",
         applies: Optional[Callable[[str], bool]] = None):
    """Register a rule; the decorated function's docstring is the
    user-facing description."""
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r} for rule {name}")

    def deco(fn):
        RULES[name] = Rule(
            name=name, severity=severity,
            doc=(fn.__doc__ or "").strip().splitlines()[0],
            check=fn, applies=applies or (lambda relpath: True),
        )
        return fn

    return deco


def _norm(relpath: str) -> str:
    return relpath.replace(os.sep, "/")


def _not_tests(relpath: str) -> bool:
    p = _norm(relpath)
    return not (p.startswith("tests/") or "/tests/" in p)


def _library_code(relpath: str) -> bool:
    """Library scope: not tests, not operational scripts (probe/gate
    scripts use asserts as executable documentation)."""
    p = _norm(relpath)
    return _not_tests(p) and not (p.startswith("scripts/")
                                  or "/scripts/" in p)


# -- suppression pragmas ------------------------------------------------------

_PRAGMA_RE = re.compile(r"#\s*lint:\s*disable=([A-Za-z0-9_\-, ]+)")


def suppressions(ctx: FileContext) -> Tuple[Dict[int, Set[str]],
                                            List[Finding]]:
    """(line -> suppressed rule names, bad-pragma findings). A pragma on
    line N suppresses findings on N and N+1 (the comment-above idiom)."""
    supp: Dict[int, Set[str]] = {}
    bad: List[Finding] = []
    for i, text in enumerate(ctx.lines, 1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        names = {t.strip() for t in m.group(1).split(",") if t.strip()}
        unknown = sorted(n for n in names if n not in RULES)
        if unknown:
            bad.append(Finding(
                rule="bad-pragma", path=ctx.relpath, line=i,
                col=text.index("#"), severity="error",
                message=(f"lint: disable names unknown rule(s) "
                         f"{', '.join(unknown)} (known: "
                         f"{', '.join(sorted(RULES))})"),
                snippet=text.strip(),
            ))
        known = names - set(unknown)
        if known:
            # pure comment line: the pragma governs the NEXT line too
            supp.setdefault(i, set()).update(known)
            if text.lstrip().startswith("#"):
                supp.setdefault(i + 1, set()).update(known)
    return supp, bad


# -- rule: pure-stdlib --------------------------------------------------------


def _stdlib_names() -> frozenset:
    names = getattr(sys, "stdlib_module_names", None)
    if names:
        return frozenset(names) | {"__future__"}
    # pre-3.10 fallback: forbid the third-party stack this repo uses
    return frozenset()


_STDLIB = _stdlib_names()
_FORBIDDEN_PREFIXES = ("jax", "jaxlib", "numpy", "np", "scipy", "flax",
                       "optax", "chex", "einops", "stencil_tpu")


def _is_stdlib(mod: str) -> bool:
    top = mod.split(".")[0]
    if _STDLIB:
        return top in _STDLIB
    return not any(top == p or top.startswith(p + ".")
                   for p in _FORBIDDEN_PREFIXES)


def _pure_stdlib_applies(relpath: str) -> bool:
    p = _norm(relpath)
    return (any(p == f or p.endswith("/" + f) for f in PURE_STDLIB_FILES)
            or any(p == f or p.endswith("/" + f)
                   for f in PURE_STDLIB_TOP_LEVEL))


@rule("pure-stdlib", severity="error", applies=_pure_stdlib_applies)
def check_pure_stdlib(ctx: FileContext) -> List[Finding]:
    """File-path-loaded modules (obs/watchdog, obs/ledger, obs/status)
    must import only the stdlib, at any nesting depth; bench.py's module
    top level likewise (its child code paths may import jax in
    functions)."""
    p = _norm(ctx.relpath)
    top_level_only = (
        any(p == f or p.endswith("/" + f) for f in PURE_STDLIB_TOP_LEVEL)
        and not any(p == f or p.endswith("/" + f)
                    for f in PURE_STDLIB_FILES))
    out: List[Finding] = []
    r = RULES["pure-stdlib"]

    def visit(node, at_top: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                if not top_level_only:
                    visit(child, False)
                continue
            if isinstance(child, ast.Import):
                for alias in child.names:
                    if not _is_stdlib(alias.name):
                        out.append(ctx.finding(
                            r, child,
                            f"non-stdlib import {alias.name!r} in a "
                            f"pure-stdlib module (loaded by file path: "
                            f"importing it must never pull in "
                            f"jax/numpy/stencil_tpu)"))
            elif isinstance(child, ast.ImportFrom):
                if child.level and child.level > 0:
                    out.append(ctx.finding(
                        r, child,
                        "relative import in a pure-stdlib module: the "
                        "file is loaded by file path, where no package "
                        "context exists"))
                elif child.module and not _is_stdlib(child.module):
                    out.append(ctx.finding(
                        r, child,
                        f"non-stdlib import {child.module!r} in a "
                        f"pure-stdlib module"))
            visit(child, at_top)

    visit(ctx.tree, True)
    return out


# -- rule: telemetry-vocab ----------------------------------------------------

_RECORD_NAME_ARG = {"counter": 0, "gauge": 0, "span": 0, "meta": 0,
                    "emit": 1}

_vocab_cache: Optional[frozenset] = None


def telemetry_vocab() -> frozenset:
    """The sanctioned metric-name set — obs/telemetry.py is the one
    authority (KNOWN_NAMES next to NAME_FIELDS)."""
    global _vocab_cache
    if _vocab_cache is None:
        from ..obs.telemetry import KNOWN_NAMES

        _vocab_cache = frozenset(KNOWN_NAMES)
    return _vocab_cache


@rule("telemetry-vocab", severity="error", applies=_library_code)
def check_telemetry_vocab(ctx: FileContext) -> List[Finding]:
    """Literal metric names at Recorder record sites (span/counter/
    gauge/meta/emit) must be in obs/telemetry.KNOWN_NAMES; a typo'd name
    validates silently otherwise. Dynamically-built names are explicitly
    generic and exempt."""
    vocab = telemetry_vocab()
    out: List[Finding] = []
    r = RULES["telemetry-vocab"]
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        idx = _RECORD_NAME_ARG.get(fn.attr)
        if idx is None or len(node.args) <= idx:
            continue
        arg = node.args[idx]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            continue  # dynamic name: explicitly generic
        name = arg.value
        if name in vocab:
            continue
        out.append(ctx.finding(
            r, arg,
            f"metric name {name!r} is not in the telemetry vocabulary "
            f"(obs/telemetry.KNOWN_NAMES): a typo here validates "
            f"silently and no dashboard will aggregate it — add the "
            f"name to the vocabulary or build it dynamically if generic"))
    return out


# -- rule: atomic-write -------------------------------------------------------


@rule("atomic-write", severity="error", applies=_not_tests)
def check_atomic_write(ctx: FileContext) -> List[Finding]:
    """json.dump through a plain ``open(path, "w")`` with no
    os.replace/os.rename in the same function: a crash mid-write leaves
    a torn artifact; use the repo's tmp+fsync+rename protocol."""
    out: List[Finding] = []
    r = RULES["atomic-write"]

    def scopes(node):
        """(scope node, body-walk excluding nested functions)."""
        own: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(node))
        while stack:
            n = stack.pop()
            own.append(n)
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(n))
        yield node, own
        for n in own:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from scopes(n)

    for _scope, body in scopes(ctx.tree):
        opens = []
        dumps = []
        has_replace = False
        for n in body:
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Name) and f.id == "open":
                mode = None
                if len(n.args) > 1 and isinstance(n.args[1], ast.Constant):
                    mode = n.args[1].value
                for kw in n.keywords:
                    if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                        mode = kw.value.value
                if isinstance(mode, str) and mode.startswith("w"):
                    target = ast.unparse(n.args[0]) if n.args else ""
                    opens.append((n, target))
            elif isinstance(f, ast.Attribute):
                # .rename never exists on str; .replace does — only an
                # os/shutil receiver counts as the atomic protocol, or a
                # str.replace in scope would silence the rule (a pathlib
                # tmp.replace(path) reads as a finding to pragma, which
                # is visible — the false negative would not be)
                if f.attr == "rename" or (
                        f.attr == "replace"
                        and isinstance(f.value, ast.Name)
                        and f.value.id in ("os", "shutil")):
                    has_replace = True
                elif (f.attr == "dump" and isinstance(f.value, ast.Name)
                      and f.value.id == "json"):
                    dumps.append(n)
        if has_replace or not dumps:
            continue
        plain = [(n, t) for n, t in opens if "tmp" not in t.lower()]
        if not plain:
            continue
        for d in dumps:
            out.append(ctx.finding(
                r, d,
                f"json.dump through a plain open({plain[0][1]}, 'w') "
                f"with no os.replace/os.rename in scope: a crash "
                f"mid-write leaves a torn artifact — write to a .tmp "
                f"sibling, fsync, then os.replace (the ckpt/ledger/"
                f"status discipline)"))
    return out


# -- rule: no-bare-assert -----------------------------------------------------

_PUBLIC_DUNDERS = ("__init__", "__post_init__", "__call__")


@rule("no-bare-assert", severity="error", applies=_library_code)
def check_no_bare_assert(ctx: FileContext) -> List[Finding]:
    """``assert`` used for validation in a public library function
    vanishes under ``python -O``, silently accepting the bad input;
    raise ValueError/RuntimeError instead. Private helpers and nested
    functions may keep internal-invariant asserts; ``assert_*``-named
    checkers are exempt by design."""
    out: List[Finding] = []
    r = RULES["no-bare-assert"]

    # ``at_boundary`` tracks the lexical SCOPE, not the direct parent:
    # a def under a module-level if/try (feature gates, optional-dep
    # fallbacks) is just as public as one at the top level
    def visit(node, at_boundary: bool, boundary_fn: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
                public = (at_boundary
                          and (not name.startswith("_")
                               or name in _PUBLIC_DUNDERS)
                          and not name.startswith("assert"))
                visit(child, False, name if public else None)
            elif isinstance(child, ast.ClassDef):
                visit(child, True, None)
            elif isinstance(child, ast.Assert):
                if boundary_fn is not None:
                    out.append(ctx.finding(
                        r, child,
                        f"assert in public function {boundary_fn!r} "
                        f"vanishes under python -O: raise ValueError "
                        f"(bad argument) or RuntimeError (bad state) "
                        f"so the validation survives every interpreter "
                        f"mode"))
                visit(child, at_boundary, boundary_fn)
            else:
                visit(child, at_boundary, boundary_fn)

    visit(ctx.tree, True, None)
    return out


# -- rule: fstring-placeholder ------------------------------------------------

# a {placeholder} that looks like an expression (identifier head, then
# attribute/index/call trailers, optional !conversion / :format-spec)
_PLACEHOLDER_RE = re.compile(
    r"\{[A-Za-z_][A-Za-z0-9_]*"
    r"(?:\.[A-Za-z0-9_]+|\[[^\]{}]*\]|\(\))*"
    r"(?:![sra])?(?::[^{}]*)?\}"
)

_LOG_METHODS = ("debug", "info", "warn", "warning", "error", "fatal",
                "critical", "exception")


@rule("fstring-placeholder", severity="error", applies=_not_tests)
def check_fstring_placeholder(ctx: FileContext) -> List[Finding]:
    """A plain string containing ``{name}`` placeholders fed to raise or
    a log call without the f-prefix (the PR 6 bug class): the reader
    gets the literal placeholder, not the value. ``.format()`` and
    ``{{`` escapes are recognized."""
    out: List[Finding] = []
    r = RULES["fstring-placeholder"]
    seen: Set[int] = set()

    def formatted_receivers(root) -> Set[int]:
        """ids of string constants that ARE formatted (x.format / x % y)."""
        done: Set[int] = set()
        for n in ast.walk(root):
            if (isinstance(n, ast.Attribute) and n.attr == "format"
                    and isinstance(n.value, ast.Constant)):
                done.add(id(n.value))
            if (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Mod)
                    and isinstance(n.left, ast.Constant)):
                done.add(id(n.left))
        return done

    def scan(root, where: str):
        done = formatted_receivers(root)
        for n in ast.walk(root):
            if isinstance(n, ast.JoinedStr):
                # the literal parts of an f-string are already formatted
                done.update(id(v) for v in ast.walk(n)
                            if isinstance(v, ast.Constant))
        for n in ast.walk(root):
            if not (isinstance(n, ast.Constant) and isinstance(n.value, str)):
                continue
            if id(n) in done or id(n) in seen:
                continue
            s = n.value
            if "{{" in s or "}}" in s:
                continue
            if _PLACEHOLDER_RE.search(s):
                seen.add(id(n))
                out.append(ctx.finding(
                    r, n,
                    f"string at a {where} site contains "
                    f"{{placeholder}} but is not an f-string: the "
                    f"reader gets the literal braces, not the value "
                    f"(add the f prefix or .format())"))

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Raise):
            scan(node, "raise")
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in _LOG_METHODS):
            for a in list(node.args) + [k.value for k in node.keywords]:
                scan(a, "log")
    return out


# -- rule: host-sync-in-hot-loop ----------------------------------------------

_TRACE_WRAPPERS = ("jit", "shard_map", "pallas_call", "fori_loop",
                   "while_loop", "scan", "cond", "switch", "remat",
                   "checkpoint", "vmap", "pmap", "custom_jvp", "custom_vjp",
                   "named_call")

_SYNC_ATTR_CALLS = ("item", "tolist", "block_until_ready")
_SYNC_DOTTED = {
    ("np", "asarray"), ("np", "array"), ("numpy", "asarray"),
    ("numpy", "array"), ("jax", "device_get"),
    ("time", "time"), ("time", "perf_counter"), ("time", "monotonic"),
}


def _dotted(fn) -> Tuple[str, ...]:
    parts: List[str] = []
    node = fn
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return tuple(reversed(parts))


def _mentions_trace_wrapper(expr) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and n.attr in _TRACE_WRAPPERS:
            return True
        if isinstance(n, ast.Name) and n.id in _TRACE_WRAPPERS:
            return True
    return False


@rule("host-sync-in-hot-loop", severity="error", applies=_not_tests)
def check_host_sync(ctx: FileContext) -> List[Finding]:
    """Host syncs (``.item()``, ``float()``, ``np.asarray``,
    ``time.time()``, ``jax.device_get``) inside functions traced into
    the fused step loops: a sync serializes the device pipeline, and a
    clock call burns a trace-time constant into the compiled program.
    Traced functions are found by reachability from jit/shard_map/
    pallas_call/fori_loop/scan seeds."""
    out: List[Finding] = []
    r = RULES["host-sync-in-hot-loop"]

    # index every function/lambda, with class qualification and parents
    defs: Dict[str, List[ast.AST]] = {}
    qual: Dict[int, str] = {}

    def index(node, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                index(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = (f"{cls}.{child.name}"
                        if isinstance(node, ast.ClassDef) else child.name)
                defs.setdefault(child.name, []).append(child)
                defs.setdefault(name, []).append(child)
                qual[id(child)] = name
                index(child, cls)
            else:
                index(child, cls)

    index(ctx.tree, None)

    def resolve_ref(expr, cls_hint: Optional[str]) -> List[ast.AST]:
        """Function defs an argument expression may refer to."""
        if isinstance(expr, ast.Lambda):
            return [expr]
        if isinstance(expr, ast.Name):
            return defs.get(expr.id, [])
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            # self.method: try class-qualified first, else by bare name
            for key in ([f"{cls_hint}.{expr.attr}"] if cls_hint else []) + \
                    [expr.attr]:
                if key in defs:
                    return defs[key]
        return []

    def enclosing_class(node) -> Optional[str]:
        name = qual.get(id(node), "")
        return name.split(".")[0] if "." in name else None

    traced: Set[int] = set()
    traced_nodes: List[ast.AST] = []

    def mark(fn_node):
        if id(fn_node) not in traced:
            traced.add(id(fn_node))
            traced_nodes.append(fn_node)

    # seeds: decorated with a trace wrapper, or passed to one
    for fns in defs.values():
        for fn_node in fns:
            for dec in getattr(fn_node, "decorator_list", []):
                if _mentions_trace_wrapper(dec):
                    mark(fn_node)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if not (name and name[-1] in _TRACE_WRAPPERS):
            continue
        for a in list(node.args) + [k.value for k in node.keywords]:
            for ref in resolve_ref(a, None):
                mark(ref)
            # partial(body, ...) / nested call args
            if isinstance(a, ast.Call):
                for aa in a.args:
                    for ref in resolve_ref(aa, None):
                        mark(ref)

    # propagate: any function referenced from a traced body is traced
    # (called directly, or passed to tree.map/scan inside traced code)
    i = 0
    while i < len(traced_nodes):
        t = traced_nodes[i]
        i += 1
        cls = enclosing_class(t)
        for n in ast.walk(t):
            if n is t:
                continue
            if isinstance(n, (ast.Name, ast.Attribute, ast.Lambda)):
                for ref in resolve_ref(n, cls):
                    mark(ref)

    # scan traced bodies (excluding their nested defs, which are marked
    # separately if reached) for host syncs
    for t in traced_nodes:
        stack = list(ast.iter_child_nodes(t))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and id(n) in traced:
                continue  # reported under its own traced entry
            stack.extend(ast.iter_child_nodes(n))
            if not isinstance(n, ast.Call):
                continue
            name = _dotted(n.func)
            fname = qual.get(id(t), getattr(t, "name", "<lambda>"))
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr in _SYNC_ATTR_CALLS and not n.args):
                out.append(ctx.finding(
                    r, n,
                    f".{n.func.attr}() inside traced function "
                    f"{fname!r}: a host sync in the step loop "
                    f"serializes the device pipeline"))
            elif name in _SYNC_DOTTED:
                what = ".".join(name)
                why = ("burns a trace-time constant into the compiled "
                       "program" if name[0] == "time"
                       else "forces a device-to-host transfer")
                out.append(ctx.finding(
                    r, n,
                    f"{what}() inside traced function {fname!r}: {why}"))
            elif (isinstance(n.func, ast.Name)
                  and n.func.id in ("float", "int") and n.args
                  and not isinstance(n.args[0], ast.Constant)
                  # float(ALL_CAPS) converts a module constant at trace
                  # time — a static value, not a sync
                  and not (isinstance(n.args[0], ast.Name)
                           and n.args[0].id.isupper())):
                out.append(ctx.finding(
                    r, n,
                    f"{n.func.id}() on a computed value inside traced "
                    f"function {fname!r}: on a traced array this is a "
                    f"host sync (or a trace-time error); keep scalars "
                    f"on-device with jnp"))
    return out


# -- baseline -----------------------------------------------------------------


def assign_fingerprints(findings: Sequence[Finding]) -> List[Finding]:
    """Stable fingerprints: rule + file basename + line text + occurrence
    index — line-number-independent, so edits elsewhere in the file never
    invalidate a baseline entry."""
    counts: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.rule, f.path, f.snippet)
        idx = counts.get(key, 0)
        counts[key] = idx + 1
        h = hashlib.sha1(
            "\x1f".join((f.rule, _norm(f.path), f.snippet,
                         str(idx))).encode()
        ).hexdigest()[:16]
        out.append(Finding(**{**f.__dict__, "fingerprint":
                              f"{f.rule}:{h}"}))
    return out


def load_baseline(path: str) -> Set[str]:
    """Fingerprint set from a committed baseline file. Missing file =
    empty baseline; a malformed one is a loud error (a torn baseline
    must not silently un-suppress or mask everything)."""
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != 1 \
            or not isinstance(doc.get("fingerprints"), list):
        raise ValueError(
            f"{path}: not a v1 lint baseline "
            "({'version': 1, 'fingerprints': [...]})")
    return set(str(fp) for fp in doc["fingerprints"])


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Atomic baseline rewrite (the repo's own tmp+fsync+rename rule)."""
    doc = {"version": 1,
           "fingerprints": sorted(f.fingerprint for f in findings)}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# -- driver -------------------------------------------------------------------


def iter_py_files(paths: Sequence[str], repo_root: str) -> List[str]:
    """Expand files/dirs to .py files (repo-relative), excluding tests,
    caches, and native sources."""
    out: List[str] = []
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(repo_root, p)
        if os.path.isfile(ap):
            if ap.endswith(".py"):
                out.append(ap)
            continue
        for root, dirs, files in os.walk(ap):
            dirs[:] = [d for d in sorted(dirs)
                       if d not in EXCLUDE_DIR_NAMES]
            for fn in sorted(files):
                if fn.endswith(".py"):
                    out.append(os.path.join(root, fn))
    uniq: List[str] = []
    seen: Set[str] = set()
    for ap in out:
        rel = _norm(os.path.relpath(ap, repo_root))
        if rel in seen or any(rel.startswith(pre)
                              for pre in EXCLUDE_PREFIXES):
            continue
        seen.add(rel)
        uniq.append(ap)
    return uniq


def lint_paths(paths: Sequence[str], repo_root: Optional[str] = None,
               rules: Optional[Sequence[str]] = None
               ) -> Tuple[List[Finding], List[str]]:
    """Lint files/dirs; returns (fingerprinted findings, engine errors).
    ``rules`` restricts to a subset (unknown names are an error)."""
    repo_root = repo_root or os.getcwd()
    if rules:
        unknown = sorted(set(rules) - set(RULES))
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(unknown)}")
    active = [RULES[n] for n in (rules or sorted(RULES))]
    findings: List[Finding] = []
    errors: List[str] = []
    for ap in iter_py_files(paths, repo_root):
        rel = _norm(os.path.relpath(ap, repo_root))
        try:
            src = open(ap, encoding="utf-8").read()
            tree = ast.parse(src, filename=rel)
        except (OSError, SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        ctx = FileContext(relpath=rel, src=src,
                          lines=src.splitlines(), tree=tree)
        supp, bad = suppressions(ctx)
        findings.extend(bad)  # bad pragmas are never suppressible
        for r in active:
            if not r.applies(rel):
                continue
            try:
                got = r.check(ctx)
            except Exception as e:  # a broken rule must name itself
                errors.append(f"{rel}: rule {r.name} crashed: "
                              f"{type(e).__name__}: {e}")
                continue
            for f in got:
                if r.name in supp.get(f.line, set()):
                    continue
                findings.append(f)
    return assign_fingerprints(findings), errors
