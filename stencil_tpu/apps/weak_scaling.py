"""weak_scaling — the day-1 multi-chip harness for the north-star table.

Given an N-chip slice this runs the three BASELINE.json multi-chip configs
and emits one CSV plus weak-scaling efficiencies against recorded
single-chip numbers, so the first hardware session produces the scaling
table instead of engineering (reference workflow:
scripts/summit/512node_weak_exchange.sh:17-29 — one submission per scale,
CSV rows appended per run):

- config 2: exchange, 256^3 *global*, radius 2, 4 quantities (2x2x2
  partition at 8 chips; whatever partition N chips realize otherwise)
- config 3: exchange_weak, 512^3 *per chip*, radius 3, 4 quantities
- config 5: jacobi3d overlap step, 256^3 per chip (1024^3 global at 64
  chips), plus the measure_overlap hidden-fraction instrument at the same
  per-chip size

Efficiency definitions (vs the ``--base`` JSON, by default the repo's
recorded single-chip numbers, re-recordable with ``--record-base`` on one
chip):

- jacobi:   eff = (Mcells/s/chip at N) / (Mcells/s/chip at 1) — the >90%
            north star (BASELINE.json).
- exchange: t(1 chip)/t(N chips) per exchange at the same per-chip load
            (config 3); reported as a ratio, not a percentage, because the
            1-chip "exchange" is self-wrap halo fill, a different physical
            operation than ICI permutes — the absolute GB/s column is the
            number that matters.
- overlap:  hidden_frac from measure_overlap (1.0 = exchange fully hidden).

Usage:
  python -m stencil_tpu.apps.weak_scaling                  # real chips
  python -m stencil_tpu.apps.weak_scaling --cpu 8 --smoke  # virtual mesh
  python -m stencil_tpu.apps.weak_scaling --record-base    # on 1 chip

Dispatch-overhead caveat: iterations run in fused chunks of ``iters // 3``.
On the tunneled single-chip platform (~87 ms/dispatch) the efficiency
columns are only apples-to-apples when runs use the same ``--iters`` as
``--record-base`` (default 360); on a real pod slice dispatch cost is
negligible and any iters works.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Optional

import jax

from ..geometry import Dim3
from ..obs import telemetry
from ..parallel import Method
from ..utils import logging as log
from . import bench_exchange, exchange_weak, jacobi3d, measure_overlap

# Single-chip anchors (v5e; see BASELINE.md). --record-base overwrites
# these with freshly measured values. The jacobi anchor is the
# 256^3-per-chip config-5 configuration itself (fused loop, deep_halo=4 =>
# temporal depth PINNED at k=4 on every device count, same as the scaled
# runs — ADVICE r3), NOT the 512^3 headline, so the efficiency column
# compares like with like.
#
# Recorded round 5 (2026-07-31, scripts/r05_logs/record_base.log) at the
# pinned k=4 via --record-base on the chip; scripts/weak_base.json holds
# the full-precision values and takes precedence whenever it exists.
DEFAULT_BASE = {
    "jacobi_mcells_per_s_per_dev": 14337.0,  # 256^3 deep_halo=4 (k=4 pin)
    "exchange_weak_trimean_s": 5.41e-3,      # 512^3 radius-3 4q self-wrap fill
    "config2_trimean_s": 2.21e-3,            # 256^3 radius-2 4q self-wrap fill
}


def _base_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "scripts", "weak_base.json")


def run(
    devices=None,
    iters: int = 30,
    jacobi_iters: int = 60,
    per_chip: Dim3 = Dim3(256, 256, 256),
    exw_per_chip: Dim3 = Dim3(512, 512, 512),
    config2_global: Dim3 = Dim3(256, 256, 256),
    base: Optional[dict] = None,
    use_pallas: Optional[bool] = None,
    overlap_rounds: int = 3,
    deep_halo: int = 4,
    chunk: Optional[int] = None,
) -> dict:
    """Run configs 2/3/5 on ``devices`` and return rows + efficiencies.

    ``chunk`` (iterations fused per dispatch) defaults to ``iters // 3`` —
    the anchors are recorded with large chunks, and a small chunk makes the
    efficiency columns measure dispatch overhead instead of scaling
    (~87 ms per dispatch on the tunneled platform)."""
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    missing = sorted(set(DEFAULT_BASE) - set(base or {}))
    if missing:
        # ADVICE r4: make it visible when built-in constants (not a
        # measured scripts/weak_base.json) anchor any efficiency column —
        # including a partial --base dict
        log.warn(
            "weak-scaling efficiency columns "
            f"{missing} anchored to built-in DEFAULT_BASE constants; run "
            "--record-base (or pass a full --base) for measured anchors"
        )
    base = dict(DEFAULT_BASE, **(base or {}))
    if chunk is None:
        chunk = max(1, iters // 3)
    rows = []

    # -- config 2: fixed global exchange ------------------------------------
    c2 = bench_exchange.run(
        config2_global.x, config2_global.y, config2_global.z,
        iters=iters, quantities=4, devices=devices, chunk=chunk,
    )[-1]  # the "uniform/2" row — config 2's radius-2 halo
    c2_eff = base["config2_trimean_s"] / c2["trimean_s"]
    rows.append(("config2_exchange", config2_global.x, config2_global.y,
                 config2_global.z, n, c2["trimean_s"],
                 c2["bytes_per_s"] / 1e9, c2_eff))

    # -- config 3: weak-scaled exchange -------------------------------------
    c3 = exchange_weak.run(
        exw_per_chip.x, exw_per_chip.y, exw_per_chip.z,
        iters=iters, devices=devices, weak=True, chunk=chunk,
    )
    c3_eff = base["exchange_weak_trimean_s"] / c3["trimean_s"]
    rows.append(("config3_exchange_weak", c3["x"], c3["y"], c3["z"], n,
                 c3["trimean_s"], c3["gb_per_s"], c3_eff))

    # -- config 5: overlapped jacobi + hidden fraction ----------------------
    # deep_halo lets the fused loop temporally block across chips (one
    # radius-k exchange per k steps); the anchor is a 256^3 single-chip run
    # of the SAME configuration so the efficiency column measures scaling,
    # not temporal-blocking availability
    c5 = jacobi3d.run(
        per_chip.x, per_chip.y, per_chip.z,
        iters=jacobi_iters, overlap=True, devices=devices, weak=True,
        deep_halo=deep_halo, chunk=min(chunk, jacobi_iters),
    )
    jac_eff = c5["mcells_per_s_per_dev"] / base["jacobi_mcells_per_s_per_dev"]
    rows.append(("config5_jacobi_overlap", c5["x"], c5["y"], c5["z"], n,
                 c5["iter_trimean_s"], c5["mcells_per_s_per_dev"], jac_eff))

    ov = measure_overlap.run(
        per_chip.x, per_chip.y, per_chip.z,
        radius=1, iters=max(10, iters // 3), rounds=overlap_rounds,
        devices=devices, weak=True, use_pallas=use_pallas,
    )
    rows.append(("config5_hidden_frac", ov["x"], ov["y"], ov["z"], n,
                 ov["overlap_s"], ov["hidden_s"], ov["hidden_frac"]))

    rec = telemetry.get()
    if rec.enabled:
        for name, _x, _y, _z, _n, secs, thr, eff in rows:
            rec.gauge(f"weak.{name}.seconds", secs, phase="scaling", unit="s")
            rec.gauge(f"weak.{name}.efficiency", eff, phase="scaling")
    return {
        "devices": n,
        "rows": rows,
        "results": {"config2": c2, "config3": c3, "config5": c5,
                    "overlap": ov},
    }


# `metric` is per-row heterogeneous (GB/s for the exchange configs,
# Mcells/s/chip for jacobi, hidden seconds for the overlap instrument) —
# rows are keyed by `config`, so never aggregate the column across rows.
CSV_HEADER = "config,x,y,z,devices,seconds,metric,efficiency"


def csv_rows(res: dict) -> list:
    out = [CSV_HEADER]
    for name, x, y, z, n, secs, thr, eff in res["rows"]:
        out.append(f"{name},{x},{y},{z},{n},{secs:e},{thr:.3f},{eff:.4f}")
    return out


def record_base(devices=None, iters: int = 360, path: str = "") -> dict:
    """Measure the single-chip anchors and write them to ``path``.

    Large fused chunks: the tunneled single-chip platform pays ~87 ms per
    dispatch, which would dominate any per-10-iteration chunk (a first
    recording with chunk 10 read 5x slow across the board)."""
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) != 1:
        raise ValueError("--record-base wants exactly one device")
    chunk = max(1, iters // 3)
    c2 = bench_exchange.run(256, 256, 256, iters=iters, quantities=4,
                            devices=devices, chunk=chunk)[-1]  # "uniform/2"
    c3 = exchange_weak.run(512, 512, 512, iters=iters, devices=devices,
                           chunk=chunk)
    # same shape as run()'s config 5: 256^3 per chip, deep_halo fused loop
    c5 = jacobi3d.run(256, 256, 256, iters=iters, overlap=True,
                      devices=devices, weak=False, deep_halo=4, chunk=chunk)
    base = {
        "jacobi_mcells_per_s_per_dev": c5["mcells_per_s_per_dev"],
        "exchange_weak_trimean_s": c3["trimean_s"],
        "config2_trimean_s": c2["trimean_s"],
    }
    path = path or _base_path()
    # tmp+fsync+rename: the recorded base anchors every later weak-scaling
    # column — a torn write must never replace a good one
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(base, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    log.info(f"single-chip base recorded to {path}: {base}")
    return base


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="weak-scaling day-1 harness")
    p.add_argument("--cpu", type=int, default=0, help="virtual CPU devices")
    p.add_argument("--iters", type=int, default=None,
                   help="timed iterations (default 30; 360 for --record-base "
                        "— anchors need large fused chunks on the tunneled "
                        "single chip)")
    p.add_argument("--jacobi-iters", type=int, default=60)
    p.add_argument("--smoke", action="store_true",
                   help="tiny sizes for the virtual-mesh smoke test")
    p.add_argument("--base", default="", help="single-chip anchors JSON")
    p.add_argument("--record-base", action="store_true",
                   help="measure + write the single-chip anchors (1 chip)")
    p.add_argument("--out", default="", help="also append CSV to this file")
    p.add_argument("--pallas", dest="use_pallas", action="store_true",
                   default=None, help="force the Pallas overlap variant")
    from ._bench_common import add_metrics_flags, start_metrics
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    # the config 2/3/5 sub-apps all record through this process recorder
    start_metrics(args, "weak_scaling")

    if args.record_base:
        record_base(iters=args.iters or 360, path=args.base)
        return 0

    base = None
    base_path = args.base or _base_path()
    if os.path.exists(base_path):
        with open(base_path) as f:
            base = json.load(f)

    kw = {}
    if args.smoke:
        kw = dict(per_chip=Dim3(32, 32, 32), exw_per_chip=Dim3(32, 32, 32),
                  config2_global=Dim3(32, 32, 32), iters=4, jacobi_iters=4,
                  overlap_rounds=1)
    else:
        kw = dict(iters=args.iters or 30, jacobi_iters=args.jacobi_iters)
    res = run(base=base, use_pallas=args.use_pallas, **kw)

    lines = csv_rows(res)
    for line in lines:
        print(line)
    if args.out:
        new = not os.path.exists(args.out)
        with open(args.out, "a") as f:
            for line in lines if new else lines[1:]:
                f.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
