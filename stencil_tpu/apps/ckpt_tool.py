"""ckpt_tool — inspect / validate / diff checkpoint snapshots.

The operator's window into the elastic checkpoint format (ckpt/) and the
CI integrity gate:

- ``inspect PATH``   print a snapshot's manifest summary (PATH may be a
                     snapshot dir or a checkpoint dir — the latter
                     resolves through ``LATEST``).
- ``validate PATH``  full integrity check (manifest schema, payload byte
                     counts + SHA-256, block coverage); ``--all`` checks
                     every snapshot under a checkpoint dir. Exit 1 on any
                     problem — this is the CI gate. ``--quarantine``
                     renames invalid snapshots aside (``quarantine-*``)
                     so auto-resume stops rescanning them on every
                     restart.
- ``diff A B``       compare two snapshots' metadata; ``--data``
                     additionally reassembles every quantity's global
                     interior from both and requires bit-equality (the
                     save->kill->resume == uninterrupted proof in CI).
                     Exit 1 on any difference.

Pure numpy + stdlib at runtime (no jax backend is initialized), so it
runs anywhere the snapshot files are mountable.

Usage: python -m stencil_tpu.apps.ckpt_tool validate runs/ckpt
"""

from __future__ import annotations

import argparse
import json
import os
from typing import List, Optional

import numpy as np

from ..ckpt import (
    LATEST_NAME,
    assemble_global,
    list_snapshots,
    load_manifest,
    read_latest,
    validate_snapshot,
)


def resolve_snapshot(path: str) -> str:
    """PATH -> snapshot dir: either PATH is one (has a manifest) or it is
    a checkpoint dir whose LATEST/newest snapshot is taken."""
    if os.path.isfile(os.path.join(path, "manifest.json")):
        return path
    latest = read_latest(path)
    if latest and os.path.isdir(os.path.join(path, latest)):
        return os.path.join(path, latest)
    snaps = list_snapshots(path)
    if snaps:
        return os.path.join(path, snaps[-1])
    raise SystemExit(f"ckpt_tool: no snapshot found at {path}")


def _summary(snap: str, m: dict) -> str:
    g, p = m["global"], m["partition"]
    nbytes = sum(f["bytes"] for f in m["files"])
    qs = ", ".join(f"{q['name']}:{q['dtype']}" for q in m["quantities"])
    return (
        f"{snap}\n"
        f"  step      {m['step']}\n"
        f"  global    ({g['x']},{g['y']},{g['z']})  "
        f"partition ({p['x']},{p['y']},{p['z']})\n"
        f"  quantities {qs}\n"
        f"  files     {len(m['files'])}  bytes {nbytes}\n"
    )


def cmd_inspect(args) -> int:
    snap = resolve_snapshot(args.path)
    m = load_manifest(snap)
    if args.json:
        print(json.dumps(m, indent=1))
    else:
        print(_summary(snap, m), end="")
    return 0


def cmd_validate(args) -> int:
    targets: List[str] = []
    if args.all:
        snaps = list_snapshots(args.path)
        if not snaps:
            print(f"ckpt_tool: no snapshots under {args.path}")
            return 1
        targets = [os.path.join(args.path, s) for s in snaps]
    else:
        targets = [resolve_snapshot(args.path)]
    rc = 0
    for snap in targets:
        errs = validate_snapshot(snap, deep=not args.shallow)
        if errs:
            rc = 1
            print(f"INVALID {snap}")
            for e in errs:
                print(f"  - {e}")
            if args.quarantine:
                from ..ckpt import quarantine_snapshot

                ckpt_dir, name = os.path.split(os.path.normpath(snap))
                dest = quarantine_snapshot(ckpt_dir or ".", name,
                                           reason=errs[0])
                if dest:
                    print(f"  quarantined -> {os.path.basename(dest)}")
        else:
            print(f"ok {snap}")
    if args.all:
        latest = read_latest(args.path)
        if latest and not os.path.isdir(os.path.join(args.path, latest)):
            print(f"INVALID {LATEST_NAME} -> missing snapshot {latest}")
            rc = 1
    return rc


def _meta_diffs(a: dict, b: dict) -> List[str]:
    out = []
    for key in ("v", "payload", "global", "partition"):
        if a.get(key) != b.get(key):
            out.append(f"{key}: {a.get(key)!r} != {b.get(key)!r}")
    qa = {q["name"]: q["dtype"] for q in a["quantities"]}
    qb = {q["name"]: q["dtype"] for q in b["quantities"]}
    if qa != qb:
        out.append(f"quantities: {qa!r} != {qb!r}")
    if a["step"] != b["step"]:
        out.append(f"step: {a['step']} != {b['step']}")
    return out


def cmd_diff(args) -> int:
    sa, sb = resolve_snapshot(args.a), resolve_snapshot(args.b)
    ma, mb = load_manifest(sa), load_manifest(sb)
    diffs = _meta_diffs(ma, mb)
    if getattr(args, "elastic", False):
        # elastic comparison: the two snapshots may legitimately live on
        # different partitions of the SAME global grid (a mesh-reshape
        # resume, or a mid-run plan hot-swap) — the claim under test is
        # the assembled payload, so a partition-only meta delta is not a
        # difference. Grid/quantity/step deltas still are.
        diffs = [d for d in diffs if not d.startswith("partition")]
    # data comparison only makes sense on a shared grid + quantity set
    comparable = not any(d.startswith(("global", "quantities")) for d in diffs)
    if args.data and comparable:
        for q in ma["quantities"]:
            name = q["name"]
            ga = assemble_global(sa, ma, name)
            gb = assemble_global(sb, mb, name)
            if ga.dtype != gb.dtype:
                diffs.append(f"data[{name}]: dtype {ga.dtype} != {gb.dtype}")
            elif not np.array_equal(ga, gb, equal_nan=True):
                n = int(np.sum(ga != gb))
                with np.errstate(invalid="ignore"):
                    mx = float(np.nanmax(np.abs(
                        ga.astype(np.float64) - gb.astype(np.float64))))
                diffs.append(
                    f"data[{name}]: {n} differing cells, max |delta| {mx:g}"
                )
    elif args.data:
        diffs.append("data: skipped (grids/quantity sets differ)")
    if diffs:
        print(f"DIFFER {sa} vs {sb}")
        for d in diffs:
            print(f"  - {d}")
        return 1
    print(f"identical {sa} == {sb}"
          + (" (bit-exact payloads)" if args.data else " (metadata)"))
    return 0


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        description="inspect / validate / diff checkpoint snapshots"
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    pi = sub.add_parser("inspect", help="print a snapshot's manifest summary")
    pi.add_argument("path")
    pi.add_argument("--json", action="store_true",
                    help="dump the full manifest as JSON")
    pi.set_defaults(fn=cmd_inspect)
    pv = sub.add_parser("validate", help="integrity-check snapshot(s)")
    pv.add_argument("path")
    pv.add_argument("--all", action="store_true",
                    help="validate every snapshot under a checkpoint dir")
    pv.add_argument("--shallow", action="store_true",
                    help="skip SHA-256 (byte counts + coverage only)")
    pv.add_argument("--quarantine", action="store_true",
                    help="rename invalid snapshots aside (quarantine-*) so "
                         "auto-resume stops rescanning them on every "
                         "restart; the bytes stay on disk as evidence")
    pv.set_defaults(fn=cmd_validate)
    pd = sub.add_parser("diff", help="compare two snapshots")
    pd.add_argument("a")
    pd.add_argument("b")
    pd.add_argument("--elastic", action="store_true",
                    help="ignore partition-shape meta deltas: compare "
                         "two partitions of the same global grid (a "
                         "mesh-reshape resume or a mid-run plan "
                         "hot-swap) by their assembled payloads")
    pd.add_argument("--data", action="store_true",
                    help="also require bit-exact payload equality")
    pd.set_defaults(fn=cmd_diff)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
