"""measure_overlap — does the fused step actually hide the exchange?

TPU-native analogue of the reference's ``measure-buf-exchange``
(reference: bin/measure_buf_exchange.cu:10-19), which timed a spin kernel
concurrent with peer copies to demonstrate stream overlap. Here overlap is
XLA's scheduling of the halo ``ppermute``s concurrently with the interior
sweep inside one jitted step, so the measurement is four timed variants of
the same jacobi workload on the same mesh:

- ``compute``:  full sweep, no exchange at all (the compute floor)
- ``exchange``: exchange only (the communication cost)
- ``serial``:   exchange-then-full-sweep in one jit (overlap=False path)
- ``overlap``:  interior sweep / exchange / exterior sweeps in one jit
                (overlap=True path — the reference's signature structure,
                bin/jacobi3d.cu:296-368)

Reported: ``hidden = t_serial - t_overlap`` (the exchange time the
overlapped structure recovers) and ``hidden_frac = hidden / t_exchange``
(1.0 = the exchange is fully hidden behind interior compute; <= 0 = the
structure hides nothing). ``--trace DIR`` additionally writes a
``jax.profiler`` trace of one overlapped chunk for inspection in
TensorBoard/Perfetto — the nsys-workflow analogue (reference:
README.md:91-130).

CSV: devices,x,y,z,radius,iters,compute_s,exchange_s,serial_s,overlap_s,
hidden_s,hidden_frac

Note: with --pallas the serial/overlap variants run the fused-kernel fast
path (the overlap variant is the full-sweep-on-pre-exchange-data + shell
patch structure of ops/jacobi.py; its dataflow independence is machine-
checked by tests/test_overlap_hlo.py). Pallas kernels execute on TPU
only, so --pallas requires real chips — the default XLA path is what the
virtual CPU mesh can run.

Usage: python -m stencil_tpu.apps.measure_overlap --cpu 8 --x 64
"""

from __future__ import annotations

import argparse
import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..api import DistributedDomain
from ..geometry import Dim3, Rect3
from ..ops.jacobi import INIT_TEMP, jacobi_sweep, make_jacobi_loop, sphere_sel
from ..parallel.exchange import BLOCK_PSPEC, shard_blocks
from ..utils import logging as log
from ..utils import timer
from ..utils.statistics import Statistics
from ..utils.sync import hard_sync
from .jacobi3d import weak_scale


def _compute_only_loop(dd: DistributedDomain, iters: int):
    """Full-region sweep with NO exchange — the compute floor."""
    spec = dd.spec
    off = spec.compute_offset()
    compute = Rect3(off, off + spec.base)

    def body(curr, nxt):
        out = jacobi_sweep(curr, nxt, compute)
        return out, curr

    def many(curr, nxt):
        return jax.lax.fori_loop(0, iters, lambda _, cn: body(*cn), (curr, nxt))

    fn = jax.shard_map(
        many,
        mesh=dd.mesh,
        in_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
        out_specs=(BLOCK_PSPEC, BLOCK_PSPEC),
    )
    return jax.jit(fn, donate_argnums=(0, 1))


def _time(fn, state, rounds: int, bucket: str):
    state = fn(*state) if isinstance(state, tuple) else fn(state)
    hard_sync(state)
    st = Statistics()
    for _ in range(rounds):
        t0 = time.perf_counter()
        with timer.timed(bucket):
            state = fn(*state) if isinstance(state, tuple) else fn(state)
            hard_sync(state)
        st.insert(time.perf_counter() - t0)
    return st.trimean(), state


def run(
    x: int = 64,
    y: int = 64,
    z: int = 64,
    radius: int = 1,
    iters: int = 10,
    rounds: int = 3,
    devices=None,
    weak: bool = True,
    use_pallas: Optional[bool] = False,
    trace_dir: str = "",
) -> dict:
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    size = weak_scale(x, y, z, n) if weak else Dim3(x, y, z)

    dd = DistributedDomain(size.x, size.y, size.z)
    dd.set_radius(radius)
    dd.set_devices(devices)
    h = dd.add_data("temperature", "float32")
    dd.realize()
    sharding = dd.sharding()
    shape = dd.spec.stacked_shape_zyx()
    dd.set_curr(h, jax.device_put(jnp.full(shape, INIT_TEMP, jnp.float32), sharding))
    sel = shard_blocks(sphere_sel(size), dd.spec, dd.mesh)
    curr, nxt = dd.get_curr(h), dd.get_next(h)

    ex = dd.halo_exchange
    t_comp, (curr, nxt) = _time(
        _compute_only_loop(dd, iters), (curr, nxt), rounds, "overlap.compute"
    )
    t_exch, state = _time(ex.make_loop(iters), {0: curr}, rounds, "overlap.exchange")
    curr = state[0]
    serial_fn = make_jacobi_loop(ex, iters, overlap=False, use_pallas=use_pallas)
    t_serial, (curr, nxt) = _time(
        lambda c, x_: serial_fn(c, x_, sel), (curr, nxt), rounds, "overlap.serial"
    )
    overlap_fn = make_jacobi_loop(ex, iters, overlap=True, use_pallas=use_pallas)
    t_overlap, (curr, nxt) = _time(
        lambda c, x_: overlap_fn(c, x_, sel), (curr, nxt), rounds, "overlap.overlap"
    )

    if trace_dir:
        with jax.profiler.trace(trace_dir):
            curr, nxt = overlap_fn(curr, nxt, sel)
            hard_sync(curr)
        log.info(f"profiler trace written under {trace_dir}")

    hidden = t_serial - t_overlap
    hidden_frac = hidden / t_exch if t_exch > 0 else 0.0
    return {
        "devices": n,
        "x": size.x,
        "y": size.y,
        "z": size.z,
        "radius": radius,
        "iters": iters,
        "compute_s": t_comp,
        "exchange_s": t_exch,
        "serial_s": t_serial,
        "overlap_s": t_overlap,
        "hidden_s": hidden,
        "hidden_frac": hidden_frac,
        "domain": dd,
    }


def csv_row(r: dict) -> str:
    return (
        f"measure_overlap,{r['devices']},{r['x']},{r['y']},{r['z']},{r['radius']},"
        f"{r['iters']},{r['compute_s']:.6f},{r['exchange_s']:.6f},"
        f"{r['serial_s']:.6f},{r['overlap_s']:.6f},{r['hidden_s']:.6f},"
        f"{r['hidden_frac']:.3f}"
    )


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="comm/compute overlap measurement (TPU)")
    p.add_argument("--x", type=int, default=64)
    p.add_argument("--y", type=int, default=64)
    p.add_argument("--z", type=int, default=64)
    p.add_argument("--radius", type=int, default=1)
    p.add_argument("--iters", type=int, default=10, help="iterations per fused chunk")
    p.add_argument("--rounds", type=int, default=3, help="timed chunks per variant")
    p.add_argument("--no-weak", action="store_true")
    p.add_argument("--pallas", action="store_true",
                   help="measure the Pallas sweep path instead of XLA")
    p.add_argument("--trace", type=str, default="",
                   help="write a jax.profiler trace of one overlapped chunk here")
    p.add_argument("--cpu", type=int, default=0, help="force N virtual CPU devices")
    from ._bench_common import add_metrics_flags, finish_metrics, start_metrics
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    rec = start_metrics(args, "measure_overlap")
    r = run(
        args.x, args.y, args.z,
        radius=args.radius,
        iters=args.iters,
        rounds=args.rounds,
        devices=jax.devices()[: args.cpu] if args.cpu else None,
        weak=not args.no_weak,
        use_pallas=True if args.pallas else False,
        trace_dir=args.trace,
    )
    print(csv_row(r))
    log.info(
        f"exchange {r['exchange_s']*1e3:.2f} ms/chunk, hidden "
        f"{r['hidden_s']*1e3:.2f} ms ({r['hidden_frac']*100:.0f}% of exchange)"
    )
    log.info(timer.report())
    for key in ("compute_s", "exchange_s", "serial_s", "overlap_s", "hidden_s"):
        rec.gauge(f"overlap.{key}", r[key], phase="step", unit="s")
    rec.gauge("overlap.hidden_frac", r["hidden_frac"], phase="step")
    finish_metrics(rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
