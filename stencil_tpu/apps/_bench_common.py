"""Shared machinery for the exchange benchmarks (exchange_weak,
exchange_strong, bench_exchange): build a domain, run fused exchange loops,
report trimean statistics — the structure of the reference's timed exchange
loop (reference: bin/exchange_weak.cu:140-196)."""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..api import DistributedDomain
from ..geometry import Dim3, Radius
from ..obs import telemetry
from ..parallel import IntraNodeRandom, Method, NodeAware, Trivial
from ..utils.statistics import Statistics
from ..utils.sync import hard_sync


def add_metrics_flags(p, dma: bool = False) -> None:
    """The flight-recorder flags every bench app shares; ``dma=True`` adds
    the static-DMA-truth opt-in for apps with a Pallas fast path."""
    p.add_argument(
        "--metrics-out",
        default=os.environ.get("STENCIL_METRICS_OUT", ""),
        help="append telemetry records (one JSON object per line; schema "
             "stencil_tpu/obs/telemetry.py, aggregated by apps/report.py) "
             "to this file",
    )
    p.add_argument("--run-id", default="",
                   help="telemetry run id (default: generated)")
    if dma:
        p.add_argument(
            "--metrics-dma", action="store_true",
            help="also record the compiled Mosaic kernels' static per-pass "
                 "DMA bytes (a full TPU lowering; needs the Pallas fast "
                 "path)",
        )


def start_metrics(args, app: str) -> "telemetry.Recorder":
    """Install the process-default recorder from parsed flags.

    The run's argv config rides along as the first meta record, so a
    metrics file is self-describing. Apps call this AFTER any --cpu
    backend configuration (recording must never pin the platform)."""
    return telemetry.configure(
        metrics_out=getattr(args, "metrics_out", "") or None,
        app=app,
        run_id=getattr(args, "run_id", "") or None,
        config=vars(args),
    )


def add_live_flags(p) -> None:
    """The live-observability flags (obs/live.py + obs/status.py) the
    guarded apps share: an in-run anomaly sentinel over the chunk-cycle
    step latency, and an atomic run-status snapshot file."""
    p.add_argument(
        "--status-file", default=os.environ.get("STENCIL_STATUS_FILE", ""),
        help="rewrite an atomic run-status snapshot here every chunk "
             "(step, throughput, health counts, anomalies; read it with "
             "`report --status FILE [--follow]`)",
    )
    p.add_argument(
        "--live-sentinel", action="store_true",
        help="in-run anomaly detection: judge each chunk's per-step "
             "latency against a streaming trimean±MAD band (obs/live.py); "
             "excursions emit anomaly.detected / replan.requested records "
             "mid-run and show in the status snapshot",
    )
    p.add_argument(
        "--live-config", default="",
        help="sentinel knobs as JSON (inline '{...}' or a file path): "
             "{\"*\": {window, min_history, mad_k, rel_tol, abs_tol, "
             "direction, clear_after}, \"<key>\": {...}} — the perf_tool "
             "--leg-config shape",
    )


def load_live_config(value: str) -> dict:
    """Parse --live-config: an inline JSON object or a JSON file path.
    Raises OSError/ValueError — the apps pre-validate at parse time and
    map both to a clean argparse error, never a traceback."""
    import json

    if not value:
        return {}
    if value.lstrip()[:1] in ("{", "["):  # inline JSON, not a path
        text = value
    else:
        with open(value) as f:
            text = f.read()
    cfg = json.loads(text)  # JSONDecodeError is a ValueError
    if not isinstance(cfg, dict):
        raise ValueError("--live-config must be a JSON object")
    from ..obs.live import validate_config

    errs = validate_config(cfg)
    if errs:
        raise ValueError("; ".join(errs))
    return cfg


def canonicalize_live_config(args) -> dict:
    """Parse-time validation of ``--live-config`` that ALSO rewrites the
    flag to canonical inline JSON, so ``make_live`` never re-reads a
    file (the validated content is what runs — no window for the file
    to change or vanish between argparse and backend init). Returns the
    parsed config; raises OSError/ValueError for the app's ``p.error``."""
    import json

    cfg = load_live_config(getattr(args, "live_config", ""))
    args.live_config = json.dumps(cfg) if cfg else ""
    return cfg


def make_live(args, rec: "telemetry.Recorder", app: str):
    """Build the (sentinel, status writer) pair from parsed flags —
    (None, None) when neither live flag is set."""
    sentinel = status = None
    if getattr(args, "live_sentinel", False):
        from ..obs.live import LiveSentinel

        sentinel = LiveSentinel(
            load_live_config(getattr(args, "live_config", "")), rec=rec)
    if getattr(args, "status_file", ""):
        from ..obs.status import StatusWriter

        status = StatusWriter(args.status_file, app=app, run=rec.run_id)
    return sentinel, status


def finish_live(rec: "telemetry.Recorder", sentinel, status,
                outcome: Optional[str] = None, gauge: bool = True) -> None:
    """The live epilogue: the run's anomaly count lands as a gauge (so
    metrics-JSONL ingest puts in-run instability in the LEDGER, where
    the cross-run sentinel sees it), and the final status snapshot gets
    its outcome. ``gauge=False`` for callers whose engine already
    emitted the count (the campaign driver does)."""
    if gauge and sentinel is not None and rec.enabled:
        rec.gauge("live.anomaly_count", float(sentinel.detected_total),
                  phase="live")
    if status is not None:
        status.update(outcome=outcome,
                      anomalies=(sentinel.summary()
                                 if sentinel is not None else None))


def finish_metrics(rec: "telemetry.Recorder") -> None:
    """The apps' shared exit epilogue: snapshot the global timer buckets
    as gauges and close the sink (no-op on a disabled recorder)."""
    if rec.enabled:
        rec.record_timer_buckets()
        rec.close()


def resume_from_checkpoint(dd, ckpt_dir: str, iters: int) -> int:
    """The apps' shared resume policy (jacobi3d, astaroth): restore the
    newest valid compatible snapshot, warn when it is beyond the run's
    target (and never re-label it — step accounting stays truthful),
    record the resumed-from-step gauge, and return the start step
    (0 = fresh start)."""
    from ..utils import logging as log

    restored = dd.restore_checkpoint(ckpt_dir)
    if restored is None:
        return 0
    if restored > iters:
        log.warn(f"checkpoint step {restored} is beyond the target {iters}; "
                 "nothing to run and the snapshot is NOT relabeled")
    start = min(restored, iters)
    telemetry.get().gauge("ckpt.resumed_from_step", start, phase="ckpt")
    log.info(f"resuming from checkpointed step {start}")
    return start


def coord_state(dd, quantities: int):
    """Deterministic per-quantity coordinate fields on a realized domain
    (value = z*1e6 + y*1e3 + x + quantity index) — the bit-for-bit
    agreement fixture shared by the method-ablation harness and the
    exchange tests (same idiom as tests/test_exchange.py; reference:
    test_cuda_mpi_distributed_domain.cu:11-17)."""
    import numpy as np

    from ..parallel.exchange import shard_blocks

    g = dd.spec.global_size
    coord = (
        np.arange(g.z)[:, None, None] * 1_000_000.0
        + np.arange(g.y)[None, :, None] * 1_000.0
        + np.arange(g.x)[None, None, :]
    ).astype(np.float32)
    return {
        i: shard_blocks(coord + i, dd.spec, dd.mesh) for i in range(quantities)
    }


def placement_from_flags(naive: bool, random_: bool):
    """--naive -> Trivial, --random -> IntraNodeRandom, default NodeAware
    (reference: bin/exchange_weak.cu:149-153, exchange_strong.cu)."""
    if naive:
        return Trivial()
    if random_:
        return IntraNodeRandom()
    return NodeAware()


def time_exchange(
    size: Dim3,
    radius: Radius,
    iters: int,
    method: Method = Method.AXIS_COMPOSED,
    devices: Optional[Sequence] = None,
    placement=None,
    quantities: int = 4,
    dtype: str = "float32",
    chunk: int = 10,
    prefix: str = "",
    batch_quantities: bool = True,
    partition=None,
    wire_dtype=None,
    fused: bool = False,
    hierarchy=None,
) -> dict:
    """Realize a domain with ``quantities`` quantities and time ``iters``
    exchanges in fused chunks. Returns stats + the domain.

    ``batch_quantities=False`` times the historical
    one-collective-per-quantity program (the ``--batched-ab`` baseline);
    ``partition`` forces the block grid (e.g. ``(2, 2, 2)``) so A/B runs
    pin the mesh instead of trusting the auto-partitioner; ``wire_dtype``
    turns on the (lossy) bf16/fp8-on-the-wire carrier compression;
    ``fused`` times the fused compute+exchange variant's concurrent
    per-direction transport (REMOTE_DMA only — the autotuner's fused
    candidates probe through here). ``placement`` is a Placement
    strategy OR a plain assignment tuple (``PlanChoice.placement`` —
    wrapped in :class:`~stencil_tpu.parallel.FixedAssignment` so placed
    plan candidates probe on exactly their tuned mesh). ``hierarchy``
    is the outer DCN split ``(axis, hosts)`` — hierarchical candidates
    time the two-level transport (DCN slabs overlapped behind the inner
    phases), on whatever host fabric the process sees
    (STENCIL_VIRTUAL_HOSTS in-process)."""
    devices = list(devices) if devices is not None else jax.devices()
    if placement is not None and not hasattr(placement, "arrange"):
        from ..parallel import FixedAssignment

        placement = FixedAssignment(placement)
    dd = DistributedDomain(size.x, size.y, size.z)
    dd.set_radius(radius)
    dd.set_methods(method)
    dd.set_quantity_batching(batch_quantities)
    if fused:
        dd.set_fused_exchange(True)
    if hierarchy is not None:
        dd.set_hierarchy(hierarchy)
    if wire_dtype:
        dd.set_wire_dtype(wire_dtype)
    if partition is not None:
        dd.set_partition(partition)
    dd.set_devices(devices)
    if placement is not None:
        dd.set_placement(placement)
    if prefix:
        dd.set_output_prefix(prefix)
    for i in range(quantities):
        dd.add_data(f"d{i}", dtype)
    dd.realize()

    rec = telemetry.get()
    itemsizes = [jnp.dtype(dtype).itemsize] * quantities
    state = dd.curr_state()
    chunk = max(1, min(chunk, iters))
    tail = iters % chunk
    loops = {chunk: dd.halo_exchange.make_loop(chunk)}
    if tail:
        loops[tail] = dd.halo_exchange.make_loop(tail)
    # the wire tag keeps a --wire-ab run's legs separable in aggregation
    # (report._agg_key splits on it, like method/batched); the variant
    # tag does the same for the fused A/B legs
    wtag = {"wire": str(wire_dtype)} if wire_dtype else {}
    if fused:
        wtag["variant"] = "fused"
    if hierarchy is not None:
        # keeps a hierarchical-vs-flat A/B's legs separable in
        # aggregation, like wire/variant above
        wtag["hierarchy"] = f"{hierarchy[0]}{hierarchy[1]}"
    # compile + warm every loop size OUTSIDE the timed region
    with rec.span("exchange.warmup", phase="compile", method=method.value,
                  batched=batch_quantities, **wtag):
        for fn in loops.values():
            state = fn(state)
        hard_sync(state)
    census = None
    if rec.enabled:
        # compile-time truth: census the compiled single-exchange program
        # (exact on-wire volume) alongside the measured times below; the
        # census rides the result so callers (ablate) never recompile it
        # the batched tag keeps A/B runs separable in the aggregated
        # gauges: without it the permutes_per_quantity tripwire would
        # average the batched leg with its per-quantity baseline
        census = telemetry.record_exchange_truth(
            dd.halo_exchange, state, itemsizes, batched=batch_quantities,
            **wtag)

    stats = Statistics()
    samples = []
    done = 0
    while done < iters:
        k = min(chunk, iters - done)
        t0 = time.perf_counter()
        state = loops[k](state)
        hard_sync(state)
        per = (time.perf_counter() - t0) / k
        stats.insert(per)
        samples.append(per)
        rec.emit("span", "exchange.iter", phase="exchange", seconds=per,
                 iters=k, method=method.value, batched=batch_quantities,
                 **wtag)
        done += k
    dd._curr = dict(state)  # the loops donated the original buffers
    if rec.enabled:
        # per-phase attribution: pair the installed cost model's
        # prediction for THIS realized plan with the measured samples
        # above — one plan.attrib.phase record per sample, the raw
        # material of `plan_tool calibrate` and `perf_tool drift`
        from ..obs import attribution
        from ..plan.ir import PlanChoice, PlanConfig
        from .machine_info import fabric_fingerprint

        pm = dd.plan_meta()
        pchoice = PlanChoice.from_json(pm["choice"])
        attribution.attribute_and_judge(
            rec,
            PlanConfig.from_json(pm["key"]),
            pchoice,
            samples,
            phase="exchange.iter",
            kernel_variant="fused" if fused else None,
            fabric=fabric_fingerprint(devices=devices),
        )
        # the run's plan identity — the join key between this metrics
        # file, the plan DB, and any fitted calibration row
        rec.meta("plan.fingerprint", fingerprint=pchoice.fingerprint(),
                 choice=pchoice.label(), calibration="modeled(default)",
                 **wtag)
    if rec.enabled:
        rec.gauge("exchange.trimean_s", stats.trimean(), phase="exchange",
                  unit="s", method=method.value, batched=batch_quantities,
                  **wtag)
        rec.gauge(
            "exchange.gb_per_s",
            dd.halo_exchange.bytes_logical(itemsizes) / stats.trimean() / 1e9,
            phase="exchange", method=method.value, batched=batch_quantities,
            **wtag,
        )
    return {
        "domain": dd,
        "census": census,
        "stats": stats,
        "trimean_s": stats.trimean(),
        "min_s": stats.min(),
        "bytes_logical": dd.halo_exchange.bytes_logical(itemsizes),
        "bytes_moved": dd.halo_exchange.bytes_moved(itemsizes),
        "gb_per_s": dd.halo_exchange.bytes_logical(itemsizes) / stats.trimean() / 1e9,
        "local_size": dd.spec.base,
        "devices": len(devices),
    }
