"""perf_tool — the performance ledger's CLI: ingest, trend, diff, gate, render.

The cross-run half of the observability stack (`obs/ledger.py` is the
storage + ingest library): rounds of BENCH/MULTICHIP payloads and
metrics-JSONL gauge trimeans land as keyed ledger entries, and this tool
turns the accumulated history into

- ``trend``:  per-leg tables across round labels (value, delta vs prev);
- ``diff``:   one label vs another, per leg;
- ``gate``:   the regression sentinel — a new measurement must sit inside
  its leg's trimean ± MAD tolerance band (per-leg thresholds
  configurable; direction-aware: a throughput leg trips LOW, a
  seconds leg trips HIGH); exits nonzero with a named-leg verdict;
- ``drift``:  the calibration drift sentinel — the installed cost-model
  calibration's per-phase predictions must sit inside the measured
  attribution samples' trimean ± MAD band (``obs/attribution.judge_drift``,
  the same band formula as ``gate``); exits nonzero naming the phase;
- ``render``: a markdown dashboard for CI artifacts;
- ``ingest``: map payload files into the ledger (``--legacy`` for the
  committed BENCH_r0*/MULTICHIP_r0* shapes; metrics JSONL and live
  bench payloads are auto-detected).

Usage:
  python -m stencil_tpu.apps.perf_tool ingest --ledger LEDGER.jsonl --legacy BENCH_r0*.json MULTICHIP_r0*.json
  python -m stencil_tpu.apps.perf_tool trend --ledger LEDGER.jsonl [--metric LEG ...]
  python -m stencil_tpu.apps.perf_tool gate --ledger LEDGER.jsonl --metric LEG [--label L] [--rel-tol 0.1]
  python -m stencil_tpu.apps.perf_tool render --ledger LEDGER.jsonl --out dashboard.md
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import ledger
from .report import _rows_to_table
# the band/direction semantics are shared with the IN-run sentinel
# (obs/live.py is the one authority; this module applies them to the
# cross-run ledger, live.py to streaming chunk latencies)
from ..obs.live import base_metric, default_direction  # noqa: F401


_ROUND_LABEL_RE = re.compile(r"^r(\d+)$")


def order_key(e: dict) -> Tuple:
    """Round ordering within a trend group.

    ``rNN`` round labels order by their round NUMBER — a round
    BACKFILLED after later rounds (``ingest --legacy BENCH_r03.json``
    stamps r03 with today's ``t``) keeps its round position instead of
    becoming the trend's "latest" and the gate's default judged label.
    Every other label (live ``bench-<timestamp>`` appends, gate ``runN``
    labels, ad-hoc ingests) orders by measurement time AFTER the rNN
    prehistory — plain lexicographic label order would sort the default
    bench label ("b" < "r") before r01, hiding a freshly appended
    regression from the no-``--label`` gate entirely."""
    m = _ROUND_LABEL_RE.match(e["label"])
    if m:
        return (0, int(m.group(1)), e["t"], e["label"])
    return (1, e["t"], e["label"])


def groups(entries: Sequence[dict],
           metrics: Optional[Sequence[str]] = None,
           platform: Optional[str] = None) -> Dict[Tuple, List[dict]]:
    """Fold entries into trend groups keyed by (metric, platform,
    config fingerprint), each round-ordered via :func:`order_key`.

    Platform-"unknown" entries of a metric (outage rounds — the driver
    cannot know the platform of a run that produced no payload, cf. the
    BENCH_r03 zero) join EVERY platform-tagged group of that metric, so
    the trend shows the zero / the rc=1 inside the real trajectory
    instead of an isolated single-entry group nobody reads. They stand
    alone only when no platform-tagged group of the metric exists
    (e.g. the MULTICHIP docs, which are all "unknown")."""
    out: Dict[Tuple, List[dict]] = {}
    wild: Dict[str, List[dict]] = {}
    for e in entries:
        if metrics and e["metric"] not in metrics and \
                base_metric(e["metric"]) not in metrics:
            continue
        if e["platform"] == "unknown" and platform != "unknown":
            wild.setdefault(e["metric"], []).append(e)
            continue
        if platform and e["platform"] != platform:
            continue
        out.setdefault((e["metric"], e["platform"], e["config"]), []).append(e)
    for metric, es in wild.items():
        keys = [k for k in out if k[0] == metric]
        if keys:
            for k in keys:
                out[k].extend(es)
        else:
            # no platform-tagged group to join — the entries stand alone,
            # INCLUDING under a --platform filter (an all-unknown metric
            # may well belong to the filtered platform; hiding it would
            # silently un-judge e.g. multichip_dryrun_ok under
            # `gate --platform tpu`)
            for e in es:
                out.setdefault((metric, "unknown", e["config"]), []).append(e)
    for v in out.values():
        v.sort(key=order_key)
    return out


def _fmt(v: float) -> str:
    return f"{v:.6g}"


# -- trend / diff -------------------------------------------------------------


def trend_tables(entries: Sequence[dict],
                 metrics: Optional[Sequence[str]] = None,
                 platform: Optional[str] = None,
                 markdown: bool = False) -> str:
    """Per-leg trajectory across labels: value, unit, platform, rev, and
    the ratio against the previous round of the same leg."""
    gs = groups(entries, metrics, platform)
    if not gs:
        return ("_no ledger entries match_" if markdown
                else "# no ledger entries match")
    lines: List[str] = []
    for (metric, plat, cfg), es in sorted(gs.items()):
        title = f"{metric} · {plat} · cfg {cfg}"
        lines.append(f"\n**{title}**" if markdown else f"# {title}")
        rows = []
        prev: Optional[float] = None
        for e in es:
            delta = "-" if prev in (None, 0) else f"{e['value'] / prev:.3f}x"
            rows.append([e["label"], _fmt(e["value"]), e.get("unit") or "-",
                         e.get("rev") or "-", e["source"], delta])
            prev = e["value"]
        lines += _rows_to_table(
            ["label", "value", "unit", "rev", "source", "vs_prev"],
            rows, markdown)
    return "\n".join(lines).lstrip("\n")


def trend_json(entries: Sequence[dict],
               metrics: Optional[Sequence[str]] = None,
               platform: Optional[str] = None,
               gate_args: Optional[dict] = None) -> dict:
    """Machine-readable trend: the per-leg trajectory PLUS each leg's
    sentinel verdict, as one JSON document — so CI archives the trend as
    an artifact instead of scraping the markdown table. Same grouping/
    ordering as :func:`trend_tables`; verdicts come from
    :func:`evaluate_gate` with default (or ``gate_args``) thresholds on
    each leg's newest label."""
    gs = groups(entries, metrics, platform)
    verdicts = {
        (v["metric"], v["platform"], v["config"]): v
        for v in evaluate_gate(entries, metrics=metrics, platform=platform,
                               **(gate_args or {}))
    }
    legs = []
    for (metric, plat, cfg), es in sorted(gs.items()):
        points = []
        prev: Optional[float] = None
        for e in es:
            points.append({
                "label": e["label"],
                "value": e["value"],
                "unit": e.get("unit"),
                "rev": e.get("rev"),
                "source": e["source"],
                "t": e["t"],
                "vs_prev": (e["value"] / prev
                            if prev not in (None, 0) else None),
            })
            prev = e["value"]
        legs.append({
            "metric": metric,
            "platform": plat,
            "config": cfg,
            "points": points,
            "verdict": verdicts.get((metric, plat, cfg)),
        })
    return {"kind": "perf-trend", "v": 1,
            "n_entries": len(entries), "legs": legs}


def diff_tables(entries: Sequence[dict], label_a: str, label_b: str,
                markdown: bool = False) -> str:
    """Leg-by-leg comparison of two labels (ratio = B / A)."""
    rows = []
    for (metric, plat, cfg), es in sorted(groups(entries).items()):
        a = [e for e in es if e["label"] == label_a]
        b = [e for e in es if e["label"] == label_b]
        if not a or not b:
            continue
        va, vb = a[-1]["value"], b[-1]["value"]
        rows.append([metric, plat, _fmt(va), _fmt(vb),
                     f"{vb / va:.3f}" if va else "-"])
    if not rows:
        return (f"_no legs present under both {label_a!r} and {label_b!r}_"
                if markdown else
                f"# no legs present under both {label_a!r} and {label_b!r}")
    head = [f"**{label_a} vs {label_b}**"] if markdown else \
        [f"# {label_a} vs {label_b}"]
    return "\n".join(head + _rows_to_table(
        ["metric", "platform", label_a, label_b, "ratio"], rows, markdown))


# -- the regression sentinel --------------------------------------------------


def load_leg_config(path: Optional[str]) -> dict:
    if not path:
        return {}
    with open(path) as f:
        cfg = json.load(f)
    if not isinstance(cfg, dict):
        raise ValueError(f"leg config {path} must be a JSON object")
    return cfg


def evaluate_gate(entries: Sequence[dict], *,
                  metrics: Optional[Sequence[str]] = None,
                  label: Optional[str] = None,
                  mad_k: float = 3.0, rel_tol: float = 0.05,
                  abs_tol: float = 0.0, min_history: int = 1,
                  leg_config: Optional[dict] = None,
                  platform: Optional[str] = None) -> List[dict]:
    """The sentinel: per leg, the newest measurement (or the entries of
    ``label``) is judged against the tolerance band of its history —
    center = trimean, half-width = max(mad_k * MAD, rel_tol * |trimean|,
    abs_tol). Direction-aware (a throughput leg only trips when it falls
    BELOW the band; a seconds leg when it rises above; ``"both"``
    available per leg). Returns one verdict dict per (leg, platform,
    config) group; ``status`` is ``pass`` / ``fail`` / ``skip``."""
    leg_config = leg_config or {}
    verdicts: List[dict] = []
    for (metric, plat, cfg), es in sorted(
            groups(entries, metrics, platform).items()):
        over = dict(leg_config.get("*", {}))
        over.update(leg_config.get(base_metric(metric), {}))
        over.update(leg_config.get(metric, {}))
        k = float(over.get("mad_k", mad_k))
        rtol = float(over.get("rel_tol", rel_tol))
        atol = float(over.get("abs_tol", abs_tol))
        need = int(over.get("min_history", min_history))
        lbl = label or es[-1]["label"]
        new = [e["value"] for e in es if e["label"] == lbl]
        hist = [e["value"] for e in es if e["label"] != lbl]
        v = {"metric": metric, "platform": plat, "config": cfg,
             "label": lbl, "n_history": len(hist)}
        if not new:
            v.update(status="skip", reason=f"no entries labeled {lbl!r}")
            verdicts.append(v)
            continue
        value = ledger.trimean(new)
        v["value"] = value
        if len(hist) < need:
            v.update(status="skip",
                     reason=f"history {len(hist)} < min_history {need}")
            verdicts.append(v)
            continue
        center = ledger.trimean(hist)
        tol = max(k * ledger.mad(hist), rtol * abs(center), atol)
        direction = over.get("direction") or default_direction(
            metric, es[-1].get("unit"))
        lo, hi = center - tol, center + tol
        bad_low = value < lo and direction in ("higher", "both")
        bad_high = value > hi and direction in ("lower", "both")
        v.update(center=center, tol=tol, lo=lo, hi=hi, direction=direction)
        if bad_low or bad_high:
            v.update(status="fail",
                     reason=("regressed below" if bad_low else
                             "regressed above")
                     + f" the band [{_fmt(lo)}, {_fmt(hi)}]")
        else:
            v.update(status="pass", reason="within band")
        verdicts.append(v)
    return verdicts


def gate_report(verdicts: Sequence[dict]) -> str:
    lines = []
    for v in verdicts:
        band = (f" band=[{_fmt(v['lo'])}, {_fmt(v['hi'])}]"
                f" center={_fmt(v['center'])} ({v['direction']})"
                if "center" in v else "")
        val = f" value={_fmt(v['value'])}" if "value" in v else ""
        lines.append(
            f"GATE {v['status'].upper()} {v['metric']} [{v['platform']}"
            f"/{v['config']}] label={v['label']}{val}{band}"
            f" n_history={v['n_history']}: {v['reason']}")
    return "\n".join(lines)


# -- markdown dashboard -------------------------------------------------------


def render_dashboard(entries: Sequence[dict], *, gate_args: dict = None,
                     source: str = "") -> str:
    """The CI-artifact dashboard: latest values, sentinel verdicts, and
    every leg's trend table, as one markdown document."""
    gs = groups(entries)
    lines = ["# Performance dashboard", ""]
    labels = sorted({e["label"] for e in entries})
    lines.append(f"{len(entries)} ledger entries · {len(gs)} legs · "
                 f"labels: {', '.join(labels) or '-'}"
                 + (f" · source `{source}`" if source else ""))
    lines += ["", "## Latest", ""]
    rows = []
    for (metric, plat, cfg), es in sorted(gs.items()):
        e = es[-1]
        prev = es[-2]["value"] if len(es) > 1 else None
        rows.append([metric, plat, e["label"], _fmt(e["value"]),
                     e.get("unit") or "-",
                     f"{e['value'] / prev:.3f}x" if prev else "-"])
    lines += _rows_to_table(
        ["metric", "platform", "label", "value", "unit", "vs_prev"],
        rows, markdown=True)
    verdicts = evaluate_gate(entries, **(gate_args or {}))
    judged = [v for v in verdicts if v["status"] != "skip"]
    if judged:
        lines += ["", "## Regression sentinel", ""]
        vr = [[v["metric"], v["platform"], v["label"], v["status"],
               v["reason"]] for v in judged]
        lines += _rows_to_table(
            ["metric", "platform", "label", "status", "verdict"],
            vr, markdown=True)
    lines += ["", "## Trends", "",
              trend_tables(entries, markdown=True)]
    return "\n".join(lines) + "\n"


# -- ingest -------------------------------------------------------------------

# the literal "r" is required: every committed round file is _rNN, and a
# loose _<digits> match would turn e.g. bench_128.json into round "r128" —
# which order_key then sorts into the rNN prehistory as the newest round
_LABEL_RE = re.compile(r"_r(\d+)\.\w+$")


def _label_from_filename(path: str) -> Optional[str]:
    m = _LABEL_RE.search(os.path.basename(path))
    return f"r{int(m.group(1)):02d}" if m else None


def ingest_file(path: str, *, label: Optional[str] = None,
                platform: str = "unknown", rev: Optional[str] = None,
                spans: bool = False) -> List[dict]:
    """Map one file into ledger entries, auto-detecting its shape:
    a legacy BENCH wrapper ({"n", "rc", "parsed"}), a legacy MULTICHIP
    doc ({"n_devices", "ok"}), a live bench payload ({"metric",
    "value"}), or a telemetry metrics JSONL."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if isinstance(doc, dict) and {"run", "proc", "kind", "name"} <= set(doc):
        # a single-line metrics JSONL parses as ONE dict — it is still a
        # telemetry record stream, not a payload doc
        doc = None
    if isinstance(doc, dict):
        if "parsed" in doc or ("n" in doc and "tail" in doc):
            return ledger.entries_from_legacy_bench(
                doc, label=label or _label_from_filename(path), rev=rev)
        if "n_devices" in doc:
            lbl = label or _label_from_filename(path)
            if lbl is None:
                raise ValueError(
                    f"{path}: a MULTICHIP doc carries no round number — "
                    "pass --label or keep the _rNN filename")
            return ledger.entries_from_legacy_multichip(doc, label=lbl,
                                                        rev=rev)
        if "metric" in doc and "value" in doc:
            return ledger.entries_from_bench_payload(
                doc, label=label or _label_from_filename(path)
                or "adhoc", rev=rev)
        raise ValueError(f"{path}: unrecognized payload shape "
                         f"(keys {sorted(doc)[:6]})")
    # not one JSON object: treat as telemetry metrics JSONL
    from ..obs import telemetry

    records = []
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}:{i}: unparseable JSON ({e})")
        errs = telemetry.validate_record(rec)
        if errs:
            raise ValueError(f"{path}:{i}: {errs[0]}")
        records.append(rec)
    return ledger.entries_from_metrics_records(
        records, label=label, platform=platform, rev=rev, spans=spans)


# -- CLI ----------------------------------------------------------------------


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        description="performance ledger: ingest, trend, diff, gate, render")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp, markdown=False):
        sp.add_argument("--ledger", required=True, help="ledger JSONL path")
        if markdown:
            # only the table subcommands have a plain-text/markdown split;
            # gate output is line-oriented and render is always markdown
            sp.add_argument("--markdown", action="store_true")

    sp = sub.add_parser("ingest", help="map payload files into the ledger")
    sp.add_argument("--ledger", required=True)
    sp.add_argument("paths", nargs="+")
    sp.add_argument("--legacy", action="store_true",
                    help="committed BENCH_r0*/MULTICHIP_r0* shapes (label "
                         "inferred from the round number/filename)")
    sp.add_argument("--label", default="",
                    help="round label for the new entries (default: "
                         "inferred per file)")
    sp.add_argument("--platform", default="unknown",
                    help="platform tag for metrics-JSONL ingest")
    sp.add_argument("--rev", default="",
                    help="git revision to stamp (default: none for "
                         "--legacy, the repo's HEAD otherwise)")
    sp.add_argument("--spans", action="store_true",
                    help="also ingest span trimeans from metrics JSONL "
                         "(as <name>.trimean_s)")

    sp = sub.add_parser("trend", help="per-leg trajectory across labels")
    common(sp, markdown=True)
    sp.add_argument("--metric", action="append", default=[])
    sp.add_argument("--platform", default="")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable output (per-leg trajectory + "
                         "sentinel verdicts) instead of tables — the "
                         "CI-artifact shape")
    sp.add_argument("--out", default="",
                    help="with --json, also write the document here")

    sp = sub.add_parser("diff", help="one label vs another, per leg")
    common(sp, markdown=True)
    sp.add_argument("--a", required=True)
    sp.add_argument("--b", required=True)

    sp = sub.add_parser("gate", help="regression sentinel (exit 1 on trip)")
    common(sp)
    sp.add_argument("--metric", action="append", default=[],
                    help="leg(s) to judge (default: every leg)")
    sp.add_argument("--label", default="",
                    help="label under judgment (default: each leg's newest)")
    sp.add_argument("--platform", default="")
    sp.add_argument("--mad-k", type=float, default=3.0,
                    help="band half-width in MADs (default 3)")
    sp.add_argument("--rel-tol", type=float, default=0.05,
                    help="band half-width floor as a fraction of the "
                         "history trimean (default 0.05)")
    sp.add_argument("--abs-tol", type=float, default=0.0)
    sp.add_argument("--min-history", type=int, default=1,
                    help="history entries required before judging "
                         "(fewer = skip, not fail)")
    sp.add_argument("--leg-config", default="",
                    help="JSON of per-leg overrides: {leg: {rel_tol, mad_k, "
                         "abs_tol, direction, min_history}}; '*' sets "
                         "defaults")

    sp = sub.add_parser(
        "drift",
        help="calibration drift sentinel: judge the installed "
             "calibration's predictions against a run's measured "
             "attribution samples (exit 1 naming the drifted phase)")
    sp.add_argument("--metrics", required=True,
                    help="metrics JSONL with plan.attrib.phase records "
                         "(a --metrics-out file)")
    sp.add_argument("--phase", action="append", default=[],
                    help="phase(s) to judge (default: every attributed "
                         "phase)")
    sp.add_argument("--mad-k", type=float, default=3.0,
                    help="band half-width in MADs of the measured "
                         "samples (default 3 — the gate's band)")
    sp.add_argument("--rel-tol", type=float, default=0.05,
                    help="band half-width floor as a fraction of the "
                         "measured trimean (default 0.05; raise for "
                         "noisy CPU fabrics — but keep it < 1, or a "
                         "prediction far BELOW the measured center can "
                         "never trip)")
    sp.add_argument("--abs-tol", type=float, default=0.0)

    sp = sub.add_parser("render", help="markdown dashboard for CI artifacts")
    common(sp)
    sp.add_argument("--out", default="", help="also write the dashboard here")

    args = p.parse_args(argv)

    if args.cmd == "drift":
        # ledger-free like ingest: the evidence is one run's metrics
        # file; the band authority is obs/attribution.judge_drift — the
        # same trimean±max(k·MAD, rtol·|center|, atol) formula
        # evaluate_gate applies to ledger history
        if not os.path.exists(args.metrics):
            print(f"[perf] no such metrics file: {args.metrics}",
                  file=sys.stderr)
            return 2
        from ..obs import telemetry
        from ..obs.attribution import judge_drift, phases_from_records

        records: List[dict] = []
        with open(args.metrics) as f:
            for i, line in enumerate(f, 1):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    print(f"[perf] {args.metrics}:{i}: unparseable JSON "
                          f"({e})", file=sys.stderr)
                    return 2
                errs = telemetry.validate_record(rec)
                if errs:
                    print(f"[perf] {args.metrics}:{i}: {errs[0]}",
                          file=sys.stderr)
                    return 2
                records.append(rec)
        phases = phases_from_records(records)
        if args.phase:
            phases = {k: v for k, v in phases.items() if k in args.phase}
        if not phases:
            print("[perf] drift judged nothing (no plan.attrib.phase "
                  "records match)", file=sys.stderr)
            return 2
        drifted: List[str] = []
        for phase, g in sorted(phases.items()):
            v = judge_drift(phase, g["predicted_s"], g["samples"],
                            mad_k=args.mad_k, rel_tol=args.rel_tol,
                            abs_tol=args.abs_tol)
            status = "PASS" if v.ok else "FAIL"
            print(f"DRIFT {status} [{g['method']}] {v.describe()} "
                  f"calibration={g['provenance'] or 'modeled(default)'}")
            if not v.ok:
                drifted.append(phase)
        if drifted:
            print(f"[perf] CALIBRATION DRIFT: {', '.join(drifted)} — "
                  "refit with `plan_tool calibrate`", file=sys.stderr)
            return 1
        return 0

    if args.cmd == "ingest":
        if args.label and len(args.paths) > 1:
            # one label across files: same-keyed entries (same metric/
            # platform/config/rev) dedup to the FIRST file's value
            print(f"[perf] WARNING: one --label {args.label!r} across "
                  f"{len(args.paths)} files — entries sharing a key keep "
                  f"only the first file's value (use per-file labels to "
                  f"ingest repeat runs of one config)", file=sys.stderr)
        rev = args.rev or (None if args.legacy else ledger.git_rev(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))))
        entries: List[dict] = []
        for path in args.paths:
            got = ingest_file(path, label=args.label or None,
                              platform=args.platform, rev=rev,
                              spans=args.spans)
            print(f"[perf] {path}: {len(got)} entries")
            entries.extend(got)
        n = ledger.append_entries(args.ledger, entries)
        print(f"[perf] appended {n} new entries to {args.ledger} "
              f"({len(entries) - n} already present)")
        return 0

    if not os.path.exists(args.ledger):
        # load_ledger maps absence to an empty ledger (right for a first
        # append) — but a READ of a mistyped path must fail, not render
        # an empty trend/dashboard with rc 0 and keep CI green
        print(f"[perf] no such ledger: {args.ledger}", file=sys.stderr)
        return 2
    entries = ledger.load_ledger(args.ledger)
    if args.cmd == "trend":
        if args.json:
            if args.markdown:
                print("# --json ignores --markdown", file=sys.stderr)
            doc = trend_json(entries, args.metric or None,
                             args.platform or None)
            text = json.dumps(doc, indent=1, sort_keys=True)
            print(text)
            if args.out:
                with open(args.out, "w") as f:
                    f.write(text + "\n")
            return 0
        if args.out:
            print("# trend --out requires --json", file=sys.stderr)
        print(trend_tables(entries, args.metric or None,
                           args.platform or None, markdown=args.markdown))
        return 0
    if args.cmd == "diff":
        print(diff_tables(entries, args.a, args.b, markdown=args.markdown))
        return 0
    if args.cmd == "gate":
        try:
            leg_cfg = load_leg_config(args.leg_config or None)
        except (OSError, json.JSONDecodeError, ValueError) as e:
            # a usage error must not read as a regression trip: exit 2
            # with a message, the mistyped---ledger-path discipline
            print(f"[perf] bad --leg-config: {e}", file=sys.stderr)
            return 2
        verdicts = evaluate_gate(
            entries, metrics=args.metric or None, label=args.label or None,
            mad_k=args.mad_k, rel_tol=args.rel_tol, abs_tol=args.abs_tol,
            min_history=args.min_history, leg_config=leg_cfg,
            platform=args.platform or None)
        print(gate_report(verdicts))
        failed = [v for v in verdicts if v["status"] == "fail"]
        judged = [v for v in verdicts if v["status"] == "pass"] + failed
        if failed:
            print(f"[perf] GATE TRIPPED: "
                  f"{', '.join(v['metric'] for v in failed)}",
                  file=sys.stderr)
            return 1
        if not judged:
            print("[perf] gate judged nothing (no history / no matching "
                  "entries)", file=sys.stderr)
            return 2
        return 0
    if args.cmd == "render":
        text = render_dashboard(entries, source=args.ledger)
        print(text)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        return 0
    raise AssertionError(args.cmd)


if __name__ == "__main__":
    raise SystemExit(main())
