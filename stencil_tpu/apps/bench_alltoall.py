"""bench_alltoall — all-to-all collective throughput, two strategies.

TPU-native analogue of the reference's bench-alltoallv (reference:
bin/bench_alltoallv.cu:12-60), which compared cudaMemcpyPeerAsync
all-to-all against MPI_Alltoallv. The TPU strategies:

- ``all_to_all``: XLA's native ``lax.all_to_all`` collective — one fused
  transpose over the mesh (the MPI_Alltoallv analogue).
- ``ring``: n-1 ``lax.ppermute`` ring rotations delivering one peer's
  payload per step (the hand-rolled peer-copy analogue) — measures what
  the collective buys over composed point-to-points.

Each device exchanges ``bytes`` with every other device; reported GB/s is
per-device egress (n-1 peer payloads / time).

CSV: bench_alltoall,<strategy>,<devices>,<bytes_per_pair>,<trimean_s>,<gb_per_s>

Usage: python -m stencil_tpu.apps.bench_alltoall --cpu 8
"""

from __future__ import annotations

import argparse
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import logging as log
from ..utils.statistics import Statistics
from ..utils.sync import hard_sync


def _alltoall_body(n: int):
    def body(x):  # x: (1, n, k) — this device's row of payloads
        v = x[0]
        y = lax.all_to_all(v, "i", split_axis=0, concat_axis=0, tiled=True)
        return y[None]

    return body


def _ring_body(n: int):
    def body(x):  # x: (1, n, k)
        v = x[0]
        me = lax.axis_index("i")
        out = v
        for s in range(1, n):
            # send my payload for peer (me+s) forward s hops; receive the
            # payload of peer (me-s) destined to me into its row
            perm = [(i, (i + s) % n) for i in range(n)]
            sent = jnp.take(v, jnp.mod(me + s, n), axis=0)
            got = lax.ppermute(sent, "i", perm)
            out = lax.dynamic_update_index_in_dim(
                out, got, jnp.mod(me - s, n), axis=0
            )
        return out[None]

    return body


def run(
    sizes_kb: Sequence[int] = (64, 256, 1024),
    devices=None,
    iters: int = 10,
    rounds: int = 3,
) -> list:
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if n < 2:
        raise ValueError("all-to-all needs at least 2 devices")
    mesh = Mesh(np.asarray(devices), ("i",))
    rows = []
    for strategy, make_body in (("all_to_all", _alltoall_body), ("ring", _ring_body)):
        for kb in sizes_kb:
            k = max(1, kb * 1024 // 4)
            body = make_body(n)

            def many(x):
                return lax.fori_loop(0, iters, lambda _, b: body(b), x)

            fn = jax.jit(
                jax.shard_map(
                    many, mesh=mesh, in_specs=P("i", None, None),
                    out_specs=P("i", None, None),
                ),
                donate_argnums=0,
            )
            buf = jax.device_put(
                jnp.zeros((n, n, k), jnp.float32),
                NamedSharding(mesh, P("i", None, None)),
            )
            buf = fn(buf)
            hard_sync(buf)
            st = Statistics()
            for _ in range(rounds):
                t0 = time.perf_counter()
                buf = fn(buf)
                hard_sync(buf)
                st.insert(time.perf_counter() - t0)
            per_pair = k * 4
            egress = per_pair * (n - 1)
            rows.append(
                {
                    "strategy": strategy,
                    "devices": n,
                    "bytes_per_pair": per_pair,
                    "trimean_s": st.trimean() / iters,
                    "gb_per_s": egress * iters / st.trimean() / 1e9,
                }
            )
    return rows


def csv_row(r: dict) -> str:
    return (
        f"bench_alltoall,{r['strategy']},{r['devices']},{r['bytes_per_pair']},"
        f"{r['trimean_s']:e},{r['gb_per_s']:.3f}"
    )


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="all-to-all throughput (TPU)")
    p.add_argument("--sizes-kb", type=str, default="64,256,1024")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--cpu", type=int, default=0, help="force N virtual CPU devices")
    from ._bench_common import add_metrics_flags, finish_metrics, start_metrics
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    rec = start_metrics(args, "bench_alltoall")
    sizes = tuple(int(s) for s in args.sizes_kb.split(","))
    for r in run(sizes_kb=sizes):
        print(csv_row(r))
        rec.gauge("bench_alltoall.gb_per_s", r["gb_per_s"], phase="exchange",
                  strategy=r["strategy"], bytes=r["bytes_per_pair"],
                  devices=r["devices"])
    finish_metrics(rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
