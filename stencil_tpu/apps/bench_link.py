"""bench_link — per-mesh-axis neighbor-shift bandwidth sweep.

TPU-native analogue of the reference's bench-mpi point-to-point bandwidth
survey by node pair (reference: bin/bench_mpi.cu): on TPU the links that
matter are the mesh axes the halo exchange shifts along, so this measures
``lax.ppermute`` ring-shift bandwidth per mesh axis over a range of
message sizes. Every device sends one message per shift, so the reported
GB/s is per-device unidirectional throughput on that axis — the number to
compare against the ICI roofline and against ``pingpong`` latency.

CSV: bench_link,<axis>,<devices_on_axis>,<bytes>,<trimean_s>,<gb_per_s>

Usage: python -m stencil_tpu.apps.bench_link --cpu 8 --sizes-kb 64,1024
"""

from __future__ import annotations

import argparse
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..geometry import Dim3, RankPartition
from ..parallel.mesh import MESH_AXES, grid_mesh
from ..utils import logging as log
from ..utils.statistics import Statistics
from ..utils.sync import hard_sync


def run(
    sizes_kb: Sequence[int] = (64, 256, 1024, 4096),
    dim=None,
    devices=None,
    iters: int = 20,
    rounds: int = 3,
) -> list:
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if dim is None:
        dim = RankPartition(Dim3(256, 256, 256), n).dim()
    dim = Dim3.of(dim)
    mesh = grid_mesh(dim, devices)
    rows = []
    for axis in MESH_AXES:
        n_axis = mesh.shape[axis]
        if n_axis < 2:
            continue
        fwd = [(i, (i + 1) % n_axis) for i in range(n_axis)]
        for kb in sizes_kb:
            count = max(1, kb * 1024 // 4)

            def many(block):
                return lax.fori_loop(
                    0, iters, lambda _, b: lax.ppermute(b, axis, fwd), block
                )

            fn = jax.jit(
                jax.shard_map(
                    many,
                    mesh=mesh,
                    in_specs=P(*MESH_AXES, None),
                    out_specs=P(*MESH_AXES, None),
                ),
                donate_argnums=0,
            )
            buf = jax.device_put(
                jnp.zeros((dim.z, dim.y, dim.x, count), jnp.float32),
                NamedSharding(mesh, P(*MESH_AXES, None)),
            )
            buf = fn(buf)
            hard_sync(buf)
            st = Statistics()
            for _ in range(rounds):
                t0 = time.perf_counter()
                buf = fn(buf)
                hard_sync(buf)
                st.insert(time.perf_counter() - t0)
            nbytes = count * 4
            rows.append(
                {
                    "axis": axis,
                    "devices_on_axis": n_axis,
                    "bytes": nbytes,
                    "trimean_s": st.trimean() / iters,
                    "gb_per_s": nbytes * iters / st.trimean() / 1e9,
                }
            )
    return rows


def csv_row(r: dict) -> str:
    return (
        f"bench_link,{r['axis']},{r['devices_on_axis']},{r['bytes']},"
        f"{r['trimean_s']:e},{r['gb_per_s']:.3f}"
    )


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(description="per-mesh-axis shift bandwidth (TPU)")
    p.add_argument("--sizes-kb", type=str, default="64,256,1024,4096")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--cpu", type=int, default=0, help="force N virtual CPU devices")
    from ._bench_common import add_metrics_flags, finish_metrics, start_metrics
    add_metrics_flags(p)
    args = p.parse_args(argv)
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    rec = start_metrics(args, "bench_link")
    sizes = tuple(int(s) for s in args.sizes_kb.split(","))
    for r in run(sizes_kb=sizes):
        print(csv_row(r))
        rec.gauge("bench_link.gb_per_s", r["gb_per_s"], phase="exchange",
                  axis=r["axis"], bytes=r["bytes"],
                  devices_on_axis=r["devices_on_axis"])
    finish_metrics(rec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
