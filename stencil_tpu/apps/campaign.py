"""campaign — multi-tenant batched serving of many small domains.

The CLI over ``stencil_tpu/campaign/``: queue N tenant jobs (independent
periodic jacobi boxes, seeded per-tenant initial fields), serve them in
fixed-size batch slots under one compiled program per shape bucket
(``--mode batched``), one at a time through the standard single-domain
machinery (``--mode sequential``), or both back-to-back with the
tracked ratio and an optional bit-parity check (``--mode ab`` — the
``campaign_batched_over_sequential`` bench leg and the CI campaign
gate's harness).

Prints ONE JSON summary line (aggregate Mcells/s, p50/p99 per-tenant
step latency, evictions, compile-cache hits) and records the same as
gauges when ``--metrics-out`` is set:

- ``campaign.batched_mcells_per_s`` / ``campaign.sequential_mcells_per_s``
- ``campaign.batched_p50_step_s`` / ``..._p99_step_s`` (+ sequential)
- ``campaign.batched_over_sequential`` (ab mode; > 1 = batching wins)

Fault handling rides the driver: ``--inject nan@3:tenant=t2:repeat=always``
drives one tenant to the rc-43 ``fault`` outcome — it is evicted (its
lane backfilled from the queue) while its siblings keep stepping, and
its evidence bundle + last-healthy snapshot land under
``<campaign-dir>/tenants/t2/``.

Usage: python -m stencil_tpu.apps.campaign --cpu 8 --tenants 8 --slot 4 \
           --size 16 --steps 6 --mode ab --check-parity
"""

from __future__ import annotations

import argparse
import json
import math
import os
import tempfile
from typing import Optional

import numpy as np
import jax

from ..obs import telemetry
from ..utils import logging as log


def _finite_gauge(rec, name: str, value: float, **tags) -> None:
    if value is not None and math.isfinite(value):
        rec.gauge(name, value, **tags)


def _round6(value: float):
    """None for a non-finite sample (a latency-less run — e.g. 0 steps
    or everything revived-complete) so the one-line summary stays strict
    JSON: ``json.dumps`` would happily emit a bare ``NaN`` token."""
    return round(value, 6) if math.isfinite(value) else None


def parse_deadlines(spec: str) -> dict:
    """``--deadline-ms`` grammar: a bare number applies to every tenant
    (``"50"``), comma-separated ``tid=ms`` pairs pin individual tenants
    (``"t1=0.5,t3=100"``); ``*=ms`` mixes a default with overrides.
    Raises ValueError on anything else — a mistyped SLO must never run
    the campaign silently un-judged (the fault-spec discipline)."""
    out: dict = {}
    if not spec:
        return out
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" in item:
            tid, ms = item.split("=", 1)
            out[tid.strip()] = float(ms)
        else:
            out["*"] = float(item)
    for tid, ms in out.items():
        if not math.isfinite(ms) or ms <= 0:
            # float('nan') parses fine but p99 > nan is always False —
            # the tenant would run with its SLO silently un-judged
            raise ValueError(f"deadline for {tid!r} must be a positive "
                             f"finite number of ms, got {ms!r}")
    return out


def build_jobs(args) -> list:
    from ..campaign import TenantJob

    # main() stashes the validated dict; a programmatic caller without
    # it falls back to parsing the raw flag
    deadlines = getattr(args, "_deadlines", None)
    if deadlines is None:
        deadlines = parse_deadlines(args.deadline_ms)
    return [
        TenantJob(f"t{i}", (args.size, args.size, args.size), args.steps,
                  args.dtype, seed=args.init_seed + i,
                  workload=args.workload,
                  deadline_ms=deadlines.get(f"t{i}", deadlines.get("*")))
        for i in range(args.tenants)
    ]


def run_modes(args, campaign_dir: str, sentinel=None, status=None) -> dict:
    from ..campaign import CampaignDriver, CompileCache, run_sequential

    devices = jax.devices()[: args.cpu] if args.cpu else jax.devices()
    jobs = build_jobs(args)
    rec = telemetry.get()
    out: dict = {
        "app": "campaign",
        "mode": args.mode,
        "tenants": args.tenants,
        "slot": args.slot,
        "size": args.size,
        "steps": args.steps,
        "dtype": args.dtype,
        "devices": len(devices),
        "campaign_dir": campaign_dir,
    }

    seq = None
    if args.mode in ("sequential", "ab"):
        seq = run_sequential(jobs, devices=devices, chunk=args.chunk)
        out["sequential_mcells_per_s"] = round(
            seq["aggregate_mcells_per_s"], 3)
        out["sequential_p50_step_s"] = _round6(seq["p50_step_s"])
        out["sequential_p99_step_s"] = _round6(seq["p99_step_s"])
        _finite_gauge(rec, "campaign.sequential_mcells_per_s",
                      seq["aggregate_mcells_per_s"], phase="step")
        _finite_gauge(rec, "campaign.sequential_p50_step_s",
                      seq["p50_step_s"], phase="step", unit="s")
        _finite_gauge(rec, "campaign.sequential_p99_step_s",
                      seq["p99_step_s"], phase="step", unit="s")

    bat = None
    if args.mode in ("batched", "ab"):
        cache = CompileCache()
        controller = None
        if getattr(args, "replan", False) and sentinel is not None:
            # the campaign's between-slot swap: a latched
            # replan.requested re-tunes the bucket's exchange-plan
            # config (force=True, static-only — slots must not stall on
            # probes) and persists the verdict into --plan-db, where
            # every later plan consumer replays it. The slot programs
            # themselves are bucket-keyed (batch-axis, zero-collective):
            # the apply is the DB install, not a mid-slot reshard.
            from ..campaign.driver import WORKLOADS
            from ..geometry import Dim3, Radius
            from ..plan.replan import ReplanController

            wl = WORKLOADS[args.workload]
            nq = len(wl.quantity_names(args.dtype))
            radius = Radius.constant(wl.default_radius)

            def retune_fn():
                from ..plan.autotune import autotune as _plan_autotune

                res = _plan_autotune(
                    Dim3(args.size, args.size, args.size), radius,
                    [args.dtype] * nq, devices=devices,
                    db_path=args.plan_db or None, probe=False, force=True,
                )
                return res.choice

            controller = ReplanController(
                retune_fn, lambda choice, st: None, sentinel=sentinel)
            sentinel.on_replan = controller.request
        elif getattr(args, "replan", False):
            log.warn("campaign: --replan needs --live-sentinel; ignoring")
        drv = CampaignDriver(
            jobs, args.slot, campaign_dir,
            devices=devices, chunk=args.chunk,
            ckpt_every=args.ckpt_every, ckpt_keep=args.ckpt_keep,
            health_every=args.health_every, max_abs=args.max_abs or None,
            max_rollbacks=args.max_rollbacks,
            rollback_backoff=args.rollback_backoff,
            inject=args.inject or None, inject_seed=args.inject_seed,
            resume=args.resume, cache=cache, use_pallas=args.use_pallas,
            sentinel=sentinel, status=status, replan=controller,
        )
        bat = drv.run()
        if controller is not None:
            out["replans_applied"] = controller.swaps
            out["replans_rejected"] = controller.rejected
        out["batched_mcells_per_s"] = round(
            bat["aggregate_mcells_per_s"], 3)
        out["batched_p50_step_s"] = _round6(bat["p50_step_s"])
        out["batched_p99_step_s"] = _round6(bat["p99_step_s"])
        out["slots"] = bat["slots"]
        out["evicted"] = bat["evicted"]
        out["slo_violations"] = bat["slo_violations"]
        out["anomalies"] = bat["anomalies"]
        out["cache"] = bat["cache"]
        _finite_gauge(rec, "campaign.batched_mcells_per_s",
                      bat["aggregate_mcells_per_s"], phase="step")
        _finite_gauge(rec, "campaign.batched_p50_step_s",
                      bat["p50_step_s"], phase="step", unit="s")
        _finite_gauge(rec, "campaign.batched_p99_step_s",
                      bat["p99_step_s"], phase="step", unit="s")

    if args.mode == "ab":
        ratio = (bat["aggregate_mcells_per_s"]
                 / seq["aggregate_mcells_per_s"]
                 if seq["aggregate_mcells_per_s"] > 0 else 0.0)
        out["batched_over_sequential"] = round(ratio, 3)
        _finite_gauge(rec, "campaign.batched_over_sequential", ratio,
                      phase="step")
        if args.check_parity:
            mismatches = []
            for tid, br in bat["results"].items():
                if br.outcome != "done":
                    continue  # evicted tenants diverge by construction
                sr = seq["results"].get(tid)
                if sr is None or sr.final.tobytes() != br.final.tobytes():
                    mismatches.append(tid)
            out["parity"] = "ok" if not mismatches else "MISMATCH"
            out["parity_mismatches"] = mismatches
            if mismatches:
                log.error(f"campaign: batched results differ from "
                          f"sequential for {mismatches}")
    return out


def main(argv: Optional[list] = None) -> int:
    from ..parallel.distributed import maybe_init_from_env
    maybe_init_from_env()
    p = argparse.ArgumentParser(
        description="multi-tenant batched campaign driver")
    p.add_argument("--tenants", type=int, default=8,
                   help="number of queued tenant jobs")
    p.add_argument("--slot", type=int, default=4,
                   help="batch-slot size B: tenants stepped per compiled "
                        "program (padded with dead tenants when the queue "
                        "drains)")
    p.add_argument("--size", type=int, default=16,
                   help="per-tenant cubic domain edge")
    p.add_argument("--steps", type=int, default=6,
                   help="steps per tenant")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    p.add_argument("--workload", choices=["jacobi", "astaroth"],
                   default="jacobi",
                   help="tenant physics: jacobi (single-quantity heat) or "
                        "astaroth (8-field MHD via the batched RK3 step); "
                        "astaroth serves --mode batched only (its "
                        "sequential baseline is a B=1 slot)")
    p.add_argument("--chunk", type=int, default=2,
                   help="fused steps per dispatch")
    p.add_argument("--mode", choices=["batched", "sequential", "ab"],
                   default="batched",
                   help="ab = sequential baseline then batched, with the "
                        "campaign_batched_over_sequential ratio")
    p.add_argument("--check-parity", action="store_true",
                   help="(ab) exit 1 unless every completed tenant's final "
                        "field is bit-identical between modes")
    p.add_argument("--campaign-dir", default="",
                   help="per-tenant durable state root (default: a fresh "
                        "temp dir)")
    p.add_argument("--ckpt-every", type=int, default=0,
                   help="checkpoint every active lane every N slot steps "
                        "(0 = only final/eviction snapshots)")
    p.add_argument("--ckpt-keep", type=int, default=3)
    p.add_argument("--resume", action="store_true",
                   help="pack tenants from their newest valid snapshot "
                        "(revives evicted tenants)")
    p.add_argument("--health-every", type=int, default=0,
                   help="per-lane health-check cadence in slot steps "
                        "(default: every fused chunk)")
    p.add_argument("--max-abs", type=float, default=0.0,
                   help="divergence ceiling on max|u| (0 = none)")
    p.add_argument("--max-rollbacks", type=int, default=2,
                   help="rollbacks per faulting step before the tenant is "
                        "EVICTED with the rc-43 evidence bundle")
    p.add_argument("--rollback-backoff", type=float, default=0.05)
    p.add_argument("--inject", default="",
                   help="per-tenant fault spec, e.g. "
                        "'nan@3:tenant=t2:repeat=always' (campaign/inject)")
    p.add_argument("--inject-seed", type=int, default=None)
    p.add_argument("--init-seed", type=int, default=0,
                   help="tenant i's initial field is seeded init-seed + i")
    p.add_argument("--replan", action="store_true",
                   help="between-slot plan hot-swap (needs "
                        "--live-sentinel, batched/ab mode): a latched "
                        "replan.requested re-tunes the bucket's exchange "
                        "plan at the next slot boundary and persists it "
                        "to --plan-db (replan.applied/rejected records)")
    p.add_argument("--plan-db", default="",
                   help="plan DB the --replan re-tune persists into")
    p.add_argument("--use-pallas", action="store_true",
                   help="batched Pallas fast path (TPU; aligned layout)")
    p.add_argument("--deadline-ms", default="",
                   help="per-step latency SLO: a bare number applies to "
                        "all tenants, 'tid=ms' pairs pin individuals "
                        "('t1=0.5,t3=100'); a tenant whose ONLINE p99 "
                        "exceeds its deadline emits one slo.violation "
                        "record and shows as violated in the status lanes")
    p.add_argument("--cpu", type=int, default=0,
                   help="force N virtual CPU devices")
    from ._bench_common import (add_live_flags, add_metrics_flags,
                                finish_live, finish_metrics, make_live,
                                start_metrics)
    add_metrics_flags(p)
    add_live_flags(p)
    args = p.parse_args(argv)
    try:
        deadlines = parse_deadlines(args.deadline_ms)
    except ValueError as e:
        p.error(f"bad --deadline-ms: {e}")
    args._deadlines = deadlines  # parsed once; build_jobs reuses it
    known = {f"t{i}" for i in range(args.tenants)} | {"*"}
    unknown = sorted(set(deadlines) - known)
    if unknown:
        # a mistyped tenant id must not run the campaign un-judged
        p.error(f"--deadline-ms names unknown tenant(s) {unknown} "
                f"(tenants are t0..t{args.tenants - 1})")
    if args.mode == "sequential":
        # the live layer rides the guarded batched driver; accepting the
        # flags here would silently observe nothing
        if args.live_sentinel:
            p.error("--live-sentinel rides the batched driver; --mode "
                    "sequential runs outside it (use batched or ab)")
        if args.replan:
            # same slot-boundary machinery: sequential serving has no
            # slots to swap between
            p.error("--replan swaps plans at slot boundaries of the "
                    "batched driver; --mode sequential has none "
                    "(use batched or ab)")
        if args.status_file:
            # may come from the globally-exported STENCIL_STATUS_FILE
            # env var rather than the command line — warn + ignore
            # instead of breaking every sequential invocation in an
            # environment that sets it for the other apps
            log.warn("campaign: --status-file/STENCIL_STATUS_FILE is "
                     "ignored in --mode sequential (status snapshots "
                     "ride the guarded batched driver)")
            args.status_file = ""
    if args.replan and not args.plan_db:
        # the campaign swap's APPLY is the DB install — without a DB the
        # re-tune would persist nowhere, no slot program would ever
        # consult it, and replan.applied would claim a swap that did
        # nothing (the sibling misuses error loudly; so does this one)
        p.error("--replan persists the re-tuned plan into --plan-db; "
                "pass one (the swap would otherwise install nothing)")
    from ._bench_common import canonicalize_live_config
    try:
        canonicalize_live_config(args)
    except (OSError, ValueError) as e:
        p.error(f"bad --live-config: {e}")

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    if args.dtype == "float64":
        jax.config.update("jax_enable_x64", True)
    if args.workload == "astaroth" and args.mode != "batched":
        p.error("--workload astaroth serves --mode batched only (the "
                "sequential baseline is a B=1 slot through the driver)")
    if args.workload == "astaroth" and args.use_pallas:
        p.error("--workload astaroth runs the XLA batched step; the "
                "batched Pallas astaroth substep is a hardware-session "
                "follow-up (drop --use-pallas)")
    rec = start_metrics(args, "campaign")
    sentinel, status = make_live(args, rec, "campaign")

    campaign_dir = args.campaign_dir or tempfile.mkdtemp(prefix="campaign-")
    out = run_modes(args, campaign_dir, sentinel=sentinel, status=status)
    print(json.dumps(out, default=str))
    # gauge=False: the driver's run() already recorded live.anomaly_count
    finish_live(rec, sentinel, status, outcome="done", gauge=False)
    finish_metrics(rec)
    if out.get("parity") == "MISMATCH":
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
